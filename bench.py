"""Benchmark: NG15-scale dataset realizations per second on one chip.

Workload (the reference's realistic configuration, BASELINE.md): 68
pulsars x 7,758 TOAs, per-backend EFAC+EQUAD (4 backends), ECORR jitter,
30-mode power-law red noise, Hellings-Downs-correlated GWB on the default
npts=600/howml=10 grid (~3,000 frequency bins), a 100-source CW outlier
catalog, and a per-pulsar quadratic refit — i.e. one complete synthetic
dataset per realization.

North star (BASELINE.json): 1,000 such realizations in < 60 s on a v5e-8
=> 16.67 realizations/s for the whole 8-chip slice. ``vs_baseline`` below
is single-chip-rate / 16.67: a value >= 1 means ONE chip beats the target
set for eight (the realization axis is embarrassingly parallel, so 8 chips
scale this ~8x further; tests/test_sharding.py validates that path).

Prints exactly one JSON line (stdout). Robustness against the tunneled
TPU backend (round-1 failure mode: backend init hung/died, zero evidence
recorded): the measured child process probes the backend IN-PROCESS
under a watchdog and, on success, runs the measurement on the SAME live
client — fast_capture.py's probe-and-hold. The old shape (probe in one
subprocess, workload in a fresh second client) is exactly what lost the
round-5 tunnel window: the probe's healthy connection was thrown away
and the fresh client wedged in init (VERDICT r5 "Next round" #1). The
parent keeps the hard deadline and bounded retries, so a hung runtime
still can never hang the bench — worst case it prints a failure JSON
with the diagnosis. Timing syncs via host readback (block_until_ready
returns at dispatch on this backend, see .claude/skills/verify).

Tuning knobs via env: BENCH_CHUNK (realizations per jitted call, default
800), BENCH_NREP (timed repetitions, default 5), BENCH_PRNG ('threefry'
default; 'rbg' uses the hardware RngBitGenerator for the per-realization
draws), BENCH_PROBE_TRIES (child relaunches after a wedged in-process
probe, default 3), BENCH_PROBE_TIMEOUT (probe watchdog, s, default 180),
BENCH_TIMEOUT (overall child deadline, s, default 1500),
BENCH_BACKEND (forwarded to Recipe.cgw_backend, default 'auto').
"""
import json
import os
import subprocess
import sys
import time

import numpy as np

_METRIC = (
    "NG15-scale full-dataset realizations/sec, single chip "
    "(68 psr x 7758 TOAs: EFAC+EQUAD+ECORR+RN30+HD-GWB(Nf~3000)"
    "+100-CW catalog+quadratic fit)"
)
_NORTH_STAR_RATE = 1000.0 / 60.0  # v5e-8 whole-slice target

#: bench-JSON schema version, consumed by the bench-diff regression gate
#: (pta_replicator_tpu.obs.regress). Bump when a metric's NAME keeps its
#: spelling but changes meaning/units — bench-diff refuses files stamped
#: newer than it knows rather than mis-aligning them. v2 = the first
#: stamped version (adds schema_version / git_rev / platform).
BENCH_SCHEMA_VERSION = 2


def _provenance() -> dict:
    """Self-describing stamp on every bench JSON (success AND failure):
    schema version, git revision, and the host/runtime platform — what
    bench-diff needs to refuse or annotate cross-round comparisons.
    The ONE stamping implementation is shared with the MULTICHIP /
    validate_device evidence series (utils.provenance)."""
    from pta_replicator_tpu.utils.provenance import provenance_stamp

    return provenance_stamp(
        BENCH_SCHEMA_VERSION,
        repo_root=os.path.dirname(os.path.abspath(__file__)),
    )

def _probe_and_hold() -> float:
    """In-process backend probe under a watchdog; the caller keeps the
    SAME live client for the measurement (probe-and-hold, the shape
    benchmarks/fast_capture.py proved out across rounds 3-5).

    Exits 3 when backend init wedges past BENCH_PROBE_TIMEOUT or
    raises fast (connection refused), and 4 on a silent fallback to
    the wrong backend (a failed TPU-plugin init falls back to CPU,
    which must read as "unreachable", not as a healthy chip). The
    parent retries BOTH with backoff, up to BENCH_PROBE_TRIES — the
    tunnel flaps on a minutes cadence and every one of these outcomes
    is its transient signature. Returns the probe wall seconds.

    benchmarks/fast_capture.py deliberately keeps its own variant of
    this machinery: its watchdog is resettable per stage (``arm()``)
    and guards the whole smallest-first capture battery, not just the
    probe — the proven-on-hardware script is not restructured to share
    a probe-only helper.
    """
    import threading

    import jax
    import jax.numpy as jnp

    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "180"))
    # single-writer heartbeat, watchdog only reads (fast_capture's
    # pattern): a lock could itself wedge a dying init
    armed = [True]
    deadline = [time.monotonic() + probe_timeout]

    def _watchdog():
        while armed[0]:
            time.sleep(2.0)
            if armed[0] and time.monotonic() > deadline[0]:
                print(
                    f"backend probe wedged past {probe_timeout:.0f}s, "
                    "exiting 3",
                    file=sys.stderr, flush=True,
                )
                os._exit(3)

    threading.Thread(target=_watchdog, daemon=True).start()
    t0 = time.monotonic()
    try:
        float(np.asarray(jnp.ones((256, 256)) @ jnp.ones((256, 256))).sum())
    except BaseException as exc:  # fast init failure: as retryable as a wedge
        print(f"backend probe failed: {exc!r}"[:300], file=sys.stderr,
              flush=True)
        raise SystemExit(3)
    armed[0] = False  # held client is live; BENCH_TIMEOUT bounds the rest
    want = os.environ.get("BENCH_PLATFORM", "tpu")
    if jax.default_backend() != want:
        print(
            f"probed backend is {jax.default_backend()}, wanted {want}",
            file=sys.stderr, flush=True,
        )
        raise SystemExit(4)
    return time.monotonic() - t0


def _fail(error: str):
    """Failure JSON. On a tunnel outage, point at any self-timestamped
    on-hardware evidence the recovery watchers captured earlier in the
    round (BENCH_PREVIEW_*.json) and the builder notes — a zero here
    means 'chip unreachable at measurement time', not 'no evidence'."""
    payload = {
        "metric": _METRIC,
        "value": 0.0,
        "unit": "realizations/s",
        "vs_baseline": 0.0,
        "error": error,
        **_provenance(),
    }
    here = os.path.dirname(os.path.abspath(__file__))
    backups = sorted(
        f for f in os.listdir(here)
        if f.startswith(("BENCH_PREVIEW_", "BENCH_RECOVERY_", "BENCH_NOTES_"))
    )
    if backups:
        payload["backup_evidence"] = backups
        for f in reversed(backups):
            if f.endswith(".json"):
                try:
                    with open(os.path.join(here, f)) as fh:
                        prev = json.load(fh)
                    if prev.get("value"):
                        payload["backup_value"] = prev["value"]
                        payload["backup_timestamp"] = prev.get("timestamp")
                        payload["backup_source"] = f
                        break
                except Exception:
                    pass
    print(json.dumps(payload))


def _stage_breakdown(batch, recipe, nreal: int = 20) -> dict:
    """ms/realization for each injection stage, measured standalone
    (separate jits, host-readback fencing), at the bench workload shapes."""
    import jax
    import jax.numpy as jnp

    from pta_replicator_tpu.utils.profiling import injection_stage_fns

    keys = jax.random.split(jax.random.PRNGKey(7), nreal)
    stages = injection_stage_fns(batch, recipe)

    for f in stages.values():
        np.asarray(f(keys))  # compile everything up front

    # queue reps back-to-back, fence once (a per-call readback would
    # measure the tunnel roundtrip, not the device); two interleaved
    # passes + min per stage to shave tunnel-throughput drift
    from pta_replicator_tpu import obs

    reps = 10
    best = {}
    for _ in range(2):
        for name, f in stages.items():
            with obs.span(f"stage_{name}", reps=reps):
                t0 = time.perf_counter()
                for _ in range(reps):
                    r = f(keys)
                float(jnp.sum(jnp.abs(r)))
                per = (time.perf_counter() - t0) / reps
            per /= 1 if name.endswith("_once") else nreal
            best[name] = min(best.get(name, per), per)
    return {name: round(per * 1e3, 4) for name, per in best.items()}


def random_cw_catalog(rng, ncw):
    """Shim over scenarios.compile.random_cw_catalog — the ONE sampler
    (moved into the scenario compiler in round 12; every benchmarks/
    tool still imports it from here)."""
    from pta_replicator_tpu.scenarios.compile import random_cw_catalog

    return random_cw_catalog(rng, ncw)


def _cpu_oracle_rate(npsr=68, ntoa=7758, ncw=100):
    """Measured realizations/s of the ORACLE (host numpy) path on the
    bench workload (VERDICT r3 item 8: the 'matching-or-beating' claim
    needs a measured reference side; the reference publishes no numbers
    and its deps don't install here, so the framework's own
    reference-semantics oracle is the stand-in). Ingest (par parse, TOA
    fabrication, make_ideal) is excluded — the timed region is one full
    realization: HD-correlated GWB + per-backend EFAC/EQUAD + ECORR +
    30-mode red noise + 100-source CW catalog + quadratic spin fit,
    mirroring the device pipeline stage for stage."""
    import os as _os
    import tempfile

    import pta_replicator_tpu as ptr

    base = open(
        "/root/reference/test_partim_small/par/JPSR00.par"
    ).read()
    rng = np.random.default_rng(0)
    mjds = np.linspace(53000.0, 53000.0 + 16 * 365.25, ntoa)
    cat = random_cw_catalog(np.random.default_rng(1), ncw)
    flags = ["B0", "B1", "B2", "B3"]
    with tempfile.TemporaryDirectory() as d:
        psrs = []
        for i in range(npsr):
            ra = rng.uniform(0, 24)
            dec = rng.uniform(-80, 80)
            lines = []
            for line in base.splitlines():
                key = line.split()[0] if line.split() else ""
                if key == "RAJ":
                    line = f"RAJ {int(ra)}:{int((ra % 1) * 60):02d}:00.0"
                elif key == "DECJ":
                    line = f"DECJ {int(dec)}:{int((abs(dec) % 1) * 60):02d}:00.0"
                elif key == "PSR":
                    line = f"PSR JFAKE{i:02d}"
                lines.append(line)
            p = _os.path.join(d, f"f{i}.par")
            open(p, "w").write("\n".join(lines))
            psr = ptr.simulate_pulsar(p, mjds, 0.5)
            for j, fl in enumerate(psr.toas.flags):
                fl["f"] = flags[j % 4]
            ptr.make_ideal(psr)
            psrs.append(psr)

        t0 = time.perf_counter()
        ptr.add_gwb(psrs, -14.0, 4.33, seed=1)
        for i, psr in enumerate(psrs):
            ptr.add_measurement_noise(
                psr, efac=[1.0, 1.1, 1.2, 1.3], log10_equad=[-7.0] * 4,
                flags=flags, seed=100 + i,
            )
            ptr.add_jitter(
                psr, log10_ecorr=[-7.0] * 4, flags=flags, seed=200 + i,
            )
            ptr.add_red_noise(psr, -14.0, 4.0, components=30, seed=300 + i)
            ptr.add_catalog_of_cws(psr, *cat)
            psr.fit(fitter="wls", params="spin", nspin=3)
        return 1.0 / (time.perf_counter() - t0)


def build_workload(npsr=68, ntoa=7758, nbackend=4, ncw=100,
                   with_fingerprint=False):
    """The canonical bench workload — a thin shim over the scenario
    compiler's ``bench_flagship`` preset (scenarios.compile.
    flagship_workload, the ONE implementation of the workload's legacy
    RNG call order and content fingerprint; the committed
    ``scenarios/specs/flagship.json`` compiles through the same code).
    Shared with benchmarks/fused_ablation.py so stage attribution is
    always measured on the headline workload. This shim keeps bench's
    env knobs: BENCH_BACKEND selects the CW-catalog backend and
    BENCH_SYNTH_PRECISION ({default, high, highest}) A/Bs the GWB
    DFT-synthesis MXU pass count (VERDICT r3 weak #2's named knob).
    """
    from pta_replicator_tpu.scenarios.compile import flagship_workload

    return flagship_workload(
        npsr=npsr, ntoa=ntoa, nbackend=nbackend, ncw=ncw,
        with_fingerprint=with_fingerprint,
        cgw_backend=os.environ.get("BENCH_BACKEND", "auto"),
        gwb_synthesis_precision=os.environ.get("BENCH_SYNTH_PRECISION")
        or None,
    )


def _bench():
    """The measured workload; runs in a child process (BENCH_CHILD=1)."""
    import jax

    # BENCH_PLATFORM forces a backend (e.g. 'cpu' for harness testing);
    # the env var alone is not enough because the axon TPU plugin
    # overrides JAX_PLATFORMS at import
    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)

    # persistent compilation cache: on the tunneled backend the flagship
    # compile is minutes, and the tunnel flaps on a minutes cadence — a
    # cached executable from any earlier successful window (e.g. the
    # recovery watcher's capture run) makes the next bench attempt fit
    # inside a short window instead of burning it on recompilation
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache")
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # cache is an optimization, never a bench failure

    # probe-and-hold: first device op under a watchdog, measurement on
    # the same client (see _probe_and_hold; exits 3/4 on wedge/fallback)
    probe_s = _probe_and_hold()

    # structured telemetry: jax compile accounting + per-section spans,
    # embedded into the bench JSON as the "telemetry" block so future
    # rounds carry per-stage evidence (obs.telemetry_summary below).
    # BENCH_TELEMETRY=DIR upgrades this to a full capture with a flight
    # recorder: `python -m pta_replicator_tpu watch DIR` then shows the
    # bench's live heartbeat (which section it is in, compile counters),
    # and a killed/timed-out bench leaves DIR/postmortem.json naming the
    # section it died in — benchmarks/recovery_watch.sh uses exactly this.
    from pta_replicator_tpu import obs

    bench_telemetry = os.environ.get("BENCH_TELEMETRY")
    if bench_telemetry:
        obs.start_capture(bench_telemetry)
    else:
        obs.install_jax_hooks()

    prng = os.environ.get("BENCH_PRNG", "threefry")
    if prng not in ("threefry", "rbg"):
        raise SystemExit(f"BENCH_PRNG must be 'threefry' or 'rbg', got {prng!r}")
    if prng == "rbg":
        jax.config.update("jax_default_prng_impl", "rbg")
    import jax.numpy as jnp

    from pta_replicator_tpu.models import batched as B
    from pta_replicator_tpu.models.batched import (
        deterministic_delays,
        quadratic_fit_subtract,
        realization_delays,
    )

    ncw = 100
    batch, recipe = build_workload(ncw=ncw)

    # ---- evidence block: self-authenticating metadata (ADVICE.md r2)
    extra = {
        "jax_backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "jax_version": jax.__version__,
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "probe_s": round(probe_s, 3),
        "probe_and_hold": True,  # same client probed AND measured
    }

    # ---- real-data ingest timing (VERDICT r2 item 8): par/tim -> frozen
    # batch cold start on the one real NANOGrav fixture with a tim file
    try:
        par = "/root/reference/test_partim/par/B1855+09.par"
        tim = "/root/reference/test_partim/tim/B1855+09.tim"
        if os.path.exists(par) and os.path.exists(tim):
            from pta_replicator_tpu import load_pulsar, make_ideal
            from pta_replicator_tpu.batch import freeze

            with obs.span("ingest_b1855"):
                t0 = time.perf_counter()
                psr = load_pulsar(par, tim)
                make_ideal(psr)
                b1855 = freeze([psr], dtype=jnp.float32)
            extra["ingest_b1855_s"] = round(time.perf_counter() - t0, 3)
            extra["ingest_b1855_ntoa"] = int(b1855.ntoa_max)
    except Exception as exc:
        extra["ingest_error"] = repr(exc)

    # ---- CW backend timing (scan, the production backend). The Pallas
    # kernel was retired round 5 (tied-or-lost on a real v5e at the
    # flagship shape across rounds 3-4 with no hardware window to show a
    # large-catalog win — docs/DESIGN.md section 4); the archived kernel
    # is still measurable via benchmarks/cw_scaling.py, which calls it
    # directly, so the bench no longer spends chip time on it.
    args8 = [recipe.cgw_params[i] for i in range(8)]

    # The traced scalar input keeps the graph from being constant-folded,
    # which would fake a near-zero scan timing.
    _cw_fn = jax.jit(
        lambda eps: B.cgw_catalog_delays(
            batch, *args8, chunk=recipe.cgw_chunk, backend="scan"
        )
        + eps
    )

    def _time_cw(reps=10):
        zero = jnp.zeros((), batch.toas_s.dtype)
        np.asarray(_cw_fn(zero))  # compile (cached after first pass) + run
        t0 = time.perf_counter()
        for _ in range(reps):
            out = _cw_fn(zero)
        np.asarray(out)  # host readback fences the FIFO queue
        return (time.perf_counter() - t0) / reps * 1e3, out

    try:
        used = recipe.cgw_backend if recipe.cgw_backend != "auto" else "scan"
        extra["cgw_backend_used"] = used
        extra["pallas"] = "retired r5 (docs/DESIGN.md section 4)"
        if jax.default_backend() == "tpu":
            extra["cgw_scan_ms"] = round(_time_cw()[0], 3)
    except Exception as exc:  # cross-check must never kill the bench
        extra["cgw_crosscheck_error"] = repr(exc)


    chunk = int(os.environ.get("BENCH_CHUNK", "800"))  # realizations/call

    # The CW-catalog/burst/memory delays depend only on (batch, recipe):
    # compute them ONCE for the whole sweep and pass them into every
    # chunk as data. Rebuilding them inside each chunk call (the r02
    # bench shape) cost ~11 ms/chunk — at chunk=100 that was ~1/3 of
    # total runtime. Eager on purpose: under jit(deterministic_delays)
    # the source params become tracers and the CW planes lose their f64
    # host precompute (parallel.mesh.static_delays documents the trap).
    static = deterministic_delays(batch, recipe)
    np.asarray(static)

    # BENCH_FIT: 'quad' (default, the headline config — comparable
    # across rounds), 'full' (166-column WLS design fit), or 'gls'
    # (same columns, nested-Woodbury GLS weighted by the recipe noise
    # model). The non-default modes measure the full-model refit cost
    # at bench scale; BENCH_FIT_K overrides the column count.
    fit_mode = os.environ.get("BENCH_FIT", "quad")
    if fit_mode not in ("quad", "full", "gls"):
        raise SystemExit(f"BENCH_FIT must be quad|full|gls, got {fit_mode!r}")
    extra["fit_mode"] = fit_mode
    if fit_mode != "quad":
        import dataclasses

        kcols = int(os.environ.get("BENCH_FIT_K", "166"))
        # generated ON DEVICE (fixed key, deterministic): the (68, 7758,
        # 166) f32 design is ~350 MB — a host->tunnel transfer of that
        # size can eat a whole tunnel window, and the measured rate does
        # not depend on the design's values
        fitD = jax.random.normal(
            jax.random.PRNGKey(3),
            (batch.npsr, batch.ntoa_max, kcols),
            batch.toas_s.dtype,
        )
        recipe = dataclasses.replace(
            recipe, fit_design=fitD, fit_gls=(fit_mode == "gls")
        )
        extra["fit_columns"] = kcols

    @jax.jit
    def run_chunk(key, static):
        keys = jax.random.split(key, chunk)

        def one(k):
            d = realization_delays(k, batch, recipe) + static
            if fit_mode != "quad":
                return B.finalize_residuals(d, batch, recipe, True)
            # the quad fit projects out the weighted constant at full
            # precision, so no separate residualize pass is needed
            return quadratic_fit_subtract(d, batch)

        res = jax.vmap(one)(keys)
        # reduce on device: per-realization, per-pulsar RMS (avoids hauling
        # (R, 68, 7758) residual cubes back to host in the timing loop)
        return jnp.sqrt(
            jnp.sum(res**2 * batch.mask, axis=-1) / jnp.sum(batch.mask, axis=-1)
        )

    # AOT-compile once and reuse the SAME executable for warm-up, the
    # timed loop, and cost_analysis (calling the jit wrapper after
    # .lower().compile() would build a second executable — minutes of
    # extra compile on the tunneled backend, risking BENCH_TIMEOUT)
    with obs.span("aot_compile", chunk=chunk):
        compiled = run_chunk.lower(jax.random.PRNGKey(0), static).compile()

    # warm-up. NOTE: sync via host readback of the (chunk, Np)
    # reduction, not block_until_ready() — on the remote-tunneled TPU
    # backend block_until_ready returns at dispatch, before execution.
    # Device execution is FIFO, so reading the last chunk's result back
    # fences every queued chunk.
    with obs.span("warmup"):
        out = compiled(jax.random.PRNGKey(0), static)
        np.asarray(out)

    nrep = int(os.environ.get("BENCH_NREP", "5"))
    with obs.span("measure", nrep=nrep, chunk=chunk):
        t0 = time.perf_counter()
        for i in range(nrep):
            out = compiled(jax.random.PRNGKey(i + 1), static)
        np.asarray(out)
        elapsed = time.perf_counter() - t0

    rate = nrep * chunk / elapsed
    extra["measure_elapsed_s"] = round(elapsed, 3)
    extra["bench_chunk"] = chunk

    # ---- telemetry self-overhead (the temporal obs layer's <1%-of-wall
    # claim, measured not asserted): re-run the identical measure loop
    # with a flight recorder + series sampler ticking at the default
    # 1 s cadence and read back the self-accounted obs.overhead_s
    # counter. CPU-gated like capture_pending — re-measuring on the
    # tunneled TPU would spend window time on bookkeeping
    # (BENCH_OBS_OVERHEAD=1 forces, =0 skips).
    want_overhead = os.environ.get(
        "BENCH_OBS_OVERHEAD",
        "1" if jax.default_backend() == "cpu" else "0",
    ) == "1"
    if want_overhead:
        try:
            import shutil
            import tempfile

            from pta_replicator_tpu.obs import flightrec as _flightrec
            from pta_replicator_tpu.obs import names as _obs_names

            def _overhead_total():
                val = 0.0
                for m in obs.REGISTRY.metrics():
                    if m.name == _obs_names.OBS_OVERHEAD_S and not m.labels:
                        val = float(m.value)
                return val

            own_rec = _flightrec.active() is None
            oh_dir = tempfile.mkdtemp(prefix="bench_obsoverhead_")
            rec_ = (
                _flightrec.FlightRecorder(oh_dir, stall_timeout_s=None)
                .start() if own_rec else _flightrec.active()
            )
            try:
                oh_before = _overhead_total()
                # steady-state window: repeat the step for >= ~30 s so
                # the number reflects the sampler's regulated duty
                # cycle, not the cold first tick (the recorder backs
                # its cadence off when a tick measures expensive —
                # obs/flightrec.py OVERHEAD_TARGET)
                oh_window_s = float(
                    os.environ.get("BENCH_OBS_WINDOW", "30"))
                t0 = time.perf_counter()
                reps_done = 0
                while (reps_done < nrep
                       or time.perf_counter() - t0 < oh_window_s):
                    out = compiled(
                        jax.random.PRNGKey(100 + reps_done), static
                    )
                    if reps_done % 2 == 1:
                        np.asarray(out)  # keep the dispatch queue bounded
                    reps_done += 1
                np.asarray(out)
                step_s = time.perf_counter() - t0
                # one final sampler-cadence tick is always captured
                # even if the window ended between ticks
                time.sleep(max(0.0, 1.1 - step_s))
                overhead_s = _overhead_total() - oh_before
            finally:
                # a raising step must not leave the throwaway recorder
                # installed as the process-wide active one (its sampler
                # would keep ticking into the leaked temp dir for the
                # rest of the bench)
                if own_rec:
                    rec_.stop(finished=True)
                    shutil.rmtree(oh_dir, ignore_errors=True)
            window_s = max(step_s, 1.1)
            extra["obs_overhead"] = {
                "overhead_s": round(overhead_s, 6),
                "step_s": round(step_s, 3),
                "steps": reps_done,
                # CPU seconds the sampler thread consumed (GC excluded,
                # see obs/flightrec.py) over the observed wall window
                "overhead_pct_of_step": round(
                    100.0 * overhead_s / window_s, 4
                ),
                "recorder": "own" if own_rec else "BENCH_TELEMETRY",
            }
        except Exception as exc:
            extra["obs_overhead_error"] = repr(exc)[:150]
    # the deterministic CW/burst delays are computed once per sweep
    # (they are key-independent data); their one-time cost is reported
    # separately as stages.cgw_catalog_once
    extra["cgw_static_amortized"] = True

    # ---- pipelined sweep A/B: the checkpointed-sweep executor's overlap
    # (parallel.pipeline, PR 2) measured on the bench workload — depth 1
    # (synchronous dispatch->fence->write) vs depth 2 (double-buffered).
    # Small (3 chunks, reduced readback) so it cannot eat the window;
    # the per-stage dispatch/drain/io_write spans land in the telemetry
    # block below. BENCH_SWEEP_PIPELINE=0 skips.
    if os.environ.get("BENCH_SWEEP_PIPELINE", "1") == "1":
        try:
            import shutil
            import tempfile

            from pta_replicator_tpu.utils.sweep import sweep as _sweep

            sp_chunk = min(chunk, 200)
            sp_nchunks = 3
            sp = {"chunk": sp_chunk, "nchunks": sp_nchunks,
                  "reduce": "rms"}
            # warm the sweep's realize engine at this chunk shape first:
            # the depth-1 arm runs first and must not absorb the compile
            from pta_replicator_tpu.models.batched import realize as _rlz

            np.asarray(_rlz(jax.random.PRNGKey(122), batch, recipe,
                            nreal=sp_chunk, static=static))
            # depth 2 FIRST: its drain deadline bounds a wedged tunnel
            # (the depth-1 synchronous loop has no deadline — its fence
            # would block until the child's BENCH_TIMEOUT kill), so a
            # slow/stuck depth-2 arm skips the unbounded one entirely
            for depth_ in (2, 1):
                d_ = tempfile.mkdtemp(prefix="bench_sweeppipe_")
                try:
                    with obs.span("sweep_ab", depth=depth_):
                        t0 = time.perf_counter()
                        _sweep(
                            jax.random.PRNGKey(123), batch, recipe,
                            nreal=sp_chunk * sp_nchunks, chunk=sp_chunk,
                            checkpoint_path=os.path.join(d_, "s.npz"),
                            pipeline_depth=depth_,
                            drain_timeout_s=300.0,
                        )
                        sp[f"depth{depth_}_s"] = round(
                            time.perf_counter() - t0, 3
                        )
                finally:
                    shutil.rmtree(d_, ignore_errors=True)
                if time.perf_counter() - t0 > 300:
                    sp["aborted"] = "depth arm exceeded 300s"
                    break
            if "depth2_s" in sp and "depth1_s" in sp:
                sp["speedup_depth2_vs_depth1"] = round(
                    sp["depth1_s"] / sp["depth2_s"], 3
                )
            extra["sweep_pipeline"] = sp
        except Exception as exc:
            extra["sweep_pipeline_error"] = repr(exc)[:200]

    # ---- CPU-oracle baseline (VERDICT r3 item 8): one honest measured
    # speedup ratio replacing the soft north-star multiple. ~20 s of
    # host-side numpy; BENCH_CPU_ORACLE=0 skips it.
    if os.environ.get("BENCH_CPU_ORACLE", "1") == "1":
        try:
            orate = _cpu_oracle_rate()
            extra["cpu_oracle_real_per_s"] = round(orate, 4)
            extra["speedup_vs_cpu_oracle"] = round(rate / orate, 1)
        except Exception as exc:
            extra["cpu_oracle_error"] = repr(exc)[:200]

    # ---- achieved FLOP/s + roofline from XLA's own cost model (VERDICT
    # r2 weak #3: "fast" must be a measured claim). One shared extraction
    # (obs.devprof, also used by benchmarks/fast_capture.py): jax.cost.*
    # and jax.roofline.* gauges land in the telemetry block below, and
    # the flat fields (xla_flops_per_chunk, achieved_tflops_per_s,
    # mfu_vs_bf16_peak_pct, intensity, bound class) keep their bench-diff
    # alignable spellings. The MFU peak is the bf16 MXU number for the
    # recorded device_kind; the workload is f32, so MFU is a conservative
    # lower bound on hardware utilization.
    from pta_replicator_tpu.obs import devprof

    extra.update(devprof.bench_cost_fields(
        compiled, reps=nrep, elapsed_s=elapsed,
        device_kind=extra["device_kind"], label="bench.run_chunk",
    ))

    # instrumented_jit labels that (re)compiled during this run (the
    # sweep A/B's realize engine): record their jax.cost.* gauges too.
    # CPU-only inside capture_pending — on the tunneled TPU a re-lower
    # could burn the window, and the AOT block above already covers the
    # headline executable.
    try:
        devprof.capture_pending()
    except Exception as exc:
        extra["devprof_pending_error"] = repr(exc)[:150]

    # ---- per-stage breakdown (VERDICT r2 item 3): ms per realization of
    # each injection op, measured standalone over a small key batch
    try:
        # standalone per-stage timings are dispatch-dominated UPPER BOUNDS
        # on the tunneled backend (they sum to ~7x the fused cost);
        # benchmarks/fused_ablation.py measures true fused marginals
        extra["stages_standalone_upper_bound_ms"] = _stage_breakdown(
            batch, recipe
        )
    except Exception as exc:
        extra["stage_breakdown_error"] = repr(exc)

    # per-stage wall times + jax compile/trace counters, captured by the
    # obs subsystem across everything this child process just ran
    try:
        extra["telemetry"] = obs.telemetry_summary()
        mem = obs.device_memory_snapshot()
        if any("bytes_in_use" in m for m in mem):
            extra["telemetry"]["device_memory"] = mem
    except Exception as exc:
        extra["telemetry_error"] = repr(exc)
    print(
        json.dumps(
            {
                "metric": _METRIC,
                "value": round(rate, 3),
                "unit": "realizations/s",
                "vs_baseline": round(rate / _NORTH_STAR_RATE, 3),
                **_provenance(),
                **extra,
            }
        )
    )
    if bench_telemetry:
        obs.finish_capture(context={"bench": True, "chunk": chunk})


def main():
    if os.environ.get("BENCH_CHILD") == "1":
        try:
            _bench()
        except BaseException:
            # SystemExit (env-validation raises) never reaches
            # sys.excepthook, and on other failures the excepthook only
            # writes the postmortem: finish_capture inside the except
            # flushes postmortem AND metrics/meta, so a BENCH_TELEMETRY
            # dir never reads as a SIGKILLed run after a config typo.
            # No-op when BENCH_TELEMETRY is unset (no capture started).
            from pta_replicator_tpu import obs

            obs.finish_capture()
            raise
        return

    deadline = float(os.environ.get("BENCH_TIMEOUT", "1500"))
    tries = int(os.environ.get("BENCH_PROBE_TRIES", "3"))
    t_start = time.monotonic()

    # chunk retry ladder: the default 800-realization chunk is tuned for
    # a v5e's HBM; if a future backend/shape OOMs, halve and retry so the
    # unattended end-of-round run still records a number instead of a
    # failure JSON. A user-set BENCH_CHUNK pins the ladder to that value.
    # The child probes in-process (probe-and-hold): the transient exit
    # codes (3 = backend init wedged or failed fast, 4 = silent fallback
    # to the wrong backend) and the backoff ladder are the SHARED tunnel
    # policy in pta_replicator_tpu.faults.retry — one classifier, one
    # backoff shape (20 s then 40 s, jittered) for bench AND the
    # production supervisors (docs/robustness.md). Transient exits retry
    # the SAME chunk (the probe failed, not the workload), bounded by
    # tries.
    from pta_replicator_tpu.faults.retry import (
        TRANSIENT_EXIT_CODES,
        TUNNEL_POLICY,
        backoff_delay,
    )

    chunks = (
        [os.environ["BENCH_CHUNK"]]
        if os.environ.get("BENCH_CHUNK")
        else ["800", "400", "200"]
    )
    last = "deadline left no time for any chunk attempt"
    tried = []
    wedges = 0
    ci = 0
    while ci < len(chunks):
        chunk = chunks[ci]
        env = dict(os.environ, BENCH_CHILD="1", BENCH_CHUNK=chunk)
        budget = deadline - (time.monotonic() - t_start)
        # always make the first attempt with whatever budget remains (a
        # short BENCH_TIMEOUT is a legitimate harness smoke run); only
        # retries need a meaningful slice of time to be worth spawning
        if budget <= 0 or (tried and budget <= 60):
            break
        tried.append(chunk)
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                timeout=budget,
                capture_output=True,
                text=True,
                env=env,
            )
        except subprocess.TimeoutExpired:
            _fail(
                f"bench child (chunk {chunk}) killed at its {budget:.0f}s "
                f"slice of the {deadline:.0f}s deadline"
                + (f" after earlier attempts {tried[:-1]}" if tried[:-1] else "")
            )
            return
        if r.returncode in TRANSIENT_EXIT_CODES:
            tail = (r.stderr or r.stdout or "").strip()[-300:]
            wedges += 1
            if wedges >= tries:
                _fail(
                    f"TPU backend unreachable after {wedges} in-process "
                    f"probes: {tail}"
                )
                return
            time.sleep(backoff_delay(wedges, TUNNEL_POLICY))
            continue  # same chunk — the probe failed, not the workload
        lines = [l for l in r.stdout.splitlines() if l.strip().startswith("{")]
        if r.returncode == 0 and lines:
            print(lines[-1])
            return
        # classify on the FULL output: XLA appends multi-KB allocation
        # dumps after RESOURCE_EXHAUSTED, so a truncated tail often
        # lacks the keyword
        full = (r.stderr or "") + (r.stdout or "")
        last = (
            f"rc={r.returncode}, no JSON line; "
            + (r.stderr or r.stdout).strip()[-400:]
        )
        oom = "RESOURCE_EXHAUSTED" in full or "out of memory" in full.lower()
        if not oom:
            break
        ci += 1  # OOM: halve the chunk and try again
    _fail(f"bench child failed (chunks tried: {tried}): {last}")


if __name__ == "__main__":
    main()
