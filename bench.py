"""Benchmark: NG15-scale dataset realizations per second on one chip.

Workload (the reference's realistic configuration, BASELINE.md): 68
pulsars x 7,758 TOAs, per-backend EFAC+EQUAD (4 backends), ECORR jitter,
30-mode power-law red noise, Hellings-Downs-correlated GWB on the default
npts=600/howml=10 grid (~3,000 frequency bins), a 100-source CW outlier
catalog, and a per-pulsar quadratic refit — i.e. one complete synthetic
dataset per realization.

North star (BASELINE.json): 1,000 such realizations in < 60 s on a v5e-8
=> 16.67 realizations/s for the whole 8-chip slice. ``vs_baseline`` below
is single-chip-rate / 16.67: a value >= 1 means ONE chip beats the target
set for eight (the realization axis is embarrassingly parallel, so 8 chips
scale this ~8x further; tests/test_sharding.py validates that path).

Prints exactly one JSON line (stdout). Tuning knobs via env:
BENCH_CHUNK (realizations per jitted call, default 100), BENCH_NREP
(timed repetitions, default 5), BENCH_PRNG ('threefry' default; 'rbg'
uses the hardware RngBitGenerator for the per-realization draws —
faster on TPU, still threefry-quality key splits).
"""
import json
import os
import time

import numpy as np


def main():
    import jax

    prng = os.environ.get("BENCH_PRNG", "threefry")
    if prng not in ("threefry", "rbg"):
        raise SystemExit(f"BENCH_PRNG must be 'threefry' or 'rbg', got {prng!r}")
    if prng == "rbg":
        jax.config.update("jax_default_prng_impl", "rbg")
    import jax.numpy as jnp

    from pta_replicator_tpu.batch import synthetic_batch
    from pta_replicator_tpu.models.batched import (
        Recipe,
        deterministic_delays,
        quadratic_fit_subtract,
        realization_delays,
        residualize,
    )
    from pta_replicator_tpu.ops.orf import hellings_downs_matrix

    npsr, ntoa, nbackend, ncw = 68, 7758, 4, 100
    batch = synthetic_batch(npsr=npsr, ntoa=ntoa, nbackend=nbackend, seed=0)

    rng = np.random.default_rng(0)
    phat = np.asarray(batch.phat, dtype=np.float64)
    locs = np.stack(
        [np.arctan2(phat[:, 1], phat[:, 0]), np.arccos(np.clip(phat[:, 2], -1, 1))],
        axis=1,
    )
    orf = hellings_downs_matrix(locs)
    cat = np.stack(
        [
            np.arccos(rng.uniform(-1, 1, ncw)),
            rng.uniform(0, 2 * np.pi, ncw),
            10 ** rng.uniform(8, 9.5, ncw),
            rng.uniform(50, 1000, ncw),
            10 ** rng.uniform(-8.8, -7.6, ncw),
            rng.uniform(0, 2 * np.pi, ncw),
            rng.uniform(0, np.pi, ncw),
            np.arccos(rng.uniform(-1, 1, ncw)),
        ]
    )
    recipe = Recipe(
        efac=jnp.asarray(rng.uniform(0.9, 1.3, (npsr, nbackend))),
        log10_equad=jnp.asarray(rng.uniform(-7.5, -6.0, (npsr, nbackend))),
        log10_ecorr=jnp.asarray(rng.uniform(-7.5, -6.3, (npsr, nbackend))),
        rn_log10_amplitude=jnp.asarray(rng.uniform(-14.5, -13.0, npsr)),
        rn_gamma=jnp.asarray(rng.uniform(2.0, 5.0, npsr)),
        gwb_log10_amplitude=jnp.asarray(-14.0),
        gwb_gamma=jnp.asarray(4.33),
        orf_cholesky=jnp.asarray(np.linalg.cholesky(np.asarray(orf))),
        cgw_params=jnp.asarray(cat),
        gwb_npts=600,
        gwb_howml=10.0,
        cgw_chunk=100,
    )

    chunk = int(os.environ.get("BENCH_CHUNK", "100"))  # realizations/call

    @jax.jit
    def run_chunk(key):
        keys = jax.random.split(key, chunk)
        static = deterministic_delays(batch, recipe)

        def one(k):
            d = realization_delays(k, batch, recipe) + static
            d = quadratic_fit_subtract(d, batch)
            return residualize(d, batch)

        res = jax.vmap(one)(keys)
        # reduce on device: per-realization, per-pulsar RMS (avoids hauling
        # (R, 68, 7758) residual cubes back to host in the timing loop)
        return jnp.sqrt(
            jnp.sum(res**2 * batch.mask, axis=-1) / jnp.sum(batch.mask, axis=-1)
        )

    # warm-up / compile. NOTE: sync via host readback of the (chunk, Np)
    # reduction, not block_until_ready() — on the remote-tunneled TPU
    # backend block_until_ready returns at dispatch, before execution.
    # Device execution is FIFO, so reading the last chunk's result back
    # fences every queued chunk.
    out = run_chunk(jax.random.PRNGKey(0))
    np.asarray(out)

    nrep = int(os.environ.get("BENCH_NREP", "5"))
    t0 = time.perf_counter()
    for i in range(nrep):
        out = run_chunk(jax.random.PRNGKey(i + 1))
    np.asarray(out)
    elapsed = time.perf_counter() - t0

    rate = nrep * chunk / elapsed
    north_star_rate = 1000.0 / 60.0  # v5e-8 whole-slice target
    print(
        json.dumps(
            {
                "metric": (
                    "NG15-scale full-dataset realizations/sec, single chip "
                    "(68 psr x 7758 TOAs: EFAC+EQUAD+ECORR+RN30+HD-GWB(Nf~3000)"
                    "+100-CW catalog+quadratic fit)"
                ),
                "value": round(rate, 3),
                "unit": "realizations/s",
                "vs_baseline": round(rate / north_star_rate, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
