#!/bin/bash
# Re-probe the tunnel on a ~4 min cadence; on a live window run the
# evidence battery in priority order. Every stage writes to /tmp and is
# promoted into the repo only when it produced valid JSON, so a
# mid-battery wedge can never clobber evidence captured by an earlier
# window; completed artifacts are skipped on later windows, and the loop
# keeps hunting until the whole battery is in.
cd /root/repo
LOG=/tmp/capture_log.txt
log() { date -u +"%H:%M:%SZ $*" >> $LOG; }

have() { # $1: artifact — present, parses as JSON, and is NOT an error
  # report (several battery scripts print {'error': ...} with exit 0;
  # freezing one of those as evidence would stop the retry forever)
  [ -s "$1" ] && python -c "
import json, sys
d = json.load(open(sys.argv[1]))
sys.exit(1 if (isinstance(d, dict) and d.get('error')) else 0)" "$1" 2>/dev/null
}

alive() { # 90 s probe: is the tunnel still breathing? A wedged tunnel
  # must not let the battery burn each stage's full timeout in sequence
  # (~3 h of dead time before the loop would hunt again).
  timeout 90 python -c "
import numpy as np, jax, jax.numpy as jnp
print(float(np.asarray(jnp.ones((128,128)) @ jnp.ones((128,128))).sum()))
" >/dev/null 2>&1
}

stage() { # $1 target  $2 timeout  $3... command (stdout -> target)
  local target=$1 tmo=$2; shift 2
  [ -f /tmp/tunnel_dead ] && return 2
  if have "$target"; then log "skip $(basename $target) (already captured)"; return 0; fi
  if ! alive; then
    log "tunnel dead before $(basename $target); back to hunting"
    touch /tmp/tunnel_dead
    return 2
  fi
  local tmp=/tmp/stage_out_$$.json
  timeout "$tmo" "$@" > "$tmp" 2>> /tmp/stage_err.txt
  local rc=$?
  if [ $rc -eq 0 ] && have "$tmp"; then
    mv "$tmp" "$target"; log "captured $(basename $target)"
  else
    log "stage $(basename $target) failed rc=$rc"
    return 1
  fi
}

bench_stage() { # $1 target  $2 done-marker  $3... bench cmd
  # bench.py emits a value-0.0 failure JSON on a wedge: promote only a
  # NONZERO value so a failed run never overwrites or freezes evidence
  local target=$1 marker=$2; shift 2
  [ -f /tmp/tunnel_dead ] && return 2
  if ! alive; then
    log "tunnel dead before $(basename $target); back to hunting"
    touch /tmp/tunnel_dead
    return 2
  fi
  local tmp=/tmp/bench_stage_$$.json
  timeout 1800 "$@" > "$tmp" 2>>/tmp/stage_err.txt
  local rc=$?
  log "$(basename $target) bench rc=$rc"
  if [ $rc -eq 0 ] && python -c "
import json,sys; sys.exit(0 if json.load(open(sys.argv[1])).get('value') else 1)" "$tmp"; then
    cp "$tmp" "$target"
    log "promoted $(basename $target)"
    touch "$marker"
  fi
}

log "capture loop started"
for i in $(seq 1 150); do
  timeout 2400 python benchmarks/fast_capture.py >> /tmp/fast_capture.out 2>&1
  rc=$?
  log "fast_capture attempt $i rc=$rc"
  if [ $rc -eq 0 ] || [ $rc -eq 5 ] || [ $rc -eq 6 ]; then
    # rc=5: wedged mid-ladder; rc=6: a rung errored on a live window —
    # either way early rungs may have landed and the backend was up
    log "window found (rc=$rc); running battery"
    rm -f /tmp/tunnel_dead
    # once /tmp/bench_canonical_done is set the canonical result owns
    # BENCH_PREVIEW_r05.json permanently: fast_capture's write_preview
    # checks the same marker and diverts later previews to
    # BENCH_PREVIEW_r05_fastcapture.json instead of clobbering it
    [ -f /tmp/bench_canonical_done ] || \
      bench_stage /root/repo/BENCH_PREVIEW_r05.json /tmp/bench_canonical_done python bench.py
    stage /root/repo/VPU_CEILING_r05.json     900 python benchmarks/vpu_ceiling.py
    stage /root/repo/VALIDATE_DEVICE_r05.json 1200 python benchmarks/validate_device.py 2000
    [ -f /tmp/bench_gls_done ] || \
      bench_stage /root/repo/BENCH_GLS_r05.json /tmp/bench_gls_done env BENCH_FIT=gls python bench.py
    stage /root/repo/ABLATION_r05.json        1200 python benchmarks/fused_ablation.py 800 5
    stage /root/repo/CW_SCALING_r05.json      2400 python benchmarks/cw_scaling.py 6 both
    stage /root/repo/SWEEP_RESUME_r05.json    3000 python benchmarks/sweep_kill_resume.py 1000000 800
    stage /root/repo/CW_SCALING_1E7_r05.json  3000 python benchmarks/cw_scaling.py 7 both
    if [ -f /tmp/bench_canonical_done ] \
       && have /root/repo/VPU_CEILING_r05.json \
       && have /root/repo/VALIDATE_DEVICE_r05.json \
       && [ -f /tmp/bench_gls_done ] \
       && have /root/repo/ABLATION_r05.json \
       && have /root/repo/CW_SCALING_r05.json \
       && have /root/repo/SWEEP_RESUME_r05.json \
       && have /root/repo/CW_SCALING_1E7_r05.json; then
      log "battery complete"
      exit 0
    fi
    log "battery incomplete; continuing to hunt windows"
  fi
  sleep 45
done
log "gave up"
