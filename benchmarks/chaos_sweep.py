"""Chaos bench: the flagship-shaped sweep under seeded fault schedules,
pinned byte-identical to the fault-free run — plus a serving-saturation
arm proving admission control sheds load instead of growing the queue.

The robustness contract PR 11 ships (docs/robustness.md) is only worth
committing if it is *measured*: this bench runs the pipelined sweep
(reduce_fn=None — full residual cubes through readback + checkpoint
I/O, the I/O-heavy flagship shape) fault-free once, then under several
RANDOMIZED-BUT-SEEDED fault schedules, each containing at least

* one transient chunk failure (``drain:raise@chunk=K``),
* one injected stall long enough to trip the executor's
  ``DrainTimeout`` (``drain:stall=S@chunk=K2`` with S > the arm's
  drain deadline), and
* one torn checkpoint write (``checkpoint_write:torn@call=N`` — the
  in-flight temp file is truncated mid-write and the write raises,
  exactly the artifact an interrupted write leaves),

and asserts every chaos arm (a) completes — the supervised-recovery
loop absorbs all of it, (b) produces a consolidated checkpoint
BYTE-IDENTICAL to the fault-free run (sha256 over the file), and
(c) shows its retries in telemetry (``sweep.chunk_retries`` advanced —
a recovery nobody can see is indistinguishable from a wedge). The
headline ``fault_overhead`` is the median faulted wall over the
fault-free wall, minus one: what surviving this schedule *costs*.

The server arm floods a deadline-bounded, queue-bounded
``LikelihoodServer`` far past its capacity and asserts rejects
(``ServerSaturated``) and deadline expiries happened instead of
unbounded queue growth, and that every admitted future resolved
(result or exception — never stranded) after ``stop()``.

Prints one JSON line; committed as ``CHAOS_r11_cpu.json``. Exit 1 when
any gate fails, so CI can run a small configuration directly.

Usage: python benchmarks/chaos_sweep.py [--fast]
  env: CHAOS_NREAL/CHAOS_CHUNK/CHAOS_NPSR/CHAOS_NTOA/CHAOS_ARMS/
  CHAOS_SEED/CHAOS_SERVE_N reshape the workload (--fast presets a
  seconds-scale CI configuration).
"""
import hashlib
import json
import os
import random
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from pta_replicator_tpu import likelihood as lk  # noqa: E402
from pta_replicator_tpu.batch import synthetic_batch  # noqa: E402
from pta_replicator_tpu.faults import inject  # noqa: E402
from pta_replicator_tpu.faults.retry import RetryPolicy  # noqa: E402
from pta_replicator_tpu.models.batched import Recipe  # noqa: E402
from pta_replicator_tpu.obs import REGISTRY, counter, names  # noqa: E402
from pta_replicator_tpu.utils.provenance import (  # noqa: E402
    EVIDENCE_SCHEMA_VERSION,
    provenance_stamp,
)
from pta_replicator_tpu.utils.sweep import sweep  # noqa: E402

#: the per-arm drain deadline; injected stalls exceed it so every chaos
#: arm exercises the DrainTimeout -> classify-transient -> resume path
DRAIN_TIMEOUT_S = 2.0
STALL_S = 2 * DRAIN_TIMEOUT_S

#: fast in-process recovery for a bench that injects its own faults
#: (production default backoff is 0.5 s base — here that would just
#: pad fault_overhead with sleep)
RETRY_POLICY = RetryPolicy(max_attempts=5, base_delay_s=0.1,
                           multiplier=2.0, max_delay_s=2.0, jitter=0.25)


def _sha(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for blk in iter(lambda: fh.read(1 << 20), b""):
            h.update(blk)
    return h.hexdigest()


def _faults_injected_total() -> float:
    """Sum of the labeled faults.injected counters (site= x kind=)."""
    return sum(
        m.value for m in REGISTRY.metrics()
        if getattr(m, "name", None) == names.FAULTS_INJECTED
    )


def make_schedule(rng: random.Random, nchunks: int) -> str:
    """One randomized schedule satisfying the chaos gate: >=1 transient
    chunk failure, >=1 DrainTimeout-tripping stall, >=1 torn checkpoint
    write — plus an optional seeded device-lost extra."""
    chunks = rng.sample(range(1, nchunks), 2)
    specs = [
        f"drain:raise@chunk={chunks[0]}",
        f"drain:stall={STALL_S:g}@chunk={chunks[1]}",
        # every chunk issues two checkpoint_write calls (chunk file +
        # meta sidecar): any call index lands on a real write
        f"checkpoint_write:torn@call={rng.randint(2, 2 * nchunks - 1)}",
    ]
    if rng.random() < 0.5:
        specs.append(f"dispatch:device_lost@chunk={rng.randint(1, nchunks - 1)}")
    return ";".join(specs)


def run_sweep_arm(key, batch, recipe, nreal, chunk, path,
                  schedule=None, seed=0):
    """One sweep run (optionally under an armed schedule); returns
    (wall_s, sha256, chunk_retries_delta, faults_fired)."""
    retries0 = counter(names.SWEEP_CHUNK_RETRIES).value
    injected0 = _faults_injected_total()
    fired = []
    t0 = time.monotonic()
    if schedule is None:
        sweep(key, batch, recipe, nreal=nreal, chunk=chunk,
              checkpoint_path=path, reduce_fn=None,
              drain_timeout_s=DRAIN_TIMEOUT_S,
              retry_policy=RETRY_POLICY)
    else:
        with inject.armed(schedule, seed=seed):
            sweep(key, batch, recipe, nreal=nreal, chunk=chunk,
                  checkpoint_path=path, reduce_fn=None,
                  drain_timeout_s=DRAIN_TIMEOUT_S, chunk_retries=4,
                  retry_policy=RETRY_POLICY)
            fired = inject.fired()
    wall = time.monotonic() - t0
    return (
        wall, _sha(path),
        counter(names.SWEEP_CHUNK_RETRIES).value - retries0,
        fired if schedule is not None
        else _faults_injected_total() - injected0,
    )


def run_server_arm(ckpt, batch, recipe, serve_n: int) -> dict:
    """Flood a bounded/deadline'd server far past capacity from
    closed-loop-free submitters: the point is saturation, so clients
    do NOT wait between submits."""
    import threading

    bank = lk.RealizationBank.from_checkpoint(ckpt)
    # a 10 ms deadline against a 16-deep queue and ~ms engine batches:
    # requests admitted near the back of a full queue expire before
    # their batch forms — the bench shows BOTH shedding mechanisms
    server = lk.LikelihoodServer(
        bank, batch, recipe, axes=("rn_log10_amplitude",),
        max_batch=4, max_delay_s=0.002,
        max_queue=16, request_deadline_s=0.01,
    )
    futs = []
    futs_lock = threading.Lock()

    def flood(lo, hi):
        rng = np.random.default_rng(lo)
        for _ in range(lo, hi):
            try:
                f = server.submit(
                    rn_log10_amplitude=float(rng.uniform(-14.5, -13.0))
                )
            except lk.ServerSaturated:
                continue  # shed; counted server-side in stats()
            with futs_lock:
                futs.append(f)

    with server:
        server.evaluate(rn_log10_amplitude=-13.5)  # compile warmup
        server.reset_stats()
        # exact partition: all serve_n submits are attempted, so the
        # reported "submitted" reconciles with admitted + rejected
        bounds = [k * serve_n // 4 for k in range(5)]
        threads = [
            threading.Thread(target=flood,
                             args=(bounds[k], bounds[k + 1]))
            for k in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    # stats AFTER stop(): the flood outruns the worker, so a snapshot
    # taken at join time misses the queued tail the drain still serves
    stats = server.stats()
    served = expired = stranded = 0
    for f in futs:
        if not f.done():
            stranded += 1
            continue
        if f.exception() is None:
            served += 1
        elif isinstance(f.exception(), lk.DeadlineExpired):
            expired += 1
    return {
        "submitted": serve_n,
        "admitted": len(futs),
        "served": served,
        "rejected": stats["rejected"],
        "deadline_expired": stats["deadline_expired"],
        "expired_futures": expired,
        "stranded_futures": stranded,
        "max_queue": server.max_queue,
        "request_deadline_s": server.request_deadline_s,
        "latency": stats["latency"],
        "coalesce_efficiency": round(stats["coalesce_efficiency"], 4),
        # the gate: under ~serve_n requests against a 16-deep queue,
        # load was SHED (rejects and/or expiries), nothing stranded
        "queue_bounded": bool(
            stats["rejected"] > 0 and stranded == 0
        ),
    }


def main() -> int:
    fast = "--fast" in sys.argv[1:]
    nreal = int(os.environ.get("CHAOS_NREAL", "96" if fast else "256"))
    chunk = int(os.environ.get("CHAOS_CHUNK", "16" if fast else "32"))
    npsr = int(os.environ.get("CHAOS_NPSR", "4" if fast else "8"))
    ntoa = int(os.environ.get("CHAOS_NTOA", "1024" if fast else "4096"))
    arms = int(os.environ.get("CHAOS_ARMS", "1" if fast else "3"))
    seed = int(os.environ.get("CHAOS_SEED", "11"))
    serve_n = int(os.environ.get("CHAOS_SERVE_N", "200" if fast else "400"))

    nchunks = nreal // chunk
    if nreal % chunk or nchunks < 3:
        raise SystemExit(
            f"chaos_sweep needs nreal a multiple of chunk and >= 3 "
            f"chunks to place a raise + a stall on distinct non-zero "
            f"chunks (got CHAOS_NREAL={nreal}, CHAOS_CHUNK={chunk} -> "
            f"{nchunks} chunks)"
        )

    batch = synthetic_batch(npsr=npsr, ntoa=ntoa, seed=3,
                            dtype=np.float64)
    recipe = Recipe(
        efac=jnp.ones(npsr),
        rn_log10_amplitude=jnp.full(npsr, -13.5),
        rn_gamma=jnp.full(npsr, 4.0),
    )
    key = jax.random.PRNGKey(7)
    rng = random.Random(seed)

    d = tempfile.mkdtemp(prefix="chaos_sweep_")
    failures = []
    try:
        # warmup: compile outside every timed arm
        sweep(key, batch, recipe, nreal=chunk, chunk=chunk,
              checkpoint_path=os.path.join(d, "warm.npz"),
              reduce_fn=None)

        ref_ck = os.path.join(d, "ref.npz")
        ref_wall, ref_sha, _r, _f = run_sweep_arm(
            key, batch, recipe, nreal, chunk, ref_ck
        )

        chaos = []
        for a in range(arms):
            schedule = make_schedule(rng, nchunks)
            ck = os.path.join(d, f"chaos{a}.npz")
            try:
                wall, sha, retries, fired = run_sweep_arm(
                    key, batch, recipe, nreal, chunk, ck,
                    schedule=schedule, seed=seed + a,
                )
            except BaseException as exc:  # noqa: BLE001 — the bench verdict
                failures.append(
                    f"arm {a} ({schedule}) did not recover: {exc!r}"
                )
                chaos.append({"schedule": schedule, "recovered": False,
                              "error": repr(exc)[:300]})
                continue
            arm_rec = {
                "schedule": schedule,
                "recovered": True,
                "wall_s": round(wall, 3),
                "byte_identical": sha == ref_sha,
                "chunk_retries": retries,
                "faults_fired": len(fired),
                "fired": fired,
            }
            chaos.append(arm_rec)
            if not arm_rec["byte_identical"]:
                failures.append(f"arm {a} checkpoint diverged")
            if retries < 1:
                failures.append(f"arm {a} recovered with no visible retry")
            if len(fired) < 3:
                failures.append(
                    f"arm {a} fired only {len(fired)} of >=3 faults"
                )

        server = run_server_arm(ref_ck, batch, recipe, serve_n)
        if not server["queue_bounded"]:
            failures.append(
                "server arm: no rejects under saturation, or stranded "
                f"futures ({server})"
            )

        recovered = sum(1 for c in chaos if c.get("recovered"))
        walls = [c["wall_s"] for c in chaos if c.get("recovered")]
        rec = {
            "bench": "chaos_sweep",
            "backend": jax.default_backend(),
            "nreal": nreal, "chunk": chunk, "nchunks": nchunks,
            "npsr": npsr, "ntoa": ntoa,
            "drain_timeout_s": DRAIN_TIMEOUT_S,
            "stall_s": STALL_S,
            "seed": seed,
            "fault_free_s": round(ref_wall, 3),
            "chaos_runs": arms,
            "recovered_runs": recovered,
            "byte_identical_all": all(
                c.get("byte_identical") for c in chaos
            ),
            # what surviving a schedule costs: median faulted wall over
            # the fault-free wall, minus one (ratio), and the absolute
            # seconds. On this seconds-scale CPU workload the absolute
            # number is the honest one — it is dominated by the
            # injected stall's drain deadline + the backoff ladder,
            # fixed costs the ratio amortizes away as the workload
            # grows to flagship scale
            "fault_overhead": (
                round(float(np.median(walls)) / ref_wall - 1.0, 3)
                if walls else None
            ),
            "fault_overhead_s": (
                round(float(np.median(walls)) - ref_wall, 3)
                if walls else None
            ),
            "chaos": chaos,
            "server": server,
            "ok": not failures,
            "failures": failures,
            **provenance_stamp(
                EVIDENCE_SCHEMA_VERSION,
                repo_root=os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))
                ),
            ),
        }
        print(json.dumps(rec))
        return 1 if failures else 0
    finally:
        shutil.rmtree(d, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
