"""Structured-covariance solver ladder: committed evidence that the
beyond-diagonal machinery is both FAST and RIGHT.

Arms, per problem size n (the per-pulsar TOA count):

* **ladder** — factor+solve wall time of the block-tridiagonal
  ("banded") and Kronecker kernels vs a dense Cholesky of the SAME
  matrix (the reference rung every structure must beat at scale):
  ``speedup_banded``/``speedup_kron`` higher-better, raw ``*_ms``
  lower-better (obs/regress.py directions). The blocked dense
  factorization is also timed against LAPACK as an info arm (on CPU
  LAPACK wins — the blocked kernel is the TPU/MXU formulation, kept
  bit-identical to its Pallas twin by tests/test_covariance.py).
* **oracle** — every CovOp's solve/logdet/sample against its numpy
  float64 dense oracle, gated at <= 1e-8 relative (the acceptance
  bar; runs in f64).
* **round-trip** — inject correlated noise through the production
  engine (banded CovOp in the Recipe, fold_in-derived stream), then
  recover the planted ``cov_log10_sigma`` and ``rn_log10_amplitude``
  with ``likelihood.infer.map_fit`` under the covariance-aware
  likelihood: gated at |fit - truth| <= 3 Fisher sigma per parameter.
* **fuzz-family** — a mini differential sweep: the first K generated
  scenarios carrying a ``covariance`` section run batched-vs-oracle
  (scenarios/fuzz.py); agreement_rate must be 1.0. (The full 200-
  scenario matrix with the coverage gate lives in
  benchmarks/scenario_fuzz.py -> FUZZ_r*_cpu.json.)

Prints one JSON line; committed as ``COV_r13_cpu.json`` and diffed by
``bench-diff``. Exit 1 on any gate miss — scripts/check.sh runs the
--fast configuration on every push.

Usage: python benchmarks/cov_solve.py [--fast] [--out PATH]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from pta_replicator_tpu.covariance import (  # noqa: E402
    banded_from_times,
    dense_from_times,
    kron_time_channel,
)
from pta_replicator_tpu.covariance import kernels as K  # noqa: E402
from pta_replicator_tpu.utils.provenance import (  # noqa: E402
    EVIDENCE_SCHEMA_VERSION,
    provenance_stamp,
)

NPSR = 2
SPAN_S = 16 * 365.25 * 86400.0


def _times(n, seed):
    rng = np.random.default_rng(seed)
    return np.sort(rng.uniform(0.0, SPAN_S, (NPSR, n)), axis=1)


def _median_ms(fn, reps):
    fn()  # warm (compile)
    walls = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        walls.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(walls))


def _rel(a, b):
    denom = max(float(np.max(np.abs(b))), 1e-300)
    return float(np.max(np.abs(np.asarray(a) - np.asarray(b)))) / denom


def ladder_arm(n, reps, failures):
    t = _times(n, seed=n)
    full = np.ones((NPSR, n))
    banded = banded_from_times(t, full, rho=0.6, corr_s=40 * 86400.0,
                               block=32, dtype=np.float64)
    kron = kron_time_channel(t, channels=4, time_ell_s=20 * 86400.0,
                             chan_rho=0.8, dtype=np.float64)
    dense = dense_from_times(t, full, corr_s=60 * 86400.0,
                             dtype=np.float64)
    rng = np.random.default_rng(n + 1)
    X = jnp.asarray(rng.standard_normal((NPSR, n, 4)))

    # --- banded: structured factor+solve vs dense Cholesky of SAME C
    Db, Eb = banded.D, banded.E

    def banded_solve():
        Ld, M = K.block_tridiag_cholesky(Db, Eb)
        return K.block_tridiag_solve(
            Ld, M, X.reshape(NPSR, -1, banded.block, 4)
        )

    Cb = jnp.asarray(banded.dense(pad_identity=True))

    def dense_solve_b():
        return K.dense_solve(Cb, X, method="xla")

    banded_ms = _median_ms(jax.jit(banded_solve), reps)
    dense_b_ms = _median_ms(dense_solve_b, reps)

    # --- kron: per-factor factor+solve vs dense Cholesky of SAME C
    Ct, Cf = kron.Ct, kron.Cf

    def kron_solve():
        Lt, Lf = K.kron_cholesky(Ct, Cf)
        return K.kron_solve(Lt, Lf, X)

    Ck = jnp.asarray(kron.dense())

    def dense_solve_k():
        return K.dense_solve(Ck, X, method="xla")

    kron_ms = _median_ms(jax.jit(kron_solve), reps)
    dense_k_ms = _median_ms(dense_solve_k, reps)

    # --- blocked dense factorization vs LAPACK (info: the MXU
    # formulation, expected to LOSE on CPU)
    blocked_ms = _median_ms(
        lambda: K.blocked_cholesky(Cb, block=128, backend="xla"), reps
    )
    lapack_ms = _median_ms(lambda: jnp.linalg.cholesky(Cb), reps)

    # --- oracle gates (solve + logdet + sample, every op, f64)
    worst = 0.0
    key = jax.random.PRNGKey(n)
    for name, op in (("banded", banded), ("kron", kron),
                     ("dense", dense)):
        C = op.dense(pad_identity=True)
        x = np.asarray(X[..., 0])
        z_solve = np.asarray(op.solve(jnp.asarray(x), s2=2.0))
        z_oracle = np.stack([
            np.linalg.solve(2.0 * C[p], x[p]) for p in range(NPSR)
        ])
        worst = max(worst, _rel(z_solve, z_oracle))
        ld = np.asarray(op.logdet(s2=2.0))
        ld_o = np.array([
            np.linalg.slogdet(C[p])[1] for p in range(NPSR)
        ]) + np.asarray(op.nvalid) * np.log(2.0)
        worst = max(worst, _rel(ld, ld_o))
        z = np.asarray(jax.random.normal(key, (NPSR, n), np.float64))
        smp = np.asarray(op.sample(key, s2=2.0))
        L = np.linalg.cholesky(C)
        smp_o = np.einsum("pij,pj->pi", L, z) * np.sqrt(2.0)
        worst = max(worst, _rel(smp, smp_o))
    if worst > 1e-8:
        failures.append(
            f"n={n}: CovOp-vs-dense-oracle deviation {worst:.3e} > 1e-8"
        )

    return {
        "banded_ms": round(banded_ms, 3),
        "dense_vs_banded_ms": round(dense_b_ms, 3),
        "speedup_banded": round(dense_b_ms / banded_ms, 2),
        "kron_ms": round(kron_ms, 3),
        "dense_vs_kron_ms": round(dense_k_ms, 3),
        "speedup_kron": round(dense_k_ms / kron_ms, 2),
        "blocked_factor_ms": round(blocked_ms, 3),
        "lapack_factor_ms": round(lapack_ms, 3),
        "oracle_rel_disagreement": worst,
    }


def round_trip_arm(failures):
    """Inject white + red + banded correlated noise; recover the
    planted covariance amplitude (and the red-noise amplitude) with
    the covariance-aware likelihood + map_fit."""
    import dataclasses

    from pta_replicator_tpu.batch import synthetic_batch
    from pta_replicator_tpu.likelihood.infer import map_fit
    from pta_replicator_tpu.models.batched import Recipe, realize

    batch = synthetic_batch(npsr=3, ntoa=256, nbackend=2, seed=3,
                            dtype=np.float64)
    cov = banded_from_times(
        np.asarray(batch.toas_s), np.asarray(batch.mask), rho=0.6,
        corr_s=40 * 86400.0, block=16, dtype=np.float64,
    )
    truth = {"cov_log10_sigma": -6.3, "rn_log10_amplitude": -13.3}
    recipe = Recipe(
        efac=jnp.asarray(1.1),
        rn_log10_amplitude=jnp.asarray(truth["rn_log10_amplitude"]),
        rn_gamma=jnp.asarray(4.0),
        rn_nmodes=10,
        noise_cov=cov,
        cov_log10_sigma=jnp.asarray(truth["cov_log10_sigma"]),
    )
    res = np.asarray(realize(jax.random.PRNGKey(11), batch, recipe,
                             nreal=1, fit=False))[0]
    # realize() mean-subtracts each pulsar (residualize); marginalize a
    # constant design column so the likelihood is offset-invariant too
    # — without it the removed weighted mean reads as excess correlated
    # power and biases cov_log10_sigma by ~10 sigma (measured)
    design = jnp.asarray(np.ones(batch.toas_s.shape)[..., None])
    start = {k: v + 0.25 for k, v in truth.items()}
    fit = map_fit(jnp.asarray(res), batch, recipe, start, design=design)
    out = {"converged": bool(fit.converged),
           "iterations": int(fit.iterations)}
    for i, name in enumerate(fit.names):
        z = (fit.x[i] - truth[name]) / fit.sigma[i]
        out[f"{name}_fit"] = round(float(fit.x[i]), 4)
        out[f"{name}_truth"] = truth[name]
        out[f"{name}_sigma"] = round(float(fit.sigma[i]), 4)
        out[f"{name}_zscore"] = round(float(z), 3)
        if not np.isfinite(z) or abs(z) > 3.0:
            failures.append(
                f"round-trip: {name} recovered at {fit.x[i]:.3f} vs "
                f"planted {truth[name]} ({z:+.2f} sigma > 3)"
            )
    if not fit.converged:
        failures.append("round-trip: map_fit did not converge")
    return out


def fuzz_family_arm(k, failures):
    """The first k generated scenarios carrying a covariance section,
    through the full batched-vs-oracle differential."""
    from pta_replicator_tpu.scenarios import compile_spec
    from pta_replicator_tpu.scenarios import fuzz as fz

    n_run = 0
    worst = 0.0
    kinds = set()
    idx = 0
    while n_run < k and idx < 400:
        spec = fz.sample_spec(0, idx)
        idx += 1
        if spec.covariance is None:
            continue
        compiled = compile_spec(spec, validate=False)
        r = fz.run_scenario(compiled)
        n_run += 1
        kinds.update(f for f in compiled.families
                     if f.startswith("cov_"))
        v = r.verdicts.get("covariance")
        if v is not None:
            worst = max(worst, v["rel"])
        if not r.agree:
            failures.append(
                f"fuzz-family: scenario {spec.name} disagrees "
                f"(worst {r.worst_family} {r.worst_rel:.3e})"
            )
    agreement = 1.0 if not any(
        f.startswith("fuzz-family") for f in failures
    ) else 0.0
    return {
        "n_scenarios": n_run,
        "kinds": sorted(kinds),
        "agreement_rate": agreement,
        "max_rel_covariance": worst,
    }


def main() -> int:
    fast = "--fast" in sys.argv[1:]
    out_path = None
    argv = sys.argv[1:]
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    sizes = (128, 256) if fast else (256, 512, 1024)
    reps = 3 if fast else 5

    failures = []
    t0 = time.monotonic()
    arms = {}
    for n in sizes:
        arms[f"n{n}"] = ladder_arm(n, reps, failures)
    round_trip = round_trip_arm(failures)
    fuzz_family = fuzz_family_arm(2 if fast else 6, failures)

    big = arms[f"n{sizes[-1]}"]
    for leaf in ("speedup_banded", "speedup_kron"):
        if big[leaf] < 1.0:
            failures.append(
                f"ladder: {leaf} = {big[leaf]} at n={sizes[-1]} — the "
                "structured solve lost to dense Cholesky"
            )

    rec = {
        "bench": "cov_solve",
        "backend": jax.default_backend(),
        "fast": fast,
        "wall_s": round(time.monotonic() - t0, 3),
        "npsr": NPSR,
        "sizes": list(sizes),
        "arms": arms,
        "round_trip": round_trip,
        "fuzz_family": fuzz_family,
        "ok": not failures,
        "failures": failures,
        **provenance_stamp(
            EVIDENCE_SCHEMA_VERSION,
            repo_root=os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            ),
        ),
    }
    payload = json.dumps(rec)
    print(payload)
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(payload + "\n")
    if failures:
        # CI /dev/nulls stdout (scripts/check.sh); the reason for an
        # exit 1 must land on stderr or it is invisible
        for f in failures:
            print(f"cov_solve gate miss: {f}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
