"""Critical-path attribution acceptance (PR 16).

Replays the STAGES_r15 fused-vs-stacked stage-graph workload
(``benchmarks/stage_graph.py``'s ``build_workload`` — the streamed-CW
+ red-noise sweep with durable writes), captures each arm into a real
telemetry dir, and runs the offline attribution pass
(``obs/critpath.py``) over both captures. Gates per arm:

* **verdict matches ground truth** — the analyzer's ranked bottleneck
  must be the stage the occupancy busy table (the r15 methodology:
  in-window busy seconds per stage) names busiest. The two compute the
  same physics by different code paths: occupancy sums busy intervals,
  the attribution engine decomposes the window into exclusive shadow
  contributions — when they disagree, one of them is lying.
* **>=95% attribution** — ``attributed_fraction`` (window time covered
  by some stage) must reach 0.95 on both arms: a decomposition that
  cannot account for the window cannot rank what fills it.
* **trace-coherent chains** — every reconstructed per-chunk DAG chain
  carries ONE deterministic chunk trace id end to end.
* **offline-only** — the captures contain ZERO ``critpath_analyze``
  spans: the instrumented run paid nothing for the analysis, whose own
  cost is measured and recorded as ``analyzer.overhead_s``.

The cross-round ledger (``obs/ledger.py``) is exercised against the
repo's real committed artifacts: ingest count and windowed-gate verdict
are recorded (info, not a gate here — ``perf gate`` in check.sh is the
gate).

Prints one JSON line; exit 1 with reasons on stderr when a gate fails.

Usage: python benchmarks/critpath_attribution.py [--fast]
  (honors the same STAGE_GRAPH_* env knobs as stage_graph.py)
"""
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from stage_graph import build_workload, NPSR  # noqa: E402

from pta_replicator_tpu import obs  # noqa: E402
from pta_replicator_tpu.obs import critpath, ledger, names, occupancy  # noqa: E402
from pta_replicator_tpu.utils.provenance import provenance_stamp  # noqa: E402
from pta_replicator_tpu.utils.sweep import sweep  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the acceptance bound on attributed_fraction (ISSUE 16)
MIN_ATTRIBUTED = 0.95


def run_arm(fused, batch, recipe, key, nreal, chunk, workdir):
    """One captured sweep; returns (capture dir, wall_s)."""
    arm = "fused" if fused else "stacked"
    cap = os.path.join(workdir, f"cap_{arm}")
    ckpt = os.path.join(workdir, f"sweep_{arm}.npz")
    obs.reset_all()
    obs.start_capture(cap, stall_timeout_s=None)
    t0 = time.perf_counter()
    try:
        sweep(key, batch, recipe, nreal=nreal, chunk=chunk,
              checkpoint_path=ckpt, reduce_fn=None, pipeline_depth=2,
              durable=True, fused_stream=fused)
    finally:
        wall = time.perf_counter() - t0
        obs.finish_capture()
    return cap, wall


def ground_truth_bottleneck(cap):
    """The r15 methodology, independent of the attribution engine: per-
    stage busy seconds clipped to the phase window, busiest wins (name
    tiebreak, same as the analyzer's deterministic ordering)."""
    from pta_replicator_tpu.obs.report import load_events

    events = [e for e in load_events(os.path.join(cap, "events.jsonl"))
              if e.get("type") == "span"]
    per_stage = occupancy.stage_intervals(events)
    window = occupancy._phase_window(events)
    busy = {}
    for name, iv in per_stage.items():
        if occupancy.NESTED_STAGES.get(name) in per_stage:
            continue
        clipped = occupancy._clip(occupancy.merge_intervals(iv), *window)
        if clipped:
            busy[name] = occupancy.busy_seconds(clipped)
    return min(busy, key=lambda s: (-busy[s], s)), busy


def analyze_arm(arm, cap, wall, failures):
    """Attribution pass over one captured arm + the per-arm gates."""
    t0 = time.perf_counter()
    doc = critpath.analyze_capture(cap)
    analyze_wall = time.perf_counter() - t0
    if doc is None:
        failures.append(f"{arm}: capture produced no attributable stage spans")
        return None
    out = critpath.write_critpath(cap, doc=doc)

    expected, busy = ground_truth_bottleneck(cap)
    got = doc["verdict"]["bottleneck"]
    if got != expected:
        failures.append(
            f"{arm}: verdict names {got} but the occupancy busy table "
            f"names {expected} (busy {busy})"
        )
    if doc["attributed_fraction"] < MIN_ATTRIBUTED:
        failures.append(
            f"{arm}: attributed_fraction {doc['attributed_fraction']} "
            f"below the {MIN_ATTRIBUTED} acceptance bound "
            f"(blocked {doc['blocked_s']}s of {doc['window']['wall_s']}s)"
        )
    chunks = doc["chunks"] or {}
    if chunks.get("trace_coherent_fraction") != 1.0:
        failures.append(
            f"{arm}: per-chunk chains not trace-coherent "
            f"({chunks.get('trace_coherent_fraction')})"
        )
    with open(os.path.join(cap, "events.jsonl")) as fh:
        polluted = any(
            f'"{names.SPAN_CRITPATH_ANALYZE}"' in line for line in fh
        )
    if polluted:
        failures.append(
            f"{arm}: capture contains analyzer spans — the attribution "
            "pass leaked into the run it was attributing"
        )
    return {
        "capture_wall_s": round(wall, 3),
        "verdict": doc["verdict"]["summary"],
        "bottleneck": got,
        "ground_truth_bottleneck": expected,
        "attributed_fraction": doc["attributed_fraction"],
        "critical_path_s": doc["critical_path_s"],
        "blocked_s": doc["blocked_s"],
        "chunks": chunks.get("count"),
        "trace_coherent_fraction": chunks.get("trace_coherent_fraction"),
        "queue_wait_s": chunks.get("queue_wait_s"),
        "blocked_on_window_s": chunks.get("blocked_on_window_s"),
        "stage_critical_s": {
            s: st["critical_s"] for s, st in doc["stages"].items()
        },
        # the offline cost of the analysis itself, both self-measured
        # (inside analyze_capture) and from outside the call
        "analyzer_overhead_s": doc["analyzer"]["overhead_s"],
        "analyzer_wall_s": round(analyze_wall, 6),
        "artifact": os.path.basename(out) if out else None,
    }


def main() -> int:
    fast = "--fast" in sys.argv[1:]
    batch, recipe, cfg = build_workload(fast)
    key = jax.random.PRNGKey(7)
    workdir = tempfile.mkdtemp(prefix="critpath_bench_")
    failures = []
    arms = {}
    try:
        # warm-up: compile at the bench shapes (uncaptured)
        obs.reset_all()
        sweep(key, batch, recipe, nreal=cfg["chunk"], chunk=cfg["chunk"],
              checkpoint_path=os.path.join(workdir, "warm.npz"),
              reduce_fn=None, pipeline_depth=2, durable=True)
        for arm, fused in (("stacked", False), ("fused", True)):
            cap, wall = run_arm(fused, batch, recipe, key,
                                cfg["nreal"], cfg["chunk"], workdir)
            arms[arm] = analyze_arm(arm, cap, wall, failures)
    finally:
        obs.reset_all()
        shutil.rmtree(workdir, ignore_errors=True)

    # the cross-round ledger over the repo's real committed artifacts
    # (info: the gate lives in check.sh as `perf gate`)
    led = ledger.build_ledger(REPO)
    _summary, flagged, gate_rc = ledger.gate(led, window=3)
    ledger_info = {
        "rounds": led["rounds"],
        "sources": len(led["sources"]),
        "metrics": len(led["metrics"]),
        "refused": len(led["refused"]),
        "gate_window3_regressing": sorted(flagged),
        "gate_rc": gate_rc,
    }

    rec = {
        "bench": "critpath_attribution",
        **provenance_stamp(2, repo_root=REPO),
        "fast": fast,
        "workload": {
            "npsr": NPSR, **cfg,
            "nchunks": cfg["nreal"] // cfg["chunk"],
            "reduce_fn": None, "durable_writes": True,
            "pipeline_depth": 2,
        },
        "min_attributed_fraction": MIN_ATTRIBUTED,
        "stacked": arms.get("stacked"),
        "fused": arms.get("fused"),
        "ledger": ledger_info,
        "gates": {
            "verdict_matches_occupancy": not any(
                "verdict names" in f for f in failures
            ),
            "attribution_bound": not any(
                "attributed_fraction" in f for f in failures
            ),
            "trace_coherent": not any(
                "trace-coherent" in f for f in failures
            ),
            "offline_only": not any(
                "analyzer spans" in f for f in failures
            ),
        },
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    print(json.dumps(rec))
    if failures:
        for reason in failures:
            print(f"critpath_attribution GATE FAIL: {reason}",
                  file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
