"""CW-catalog scaling: source-count ladder through the tiled backends.

The reference handles large CW catalogs with a numba prange over sources
plus 1e7-source python chunking (/root/reference/pta_replicator/
deterministic.py:258-294) — its one genuine memory-tiling strategy. The
device path tiles the (Nsrc x Ntoa) product through ``lax.scan`` source
tiles (or the Pallas kernel) with a bounded (chunk x Ntoa) workspace.
This tool measures the one-time catalog cost across an Nsrc ladder and
reports per-(source x TOA) throughput, so the tiling's linear scaling is
recorded evidence rather than a claim.

Usage: python benchmarks/cw_scaling.py [max_exp|memprobe] [backend]
  max_exp: ladder goes 10^2 .. 10^max_exp sources (default 5)
  backend: scan | pallas | streamed | both (scan+pallas) | ab
  (scan+streamed A/B; default scan; pallas needs a real TPU)
  CW_CHUNKS="1024" (env): comma-separated scan-chunk candidates for the
  >=1e5 rungs, overriding the default {512,1024,4096} sweep — a single
  1e6-source evaluation takes tens of minutes on a 1-core CPU host, so
  a CPU evidence run must bound the sweep to stay feasible.
  CW_LOOPS=2 (env): timed best-of loops per candidate (1 on CPU).
  CW_NPSR=68 / CW_NTOA=7758 (env): batch shape. The per-(source x TOA)
  throughput metric is shape-normalized, so a reduced-TOA ladder (e.g.
  CW_NTOA=122, the reference's own parity-workload TOA count) reaches
  the reference's 1e7-source regime on hosts where the full 7,758-TOA
  product would take days; rungs record the shape they ran at.
  CW_TELEMETRY=DIR (env): capture the run's telemetry (the streamed
  arm's ``cw_stream.*`` gauges land in the obs report).
Prints one JSON line.

The "streamed" arm measures the BOUNDED-MEMORY plane pipeline
(models.batched.cw_stream_response: tile stream -> double-buffered
host->device prefetch -> jitted per-tile accumulation) at equal
precompute amortization with the scan arm: the scan arm's planes are
built once at trace time and baked into its jit as constants, so the
streamed arm likewise builds its tiles once per rung — recorded as
``tile_build_once_s``; amortizing it across capture windows is the
on-disk tile cache's job (benchmarks/mk_workload.py) — and each timed
eval pays the prefetch/H2D-staging/per-tile-dispatch machinery the
scan arm never pays. ``streamed_over_scan_wall`` <= 1.0 therefore
means bounded memory costs nothing at that rung even before the
memory wall makes the comparison moot (the monolithic arm CANNOT run
the 68 psr x 1e7 flagship shape at all — see memprobe). Each streamed
rung also records the ``cw_stream.*`` gauges.

``memprobe`` mode is the memory-boundary instrument: it builds (and
stages through the prefetcher, then discards) the full plane-tile
stream for CW_NPSR (68) x CW_NSRC (1e7) sources — the exact shape whose
MONOLITHIC f64 host precompute segfaulted this host at ~113 GB
(CW_SCALING_r05_cpu.json) — sampling VmRSS per tile, and reports the
peak. No response is computed: the probe certifies the plane build's
bounded memory, the regime the monolithic path cannot enter at all.

The "pallas" arm measures the ARCHIVED Mosaic kernel (retired from the
production backend enum in round 5 — docs/DESIGN.md section 4) by
calling ops.pallas_cw.cw_catalog_response directly; this tool remains
the instrument that could reopen the decision if a large-catalog regime
ever shows the kernel winning on real hardware.
"""
import json
import os
import resource
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _vm_rss_mb() -> float:
    from pta_replicator_tpu.utils.profiling import vm_rss_mb

    return vm_rss_mb()


def memprobe():
    """Bounded-memory plane build at the monolithic path's segfault
    shape: stream (and discard) every tile, report peak RSS."""
    import jax

    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)

    from bench import random_cw_catalog
    from pta_replicator_tpu import obs
    from pta_replicator_tpu.batch import synthetic_batch
    from pta_replicator_tpu.models import batched as B
    from pta_replicator_tpu.obs import names
    from pta_replicator_tpu.parallel.prefetch import prefetch_to_device

    npsr = int(os.environ.get("CW_NPSR", "68"))
    nsrc = int(float(os.environ.get("CW_NSRC", "1e7")))
    chunk = int(os.environ.get("CW_STREAM_CHUNK", "65536"))
    ntoa = int(os.environ.get("CW_NTOA", "122"))  # planes don't touch TOAs
    batch = synthetic_batch(npsr=npsr, ntoa=ntoa, nbackend=4, seed=0)
    args = random_cw_catalog(np.random.default_rng(1), nsrc)

    rss0 = _vm_rss_mb()
    peak = rss0
    t0 = time.monotonic()
    tiles = B.cw_catalog_plane_tiles_for(
        batch, *args, chunk=chunk,
    )
    ntiles = 0
    nbytes = 0
    # the full pipeline shape minus the response: host build + H2D
    # staging through the double-buffered window, tiles dropped on the
    # floor as soon as they are staged
    for src_t, psr_t in prefetch_to_device(tiles, depth=2):
        ntiles += 1
        obs.gauge(names.CW_STREAM_TILES_DONE).set(ntiles)
        nbytes += int(src_t.nbytes) + int(psr_t.nbytes)
        peak = max(peak, _vm_rss_mb())
    wall = time.monotonic() - t0
    out = {
        "mode": "memprobe",
        "device": jax.devices()[0].device_kind,
        "npsr": npsr,
        "nsrc": nsrc,
        "stream_chunk": chunk,
        "tiles": ntiles,
        "staged_gb": round(nbytes / 1e9, 3),
        "wall_s": round(wall, 1),
        "rss_start_mb": round(rss0, 1),
        "rss_peak_mb": round(peak, 1),
        "ru_maxrss_mb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0, 1
        ),
        "monolithic_reference": (
            "same 68 psr x 1e7 src shape segfaulted the monolithic f64 "
            "plane precompute at ~113 GB (CW_SCALING_r05_cpu.json)"
        ),
    }
    print(json.dumps(out))


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "memprobe":
        memprobe()
        return
    max_exp = int(sys.argv[1]) if len(sys.argv) > 1 else 5
    backend_arg = sys.argv[2] if len(sys.argv) > 2 else "scan"

    import jax

    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)
    import jax.numpy as jnp

    from bench import random_cw_catalog
    from pta_replicator_tpu import obs
    from pta_replicator_tpu.batch import synthetic_batch
    from pta_replicator_tpu.models import batched as B
    from pta_replicator_tpu.obs import names

    telemetry = os.environ.get("CW_TELEMETRY")
    if telemetry:
        obs.start_capture(telemetry)

    npsr = int(os.environ.get("CW_NPSR", "68"))
    ntoa = int(os.environ.get("CW_NTOA", "7758"))
    batch = synthetic_batch(npsr=npsr, ntoa=ntoa, nbackend=4, seed=0)
    rng = np.random.default_rng(1)

    def catalog(n):
        return [jnp.asarray(row) for row in random_cw_catalog(rng, n)]

    backends = {
        "both": ["scan", "pallas"],
        "ab": ["scan", "streamed"],
    }.get(backend_arg, [backend_arg])
    ladder = [10**e for e in range(2, max_exp + 1)]
    out = {
        "device": jax.devices()[0].device_kind,
        "npsr": npsr,
        "ntoa": ntoa,
        "chunk": "per-rung best (see tried)",
        "results": {},
    }
    for backend in backends:
        rows = {}
        for n in ladder:
            args = catalog(n)
            # sub-chunk rungs must not pad up to a full tile (the scan
            # pads Nsrc to a chunk multiple — a 100-source rung timed at
            # chunk=1024 measures 1024 padded sources, faking a 10x
            # throughput jump between rungs). At large rungs the tile
            # size itself is a first-order knob for BOTH backends, so
            # the win-or-retire comparison sweeps it and keeps the best
            # per backend (each candidate is recorded).
            if backend == "pallas":
                # the archived kernel's tiling knob is (src_tile,
                # toa_tile), swept like the scan chunk so the
                # reopen-the-decision comparison is fair to both
                chunks = [(8, 1024), (8, 2048), (16, 1024), (32, 1024)]
            elif n >= 10**5:
                env_chunks = os.environ.get("CW_CHUNKS")
                chunks = (
                    [int(c) for c in env_chunks.split(",")]
                    if env_chunks else [512, 1024, 4096]
                )
            else:
                chunks = [min(1024, n)]
            best_row = None
            tried = {}
            for chunk in chunks:
                try:
                    if backend == "pallas":
                        from pta_replicator_tpu.ops.pallas_cw import (
                            cw_catalog_response,
                        )

                        src_c, psr_c, evolve = B.cw_catalog_planes_for(
                            batch, *args
                        )
                        u = batch.toas_s - jnp.asarray(
                            batch.start_s, batch.toas_s.dtype
                        )
                        st, tt = chunk
                        fn = jax.jit(
                            lambda eps, u=u, s=src_c, p=psr_c, e=evolve,
                            st=st, tt=tt:
                            cw_catalog_response(
                                u, s, p, psr_term=True, evolve=e,
                                src_tile=st, toa_tile=tt,
                            ) * batch.mask
                            + eps
                        )
                    elif backend == "streamed":
                        # equal precompute amortization with the scan
                        # arm (whose planes are built ONCE at trace
                        # time and baked into its jit as constants):
                        # tiles are built once per rung — build_s
                        # records that one-time cost, it is the tile
                        # cache's job to amortize it across windows —
                        # and each timed eval streams them through the
                        # prefetch + per-tile-jit machinery, H2D
                        # staging included (the scan arm stages
                        # nothing per eval)
                        t_b = time.perf_counter()
                        tiles_list = list(
                            B.cw_catalog_plane_tiles_for(
                                batch, *args, chunk=chunk
                            )
                        )
                        build_s = round(time.perf_counter() - t_b, 4)

                        tps = int(os.environ.get("CW_TILES_PER_STEP", "16"))

                        def fn(eps, tiles_list=tiles_list, tps=tps):
                            return B.cw_stream_response(
                                batch, iter(tiles_list), evolve=True,
                                prefetch_depth=2, tiles_per_step=tps,
                            ) + eps
                    else:
                        fn = jax.jit(
                            lambda eps, args=args, chunk=chunk:
                            B.cgw_catalog_delays(
                                batch, *args, chunk=chunk, backend=backend
                            )
                            + eps
                        )
                    zero = jnp.zeros((), batch.toas_s.dtype)
                    np.asarray(fn(zero))  # compile + run once
                    t0 = time.perf_counter()
                    np.asarray(fn(zero))
                    t1 = time.perf_counter() - t0
                    # target ~1s of measurement per rung, 50 reps max
                    reps = max(1, min(50, int(1.0 / max(t1, 1e-4))))
                    best = np.inf
                    loops = int(os.environ.get("CW_LOOPS", "2"))
                    # bytes_staged is a process-cumulative counter:
                    # snapshot around the timed loops and divide, so
                    # the record is per-eval, not warmup+every earlier
                    # rung (the stall/tiles gauges are per-response
                    # already — each cw_stream_response overwrites them)
                    bytes0 = obs.counter(names.CW_STREAM_BYTES_STAGED).value
                    for _ in range(loops):
                        t0 = time.perf_counter()
                        for _ in range(reps):
                            r = fn(zero)
                        np.asarray(r)  # host readback fences the queue
                        best = min(best, (time.perf_counter() - t0) / reps)
                    tried[str(chunk)] = round(best, 4)
                    if best_row is None or best < best_row["seconds"]:
                        best_row = {
                            "seconds": round(best, 4),
                            "chunk": chunk,
                            "gsrc_toa_per_s": round(
                                n * ntoa * npsr / best / 1e9, 2
                            ),
                        }
                        if backend == "streamed":
                            best_row["tile_build_once_s"] = build_s
                            staged_delta = (
                                obs.counter(
                                    names.CW_STREAM_BYTES_STAGED
                                ).value - bytes0
                            )
                            best_row["cw_stream"] = {
                                "tiles_done": obs.gauge(
                                    names.CW_STREAM_TILES_DONE
                                ).value,
                                "bytes_staged_per_eval": round(
                                    staged_delta / (loops * reps)
                                ),
                                "prefetch_stall_s": obs.gauge(
                                    names.CW_STREAM_PREFETCH_STALL_S
                                ).value,
                            }
                except Exception as exc:
                    tried[str(chunk)] = repr(exc)[:160]
            rows[str(n)] = (
                dict(best_row, tried=tried)
                if best_row is not None
                else {"error": tried}
            )
        out["results"][backend] = rows
    if "scan" in out["results"] and "streamed" in out["results"]:
        # the A/B column: streamed wall / scan wall per rung (<= 1.0 is
        # parity-or-better despite the per-eval host plane build)
        ab = {}
        for n, srow in out["results"]["scan"].items():
            trow = out["results"]["streamed"].get(n)
            if trow and "seconds" in srow and "seconds" in trow:
                ab[n] = round(trow["seconds"] / srow["seconds"], 3)
        out["streamed_over_scan_wall"] = ab
    print(json.dumps(out))
    if telemetry:
        obs.finish_capture(context={"cw_scaling": True})


if __name__ == "__main__":
    main()
