"""CW-catalog scaling: source-count ladder through the tiled backends.

The reference handles large CW catalogs with a numba prange over sources
plus 1e7-source python chunking (/root/reference/pta_replicator/
deterministic.py:258-294) — its one genuine memory-tiling strategy. The
device path tiles the (Nsrc x Ntoa) product through ``lax.scan`` source
tiles (or the Pallas kernel) with a bounded (chunk x Ntoa) workspace.
This tool measures the one-time catalog cost across an Nsrc ladder and
reports per-(source x TOA) throughput, so the tiling's linear scaling is
recorded evidence rather than a claim.

Usage: python benchmarks/cw_scaling.py [max_exp] [backend]
  max_exp: ladder goes 10^2 .. 10^max_exp sources (default 5)
  backend: scan | pallas | both (default scan; pallas needs a real TPU)
  CW_CHUNKS="1024" (env): comma-separated scan-chunk candidates for the
  >=1e5 rungs, overriding the default {512,1024,4096} sweep — a single
  1e6-source evaluation takes tens of minutes on a 1-core CPU host, so
  a CPU evidence run must bound the sweep to stay feasible.
  CW_LOOPS=2 (env): timed best-of loops per candidate (1 on CPU).
  CW_NPSR=68 / CW_NTOA=7758 (env): batch shape. The per-(source x TOA)
  throughput metric is shape-normalized, so a reduced-TOA ladder (e.g.
  CW_NTOA=122, the reference's own parity-workload TOA count) reaches
  the reference's 1e7-source regime on hosts where the full 7,758-TOA
  product would take days; rungs record the shape they ran at.
Prints one JSON line.

The "pallas" arm measures the ARCHIVED Mosaic kernel (retired from the
production backend enum in round 5 — docs/DESIGN.md section 4) by
calling ops.pallas_cw.cw_catalog_response directly; this tool remains
the instrument that could reopen the decision if a large-catalog regime
ever shows the kernel winning on real hardware.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    max_exp = int(sys.argv[1]) if len(sys.argv) > 1 else 5
    backend_arg = sys.argv[2] if len(sys.argv) > 2 else "scan"

    import jax

    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)
    import jax.numpy as jnp

    from bench import random_cw_catalog
    from pta_replicator_tpu.batch import synthetic_batch
    from pta_replicator_tpu.models import batched as B

    npsr = int(os.environ.get("CW_NPSR", "68"))
    ntoa = int(os.environ.get("CW_NTOA", "7758"))
    batch = synthetic_batch(npsr=npsr, ntoa=ntoa, nbackend=4, seed=0)
    rng = np.random.default_rng(1)

    def catalog(n):
        return [jnp.asarray(row) for row in random_cw_catalog(rng, n)]

    backends = ["scan", "pallas"] if backend_arg == "both" else [backend_arg]
    ladder = [10**e for e in range(2, max_exp + 1)]
    out = {
        "device": jax.devices()[0].device_kind,
        "npsr": npsr,
        "ntoa": ntoa,
        "chunk": "per-rung best (see tried)",
        "results": {},
    }
    for backend in backends:
        rows = {}
        for n in ladder:
            args = catalog(n)
            # sub-chunk rungs must not pad up to a full tile (the scan
            # pads Nsrc to a chunk multiple — a 100-source rung timed at
            # chunk=1024 measures 1024 padded sources, faking a 10x
            # throughput jump between rungs). At large rungs the tile
            # size itself is a first-order knob for BOTH backends, so
            # the win-or-retire comparison sweeps it and keeps the best
            # per backend (each candidate is recorded).
            if backend == "pallas":
                # the archived kernel's tiling knob is (src_tile,
                # toa_tile), swept like the scan chunk so the
                # reopen-the-decision comparison is fair to both
                chunks = [(8, 1024), (8, 2048), (16, 1024), (32, 1024)]
            elif n >= 10**5:
                env_chunks = os.environ.get("CW_CHUNKS")
                chunks = (
                    [int(c) for c in env_chunks.split(",")]
                    if env_chunks else [512, 1024, 4096]
                )
            else:
                chunks = [min(1024, n)]
            best_row = None
            tried = {}
            for chunk in chunks:
                try:
                    if backend == "pallas":
                        from pta_replicator_tpu.ops.pallas_cw import (
                            cw_catalog_response,
                        )

                        src_c, psr_c, evolve = B.cw_catalog_planes_for(
                            batch, *args
                        )
                        u = batch.toas_s - jnp.asarray(
                            batch.start_s, batch.toas_s.dtype
                        )
                        st, tt = chunk
                        fn = jax.jit(
                            lambda eps, u=u, s=src_c, p=psr_c, e=evolve,
                            st=st, tt=tt:
                            cw_catalog_response(
                                u, s, p, psr_term=True, evolve=e,
                                src_tile=st, toa_tile=tt,
                            ) * batch.mask
                            + eps
                        )
                    else:
                        fn = jax.jit(
                            lambda eps, args=args, chunk=chunk:
                            B.cgw_catalog_delays(
                                batch, *args, chunk=chunk, backend=backend
                            )
                            + eps
                        )
                    zero = jnp.zeros((), batch.toas_s.dtype)
                    np.asarray(fn(zero))  # compile + run once
                    t0 = time.perf_counter()
                    np.asarray(fn(zero))
                    t1 = time.perf_counter() - t0
                    # target ~1s of measurement per rung, 50 reps max
                    reps = max(1, min(50, int(1.0 / max(t1, 1e-4))))
                    best = np.inf
                    for _ in range(int(os.environ.get("CW_LOOPS", "2"))):
                        t0 = time.perf_counter()
                        for _ in range(reps):
                            r = fn(zero)
                        np.asarray(r)  # host readback fences the queue
                        best = min(best, (time.perf_counter() - t0) / reps)
                    tried[str(chunk)] = round(best, 4)
                    if best_row is None or best < best_row["seconds"]:
                        best_row = {
                            "seconds": round(best, 4),
                            "chunk": chunk,
                            "gsrc_toa_per_s": round(
                                n * ntoa * npsr / best / 1e9, 2
                            ),
                        }
                except Exception as exc:
                    tried[str(chunk)] = repr(exc)[:160]
            rows[str(n)] = (
                dict(best_row, tried=tried)
                if best_row is not None
                else {"error": tried}
            )
        out["results"][backend] = rows
    print(json.dumps(out))


if __name__ == "__main__":
    main()
