"""Lean TPU-window capture: hold the connection from a successful probe
straight into measurement; flush every stage's number to disk the moment
it exists. Exit 3 = backend init wedged (retry later), 0 = got the
headline number."""
import json, os, sys, threading, time
import numpy as np

OUT = "/root/repo/BENCH_CAPTURE_r05.jsonl"
T0 = time.monotonic()

def log(msg):
    print(f"[{time.monotonic()-T0:7.1f}s] {msg}", file=sys.stderr, flush=True)

def emit(rec):
    """Append the timestamped record to the capture journal; returns the
    timestamped copy so callers persist the SAME record (previews must be
    self-timestamped — bench.py's failure path cites backup_timestamp)."""
    rec = {"timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           **rec}
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()
        os.fsync(f.fileno())
    log(f"emitted: {rec}")
    return rec

# resettable stage watchdog: the tunnel can wedge at ANY device call
# (rounds 3-5 saw both init wedges and the 03:53 first-big-op wedge), so
# every stage arms its own deadline; a wedged stage exits fast and the
# outer loop re-probes on its short cadence instead of waiting out the
# 2400 s kill
_deadline = [time.monotonic() + 180.0]
_exit_code = [3]
def _watchdog():
    while True:
        time.sleep(5.0)
        if time.monotonic() > _deadline[0]:
            log(f"stage wedged past its deadline, exiting {_exit_code[0]}")
            os._exit(_exit_code[0])
threading.Thread(target=_watchdog, daemon=True).start()

def arm(seconds, code=5):
    """(Re)arm the watchdog for the next stage."""
    # single-writer heartbeat: the main thread stores, the watchdog only
    # reads, and the 5 s poll dwarfs any torn-read window (GIL-atomic
    # list-item stores) — a lock here could itself wedge a dying stage
    _deadline[0] = time.monotonic() + seconds  # graftlint: disable=thread-unlocked-global
    _exit_code[0] = code  # graftlint: disable=thread-unlocked-global

os.makedirs("/root/repo/.jax_cache", exist_ok=True)
import jax
_want = os.environ.get("FAST_CAPTURE_PLATFORM", "tpu")
if _want != "tpu":
    jax.config.update("jax_platforms", _want)
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import jax.numpy as jnp

probe = float(np.asarray(jnp.ones((256, 256)) @ jnp.ones((256, 256))).sum())
arm(300)  # workload build + device transfer budget
log(f"backend up: {jax.default_backend()} {jax.devices()[0].device_kind}, probe={probe}")
if jax.default_backend() != _want:
    log(f"backend is {jax.default_backend()}, wanted {_want}; exiting 4")
    sys.exit(4)

sys.path.insert(0, "/root/repo")
from bench import _METRIC, _NORTH_STAR_RATE, build_workload

META = {
    "jax_backend": jax.default_backend(),
    "device_kind": jax.devices()[0].device_kind,
    "jax_version": jax.__version__,
    "metric": _METRIC,  # single source of truth: bench.py
}
from pta_replicator_tpu.models import batched as B
from pta_replicator_tpu.models.batched import (
    quadratic_fit_subtract, realization_delays,
)

t = time.monotonic()
# with_fingerprint: hashed from the build's HOST numpy draws, so the
# cache check below costs zero device readbacks through the tunnel
batch, recipe, want_fp = build_workload(ncw=100, with_fingerprint=True)
# the deterministic (CW-catalog) static plane is key-independent data:
# a pre-serialized copy (benchmarks/mk_workload.py writes it on the CPU
# backend) saves one tunnel compile inside the window; fall back to the
# on-device eager compute bench.py uses when the cache file is absent
_npz = "/tmp/workload.npz"
static_np = None
if os.path.exists(_npz):
    try:
        with np.load(_npz) as z:
            cand = z["static"]
            # the cache is only trusted when its workload fingerprint
            # (build params + host draw bytes + STREAM_VERSION; stamped
            # by mk_workload.py) matches the workload just built —
            # shape/dtype alone let a stale plane from an older
            # workload definition masquerade as current (ADVICE.md r5)
            cached_fp = str(z["fingerprint"]) if "fingerprint" in z else None
        if cached_fp != want_fp:
            log(f"workload cache fingerprint {cached_fp} != {want_fp}, "
                "recomputing")
        elif (cand.shape == tuple(np.shape(batch.toas_s))
                and cand.dtype == np.dtype(np.float32)):
            static_np = cand
        else:
            log(f"stale workload cache {cand.shape}/{cand.dtype}, recomputing")
    except Exception as exc:  # truncated/corrupt file: fall back, don't die
        log(f"unreadable workload cache ({exc!r}), recomputing")
log(f"workload built {time.monotonic()-t:.1f}s (static cached: {static_np is not None})")

t = time.monotonic()
batch = jax.device_put(batch)
if static_np is not None:
    static = jax.device_put(jnp.asarray(static_np))
else:
    from pta_replicator_tpu.models.batched import deterministic_delays
    static = deterministic_delays(batch, recipe)
np.asarray(static)
log(f"static ready + fence {time.monotonic()-t:.1f}s")
emit({**META, "stage": "device_ready", "setup_s": round(time.monotonic()-T0, 1)})


def make_chunk_fn(chunk):
    @jax.jit
    def run_chunk(key, static):
        keys = jax.random.split(key, chunk)
        def one(k):
            d = realization_delays(k, batch, recipe) + static
            return quadratic_fit_subtract(d, batch)
        res = jax.vmap(one)(keys)
        return jnp.sqrt(jnp.sum(res**2 * batch.mask, axis=-1)
                        / jnp.sum(batch.mask, axis=-1))
    return run_chunk


_PREVIEW = "/root/repo/BENCH_PREVIEW_r05.json"


def write_preview(rec, path=_PREVIEW):
    """Canonical single-JSON artifact in bench.py's schema, written the
    moment a headline number exists so bench.py's failure path can cite
    it as backup evidence.

    Once the capture loop has promoted the canonical bench.py result
    into BENCH_PREVIEW_r05.json (marker: /tmp/bench_canonical_done),
    later fast-capture reruns must NOT clobber it — their previews
    divert to a separate file (ADVICE.md r5 medium: the loop skips
    bench_stage after promotion, but fast_capture still reruns every
    iteration)."""
    if path == _PREVIEW and os.path.exists("/tmp/bench_canonical_done"):
        path = "/root/repo/BENCH_PREVIEW_r05_fastcapture.json"
        log("canonical bench result promoted; preview diverted to "
            f"{path}")
    # best-not-latest (ADVICE r5 low): among COMPARABLE rungs (same
    # bench_chunk measuring the same metric — e.g. chunk800_long vs
    # chunk800_headline) keep the faster record; a different chunk is a
    # ladder upgrade and always replaces (smallest-first ladder: any
    # window yields a number, later rungs are the better evidence)
    try:
        with open(path) as f:
            prev = json.load(f)
        if (
            prev.get("bench_chunk") == rec.get("bench_chunk")
            and prev.get("metric") == rec.get("metric")
            and prev.get("value", 0) >= rec.get("value", 0)
        ):
            log(
                f"preview keeps {prev.get('stage')} "
                f"({prev.get('value')} >= {rec.get('value')} real/s); "
                f"not demoting to {rec.get('stage')}"
            )
            return
    except (FileNotFoundError, json.JSONDecodeError):
        pass  # no (readable) preview yet: write unconditionally
    with open(path, "w") as f:
        json.dump(rec, f)
        f.flush()
        os.fsync(f.fileno())


def measure(chunk, nrep, tag, budget=600):
    arm(budget)
    t = time.monotonic()
    compiled = make_chunk_fn(chunk).lower(
        jax.random.PRNGKey(0), static).compile()
    compile_s = time.monotonic() - t
    log(f"{tag}: compiled in {compile_s:.1f}s")
    t = time.monotonic()
    out = compiled(jax.random.PRNGKey(0), static)
    np.asarray(out)
    warm_s = time.monotonic() - t
    t0 = time.perf_counter()
    for i in range(nrep):
        out = compiled(jax.random.PRNGKey(i + 1), static)
    np.asarray(out)
    elapsed = time.perf_counter() - t0
    rate = nrep * chunk / elapsed
    rec = {**META, "stage": tag, "value": round(rate, 3),
           "unit": "realizations/s", "bench_chunk": chunk, "nrep": nrep,
           "measure_elapsed_s": round(elapsed, 3),
           "compile_s": round(compile_s, 1), "warmup_s": round(warm_s, 2),
           "vs_baseline": round(rate / _NORTH_STAR_RATE, 3),
           "cgw_static_amortized": True}
    # one shared cost/roofline extraction with bench.py (obs.devprof):
    # same field spellings, same peak table gated on device_kind (an MFU
    # against TPU peak is meaningless in a CPU harness run), same error
    # handling — the two hand-rolled copies had drifted
    from pta_replicator_tpu.obs import devprof
    rec.update(devprof.bench_cost_fields(
        compiled, reps=nrep, elapsed_s=elapsed,
        device_kind=META["device_kind"], label=f"fast_capture.{tag}"))
    return emit(rec)


# smallest first: ANY window yields a number — and every rung is offered
# to the preview immediately (write_preview keeps the best among
# comparable rungs), so a window that dies mid-ladder still leaves the
# best number captured so far in the canonical artifact. A rung that
# RAISES (device error, OOM — not a silent wedge) must not kill the
# capture: later rungs and the battery can still use the live window, so
# record the error and push on (exit 6 tells the loop the window was
# live despite the partial failure).
_rung_errors = 0
def try_rung(fn):
    global _rung_errors
    try:
        return fn()
    except Exception as exc:
        _rung_errors += 1
        emit({"stage": "rung_error", "error": repr(exc)[:300]})
        return None

rec = try_rung(lambda: measure(100, 3, "chunk100_quick"))
if rec: write_preview(rec)
rec = try_rung(lambda: measure(800, 5, "chunk800_headline"))
if rec: write_preview(rec)
rec = try_rung(lambda: measure(800, 20, "chunk800_long"))
if rec: write_preview(rec)


def measure_fit(chunk, nrep, mode, tag, kcols=166):
    """BENCH_FIT=full|gls analog: full-design refit at bench scale. The
    design is generated on device (350 MB host->tunnel transfer would
    eat the window; the measurement is statistically identical)."""
    arm(900)  # GLS compile is the most expensive in the battery
    import dataclasses
    fitD = jax.random.normal(
        jax.random.PRNGKey(99), (batch.npsr, batch.ntoa_max, kcols),
        batch.toas_s.dtype)
    rec2 = dataclasses.replace(recipe, fit_design=fitD,
                               fit_gls=(mode == "gls"))

    @jax.jit
    def run_chunk(key, static):
        keys = jax.random.split(key, chunk)
        def one(k):
            d = realization_delays(k, batch, rec2) + static
            return B.finalize_residuals(d, batch, rec2, True)
        res = jax.vmap(one)(keys)
        return jnp.sqrt(jnp.sum(res**2 * batch.mask, axis=-1)
                        / jnp.sum(batch.mask, axis=-1))

    t = time.monotonic()
    compiled = run_chunk.lower(jax.random.PRNGKey(0), static).compile()
    compile_s = time.monotonic() - t
    log(f"{tag}: compiled in {compile_s:.1f}s")
    out = compiled(jax.random.PRNGKey(0), static)
    np.asarray(out)
    t0 = time.perf_counter()
    for i in range(nrep):
        out = compiled(jax.random.PRNGKey(i + 1), static)
    np.asarray(out)
    elapsed = time.perf_counter() - t0
    rate = nrep * chunk / elapsed
    rec = {**META, "stage": tag, "value": round(rate, 3),
           "unit": "realizations/s", "bench_chunk": chunk, "nrep": nrep,
           "fit_mode": mode, "fit_columns": kcols,
           # the headline _METRIC says "+quadratic fit" — this record
           # measures a different refit, so the metric string must say
           # so itself, not rely on the fit_mode field (ADVICE.md r5)
           "metric": (f"{_METRIC} [{mode.upper()} {kcols}-column "
                      "full-design refit instead of the quadratic fit]"),
           "measure_elapsed_s": round(elapsed, 3),
           "compile_s": round(compile_s, 1),
           "vs_baseline": round(rate / _NORTH_STAR_RATE, 3)}
    return emit(rec)


try:
    rec = measure_fit(400, 3, "gls", "chunk400_gls")
    # OUTSIDE the BENCH_PREVIEW_* namespace: bench.py's failure path
    # scans that prefix for the HEADLINE config's backup value, and the
    # slower GLS-mode rate must never be cited as the headline's
    write_preview(rec, "/root/repo/BENCH_GLS_CAPTURE_r05.json")
except Exception as exc:
    emit({"stage": "gls_error", "error": repr(exc)[:300]})
try:
    measure_fit(400, 3, "full", "chunk400_wls_full")
except Exception as exc:
    emit({"stage": "wls_full_error", "error": repr(exc)[:300]})

# CW scan op timing at the flagship shape
try:
    arm(600)
    args8 = [recipe.cgw_params[i] for i in range(8)]
    fn = jax.jit(lambda eps: B.cgw_catalog_delays(
        batch, *args8, chunk=recipe.cgw_chunk, backend="scan") + eps)
    zero = jnp.zeros((), batch.toas_s.dtype)
    t = time.monotonic()
    np.asarray(fn(zero))
    log(f"cw scan compile+run {time.monotonic()-t:.1f}s")
    t0 = time.perf_counter()
    for _ in range(10):
        out = fn(zero)
    np.asarray(out)
    emit({**META, "stage": "cgw_scan_ms",
          "value": round((time.perf_counter() - t0) / 10 * 1e3, 3),
          "unit": "ms per 100-source catalog eval"})
except Exception as exc:
    emit({"stage": "cgw_scan_error", "error": repr(exc)[:300]})

if _rung_errors:
    log(f"fast capture complete with {_rung_errors} rung error(s); exit 6")
    sys.exit(6)
log("fast capture complete")
