"""Fused-path stage ablation on real hardware.

The standalone per-stage timings in bench.py's ``_stage_breakdown`` are
dispatch-dominated on the tunneled backend (they sum to ~7x the fused
cost). This tool measures what each stage *actually* costs inside the
fused chunk: it times the headline bench workload (imported from
bench.build_workload, so the two harnesses cannot drift apart) with one
stage removed at a time — the delta vs the full graph is that stage's
true marginal cost after XLA fusion.

Usage: python benchmarks/fused_ablation.py [chunk] [nrep]
(run from the repo root; keeps /root/.axon_site on PYTHONPATH)
Prints one JSON line: per-config ms/realization + marginal deltas.
"""
import dataclasses
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    chunk = int(sys.argv[1]) if len(sys.argv) > 1 else 800
    nrep = int(sys.argv[2]) if len(sys.argv) > 2 else 5

    import jax

    platform = os.environ.get("BENCH_PLATFORM")  # e.g. 'cpu' for smoke tests
    if platform:
        jax.config.update("jax_platforms", platform)
    import jax.numpy as jnp

    from bench import build_workload
    from pta_replicator_tpu.models.batched import (
        deterministic_delays,
        quadratic_fit_subtract,
        realization_delays,
        residualize,
    )

    batch, recipe = build_workload()

    configs = {
        "full": {},
        "no_white": {"efac": None, "log10_equad": None},
        "no_ecorr": {"log10_ecorr": None},
        "no_rn": {"rn_log10_amplitude": None},
        "no_gwb": {"gwb_log10_amplitude": None},
    }

    def make_chunk_fn(recipe, with_fit=True):
        def run_chunk(key, static):
            keys = jax.random.split(key, chunk)

            def one(k):
                d = realization_delays(k, batch, recipe) + static
                if with_fit:
                    # quad fit projects the weighted constant: no extra
                    # residualize pass (matches bench.py's run_chunk)
                    return quadratic_fit_subtract(d, batch)
                return residualize(d, batch)

            res = jax.vmap(one)(keys)
            return jnp.sqrt(
                jnp.sum(res**2 * batch.mask, axis=-1)
                / jnp.sum(batch.mask, axis=-1)
            )

        return jax.jit(run_chunk)

    # static CW delays computed once, outside all timed graphs (eagerly:
    # concrete params keep the f64 host plane precompute — see
    # parallel.mesh.static_delays)
    static = deterministic_delays(batch, recipe)
    np.asarray(static)

    out = {}

    def time_fn(fn, *args):
        compiled = fn.lower(jax.random.PRNGKey(0), *args).compile()
        np.asarray(compiled(jax.random.PRNGKey(0), *args))  # warm
        best = np.inf
        for _ in range(2):  # two passes, keep min (tunnel drift)
            t0 = time.perf_counter()
            for i in range(nrep):
                r = compiled(jax.random.PRNGKey(i + 1), *args)
            np.asarray(r)
            best = min(best, (time.perf_counter() - t0) / (nrep * chunk))
        return best * 1e3

    for name, override in configs.items():
        r = dataclasses.replace(recipe, **override)
        out[name] = round(time_fn(make_chunk_fn(r), static), 5)

    out["no_fit"] = round(time_fn(make_chunk_fn(recipe, False), static), 5)

    full_ms = out["full"]
    deltas = {
        k.replace("no_", ""): round(full_ms - v, 5)
        for k, v in out.items()
        if k.startswith("no_")
    }
    print(
        json.dumps(
            {
                "chunk": chunk,
                "nrep": nrep,
                "device": jax.devices()[0].device_kind,
                "ms_per_realization": out,
                "marginal_ms": deltas,
                "rate_full": round(1e3 / full_ms, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
