"""The raw-speed ladder's committed evidence (docs/performance.md):
fused likelihood kernels vs the composed build, the numerics-gated
bf16 rung, and the roofline tile autotuner — fast AND right.

Arms, per (npsr, ntoa, nmodes) scale:

* **fused A/B** — wall time of the composed ReducedGP build+project
  (materializes the (Np, Nt, Q) ``C0^-1 T`` image) vs the fused
  single-pass kernel assembly (``ops/pallas_gp.py``):
  ``fused_speedup`` higher-better, raw ``*_ms`` lower-better
  (obs/regress.py directions). The honest CPU framing: the fused
  pass is constrained to a SEQUENTIAL tile scan (the bit-identity
  contract with the Pallas kernel), which on CPU loses ~10-20% to the
  composed path's single multithreaded dgemm — measured 0.79-0.86x
  here. What it buys is the deleted (Np, Nt, Q) intermediate (26 MB
  at the flagship scale) and a kernel that rides the MXU on TPU,
  where the bandwidth win is the point. The flagship gate is
  therefore backend-aware: ``fused_speedup >= 1.3`` on TPU, a
  regression floor of ``>= 0.5`` on CPU (catches a pathological
  fused path without pretending CPU is the target).
* **bit-identity** — the Pallas kernels under interpret mode vs their
  tiled-XLA fallbacks, byte for byte, f32 AND f64, both kernels
  (the one-tile-implementation contract; also pinned by
  tests/test_gp_kernels.py).
* **oracle** — fused grid log L vs the composed grid (<= 1e-12
  relative, f64) at every scale, and vs the numpy f64 dense-covariance
  oracle (<= 1e-8) at the smallest scale.
* **bf16 drift** — the full ladder flow: arm the numerics observatory,
  run the fused f64 workload, write the capture, present it to
  ``precision='bf16'``; drift vs the f64 fused grid must sit within
  the covariance-family tolerance (1e-3). Also records grid
  throughput (``evals_per_s_bf16`` vs ``evals_per_s_f64``).
* **tuner** — ``likelihood/tuner.py`` search over the tile candidates
  at the flagship scale; the tuned tile is re-measured FRESH at the
  kernel level (the quantity the roofline objective optimizes) and
  must hold >= parity with the committed default tile
  (``tuner_speedup >= 0.95`` — i.e. the search's choice reproduces,
  it was not a timing fluke), and the pure lookup must return the
  persisted choice. End-to-end build times at both tiles are
  recorded as info. ``--tune`` writes the REAL cache
  (``benchmarks/gp_tuner_cache.json``); otherwise the search uses a
  scratch file and the committed cache is only read.

Prints one JSON line; committed as ``KERNELS_r20_cpu.json`` and
ingested into PERF_LEDGER.json. Exit 1 on any gate miss —
scripts/check.sh runs the --fast configuration on every push.

Usage: python benchmarks/gp_kernels.py [--fast] [--tune] [--out PATH]
"""
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from pta_replicator_tpu.batch import synthetic_batch  # noqa: E402
from pta_replicator_tpu.likelihood import gp, infer, tuner  # noqa: E402
from pta_replicator_tpu.models.batched import (  # noqa: E402
    Recipe,
    gls_noise_model,
)
from pta_replicator_tpu.obs import numerics  # noqa: E402
from pta_replicator_tpu.ops import pallas_gp  # noqa: E402
from pta_replicator_tpu.utils.provenance import (  # noqa: E402
    EVIDENCE_SCHEMA_VERSION,
    provenance_stamp,
)

#: family tolerance the bf16 rung is held to (the fuzzer's
#: covariance/total bar — scenarios/fuzz.py FAMILY_TOLERANCES)
BF16_TOL = 1e-3

GRID = {"rn_log10_amplitude": np.linspace(-14.0, -13.4, 8)}


def _scales(fast):
    # (npsr, ntoa, rn_nmodes, gwb_nmodes); the last is the flagship
    if fast:
        return [(4, 384, 8, 6), (6, 768, 12, 8)]
    return [(4, 512, 10, 8), (8, 1024, 20, 15), (16, 2048, 30, 20)]


def _setup(npsr, ntoa, rn_nmodes, gwb_nmodes, seed=3):
    batch = synthetic_batch(npsr=npsr, ntoa=ntoa, nbackend=2,
                            seed=seed, dtype=np.float64)
    nb = len(batch.backend_names)
    rng = np.random.default_rng(seed)
    recipe = Recipe(
        efac=jnp.asarray(rng.uniform(0.9, 1.4, (npsr, nb))),
        log10_equad=jnp.asarray(rng.uniform(-6.8, -6.2, (npsr, nb))),
        log10_ecorr=jnp.asarray(rng.uniform(-6.9, -6.4, (npsr, nb))),
        rn_log10_amplitude=jnp.asarray(
            rng.uniform(-13.8, -13.2, npsr)
        ),
        rn_gamma=jnp.asarray(rng.uniform(3.0, 4.5, npsr)),
        gwb_log10_amplitude=jnp.asarray(-14.2),
        gwb_gamma=jnp.asarray(13.0 / 3.0),
        rn_nmodes=rn_nmodes,
        gwb_gls_nmodes=gwb_nmodes,
    )
    res = jnp.asarray(
        rng.standard_normal(batch.toas_s.shape) * 1e-6
    ) * batch.mask
    return batch, recipe, res


def _median_ms(fn, reps):
    fn()  # warm (compile)
    walls = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        walls.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(walls))


def _rel(a, b):
    denom = max(float(np.max(np.abs(np.asarray(b)))), 1e-300)
    return float(np.max(np.abs(np.asarray(a) - np.asarray(b)))) / denom


def bit_identity_arm(failures):
    """Interpret-mode Pallas vs tiled-XLA fallback, byte for byte,
    both kernels, both dtypes."""
    out = {}
    for dtype, tag in ((np.float32, "f32"), (np.float64, "f64")):
        rng = np.random.default_rng(5)
        T = jnp.asarray(rng.standard_normal((3, 100, 7)), dtype)
        mask = rng.random((3, 100)) > 0.1
        w = jnp.asarray(rng.uniform(0.5, 2.0, (3, 100)) * mask, dtype)
        r = jnp.asarray(rng.standard_normal((3, 100)) * mask, dtype)
        wa = pallas_gp.fused_woodbury_xla(T, w, r, tile=32)
        wb = pallas_gp.fused_woodbury_update(T, w, r, tile=32,
                                             interpret=True)
        wood = all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(wa, wb)
        )
        A = rng.standard_normal((2, 5, 4, 4))
        D = jnp.asarray(A @ np.swapaxes(A, -1, -2) + 6.0 * np.eye(4),
                        dtype)
        E = jnp.asarray(0.2 * rng.standard_normal((2, 4, 4, 4)), dtype)
        X = jnp.asarray(rng.standard_normal((2, 5, 4, 3)), dtype)
        ta = pallas_gp.tridiag_factor_solve_xla(D, E, X)
        tb = pallas_gp.tridiag_factor_solve(D, E, X, interpret=True)
        tri = all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(ta, tb)
        )
        out[f"woodbury_bit_identical_{tag}"] = wood
        out[f"tridiag_bit_identical_{tag}"] = tri
        if not wood:
            failures.append(
                f"bit-identity: fused Woodbury interpret != xla at {tag}"
            )
        if not tri:
            failures.append(
                f"bit-identity: tridiag interpret != xla at {tag}"
            )
    return out


def ab_arm(scale, reps, tile, failures, oracle=False):
    """Composed-vs-fused build+project A/B at one scale + the
    agreement gates."""
    npsr, ntoa, rn_nm, gwb_nm = scale
    batch, recipe, res = _setup(npsr, ntoa, rn_nm, gwb_nm)

    @jax.jit
    def composed(r):
        red = gp.ReducedGP.build(batch, recipe, dtype=r.dtype)
        proj = red.project(r, batch)
        return red.TNT, proj.rNr, proj.d

    @jax.jit
    def fused(r):
        red, proj = gp.ReducedGP.build_fused(
            batch, recipe, residuals=r, dtype=r.dtype,
            tile=tile, backend="xla",
        )
        return red.TNT, proj.rNr, proj.d

    composed_ms = _median_ms(lambda: composed(res), reps)
    fused_ms = _median_ms(lambda: fused(res), reps)

    ca, cb, cc = composed(res)
    fa, fb, fc = fused(res)
    tnt_rel = _rel(fa, ca)
    proj_rel = max(_rel(fb, cb), _rel(fc, cc))

    ll = np.asarray(infer.grid_loglikelihood(res, batch, recipe, GRID))
    llf = np.asarray(infer.grid_loglikelihood(
        res, batch, recipe, GRID, fused=True, tile=tile, backend="xla"
    ))
    grid_rel = float(np.max(np.abs(llf - ll) / np.abs(ll)))
    tag = f"np{npsr}_nt{ntoa}"
    if grid_rel > 1e-12 or tnt_rel > 1e-12:
        failures.append(
            f"{tag}: fused-vs-composed disagreement (grid {grid_rel:.3e}"
            f", TNT {tnt_rel:.3e}) > 1e-12"
        )
    rec = {
        "composed_ms": round(composed_ms, 3),
        "fused_ms": round(fused_ms, 3),
        "fused_speedup": round(composed_ms / fused_ms, 3),
        "tnt_rel": tnt_rel,
        "proj_rel": proj_rel,
        "grid_rel": grid_rel,
    }
    if oracle:
        import dataclasses

        r2 = dataclasses.replace(
            recipe,
            rn_log10_amplitude=jnp.full(
                npsr, GRID["rn_log10_amplitude"][0]
            ),
        )
        oracle_ll = float(gp.dense_loglikelihood(res, batch, r2))
        oracle_rel = abs(llf[0] - oracle_ll) / abs(oracle_ll)
        rec["oracle_rel"] = oracle_rel
        if oracle_rel > 1e-8:
            failures.append(
                f"{tag}: fused-vs-dense-oracle deviation "
                f"{oracle_rel:.3e} > 1e-8"
            )
    return rec, (batch, recipe, res)


def bf16_arm(setup, tile, reps, failures):
    """The full ladder flow: capture -> verdict -> gated bf16 run,
    drift held to the covariance-family tolerance."""
    batch, recipe, res = setup
    ll64 = np.asarray(infer.grid_loglikelihood(
        res, batch, recipe, GRID, fused=True, tile=tile, backend="xla"
    ))
    with tempfile.TemporaryDirectory() as cap:
        numerics.reset()
        numerics.arm()
        try:
            infer.grid_loglikelihood(
                res, batch, recipe, GRID, fused=True, tile=tile,
                backend="xla",
            )
            numerics.write(cap)
        finally:
            numerics.disarm()
            numerics.reset()
        verdict = numerics.ladder_verdict(json.loads(
            open(os.path.join(cap, "numerics.json")).read()
        ))
        sites = {
            s: verdict.get(s, {"ready": False, "reasons": ["missing"]})
            for s in gp.FUSED_PRECISION_SITES
        }
        not_ready = [s for s, v in sites.items() if not v["ready"]]
        if not_ready:
            failures.append(
                f"bf16: ladder verdict not ready for {not_ready} — "
                "the gated rung is unreachable on this workload"
            )
            return {"ready": False, "not_ready": not_ready}
        g = int(np.asarray(GRID["rn_log10_amplitude"]).size)

        def run64():
            return infer.grid_loglikelihood(
                res, batch, recipe, GRID, fused=True, tile=tile,
                backend="xla",
            )

        def run16():
            return infer.grid_loglikelihood(
                res, batch, recipe, GRID, fused=True, tile=tile,
                backend="xla", precision="bf16", numerics_capture=cap,
            )

        ll16 = np.asarray(run16())
        drift = float(np.max(np.abs(ll16 - ll64) / np.abs(ll64)))
        ms64 = _median_ms(run64, reps)
        ms16 = _median_ms(run16, reps)
    if drift > BF16_TOL:
        failures.append(
            f"bf16: grid drift {drift:.3e} vs f64 fused > {BF16_TOL}"
            " (covariance-family tolerance)"
        )
    return {
        "ready": True,
        "bf16_max_drift": drift,
        "tolerance": BF16_TOL,
        "evals_per_s_f64": round(g / (ms64 / 1e3), 2),
        "evals_per_s_bf16": round(g / (ms16 / 1e3), 2),
    }


#: search space for the bench's tuner arm — the module defaults plus
#: the whole-Nt tile the flagship scale favors on CPU
TUNER_CANDIDATES = (128, 256, 512, 1024, 2048)


def tuner_arm(setup, reps, tune, failures, gate=True):
    """Search the tile candidates at the flagship scale; re-measure
    the tuned choice fresh at the kernel level and gate it at >=
    parity with the committed default tile. ``gate=False`` (the
    --fast arm) records the re-measurement without failing on it: at
    the fast scale the tile landscape is flat and scheduler noise
    picks the winner — the parity contract is the full run's."""
    batch, recipe, res = setup

    _sigma2, _ecorr2, U, _phi = gls_noise_model(batch, recipe)
    T = jnp.asarray(U, np.float64)
    dtype = T.dtype
    winv = jnp.where(batch.mask > 0, 1.0, 0.0).astype(dtype)
    r0 = jnp.zeros(batch.mask.shape, dtype)

    def kernel_at(tile):
        run = jax.jit(
            lambda a, b, c, t=int(tile):
            pallas_gp.fused_woodbury_xla(a, b, c, tile=t)
        )
        return lambda: run(T, winv, r0)

    def build_at(tile):
        @jax.jit
        def run(r, t=int(tile)):
            red, proj = gp.ReducedGP.build_fused(
                batch, recipe, residuals=r, dtype=r.dtype,
                tile=t, backend="xla",
            )
            return red.TNT, proj.rNr, proj.d

        return lambda: run(res)

    if tune:
        cache_path = tuner.DEFAULT_CACHE_PATH
    else:
        cache_path = os.path.join(
            tempfile.mkdtemp(prefix="gp_tuner_"), "cache.json"
        )
    choice = tuner.autotune(
        batch, T, backend="xla", candidates=TUNER_CANDIDATES,
        reps=reps, cache_path=cache_path,
    )
    looked_up = tuner.woodbury_tile(batch, "xla",
                                    cache_path=cache_path)
    # fresh kernel-level re-measurement — the quantity the roofline
    # objective optimized; >= parity means the choice reproduces
    default_ms = _median_ms(
        kernel_at(pallas_gp.DEFAULT_WOODBURY_TILE), reps
    )
    tuned_ms = _median_ms(kernel_at(looked_up), reps)
    speedup = default_ms / tuned_ms
    if looked_up != choice["tile"]:
        failures.append(
            f"tuner: lookup returned {looked_up}, search chose "
            f"{choice['tile']} — the cache round trip is broken"
        )
    if gate and speedup < 0.95:
        failures.append(
            f"tuner: tuned tile {looked_up} re-measures at "
            f"{speedup:.2f}x the default kernel — the search choice "
            "did not reproduce"
        )
    return {
        "tuned_tile": int(looked_up),
        "default_tile": int(pallas_gp.DEFAULT_WOODBURY_TILE),
        "kernel_default_ms": round(default_ms, 3),
        "kernel_tuned_ms": round(tuned_ms, 3),
        "tuner_speedup": round(speedup, 3),
        "build_default_ms": round(
            _median_ms(build_at(pallas_gp.DEFAULT_WOODBURY_TILE),
                       reps), 3
        ),
        "build_tuned_ms": round(_median_ms(build_at(looked_up), reps),
                                3),
        "candidates": choice["candidates"],
        "wrote_committed_cache": bool(tune),
    }


def main() -> int:
    argv = sys.argv[1:]
    fast = "--fast" in argv
    tune = "--tune" in argv
    out_path = None
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    reps = 3 if fast else 5
    scales = _scales(fast)

    failures = []
    t0 = time.monotonic()
    bit_identity = bit_identity_arm(failures)
    arms = {}
    flagship_setup = None
    for i, scale in enumerate(scales):
        # the committed default tile everywhere: the tuner arm owns
        # the tuned-vs-default comparison
        rec, setup = ab_arm(
            scale, reps, pallas_gp.DEFAULT_WOODBURY_TILE, failures,
            oracle=(i == 0),
        )
        arms[f"np{scale[0]}_nt{scale[1]}"] = rec
        flagship_setup = setup
    flagship = arms[f"np{scales[-1][0]}_nt{scales[-1][1]}"]
    # backend-aware speed gate (module docstring: the honest framing)
    floor = 1.3 if jax.default_backend() == "tpu" else 0.5
    if flagship["fused_speedup"] < floor:
        failures.append(
            f"flagship: fused_speedup {flagship['fused_speedup']} < "
            f"{floor} on {jax.default_backend()}"
        )
    bf16 = bf16_arm(flagship_setup, pallas_gp.DEFAULT_WOODBURY_TILE,
                    reps, failures)
    tuner_rec = tuner_arm(flagship_setup, reps, tune, failures,
                          gate=not fast)

    rec = {
        "bench": "gp_kernels",
        "backend": jax.default_backend(),
        "fast": fast,
        "wall_s": round(time.monotonic() - t0, 3),
        "scales": [list(s) for s in scales],
        "bit_identity": bit_identity,
        "arms": arms,
        "bf16": bf16,
        "tuner": tuner_rec,
        "ok": not failures,
        "failures": failures,
        **provenance_stamp(
            EVIDENCE_SCHEMA_VERSION,
            repo_root=os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            ),
        ),
    }
    payload = json.dumps(rec)
    print(payload)
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(payload + "\n")
    if failures:
        # CI /dev/nulls stdout (scripts/check.sh); the reason for an
        # exit 1 must land on stderr or it is invisible
        for f in failures:
            print(f"gp_kernels gate miss: {f}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
