"""Bench ladder for the likelihood subsystem: raw rank-reduced
evaluation throughput + the request-batched serving path's SLOs.

Three blocks, one JSON line (the LIKELIHOOD bench series,
``LIKELIHOOD_r*_cpu.json``, bench-diff-gated):

* ``raw_eval`` — hyperparameter-grid pricing of a realization bank
  through the two engines: the DIRECT path (full noise-model rebuild
  per point — what a naive implementation pays) vs the ReducedGP fast
  path (one Nt-sized projection, then a small Cholesky per point).
  Headline ``evals_per_s`` counts (hyperparameter point x realization)
  likelihood evaluations per second on the reduced path;
  ``reduced_speedup`` is the measured ratio between the two engines at
  the same grid (the rank-reduction payoff, arXiv:2607.06834's point).
* ``serve`` — the LikelihoodServer under closed-loop client load:
  ``--clients`` threads submitting grid-sampled requests as fast as
  results return. Reports the full SLO block: request latency
  p50/p95/p99 (streaming P^2 estimators), ``coalesce_efficiency``
  (served requests / batch-slot capacity — the dynamic-batching win),
  ``evals_per_s`` and ``requests_per_s``.
* ``serve_sweep`` — coalescing knee: the same load at max_batch 1
  (no coalescing — the control) vs the configured batch, so the
  batching gain is measured, not asserted.

Workload: synthetic NG15-flavored batch (default 16 psr x 1024 TOA,
EFAC/EQUAD/ECORR + 30-mode red noise + GWB auto-term: reduced basis
rank 120 + GP columns), bank of 128 realizations synthesized in
process. Sizes are CPU-container-friendly; env overrides
LKBENCH_NPSR / _NTOA / _NREAL / _GRID / _REQUESTS / _CLIENTS /
_MAX_BATCH scale it up on real hardware.

Usage: python benchmarks/likelihood_serve.py [--out PATH]
"""
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from pta_replicator_tpu import likelihood as lk  # noqa: E402
from pta_replicator_tpu import obs  # noqa: E402
from pta_replicator_tpu.batch import synthetic_batch  # noqa: E402
from pta_replicator_tpu.models.batched import Recipe, realize  # noqa: E402
from pta_replicator_tpu.utils.provenance import provenance_stamp  # noqa: E402

NPSR = int(os.environ.get("LKBENCH_NPSR", 16))
NTOA = int(os.environ.get("LKBENCH_NTOA", 1024))
NREAL = int(os.environ.get("LKBENCH_NREAL", 128))
GRID = int(os.environ.get("LKBENCH_GRID", 32))
REQUESTS = int(os.environ.get("LKBENCH_REQUESTS", 256))
CLIENTS = int(os.environ.get("LKBENCH_CLIENTS", 8))
MAX_BATCH = int(os.environ.get("LKBENCH_MAX_BATCH", 8))
MAX_DELAY_MS = float(os.environ.get("LKBENCH_MAX_DELAY_MS", 5.0))


def build_workload():
    batch = synthetic_batch(npsr=NPSR, ntoa=NTOA, nbackend=2, seed=0)
    nb = len(batch.backend_names)
    rng = np.random.default_rng(1)
    recipe = Recipe(
        efac=jnp.asarray(rng.uniform(0.9, 1.3, (NPSR, nb))),
        log10_equad=jnp.asarray(-6.5),
        log10_ecorr=jnp.asarray(-6.8),
        rn_log10_amplitude=jnp.asarray(rng.uniform(-13.8, -13.3, NPSR)),
        rn_gamma=jnp.asarray(rng.uniform(3.0, 4.5, NPSR)),
        gwb_log10_amplitude=jnp.asarray(-14.2),
        gwb_gamma=jnp.asarray(13.0 / 3.0),
    )
    bank = np.asarray(jax.block_until_ready(
        realize(jax.random.PRNGKey(0), batch, recipe, nreal=NREAL)
    ))
    return batch, recipe, bank


def bench_raw_eval(batch, recipe, bank):
    """Grid x bank pricing through both engines (best-of-3 reps each,
    compile excluded by a warmup call)."""
    grid, _shape = lk.grid_cartesian({
        "gwb_log10_amplitude": np.linspace(-14.6, -13.8, GRID),
    })
    g_arr = {k: jnp.asarray(v) for k, v in grid.items()}
    G = GRID

    # reduced path: engine warmup, then timed reps (includes the
    # projection amortized separately — serving reprojects only when
    # the bank changes)
    t0 = time.perf_counter()
    reduced = lk.gp.ReducedGP.build(batch, recipe)
    proj = jax.block_until_ready(
        jax.vmap(lambda r: reduced.project(r, batch))(jnp.asarray(bank))
    )
    project_s = time.perf_counter() - t0

    from pta_replicator_tpu.likelihood.infer import (
        _reduced_grid_engine_bank,
        _theta_block,
    )

    names, theta = _theta_block(g_arr, batch.toas_s.dtype)
    engine = _reduced_grid_engine_bank(names)
    jax.block_until_ready(engine(theta, reduced, proj, batch, recipe))
    reduced_s = min(
        _timed(lambda: jax.block_until_ready(
            engine(theta, reduced, proj, batch, recipe)))
        for _ in range(3)
    )

    # direct path at the same grid: per-point noise-model rebuild +
    # per-realization Nt-sized Woodbury (vmapped over the bank too)
    from pta_replicator_tpu.obs import instrumented_jit

    def direct(theta_block, bank_block):
        def one(th):
            import dataclasses

            r2 = dataclasses.replace(
                recipe, **{names[0]: th[0]}
            )
            return jax.vmap(
                lambda r: lk.loglikelihood(r, batch, r2)
            )(bank_block)

        return jax.vmap(one)(theta_block)

    djit = instrumented_jit(direct, name="likelihood.gp_engine")
    bank_dev = jnp.asarray(bank)
    jax.block_until_ready(djit(theta, bank_dev))
    direct_s = min(
        _timed(lambda: jax.block_until_ready(djit(theta, bank_dev)))
        for _ in range(3)
    )

    # coalescing-cost microbench: per-request engine wall vs batch
    # size, in isolation (no clients, no queueing). On a dispatch-
    # bound accelerator per-request cost FALLS with batch size (the
    # amortization serving exists for); on a compute-bound CPU host it
    # is flat-to-rising — the committed numbers pin which regime the
    # capture ran in, and batch_overhead_ratio (per-request cost at
    # max_batch / at 1) is the lower-better leaf bench-diff watches.
    per_request_ms = {}
    for nb in sorted({1, 2, MAX_BATCH}):
        gb = {
            "gwb_log10_amplitude": jnp.linspace(-14.5, -14.0, nb),
            "gwb_gamma": jnp.full((nb,), 4.33),
        }
        nb_names, nb_theta = _theta_block(gb, batch.toas_s.dtype)
        nb_engine = _reduced_grid_engine_bank(nb_names)
        jax.block_until_ready(
            nb_engine(nb_theta, reduced, proj, batch, recipe)
        )
        t = min(
            _timed(lambda: jax.block_until_ready(
                nb_engine(nb_theta, reduced, proj, batch, recipe)))
            for _ in range(5)
        )
        per_request_ms[f"b{nb}"] = round(t / nb * 1e3, 3)

    evals = G * bank.shape[0]
    return {
        "grid_points": G,
        "nreal": int(bank.shape[0]),
        "project_s": round(project_s, 4),
        "reduced_s": round(reduced_s, 4),
        "direct_s": round(direct_s, 4),
        "evals_per_s": round(evals / reduced_s, 2),
        "direct_evals_per_s": round(evals / direct_s, 2),
        "reduced_speedup": round(direct_s / reduced_s, 2),
        "engine_per_request_ms": per_request_ms,
        "batch_overhead_ratio": round(
            per_request_ms[f"b{MAX_BATCH}"] / per_request_ms["b1"], 3
        ),
    }


def _timed(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def bench_serve(batch, recipe, bank, max_batch, tag):
    """Closed-loop client load against the server; returns the SLO
    stats block plus wall time."""
    server = lk.LikelihoodServer(
        lk.RealizationBank.from_array(bank),
        batch, recipe,
        axes=("gwb_log10_amplitude", "gwb_gamma"),
        max_batch=max_batch,
        max_delay_s=MAX_DELAY_MS / 1e3,
    )
    rng = np.random.default_rng(2)
    amps = rng.uniform(-14.6, -13.8, REQUESTS)
    gammas = rng.uniform(3.8, 4.8, REQUESTS)
    errors = []

    def client(indices):
        for i in indices:
            try:
                server.submit(
                    gwb_log10_amplitude=amps[i], gwb_gamma=gammas[i]
                ).result(timeout=300)
            except Exception as exc:  # noqa: BLE001 — reported in JSON
                errors.append(repr(exc))
                return

    # warm the engine before the clock starts (compile is a one-time
    # cost the SLO numbers must not smear over)
    with server:
        server.evaluate(gwb_log10_amplitude=-14.2, gwb_gamma=4.33)
        server.reset_stats()
        t0 = time.perf_counter()
        threads = [
            threading.Thread(
                target=client, args=(range(k, REQUESTS, CLIENTS),)
            )
            for k in range(CLIENTS)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        stats = server.stats()
    out = {
        "tag": tag,
        "wall_s": round(wall, 4),
        "clients": CLIENTS,
        "requests": stats["requests"],
        "max_batch": max_batch,
        "max_delay_ms": MAX_DELAY_MS,
        "coalesce_efficiency": round(stats["coalesce_efficiency"], 4),
        "batch_fill_mean": round(stats["batch_fill_mean"], 3),
        "evals_per_s": round(stats["evals"] / wall, 2),
        "requests_per_s": round(stats["requests"] / wall, 2),
        "latency": {
            k: round(v, 6) for k, v in stats["latency"].items()
        },
    }
    if errors:
        out["errors"] = errors[:8]
    return out


def main():
    out_path = None
    if "--out" in sys.argv:
        out_path = sys.argv[sys.argv.index("--out") + 1]
    obs.reset_all()
    t_setup = time.perf_counter()
    batch, recipe, bank = build_workload()
    setup_s = time.perf_counter() - t_setup

    doc = {
        "artifact": (
            "likelihood/ bench: rank-reduced GP likelihood engine "
            "throughput + request-batched serving SLOs (ISSUE 9 "
            "tentpole evidence)"
        ),
        **provenance_stamp(2),
        "device_kind": jax.devices()[0].platform,
        "workload": {
            "npsr": NPSR, "ntoa": NTOA, "nreal": NREAL,
            "noise_model": "EFAC+EQUAD+ECORR+RN(30)+GWBauto(30)",
            "reduced_rank": int(
                lk.gp.ReducedGP.build(batch, recipe).TNT.shape[-1]
            ),
            "bank_synthesis_s": round(setup_s, 3),
        },
        "raw_eval": bench_raw_eval(batch, recipe, bank),
        "serve": bench_serve(batch, recipe, bank, MAX_BATCH, "batched"),
        "serve_nobatch_control": bench_serve(
            batch, recipe, bank, 1, "control"
        ),
    }
    ratio = doc["raw_eval"]["batch_overhead_ratio"]
    doc["summary"] = (
        f"reduced engine {doc['raw_eval']['evals_per_s']:.0f} evals/s "
        f"({doc['raw_eval']['reduced_speedup']:.1f}x the direct path); "
        f"serving {doc['serve']['requests_per_s']:.0f} req/s at "
        f"p50 {doc['serve']['latency'].get('p50', 0) * 1e3:.1f} ms / "
        f"p99 {doc['serve']['latency'].get('p99', 0) * 1e3:.1f} ms, "
        f"coalesce {doc['serve']['coalesce_efficiency']:.2f}; "
        f"uncoalesced control "
        f"{doc['serve_nobatch_control']['requests_per_s']:.0f} req/s — "
        f"on this CPU host the engine is COMPUTE-bound (per-request "
        f"dispatch ~0.1 ms vs ~{doc['raw_eval']['engine_per_request_ms']['b1']:.0f} ms "
        f"compute; batch_overhead_ratio {ratio:.2f}), so coalescing is "
        "amortization headroom for accelerator dispatch, not a CPU "
        "throughput win — the control arm pins that honestly"
    )
    payload = json.dumps(doc, indent=1, sort_keys=True)
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(payload + "\n")
    print(payload)


if __name__ == "__main__":
    main()
