"""Pre-serialize the bench workload's deterministic static plane on the
CPU backend, so benchmarks/fast_capture.py spends a flaky-tunnel window
on the measurement instead of on an extra compile.

The static plane (CW-catalog delays; deterministic_delays) is
key-independent data: its f64 host plane precompute happens on the host
either way, so the CPU-computed f32 plane is numerically equivalent input
data for the rate measurement (the timed region is run_chunk only).
Writes /tmp/workload.npz (~2 MB).
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_platforms", "cpu")

from bench import build_workload  # noqa: E402
from pta_replicator_tpu.models.batched import deterministic_delays  # noqa: E402

t = time.time()
batch, recipe = build_workload(ncw=100)
static = np.asarray(deterministic_delays(batch, recipe))
# atomic write: a reader (fast_capture mid-window) must never see a
# truncated file
tmp = "/tmp/workload.tmp.npz"  # np.savez appends .npz to other suffixes
np.savez(tmp, static=static)
os.replace(tmp, "/tmp/workload.npz")
print(f"wrote /tmp/workload.npz {static.shape} {static.dtype} "
      f"in {time.time()-t:.1f}s")
