"""Pre-serialize the bench workload's deterministic static plane on the
CPU backend, so benchmarks/fast_capture.py spends a flaky-tunnel window
on the measurement instead of on an extra compile.

The static plane (CW-catalog delays; deterministic_delays) is
key-independent data: its f64 host plane precompute happens on the host
either way, so the CPU-computed f32 plane is numerically equivalent input
data for the rate measurement (the timed region is run_chunk only).
Writes /tmp/workload.npz (~2 MB).

It also writes the CW coefficient-plane TILE cache
(/tmp/cw_plane_tiles.npz, parallel.prefetch.save_plane_tiles) stamped
with the same workload fingerprint: the streamed plane pipeline
(models.batched.cw_stream_response) can then feed a TPU capture window
straight from disk — zero seconds rebuilding planes inside the window,
and at large-catalog shapes (MK_NCW) the tiles are the only
memory-feasible serialization (the monolithic plane set at the
reference's 1e7-source regime needs >100 GB of f64 host intermediates;
CW_SCALING_r05_cpu.json records the segfault).

Env knobs: MK_NCW (catalog size, default 100 — the bench workload),
MK_PLANE_CHUNK (tile width, default 65536), MK_PLANE_TILES (tile-cache
path; '0' skips, default /tmp/cw_plane_tiles.npz).
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_platforms", "cpu")

from bench import build_workload  # noqa: E402
from pta_replicator_tpu.models.batched import (  # noqa: E402
    cw_catalog_plane_tiles_for,
    deterministic_delays,
)
from pta_replicator_tpu.parallel.prefetch import save_plane_tiles  # noqa: E402

ncw = int(os.environ.get("MK_NCW", "100"))
t = time.monotonic()
# the fingerprint binds the cache to THIS workload definition (build
# params, host draw bytes, STREAM_VERSION): fast_capture verifies it
# before reuse, so a plane serialized from an older workload can never
# silently substitute different static data (ADVICE.md r5)
batch, recipe, fp = build_workload(ncw=ncw, with_fingerprint=True)
static = np.asarray(deterministic_delays(batch, recipe))
# atomic write: a reader (fast_capture mid-window) must never see a
# truncated file
tmp = "/tmp/workload.tmp.npz"  # np.savez appends .npz to other suffixes
np.savez(tmp, static=static, fingerprint=np.array(fp))
os.replace(tmp, "/tmp/workload.npz")
print(f"wrote /tmp/workload.npz {static.shape} {static.dtype} "
      f"fp={fp} in {time.monotonic()-t:.1f}s")

tiles_path = os.environ.get("MK_PLANE_TILES", "/tmp/cw_plane_tiles.npz")
if tiles_path != "0":
    t = time.monotonic()
    chunk = int(os.environ.get("MK_PLANE_CHUNK", "65536"))
    # pdist/pphase forwarded exactly as deterministic_delays' streamed
    # path forwards them: the fingerprint only covers the DRAWN recipe
    # inputs, so a constant pdist/pphase dropped here would produce a
    # cache with different pulsar-term physics that still passes the
    # fingerprint gate
    tiles = cw_catalog_plane_tiles_for(
        batch, *[recipe.cgw_params[i] for i in range(8)],
        pdist=recipe.cgw_pdist if recipe.cgw_pdist is not None else 1.0,
        pphase=recipe.cgw_pphase,
        evolve=recipe.cgw_evolve, phase_approx=recipe.cgw_phase_approx,
        tref_s=recipe.cgw_tref_s, chunk=chunk,
    )
    # save_plane_tiles streams tile-by-tile (bounded memory) and renames
    # into place only when complete, so the same mid-window reader
    # guarantee holds; the fingerprint gates reuse exactly like the
    # static-plane cache above
    ntiles = save_plane_tiles(
        tiles_path, tiles, fingerprint=fp,
        meta={"ncw": ncw, "chunk": chunk, "npsr": int(batch.npsr),
              "evolve": bool(recipe.cgw_evolve),
              "psr_term": bool(recipe.cgw_psr_term)},
    )
    print(f"wrote {tiles_path} ({ntiles} tile(s), chunk={chunk}) "
          f"fp={fp} in {time.monotonic()-t:.1f}s")
