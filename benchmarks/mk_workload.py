"""Pre-serialize the bench workload's deterministic static plane on the
CPU backend, so benchmarks/fast_capture.py spends a flaky-tunnel window
on the measurement instead of on an extra compile.

The static plane (CW-catalog delays; deterministic_delays) is
key-independent data: its f64 host plane precompute happens on the host
either way, so the CPU-computed f32 plane is numerically equivalent input
data for the rate measurement (the timed region is run_chunk only).
Writes /tmp/workload.npz (~2 MB).
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_platforms", "cpu")

from bench import build_workload  # noqa: E402
from pta_replicator_tpu.models.batched import deterministic_delays  # noqa: E402

t = time.monotonic()
# the fingerprint binds the cache to THIS workload definition (build
# params, host draw bytes, STREAM_VERSION): fast_capture verifies it
# before reuse, so a plane serialized from an older workload can never
# silently substitute different static data (ADVICE.md r5)
batch, recipe, fp = build_workload(ncw=100, with_fingerprint=True)
static = np.asarray(deterministic_delays(batch, recipe))
# atomic write: a reader (fast_capture mid-window) must never see a
# truncated file
tmp = "/tmp/workload.tmp.npz"  # np.savez appends .npz to other suffixes
np.savez(tmp, static=static, fingerprint=np.array(fp))
os.replace(tmp, "/tmp/workload.npz")
print(f"wrote /tmp/workload.npz {static.shape} {static.dtype} "
      f"fp={fp} in {time.monotonic()-t:.1f}s")
