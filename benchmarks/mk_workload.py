"""Pre-serialize the bench workload's deterministic static plane on the
CPU backend, so benchmarks/fast_capture.py spends a flaky-tunnel window
on the measurement instead of on an extra compile.

Since round 12 this is a thin shim over the scenario compiler: the
flagship workload is the committed spec
``pta_replicator_tpu/scenarios/specs/flagship.json`` (the
``bench_flagship`` preset), compiled by ``scenarios.compile`` — the ONE
implementation of the workload's legacy RNG call order and content
fingerprint, so the ``/tmp/workload.npz`` fingerprint contract is
unchanged (tests pin the shim's fingerprint against
``bench.build_workload``'s).

The static plane (CW-catalog delays; deterministic_delays) is
key-independent data: its f64 host plane precompute happens on the host
either way, so the CPU-computed f32 plane is numerically equivalent input
data for the rate measurement (the timed region is run_chunk only).
Writes /tmp/workload.npz (~2 MB).

It also writes the CW coefficient-plane TILE cache
(/tmp/cw_plane_tiles.npz, parallel.prefetch.save_plane_tiles) stamped
with the same workload fingerprint: the streamed plane pipeline
(models.batched.cw_stream_response) can then feed a TPU capture window
straight from disk — zero seconds rebuilding planes inside the window,
and at large-catalog shapes (MK_NCW) the tiles are the only
memory-feasible serialization (the monolithic plane set at the
reference's 1e7-source regime needs >100 GB of f64 host intermediates;
CW_SCALING_r05_cpu.json records the segfault).

Env knobs: MK_NCW (catalog size, default 100 — the bench workload),
MK_PLANE_CHUNK (tile width, default 65536), MK_PLANE_TILES (tile-cache
path; '0' skips, default /tmp/cw_plane_tiles.npz), MK_SPEC (an
alternative scenario spec file to compile instead of the flagship).
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_platforms", "cpu")

from pta_replicator_tpu.models.batched import (  # noqa: E402
    cw_catalog_plane_tiles_for,
    deterministic_delays,
)
from pta_replicator_tpu.parallel.prefetch import save_plane_tiles  # noqa: E402
from pta_replicator_tpu.scenarios import compile_spec, load_spec  # noqa: E402

spec_path = os.environ.get("MK_SPEC") or os.path.join(
    os.path.dirname(__file__), "..", "pta_replicator_tpu", "scenarios",
    "specs", "flagship.json",
)
spec = load_spec(spec_path)
if spec.preset == "bench_flagship":
    # MK_NCW scales the flagship catalog exactly as it always did (the
    # fingerprint covers the override, so a differently-sized cache can
    # never masquerade as the bench workload) — but only when actually
    # SET, so an MK_SPEC carrying its own ncw is not silently clobbered
    # by the default; BENCH_BACKEND / BENCH_SYNTH_PRECISION keep
    # flowing into the recipe exactly as they did through
    # bench.build_workload (recipe knobs, not fingerprint inputs)
    if "MK_NCW" in os.environ:
        spec.preset_params = {**spec.preset_params,
                              "ncw": int(os.environ["MK_NCW"])}
    if os.environ.get("BENCH_BACKEND"):
        spec.preset_params["cgw_backend"] = os.environ["BENCH_BACKEND"]
    if os.environ.get("BENCH_SYNTH_PRECISION"):
        spec.preset_params["gwb_synthesis_precision"] = os.environ[
            "BENCH_SYNTH_PRECISION"]

t = time.monotonic()
# the fingerprint binds the cache to THIS workload definition (build
# params, host draw bytes, STREAM_VERSION): fast_capture verifies it
# before reuse, so a plane serialized from an older workload can never
# silently substitute different static data (ADVICE.md r5)
compiled = compile_spec(spec)
batch, recipe, fp = compiled.batch, compiled.recipe, compiled.fingerprint
# the catalog size ACTUALLY compiled (tile-cache meta + log) — never
# the MK_NCW env default, which does not apply to non-preset specs
ncw = (int(recipe.cgw_params.shape[1])
       if recipe.cgw_params is not None else 0)
static = np.asarray(deterministic_delays(batch, recipe))
# atomic write: a reader (fast_capture mid-window) must never see a
# truncated file
tmp = "/tmp/workload.tmp.npz"  # np.savez appends .npz to other suffixes
np.savez(tmp, static=static, fingerprint=np.array(fp))
os.replace(tmp, "/tmp/workload.npz")
print(f"wrote /tmp/workload.npz {static.shape} {static.dtype} "
      f"fp={fp} in {time.monotonic()-t:.1f}s")

tiles_path = os.environ.get("MK_PLANE_TILES", "/tmp/cw_plane_tiles.npz")
if tiles_path != "0" and recipe.cgw_params is not None:
    t = time.monotonic()
    chunk = int(os.environ.get("MK_PLANE_CHUNK", "65536"))
    # pdist/pphase forwarded exactly as deterministic_delays' streamed
    # path forwards them: the fingerprint only covers the DRAWN recipe
    # inputs, so a constant pdist/pphase dropped here would produce a
    # cache with different pulsar-term physics that still passes the
    # fingerprint gate
    tiles = cw_catalog_plane_tiles_for(
        batch, *[recipe.cgw_params[i] for i in range(8)],
        pdist=recipe.cgw_pdist if recipe.cgw_pdist is not None else 1.0,
        pphase=recipe.cgw_pphase,
        evolve=recipe.cgw_evolve, phase_approx=recipe.cgw_phase_approx,
        tref_s=recipe.cgw_tref_s, chunk=chunk,
    )
    # save_plane_tiles streams tile-by-tile (bounded memory) and renames
    # into place only when complete, so the same mid-window reader
    # guarantee holds; the fingerprint gates reuse exactly like the
    # static-plane cache above
    ntiles = save_plane_tiles(
        tiles_path, tiles, fingerprint=fp,
        meta={"ncw": ncw, "chunk": chunk, "npsr": int(batch.npsr),
              "evolve": bool(recipe.cgw_evolve),
              "psr_term": bool(recipe.cgw_psr_term)},
    )
    print(f"wrote {tiles_path} ({ntiles} tile(s), chunk={chunk}) "
          f"fp={fp} in {time.monotonic()-t:.1f}s")
