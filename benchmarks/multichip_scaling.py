"""Multi-chip scaling bench for the flagship sweep: the full pipelined
step (sharded dispatch -> per-shard readback -> sharded checkpoints) at
1/2/4/8 devices, with device-compute scaling efficiency and occupancy
bottleneck attribution per arm.

Runnable TODAY on CPU (the point: every round records a number even
when the TPU tunnel is down): when no devices are forced yet, the
script sets ``XLA_FLAGS=--xla_force_host_platform_device_count=8``
itself before JAX initializes. On a real TPU slice the same script is
the MULTICHIP capture tool — no flags needed, the arms walk the real
chips.

What "scaling efficiency" means here, precisely:

* ``speedup`` per arm is wall-clock of the device-compute portion
  (chunked engine dispatches fenced once at the end — no readback, no
  disk) vs the single-device arm.
* ``attainable_speedup`` is the parallel headroom the host actually
  offers. On a real accelerator platform that is simply ``n_devices``.
  On the CPU host platform the "devices" are virtual and share the
  machine's cores, AND the single-device XLA CPU backend already runs
  multi-threaded — so the attainable speedup from sharding is
  ``min(n_devices, ncores / util_1)`` where ``util_1`` is the measured
  core-utilization of the single-device arm (process cpu-time / wall).
  A 2-core host whose baseline already burns 1.4 cores can at best go
  1.43x faster, no matter how many virtual devices exist; pretending
  the ideal is 8x would make the CPU number meaningless noise, and
  pretending it is 1x would hide real sharding overhead.
* ``scaling_efficiency = speedup / attainable_speedup`` — on TPU this
  reduces to the classic strong-scaling efficiency (target >= 0.75 =
  6x/8 devices, ROADMAP item 2); on CPU it isolates exactly what CAN
  be measured without real parallel silicon: how much wall the
  multi-chip machinery (per-device dispatch, shard assembly,
  collectives) costs relative to the headroom available. Both the raw
  and normalized numbers are in the JSON; nothing is hidden.

Bit-identity evidence (the sharded-checkpoint contract) is measured on
a white-noise workload — elementwise per (real, psr, toa), so XLA's
shape-dependent contraction lowering cannot reorder any float
reduction — where the 8-device sharded-checkpoint sweep must produce a
consolidated npz BYTE-equal to the single-chip pipelined sweep. The
full (red-noise) workload's cross-topology deviation is reported as
``single_chip_max_abs_dev`` (float reduction order in partitioned
contractions, the documented utils.sweep caveat — ~1e-20 in f64).

Occupancy: each full-step arm runs under the obs tracer and embeds the
``multichip_sweep``-windowed stage-occupancy analysis (obs.occupancy)
— per-stage duty, overlap efficiency, and the bottleneck verdict
("where does the gap go: H2D, readback, or write"), PR 6's attribution
machinery pointed at the multi-chip path.

Fused mesh arms (r17): every device count also runs the sweep as ONE
fused stage graph (``fused_stream=True`` + mesh — host tile build,
per-device H2D, sharded compute, per-shard D2H, and PARALLEL per-shard
durable writers in a single overlapped graph). Per fused arm the
critical-path attribution (obs.critpath) records
``io_write_exclusive_share`` — the exclusive-shadow seconds io_write
holds on the critical path as a fraction of wall (the r06 baseline
pinned io_write at 83% busy; the parallel writers + fused overlap must
pull its exclusive share well below that) — and
``shard_writer_occupancy``, the mean number of concurrently-busy shard
writers (sum of shard_write span seconds / io_write busy seconds;
1.0 = serial writes, >1 = genuinely overlapped pwrite+fsync).

Fused identity evidence: at >= 2 mesh shapes the fused mesh sweep's
consolidated npz is byte-equal to the stacked mesh sweep AND the
single-chip pipelined sweep, and a fused sweep killed mid-run under
one mesh shape resumes FUSED under a different shape to the same
bytes (the preemption + retopology story, end to end).

Prints one JSON line. Knobs: MULTICHIP_NREAL (2048), MULTICHIP_CHUNK
(512), MULTICHIP_NPSR (8), MULTICHIP_NTOA (4096), MULTICHIP_NMODES
(100), MULTICHIP_DEVICES ("1,2,4,8"), MULTICHIP_NREP (3). The default
chunk is deliberately large: the multi-device execution overhead of
the virtual-CPU backend is a fixed per-dispatch cost (~0.15 s/chunk at
8 devices on the 2-core host), so small chunks measure dispatch amortization,
not the sharded pipeline.

``--fast`` runs the seconds-scale CI arm (scripts/check.sh): 8 virtual
CPU devices, a 2-chunk fused mesh sweep, the multi-shape byte-identity
+ crash-resume gates, and the writer-overlap gate
(``shard_writer_occupancy > 1``) — exit 1 with reasons on stderr.
"""
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# force the virtual multi-device CPU host BEFORE jax initializes, unless
# the caller already forced a device count (or runs on real chips)
if "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
) and os.environ.get("JAX_PLATFORMS", "cpu") == "cpu":
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from pta_replicator_tpu import obs  # noqa: E402
from pta_replicator_tpu.batch import synthetic_batch  # noqa: E402
from pta_replicator_tpu.models.batched import Recipe, realize  # noqa: E402
from pta_replicator_tpu.obs import critpath, names, occupancy  # noqa: E402
from pta_replicator_tpu.parallel.mesh import (  # noqa: E402
    make_mesh,
    sharded_realize,
    static_delays,
)
from pta_replicator_tpu.utils.provenance import (  # noqa: E402
    EVIDENCE_SCHEMA_VERSION,
    provenance_stamp,
)
from pta_replicator_tpu.utils.sweep import sweep  # noqa: E402


def _compute_arm(n_dev, key, batch, recipe, nreal, chunk, nrep):
    """Device-compute portion only: dispatch every chunk back-to-back
    through the (sharded) engine, fence once — no readback of the
    cubes, no disk. Returns (best wall_s, util_cores at best rep)."""
    mesh = make_mesh(n_dev, 1) if n_dev > 1 else None
    static = static_delays(batch, recipe, mesh=mesh)

    def run():
        outs = []
        for i in range(nreal // chunk):
            k = jax.random.fold_in(key, i)
            if mesh is not None:
                outs.append(sharded_realize(
                    k, batch, recipe, nreal=chunk, mesh=mesh, static=static
                ))
            else:
                outs.append(realize(k, batch, recipe, nreal=chunk,
                                    static=static))
        jax.block_until_ready(outs)

    run()  # warm: compile the engine for this mesh
    best = None
    for _ in range(nrep):
        c0, t0 = time.process_time(), time.perf_counter()
        run()
        wall = time.perf_counter() - t0
        util = (time.process_time() - c0) / wall
        if best is None or wall < best[0]:
            best = (wall, util)
    return best


def _full_step_arm(n_dev, key, batch, recipe, nreal, chunk, workdir,
                   fused=False):
    """The complete flagship step: pipelined sweep with full residual
    cubes, per-shard readback, and sharded checkpoints, under the obs
    tracer — stacked (default) or as the ONE fused stage graph (r17).
    Returns (wall_s, occupancy, result, captured span events)."""
    mesh = make_mesh(n_dev, 1) if n_dev > 1 else None
    arm_dir = tempfile.mkdtemp(prefix=f"mc_d{n_dev}_", dir=workdir)
    ck = os.path.join(arm_dir, "sweep.npz")
    obs.reset_all()
    t0 = time.perf_counter()
    out = sweep(key, batch, recipe, nreal=nreal, chunk=chunk,
                checkpoint_path=ck, reduce_fn=None, mesh=mesh,
                pipeline_depth=2, durable=True, fused_stream=fused)
    wall = time.perf_counter() - t0
    events = obs.TRACER.events()
    if obs.TRACER.dropped:
        occ = {"skipped": f"{obs.TRACER.dropped} span records dropped"}
    else:
        occ = obs.occupancy.analyze(events)
    shutil.rmtree(arm_dir, ignore_errors=True)
    return wall, occ, out, events


def _writer_stats(events):
    """(io_write_exclusive_share, shard_writer_occupancy, verdict
    summary) from a fused arm's capture.

    ``io_write_exclusive_share`` is critpath's exclusive-shadow
    attribution for io_write over the phase window (seconds only
    io_write was the busiest active stage, / wall) — the honest
    "is the step write-bound?" number, immune to the double-counting
    a raw duty figure carries once writes overlap compute.
    ``shard_writer_occupancy`` is sum(shard_write span wall) / the
    busy seconds of the shard_write spans' union: the mean number of
    concurrently-busy per-shard writers while ANY writer is busy
    (1.0 = strictly serial writes, N = all N writers overlapped)."""
    spans = [e for e in events if e.get("type") == "span"]
    doc = critpath.analyze(spans)
    share = None
    if doc:
        st = (doc.get("stages") or {}).get(names.SPAN_IO_WRITE)
        share = None if st is None else st["critical_share"]
    shard_iv = occupancy.stage_intervals(
        spans, stages=[names.SPAN_SHARD_WRITE]
    ).get(names.SPAN_SHARD_WRITE, [])
    shard_sum = sum(t1 - t0 for t0, t1 in shard_iv)
    shard_union = occupancy.busy_seconds(
        occupancy.merge_intervals(shard_iv))
    writers = (round(shard_sum / shard_union, 3)
               if shard_union > 0.0 else None)
    verdict = ((doc or {}).get("verdict") or {}).get("summary")
    return share, writers, verdict


def _bit_identity_check(key, npsr, ntoa, workdir, n_dev):
    """White-noise workload (elementwise — no contraction for XLA to
    re-tile): single-chip pipelined sweep vs n_dev-device sharded-
    checkpoint sweep must agree BYTE-for-byte on the consolidated npz
    and exactly on the result."""
    batch = synthetic_batch(npsr=npsr, ntoa=ntoa, nbackend=2, seed=3)
    recipe = Recipe(
        efac=jnp.full((npsr, 2), 1.1, batch.toas_s.dtype),
        log10_equad=jnp.full((npsr, 2), -6.5, batch.toas_s.dtype),
    )
    d = tempfile.mkdtemp(prefix="mc_bitid_", dir=workdir)
    ck1 = os.path.join(d, "single.npz")
    ckn = os.path.join(d, "mesh.npz")
    # chunk >= 2 realizations per shard: a size-1 vmap rides a different
    # XLA fusion even for elementwise code (measured) — per-shard >= 2
    # keeps the lowering, and therefore the bytes, identical
    nreal, chunk = 8 * n_dev, 2 * n_dev
    ref = sweep(key, batch, recipe, nreal=nreal, chunk=chunk,
                checkpoint_path=ck1, reduce_fn=None, pipeline_depth=2)
    mesh = make_mesh(n_dev, 1)
    got = sweep(key, batch, recipe, nreal=nreal, chunk=chunk,
                checkpoint_path=ckn, reduce_fn=None, mesh=mesh,
                pipeline_depth=2)
    same_bytes = open(ck1, "rb").read() == open(ckn, "rb").read()
    same_values = bool(np.array_equal(ref, got))
    shutil.rmtree(d, ignore_errors=True)
    return same_bytes and same_values


def _fused_identity_check(key, npsr, ntoa, workdir, shapes):
    """The r17 identity gates on the white-noise workload: at every
    mesh shape in ``shapes`` the FUSED mesh sweep's consolidated npz is
    byte-equal to the stacked mesh sweep AND to the single-chip
    pipelined reference; plus the retopology gate — a fused sweep
    killed after 2 chunks under shapes[0] resumes FUSED under
    shapes[-1] to the same bytes. Returns {gate_name: bool}."""
    batch = synthetic_batch(npsr=npsr, ntoa=ntoa, nbackend=2, seed=3)
    recipe = Recipe(
        efac=jnp.full((npsr, 2), 1.1, batch.toas_s.dtype),
        log10_equad=jnp.full((npsr, 2), -6.5, batch.toas_s.dtype),
    )
    d = tempfile.mkdtemp(prefix="mc_fused_bitid_", dir=workdir)
    gates = {}
    # chunk holds >= 2 realizations per shard on the LARGEST real axis
    max_real = max(s[0] for s in shapes)
    nreal, chunk = 8 * max_real, 2 * max_real
    ck_ref = os.path.join(d, "single.npz")
    sweep(key, batch, recipe, nreal=nreal, chunk=chunk,
          checkpoint_path=ck_ref, reduce_fn=None, pipeline_depth=2)
    ref_bytes = open(ck_ref, "rb").read()
    for shape in shapes:
        tag = f"{shape[0]}x{shape[1]}"
        ck_s = os.path.join(d, f"stacked_{tag}.npz")
        ck_f = os.path.join(d, f"fused_{tag}.npz")
        sweep(key, batch, recipe, nreal=nreal, chunk=chunk,
              checkpoint_path=ck_s, reduce_fn=None,
              mesh=make_mesh(*shape), pipeline_depth=2)
        sweep(key, batch, recipe, nreal=nreal, chunk=chunk,
              checkpoint_path=ck_f, reduce_fn=None,
              mesh=make_mesh(*shape), pipeline_depth=2,
              fused_stream=True)
        gates[f"fused_{tag}_bit_identical"] = (
            open(ck_f, "rb").read() == ref_bytes)
        gates[f"stacked_{tag}_bit_identical"] = (
            open(ck_s, "rb").read() == ref_bytes)

    class _Stop(Exception):
        pass

    def bomb(done, total):
        if done == 2:
            raise _Stop

    ck_r = os.path.join(d, "retopo.npz")
    try:
        sweep(key, batch, recipe, nreal=nreal, chunk=chunk,
              checkpoint_path=ck_r, reduce_fn=None,
              mesh=make_mesh(*shapes[0]), pipeline_depth=2,
              fused_stream=True, progress=bomb)
    except _Stop:
        pass
    sweep(key, batch, recipe, nreal=nreal, chunk=chunk,
          checkpoint_path=ck_r, reduce_fn=None,
          mesh=make_mesh(*shapes[-1]), pipeline_depth=2,
          fused_stream=True)
    gates["fused_resume_across_mesh_change_bit_identical"] = (
        open(ck_r, "rb").read() == ref_bytes)
    shutil.rmtree(d, ignore_errors=True)
    return gates


def main() -> int:
    fast = "--fast" in sys.argv[1:]
    if fast:
        defaults = dict(nreal="32", chunk="16", npsr="8", ntoa="256",
                        nmodes="16", nrep="1", devices="1,8")
    else:
        defaults = dict(nreal="2048", chunk="512", npsr="8", ntoa="4096",
                        nmodes="100", nrep="3", devices="1,2,4,8")
    nreal = int(os.environ.get("MULTICHIP_NREAL", defaults["nreal"]))
    chunk = int(os.environ.get("MULTICHIP_CHUNK", defaults["chunk"]))
    npsr = int(os.environ.get("MULTICHIP_NPSR", defaults["npsr"]))
    ntoa = int(os.environ.get("MULTICHIP_NTOA", defaults["ntoa"]))
    nmodes = int(os.environ.get("MULTICHIP_NMODES", defaults["nmodes"]))
    nrep = int(os.environ.get("MULTICHIP_NREP", defaults["nrep"]))
    arms = [int(x) for x in os.environ.get(
        "MULTICHIP_DEVICES", defaults["devices"]).split(",")]

    platform = jax.default_backend()
    n_visible = jax.device_count()
    ncores = os.cpu_count() or 1
    arms = [n for n in arms if n <= n_visible]

    batch = synthetic_batch(npsr=npsr, ntoa=ntoa, seed=0)
    recipe = Recipe(
        efac=jnp.ones(npsr, batch.toas_s.dtype),
        rn_log10_amplitude=jnp.full(npsr, -14.0, batch.toas_s.dtype),
        rn_gamma=jnp.full(npsr, 4.0, batch.toas_s.dtype),
        rn_nmodes=nmodes,
    )
    key = jax.random.PRNGKey(7)
    workdir = tempfile.mkdtemp(prefix="multichip_scaling_")
    try:
        arm_recs = {}
        # only the first arm's cube is needed later (cross-topology
        # deviation vs the top arm) — retaining every arm's full result
        # cube would hold len(arms) copies of the workload in host RAM
        first_out = None
        last_out = None
        base = None
        fused_base_s = None
        for n in arms:
            comp_s, util = _compute_arm(
                n, key, batch, recipe, nreal, chunk, nrep)
            full_s, occ, out, _ev = _full_step_arm(
                n, key, batch, recipe, nreal, chunk, workdir)
            fused_s, _focc, fout, fev = _full_step_arm(
                n, key, batch, recipe, nreal, chunk, workdir, fused=True)
            share, writers, verdict = _writer_stats(fev)
            fused_matches = bool(np.array_equal(out, fout))
            del fout
            if first_out is None:
                first_out = out
            last_out = out
            if base is None:
                base = (comp_s, util)
            if fused_base_s is None:
                fused_base_s = fused_s
            speedup = base[0] / comp_s
            if platform == "cpu":
                # virtual devices share ncores, and the 1-device XLA CPU
                # arm is already multi-threaded: the headroom sharding
                # can claim is what the baseline left on the table
                attainable = min(float(n), max(1.0, ncores / base[1]))
            else:
                attainable = float(n)
            rec = {
                "devices": n,
                "compute_s": round(comp_s, 3),
                "compute_util_cores": round(util, 2),
                "compute_real_per_s": round(nreal / comp_s, 1),
                "per_device_real_per_s": round(nreal / comp_s / n, 1),
                "speedup": round(speedup, 3),
                "attainable_speedup": round(attainable, 3),
                "scaling_efficiency": round(speedup / attainable, 3),
                "full_step_s": round(full_s, 3),
                "full_step_real_per_s": round(nreal / full_s, 1),
                # the fused stage-graph arm (r17): same step, ONE graph
                "fused_full_step_s": round(fused_s, 3),
                "fused_full_step_real_per_s": round(nreal / fused_s, 1),
                "fused_step_speedup": round(fused_base_s / fused_s, 3),
                "fused_step_scaling_efficiency": round(
                    fused_base_s / fused_s / attainable, 3),
                "fused_matches_stacked": fused_matches,
                "io_write_exclusive_share": share,
                "shard_writer_occupancy": writers,
                "fused_verdict": verdict,
                "occupancy": occ,
            }
            arm_recs[str(n)] = rec

        top = arms[-1]
        dev = float(np.abs(last_out - first_out).max()) if (
            len(arms) > 1) else 0.0
        bit_identical = _bit_identity_check(key, npsr, ntoa, workdir, top)
        shapes = [(top // 2, 2), (top, 1)] if top >= 2 else [(1, 1)]
        fused_gates = _fused_identity_check(
            key, npsr, ntoa, workdir, shapes)
        head = arm_recs[str(top)]
        rec = {
            "bench": "multichip_scaling",
            # "host", not "platform": the provenance stamp spread below
            # owns the `platform` key (python/os/machine, BENCH-series
            # parity) and must not clobber the backend/core record
            "host": {"backend": platform, "cores": ncores,
                     "devices_visible": n_visible},
            "fast": fast,
            "workload": {
                "nreal": nreal, "chunk": chunk, "npsr": npsr,
                "ntoa": ntoa, "rn_nmodes": nmodes, "nrep": nrep,
                "reduce_fn": None, "durable_writes": True,
            },
            "arms": arm_recs,
            # headline (the top arm's device-compute number, gated
            # higher-better by bench-diff) + its attribution
            "scaling_efficiency": head["scaling_efficiency"],
            "per_device_real_per_s": head["per_device_real_per_s"],
            "bottleneck": (head["occupancy"] or {}).get("bottleneck"),
            # r17 headlines, top fused arm: the exclusive-shadow share
            # io_write holds on the critical path (lower-better; the
            # r06 stacked baseline pinned io_write at 83% busy) and the
            # mean concurrently-busy shard writers (higher-better)
            "io_write_exclusive_share": head["io_write_exclusive_share"],
            "shard_writer_occupancy": head["shard_writer_occupancy"],
            "fused_step_scaling_efficiency":
                head["fused_step_scaling_efficiency"],
            # sharded-checkpoint contract: byte-equal consolidated npz
            # vs the single-chip pipelined path (white-noise workload),
            # and the full workload's cross-topology float deviation
            "bit_identical": bit_identical,
            "fused_identity": fused_gates,
            "single_chip_max_abs_dev": dev,
            "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                       time.gmtime()),
            **provenance_stamp(EVIDENCE_SCHEMA_VERSION),
        }
        print(json.dumps(rec))
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    failures = []
    if not bit_identical:
        failures.append(
            "stacked mesh sweep not byte-identical to single-chip")
    for gate, ok in fused_gates.items():
        if not ok:
            failures.append(f"fused identity gate failed: {gate}")
    if not head["fused_matches_stacked"]:
        failures.append(
            "fused mesh arm's result cube differs from the stacked arm")
    writers = head["shard_writer_occupancy"]
    if writers is None or writers <= 1.0:
        failures.append(
            "shard writers did not overlap: shard_writer_occupancy "
            f"{writers} (need > 1.0 — parallel per-shard writes)"
        )
    # the io exclusive-share gate (< 0.50 vs r06's 83%-busy baseline)
    # is enforced on the fast/CI arm only: its write volume is sized so
    # the stage measures the overlap machinery, not raw disk bandwidth.
    # At flagship write volume (~0.5 GB durable) a single-disk host
    # saturates on bandwidth no writer fan-out can exceed — the full
    # artifact records that share honestly instead of gating on it
    # (same attainable-adjusted reasoning as the r06 scaling gate).
    share = head["io_write_exclusive_share"]
    if fast and (share is None or share >= 0.50):
        failures.append(
            f"io_write exclusive-shadow share {share} (need < 0.50 on "
            "the fast arm — write stage not overlapped by the graph)"
        )
    if failures:
        for reason in failures:
            print(f"multichip_scaling GATE FAIL: {reason}",
                  file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
