"""Multi-chip scaling bench for the flagship sweep: the full pipelined
step (sharded dispatch -> per-shard readback -> sharded checkpoints) at
1/2/4/8 devices, with device-compute scaling efficiency and occupancy
bottleneck attribution per arm.

Runnable TODAY on CPU (the point: every round records a number even
when the TPU tunnel is down): when no devices are forced yet, the
script sets ``XLA_FLAGS=--xla_force_host_platform_device_count=8``
itself before JAX initializes. On a real TPU slice the same script is
the MULTICHIP capture tool — no flags needed, the arms walk the real
chips.

What "scaling efficiency" means here, precisely:

* ``speedup`` per arm is wall-clock of the device-compute portion
  (chunked engine dispatches fenced once at the end — no readback, no
  disk) vs the single-device arm.
* ``attainable_speedup`` is the parallel headroom the host actually
  offers. On a real accelerator platform that is simply ``n_devices``.
  On the CPU host platform the "devices" are virtual and share the
  machine's cores, AND the single-device XLA CPU backend already runs
  multi-threaded — so the attainable speedup from sharding is
  ``min(n_devices, ncores / util_1)`` where ``util_1`` is the measured
  core-utilization of the single-device arm (process cpu-time / wall).
  A 2-core host whose baseline already burns 1.4 cores can at best go
  1.43x faster, no matter how many virtual devices exist; pretending
  the ideal is 8x would make the CPU number meaningless noise, and
  pretending it is 1x would hide real sharding overhead.
* ``scaling_efficiency = speedup / attainable_speedup`` — on TPU this
  reduces to the classic strong-scaling efficiency (target >= 0.75 =
  6x/8 devices, ROADMAP item 2); on CPU it isolates exactly what CAN
  be measured without real parallel silicon: how much wall the
  multi-chip machinery (per-device dispatch, shard assembly,
  collectives) costs relative to the headroom available. Both the raw
  and normalized numbers are in the JSON; nothing is hidden.

Bit-identity evidence (the sharded-checkpoint contract) is measured on
a white-noise workload — elementwise per (real, psr, toa), so XLA's
shape-dependent contraction lowering cannot reorder any float
reduction — where the 8-device sharded-checkpoint sweep must produce a
consolidated npz BYTE-equal to the single-chip pipelined sweep. The
full (red-noise) workload's cross-topology deviation is reported as
``single_chip_max_abs_dev`` (float reduction order in partitioned
contractions, the documented utils.sweep caveat — ~1e-20 in f64).

Occupancy: each full-step arm runs under the obs tracer and embeds the
``multichip_sweep``-windowed stage-occupancy analysis (obs.occupancy)
— per-stage duty, overlap efficiency, and the bottleneck verdict
("where does the gap go: H2D, readback, or write"), PR 6's attribution
machinery pointed at the multi-chip path.

Prints one JSON line. Knobs: MULTICHIP_NREAL (2048), MULTICHIP_CHUNK
(512), MULTICHIP_NPSR (8), MULTICHIP_NTOA (4096), MULTICHIP_NMODES
(100), MULTICHIP_DEVICES ("1,2,4,8"), MULTICHIP_NREP (3). The default
chunk is deliberately large: the multi-device execution overhead of
the virtual-CPU backend is a fixed per-dispatch cost (~0.15 s/chunk at
8 devices on the 2-core host), so small chunks measure dispatch amortization,
not the sharded pipeline.
"""
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# force the virtual multi-device CPU host BEFORE jax initializes, unless
# the caller already forced a device count (or runs on real chips)
if "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
) and os.environ.get("JAX_PLATFORMS", "cpu") == "cpu":
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from pta_replicator_tpu import obs  # noqa: E402
from pta_replicator_tpu.batch import synthetic_batch  # noqa: E402
from pta_replicator_tpu.models.batched import Recipe, realize  # noqa: E402
from pta_replicator_tpu.parallel.mesh import (  # noqa: E402
    make_mesh,
    sharded_realize,
    static_delays,
)
from pta_replicator_tpu.utils.provenance import (  # noqa: E402
    EVIDENCE_SCHEMA_VERSION,
    provenance_stamp,
)
from pta_replicator_tpu.utils.sweep import sweep  # noqa: E402


def _compute_arm(n_dev, key, batch, recipe, nreal, chunk, nrep):
    """Device-compute portion only: dispatch every chunk back-to-back
    through the (sharded) engine, fence once — no readback of the
    cubes, no disk. Returns (best wall_s, util_cores at best rep)."""
    mesh = make_mesh(n_dev, 1) if n_dev > 1 else None
    static = static_delays(batch, recipe, mesh=mesh)

    def run():
        outs = []
        for i in range(nreal // chunk):
            k = jax.random.fold_in(key, i)
            if mesh is not None:
                outs.append(sharded_realize(
                    k, batch, recipe, nreal=chunk, mesh=mesh, static=static
                ))
            else:
                outs.append(realize(k, batch, recipe, nreal=chunk,
                                    static=static))
        jax.block_until_ready(outs)

    run()  # warm: compile the engine for this mesh
    best = None
    for _ in range(nrep):
        c0, t0 = time.process_time(), time.perf_counter()
        run()
        wall = time.perf_counter() - t0
        util = (time.process_time() - c0) / wall
        if best is None or wall < best[0]:
            best = (wall, util)
    return best


def _full_step_arm(n_dev, key, batch, recipe, nreal, chunk, workdir):
    """The complete flagship step: pipelined sweep with full residual
    cubes, per-shard readback, and sharded checkpoints, under the obs
    tracer. Returns (wall_s, occupancy, result, consolidated sha or
    bytes path)."""
    mesh = make_mesh(n_dev, 1) if n_dev > 1 else None
    arm_dir = tempfile.mkdtemp(prefix=f"mc_d{n_dev}_", dir=workdir)
    ck = os.path.join(arm_dir, "sweep.npz")
    obs.reset_all()
    t0 = time.perf_counter()
    out = sweep(key, batch, recipe, nreal=nreal, chunk=chunk,
                checkpoint_path=ck, reduce_fn=None, mesh=mesh,
                pipeline_depth=2, durable=True)
    wall = time.perf_counter() - t0
    if obs.TRACER.dropped:
        occ = {"skipped": f"{obs.TRACER.dropped} span records dropped"}
    else:
        occ = obs.occupancy.analyze(obs.TRACER.events())
    shutil.rmtree(arm_dir, ignore_errors=True)
    return wall, occ, out


def _bit_identity_check(key, npsr, ntoa, workdir, n_dev):
    """White-noise workload (elementwise — no contraction for XLA to
    re-tile): single-chip pipelined sweep vs n_dev-device sharded-
    checkpoint sweep must agree BYTE-for-byte on the consolidated npz
    and exactly on the result."""
    batch = synthetic_batch(npsr=npsr, ntoa=ntoa, nbackend=2, seed=3)
    recipe = Recipe(
        efac=jnp.full((npsr, 2), 1.1, batch.toas_s.dtype),
        log10_equad=jnp.full((npsr, 2), -6.5, batch.toas_s.dtype),
    )
    d = tempfile.mkdtemp(prefix="mc_bitid_", dir=workdir)
    ck1 = os.path.join(d, "single.npz")
    ckn = os.path.join(d, "mesh.npz")
    # chunk >= 2 realizations per shard: a size-1 vmap rides a different
    # XLA fusion even for elementwise code (measured) — per-shard >= 2
    # keeps the lowering, and therefore the bytes, identical
    nreal, chunk = 8 * n_dev, 2 * n_dev
    ref = sweep(key, batch, recipe, nreal=nreal, chunk=chunk,
                checkpoint_path=ck1, reduce_fn=None, pipeline_depth=2)
    mesh = make_mesh(n_dev, 1)
    got = sweep(key, batch, recipe, nreal=nreal, chunk=chunk,
                checkpoint_path=ckn, reduce_fn=None, mesh=mesh,
                pipeline_depth=2)
    same_bytes = open(ck1, "rb").read() == open(ckn, "rb").read()
    same_values = bool(np.array_equal(ref, got))
    shutil.rmtree(d, ignore_errors=True)
    return same_bytes and same_values


def main():
    nreal = int(os.environ.get("MULTICHIP_NREAL", "2048"))
    chunk = int(os.environ.get("MULTICHIP_CHUNK", "512"))
    npsr = int(os.environ.get("MULTICHIP_NPSR", "8"))
    ntoa = int(os.environ.get("MULTICHIP_NTOA", "4096"))
    nmodes = int(os.environ.get("MULTICHIP_NMODES", "100"))
    nrep = int(os.environ.get("MULTICHIP_NREP", "3"))
    arms = [int(x) for x in os.environ.get(
        "MULTICHIP_DEVICES", "1,2,4,8").split(",")]

    platform = jax.default_backend()
    n_visible = jax.device_count()
    ncores = os.cpu_count() or 1
    arms = [n for n in arms if n <= n_visible]

    batch = synthetic_batch(npsr=npsr, ntoa=ntoa, seed=0)
    recipe = Recipe(
        efac=jnp.ones(npsr, batch.toas_s.dtype),
        rn_log10_amplitude=jnp.full(npsr, -14.0, batch.toas_s.dtype),
        rn_gamma=jnp.full(npsr, 4.0, batch.toas_s.dtype),
        rn_nmodes=nmodes,
    )
    key = jax.random.PRNGKey(7)
    workdir = tempfile.mkdtemp(prefix="multichip_scaling_")
    try:
        arm_recs = {}
        # only the first arm's cube is needed later (cross-topology
        # deviation vs the top arm) — retaining every arm's full result
        # cube would hold len(arms) copies of the workload in host RAM
        first_out = None
        last_out = None
        base = None
        for n in arms:
            comp_s, util = _compute_arm(
                n, key, batch, recipe, nreal, chunk, nrep)
            full_s, occ, out = _full_step_arm(
                n, key, batch, recipe, nreal, chunk, workdir)
            if first_out is None:
                first_out = out
            last_out = out
            if base is None:
                base = (comp_s, util)
            speedup = base[0] / comp_s
            if platform == "cpu":
                # virtual devices share ncores, and the 1-device XLA CPU
                # arm is already multi-threaded: the headroom sharding
                # can claim is what the baseline left on the table
                attainable = min(float(n), max(1.0, ncores / base[1]))
            else:
                attainable = float(n)
            rec = {
                "devices": n,
                "compute_s": round(comp_s, 3),
                "compute_util_cores": round(util, 2),
                "compute_real_per_s": round(nreal / comp_s, 1),
                "per_device_real_per_s": round(nreal / comp_s / n, 1),
                "speedup": round(speedup, 3),
                "attainable_speedup": round(attainable, 3),
                "scaling_efficiency": round(speedup / attainable, 3),
                "full_step_s": round(full_s, 3),
                "full_step_real_per_s": round(nreal / full_s, 1),
                "occupancy": occ,
            }
            arm_recs[str(n)] = rec

        top = arms[-1]
        dev = float(np.abs(last_out - first_out).max()) if (
            len(arms) > 1) else 0.0
        bit_identical = _bit_identity_check(key, npsr, ntoa, workdir, top)
        head = arm_recs[str(top)]
        rec = {
            "bench": "multichip_scaling",
            # "host", not "platform": the provenance stamp spread below
            # owns the `platform` key (python/os/machine, BENCH-series
            # parity) and must not clobber the backend/core record
            "host": {"backend": platform, "cores": ncores,
                     "devices_visible": n_visible},
            "workload": {
                "nreal": nreal, "chunk": chunk, "npsr": npsr,
                "ntoa": ntoa, "rn_nmodes": nmodes, "nrep": nrep,
                "reduce_fn": None, "durable_writes": True,
            },
            "arms": arm_recs,
            # headline (the top arm's device-compute number, gated
            # higher-better by bench-diff) + its attribution
            "scaling_efficiency": head["scaling_efficiency"],
            "per_device_real_per_s": head["per_device_real_per_s"],
            "bottleneck": (head["occupancy"] or {}).get("bottleneck"),
            # sharded-checkpoint contract: byte-equal consolidated npz
            # vs the single-chip pipelined path (white-noise workload),
            # and the full workload's cross-topology float deviation
            "bit_identical": bit_identical,
            "single_chip_max_abs_dev": dev,
            "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                       time.gmtime()),
            **provenance_stamp(EVIDENCE_SCHEMA_VERSION),
        }
        print(json.dumps(rec))
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    main()
