"""Numerics-observatory evidence bench: probes proven bitwise-neutral,
cheap, and pointing at the producing site.

The observatory (obs/numerics.py, docs/numerics.md) is only worth
committing if four claims hold MEASURABLY:

* **identity arm** — the flagship-shaped sweep cube is sha256-identical
  across (A) disarmed, (B) armed, and (C) disarmed-after-an-arm/disarm-
  cycle. Disarmed probes literally ``return x`` before touching jax
  (A == C is the "imports cost nothing" gate); armed probes are
  identity on the data path (B == A — the reductions ride beside the
  graph, never in it).
* **overhead arm** — the probe machinery (the EXACT subgraph the armed
  engine adds: per-realization slab stats under vmap, reduced into the
  donated stats buffer) microbenched standalone, scaled by the site
  count one flagship realize step ACTUALLY arms (read back from the
  ledger, not assumed), against the measured step wall. Gate: < 1%
  (``NP_OVERHEAD_GATE``, enforced on the committed non-fast run).
  Measured this way — rather than gating on a whole-step wall-clock
  A/B — because ~100 us of machinery against a ~100 ms step makes the
  A/B mostly scheduler noise (the TRACE_r14 lesson); the end-to-end
  armed-vs-disarmed delta is still reported as an informational
  cross-check.
* **planted-overflow arm** — ``log10_equad=25`` overflows the f32
  white-noise variance to inf (an efac blowup alone does NOT plant:
  XLA simplifies ``sqrt((efac*err)**2)`` to ``|efac*err|`` and the
  overflowing intermediate never materializes; the ``var + equad**2``
  sum defeats the rewrite); the ledger must name ``realization.white``
  (the producing probe site) and no other in-graph site.
* **planted-NaN arm** — a ``drain:nan@chunk=1`` fault poisons one
  element of the in-flight chunk AFTER device compute; only the drain
  seam's host scan can see it, so the ledger must name ``drain`` and
  no in-graph site (the last-line-of-defense claim).
* **drift arm** — with 1-in-1 sampling, every chunk's realization 0
  replays through the f64 shadow oracle; each sampled family's worst
  relative drift must sit within the fuzzer's family tolerance
  (``scenarios.fuzz.FAMILY_TOLERANCES`` — the same bar the fuzz gate
  holds).

Prints one JSON line (committed as ``NUMERICS_r18_cpu.json``); exit 1
on any gate miss, with the reasons on stderr (stdout is routinely
/dev/null'd in CI — the PR 12/13 lesson).

Usage: python benchmarks/numerics_probe.py [--fast] [--out PATH]
  env: NP_NPSR / NP_NTOA / NP_NREAL / NP_CHUNK / NP_STEP_NPSR /
       NP_STEP_NTOA / NP_STEP_CHUNK reshape the workload (--fast
       presets a seconds-scale CI arm).
"""
import hashlib
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from pta_replicator_tpu.batch import synthetic_batch  # noqa: E402
from pta_replicator_tpu.faults import inject  # noqa: E402
from pta_replicator_tpu.models.batched import Recipe, realize  # noqa: E402
from pta_replicator_tpu.obs import numerics  # noqa: E402
from pta_replicator_tpu.scenarios.fuzz import FAMILY_TOLERANCES  # noqa: E402
from pta_replicator_tpu.utils.provenance import (  # noqa: E402
    EVIDENCE_SCHEMA_VERSION,
    provenance_stamp,
)
from pta_replicator_tpu.utils.sweep import sweep  # noqa: E402

#: probe-overhead gate: the observatory must cost < 1% of the flagship
#: CPU step when armed
NP_OVERHEAD_GATE = 0.01

#: a drift_every large enough that no bench chunk index ever samples —
#: arms the probes without the shadow-oracle replay
NO_DRIFT = 1_000_000_000


def _cube_sha(cube: np.ndarray) -> str:
    arr = np.ascontiguousarray(np.asarray(cube))
    h = hashlib.sha256()
    h.update(arr.dtype.str.encode())
    h.update(str(arr.shape).encode())
    h.update(arr.tobytes())
    return h.hexdigest()


def _flagship_recipe(npsr: int) -> Recipe:
    return Recipe(
        efac=jnp.ones(npsr),
        rn_log10_amplitude=jnp.full(npsr, -13.5),
        rn_gamma=jnp.full(npsr, 4.0),
    )


def _run_sweep(tag, key, batch, recipe, nreal, chunk):
    d = tempfile.mkdtemp(prefix=f"numerics_probe_{tag}_")
    return sweep(
        key, batch, recipe, nreal=nreal, chunk=chunk,
        checkpoint_path=os.path.join(d, "sweep.npz"), reduce_fn=None,
    )


def run_identity_arm(nreal, chunk, npsr, ntoa, failures):
    """A (disarmed) == B (armed) == C (disarmed after an arm/disarm
    cycle), by sha256 over the sweep cube's bytes."""
    batch = synthetic_batch(npsr=npsr, ntoa=ntoa, seed=11)
    recipe = _flagship_recipe(npsr)
    key = jax.random.PRNGKey(3)

    numerics.reset()
    sha_disarmed = _cube_sha(
        _run_sweep("disarmed", key, batch, recipe, nreal, chunk)
    )
    numerics.arm(drift_every=NO_DRIFT)
    sha_armed = _cube_sha(
        _run_sweep("armed", key, batch, recipe, nreal, chunk)
    )
    armed_sites = sorted(numerics.snapshot()["sites"])
    numerics.disarm()
    sha_cycled = _cube_sha(
        _run_sweep("cycled", key, batch, recipe, nreal, chunk)
    )
    numerics.reset()
    if sha_cycled != sha_disarmed:
        failures.append(
            "identity: disarmed cube changed after an arm/disarm cycle "
            f"({sha_disarmed[:12]} -> {sha_cycled[:12]}) — the disarmed "
            "graph is not bitwise the unprobed graph"
        )
    if sha_armed != sha_disarmed:
        failures.append(
            "identity: ARMED cube differs from disarmed "
            f"({sha_disarmed[:12]} vs {sha_armed[:12]}) — probes are "
            "not identity on the data path"
        )
    if not armed_sites:
        failures.append(
            "identity: the armed sweep recorded no probe sites — the "
            "probes compiled out of the armed graph"
        )
    return {
        "sha_disarmed": sha_disarmed,
        "sha_armed": sha_armed,
        "sha_disarmed_after_cycle": sha_cycled,
        "armed_probe_sites": armed_sites,
    }


def run_overhead_arm(step_npsr, step_ntoa, step_chunk, fast, failures):
    """TRACE_r14 method: the probe machinery — the exact subgraph the
    armed engine adds (per-realization slab stats under vmap, reduced
    into the donated stats buffer) — microbenched standalone, times the
    site count one flagship step actually arms (from the ledger), over
    the measured step wall. The armed-vs-disarmed whole-step A/B rides
    along informationally; it is NOT the gate because scheduler noise
    at the ~100 ms scale dwarfs ~100 us of machinery."""
    batch = synthetic_batch(npsr=step_npsr, ntoa=step_ntoa, seed=5)
    recipe = _flagship_recipe(step_npsr)
    key = jax.random.PRNGKey(2)

    def step_wall_median(reps=9):
        np.asarray(realize(key, batch, recipe, nreal=step_chunk))
        ws = []
        for rep in range(reps):
            t0 = time.perf_counter()
            np.asarray(realize(jax.random.fold_in(key, rep), batch,
                               recipe, nreal=step_chunk))
            ws.append(time.perf_counter() - t0)
        return float(np.median(ws))

    # the disarmed step wall: the denominator of the <1% claim
    numerics.reset()
    step_wall = step_wall_median()

    # sites one armed step arms, read back from the ledger not assumed
    numerics.arm(drift_every=NO_DRIFT)
    np.asarray(realize(key, batch, recipe, nreal=step_chunk))
    numerics.flush()
    snap = numerics.snapshot()
    sites_per_step = len(snap["sites"])
    scanned = {
        s: rec["elements"] // max(1, rec["calls"])
        for s, rec in snap["sites"].items()
    }
    if sites_per_step < 1:
        failures.append(
            "overhead: the armed realize step fired no probes — "
            "nothing to measure"
        )
    armed_wall = step_wall_median()
    numerics.reset()

    # machinery microbench: one site's collector subgraph, standalone.
    # Feeding a MATERIALIZED operand is conservative — in the engine the
    # slab is recomputed from still-fused values, never re-read from
    # memory.
    x = jax.random.normal(
        key, (step_chunk, step_npsr, step_ntoa), jnp.float32
    )

    def machinery(v):
        col = numerics.Collector()

        def one(row):
            col.add("bench.overhead_site", row)
            return col.take()

        return numerics.reduce_stats(jax.vmap(one)(v))

    m = jax.jit(machinery)
    fetch = lambda tree: jax.tree_util.tree_map(np.asarray, tree)  # noqa: E731
    fetch(m(x))
    ws = []
    for _ in range(50):
        t0 = time.perf_counter()
        fetch(m(x))
        ws.append(time.perf_counter() - t0)
    machinery_s = float(np.median(ws))

    overhead_s = machinery_s * sites_per_step
    fraction = overhead_s / step_wall if step_wall > 0 else 0.0
    if fraction >= NP_OVERHEAD_GATE and not fast:
        failures.append(
            f"overhead: probes cost {100 * fraction:.3f}% of the step "
            f"({overhead_s * 1e6:.2f} us vs {step_wall:.3f} s) — gate "
            f"{100 * NP_OVERHEAD_GATE:g}%"
        )
    delta = max(0.0, armed_wall - step_wall)
    return {
        "machinery_s_per_site": round(machinery_s, 9),
        "sites_per_step": sites_per_step,
        "scanned_elements_per_site": scanned,
        "step_wall_s": round(step_wall, 4),
        "step_shape": f"{step_npsr}x{step_ntoa}x{step_chunk}",
        "overhead_fraction": round(fraction, 8),
        "overhead_gate": NP_OVERHEAD_GATE,
        "gate_enforced": not fast,
        "end_to_end_informational": {
            "armed_wall_s": round(armed_wall, 4),
            "delta_s": round(delta, 5),
            "fraction": round(
                delta / step_wall if step_wall > 0 else 0.0, 6
            ),
        },
    }


def run_overflow_arm(npsr, ntoa, failures):
    """log10_equad=25 overflows the f32 white-noise variance (the
    ``var + equad**2`` sum — unlike an efac blowup — survives XLA's
    ``sqrt(x**2) -> |x|`` rewrite): the ledger must name
    realization.white — the PRODUCING probe site — and no other
    in-graph site."""
    batch = synthetic_batch(npsr=npsr, ntoa=ntoa, seed=7)
    recipe = Recipe(efac=jnp.ones(npsr), log10_equad=jnp.full(npsr, 25.0))
    numerics.reset()
    numerics.arm(drift_every=NO_DRIFT)
    np.asarray(realize(jax.random.PRNGKey(9), batch, recipe, nreal=4))
    numerics.flush()
    snap = numerics.snapshot()
    numerics.reset()
    dirty = sorted(
        site for site, rec in snap["sites"].items() if rec["nonfinite"]
    )
    white = snap["sites"].get("realization.white")
    if white is None or not white["nonfinite"]:
        failures.append(
            "overflow: the planted f32 overflow was NOT caught at "
            f"realization.white (non-finite sites: {dirty})"
        )
    elif dirty != ["realization.white"]:
        failures.append(
            "overflow: non-finites attributed beyond the producing "
            f"site: {dirty}"
        )
    if white is not None and not white["episodes"]:
        failures.append(
            "overflow: no non-finite episode opened at "
            "realization.white"
        )
    return {
        "nonfinite_sites": dirty,
        "nonfinite_count": white["nonfinite"] if white else 0,
        "episodes": white["episodes"] if white else 0,
    }


def run_nan_arm(nreal, chunk, npsr, ntoa, failures):
    """A drain:nan fault poisons one element AFTER device compute —
    only the drain seam's host scan can see it, so the ledger must
    name ``drain`` and no in-graph probe site."""
    batch = synthetic_batch(npsr=npsr, ntoa=ntoa, seed=13)
    recipe = _flagship_recipe(npsr)
    numerics.reset()
    numerics.arm(drift_every=NO_DRIFT)
    with inject.armed(f"{inject.SITE_DRAIN}:nan@chunk=1", seed=5):
        cube = np.asarray(_run_sweep(
            "nan", jax.random.PRNGKey(17), batch, recipe, nreal, chunk
        ))
    numerics.flush()
    snap = numerics.snapshot()
    numerics.reset()
    dirty = sorted(
        site for site, rec in snap["sites"].items() if rec["nonfinite"]
    )
    drain = snap["sites"].get("drain")
    planted = int(np.sum(~np.isfinite(cube)))
    if not planted:
        failures.append(
            "nan: the drain:nan fault left no non-finite in the cube — "
            "the poison never reached the data"
        )
    if drain is None or not drain["nonfinite"]:
        failures.append(
            "nan: the poisoned chunk was NOT caught at the drain scan "
            f"(non-finite sites: {dirty})"
        )
    elif dirty != ["drain"]:
        failures.append(
            "nan: a post-device poison showed up at in-graph sites "
            f"{dirty} — attribution is wrong"
        )
    return {
        "nonfinite_sites": dirty,
        "planted_elements": planted,
        "drain_nonfinite": drain["nonfinite"] if drain else 0,
    }


def run_drift_arm(nreal, chunk, npsr, ntoa, failures):
    """1-in-1 sampling: every chunk replays realization 0 through the
    f64 shadow oracle; each family's worst drift must sit within the
    fuzzer's tolerance."""
    batch = synthetic_batch(npsr=npsr, ntoa=ntoa, seed=19)
    recipe = _flagship_recipe(npsr)
    numerics.reset()
    numerics.arm(drift_every=1)
    _run_sweep("drift", jax.random.PRNGKey(23), batch, recipe, nreal,
               chunk)
    numerics.flush()
    snap = numerics.snapshot()
    numerics.reset()
    drift = snap["drift"]
    if not drift:
        failures.append(
            "drift: 1-in-1 sampling recorded no drift families — the "
            "drain seam never reached the shadow oracle"
        )
    for family in ("white", "red"):
        if family not in drift:
            failures.append(f"drift: family {family!r} never sampled")
    for family, rec in drift.items():
        tol = rec.get("tolerance") or FAMILY_TOLERANCES.get(family)
        if not rec["samples"]:
            failures.append(f"drift: family {family!r} has no samples")
        if tol is not None and rec["worst"] > tol:
            failures.append(
                f"drift: family {family!r} drifted {rec['worst']:.3g} "
                f"> tolerance {tol:g} vs the f64 oracle"
            )
    return {
        family: {
            "worst": rec["worst"], "samples": rec["samples"],
            "tolerance": rec["tolerance"],
        }
        for family, rec in sorted(drift.items())
    }


def main() -> int:
    fast = "--fast" in sys.argv[1:]
    out_path = None
    if "--out" in sys.argv[1:]:
        out_path = sys.argv[sys.argv.index("--out") + 1]
    npsr = int(os.environ.get("NP_NPSR", "4"))
    ntoa = int(os.environ.get("NP_NTOA", "96" if fast else "256"))
    nreal = int(os.environ.get("NP_NREAL", "8" if fast else "32"))
    chunk = int(os.environ.get("NP_CHUNK", "4" if fast else "8"))
    step_npsr = int(os.environ.get("NP_STEP_NPSR", "4" if fast else "8"))
    step_ntoa = int(os.environ.get("NP_STEP_NTOA",
                                   "512" if fast else "4096"))
    step_chunk = int(os.environ.get("NP_STEP_CHUNK",
                                    "16" if fast else "64"))

    failures = []
    identity = run_identity_arm(nreal, chunk, npsr, ntoa, failures)
    overflow = run_overflow_arm(npsr, ntoa, failures)
    nan = run_nan_arm(nreal, chunk, npsr, ntoa, failures)
    drift = run_drift_arm(nreal, chunk, npsr, ntoa, failures)
    overhead = run_overhead_arm(step_npsr, step_ntoa, step_chunk, fast,
                                failures)
    numerics.reset()

    rec = {
        "bench": "numerics_probe",
        "backend": jax.default_backend(),
        "fast": fast,
        "identity": identity,
        "overflow": overflow,
        "nan": nan,
        "drift": drift,
        "overhead": overhead,
        "ok": not failures,
        "failures": failures,
        **provenance_stamp(
            EVIDENCE_SCHEMA_VERSION,
            repo_root=os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            ),
        ),
    }
    payload = json.dumps(rec)
    print(payload)
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(payload + "\n")
    for reason in failures:
        # stdout is routinely /dev/null'd in CI: gate-miss reasons
        # must reach stderr
        print(f"numerics_probe GATE MISS: {reason}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
