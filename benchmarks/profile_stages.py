"""Per-stage device timings for the NG15-scale benchmark workload.

Times each injection op (and the end-to-end chunk) separately on the
current backend, syncing by host readback of a small reduction (on the
tunneled TPU backend ``block_until_ready`` returns at dispatch — see
bench.py). Prints one JSON line per stage to stdout.

Usage:  python benchmarks/profile_stages.py [--nreal 20] [--small]
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nreal", type=int, default=20)
    ap.add_argument("--small", action="store_true",
                    help="3x122 toy shapes instead of NG15 scale")
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. 'cpu'); default: "
                         "whatever backend the session resolves")
    args = ap.parse_args()

    import jax

    # opt-in platform override (e.g. --platform cpu for a local run).
    # Deliberately NOT read from JAX_PLATFORMS: hosted environments
    # preset that to a remote plugin, and forwarding it can hang on an
    # unreachable device (.claude/skills/verify gotchas).
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import jax.numpy as jnp

    from pta_replicator_tpu.batch import synthetic_batch
    from pta_replicator_tpu.models import batched as B
    from pta_replicator_tpu.ops.orf import hellings_downs_matrix

    if args.small:
        npsr, ntoa, nbackend, ncw = 3, 122, 2, 16
        npts, howml = 120, 4.0
    else:
        npsr, ntoa, nbackend, ncw = 68, 7758, 4, 100
        npts, howml = 600, 10.0

    batch = synthetic_batch(npsr=npsr, ntoa=ntoa, nbackend=nbackend, seed=0)
    rng = np.random.default_rng(0)
    phat = np.asarray(batch.phat, dtype=np.float64)
    locs = np.stack(
        [np.arctan2(phat[:, 1], phat[:, 0]),
         np.arccos(np.clip(phat[:, 2], -1, 1))], axis=1,
    )
    M = jnp.asarray(np.linalg.cholesky(hellings_downs_matrix(locs)))
    from bench import random_cw_catalog

    cat = jnp.asarray(random_cw_catalog(rng, ncw))
    recipe = B.Recipe(
        efac=jnp.asarray(1.1),
        log10_equad=jnp.asarray(-6.5),
        log10_ecorr=jnp.asarray(-6.5),
        rn_log10_amplitude=jnp.asarray(-14.0),
        rn_gamma=jnp.asarray(4.33),
        gwb_log10_amplitude=jnp.asarray(-14.0),
        gwb_gamma=jnp.asarray(4.33),
        orf_cholesky=M,
        cgw_params=cat,
        gwb_npts=npts,
        gwb_howml=howml,
        cgw_chunk=ncw,
    )

    R = args.nreal
    keys = jax.random.split(jax.random.PRNGKey(0), R)

    # one stage table shared with bench.py's per-stage evidence
    from pta_replicator_tpu.utils.profiling import injection_stage_fns

    stages = injection_stage_fns(batch, recipe)

    def run(f):
        t0 = time.perf_counter()
        out = f(keys)
        float(jnp.sum(jnp.abs(out)))  # readback fence
        return time.perf_counter() - t0

    for name, f in stages.items():
        t_compile = run(f)
        t_run = min(run(f) for _ in range(3))
        per_real = t_run / (1 if name.endswith("_once") else R)
        print(json.dumps({
            "stage": name,
            "compile_plus_run_s": round(t_compile, 3),
            "run_s": round(t_run, 4),
            "per_realization_ms": round(1e3 * per_real, 3),
        }), flush=True)


if __name__ == "__main__":
    main()
