#!/bin/bash
# Tunnel-recovery watcher, consolidated from the per-round
# recovery_watch_r0*.sh copies: probe the TPU tunnel on a fixed cadence;
# on recovery run the hardware-evidence battery in priority order,
# writing self-timestamped JSONs into the repo root (a mid-battery
# tunnel drop still leaves the highest-priority artifacts).
#
# Observability (the reason the per-round copies could be retired): the
# bench runs under BENCH_TELEMETRY, so its flight recorder heartbeats
# into $TDIR/progress.json — a background
#   python -m pta_replicator_tpu watch $TDIR
# tails that into the log (one line per heartbeat change: current
# section, compile counters, stall warnings), and a killed/wedged bench
# leaves $TDIR/postmortem.json (`python -m pta_replicator_tpu
# postmortem $TDIR`). After the bench step, the bench-trajectory gate
# diffs the fresh preview against the last promoted BENCH_r*.json.
#
# Usage: recovery_watch.sh [ROUND] [TRIES] [SLEEP_S]
#   ROUND    artifact-name label              (default: r06)
#   TRIES    probe attempts before giving up  (default: 230)
#   SLEEP_S  seconds between probes           (default: 180)
# Env:
#   RW_STEPS  space-separated subset/order of:
#             bench gls validate ablation vpu cw6 sweep cw7
#             (default: all, in that order)
set -u
ROUND=${1:-r06}
TRIES=${2:-230}
SLEEP_S=${3:-180}
STEPS=${RW_STEPS:-"bench gls validate ablation vpu cw6 sweep cw7"}
LOG=/tmp/recovery_log_${ROUND}.txt
TDIR=/tmp/recovery_telemetry_${ROUND}

cd /root/repo
log() { date -u +"%H:%M:%SZ $*" >> "$LOG"; }

WATCH_PID=
start_watch() {
  # supervised heartbeat tail, armed only while the (captured) bench
  # step runs: `watch` exits whenever a run finishes or leaves a
  # postmortem — the bench driver's OOM retry ladder does both — so a
  # restart loop keeps tailing across retries; each retry's
  # start_capture clears the stale artifacts the previous child left
  ( while :; do
      python -m pta_replicator_tpu watch "$TDIR" --interval 30 \
        >> "$LOG" 2>/dev/null
      sleep 10
    done ) &
  WATCH_PID=$!
}
stop_watch() {
  if [ -n "$WATCH_PID" ]; then
    pkill -P "$WATCH_PID" 2>/dev/null
    kill "$WATCH_PID" 2>/dev/null
    WATCH_PID=
  fi
}

run_step() {  # run_step <step-name>
  case "$1" in
    bench)    t=1600; out=BENCH_PREVIEW_${ROUND}.json
              cmd=(env BENCH_TELEMETRY="$TDIR" python bench.py) ;;
    gls)      t=1600; out=BENCH_GLS_${ROUND}.json
              cmd=(env BENCH_FIT=gls python bench.py) ;;
    validate) t=900;  out=VALIDATE_DEVICE_${ROUND}.json
              cmd=(python benchmarks/validate_device.py 2000) ;;
    ablation) t=900;  out=ABLATION_${ROUND}.json
              cmd=(python benchmarks/fused_ablation.py 800 5) ;;
    vpu)      t=600;  out=VPU_CEILING_${ROUND}.json
              cmd=(python benchmarks/vpu_ceiling.py) ;;
    cw6)      t=2400; out=CW_SCALING_${ROUND}.json
              cmd=(python benchmarks/cw_scaling.py 6 both) ;;
    sweep)    t=3000; out=SWEEP_RESUME_${ROUND}.json
              cmd=(python benchmarks/sweep_kill_resume.py 1000000 800) ;;
    cw7)      t=3000; out=CW_SCALING_1E7_${ROUND}.json
              cmd=(python benchmarks/cw_scaling.py 7 both) ;;
    *)        log "unknown step '$1' skipped"; return ;;
  esac
  [ "$1" = bench ] && start_watch
  timeout "$t" "${cmd[@]}" > "/root/repo/$out" 2>"/tmp/${1}_${ROUND}.err"
  step_rc=$?
  [ "$1" = bench ] && stop_watch
  log "$1 done rc=$step_rc -> $out"
  if [ "$1" = bench ]; then
    # bench-trajectory gate: fresh preview vs the last promoted round
    # (BENCH_r*.json, not r0*: the glob must keep matching past r09)
    last=$(ls /root/repo/BENCH_r[0-9]*.json 2>/dev/null | tail -1)
    if [ -n "$last" ]; then
      python -m pta_replicator_tpu bench-diff "$last" \
        "/root/repo/$out" --threshold 0.10 >> "$LOG" 2>&1
      diff_rc=$?  # captured before any substitution can clobber $?
      log "bench-diff vs $(basename "$last") rc=$diff_rc"
    fi
  fi
}

for i in $(seq 1 "$TRIES"); do
  if timeout 90 python -c "
import jax, jax.numpy as jnp, numpy as np
float(np.asarray(jnp.ones((128,128)) @ jnp.ones((128,128))).sum())
" >/dev/null 2>&1; then
    log "tunnel up, starting $ROUND battery (steps: $STEPS)"
    mkdir -p "$TDIR"
    # a previous same-ROUND run's final heartbeat/postmortem would make
    # the watcher exit before the new bench even starts capturing
    rm -f "$TDIR/progress.json" "$TDIR/postmortem.json"
    for step in $STEPS; do
      run_step "$step"
    done
    stop_watch
    log "battery complete"
    exit 0
  fi
  sleep "$SLEEP_S"
done
log "gave up waiting"
