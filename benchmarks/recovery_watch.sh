#!/bin/bash
cd /root/repo
for i in $(seq 1 200); do
  if timeout 90 python -c "
import jax, jax.numpy as jnp, numpy as np
float(np.asarray(jnp.ones((128,128)) @ jnp.ones((128,128))).sum())
" >/dev/null 2>&1; then
    date -u +"%H:%M:%SZ tunnel up, starting battery" >> /tmp/recovery_log.txt
    timeout 1600 python bench.py > /root/repo/BENCH_RECOVERY_r03.json 2>/tmp/bench_recovery.err
    date -u +"%H:%M:%SZ bench done rc=$?" >> /tmp/recovery_log.txt
    timeout 900 python benchmarks/validate_device.py 2000 > /root/repo/VALIDATE_DEVICE_r03.json 2>/tmp/validate_recovery.err
    date -u +"%H:%M:%SZ validate done rc=$?" >> /tmp/recovery_log.txt
    timeout 900 python benchmarks/fused_ablation.py 800 5 > /root/repo/ABLATION_r03.json 2>/tmp/ablation_recovery.err
    date -u +"%H:%M:%SZ ablation done rc=$?" >> /tmp/recovery_log.txt
    timeout 1200 python benchmarks/cw_scaling.py 5 both > /root/repo/CW_SCALING_r03.json 2>/tmp/cwscale_recovery.err
    date -u +"%H:%M:%SZ cw_scaling done rc=$?" >> /tmp/recovery_log.txt
    exit 0
  fi
  sleep 180
done
date -u +"%H:%M:%SZ gave up waiting" >> /tmp/recovery_log.txt
