#!/bin/bash
# Round-4 tunnel-recovery watcher: probe every 3 minutes; on recovery run
# the full hardware-evidence battery (VERDICT r3 item 1) and write
# self-timestamped JSONs into the repo root. Safe to re-run; each tool
# stamps device kind + UTC time into its output.
cd /root/repo
for i in $(seq 1 220); do
  if timeout 90 python -c "
import jax, jax.numpy as jnp, numpy as np
float(np.asarray(jnp.ones((128,128)) @ jnp.ones((128,128))).sum())
" >/dev/null 2>&1; then
    date -u +"%H:%M:%SZ tunnel up, starting r04 battery" >> /tmp/recovery_log_r04.txt
    timeout 1600 python bench.py > /root/repo/BENCH_PREVIEW_r04.json 2>/tmp/bench_r04.err
    date -u +"%H:%M:%SZ bench done rc=$?" >> /tmp/recovery_log_r04.txt
    timeout 900 python benchmarks/validate_device.py 2000 > /root/repo/VALIDATE_DEVICE_r04.json 2>/tmp/validate_r04.err
    date -u +"%H:%M:%SZ validate done rc=$?" >> /tmp/recovery_log_r04.txt
    timeout 900 python benchmarks/fused_ablation.py 800 5 > /root/repo/ABLATION_r04.json 2>/tmp/ablation_r04.err
    date -u +"%H:%M:%SZ ablation done rc=$?" >> /tmp/recovery_log_r04.txt
    timeout 600 python benchmarks/vpu_ceiling.py > /root/repo/VPU_CEILING_r04.json 2>/tmp/vpu_r04.err
    date -u +"%H:%M:%SZ vpu_ceiling done rc=$?" >> /tmp/recovery_log_r04.txt
    timeout 2400 python benchmarks/cw_scaling.py 6 both > /root/repo/CW_SCALING_r04.json 2>/tmp/cwscale_r04.err
    date -u +"%H:%M:%SZ cw_scaling done rc=$?" >> /tmp/recovery_log_r04.txt
    exit 0
  fi
  sleep 180
done
date -u +"%H:%M:%SZ gave up waiting" >> /tmp/recovery_log_r04.txt
