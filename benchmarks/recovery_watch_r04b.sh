#!/bin/bash
# Stage-2 watcher: once the main r04 battery has produced its last
# artifact (CW_SCALING_r04.json), run the large kill/resume sweep
# rehearsal on the chip (VERDICT r3 item 6). Separate from
# recovery_watch_r04.sh so editing this never perturbs the running
# stage-1 script.
cd /root/repo
for i in $(seq 1 400); do
  if [ -s /root/repo/CW_SCALING_r04.json ]; then
    date -u +"%H:%M:%SZ battery artifacts present, starting sweep rehearsal" >> /tmp/recovery_log_r04.txt
    timeout 3000 python benchmarks/sweep_kill_resume.py 1000000 800 > /root/repo/SWEEP_RESUME_r04.json 2>/tmp/sweep_r04.err
    date -u +"%H:%M:%SZ sweep rehearsal done rc=$?" >> /tmp/recovery_log_r04.txt
    exit 0
  fi
  sleep 120
done
