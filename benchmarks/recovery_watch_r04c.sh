#!/bin/bash
# Stage-3 watcher: after the sweep rehearsal artifact exists, push the
# CW source ladder to the reference's full operating regime (1e7
# sources, deterministic.py:258-264) — single rung, both backends.
cd /root/repo
for i in $(seq 1 500); do
  if [ -s /root/repo/SWEEP_RESUME_r04.json ]; then
    date -u +"%H:%M:%SZ starting 1e7-source CW rung" >> /tmp/recovery_log_r04.txt
    timeout 3000 python benchmarks/cw_scaling.py 7 both > /root/repo/CW_SCALING_1E7_r04.json 2>/tmp/cw7_r04.err
    date -u +"%H:%M:%SZ 1e7 rung done rc=$?" >> /tmp/recovery_log_r04.txt
    exit 0
  fi
  sleep 120
done
