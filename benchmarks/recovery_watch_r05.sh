#!/bin/bash
# Round-5 tunnel-recovery watcher (VERDICT r4 item 1): probe every 3
# minutes for the full round; on recovery run the complete hardware
# evidence battery in priority order, writing self-timestamped JSONs to
# the repo root. One script (no staged watchers this round) so a
# mid-battery tunnel drop still leaves the highest-priority artifacts.
cd /root/repo
for i in $(seq 1 230); do
  if timeout 90 python -c "
import jax, jax.numpy as jnp, numpy as np
float(np.asarray(jnp.ones((128,128)) @ jnp.ones((128,128))).sum())
" >/dev/null 2>&1; then
    log() { date -u +"%H:%M:%SZ $*" >> /tmp/recovery_log_r05.txt; }
    log "tunnel up, starting r05 battery"
    timeout 1600 python bench.py > /root/repo/BENCH_PREVIEW_r05.json 2>/tmp/bench_r05.err
    log "bench done rc=$?"
    BENCH_FIT=gls timeout 1600 python bench.py > /root/repo/BENCH_GLS_r05.json 2>/tmp/bench_gls_r05.err
    log "bench gls done rc=$?"
    timeout 900 python benchmarks/validate_device.py 2000 > /root/repo/VALIDATE_DEVICE_r05.json 2>/tmp/validate_r05.err
    log "validate done rc=$?"
    timeout 900 python benchmarks/fused_ablation.py 800 5 > /root/repo/ABLATION_r05.json 2>/tmp/ablation_r05.err
    log "ablation done rc=$?"
    timeout 600 python benchmarks/vpu_ceiling.py > /root/repo/VPU_CEILING_r05.json 2>/tmp/vpu_r05.err
    log "vpu_ceiling done rc=$?"
    timeout 2400 python benchmarks/cw_scaling.py 6 both > /root/repo/CW_SCALING_r05.json 2>/tmp/cwscale_r05.err
    log "cw_scaling 1e6 done rc=$?"
    timeout 3000 python benchmarks/sweep_kill_resume.py 1000000 800 > /root/repo/SWEEP_RESUME_r05.json 2>/tmp/sweep_r05.err
    log "sweep kill/resume done rc=$?"
    timeout 3000 python benchmarks/cw_scaling.py 7 both > /root/repo/CW_SCALING_1E7_r05.json 2>/tmp/cw7_r05.err
    log "cw_scaling 1e7 done rc=$?"
    log "battery complete"
    exit 0
  fi
  sleep 180
done
date -u +"%H:%M:%SZ gave up waiting" >> /tmp/recovery_log_r05.txt
