"""Request-trace + SLO evidence bench: causal tracing proven stitched,
error budgets proven scored, overhead proven negligible.

The causal-tracing PR (docs/tracing.md) is only worth committing if a
chaos-loaded run demonstrably yields COMPLETE traces — so this bench
drives the two traced production paths under capture and parses the
evidence back out of events.jsonl:

* **serving arm** — a bounded/deadline'd ``LikelihoodServer`` flooded
  past capacity from concurrent clients, with a seeded transient
  engine flap (``likelihood_batch:raise@call=1``) absorbed by the
  in-place retry (gated: the ``faults.retry`` event must appear in the
  capture). Gates: every SERVED request's trace stitches
  submit -> queue-wait -> (a ``likelihood_batch`` span linking its
  trace_id) -> resolution; every REJECTED/EXPIRED request leaves its
  trace_id in the stamped exception message AND a matching
  ``likelihood.rejected``/``likelihood.deadline_expired`` event; the
  SLO engine scored both configured objectives and the saturation arm
  fired ``slo.breach``; the merged timeline renders the request
  traces as chrome flow arrows (``trace_flow_events > 0``).
* **sweep arm** — a pipelined sweep under ``drain:raise@chunk=1`` with
  supervised recovery. Gates: every chunk's trace carries dispatch +
  drain + io_write; the retried chunk's trace holds BOTH dispatch
  attempts (trace ids derive from (checkpoint path, chunk), so the
  retry re-joins the same trace) plus a trace-stamped ``faults.retry``
  event.
* **overhead arm** — the tracing machinery's cost per span measured
  directly (K spans with vs without a live TraceContext, same tracer,
  no sink), scaled by the spans-per-chunk the sweep actually emits,
  against the measured wall of one flagship-shaped realize step.
  Gate: < 1% (``RT_OVERHEAD_GATE``). Measured this way — rather than
  A/B-ing two whole sweeps — because the context cost is nanoseconds
  against a multi-second step: a wall-clock A/B would be 100% noise.

Prints one JSON line (committed as ``TRACE_r14_cpu.json``); exit 1 on
any gate miss, with the reasons on stderr (stdout is routinely
/dev/null'd in CI — the PR 12/13 lesson).

Usage: python benchmarks/request_trace.py [--fast] [--out PATH]
  env: RT_REQUESTS / RT_NPSR / RT_NTOA / RT_NREAL_BANK / RT_SWEEP_NREAL
       / RT_SWEEP_CHUNK / RT_STEP_NPSR / RT_STEP_NTOA / RT_STEP_CHUNK
       reshape the workload (--fast presets a seconds-scale CI arm).
"""
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from pta_replicator_tpu import likelihood as lk  # noqa: E402
from pta_replicator_tpu import obs  # noqa: E402
from pta_replicator_tpu.batch import synthetic_batch  # noqa: E402
from pta_replicator_tpu.faults import inject  # noqa: E402
from pta_replicator_tpu.faults.retry import RetryPolicy  # noqa: E402
from pta_replicator_tpu.models.batched import Recipe, realize  # noqa: E402
from pta_replicator_tpu.obs import names  # noqa: E402
from pta_replicator_tpu.obs.timeline import build_timeline  # noqa: E402
from pta_replicator_tpu.obs.trace import (  # noqa: E402
    Tracer,
    adopt,
    chunk_trace_context,
    new_trace_context,
)
from pta_replicator_tpu.utils.provenance import (  # noqa: E402
    EVIDENCE_SCHEMA_VERSION,
    provenance_stamp,
)
from pta_replicator_tpu.utils.sweep import sweep  # noqa: E402

#: tracing-overhead gate: the trace-context machinery must cost < 1%
#: of the flagship CPU step
RT_OVERHEAD_GATE = 0.01

#: the serving arm's SLO objectives: a latency objective the loaded
#: server can mostly meet, and an availability objective the
#: saturation flood is GUARANTEED to breach — admitted-but-expired
#: requests are a sub-stream of likelihood.requests (the BAD ⊆ TOTAL
#: contract), and the 50 ms deadline against a flooded 8-deep queue
#: expires far more than the 1% allowance — so the bench proves both
#: the scoring and the breach path
SLO_SPEC = (
    "serve=likelihood_batch:p99_ms<=500@95%;"
    "admit=err(likelihood.deadline_expired/likelihood.requests)@99.5%"
)

RETRY_POLICY = RetryPolicy(max_attempts=5, base_delay_s=0.05,
                           multiplier=2.0, max_delay_s=0.5, jitter=0.25)


def _load_events(capture_dir):
    events = []
    with open(os.path.join(capture_dir, "events.jsonl")) as fh:
        for line in fh:
            line = line.strip()
            if line:
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    pass
    return events


def _trace_spans(events):
    """{trace_id: [span name, ...]} over the span records."""
    out = {}
    for rec in events:
        if rec.get("type") == "span" and "trace_id" in rec:
            out.setdefault(rec["trace_id"], []).append(rec["name"])
    return out


def _batch_links(events):
    """Every trace_id named in a likelihood_batch span's links field."""
    linked = set()
    for rec in events:
        if rec.get("type") == "span" and \
                rec.get("name") == names.SPAN_LIKELIHOOD_BATCH:
            linked.update(rec.get("links") or [])
    return linked


def run_serving_arm(n_requests, npsr, ntoa, nreal_bank, failures):
    """The chaos-loaded server under capture; returns the evidence
    block and appends gate misses to ``failures``."""
    batch = synthetic_batch(npsr=npsr, ntoa=ntoa, seed=3)
    recipe = Recipe(
        efac=jnp.ones(npsr),
        rn_log10_amplitude=jnp.full(npsr, -13.5),
        rn_gamma=jnp.full(npsr, 4.0),
    )
    bank = np.asarray(
        realize(jax.random.PRNGKey(7), batch, recipe, nreal=nreal_bank)
    )
    d = tempfile.mkdtemp(prefix="request_trace_serve_")
    obs.start_capture(d, heartbeat_interval_s=0.05, stall_timeout_s=None,
                      slo=SLO_SPEC)
    served, rejected_msgs, expired_msgs = [], [], []
    futs_lock = threading.Lock()
    try:
        server = lk.LikelihoodServer(
            lk.RealizationBank.from_array(bank), batch, recipe,
            axes=("rn_log10_amplitude",),
            max_batch=4, max_delay_s=0.002,
            max_queue=8, request_deadline_s=0.05,
        )
        futs = []

        def flood(lo, hi):
            rng = np.random.default_rng(lo)
            for _ in range(lo, hi):
                try:
                    f = server.submit(
                        rn_log10_amplitude=float(
                            rng.uniform(-14.5, -13.0))
                    )
                except lk.ServerSaturated as exc:
                    rejected_msgs.append(str(exc))
                    continue
                with futs_lock:
                    futs.append(f)

        with server:
            server.evaluate(rn_log10_amplitude=-13.5)  # compile
            server.reset_stats()
            with inject.armed(
                f"{inject.SITE_SERVER_ENGINE}:raise@call=1", seed=1
            ):
                # flood phase: 4 threads slam the bounded queue; the
                # first engine call under the schedule is a transient
                # flap the in-place retry absorbs
                bounds = [k * n_requests // 4 for k in range(5)]
                threads = [
                    threading.Thread(target=flood,
                                     args=(bounds[k], bounds[k + 1]))
                    for k in range(4)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            # let the flood's queued tail drain before the expiry
            # phase (its submits must not be shed by admission control)
            wait_until = time.monotonic() + 30.0
            while time.monotonic() < wait_until and server._pending:
                time.sleep(0.01)
            # deadline-expiry phase (deterministic): stall the next
            # engine batch well past the 50 ms request deadline, then
            # queue requests behind it — they expire while the worker
            # is held inside the stalled batch, whatever the host's
            # speed. This is what pushes the admit objective past its
            # 0.5% allowance (the breach-path evidence).
            with inject.armed(
                f"{inject.SITE_SERVER_ENGINE}:stall=0.4@call=1", seed=2
            ):
                futs.append(server.submit(rn_log10_amplitude=-13.5))
                time.sleep(0.05)  # the worker enters the stalled batch
                for k in range(6):
                    futs.append(server.submit(
                        deadline_s=0.05,
                        rn_log10_amplitude=-13.5 - 0.01 * k,
                    ))
        stats = server.stats()
        for f in futs:
            if not f.done():
                failures.append("serving: stranded future after stop()")
                continue
            exc = f.exception()
            if exc is None:
                served.append(f.trace_id)
            elif isinstance(exc, lk.DeadlineExpired):
                expired_msgs.append((f.trace_id, str(exc)))
            else:
                failures.append(f"serving: unexpected failure {exc!r}")
        # let the sampler tick at least once after the load so the
        # availability objective's counter deltas land in its window
        time.sleep(0.4)
        slo_doc = None
        slo_path = os.path.join(d, "slo.json")
        if os.path.exists(slo_path):
            with open(slo_path) as fh:
                slo_doc = json.load(fh)
    finally:
        obs.finish_capture()

    events = _load_events(d)
    spans = _trace_spans(events)
    linked = _batch_links(events)
    stitched = 0
    for tid in served:
        got = spans.get(tid, [])
        ok = (
            names.SPAN_LIKELIHOOD_SUBMIT in got
            and names.SPAN_LIKELIHOOD_QUEUE_WAIT in got
            and names.SPAN_LIKELIHOOD_RESOLVE in got
            and tid in linked
        )
        if ok:
            stitched += 1
        else:
            failures.append(
                f"serving: request {tid} trace incomplete: spans={got}"
                f" linked={tid in linked}"
            )
    # shed requests are greppable by exactly their stamped trace id
    event_traces = {
        name: {
            rec.get("trace_id") for rec in events
            if rec.get("type") == "event" and rec.get("name") == name
        }
        for name in (names.EVENT_LIKELIHOOD_REJECTED,
                     names.EVENT_LIKELIHOOD_DEADLINE_EXPIRED)
    }
    for msg in rejected_msgs:
        tid = msg.rsplit("(trace ", 1)[-1].rstrip(")")
        if tid not in event_traces[names.EVENT_LIKELIHOOD_REJECTED] or \
                names.SPAN_LIKELIHOOD_SUBMIT not in spans.get(tid, []):
            failures.append(
                f"serving: rejected request {tid} not greppable "
                "(no stamped event/submit span)"
            )
    for tid, msg in expired_msgs:
        if f"(trace {tid})" not in msg:
            failures.append(
                f"serving: DeadlineExpired message not stamped: {msg!r}"
            )
        if tid not in event_traces[
            names.EVENT_LIKELIHOOD_DEADLINE_EXPIRED
        ]:
            failures.append(
                f"serving: expired request {tid} has no stamped event"
            )
    if not rejected_msgs:
        failures.append("serving: flood produced no ServerSaturated")
    breaches = sum(
        1 for rec in events
        if rec.get("type") == "event"
        and rec.get("name") == names.EVENT_SLO_BREACH
    )
    # the armed engine flap must actually have been absorbed: count
    # the serve-scope faults.retry events the in-place retry emitted
    # (a hardcoded claim would survive the schedule silently not
    # firing — the evidence must come from the capture)
    engine_retries = sum(
        1 for rec in events
        if rec.get("type") == "event"
        and rec.get("name") == names.EVENT_FAULT_RETRY
        and (rec.get("attrs") or {}).get("scope") == "serve"
    )
    if engine_retries < 1:
        failures.append(
            "serving: the armed transient engine flap left no "
            "faults.retry event — the retry path was not exercised"
        )
    if slo_doc is None or set(slo_doc.get("objectives", {})) != \
            {"serve", "admit"}:
        failures.append(f"serving: slo.json incomplete: {slo_doc!r}")
    elif "admit" not in (slo_doc.get("breached") or []) or not breaches:
        failures.append(
            "serving: the saturation flood did not breach the admit "
            f"objective (breached={slo_doc.get('breached')}, "
            f"breach events={breaches})"
        )
    timeline = build_timeline(d)
    trace_flows = timeline["otherData"]["trace_flow_events"]
    if not trace_flows:
        failures.append("serving: timeline rendered no trace flow events")
    return {
        "requests": n_requests,
        "served": len(served),
        "stitched": stitched,
        "stitched_fraction": (
            round(stitched / len(served), 4) if served else None
        ),
        "rejected": stats["rejected"],
        "deadline_expired": stats["deadline_expired"],
        "engine_retries_absorbed": engine_retries,
        "latency": stats["latency"],
        "slo": slo_doc,
        "slo_breach_events": breaches,
        "timeline_trace_flow_events": trace_flows,
    }


def run_sweep_arm(nreal, chunk, npsr, ntoa, failures):
    """The faulted sweep under capture; returns the evidence block."""
    batch = synthetic_batch(npsr=npsr, ntoa=ntoa, seed=1)
    recipe = Recipe(efac=jnp.ones(npsr))
    d = tempfile.mkdtemp(prefix="request_trace_sweep_")
    obs.start_capture(d, heartbeat_interval_s=0.2, stall_timeout_s=None)
    try:
        ck = os.path.join(d, "sweep.npz")
        with inject.armed(f"{inject.SITE_DRAIN}:raise@chunk=1", seed=0):
            sweep(jax.random.PRNGKey(0), batch, recipe, nreal=nreal,
                  chunk=chunk, checkpoint_path=ck, reduce_fn=None,
                  chunk_retries=2, retry_policy=RETRY_POLICY)
    finally:
        obs.finish_capture()
    events = _load_events(d)
    nchunks = nreal // chunk
    by_chunk = {}
    for rec in events:
        if rec.get("type") != "span" or "trace_id" not in rec:
            continue
        c = (rec.get("attrs") or {}).get("chunk")
        if c is None:
            continue
        by_chunk.setdefault(int(c), {}).setdefault(
            rec["trace_id"], []
        ).append(rec["name"])
    complete = 0
    retried_attempts = 0
    for c in range(nchunks):
        traces = by_chunk.get(c, {})
        if len(traces) != 1:
            failures.append(
                f"sweep: chunk {c} spans split over {len(traces)} "
                "trace ids (expected exactly one)"
            )
            continue
        ((tid, spans_c),) = traces.items()
        if {names.SPAN_DISPATCH, names.SPAN_DRAIN,
                names.SPAN_IO_WRITE} <= set(spans_c):
            complete += 1
        else:
            failures.append(
                f"sweep: chunk {c} trace incomplete: {sorted(spans_c)}"
            )
        if c == 1:
            retried_attempts = spans_c.count(names.SPAN_DISPATCH)
            if retried_attempts < 2:
                failures.append(
                    "sweep: retried chunk 1 shows "
                    f"{retried_attempts} dispatch attempt(s) in its "
                    "trace (expected a multi-attempt trace)"
                )
            retry_stamped = any(
                rec.get("type") == "event"
                and rec.get("name") == names.EVENT_FAULT_RETRY
                and rec.get("trace_id") == tid
                for rec in events
            )
            if not retry_stamped:
                failures.append(
                    "sweep: no faults.retry event stamped with the "
                    "retried chunk's trace id"
                )
    return {
        "nchunks": nchunks,
        "complete_chunk_traces": complete,
        "retried_chunk_attempts": retried_attempts,
    }


def run_overhead_arm(step_npsr, step_ntoa, step_chunk, failures):
    """Per-span trace-context cost x spans-per-chunk vs the measured
    flagship-shaped step wall."""
    k = 4000
    tracer = Tracer()  # private, no sink: measures the machinery only

    def spin():
        for _ in range(k):
            with tracer.span(names.SPAN_DISPATCH):
                pass

    spin()  # warm
    t0 = time.perf_counter()
    spin()
    t_plain = time.perf_counter() - t0
    with adopt(new_trace_context()):
        spin()  # warm the traced path
        t0 = time.perf_counter()
        spin()
        t_traced = time.perf_counter() - t0
    per_span_s = max(0.0, (t_traced - t_plain) / k)
    t0 = time.perf_counter()
    for i in range(k):
        chunk_trace_context("overhead-probe", i)
    ctx_create_s = (time.perf_counter() - t0) / k

    batch = synthetic_batch(npsr=step_npsr, ntoa=step_ntoa, seed=5)
    recipe = Recipe(
        efac=jnp.ones(step_npsr),
        rn_log10_amplitude=jnp.full(step_npsr, -13.5),
        rn_gamma=jnp.full(step_npsr, 4.0),
    )
    key = jax.random.PRNGKey(2)
    np.asarray(realize(key, batch, recipe, nreal=step_chunk))  # compile
    walls = []
    for rep in range(3):
        t0 = time.perf_counter()
        np.asarray(realize(jax.random.fold_in(key, rep), batch, recipe,
                           nreal=step_chunk))
        walls.append(time.perf_counter() - t0)
    step_wall = float(np.median(walls))
    # a pipelined sweep chunk emits 3 stage spans (dispatch/drain/
    # io_write) + 1 context derivation; everything else (engine spans)
    # exists with or without tracing
    spans_per_chunk = 3
    overhead_s = ctx_create_s + spans_per_chunk * per_span_s
    fraction = overhead_s / step_wall if step_wall > 0 else 0.0
    if fraction >= RT_OVERHEAD_GATE:
        failures.append(
            f"overhead: tracing costs {100 * fraction:.3f}% of the "
            f"step ({overhead_s * 1e6:.2f} us vs {step_wall:.3f} s) — "
            f"gate {100 * RT_OVERHEAD_GATE:g}%"
        )
    return {
        "per_span_ctx_s": round(per_span_s, 9),
        "ctx_create_s": round(ctx_create_s, 9),
        "spans_per_chunk": spans_per_chunk,
        "step_wall_s": round(step_wall, 4),
        "step_shape": f"{step_npsr}x{step_ntoa}x{step_chunk}",
        "overhead_fraction": round(fraction, 8),
        "overhead_gate": RT_OVERHEAD_GATE,
    }


def main() -> int:
    fast = "--fast" in sys.argv[1:]
    out_path = None
    if "--out" in sys.argv[1:]:
        out_path = sys.argv[sys.argv.index("--out") + 1]
    n_requests = int(os.environ.get("RT_REQUESTS",
                                    "64" if fast else "240"))
    npsr = int(os.environ.get("RT_NPSR", "4"))
    ntoa = int(os.environ.get("RT_NTOA", "96" if fast else "256"))
    nreal_bank = int(os.environ.get("RT_NREAL_BANK",
                                    "6" if fast else "16"))
    sweep_nreal = int(os.environ.get("RT_SWEEP_NREAL",
                                     "16" if fast else "64"))
    sweep_chunk = int(os.environ.get("RT_SWEEP_CHUNK",
                                     "4" if fast else "16"))
    step_npsr = int(os.environ.get("RT_STEP_NPSR", "4" if fast else "8"))
    step_ntoa = int(os.environ.get("RT_STEP_NTOA",
                                   "512" if fast else "4096"))
    step_chunk = int(os.environ.get("RT_STEP_CHUNK",
                                    "16" if fast else "64"))

    failures = []
    serving = run_serving_arm(n_requests, npsr, ntoa, nreal_bank,
                              failures)
    sweep_block = run_sweep_arm(sweep_nreal, sweep_chunk, npsr, ntoa,
                                failures)
    overhead = run_overhead_arm(step_npsr, step_ntoa, step_chunk,
                                failures)

    rec = {
        "bench": "request_trace",
        "backend": jax.default_backend(),
        "fast": fast,
        "serving": serving,
        "sweep": sweep_block,
        "overhead": overhead,
        "ok": not failures,
        "failures": failures,
        **provenance_stamp(
            EVIDENCE_SCHEMA_VERSION,
            repo_root=os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            ),
        ),
    }
    payload = json.dumps(rec)
    print(payload)
    if out_path:
        with open(out_path, "w") as fh:
            fh.write(payload + "\n")
    for reason in failures:
        # stdout is routinely /dev/null'd in CI: gate-miss reasons
        # must reach stderr
        print(f"request_trace GATE MISS: {reason}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
