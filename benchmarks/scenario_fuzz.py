"""Scenario-fuzz bench: the differential matrix as committed evidence.

Runs N (default 200) generated scenarios through the batched-vs-oracle
differential (pta_replicator_tpu/scenarios/fuzz.py) and gates on the
whole contract at once:

* **0 unexplained disagreements** — every scenario's every enabled
  family (and the jit-fused engine total) within its documented
  tolerance of the oracle ``models/`` single-pulsar path, under shared
  PRNG streams;
* **coverage** — the fixed-seed generator must have exercised every
  Recipe signal family and structural variant (white/ecorr/red/
  chromatic, power-law + turnover + free-spectrum GWB, HD /
  uncorrelated / anisotropic ORFs, population-split + explicit +
  streamed CW catalogs, bursts, memory, gaussian transients, glitch
  steps) at least once — a fuzz run that silently stopped sampling a
  family proves nothing about it;
* **pipelined-vs-sync sweep byte-identity** on a sampled subset of
  scenarios carrying sweep plans;
* **the planted-bug arm** — a controlled defect injected into one
  batched family must be detected, shrunk to a minimal spec containing
  exactly that family, written as a replayable spec file, and the
  replay WITHOUT the defect must pass (the harness's own
  false-positive control).

Prints one JSON line; committed as ``FUZZ_r12_cpu.json`` and diffed by
``bench-diff`` (scenarios_per_s / agreement_rate higher-better,
max_rel_disagreement lower-better — obs/regress.py). Exit 1 on any
gate miss, so CI runs the --fast configuration directly
(scripts/check.sh).

Usage: python benchmarks/scenario_fuzz.py [--fast] [--out PATH]
  env: FUZZ_N / FUZZ_SEED / FUZZ_SWEEP_EVERY reshape the run.
"""
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

from pta_replicator_tpu.scenarios import compile_spec, fuzz as fz  # noqa: E402
from pta_replicator_tpu.scenarios.spec import load_spec  # noqa: E402
from pta_replicator_tpu.utils.provenance import (  # noqa: E402
    EVIDENCE_SCHEMA_VERSION,
    provenance_stamp,
)

#: every signal family / structural variant the generator must have
#: exercised in a full run (spec_families tokens). The fixed seed makes
#: this deterministic: a miss means the generator (or the token map)
#: changed, not bad luck.
REQUIRED_COVERAGE = (
    "white", "ecorr", "red", "chromatic",
    "gwb_powerlaw", "gwb_turnover", "gwb_freespec",
    "orf_hd", "orf_none", "orf_aniso",
    "cw", "cw_streamed", "population_cw",
    "burst", "memory", "transient", "glitch",
    # beyond-diagonal correlated noise (ISSUE 13): every structured
    # covariance family must be differentially exercised against the
    # dense f64 oracle
    "cov_banded", "cov_kron", "cov_dense",
)


def planted_bug_arm(out_dir: str) -> dict:
    """Inject a controlled defect into one batched family; require
    detection, shrinking to exactly that family, and a replayable
    minimal spec that PASSES once the defect is removed."""
    planted_family = "ecorr"
    report = fz.fuzz(
        6, root_seed=5, out_dir=out_dir,
        perturb={"family": planted_family, "scale": 1.01},
    )
    arm = {
        "planted_family": planted_family,
        "scale": 1.01,
        "n_scenarios": report["n_scenarios"],
        "detected": report["n_disagreements"],
        "failures": report["failures"],
    }
    problems = []
    if not report["n_disagreements"]:
        problems.append("planted bug was not detected")
    for f in report["failures"]:
        if f["minimal_families"] != [planted_family]:
            problems.append(
                f"shrinker did not converge to the planted family: "
                f"{f['minimal_families']}"
            )
        replay_file = f.get("replay_file")
        if not replay_file or not os.path.exists(replay_file):
            problems.append("no replayable minimal spec written")
            continue
        # the false-positive control: the minimal spec WITHOUT the
        # planted defect must agree (the spec is innocent, the
        # perturbation was the bug)
        res = fz.run_scenario(
            compile_spec(load_spec(replay_file), validate=False)
        )
        if not res.agree:
            problems.append(
                f"minimal spec {replay_file} disagrees even without "
                "the planted defect"
            )
    arm["ok"] = not problems
    arm["problems"] = problems
    return arm


def main() -> int:
    fast = "--fast" in sys.argv[1:]
    out_path = None
    argv = sys.argv[1:]
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    n = int(os.environ.get("FUZZ_N", "8" if fast else "200"))
    seed = int(os.environ.get("FUZZ_SEED", "0"))
    sweep_every = int(os.environ.get("FUZZ_SWEEP_EVERY",
                                     "4" if fast else "8"))

    failures = []
    d = tempfile.mkdtemp(prefix="scenario_fuzz_")
    # shrunk replayable failing specs must OUTLIVE the bench (the whole
    # point is re-running them after an exit-1) — they go to a durable
    # dir, not the tempdir the finally below deletes; created only when
    # a disagreement actually happens. The planted arm's specs stay in
    # the tempdir: they are validated in-process and intentionally
    # transient.
    fail_dir = os.environ.get("FUZZ_FAIL_DIR", "scenario_fuzz_failures")
    try:
        t0 = time.monotonic()
        report = fz.fuzz(
            n, root_seed=seed, out_dir=fail_dir,
            sweep_every=sweep_every,
            progress=(lambda done, total: print(
                f"scenario {done}/{total}", file=sys.stderr)
                if not fast else None),
        )
        if report["n_disagreements"]:
            failures.append(
                f"{report['n_disagreements']} unexplained "
                f"disagreement(s): {report['failures']}"
            )
        si = report["sweep_identity"]
        if si["checked"] == 0:
            failures.append("sweep-identity arm never ran (no scenario "
                            "carried a sweep plan at this seed)")
        elif not si["all_bit_identical"]:
            failures.append("pipelined-vs-sync sweep byte-identity "
                            "violated")
        missing = [fam for fam in REQUIRED_COVERAGE
                   if not report["coverage"].get(fam)]
        if missing and not fast:
            failures.append(f"coverage gap: {missing} never sampled")

        planted = planted_bug_arm(os.path.join(d, "planted"))
        if not planted["ok"]:
            failures.append(f"planted-bug arm: {planted['problems']}")

        rec = {
            "bench": "scenario_fuzz",
            "backend": jax.default_backend(),
            "fast": fast,
            "wall_s": round(time.monotonic() - t0, 3),
            "n_scenarios": report["n_scenarios"],
            "root_seed": seed,
            "scenarios_per_s": report["scenarios_per_s"],
            "agreement_rate": report["agreement_rate"],
            "n_disagreements": report["n_disagreements"],
            "max_rel_disagreement": report["max_rel_disagreement"],
            "max_rel_by_family": report["max_rel_by_family"],
            "tolerances": report["tolerances"],
            "coverage": report["coverage"],
            "combo_histogram_size": report["combo_histogram_size"],
            "required_coverage_missing": missing,
            "sweep_identity": si,
            "planted_bug": planted,
            "ok": not failures,
            "failures": failures,
            **provenance_stamp(
                EVIDENCE_SCHEMA_VERSION,
                repo_root=os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))
                ),
            ),
        }
        payload = json.dumps(rec)
        print(payload)
        if out_path:
            with open(out_path, "w") as fh:
                fh.write(payload + "\n")
        return 1 if failures else 0
    finally:
        shutil.rmtree(d, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
