"""End-to-end overlap A/B for the fused stage-graph sweep (PR 15).

Two identical streamed-CW sweeps (checkpointed, ``reduce_fn=None``,
durable writes — every chunk hauls a full residual cube through
readback and an fsync'd checkpoint):

* **stacked** — the classic two-pipeline composition: the streamed CW
  static precompute runs to completion first (its own tile-build/H2D
  prefetch window), then the pipelined sweep executor runs its
  dispatch/drain/io_write window. The two windows never overlap across
  the compute boundary.
* **fused** — ``sweep(fused_stream=True)``: ONE stage graph
  (``static_build -> dispatch -> drain -> io_write``, parallel/
  stages.py) where chunk ``i+1``'s CW tile-build/H2D stages run
  concurrently with chunk ``i``'s compute, readback, and checkpoint
  write.

Headline metric per arm: ``overlap_efficiency_e2e`` — obs.occupancy's
overlap efficiency computed over the WHOLE end-to-end window (host
precompute + dispatch + readback + durable write busy vs the arm's
wall), i.e. how close the composition came to ideal pipelining of
everything it did. The gate: the fused arm must measure STRICTLY above
the stacked baseline, with byte-identical checkpoints (sha256).

Honest framing (docs/streaming.md has the long form): on a fixed
recipe the fused graph re-derives an IDENTICAL static per chunk — it
spends ``nchunks x`` the host tile-build work of the stacked arm and
hides it under the compute/IO window, so its wall stays near parity
(``wall_ratio`` is recorded, not gated) while its end-to-end overlap
efficiency is far higher. The fused mode is the substrate for sweeps
whose per-chunk deterministic content varies (and for hosts with spare
cores where the rebuild is free); this bench pins the SCHEDULING
property — the stages genuinely run concurrently — and the byte
identity that makes the fusion safe to turn on.

Prints one JSON line; exit 1 with reasons on stderr when a gate fails.

Usage: python benchmarks/stage_graph.py [--fast]
  STAGE_GRAPH_NCW/_STREAM_CHUNK/_NTOA/_NMODES/_NREAL/_CHUNK/_NREP
  reshape the workload (--fast presets a seconds-scale CI shape).
"""
import hashlib
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from pta_replicator_tpu import obs  # noqa: E402
from pta_replicator_tpu.batch import synthetic_batch  # noqa: E402
from pta_replicator_tpu.models.batched import Recipe  # noqa: E402
from pta_replicator_tpu.obs import names, occupancy  # noqa: E402
from pta_replicator_tpu.utils.provenance import provenance_stamp  # noqa: E402
from pta_replicator_tpu.utils.sweep import sweep  # noqa: E402

NPSR = 8


def _env(name, default):
    return int(os.environ.get(name, str(default)))


def build_workload(fast: bool):
    if fast:
        cfg = dict(ncw=_env("STAGE_GRAPH_NCW", 6000),
                   stream_chunk=_env("STAGE_GRAPH_STREAM_CHUNK", 1024),
                   ntoa=_env("STAGE_GRAPH_NTOA", 1024),
                   nmodes=_env("STAGE_GRAPH_NMODES", 256),
                   nreal=_env("STAGE_GRAPH_NREAL", 2048),
                   chunk=_env("STAGE_GRAPH_CHUNK", 512),
                   nrep=_env("STAGE_GRAPH_NREP", 1))
    else:
        cfg = dict(ncw=_env("STAGE_GRAPH_NCW", 10000),
                   stream_chunk=_env("STAGE_GRAPH_STREAM_CHUNK", 1024),
                   ntoa=_env("STAGE_GRAPH_NTOA", 2048),
                   nmodes=_env("STAGE_GRAPH_NMODES", 384),
                   nreal=_env("STAGE_GRAPH_NREAL", 4096),
                   chunk=_env("STAGE_GRAPH_CHUNK", 1024),
                   nrep=_env("STAGE_GRAPH_NREP", 3))
    batch = synthetic_batch(npsr=NPSR, ntoa=cfg["ntoa"], seed=0)
    rng = np.random.default_rng(1)
    ncw = cfg["ncw"]
    params = np.stack([
        np.arccos(rng.uniform(-1, 1, ncw)),
        rng.uniform(0, 2 * np.pi, ncw),
        10 ** rng.uniform(8, 9.5, ncw),
        rng.uniform(50, 1000, ncw),
        10 ** rng.uniform(-8.8, -7.6, ncw),
        rng.uniform(0, 2 * np.pi, ncw),
        rng.uniform(0, np.pi, ncw),
        np.arccos(rng.uniform(-1, 1, ncw)),
    ])
    # streamed CW catalog + red noise: the flagship shape in miniature —
    # a per-chunk host f64 tile build comparable to (but below) the
    # chunk's device compute + durable I/O, so the fused graph can hide
    # the rebuild entirely while the stacked arm pays its windows
    # back to back
    recipe = Recipe(
        efac=jnp.ones(NPSR, batch.toas_s.dtype),
        rn_log10_amplitude=jnp.full(NPSR, -14.0, batch.toas_s.dtype),
        rn_gamma=jnp.full(NPSR, 4.0, batch.toas_s.dtype),
        rn_nmodes=cfg["nmodes"],
        cgw_params=jnp.asarray(params),
        cgw_stream_chunk=cfg["stream_chunk"],
    )
    return batch, recipe, cfg


def run_arm(fused, batch, recipe, key, nreal, chunk, workdir):
    """One sweep into a fresh cold-file subdirectory; returns
    (wall_s, per-stage busy, overlap stats over the e2e window,
    checkpoint sha256)."""
    arm_dir = tempfile.mkdtemp(prefix=f"arm_{'fused' if fused else 'stacked'}_",
                               dir=workdir)
    ckpt = os.path.join(arm_dir, "sweep.npz")
    obs.reset_all()
    t0 = time.perf_counter()
    sweep(key, batch, recipe, nreal=nreal, chunk=chunk,
          checkpoint_path=ckpt, reduce_fn=None, pipeline_depth=2,
          durable=True, fused_stream=fused)
    wall = time.perf_counter() - t0
    if obs.TRACER.dropped:
        raise RuntimeError(
            f"{obs.TRACER.dropped} span records dropped — arm larger "
            "than the idle event buffer; shrink the workload"
        )
    events = obs.TRACER.events()
    # the end-to-end stage set of each composition: the host-precompute
    # stage (the whole static_delays call for stacked, the per-chunk
    # static_build stage for fused — each CONTAINS its nested CW
    # tile-stream spans, so neither is double-counted) plus the three
    # sweep pipeline stages
    static_span = (names.SPAN_STATIC_BUILD if fused
                   else names.SPAN_STATIC_DELAYS)
    stage_set = [static_span, names.SPAN_DISPATCH, names.SPAN_DRAIN,
                 names.SPAN_IO_WRITE]
    intervals = occupancy.stage_intervals(events, stages=stage_set)
    busy = {s: occupancy.busy_seconds(intervals.get(s, []))
            for s in stage_set}
    stats = occupancy.overlap_stats(busy, wall)
    h = hashlib.sha256()
    with open(ckpt, "rb") as fh:
        for piece in iter(lambda: fh.read(1 << 22), b""):
            h.update(piece)
    shutil.rmtree(arm_dir, ignore_errors=True)
    return wall, busy, stats, h.hexdigest()


def main() -> int:
    fast = "--fast" in sys.argv[1:]
    batch, recipe, cfg = build_workload(fast)
    key = jax.random.PRNGKey(7)
    workdir = tempfile.mkdtemp(prefix="stage_graph_")
    arms = {"stacked": [], "fused": []}
    busies = {}
    effs = {"stacked": [], "fused": []}
    digests = {}
    try:
        # warm-up: compile the realize engine + stream steps at the
        # bench shapes, touch the filesystem once
        run_arm(False, batch, recipe, key, cfg["chunk"], cfg["chunk"],
                workdir)
        # interleave arms so filesystem/vCPU drift hits both equally
        for _ in range(cfg["nrep"]):
            for name, fused in (("stacked", False), ("fused", True)):
                wall, busy, stats, digest = run_arm(
                    fused, batch, recipe, key, cfg["nreal"],
                    cfg["chunk"], workdir,
                )
                arms[name].append(wall)
                eff = stats.get("overlap_efficiency")
                if eff is not None:
                    effs[name].append(eff)
                if name not in busies or wall <= min(arms[name]):
                    busies[name] = {k: round(v, 3) for k, v in busy.items()}
                digests[name] = digest
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    med = lambda xs: float(np.median(xs)) if xs else None  # noqa: E731
    stacked_eff = med(effs["stacked"])
    fused_eff = med(effs["fused"])
    stacked_wall = med(arms["stacked"])
    fused_wall = med(arms["fused"])
    bit_identical = digests.get("stacked") == digests.get("fused")

    failures = []
    if not bit_identical:
        failures.append(
            "checkpoints differ between the stacked and fused arms "
            f"(sha256 {digests.get('stacked')} vs {digests.get('fused')})"
        )
    if stacked_eff is None or fused_eff is None:
        failures.append("an arm produced no overlap-efficiency measure")
    elif not fused_eff > stacked_eff:
        failures.append(
            "fused end-to-end overlap efficiency "
            f"{fused_eff} is not strictly above the stacked baseline "
            f"{stacked_eff}"
        )

    rec = {
        "bench": "stage_graph",
        **provenance_stamp(2, repo_root=os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))),
        "fast": fast,
        "workload": {
            "npsr": NPSR, **cfg,
            "nchunks": cfg["nreal"] // cfg["chunk"],
            "reduce_fn": None, "durable_writes": True,
            "pipeline_depth": 2,
        },
        "stacked": {
            "wall_s": round(stacked_wall, 3),
            "all_wall_s": [round(x, 3) for x in arms["stacked"]],
            "overlap_efficiency_e2e": stacked_eff,
            "stage_busy_s": busies.get("stacked"),
        },
        "fused": {
            "wall_s": round(fused_wall, 3),
            "all_wall_s": [round(x, 3) for x in arms["fused"]],
            "overlap_efficiency_e2e": fused_eff,
            "stage_busy_s": busies.get("fused"),
        },
        "efficiency_gain": (
            None if None in (fused_eff, stacked_eff)
            else round(fused_eff - stacked_eff, 3)
        ),
        # info, not a gate: at identical per-chunk content the fused
        # graph does nchunks x the host tile-build work of the stacked
        # arm and hides it under the compute/IO window — near-parity
        # wall on this shared-core CPU host, real headroom on hosts
        # with idle cores (see the bench docstring / docs/streaming.md)
        "wall_ratio_fused_vs_stacked": round(fused_wall / stacked_wall, 3),
        "bit_identical": bit_identical,
        "gates": {
            "bit_identical": bit_identical,
            "fused_eff_above_stacked": bool(
                fused_eff is not None and stacked_eff is not None
                and fused_eff > stacked_eff
            ),
        },
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    print(json.dumps(rec))
    if failures:
        for reason in failures:
            print(f"stage_graph GATE FAIL: {reason}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
