"""Preemption rehearsal at scale: kill a resumable sweep mid-run on the
real chip, resume it, and require bit-identical results vs an
uninterrupted run (VERDICT r3 item 6 — utils/sweep had only been
exercised at toy sizes on CPU).

Protocol:
  1. run an uninterrupted sweep of ``nreal`` realizations -> ckpt A;
  2. spawn a child process running the SAME sweep -> ckpt B, SIGKILL it
     once at least a third of the chunk files exist (a real preemption:
     no atexit, no cleanup);
  3. re-run the child; it must resume from the surviving chunks and
     consolidate;
  4. compare A and B byte-for-byte per chunk block.

Usage: python benchmarks/sweep_kill_resume.py [nreal] [chunk]
  defaults 1_000_000 x 800 on TPU-class hardware; use small values
  (e.g. 2048 256) for a CPU smoke run with BENCH_PLATFORM=cpu.
  SWEEP_NPSR / SWEEP_NTOA / SWEEP_NCW shrink the per-realization
  workload (default: the full 68 x 7758 bench shape) so a CPU-only
  round can still push the REALIZATION axis past 1e5 — the checkpoint
  cadence, chunk files, and stream-contract fingerprints are what this
  rehearsal exercises, and they scale with nreal/chunk, not with the
  pulsar count.
Prints one JSON line.
"""
import glob
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _workload_shape() -> tuple:
    """(npsr, ntoa, ncw) from the SWEEP_* env knobs — parsed in exactly
    one place so the report fingerprint and the executed workload can
    never disagree."""
    return (
        int(os.environ.get("SWEEP_NPSR", "68")),
        int(os.environ.get("SWEEP_NTOA", "7758")),
        int(os.environ.get("SWEEP_NCW", "100")),
    )


def _run_sweep(ckpt: str, nreal: int, chunk: int) -> np.ndarray:
    import jax

    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)
    from bench import build_workload
    from pta_replicator_tpu.utils.sweep import sweep

    npsr, ntoa, ncw = _workload_shape()
    batch, recipe = build_workload(npsr=npsr, ntoa=ntoa, ncw=ncw)
    return sweep(
        jax.random.PRNGKey(42), batch, recipe, nreal=nreal,
        checkpoint_path=ckpt, chunk=chunk,
    )


def main():
    if os.environ.get("SWEEP_CHILD") == "1":
        out = _run_sweep(
            sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
        )
        print(f"child done {out.shape}", flush=True)
        return

    nreal = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    chunk = int(sys.argv[2]) if len(sys.argv) > 2 else 800
    nchunks = nreal // chunk
    d = tempfile.mkdtemp(prefix="sweep_kr_")
    ckpt_a = os.path.join(d, "a.npz")
    ckpt_b = os.path.join(d, "b.npz")
    npsr, ntoa, ncw = _workload_shape()
    report = {
        "nreal": nreal, "chunk": chunk,
        "npsr": npsr, "ntoa": ntoa, "ncw": ncw,
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }

    t0 = time.perf_counter()
    ref = _run_sweep(ckpt_a, nreal, chunk)
    report["uninterrupted_s"] = round(time.perf_counter() - t0, 2)
    report["rate_real_per_s"] = round(nreal / report["uninterrupted_s"], 1)

    # the child inherits the SWEEP_* workload env unchanged, so A and B
    # provably run the same shape
    env = dict(os.environ, SWEEP_CHILD="1")
    args = [sys.executable, os.path.abspath(__file__), ckpt_b,
            str(nreal), str(chunk)]
    child = subprocess.Popen(args, env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, text=True)
    # SIGKILL once >= 1/3 of the chunk files exist (and the run is
    # provably mid-flight, not finished)
    deadline = time.monotonic() + 3600
    killed_at = None
    while time.monotonic() < deadline:
        nfiles = len(glob.glob(ckpt_b + ".chunk*.npy"))
        if nfiles >= max(1, nchunks // 3) and nfiles < nchunks:
            child.send_signal(signal.SIGKILL)
            killed_at = nfiles
            break
        if child.poll() is not None:
            break
        time.sleep(0.2)
    child.wait()
    if killed_at is None:
        report["error"] = "child finished before the kill trigger"
        print(json.dumps(report))
        return
    report["killed_after_chunks"] = killed_at
    report["chunks_total"] = nchunks

    t0 = time.perf_counter()
    r2 = subprocess.run(args, env=env, capture_output=True, text=True)
    report["resume_s"] = round(time.perf_counter() - t0, 2)
    if r2.returncode != 0:
        report["error"] = f"resume failed: {r2.stdout[-400:]}"
        print(json.dumps(report))
        return

    with np.load(ckpt_b) as z:
        resumed = np.concatenate(
            [z[f"chunk{i}"] for i in range(nchunks)], axis=0
        )
    report["bit_identical"] = bool(
        ref.shape == resumed.shape
        and ref.tobytes() == resumed.tobytes()
    )
    if not report["bit_identical"]:
        diff = np.abs(ref - resumed)
        report["max_abs_diff"] = float(diff.max())
    import jax

    report["device"] = jax.devices()[0].device_kind
    print(json.dumps(report))


if __name__ == "__main__":
    main()
