"""A/B microbench for the pipelined sweep executor: identical sweeps at
pipeline_depth=1 (the synchronous reference loop) and depth>=2 (the
double-buffered executor), on the CPU backend with ``reduce_fn=None`` —
the I/O-heavy configuration where every chunk hauls a full
(chunk, Np, Nt) residual cube through host readback and a .npy
checkpoint write, i.e. exactly the latency the pipeline exists to hide.

Prints one JSON line::

    {"depth1_s": ..., "depth2_s": ..., "reduction_pct": ...,
     "bit_identical": true, "telemetry": {"depth1": {...}, "depth2": {...}}}

``reduction_pct`` is the headline: wall-time saved by depth 2 vs depth 1
(acceptance floor: >= 20%). The per-arm ``telemetry`` blocks carry the
span aggregates that evidence the overlap — at depth 1 the chunk wall is
the SUM of compute + ``readback_fence`` + write; at depth 2 the
``drain`` + ``io_write`` totals overlap the dispatch stream, so
``sweep_pipeline`` wall approaches max(compute, drain+io) instead of the
sum. ``bit_identical`` confirms the two arms produced byte-equal
consolidated checkpoints (the executor's core contract).

Usage: python benchmarks/sweep_overlap.py [nreal] [chunk] [depth]
  defaults 2048 x 256, depth 2; SWEEP_OVERLAP_NPSR / _NTOA / _NREP
  reshape the workload (defaults 8 x 8192, 5 reps, median-of-reps —
  arms interleaved, each rep on cold files).
"""
import hashlib
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from pta_replicator_tpu import obs  # noqa: E402
from pta_replicator_tpu.batch import synthetic_batch  # noqa: E402
from pta_replicator_tpu.models.batched import Recipe  # noqa: E402
from pta_replicator_tpu.utils.sweep import sweep  # noqa: E402


def _pipeline_spans(summary: dict) -> dict:
    """The sweep-relevant span aggregates from an obs summary (path
    suffix match: worker-thread spans nest under the sweep span)."""
    keep = (
        "sweep_chunk", "readback_fence", "sweep_pipeline", "dispatch",
        "drain", "io_write",
    )
    out = {}
    for path, agg in summary.items():
        leaf = path.rsplit("/", 1)[-1]
        if leaf in keep:
            out[leaf] = {
                "calls": agg["calls"],
                "total_s": round(agg["total_s"], 4),
            }
    return out


def run_arm(depth, key, batch, recipe, nreal, chunk, workdir):
    """One sweep at ``depth`` into a fresh checkpoint; returns
    (wall_s, telemetry, occupancy, sha256 of the consolidated npz).

    A FRESH subdirectory per invocation: re-writing the same chunk
    filenames would hit warm page-cache/9p entries on later reps,
    silently deleting the I/O cost the pipeline exists to hide (a real
    sweep writes every chunk file exactly once). Cold files for every
    arm, every rep, keeps the A/B honest."""
    arm_dir = tempfile.mkdtemp(prefix=f"arm_d{depth}_", dir=workdir)
    ckpt = os.path.join(arm_dir, f"sweep_d{depth}.npz")
    obs.reset_all()
    t0 = time.perf_counter()
    # durable=True: fsync-backed checkpoint writes. This is the honest
    # I/O-heavy configuration — the fsync is a kernel-side disk wait
    # with no CPU cost, so the depth-1 arm pays it serially per chunk
    # while the depth>=2 arm hides it behind device compute. (Plain
    # page-cache writes are mostly memcpy, which on a CPU-only host
    # competes with XLA for the same cores and cannot be overlapped
    # away.)
    sweep(key, batch, recipe, nreal=nreal, chunk=chunk,
          checkpoint_path=ckpt, reduce_fn=None, pipeline_depth=depth,
          durable=True)
    wall = time.perf_counter() - t0
    telem = _pipeline_spans(obs.TRACER.summary())
    # measured stage occupancy of this arm (duty cycle per stage,
    # overlap efficiency, bottleneck verdict) from the same spans the
    # report's utilization section reads — the A/B's wall reduction and
    # this number must tell one story. Without a configured sink the
    # tracer's in-memory buffer caps at IDLE_MAX_EVENTS: a huge arm
    # (>~650 chunks) would silently analyze only its first part, so a
    # truncated buffer yields no occupancy block rather than a wrong one
    if obs.TRACER.dropped:
        occ = {"skipped": f"{obs.TRACER.dropped} span records dropped "
                          "(arm larger than the idle event buffer)"}
    else:
        occ = obs.occupancy.analyze(obs.TRACER.events())
    # streaming digest, not raw bytes: at the default config each
    # consolidated npz is ~0.5 GiB — holding both arms' archives
    # resident would pressure the page cache of the very host the A/B
    # is timing
    h = hashlib.sha256()
    with open(ckpt, "rb") as fh:
        for piece in iter(lambda: fh.read(1 << 22), b""):
            h.update(piece)
    shutil.rmtree(arm_dir, ignore_errors=True)
    return wall, telem, occ, h.hexdigest()


def main():
    nreal = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    chunk = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    depth = int(sys.argv[3]) if len(sys.argv) > 3 else 2
    npsr = int(os.environ.get("SWEEP_OVERLAP_NPSR", "8"))
    ntoa = int(os.environ.get("SWEEP_OVERLAP_NTOA", "8192"))
    nrep = int(os.environ.get("SWEEP_OVERLAP_NREP", "5"))

    batch = synthetic_batch(npsr=npsr, ntoa=ntoa, seed=0)
    # white noise + 150-mode red noise: device compute per chunk sized
    # to (slightly exceed) the writer thread's full per-chunk burden —
    # durable 64 MB cube writes + the incremental npz consolidation —
    # so the pipeline hides the WHOLE I/O side and the A/B measures the
    # overlap rather than trading one serial bottleneck for another
    recipe = Recipe(
        efac=jnp.ones(npsr, batch.toas_s.dtype),
        rn_log10_amplitude=jnp.full(npsr, -14.0, batch.toas_s.dtype),
        rn_gamma=jnp.full(npsr, 4.0, batch.toas_s.dtype),
        rn_nmodes=150,
    )
    key = jax.random.PRNGKey(7)
    d = tempfile.mkdtemp(prefix="sweep_overlap_")
    try:
        # warm-up: compile the realize engine + touch the filesystem once
        run_arm(1, key, batch, recipe, chunk, chunk, d)

        results = {1: [], depth: []}
        telem = {}
        occs = {}
        digests = {}
        # interleave arms so filesystem-cache drift hits both equally
        for _ in range(nrep):
            for dep in (1, depth):
                wall, t, occ, digest = run_arm(
                    dep, key, batch, recipe, nreal, chunk, d
                )
                results[dep].append(wall)
                if dep not in telem or wall <= min(results[dep]):
                    telem[dep] = t  # keep the best rep's span profile
                    occs[dep] = occ
                digests[dep] = digest

        # median over interleaved reps: the shared-host 9p filesystem and
        # vCPU load both swing ~2x between reps, and a min-of-reps pairs a
        # lucky cheap-write depth-1 rep against a typical depth-2 one;
        # the median compares typical against typical
        med = lambda xs: float(np.median(xs))  # noqa: E731
        t1, t2 = med(results[1]), med(results[depth])
        chunk_nbytes = chunk * npsr * ntoa * np.dtype(
            batch.toas_s.dtype
        ).itemsize
        rec = {
            "bench": "sweep_overlap",
            "platform": jax.default_backend(),
            "nreal": nreal, "chunk": chunk, "npsr": npsr, "ntoa": ntoa,
            "nchunks": nreal // chunk, "pipeline_depth": depth,
            "reduce_fn": None, "durable_writes": True, "nrep": nrep,
            "chunk_result_mb": round(chunk_nbytes / 2**20, 1),
            "depth1_s": round(t1, 3),
            f"depth{depth}_s": round(t2, 3),
            "depth1_all_s": [round(x, 3) for x in results[1]],
            f"depth{depth}_all_s": [round(x, 3) for x in results[depth]],
            "speedup": round(t1 / t2, 3),
            "reduction_pct": round(100.0 * (1.0 - t2 / t1), 1),
            "bit_identical": digests[1] == digests[depth],
            "telemetry": {
                "depth1": telem[1],
                f"depth{depth}": telem[depth],
            },
            # the A/B's 1 - depthN/depth1 wall reduction above is the
            # outcome; this block is the mechanism, measured: per-stage
            # duty, overlap efficiency (wall vs the serial
            # counterfactual of the same stage busy times), and the
            # bottleneck verdict for each arm
            "occupancy": {
                "depth1": occs.get(1),
                f"depth{depth}": occs.get(depth),
            },
            "measured_overlap_efficiency": (occs.get(depth) or {}).get(
                "overlap_efficiency"
            ),
            "timestamp": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
        }
        print(json.dumps(rec))
    finally:
        shutil.rmtree(d, ignore_errors=True)


if __name__ == "__main__":
    main()
