"""On-hardware statistical validation of the f32 device path.

The test suite validates the device ops on CPU in f64 (tests/conftest.py
enables x64); the real chip runs f32 with its own matmul precisions and
RNG lowering. This tool reruns the core statistical acceptance checks ON
THE DEVICE at the bench's dtype and prints one JSON line of evidence —
so "the TPU path is statistically faithful" is a measured per-round
claim, not an extrapolation from CPU tests:

- white+ECORR+RN variance budget: realization variance per pulsar vs the
  exact analytic sum (the test_pipeline_variance_matches_analytic check,
  f32, on device);
- Hellings-Downs recovery: realization-averaged cross-pulsar correlation
  matrix of a GWB-only workload vs the ORF (test_gwb_hellings_downs
  pattern);
- red-noise spectral slope: per-mode average power of an RN-only
  workload, log-log slope vs -gamma.

Usage: python benchmarks/validate_device.py [nreal]
(BENCH_PLATFORM=cpu forces the CPU backend for smoke runs.)
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    nreal = int(sys.argv[1]) if len(sys.argv) > 1 else 2000

    import jax

    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)
    import jax.numpy as jnp

    from pta_replicator_tpu.batch import synthetic_batch
    from pta_replicator_tpu.models import batched as B
    from pta_replicator_tpu.ops.fourier import fourier_frequencies, powerlaw_prior
    from pta_replicator_tpu.ops.orf import hellings_downs_matrix

    npsr, ntoa, nbackend = 32, 2048, 2
    batch = synthetic_batch(npsr=npsr, ntoa=ntoa, nbackend=nbackend, seed=11)
    phat = np.asarray(batch.phat, np.float64)
    locs = np.stack(
        [np.arctan2(phat[:, 1], phat[:, 0]), np.arccos(np.clip(phat[:, 2], -1, 1))],
        axis=1,
    )
    orf = np.asarray(hellings_downs_matrix(locs))
    M = jnp.asarray(np.linalg.cholesky(orf), batch.toas_s.dtype)
    checks = {}

    def fence(x):
        return np.asarray(x)

    # ---- 1. variance budget (white + ECORR + RN + chromatic), exact
    # analytic sum
    efac, log_eq, log_ec = 1.2, -6.3, -6.4
    gamma_rn, log_a_rn = 3.0, -13.6
    gamma_ch, log_a_ch = 2.5, -13.8
    recipe = B.Recipe(
        efac=jnp.full((npsr, nbackend), efac),
        log10_equad=jnp.full((npsr, nbackend), log_eq),
        log10_ecorr=jnp.full((npsr, nbackend), log_ec),
        rn_log10_amplitude=jnp.full(npsr, log_a_rn),
        rn_gamma=jnp.full(npsr, gamma_rn),
        chrom_log10_amplitude=jnp.full(npsr, log_a_ch),
        chrom_gamma=jnp.full(npsr, gamma_ch),
    )
    keys = jax.random.split(jax.random.PRNGKey(1), nreal)
    d = fence(
        jax.jit(jax.vmap(lambda k: B.realization_delays(k, batch, recipe)))(keys)
    )
    meas = d.var(axis=0).mean(axis=-1)
    white = (efac * np.asarray(batch.errors_s)) ** 2 + (efac * 10.0**log_eq) ** 2
    freqs = np.asarray(fourier_frequencies(batch.tspan_s, nmodes=30))

    def rn_var(log_a, gamma):
        prior = np.asarray(
            powerlaw_prior(
                np.repeat(freqs, 2, axis=-1), np.full(npsr, log_a),
                np.full(npsr, gamma), np.asarray(batch.tspan_s),
            )
        )
        return prior.sum(axis=-1) / 2

    # variance scale: ((ref/f)^index)^2 with the default index 2
    chrom_scale2 = ((1400.0 / np.asarray(batch.freqs_mhz)) ** 4).mean(axis=-1)
    want = (
        white.mean(axis=-1)
        + (10.0**log_ec) ** 2
        + rn_var(log_a_rn, gamma_rn)
        + rn_var(log_a_ch, gamma_ch) * chrom_scale2
    )
    dev = float(np.abs(meas / want - 1.0).max())
    # variance-estimator noise ~ sqrt(2/nreal) per pulsar; 0.15 was the
    # margin chosen at nreal=2000 — scale it like the HD check so short
    # smoke runs don't report sampling noise as failure
    tol = 0.15 * max(1.0, (2000.0 / nreal) ** 0.5)
    checks["variance_budget"] = {
        "max_rel_dev": round(dev, 4),
        "tolerance": round(tol, 4),
        "pass": dev < tol,
    }

    # ---- 2. Hellings-Downs correlation recovery (GWB only)
    r_gwb = B.Recipe(
        gwb_log10_amplitude=jnp.asarray(-14.0),
        gwb_gamma=jnp.asarray(4.33),
        orf_cholesky=M,
        gwb_npts=200,
        gwb_howml=4.0,
    )
    d = fence(
        jax.jit(jax.vmap(lambda k: B.realization_delays(k, batch, r_gwb)))(keys)
    )
    cov = np.einsum("ran,rbn->ab", d, d) / d.shape[0] / d.shape[2]
    corr = cov / np.sqrt(np.outer(np.diag(cov), np.diag(cov)))
    dev = float(np.abs(corr - orf / 2.0).max())
    # pure sampling noise: the max-abs deviation of an estimated
    # correlation scales ~1/sqrt(nreal) (0.08 measured at 1500)
    tol = 0.08 * (1500.0 / nreal) ** 0.5
    checks["hellings_downs"] = {
        "max_abs_dev": round(dev, 4),
        "tolerance": round(tol, 4),
        "pass": dev < tol,
    }

    # ---- 3. red-noise spectral slope: project per-mode power, fit slope
    r_rn = B.Recipe(
        rn_log10_amplitude=jnp.full(npsr, -13.8),
        rn_gamma=jnp.full(npsr, 4.33),
    )
    d = jax.jit(jax.vmap(lambda k: B.realization_delays(k, batch, r_rn)))(keys)
    # least-squares projection onto the Fourier basis recovers the drawn
    # coefficients; their realization-averaged power per mode follows the
    # power-law prior
    F, _ = B.red_noise_basis_prior(
        batch, jnp.full(npsr, -13.8), jnp.full(npsr, 4.33)
    )
    FtF = jnp.einsum("pnk,pnl->pkl", F, F, precision="highest")
    Ftd = jnp.einsum("pnk,rpn->rpk", F, d, precision="highest")
    coef = fence(
        jnp.linalg.solve(FtF[None], Ftd[..., None])[..., 0]
    )  # (R, Np, 2K)
    power = (coef**2).mean(axis=0)  # (Np, 2K)
    per_mode = power.reshape(npsr, -1, 2).sum(axis=-1)  # (Np, K)
    logf = np.log(np.asarray(fourier_frequencies(batch.tspan_s, nmodes=30)))
    slope = np.array([
        np.polyfit(logf[p], np.log(per_mode[p]), 1)[0] for p in range(npsr)
    ])
    # E[power_k] ~ f^-gamma; the fitted log-log slope estimates -gamma
    dev = float(np.abs(slope.mean() + 4.33))
    checks["rn_spectral_slope"] = {
        "mean_slope": round(float(slope.mean()), 3),
        "expected": -4.33,
        "tolerance": 0.15,
        "pass": dev < 0.15,
    }

    from pta_replicator_tpu.utils.provenance import (
        EVIDENCE_SCHEMA_VERSION,
        provenance_stamp,
    )

    print(
        json.dumps(
            {
                "device": jax.devices()[0].device_kind,
                "dtype": str(batch.toas_s.dtype),
                "nreal": nreal,
                "npsr": npsr,
                "ntoa": ntoa,
                "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                "all_pass": all(c["pass"] for c in checks.values()),
                "checks": checks,
                # schema_version/git_rev/platform, same stamping as
                # bench.py's BENCH_r*.json (bench-diff gate parity)
                **provenance_stamp(EVIDENCE_SCHEMA_VERSION),
            }
        )
    )


if __name__ == "__main__":
    main()
