"""Measured VPU/MXU ceiling for the bench pipeline (VERDICT r3 item 2).

DESIGN.md section 7 argues the workload is VPU-elementwise/RNG-bound
("transcendentals and RNG rounds cost tens of VPU cycles each") — but
that quantitative step was asserted, not measured. This tool measures
the claimed walls ON THE CHIP at the pipeline's own shapes:

  - normal draws/s (threefry bits + uniform->normal transform), the
    pipeline's dominant primitive (~1M draws/realization),
  - raw threefry bits/s (isolates the generator from the transform),
  - sin/cos and 10**x elementwise throughput (the transcendental rate),
  - fused multiply-add streaming throughput + an HBM triad bandwidth,
  - the (Np,Nf)x(Nf,npts) GWB DFT-synthesis contraction TFLOP/s,
  - the uniform-grid interp gather throughput,

then prices the bench pipeline's per-realization primitive inventory
(counted from the same ``bench.build_workload`` batch/recipe the
headline number uses) at those measured rates. The resulting
``ceiling_real_per_s`` is an attainable-rate UPPER bound: the rate the
chip could sustain if every stage ran at its isolated primitive
throughput with perfect fusion and zero scheduling overhead. Comparing
it against the achieved bench rate closes the roofline argument with
two numbers from the same session.

Usage: python benchmarks/vpu_ceiling.py  (BENCH_PLATFORM=cpu to force
CPU for harness testing). Prints one JSON line.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _timed(fn, *args, reps=None, target_s=0.5):
    """Best-of-2 seconds per call, host-readback fenced (block_until_ready
    returns at dispatch on the tunneled backend)."""
    out = fn(*args)
    np.asarray(out)  # compile + first run
    t0 = time.perf_counter()
    np.asarray(fn(*args))
    once = max(time.perf_counter() - t0, 1e-5)
    if reps is None:
        reps = max(1, min(50, int(target_s / once)))
    best = np.inf
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args)
        np.asarray(out)
        best = min(best, (time.perf_counter() - t0) / reps)
    return best


def main():
    import jax

    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)
    import jax.numpy as jnp

    from bench import build_workload
    from pta_replicator_tpu.models.gwb import dft_synthesis_matrices, gwb_grid

    batch, recipe = build_workload()
    npsr, ntoa = batch.npsr, batch.ntoa_max
    dtype = batch.toas_s.dtype

    out = {
        "device": jax.devices()[0].device_kind,
        "jax_backend": jax.default_backend(),
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "dtype": str(np.dtype(dtype)),
    }

    # ---- primitive throughputs at pipeline shapes -----------------------
    # One realization touches (Np, Nt) = (68, 7758) planes; batch B of
    # them models the chunked sweep (chunk=800 in the headline run).
    B_ = 96
    shape = (B_, npsr, ntoa)  # ~50M elements
    nelem = int(np.prod(shape))
    key = jax.random.PRNGKey(0)

    normal = jax.jit(lambda k: jax.random.normal(k, shape, dtype))
    t = _timed(normal, key)
    out["normal_draws_per_s"] = rate_normal = nelem / t

    bits = jax.jit(lambda k: jax.random.bits(k, shape, "uint32"))
    t = _timed(bits, key)
    out["threefry_bits_per_s"] = nelem * 32 / t
    out["threefry_u32_per_s"] = rate_bits = nelem / t

    x = jax.random.normal(key, shape, dtype)
    sincos = jax.jit(lambda v: jnp.sin(v) + jnp.cos(v))
    t = _timed(sincos, x)
    out["sincos_pairs_per_s"] = rate_sincos = nelem / t

    pow10 = jax.jit(lambda v: 10.0**v)
    t = _timed(pow10, x)
    out["pow10_per_s"] = nelem / t

    fma = jax.jit(lambda v: 1.5 * v + 2.5)
    t = _timed(fma, x)
    out["fma_stream_elems_per_s"] = rate_elem = nelem / t

    y = jax.random.normal(jax.random.PRNGKey(1), shape, dtype)
    triad = jax.jit(lambda a, b: a + 1.5 * b)
    t = _timed(triad, x, y)
    itemsize = np.dtype(dtype).itemsize
    out["hbm_triad_gb_per_s"] = nelem * 3 * itemsize / t / 1e9

    # ---- the one real matmul: GWB DFT synthesis -------------------------
    _, _, f = gwb_grid(batch.start_s - 86400.0, batch.stop_s + 86400.0,
                       recipe.gwb_npts, recipe.gwb_howml)
    nf, npts = len(f), recipe.gwb_npts
    cosm, sinm = dft_synthesis_matrices(nf, npts)
    cosj = jnp.asarray(cosm, dtype)
    sinj = jnp.asarray(sinm, dtype)
    Bm = 16
    re = jax.random.normal(jax.random.fold_in(key, 1), (Bm, npsr, nf), dtype)
    im = jax.random.normal(jax.random.PRNGKey(2), (Bm, npsr, nf), dtype)

    @jax.jit
    def synth(re, im):
        return (
            jnp.einsum("bpf,fn->bpn", re, cosj, precision="highest")
            - jnp.einsum("bpf,fn->bpn", im, sinj, precision="highest")
        )

    t = _timed(synth, re, im)
    synth_flops = 2 * 2 * Bm * npsr * nf * npts  # two (Np,Nf)x(Nf,npts) GEMMs
    out["dft_synth_tflops_per_s"] = rate_mm = synth_flops / t / 1e12

    # ---- interp gathers (GWB grid -> TOA times) -------------------------
    from pta_replicator_tpu.models.batched import uniform_grid_interp

    series = jax.random.normal(jax.random.fold_in(key, 2), (Bm, npsr, npts), dtype)
    tq = jnp.broadcast_to(batch.toas_s, (Bm, npsr, ntoa))
    interp = jax.jit(
        lambda s: uniform_grid_interp(
            tq, batch.start_s - 86400.0, batch.stop_s + 86400.0, s
        )
    )
    t = _timed(interp, series)
    out["interp_elems_per_s"] = rate_interp = Bm * npsr * ntoa / t

    # ---- per-realization primitive inventory (the bench recipe) ---------
    nmodes = recipe.rn_nmodes
    draws = {
        # single combined-variance normal per TOA (models/batched.py)
        "white_noise": npsr * ntoa,
        # one normal per ECORR epoch
        "ecorr": int(np.asarray(jnp.sum(batch.epoch_mask))),
        # 2*nmodes Fourier coefficients per pulsar
        "red_noise": npsr * 2 * nmodes,
        # complex Gaussian per (pulsar, frequency): 2 normals each
        "gwb": 2 * npsr * nf,
    }
    out["draws_per_realization"] = draws
    n_draws = sum(draws.values())

    flops = {
        # ORF mix: complex (Np,Np)@(Np,Nf) = 8 Np^2 Nf real flops
        "gwb_mix": 8 * npsr * npsr * nf,
        "gwb_synth": 2 * 2 * npsr * nf * npts,
        # red-noise basis contraction F(Nt,2m) @ y(2m) per pulsar
        "rn_basis": 2 * npsr * ntoa * 2 * nmodes,
        # quadratic fit: normal equations + subtract, ~3 columns
        "quad_fit": 2 * npsr * ntoa * 3 * 4,
    }
    out["matmul_flops_per_realization"] = flops
    n_flops = sum(flops.values())

    # elementwise passes over (Np, Nt): scale/sum/mask in each stage +
    # the final residualize/reduction (conservative count from the
    # jaxpr-level structure: ~6 per injection stage x 4 stages + 6)
    n_elem_passes = 30
    out["elementwise_passes_assumed"] = n_elem_passes

    t_draws = n_draws / rate_normal
    t_mm = n_flops / (rate_mm * 1e12)
    t_interp = npsr * ntoa / rate_interp
    t_elem = n_elem_passes * npsr * ntoa / rate_elem
    t_total = t_draws + t_mm + t_interp + t_elem
    out["ceiling_breakdown_us_per_realization"] = {
        "draws": round(t_draws * 1e6, 2),
        "matmuls": round(t_mm * 1e6, 2),
        "interp": round(t_interp * 1e6, 2),
        "elementwise": round(t_elem * 1e6, 2),
    }
    out["ceiling_real_per_s"] = round(1.0 / t_total, 1)
    out["note"] = (
        "ceiling = attainable-rate upper bound pricing the pipeline's "
        "primitive inventory at isolated measured throughputs (perfect "
        "fusion, zero scheduling); compare against the bench's achieved "
        "realizations/s from the same session"
    )
    # draw-rate sanity: the normal transform should cost more than raw
    # bits; record the ratio so 'RNG is not the wall' stays re-checkable
    out["normal_vs_bits_ratio"] = round(rate_bits / rate_normal, 2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
