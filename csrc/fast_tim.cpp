// Fast Tempo2 FORMAT-1 tim-file tokenizer.
//
// Native ingest path for the framework's CPU frontier: the reference
// delegates TOA parsing to PINT (simulate.py:155), whose Python-level
// line handling dominates cold-start for ~7.7k-TOA pulsars (SURVEY.md
// section 3.1). This tokenizer handles the plain-TOA fast path in one
// pass; files using stateful directives (INCLUDE/SKIP/TIME/EFAC/EQUAD)
// make it return DIRECTIVE_FOUND so the Python parser, which implements
// their full semantics, takes over.
//
// Epochs are split into (integer MJD, long-double fractional day) so the
// fraction survives a double return slot at ~2e-11 s resolution.
//
// Exposed via ctypes (no pybind11 in the build image).

#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

constexpr int64_t ERR_OPEN = -1;
constexpr int64_t DIRECTIVE_FOUND = -2;
constexpr int64_t ERR_TEXT_OVERFLOW = -3;
constexpr int64_t ERR_WRITE = -4;  // fwrite/fprintf/fclose failed (e.g. ENOSPC)

struct Reader {
    FILE* f;
    char line[8192];
};

bool is_directive(const char* tok) {
    static const char* kDirectives[] = {
        "INCLUDE", "SKIP", "NOSKIP", "TIME", "EFAC", "EQUAD",
    };
    for (const char* d : kDirectives) {
        if (strcasecmp(tok, d) == 0) return true;
    }
    return false;
}

bool is_ignorable(const char* tok) {
    return strcasecmp(tok, "FORMAT") == 0 || strcasecmp(tok, "MODE") == 0 ||
           strcasecmp(tok, "JUMP") == 0 || tok[0] == '#' ||
           (tok[0] == 'C' && tok[1] == '\0');
}

}  // namespace

extern "C" {

// Pass 1: count TOA lines. Returns count >= 0, ERR_OPEN, or
// DIRECTIVE_FOUND if the file needs the stateful Python parser.
int64_t fast_tim_count(const char* path) {
    FILE* f = fopen(path, "r");
    if (!f) return ERR_OPEN;
    char line[8192];
    int64_t n = 0;
    while (fgets(line, sizeof line, f)) {
        char head[64];
        if (sscanf(line, " %63s", head) != 1) continue;
        if (is_directive(head)) {
            fclose(f);
            return DIRECTIVE_FOUND;
        }
        if (is_ignorable(head)) continue;
        // a TOA line has at least 5 whitespace-separated fields
        int fields = 0;
        bool in_tok = false;
        for (const char* p = line; *p; ++p) {
            if (isspace(static_cast<unsigned char>(*p))) {
                in_tok = false;
            } else if (!in_tok) {
                in_tok = true;
                ++fields;
            }
        }
        if (fields >= 5) ++n;
    }
    fclose(f);
    return n;
}

// Pass 2: parse into caller-allocated arrays of length n (from pass 1).
// text buffer receives "label\x1fobs\x1fflagtext\n" per TOA. Returns the
// number parsed, or a negative error code.
int64_t fast_tim_parse(const char* path, int64_t n, int64_t* mjd_day,
                       double* mjd_frac, double* err_us, double* freq_mhz,
                       char* text, int64_t text_cap) {
    FILE* f = fopen(path, "r");
    if (!f) return ERR_OPEN;
    char line[8192];
    int64_t i = 0;
    int64_t tpos = 0;
    while (fgets(line, sizeof line, f) && i < n) {
        // tokenize in place
        char* saveptr = nullptr;
        char* tok[6];
        char work[8192];
        strncpy(work, line, sizeof work - 1);
        work[sizeof work - 1] = '\0';
        char* first = strtok_r(work, " \t\r\n", &saveptr);
        if (!first) continue;
        if (is_ignorable(first)) continue;
        tok[0] = first;
        int ntok = 1;
        while (ntok < 5) {
            char* t = strtok_r(nullptr, " \t\r\n", &saveptr);
            if (!t) break;
            tok[ntok++] = t;
        }
        if (ntok < 5) continue;

        freq_mhz[i] = strtod(tok[1], nullptr);
        // split epoch at the decimal point for lossless storage
        const char* dot = strchr(tok[2], '.');
        if (dot) {
            mjd_day[i] = strtoll(tok[2], nullptr, 10);
            long double frac = strtold(dot, nullptr);
            mjd_frac[i] = static_cast<double>(frac);
        } else {
            mjd_day[i] = strtoll(tok[2], nullptr, 10);
            mjd_frac[i] = 0.0;
        }
        err_us[i] = strtod(tok[3], nullptr);

        // label, observatory, and the raw flag tail
        const char* rest = strtok_r(nullptr, "\r\n", &saveptr);
        int64_t need = static_cast<int64_t>(strlen(tok[0])) + 1 +
                       static_cast<int64_t>(strlen(tok[4])) + 1 +
                       (rest ? static_cast<int64_t>(strlen(rest)) : 0) + 1;
        if (tpos + need >= text_cap) {
            fclose(f);
            return ERR_TEXT_OVERFLOW;
        }
        tpos += snprintf(text + tpos, text_cap - tpos, "%s\x1f%s\x1f%s\n",
                         tok[0], tok[4], rest ? rest : "");
        ++i;
    }
    fclose(f);
    return i;
}

// Fast FORMAT-1 writer — the egress mirror of the parser above. The
// dataset-materialization path (utils/export.py) writes thousands of
// tim files whose per-TOA text is identical across realizations except
// the epoch; Python-side dragon4 formatting dominated at ~45 ms per
// 7.7k-TOA pulsar. The caller passes the realization-invariant line
// parts as text records "prefix\x1fsuffix\n" (prefix = " label freq",
// suffix = "err obs flags") plus the epoch split as integer MJD day and
// 1e-15-day fraction (86 ps resolution, beyond the ~ns tim files carry).
// Returns n, or a negative error code.
int64_t fast_tim_write(const char* path, int64_t n, const int64_t* mjd_day,
                       const int64_t* frac15, const char* text) {
    FILE* f = fopen(path, "w");
    if (!f) return ERR_OPEN;
    // every stdio result is checked: a full disk (ENOSPC) must surface
    // as an error, not a silently truncated tim file
    bool ok = fputs("FORMAT 1\nMODE 1\n", f) >= 0;
    const char* p = text;
    for (int64_t i = 0; ok && i < n; ++i) {
        const char* sep = strchr(p, '\x1f');
        const char* end = strchr(p, '\n');
        if (!sep || !end || sep > end) {
            fclose(f);
            return ERR_TEXT_OVERFLOW;
        }
        const size_t pre = static_cast<size_t>(sep - p);
        const size_t suf = static_cast<size_t>(end - sep - 1);
        ok = fwrite(p, 1, pre, f) == pre &&
             fprintf(f, " %lld.%015lld ", static_cast<long long>(mjd_day[i]),
                     static_cast<long long>(frac15[i])) > 0 &&
             fwrite(sep + 1, 1, suf, f) == suf && fputc('\n', f) != EOF;
        p = end + 1;
    }
    if (fclose(f) != 0) ok = false;  // flush of buffered data can fail too
    return ok ? n : ERR_WRITE;  // distinct from ERR_OPEN: names the failure
}

}  // extern "C"
