"""End-to-end walkthrough: synthesizing a PTA dataset with every signal type.

Script analog of the reference's examples/add_noise.ipynb (cells 0-23):
load or fabricate pulsars, zero residuals, parse the NG15 noise catalog
into per-backend parameter vectors, inject white noise / ECORR / red noise
/ GWB / CW, and decompose the total residuals by ledger entry. Part B runs
the same dataset generation on the batched device path with a 1000-strong
realization axis.

Run:  python examples/add_noise.py [--plot]
"""
import argparse
import sys

import numpy as np

import pta_replicator_tpu as ptr
from pta_replicator_tpu.io import parse_noise_dict

PAR_DIR = "/root/reference/test_partim_small/par"
TIM_DIR = "/root/reference/test_partim_small/tim"
NG15 = "/root/reference/noise_dicts/ng15_dict.json"


def part_a_oracle(plot: bool = False):
    """Reference-style mutate-and-ledger workflow (CPU oracle path)."""
    # --- load three pulsars from par/tim and zero their residuals
    psrs = ptr.load_from_directories(PAR_DIR, TIM_DIR, num_psrs=3)
    for psr in psrs:
        ptr.make_ideal(psr)

    # --- array-wide Hellings-Downs-correlated GWB
    ptr.add_gwb(psrs, log10_amplitude=-14.0, spectral_index=13.0 / 3.0, seed=42)

    # --- per-pulsar noise; simulate_pulsar-style fabricated data would use
    #     the same calls (see fabricate below)
    for i, psr in enumerate(psrs):
        ptr.add_measurement_noise(psr, efac=1.1, log10_equad=np.log10(2e-7), seed=100 + i)
        ptr.add_jitter(psr, log10_ecorr=np.log10(3e-7), coarsegrain=0.1, seed=200 + i)
        ptr.add_red_noise(psr, log10_amplitude=-14.5, spectral_index=3.5, seed=300 + i)
        # beyond-reference: chromatic (DM-like) noise, amplitude at 1400 MHz
        ptr.add_chromatic_noise(psr, log10_amplitude=-14.8, spectral_index=2.5,
                                chromatic_index=2.0, seed=400 + i)

    # --- one resolvable SMBHB continuous wave
    ptr.add_cgw(
        psrs[0], gwtheta=np.pi / 3, gwphi=1.0, mc=5e9, dist=100.0, fgw=2e-8,
        phase0=1.0, psi=0.5, inc=0.7, psrTerm=True, evolve=True,
        tref=53000 * 86400,
    )

    # --- per-backend parameters from the NG15 noise catalog convention
    nd = parse_noise_dict(NG15)
    example = nd["B1855+09"]
    print(f"B1855+09 noise catalog: {len(example['backends'])} backends, "
          f"red noise (gamma={example['red_noise_gamma']:.2f}, "
          f"log10_A={example['red_noise_log10_A']:.2f})")

    # --- the provenance ledger decomposes total residuals by cause
    for psr in psrs:
        rms_us = 1e6 * float(np.sqrt(np.mean(psr.residuals.resids_value ** 2)))
        parts = {k: 1e6 * float(np.std(v)) for k, v in psr.added_signals_time.items()}
        print(f"{psr.name}: residual RMS {rms_us:7.3f} us | per-signal std:",
              {k.split("_", 1)[1]: round(v, 3) for k, v in parts.items()})

    if plot:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig, axes = plt.subplots(len(psrs), 1, figsize=(8, 8), sharex=True)
        for ax, psr in zip(axes, psrs):
            mjd = psr.toas.get_mjds()
            ax.errorbar(mjd, 1e6 * psr.residuals.resids_value,
                        1e6 * psr.toas.errors_s, fmt=".", ms=3, label="total")
            for name, dt in psr.added_signals_time.items():
                ax.plot(mjd, 1e6 * (dt - dt.mean()), lw=1,
                        label=name.split("_", 1)[1])
            ax.set_ylabel(f"{psr.name}\nresidual [us]")
            ax.legend(fontsize=6, ncol=3)
        axes[-1].set_xlabel("MJD")
        fig.savefig("add_noise_decomposition.png", dpi=120)
        print("wrote add_noise_decomposition.png")

    return psrs


def part_b_device(psrs):
    """TPU-native path: freeze once, realize a 1000-strong batch."""
    import jax
    import jax.numpy as jnp

    from pta_replicator_tpu.batch import freeze
    from pta_replicator_tpu.models.batched import Recipe, realize
    from pta_replicator_tpu.ops.coords import pulsar_ra_dec
    from pta_replicator_tpu.ops.orf import hellings_downs_matrix

    batch = freeze(psrs)
    locs = np.array([
        (lambda rd: (rd[0], np.pi / 2 - rd[1]))(pulsar_ra_dec(p.loc, p.name))
        for p in psrs
    ])
    recipe = Recipe(
        efac=jnp.full(batch.npsr, 1.1),
        log10_equad=jnp.full(batch.npsr, np.log10(2e-7)),
        log10_ecorr=jnp.full(batch.npsr, np.log10(3e-7)),
        rn_log10_amplitude=jnp.full(batch.npsr, -14.5),
        rn_gamma=jnp.full(batch.npsr, 3.5),
        chrom_log10_amplitude=jnp.full(batch.npsr, -14.8),
        chrom_gamma=jnp.full(batch.npsr, 2.5),
        gwb_log10_amplitude=jnp.asarray(-14.0),
        gwb_gamma=jnp.asarray(13.0 / 3.0),
        orf_cholesky=jnp.asarray(np.linalg.cholesky(hellings_downs_matrix(locs))),
    )
    res = realize(jax.random.PRNGKey(0), batch, recipe, nreal=1000)
    rms = np.sqrt(np.mean(np.asarray(res) ** 2, axis=-1))  # (1000, Np)
    print("device path: 1000 realizations,",
          "median per-pulsar residual RMS [us]:",
          np.round(1e6 * np.median(rms, axis=0), 3))

    # any realization can be materialized back to a reference-style
    # par/tim dataset for downstream PINT/Tempo2/enterprise pipelines
    # (CLI: --write-partim; native tim writer makes this ~ms per pulsar)
    import os
    import tempfile

    from pta_replicator_tpu.utils import materialize_realizations

    with tempfile.TemporaryDirectory() as d:
        dirs = materialize_realizations(
            psrs, batch, recipe, jax.random.PRNGKey(0), nreal=2, outdir=d,
            # the full run's key layout, so written dataset r carries
            # exactly res[r]'s injected delays (split(key, 2) would be a
            # different stream than the nreal=1000 cube above)
            keys=jax.random.split(jax.random.PRNGKey(0), 1000),
        )
        print(f"materialized {len(dirs)} datasets, e.g. {sorted(os.listdir(dirs[0]))}")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--plot", action="store_true")
    ap.add_argument("--platform", default=None,
                    help="force a jax platform for part B (e.g. 'cpu'); "
                         "default: the session's backend. Deliberately "
                         "not read from JAX_PLATFORMS (hosted "
                         "environments preset it to a remote plugin)")
    args = ap.parse_args()
    psrs = part_a_oracle(plot=args.plot)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    part_b_device(psrs)
    print("done.")
