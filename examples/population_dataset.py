"""Population-derived PTA dataset: loudest SMBHBs as resolvable CWs,
the rest as a free-spectrum GWB, realized at scale on a device mesh.

Script analog of the reference's `add_gwb_plus_outlier_cws` workflow
(/root/reference/pta_replicator/deterministic.py:565-715, Becsy, Cornish
& Kelley 2022): a synthetic SMBHB population stands in for the
holodeck-generated one (same `vals`/`weights` interface), is split into
per-frequency-bin loudest binaries + a residual spectrum, then

  Part A injects it through the mutate-and-ledger oracle path, and
  Part B freezes the array and realizes N independent datasets of the
         same population on a ('real', 'psr') jax.sharding.Mesh.

Run:  python examples/population_dataset.py            # real backend
      JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
          python examples/population_dataset.py        # 8 virtual chips
"""
import numpy as np

import pta_replicator_tpu as ptr
from pta_replicator_tpu.models.population import (
    add_gwb_plus_outlier_cws,
    population_recipe,
    split_population,
)

PAR_DIR = "/root/reference/test_partim_small/par"
TIM_DIR = "/root/reference/test_partim_small/tim"


def synthetic_population(n=40_000, seed=0):
    """A toy SMBHB population in the reference's `vals`/`weights` layout:
    [Mtot_g, Mrat, redz, Fobs_gw_hz] per binary + represented counts."""
    rng = np.random.default_rng(seed)
    msol = 1.988409871e33
    mtot = 10 ** rng.uniform(8.0, 10.0, n) * msol
    mrat = 10 ** rng.uniform(-1.5, 0.0, n)
    redz = rng.uniform(0.05, 1.5, n)
    # population dN/dln f ~ f^{-8/3}: draw via inverse CDF on [1/T, 3e-8]
    u = rng.uniform(size=n)
    flo, fhi = 2e-9, 3e-8
    fo = (flo ** (-5 / 3) + u * (fhi ** (-5 / 3) - flo ** (-5 / 3))) ** (-3 / 5)
    weights = rng.poisson(2.0, n).astype(float)
    return [mtot, mrat, redz, fo], weights


def main():
    psrs = ptr.load_from_directories(PAR_DIR, TIM_DIR, num_psrs=3)
    for p in psrs:
        ptr.make_ideal(p)

    T_obs = (psrs[0].toas.last_mjd - psrs[0].toas.first_mjd) * 86400.0
    fobs = np.arange(1, 25) / T_obs  # bin edges up to the 24th harmonic
    vals, weights = synthetic_population()

    split = split_population(vals, weights, fobs, T_obs, outlier_per_bin=5)
    print(
        f"population split: {split.outlier_fo.size} outlier CWs, "
        f"free-spectrum GWB over {split.f_centers.size} bins "
        f"(hc[0]={split.user_spectrum[0, 1]:.2e})"
    )

    # ---- Part A: oracle path (mutates the pulsars, fills the ledger)
    add_gwb_plus_outlier_cws(
        psrs, vals, weights, fobs, T_obs, outlier_per_bin=5, seed=7
    )
    for p in psrs:
        rms = 1e6 * float(np.sqrt(np.mean(p.residuals.resids_value ** 2)))
        print(f"  {p.name}: residual RMS {rms:8.3f} us, "
              f"ledger = {list(p.added_signals_time)}")

    # ---- Part B: device path — same population, N realizations, sharded
    import jax

    from pta_replicator_tpu.batch import freeze
    from pta_replicator_tpu.ops.coords import pulsar_ra_dec
    from pta_replicator_tpu.ops.orf import assemble_orf
    from pta_replicator_tpu.parallel import make_mesh, sharded_realize

    batch = freeze(psrs)
    locs = np.array(
        [pulsar_ra_dec(p.loc, p.name) for p in psrs], dtype=np.float64
    )
    locs[:, 1] = np.pi / 2 - locs[:, 1]  # dec -> polar angle
    orf = assemble_orf(locs, lmax=0)  # Hellings-Downs
    recipe = population_recipe(
        vals, weights, fobs, T_obs,
        orf_cholesky=np.linalg.cholesky(orf),
        outlier_per_bin=5, seed=7, gwb_npts=200, howml=4.0,
    )

    mesh = make_mesh(n_real=len(jax.devices()), n_psr=1)
    nreal = 8 * mesh.shape["real"]
    res = sharded_realize(
        jax.random.PRNGKey(0), batch, recipe, nreal=nreal, mesh=mesh
    )
    res = np.asarray(res)
    print(
        f"device path: {nreal} realizations on mesh {dict(mesh.shape)} -> "
        f"residuals {res.shape}, per-realization RMS "
        f"{1e6 * np.sqrt((res**2).mean()):.3f} us"
    )


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None,
                    help="force a jax platform for part B (e.g. 'cpu'); "
                         "default: the session's backend")
    args = ap.parse_args()
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    main()
