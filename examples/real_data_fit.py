"""Real-data walkthrough: load a NANOGrav pulsar, inject, refit, persist.

Exercises the standalone timing engine on the real 7,758-TOA B1855+09
fixture (ecliptic astrometry, ELL1+Shapiro binary, 147 DMX windows, FD
terms, a flag-matched JUMP): make_ideal to sub-ns, inject signals,
refit the FULL model with the damped iterated WLS solver, optionally arm
a WAVE harmonic-whitening basis, and write the fitted par/tim pair back
out (loadable by PINT/tempo2/enterprise downstream).

Run:  python examples/real_data_fit.py [outdir]
"""
import os
import sys
import tempfile

import numpy as np

import pta_replicator_tpu as ptr

PAR = "/root/reference/test_partim/par/B1855+09.par"
TIM = "/root/reference/test_partim/tim/B1855+09.tim"


def main(outdir=None):
    psr = ptr.load_pulsar(PAR, TIM)
    print(f"{psr.name}: {psr.toas.ntoas} TOAs, loc keys {sorted(psr.loc)}")

    ptr.make_ideal(psr)
    rms = float(np.std(psr.residuals.resids_value))
    print(f"after make_ideal: residual RMS {rms*1e9:.3f} ns")

    # inject a realistic noise stack (per-backend values would come from
    # a noise dict; scalars keep the walkthrough readable)
    ptr.add_measurement_noise(psr, efac=1.1, seed=11)
    ptr.add_red_noise(psr, -13.8, 3.2, components=30, seed=12)
    print(f"after injection: residual RMS "
          f"{np.std(psr.residuals.resids_value)*1e6:.3f} us")

    # full-model damped refit: spin + ecliptic astrometry (incl. PM/PX)
    # + DMX + FD + JUMP + binary, iterated to convergence
    psr.fit(fitter="wls", niter=3)
    print(f"after full-model refit: residual RMS "
          f"{np.std(psr.residuals.resids_value)*1e6:.3f} us")
    moved = {
        k: v for k, v in sorted(
            psr.fit_results.items(), key=lambda kv: -abs(kv[1])
        )[:5]
    }
    print(f"largest fitted corrections: { {k: f'{v:.3e}' for k, v in moved.items()} }")

    # optional: arm a WAVE harmonic-whitening basis (tempo2/PINT WAVE
    # model) so a further fit can absorb smooth unmodeled structure
    mjds = psr.toas.get_mjds().astype(np.float64)
    span = float(mjds.max() - mjds.min())
    psr.par.ensure_waves(10, om=2 * np.pi / (1.05 * span),
                         epoch=float(mjds.min()))
    psr.model = type(psr.model).from_par(psr.par)
    psr.fit(fitter="wls", niter=2)
    print(f"after WAVE-whitened refit: residual RMS "
          f"{np.std(psr.residuals.resids_value)*1e6:.3f} us; "
          f"wave3 amplitudes {psr.par.waves[2]}")

    # persist the fitted dataset — the par keeps every original line
    # (DMX windows, JUMP, binary) plus the fitted values and WAVE terms
    d = outdir or tempfile.mkdtemp(prefix="b1855_fit_")
    psr.write_partim(os.path.join(d, "B1855+09_fit.par"),
                     os.path.join(d, "B1855+09_fit.tim"))
    back = ptr.load_pulsar(os.path.join(d, "B1855+09_fit.par"),
                           os.path.join(d, "B1855+09_fit.tim"))
    print(f"round-trip: {back.toas.ntoas} TOAs, "
          f"{len(back.par.waves)} WAVE terms, wrote to {d}")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else None)
