"""Scale-out walkthrough: realization sweeps across a device mesh.

What the reference cannot do at all (SURVEY.md section 2: no
parallelism beyond a numba thread pool), shown end to end here:

1. freeze a pulsar array once,
2. build a ('real', 'psr') jax.sharding.Mesh over every visible device,
3. run the same realization recipe through BOTH mesh engines — the
   constraint-based one (XLA places the collectives) and the explicit
   shard_map one (zero collectives; the natural multi-host form) — and
   check they agree,
4. materialize only this host's shards, the per-host egress pattern a
   multi-host deployment uses (each host persists its own realizations).

Run on any machine (the virtual-device trick below gives 8 CPU
"devices"); on a real v5e-8 slice delete the XLA_FLAGS line and the same
code spans the 8 chips. For true multi-host, run one copy of this script
per host after `distributed.initialize()` — see
tests/test_distributed_multiprocess.py for a working two-process
rehearsal over localhost GRPC.

Run:  python examples/scale_out.py
"""
import os

# SCALE_OUT_PLATFORM=tpu (on a real slice) skips the virtual-device
# setup. Deliberately NOT read from JAX_PLATFORMS: hosted environments
# preset that to their own accelerator plugin, and inheriting it here
# would silently point the walkthrough at remote hardware.
PLATFORM = os.environ.get("SCALE_OUT_PLATFORM", "cpu")
if PLATFORM == "cpu":
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np
import jax
import jax.numpy as jnp

jax.config.update("jax_platforms", PLATFORM)
jax.devices()  # initialize the chosen backend NOW (a pre-registered
# remote-TPU plugin can otherwise capture a later first-use)

import pta_replicator_tpu as ptr
from pta_replicator_tpu.batch import freeze
from pta_replicator_tpu.models.batched import Recipe
from pta_replicator_tpu.ops.orf import hellings_downs_matrix
from pta_replicator_tpu.parallel import (
    distributed,
    make_mesh,
    shardmap_realize,
    sharded_realize,
)

PAR_DIR = "/root/reference/test_partim_small/par"
TIM_DIR = "/root/reference/test_partim_small/tim"


def main():
    # 1. ingest once on CPU, freeze to device arrays
    psrs = ptr.load_from_directories(PAR_DIR, TIM_DIR)
    for psr in psrs:
        ptr.make_ideal(psr)
    # pad to 4 pulsars so the 'psr' mesh axis divides evenly: re-freeze
    # the first pulsar under a new name (real arrays would have Np >> 8)
    batch = freeze(psrs + [psrs[0]])
    print(f"frozen: {batch.npsr} psrs x {batch.ntoa_max} TOAs, "
          f"backends {batch.backend_names}")

    phat = np.asarray(batch.phat)
    locs = np.stack(
        [np.arctan2(phat[:, 1], phat[:, 0]), np.arccos(phat[:, 2])], axis=1
    )
    recipe = Recipe(
        efac=jnp.ones(batch.npsr),
        log10_equad=jnp.full(batch.npsr, -6.7),
        rn_log10_amplitude=jnp.full(batch.npsr, -14.0),
        rn_gamma=jnp.full(batch.npsr, 13.0 / 3.0),
        gwb_log10_amplitude=jnp.asarray(-14.0),
        gwb_gamma=jnp.asarray(13.0 / 3.0),
        orf_cholesky=jnp.asarray(
            np.linalg.cholesky(hellings_downs_matrix(locs))
        ),
        gwb_npts=120,
        gwb_howml=4.0,
    )

    # 2. one 2-D mesh over all devices: realizations data-parallel,
    #    pulsars model-parallel
    topo = distributed.initialize()  # no-op single-process; GRPC multi-host
    mesh = make_mesh(n_real=topo["global_device_count"] // 2, n_psr=2)
    print(f"mesh: {dict(mesh.shape)} over {topo['global_device_count']} devices")

    # 3. both engines, same numbers
    key = jax.random.PRNGKey(0)
    nreal = 32
    a = sharded_realize(key, batch, recipe, nreal=nreal, mesh=mesh, fit=True)
    b = shardmap_realize(key, batch, recipe, nreal=nreal, mesh=mesh, fit=True)
    rms = float(jnp.sqrt(jnp.mean(a**2)))
    dev = float(jnp.max(jnp.abs(a - b)))
    print(f"residual rms {rms:.3e} s; engine agreement {dev:.3e} s")
    assert dev <= 1e-4 * rms

    # 4. per-host egress: this host's realizations only
    local = distributed.local_realizations(a)
    print(f"local block: {local.shape} (host {topo['process_index']} of "
          f"{topo['process_count']})")


if __name__ == "__main__":
    main()
