"""pta_replicator_tpu — a TPU-native (JAX/XLA) framework for synthesizing
simulated pulsar-timing-array datasets.

Standalone re-design of the capabilities of ``bencebecsy/pta_replicator``:
load or fabricate per-pulsar TOAs, zero the residuals, then inject white
measurement noise (EFAC/EQUAD), epoch-correlated jitter (ECORR), power-law
red noise, Hellings-Downs / anisotropic correlated GW backgrounds,
continuous waves (single sources and large catalogs), bursts, bursts with
memory, and arbitrary transients.

Two execution paths share one set of math kernels:

* the **CPU oracle path** (:mod:`.simulate` + the ``add_*`` operators)
  mirrors the reference's mutate-and-ledger API and its legacy-RNG draw
  order, for exact regression parity;
* the **device path** (:mod:`.batch`) freezes pulsars into padded arrays
  and evaluates every injection as a pure, key-driven JAX function
  batched over (pulsar x realization) and sharded over a device mesh.
"""

__version__ = "0.1.0"

from .simulate import (
    SimulatedPulsar,
    Residuals,
    load_pulsar,
    load_from_directories,
    simulate_pulsar,
    make_ideal,
)
from .models import (
    add_measurement_noise,
    add_jitter,
    add_chromatic_noise,
    add_red_noise,
    add_gwb,
    add_cgw,
    add_catalog_of_cws,
    add_burst,
    add_noise_transient,
    add_gw_memory,
    add_gwb_plus_outlier_cws,
    population_recipe,
    split_population,
)

__all__ = [
    "SimulatedPulsar",
    "Residuals",
    "load_pulsar",
    "load_from_directories",
    "simulate_pulsar",
    "make_ideal",
    "add_measurement_noise",
    "add_jitter",
    "add_chromatic_noise",
    "add_red_noise",
    "add_gwb",
    "add_cgw",
    "add_catalog_of_cws",
    "add_burst",
    "add_noise_transient",
    "add_gw_memory",
    "add_gwb_plus_outlier_cws",
    "population_recipe",
    "split_population",
]
