"""Command-line dataset runner: par/tim + JSON recipe → realizations.

The reference has no CLI or config runner (SURVEY.md §1 L5 — its "API"
is notebook imports). This runner covers the common batch use end to
end:

    python -m pta_replicator_tpu realize \
        --pardir par/ --timdir tim/ --recipe recipe.json \
        --nreal 1000 --out residuals.npz [--fit] [--sharded] \
        [--checkpoint sweep.npz] [--seed 0]

recipe.json maps 1:1 onto models.batched.Recipe, with scalars, lists, or
nested lists for array leaves, plus one extra key:

    "orf": "hd" (default)            Hellings-Downs correlations
           "none"                    uncorrelated common process
           {"lmax": L, "clm": [...]} anisotropic spherical-harmonic ORF

`info` prints the loaded array's shape/epochs/backends as JSON.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np


def _build_recipe(spec: dict, psrs, locs=None):
    """JSON recipe spec -> Recipe. Sky locations for the ORF come from
    ``psrs`` (the par-file path) or an explicit ``locs`` (azimuth,
    colatitude) array (the synthetic path — the likelihood subcommand
    derives them from the frozen batch's direction vectors)."""
    import jax.numpy as jnp

    from .models.batched import Recipe
    from .ops.coords import pulsar_ra_dec
    from .ops.orf import assemble_orf

    spec = dict(spec)
    orf_mode = spec.pop("orf", "hd")
    lmax_ok = (
        isinstance(orf_mode, dict)
        and isinstance(orf_mode.get("lmax"), int)
        and not isinstance(orf_mode.get("lmax"), bool)
    )
    if not (orf_mode in ("hd", "none") or lmax_ok):
        raise SystemExit(
            'recipe key "orf" must be "hd", "none", or an object with an '
            f'integer "lmax" key (and optional "clm"); got {orf_mode!r}'
        )
    static_names = {
        "tnequad", "gwb_turnover", "rn_nmodes", "rn_logf", "rn_pshift",
        "rn_libstempo", "chrom_nmodes", "chrom_ref_freq_mhz",
        "gwb_npts", "gwb_howml",
        "cgw_tref_s", "cgw_chunk", "cgw_backend", "cgw_psr_term",
        "cgw_evolve", "cgw_phase_approx", "transient_psr",
        "gwb_f0", "gwb_beta", "gwb_power",
    }
    kwargs = {}
    for key, val in spec.items():
        if key not in Recipe.__dataclass_fields__:
            raise SystemExit(f"recipe key {key!r} is not a Recipe field")
        kwargs[key] = val if key in static_names else jnp.asarray(val)

    if "orf_cholesky" not in kwargs and orf_mode != "none":
        if locs is None:
            locs = np.zeros((len(psrs), 2))
            for i, p in enumerate(psrs):
                ra, dec = pulsar_ra_dec(p.loc, p.name)
                locs[i] = ra, np.pi / 2 - dec
        if orf_mode == "hd":
            orf = assemble_orf(locs, lmax=0)
        else:
            orf = assemble_orf(
                locs, clm=orf_mode.get("clm"), lmax=int(orf_mode["lmax"])
            )
        kwargs["orf_cholesky"] = jnp.asarray(
            np.linalg.cholesky(np.asarray(orf, np.float64))
        )
    return Recipe(**kwargs)


def main(argv=None):
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if argv[:1] == ["lint"]:
        # graftlint is jax-free and must stay fast: bypass the argparse
        # tree (and the --platform plumbing) entirely
        from .analysis.cli import main as lint_main

        rc = lint_main(argv[1:])
        if rc:
            raise SystemExit(rc)
        return

    ap = argparse.ArgumentParser(prog="python -m pta_replicator_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser(
        "lint", help="graftlint: static JAX/thread/telemetry invariant "
                     "checker (see `lint --help`)")

    for name in ("realize", "info"):
        p = sub.add_parser(name)
        p.add_argument("--pardir", required=True)
        p.add_argument("--timdir", required=True)
        p.add_argument("--num-psrs", type=int, default=None)
        p.add_argument("--telemetry", default=None, metavar="DIR",
                       help="capture structured telemetry (spans, metrics, "
                            "JAX compile accounting) into DIR; inspect with "
                            "the 'report' subcommand")
    p = sub.add_parser(
        "likelihood",
        help="rank-reduced GP likelihood over a realization bank: "
             "hyperparameter grids, MAP+Fisher fits, and a "
             "request-batched serving demo with SLO stats "
             "(docs/likelihood.md)")
    p.add_argument("--bank", required=True,
                   help="realization bank: a sweep checkpoint "
                        "(consolidated npz or in-progress chunk files "
                        "from `realize --checkpoint`) or a plain .npy "
                        "residual cube (R, Np, Nt)")
    p.add_argument("--recipe", required=True,
                   help="JSON recipe (the NOISE MODEL to evaluate "
                        "under — normally the recipe the bank was "
                        "synthesized with)")
    p.add_argument("--pardir", default=None)
    p.add_argument("--timdir", default=None)
    p.add_argument("--num-psrs", type=int, default=None)
    p.add_argument("--synthetic", default=None, metavar="NPSRxNTOA",
                   help="use a synthetic frozen batch (e.g. 10x512, "
                        "seeded like the bench workload) instead of "
                        "ingesting --pardir/--timdir — the batch must "
                        "match whatever produced the bank")
    p.add_argument("--synthetic-seed", type=int, default=0)
    p.add_argument("--grid", action="append", default=[],
                   metavar="FIELD=LO:HI:N",
                   help="hyperparameter grid axis (repeatable; axes "
                        "combine as a cartesian product), e.g. "
                        "rn_log10_amplitude=-14.5:-13:16")
    p.add_argument("--map", action="append", default=[], dest="map_params",
                   metavar="FIELD=X0",
                   help="MAP+Fisher fit over these fields from the "
                        "given start values (repeatable)")
    p.add_argument("--real-index", type=int, default=0,
                   help="bank row the MAP fit runs on (default 0)")
    p.add_argument("--serve", type=int, default=0, metavar="N",
                   help="serving demo: N requests sampled over the "
                        "--grid axes, submitted from --clients threads "
                        "through the request-batched server; prints "
                        "the SLO stats block")
    p.add_argument("--clients", type=int, default=4)
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--max-delay-ms", type=float, default=5.0)
    p.add_argument("--max-queue", type=int, default=None,
                   help="bounded request queue: submissions past this "
                        "are rejected (ServerSaturated) instead of "
                        "growing the queue — the serving demo reports "
                        "the rejected count in its SLO block")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="per-request queue deadline: a request unserved "
                        "past this raises DeadlineExpired instead of "
                        "being evaluated late")
    p.add_argument("--telemetry", default=None, metavar="DIR")
    p.add_argument("--out", default=None,
                   help="write the result JSON here instead of stdout")
    p = sub.add_parser(
        "scenario",
        help="declarative scenario layer (docs/scenarios.md): validate/"
             "compile specs, run one through the sweep with its "
             "provenance stamped, fuzz the batched engine against the "
             "oracle models/ path, or replay a saved failing spec")
    p.add_argument("action",
                   choices=("validate", "compile", "run", "fuzz",
                            "replay"),
                   help="validate: check spec files and print their "
                        "content hashes; compile: spec -> workload "
                        "summary (--out writes the static-plane npz); "
                        "run: compile + checkpointed sweep with the "
                        "spec hash stamped into the sidecar; fuzz: "
                        "random scenarios through the batched-vs-"
                        "oracle differential (exit 1 on any "
                        "disagreement); replay: re-run one saved spec "
                        "through the differential")
    p.add_argument("specs", nargs="*", metavar="SPEC",
                   help="scenario spec file(s), .json or .toml")
    p.add_argument("--out", default=None,
                   help="compile: write the static plane + fingerprint "
                        "npz here; run: write the result cube npz here")
    p.add_argument("--checkpoint", default=None,
                   help="run: resumable sweep checkpoint path "
                        "(default: <out>.sweep.npz)")
    p.add_argument("--nreal", type=int, default=None,
                   help="run: override the spec's sweep.nreal")
    p.add_argument("--n", type=int, default=50,
                   help="fuzz: scenarios to generate (default 50)")
    p.add_argument("--root-seed", type=int, default=0,
                   help="fuzz: generator root seed (scenario K derives "
                        "via fold_in(root, K))")
    p.add_argument("--out-dir", default="scenario_fuzz_failures",
                   help="fuzz: directory for shrunk replayable failing "
                        "specs (default: ./scenario_fuzz_failures/, "
                        "created only when a disagreement is found)")
    p.add_argument("--sweep-every", type=int, default=0,
                   help="fuzz: run the pipelined-vs-sync sweep "
                        "byte-identity arm on every K-th scenario "
                        "that carries a sweep plan (0 = off)")
    p.add_argument("--fast", action="store_true",
                   help="fuzz: the CI arm — 8 scenarios, fixed seed, "
                        "sweep-identity every 4th")
    p.add_argument("--telemetry", default=None, metavar="DIR")
    p = sub.add_parser(
        "report", help="pretty-print a captured --telemetry directory")
    p.add_argument("dir", help="telemetry directory (events.jsonl + "
                               "metrics.json)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable aggregate instead of the tree")
    p.add_argument("--min-ms", type=float, default=0.0,
                   help="hide span paths with total wall below this")
    p = sub.add_parser(
        "watch", help="tail a live run's progress.json heartbeat (one "
                      "line per tick, including the stage-occupancy "
                      "bottleneck verdict; exits when the run finishes "
                      "or leaves a postmortem)")
    p.add_argument("dir", help="the run's --telemetry directory")
    p.add_argument("--interval", type=float, default=2.0, metavar="S",
                   help="poll period in seconds (default 2)")
    p.add_argument("--once", action="store_true",
                   help="print the current heartbeat and exit (for "
                        "scripts/cron: exit 3 when there is none)")
    p.add_argument("--serve", type=int, default=None, metavar="PORT",
                   help="also expose the live run over HTTP while "
                        "watching: /metrics (Prometheus text), "
                        "/progress, /series, /slo (error budgets), "
                        "/healthz + /readyz (503 on a fast-burn SLO "
                        "breach, docs/tracing.md) — read-only, torn-"
                        "read-safe against the sampler (docs/"
                        "observability.md 'Scraping a live run'). "
                        "Port 0 picks an ephemeral port (printed). "
                        "The server lives for the duration of the "
                        "watch")
    p.add_argument("--bind", default="127.0.0.1", metavar="HOST",
                   help="interface for --serve (default loopback; "
                        "0.0.0.0 exposes the run to the network)")
    p = sub.add_parser(
        "timeline", help="merge a capture's host spans, per-device "
                         "stage tracks, chunk flow links, and any "
                         "registered jax.profiler device traces into "
                         "ONE clock-aligned chrome://tracing file")
    p.add_argument("dir", help="the run's --telemetry directory")
    p.add_argument("--out", default=None, metavar="PATH",
                   help="output path (default DIR/timeline.json)")
    p = sub.add_parser(
        "postmortem", help="render the black box a killed/crashed run "
                           "left in its telemetry directory")
    p.add_argument("dir", help="the run's --telemetry directory")
    p = sub.add_parser(
        "bench-diff", help="diff bench.py JSONs (oldest first): delta "
                           "table with pass/warn/fail verdicts; exits "
                           "nonzero on a regression past --threshold")
    p.add_argument("files", nargs="+", metavar="BENCH_JSON",
                   help="two or more bench JSONs (raw bench.py output "
                        "or the wrapped BENCH_r*.json series)")
    p.add_argument("--threshold", type=float, default=0.10,
                   help="relative regression gate (default 0.10 = 10%%; "
                        "half of it is the warn band)")
    p = sub.add_parser(
        "critpath", help="critical-path attribution over a finished "
                         "capture: per-chunk span-DAG reconstruction, "
                         "busy/blocked/queue-wait decomposition, mesh "
                         "straggler spread, and a ranked bottleneck "
                         "verdict with estimated savings — written as "
                         "DIR/critpath.json (served at /critpath, "
                         "rendered in `report`, annotated in "
                         "`timeline`)")
    p.add_argument("dir", help="the run's --telemetry directory")
    p.add_argument("--out", default=None, metavar="PATH",
                   help="output path (default DIR/critpath.json)")
    p.add_argument("--json", action="store_true",
                   help="print the full critpath.json document instead "
                        "of the rendered verdict")
    p = sub.add_parser(
        "perf", help="cross-round performance ledger over the committed "
                     "bench artifacts: ingest (write PERF_LEDGER.json), "
                     "trend (per-metric sparkline trajectories), gate "
                     "(fail on any metric monotonically regressing over "
                     "the last --window rounds — the slow-leak class "
                     "the pairwise bench-diff cannot see)")
    p.add_argument("action", choices=("ingest", "trend", "gate"),
                   help="ingest: rebuild + write ROOT/PERF_LEDGER.json; "
                        "trend: render trajectories; gate: exit 1 on a "
                        "windowed monotone regression (reasons to "
                        "stderr)")
    p.add_argument("pattern", nargs="?", default=None,
                   help="trend: only metrics containing this substring")
    p.add_argument("--root", default=".", metavar="DIR",
                   help="directory holding the round-stamped artifacts "
                        "(default: current directory)")
    p.add_argument("--out", default=None, metavar="PATH",
                   help="ingest: ledger output path "
                        "(default ROOT/PERF_LEDGER.json)")
    p.add_argument("--window", type=int, default=3, metavar="K",
                   help="gate: rounds a metric must worsen across "
                        "monotonically to fail (default 3)")
    p.add_argument("--min-total", type=float, default=None,
                   metavar="REL",
                   help="gate: cumulative relative decline across the "
                        "window below which a monotone drift is not "
                        "flagged (default 0.05)")
    p = sub.add_parser(
        "numerics", help="render a capture's precision ledger "
                         "(numerics.json): per-probe-site non-finite "
                         "counts, |max| watermarks, overflow headroom "
                         "in bits, shadow-oracle drift per family, and "
                         "the per-kernel bf16 ladder-readiness verdict "
                         "(docs/numerics.md)")
    p.add_argument("action", choices=("report",),
                   help="report: pretty-print DIR/numerics.json")
    p.add_argument("dir", help="the run's --telemetry directory")
    p = sub.choices["realize"]
    p.add_argument("--device-trace", action="store_true",
                   help="also capture an XLA device trace (jax.profiler) "
                        "around the run, into <telemetry dir>/xla_trace, "
                        "registered as a capture artifact in meta.json "
                        "(view in TensorBoard/Perfetto); requires "
                        "--telemetry")
    p.add_argument("--recipe", required=True, help="JSON recipe file")
    p.add_argument("--nreal", type=int, default=100)
    p.add_argument("--out", required=True)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--fit", action="store_true")
    p.add_argument("--full-fit", action="store_true",
                   help="per-realization FULL-model refit (spin, "
                        "astrometry, DMX/DM, FD, binary, JUMP columns "
                        "from the loaded par files) instead of the "
                        "quadratic proxy; implies --fit")
    p.add_argument("--gls-fit", action="store_true",
                   help="weight the full-model refit by the recipe's "
                        "own noise model (nested-Woodbury GLS: white + "
                        "ECORR + achromatic/chromatic red noise) "
                        "instead of plain WLS; implies --full-fit")
    p.add_argument("--sharded", action="store_true",
                   help="shard realizations over all visible devices")
    p.add_argument("--mesh-shape", default=None, metavar="RxP",
                   help="explicit ('real','psr') mesh shape for the "
                        "sharded path, e.g. 4x2 (npsr must divide P); "
                        "default: all devices on the realization axis. "
                        "Implies --sharded. A sharded checkpointed "
                        "sweep writes per-shard chunk archives "
                        "(docs/performance.md 'Sharding the sweep')")
    p.add_argument("--checkpoint", default=None,
                   help="resumable sweep checkpoint path (chunked)")
    p.add_argument("--chunk", type=int, default=256)
    p.add_argument("--pipeline-depth", type=int, default=2,
                   help="chunks in flight for a checkpointed sweep: 2 "
                        "(default) overlaps device compute with host "
                        "readback and checkpoint I/O (double buffering; "
                        "device memory bound = depth x chunk result "
                        "size); 1 runs the synchronous debug loop. "
                        "Results are identical at every depth.")
    p.add_argument("--fused-stream", action="store_true",
                   help="run a checkpointed sweep as ONE end-to-end "
                        "stage graph: each chunk's deterministic "
                        "(streamed-CW) delays are rebuilt on a "
                        "static_build stage overlapped with earlier "
                        "chunks' compute, readback, and checkpoint "
                        "writes (docs/streaming.md). Composes with "
                        "--mesh-shape: one fused graph runs tile build, "
                        "per-device staging, sharded compute, per-shard "
                        "readback, and parallel per-shard checkpoint "
                        "writers. Byte-identical results; requires "
                        "--pipeline-depth >= 2")
    p.add_argument("--drain-timeout", type=float, default=900.0,
                   metavar="S",
                   help="fail a pipelined sweep when a single chunk "
                        "readback or checkpoint write exceeds S seconds "
                        "(wedged tunnel/filesystem). Raise it for "
                        "legitimately slow large-chunk readbacks; "
                        "<= 0 disables the deadline")
    p.add_argument("--chunk-retries", type=int, default=2,
                   help="transient chunk failures absorbed per failing "
                        "chunk by resuming from the checkpoint sidecar "
                        "(exponential backoff; docs/robustness.md). 0 "
                        "restores fail-fast")
    p.add_argument("--write-partim", default=None, metavar="DIR",
                   help="also materialize realizations as par/tim datasets "
                        "under DIR/real{r:05d}/ (pre-fit injected delays, "
                        "same key layout as the residual cube)")
    p.add_argument("--write-max", type=int, default=16,
                   help="cap on datasets written by --write-partim")
    for sp in sub.choices.values():
        sp.add_argument(
            "--faults", default=None, metavar="SCHEDULE",
            help="arm a fault-injection schedule (chaos testing, "
                 "docs/robustness.md), e.g. 'drain:raise@chunk=2;"
                 "checkpoint_write:torn@call=3'. Equivalent env: "
                 "PTA_FAULTS")
        sp.add_argument("--faults-seed", type=int, default=0,
                        help="seed for probabilistic fault triggers")
        sp.add_argument(
            "--platform", default=None,
            help="force a jax platform (e.g. 'cpu'); default: the "
                 "session's backend. Deliberately not read from "
                 "JAX_PLATFORMS (hosted environments preset it to a "
                 "remote plugin that hangs when unreachable)")
    args = ap.parse_args(argv)

    # chaos arming: the --faults flag wins, the PTA_FAULTS env var
    # covers entry points that never parse flags (tests, benches).
    # Disarmed (the overwhelmingly common case) this is one None check
    # per injection site at runtime (faults/inject.py)
    from .faults import inject as _faults_inject

    if getattr(args, "faults", None):
        _faults_inject.arm(args.faults, seed=args.faults_seed)
    else:
        _faults_inject.arm_from_env()

    if args.cmd == "report":
        from .obs.report import print_report

        print_report(args.dir, min_ms=args.min_ms, as_json=args.json)
        return
    if args.cmd == "watch":
        from .obs.report import watch_progress

        server = None
        if args.serve is not None:
            from .obs.serve import serve_directory, serve_url

            server = serve_directory(args.dir, args.serve,
                                     host=args.bind, background=True)
            print(f"serving {serve_url(server)} "
                  "(/metrics /progress /series /slo /readyz)",
                  file=sys.stderr)
        try:
            rc = watch_progress(args.dir, interval=args.interval,
                                once=args.once)
        finally:
            if server is not None:
                server.shutdown()
                server.server_close()
        if rc:
            raise SystemExit(rc)
        return
    if args.cmd == "timeline":
        from .obs.timeline import build_timeline, write_timeline

        doc = build_timeline(args.dir)
        out = write_timeline(args.dir, out=args.out, doc=doc)
        summary = dict(doc.get("otherData") or {})
        summary["out"] = out
        summary["events"] = len(doc.get("traceEvents") or [])
        print(json.dumps(summary, indent=1, sort_keys=True))
        if summary.get("problems"):
            for problem in summary["problems"]:
                print(f"warning: {problem}", file=sys.stderr)
        return
    if args.cmd == "postmortem":
        from .obs.report import print_postmortem

        print_postmortem(args.dir)
        return
    if args.cmd == "bench-diff":
        if len(args.files) < 2:
            print("bench-diff needs at least two files", file=sys.stderr)
            raise SystemExit(2)  # usage error, not "regressed" (rc 1)
        from .obs.regress import SchemaMismatch, bench_diff

        try:
            table, _summary, rc = bench_diff(
                args.files, threshold=args.threshold
            )
        except SchemaMismatch as exc:
            # exit 2 (unusable inputs), NOT 1: rc 1 is reserved for "a
            # metric regressed" and CI keys on that distinction
            print(f"bench-diff: {exc}", file=sys.stderr)
            raise SystemExit(2)
        print(table)
        if rc:
            raise SystemExit(rc)
        return
    if args.cmd == "critpath":
        from .obs import critpath as _critpath

        doc = _critpath.analyze_capture(args.dir)
        if doc is None:
            # exit 2 (unusable input), matching bench-diff's convention:
            # rc 1 would read as "a gate failed" to CI
            print(
                f"critpath: {args.dir}: no stage spans to attribute "
                "(missing events.jsonl, or the run never touched a "
                "staged executor)",
                file=sys.stderr,
            )
            raise SystemExit(2)
        out = _critpath.write_critpath(args.dir, out=args.out, doc=doc)
        if args.json:
            print(json.dumps(doc, indent=1, sort_keys=True))
        else:
            print(_critpath.render_critpath(doc))
        print(f"critpath: wrote {out}", file=sys.stderr)
        return
    if args.cmd == "perf":
        from .obs import ledger as _ledger

        led = _ledger.build_ledger(args.root)
        if args.action == "ingest":
            out = _ledger.write_ledger(args.root, out=args.out,
                                       ledger=led)
            print(
                f"perf ingest: {led['rounds']} round(s), "
                f"{len(led['metrics'])} metric trajectories, "
                f"{len(led['refused'])} refused -> {out}"
            )
            for base, reason in sorted(led["refused"].items()):
                print(f"  refused {base}: {reason}", file=sys.stderr)
        elif args.action == "trend":
            print(_ledger.render_trend(led, pattern=args.pattern))
        else:
            kwargs = {}
            if args.min_total is not None:
                kwargs["min_total"] = args.min_total
            summary, _flagged, rc = _ledger.gate(
                led, window=args.window, **kwargs
            )
            # reasons to stderr on failure, the bench gates' convention
            print(summary, file=sys.stderr if rc else sys.stdout)
            if rc:
                raise SystemExit(rc)
        return
    if args.cmd == "numerics":
        # jax-free like report/watch/perf: the ledger carries its drift
        # tolerances stamped at sample time, so rendering never needs
        # the fuzzer (or jax) on the analysis box
        from .obs import numerics as _numerics

        print(_numerics.render_report(args.dir))
        return

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    telemetry = getattr(args, "telemetry", None)
    if getattr(args, "device_trace", False) and not telemetry:
        raise SystemExit("--device-trace requires --telemetry DIR (the "
                         "trace is an artifact of the capture)")
    if not telemetry:
        return _run_command(args)

    # capture mode: stream spans/metrics (and JAX compile accounting)
    # into the telemetry dir; flush artifacts even when the run raises
    import contextlib

    from . import obs

    obs.start_capture(telemetry)
    try:
        xla_trace = (
            obs.devprof.device_trace()
            if getattr(args, "device_trace", False)
            else contextlib.nullcontext()
        )
        with obs.span(args.cmd), xla_trace:
            return _run_command(args)
    finally:
        obs.finish_capture(context={
            "argv": list(argv) if argv is not None else sys.argv[1:],
        })


def _make_mesh_arg(mesh_shape):
    """A ('real','psr') mesh from the --mesh-shape argument ("RxP"), or
    the all-devices-on-'real' default when it is None."""
    from .parallel import make_mesh

    if not mesh_shape:
        return make_mesh()
    try:
        n_real, n_psr = (int(x) for x in mesh_shape.lower().split("x"))
    except ValueError:
        raise SystemExit(
            f"--mesh-shape must look like 4x2 (got {mesh_shape!r})"
        )
    return make_mesh(n_real, n_psr)


def _axis_specs(pairs, kind):
    """Parse FIELD=LO:HI:N / FIELD=X0 CLI axis specs."""
    out = {}
    for spec in pairs:
        if "=" not in spec:
            raise SystemExit(f"--{kind} must look like FIELD=..., got "
                             f"{spec!r}")
        field, _, val = spec.partition("=")
        if kind == "grid":
            parts = val.split(":")
            if len(parts) != 3:
                raise SystemExit(
                    f"--grid axis must be FIELD=LO:HI:N, got {spec!r}"
                )
            lo, hi, n = float(parts[0]), float(parts[1]), int(parts[2])
            out[field] = np.linspace(lo, hi, n)
        else:
            out[field] = float(val)
    return out


def _run_likelihood(args):
    import jax.numpy as jnp

    from . import likelihood as lk
    from .obs import names, span

    if args.synthetic:
        try:
            npsr, ntoa = (int(x) for x in args.synthetic.lower().split("x"))
        except ValueError:
            raise SystemExit(
                f"--synthetic must look like 10x512 (got {args.synthetic!r})"
            )
        from .batch import synthetic_batch

        batch = synthetic_batch(npsr=npsr, ntoa=ntoa,
                                seed=args.synthetic_seed)
        locs = np.stack([
            np.arctan2(np.asarray(batch.phat)[:, 1],
                       np.asarray(batch.phat)[:, 0]),
            np.arccos(np.asarray(batch.phat)[:, 2]),
        ], axis=-1)
        psrs = None
    elif args.pardir and args.timdir:
        from . import load_from_directories, make_ideal
        from .batch import freeze

        with span(names.SPAN_INGEST, pardir=args.pardir):
            psrs = load_from_directories(args.pardir, args.timdir,
                                         num_psrs=args.num_psrs)
            for psr in psrs:
                make_ideal(psr)
        batch = freeze(psrs)
        locs = None
    else:
        raise SystemExit(
            "likelihood needs a dataset: --pardir/--timdir or --synthetic"
        )

    with span(names.SPAN_BUILD_RECIPE), open(args.recipe) as fh:
        recipe = _build_recipe(json.load(fh), psrs, locs=locs)

    if args.bank.endswith(".npy") and os.path.exists(args.bank):
        bank = lk.RealizationBank.from_array(np.load(args.bank))
    else:
        bank = lk.RealizationBank.from_checkpoint(args.bank)
    if tuple(bank.shape[1:]) != tuple(batch.toas_s.shape):
        raise SystemExit(
            f"bank rows are {tuple(bank.shape[1:])} but the batch is "
            f"{tuple(batch.toas_s.shape)} — the bank was synthesized "
            "from a different dataset"
        )

    result = {"bank": args.bank, "nreal": bank.nreal,
              "npsr": batch.npsr}
    grid_axes = _axis_specs(args.grid, "grid")

    with span(names.SPAN_COMPUTE):
        if grid_axes:
            grid, shape = lk.grid_cartesian(grid_axes)
            # the bank handle streams chunk-by-chunk through the
            # prefetch layer — the full cube never sits on the host
            ll = np.asarray(lk.bank_loglikelihood(
                bank, batch, recipe, grid=grid
            ))  # (G, R)
            mean = ll.mean(axis=1)
            best = int(np.argmax(mean))
            result["grid"] = {
                "axes": sorted(grid_axes),
                "shape": list(shape),
                "loglikelihood_mean": [float(v) for v in mean],
                "best": {
                    "index": best,
                    **{k: float(grid[k][best]) for k in grid},
                    "loglikelihood_mean": float(mean[best]),
                },
            }
        if args.map_params:
            mr = lk.map_fit(
                bank.row(args.real_index), batch, recipe,
                _axis_specs(args.map_params, "map"),
            )
            result["map"] = mr.as_dict()
        if args.serve:
            if not grid_axes:
                raise SystemExit(
                    "--serve needs --grid axes to sample requests from"
                )
            result["serve"] = _serve_demo(args, bank, batch, recipe,
                                          grid_axes)

    payload = json.dumps(result, indent=1, sort_keys=True)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(payload + "\n")
    else:
        print(payload)


def _serve_demo(args, bank, batch, recipe, grid_axes):
    """N requests sampled over the grid axes, submitted from
    --clients threads through the request-batched server; returns the
    SLO stats block."""
    import threading

    from . import likelihood as lk

    server = lk.LikelihoodServer(
        bank, batch, recipe, axes=tuple(grid_axes),
        max_batch=args.max_batch,
        max_delay_s=args.max_delay_ms / 1e3,
        max_queue=args.max_queue,
        request_deadline_s=(
            None if args.deadline_ms is None else args.deadline_ms / 1e3
        ),
    )
    rng = np.random.default_rng(0)
    points = {
        k: rng.choice(v, size=args.serve) for k, v in grid_axes.items()
    }
    failures = []

    def client(lo, hi):
        futs = []
        for i in range(lo, hi):
            try:
                futs.append(
                    server.submit(**{k: points[k][i] for k in points})
                )
            except lk.ServerSaturated:
                # admission control shed the request — exactly what
                # --max-queue asks for; counted in stats()["rejected"]
                continue
        for f in futs:
            try:
                f.result(timeout=120)
            except lk.DeadlineExpired:
                pass  # shed by deadline; counted in stats()
            except Exception as exc:  # noqa: BLE001 — reported below
                failures.append(repr(exc))

    # ceil partition: exactly min(clients, serve) threads, never more
    # (floor division spawned an extra thread when serve % clients != 0,
    # making any "N closed-loop clients" figure wrong)
    per = -(-args.serve // max(1, args.clients))
    threads = []
    with server:
        # warm the engine and re-zero the SLO window before the timed
        # load (the first request pays the XLA compile — same exclusion
        # the bench applies; the printed block must describe
        # steady-state serving, not one compile outlier)
        server.evaluate(**{k: float(np.atleast_1d(v)[0])
                           for k, v in grid_axes.items()})
        server.reset_stats()
        for lo in range(0, args.serve, per):
            t = threading.Thread(
                target=client, args=(lo, min(lo + per, args.serve))
            )
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        stats = server.stats()
    if failures:
        stats["failures"] = failures[:8]
    return stats


def _run_scenario(args):
    from .obs import names, span
    from .scenarios import SpecError, compile_spec, fuzz as fz, load_spec

    def load_all():
        if not args.specs:
            raise SystemExit("scenario: give at least one SPEC file")
        out = []
        for path in args.specs:
            try:
                out.append((path, load_spec(path)))
            except SpecError as exc:
                raise SystemExit(f"{path}: {exc}")
        return out

    if args.action == "validate":
        for path, spec in load_all():
            print(json.dumps({
                "spec": path, "name": spec.name,
                "hash": spec.content_hash, "valid": True,
            }))
        return

    if args.action == "compile":
        all_specs = load_all()
        if args.out and len(all_specs) > 1:
            raise SystemExit(
                "scenario compile --out takes exactly one SPEC (the "
                "output path would be overwritten per spec); compile "
                "them separately"
            )
        for path, spec in all_specs:
            compiled = compile_spec(spec, validate=False)
            summary = {
                "spec": path,
                "name": spec.name,
                "hash": compiled.spec_hash,
                "fingerprint": compiled.fingerprint,
                "families": list(compiled.families),
                "npsr": int(compiled.batch.npsr),
                "ntoa": int(np.asarray(compiled.batch.toas_s).shape[1]),
                "plan": vars(compiled.plan),
            }
            if args.out:
                static = np.asarray(compiled.static_delays())
                # np.savez appends .npz to other suffixes; atomic like
                # mk_workload so a concurrent reader never sees a torn
                # file
                tmp = args.out + ".tmp.npz"
                np.savez(tmp, static=static,
                         fingerprint=np.array(compiled.fingerprint))
                os.replace(tmp, args.out)
                summary["out"] = args.out
            print(json.dumps(summary, sort_keys=True))
        return

    if args.action == "run":
        from .utils.sweep import sweep

        specs = load_all()
        if len(specs) > 1:
            raise SystemExit(
                "scenario run takes exactly one SPEC (got "
                f"{len(specs)}); run them separately — each sweep "
                "needs its own --checkpoint/--out"
            )
        path, spec = specs[0]
        compiled = compile_spec(spec, validate=False)
        plan = compiled.plan
        nreal = args.nreal if args.nreal is not None else plan.nreal
        chunk = plan.chunk
        if nreal % chunk:
            # silently picking a different chunk would change the
            # fold_in-per-chunk key layout (and thus the draws), so a
            # non-divisible override — including nreal < chunk — is an
            # error, not an adjustment
            raise SystemExit(
                f"--nreal {nreal} must be a multiple of the spec's "
                f"sweep.chunk ({plan.chunk}); pick a multiple or edit "
                "the spec's sweep section"
            )
        ckpt = args.checkpoint or (
            (args.out or f"{spec.name}.npz") + ".sweep.npz"
        )
        with span(names.SPAN_COMPUTE, nreal=nreal):
            out = sweep(
                compiled.realize_key(), compiled.batch, compiled.recipe,
                nreal=nreal, checkpoint_path=ckpt, chunk=chunk,
                reduce_fn=None, fit=plan.fit,
                pipeline_depth=plan.pipeline_depth,
                provenance=compiled.provenance(),
            )
        summary = {
            "spec": path, "hash": compiled.spec_hash,
            "checkpoint": ckpt, "shape": list(out.shape),
            "rms_s": float(np.sqrt((np.asarray(out) ** 2).mean())),
        }
        if args.out:
            # same atomic writer as the compile action (np.savez
            # appends .npz to other suffixes, which would leave the
            # summary naming a path that doesn't exist)
            tmp = args.out + ".tmp.npz"
            np.savez(tmp, residuals=np.asarray(out),
                     mask=np.asarray(compiled.batch.mask))
            os.replace(tmp, args.out)
            summary["out"] = args.out
        print(json.dumps(summary, sort_keys=True))
        return

    if args.action == "fuzz":
        if args.specs:
            raise SystemExit(
                "scenario fuzz generates its own random scenarios and "
                "takes no SPEC files (use `scenario replay` to re-run "
                "a saved spec through the differential)"
            )
        n = 8 if args.fast else args.n
        sweep_every = 4 if args.fast else args.sweep_every
        report = fz.fuzz(
            n, root_seed=args.root_seed, out_dir=args.out_dir,
            sweep_every=sweep_every,
            progress=lambda d, t: print(f"scenario {d}/{t}",
                                        file=sys.stderr),
        )
        print(json.dumps(report, indent=1, sort_keys=True))
        if report["n_disagreements"]:
            print(f"scenario fuzz: {report['n_disagreements']} "
                  f"disagreement(s); shrunk replayable spec(s) under "
                  f"{args.out_dir}/", file=sys.stderr)
            raise SystemExit(1)
        si = report["sweep_identity"]
        if si["checked"] and not si["all_bit_identical"]:
            # stdout (the report) is routinely /dev/null'd in CI, so
            # the failure reason must reach stderr too
            print("scenario fuzz: pipelined-vs-sync sweep byte-"
                  "identity violated (see the sweep_identity block of "
                  "the report)", file=sys.stderr)
            raise SystemExit(1)
        return

    if args.action == "replay":
        rc = 0
        for path, spec in load_all():
            res = fz.run_scenario(compile_spec(spec, validate=False))
            print(json.dumps({"spec": path, **res.to_dict()},
                             indent=1, sort_keys=True))
            if not res.agree:
                rc = 1
        if rc:
            raise SystemExit(rc)
        return


def _run_command(args):
    if args.cmd == "scenario":
        return _run_scenario(args)
    if args.cmd == "likelihood":
        return _run_likelihood(args)

    from . import load_from_directories, make_ideal
    from .obs import names, span

    if getattr(args, "fused_stream", False) and not args.checkpoint:
        # only the checkpointed sweep runs the fused graph — silently
        # running the plain realize path would let the user believe
        # fused streaming happened (same refusal contract as the
        # in-sweep mesh/depth checks). Checked before ingest: a typo'd
        # invocation must not load datasets first.
        raise SystemExit(
            "--fused-stream needs --checkpoint: the fused stage graph "
            "is the checkpointed sweep executor (docs/streaming.md)"
        )
    if getattr(args, "fused_stream", False) and args.pipeline_depth < 2:
        # same pre-ingest gate: at depth 1 there is no concurrency for
        # the static build to overlap with, so the sweep would refuse
        # anyway — fail before datasets are loaded.
        raise SystemExit(
            "--fused-stream needs --pipeline-depth >= 2: at depth 1 "
            "there is no concurrency for the static build to overlap "
            "with (docs/streaming.md)"
        )

    with span(names.SPAN_INGEST, pardir=args.pardir):
        psrs = load_from_directories(args.pardir, args.timdir,
                                     num_psrs=args.num_psrs)
        for psr in psrs:
            make_ideal(psr)

    from .batch import freeze

    batch = freeze(psrs)
    if args.cmd == "info":
        print(json.dumps({
            "npsr": batch.npsr,
            "ntoa_max": batch.ntoa_max,
            "names": list(batch.names),
            "backends": list(batch.backend_names),
            "max_epochs": batch.max_epochs,
            "tref_mjd": float(batch.tref_mjd),
        }))
        return

    import jax

    with span(names.SPAN_BUILD_RECIPE), open(args.recipe) as fh:
        recipe = _build_recipe(json.load(fh), psrs)
    if args.gls_fit:
        args.full_fit = True
    if args.full_fit:
        import dataclasses

        import jax.numpy as jnp

        from .timing.fit import design_tensor

        args.fit = True
        D, _names = design_tensor(psrs, ntoa_max=batch.ntoa_max)
        recipe = dataclasses.replace(
            recipe, fit_design=jnp.asarray(D), fit_gls=bool(args.gls_fit)
        )
    key = jax.random.PRNGKey(args.seed)

    with span(names.SPAN_COMPUTE, nreal=args.nreal, fit=bool(args.fit)):
        if args.checkpoint:
            from .utils.sweep import sweep

            chunk = min(args.chunk, args.nreal)
            if args.nreal % chunk:
                raise SystemExit(
                    f"--nreal {args.nreal} must be a multiple of --chunk {chunk}"
                )
            mesh = None
            if args.sharded or args.mesh_shape:
                mesh = _make_mesh_arg(args.mesh_shape)
            out = sweep(key, batch, recipe, nreal=args.nreal,
                        checkpoint_path=args.checkpoint, chunk=chunk,
                        reduce_fn=None, fit=args.fit, mesh=mesh,
                        pipeline_depth=args.pipeline_depth,
                        drain_timeout_s=(args.drain_timeout
                                         if args.drain_timeout > 0
                                         else None),
                        chunk_retries=args.chunk_retries,
                        fused_stream=args.fused_stream,
                        progress=lambda d, t: print(f"chunk {d}/{t}",
                                                    file=sys.stderr))
        elif args.sharded or args.mesh_shape:
            from .parallel import sharded_realize

            out = np.asarray(sharded_realize(
                key, batch, recipe, nreal=args.nreal,
                mesh=_make_mesh_arg(args.mesh_shape), fit=args.fit,
            ))
        else:
            from .models.batched import realize

            out = np.asarray(realize(key, batch, recipe, nreal=args.nreal,
                                     fit=args.fit))

    with span(names.SPAN_WRITE_OUTPUT, out=args.out):
        np.savez(args.out, residuals=out, mask=np.asarray(batch.mask),
                 names=np.array(batch.names))
    summary = {
        "out": args.out,
        "shape": list(out.shape),
        "rms_s": float(np.sqrt((out**2).mean())),
    }
    if args.write_partim:
        from .utils.export import materialize_realizations, sweep_keys

        # written dataset r must carry the same delays as residual-cube
        # row r: match the engine's key layout exactly — a checkpointed
        # sweep consumes fold_in-per-chunk keys, the direct engines
        # consume split(key, nreal)
        if args.checkpoint:
            ks = sweep_keys(key, args.nreal, min(args.chunk, args.nreal))
        else:
            ks = jax.random.split(key, args.nreal)
        dirs = materialize_realizations(
            psrs, batch, recipe, key,
            nreal=min(args.nreal, args.write_max),
            outdir=args.write_partim,
            keys=ks,
        )
        summary["partim_dirs"] = len(dirs)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
