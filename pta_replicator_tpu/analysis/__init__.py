"""graftlint: static analysis of JAX/TPU, threading, and telemetry
invariants.

The codebase carries three classes of invariants that used to live only
in reviewers' heads: JAX tracing/transfer discipline (no host syncs or
f64 literals inside jit, no PRNG key reuse), thread/lock/clock
discipline (locked mutation of shared state, monotonic clocks for
durations, a recorded lock hierarchy), and telemetry naming (every
span/metric name registered once in ``obs/names.py``). ``graftlint``
enforces them on every PR:

    python -m pta_replicator_tpu lint                 # whole tree
    python -m pta_replicator_tpu lint --changed-only  # quick local loop
    python -m pta_replicator_tpu lint --format json
    python -m pta_replicator_tpu lint --update-baseline

Layout: :mod:`.engine` (AST walk, findings, ``# graftlint:
disable=<rule>`` suppressions, ``baseline.json`` ratchet),
:mod:`.rules_jax`, :mod:`.rules_threads`, :mod:`.rules_telemetry` (the
rule packs), :mod:`.cli` (the ``lint`` subcommand body). Everything is
jax-free and import-cheap; the engine never imports the code it lints.

Docs: docs/static-analysis.md (rule catalog with rationale, suppression
and baseline workflow, how to add a rule).
"""
from __future__ import annotations

from .engine import (
    Finding,
    Module,
    Rule,
    apply_baseline,
    default_rules,
    iter_python_files,
    lint,
    load_baseline,
    parse_modules,
    run_rules,
    write_baseline,
)

__all__ = [
    "Finding", "Module", "Rule", "apply_baseline", "default_rules",
    "iter_python_files", "lint", "load_baseline", "parse_modules",
    "run_rules", "write_baseline",
]
