"""Incremental lint cache: content-hash keyed findings with
import-graph invalidation.

Two tiers, both pure functions of source bytes (never of mtimes):

* **per-file** — a module's per-file rule findings are keyed by the
  digest of (its own source, the sources of its *direct project
  imports*, the analysis-environment signature). The import hashes are
  the invalidation contract: a module-rule finding is allowed to depend
  on the linted file, on what its direct imports look like (the
  telemetry registry a producer references), and on the rule code — on
  nothing else. Change ``obs/names.py`` and every module importing it
  re-lints; change an unrelated file and it does not.
* **whole-tree** — the final, sorted, suppression-classified finding
  lists are keyed by the digest of every (relpath, content-hash) pair
  plus the environment signature. On an unchanged tree the engine skips
  parsing and rule execution entirely — the warm path is hash + load +
  report, which is what makes the full whole-program lint cheap enough
  to run on every iteration (``scripts/check.sh`` times it and fails if
  a warm re-run misses).

The **environment signature** folds in every ``analysis/*.py`` source
and ``obs/names.py`` (the registry project rules consult), so editing a
rule or the registry invalidates everything. Cross-file (project +
interprocedural) findings are only reused on a whole-tree hit: any
changed file conservatively re-runs them over the full module list,
which is precisely the "changed file re-runs its dependents' cross-file
rules" contract ``--changed-only`` needs to stay whole-program-correct.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import module_dotted_name
from .engine import Finding, Module

CACHE_VERSION = 2

#: default cache file name, created under the lint root
CACHE_BASENAME = ".graftlint-cache.json"


def file_digest(source: str) -> str:
    return hashlib.sha256(source.encode("utf-8", "replace")).hexdigest()[:20]


def _digest(*parts: str) -> str:
    h = hashlib.sha256()
    for p in parts:
        h.update(p.encode())
        h.update(b"\x00")
    return h.hexdigest()[:20]


def env_signature() -> str:
    """Digest of the analysis package sources + the telemetry registry:
    the code findings are a function of, beyond the linted sources."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = []
    analysis_dir = os.path.join(pkg_dir, "analysis")
    for name in sorted(os.listdir(analysis_dir)):
        if name.endswith(".py"):
            paths.append(os.path.join(analysis_dir, name))
    names_py = os.path.join(pkg_dir, "obs", "names.py")
    if os.path.exists(names_py):
        paths.append(names_py)
    parts = [f"v{CACHE_VERSION}"]
    for p in paths:
        try:
            with open(p, encoding="utf-8", errors="replace") as fh:
                parts.append(os.path.basename(p))
                parts.append(file_digest(fh.read()))
        except OSError:
            continue
    return _digest(*parts)


def tree_key(hashes: Dict[str, str], env: str) -> str:
    return _digest(env, *(
        f"{rel}={h}" for rel, h in sorted(hashes.items())
    ))


# ------------------------------------------------------- import graph
def project_import_graph(
    mods: Sequence[Module],
) -> Dict[str, Set[str]]:
    """relpath -> relpaths of the *direct* project-internal imports,
    resolved by dotted-suffix match (relative imports were dot-stripped
    by the Module parser)."""
    dotted = {module_dotted_name(m.relpath): m.relpath for m in mods}

    def resolve_head(origin: str, importer: str) -> Optional[str]:
        parts = origin.split(".")
        for i in range(len(parts), 0, -1):
            head = ".".join(parts[:i])
            if head in dotted:
                return dotted[head]
            suffix = "." + head
            cands = [r for d, r in dotted.items() if d.endswith(suffix)]
            if len(cands) == 1:
                return cands[0]
            if cands:
                def score(rel):
                    common = 0
                    for a, b in zip(rel.split("/"), importer.split("/")):
                        if a != b:
                            break
                        common += 1
                    return (-common, len(rel), rel)
                return sorted(cands, key=score)[0]
        return None

    graph: Dict[str, Set[str]] = {}
    for m in mods:
        deps: Set[str] = set()
        for origin in m.imports.values():
            rel = resolve_head(origin, m.relpath)
            if rel is not None and rel != m.relpath:
                deps.add(rel)
        graph[m.relpath] = deps
    return graph


def dependents(
    graph: Dict[str, Set[str]], changed: Set[str]
) -> Set[str]:
    """``changed`` plus every module that transitively imports one of
    them (reverse closure; cycles in the import graph are fine)."""
    reverse: Dict[str, Set[str]] = {}
    for rel, deps in graph.items():
        for d in deps:
            reverse.setdefault(d, set()).add(rel)
    out = set(changed)
    stack = list(changed)
    while stack:
        for dep in reverse.get(stack.pop(), ()):
            if dep not in out:
                out.add(dep)
                stack.append(dep)
    return out


def module_key(
    rel: str, hashes: Dict[str, str], deps: Set[str], env: str
) -> str:
    return _digest(
        env, f"{rel}={hashes[rel]}",
        *(f"{d}={hashes[d]}" for d in sorted(deps) if d in hashes),
    )


# ------------------------------------------------------------ storage
def _dump(findings: Sequence[Finding]) -> List[dict]:
    return [dataclasses.asdict(f) for f in findings]


def _load_findings(entries) -> List[Finding]:
    return [Finding(**e) for e in entries]


class LintCache:
    """On-disk JSON cache; loads tolerant (a corrupt or version-skewed
    cache is an empty cache, never an error)."""

    def __init__(self, path: str):
        self.path = path
        self.doc: dict = {
            "version": CACHE_VERSION, "tree": {}, "files": {},
        }
        self.hits = 0
        self.misses = 0

    @classmethod
    def load(cls, path: str) -> "LintCache":
        cache = cls(path)
        try:
            with open(path) as fh:
                doc = json.load(fh)
            if isinstance(doc, dict) and \
                    doc.get("version") == CACHE_VERSION:
                cache.doc = doc
        except (OSError, ValueError):
            pass
        return cache

    def save(self) -> None:
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as fh:
                json.dump(self.doc, fh, sort_keys=True)
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    # -- whole-tree tier -------------------------------------------------
    def lookup_tree(
        self, key: str
    ) -> Optional[Tuple[List[Finding], List[Finding], int]]:
        entry = self.doc.get("tree", {})
        if entry.get("key") != key:
            return None
        return (
            _load_findings(entry["active"]),
            _load_findings(entry["suppressed"]),
            int(entry["files"]),
        )

    def store_tree(
        self, key: str, active: Sequence[Finding],
        suppressed: Sequence[Finding], nfiles: int,
    ) -> None:
        self.doc["tree"] = {
            "key": key, "active": _dump(active),
            "suppressed": _dump(suppressed), "files": nfiles,
        }

    # -- per-file tier ---------------------------------------------------
    def lookup_module(
        self, rel: str, key: str
    ) -> Optional[Tuple[List[Finding], List[Finding]]]:
        entry = self.doc.get("files", {}).get(rel)
        if not entry or entry.get("key") != key:
            self.misses += 1
            return None
        self.hits += 1
        return (
            _load_findings(entry["active"]),
            _load_findings(entry["suppressed"]),
        )

    def store_module(
        self, rel: str, key: str, active: Sequence[Finding],
        suppressed: Sequence[Finding],
    ) -> None:
        self.doc.setdefault("files", {})[rel] = {
            "key": key, "active": _dump(active),
            "suppressed": _dump(suppressed),
        }

    def prune(self, keep: Set[str]) -> None:
        files = self.doc.get("files", {})
        for rel in list(files):
            if rel not in keep:
                del files[rel]
