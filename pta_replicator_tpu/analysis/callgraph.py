"""Project-wide symbol index and conservative call graph.

The per-module rule packs see one file at a time; the interprocedural
passes (:mod:`.rules_interproc`) need to answer "which functions are
reachable from this jit entry, through which call chain, holding which
locks?" across the whole lint target set. This module builds that
substrate from the already-parsed :class:`~.engine.Module` list — still
pure ``ast``, no imports of the linted code.

Three layers:

* :class:`SymbolIndex` — every module's top-level functions, classes,
  methods, nested defs, and name-bound lambdas, addressable as
  ``relpath::qualpath`` symbols (``parallel/stages.py::StageGraph.stop``),
  plus alias-resolved import targeting: ``from ..obs import span as s``
  makes ``s`` resolve to the ``span`` def in the project's ``obs``
  package. Relative imports were dot-stripped by the Module parser, so
  origins resolve by *dotted-suffix* match against the lint set's module
  names (longest match wins, importer-package proximity breaks ties).
* :class:`CallGraph` — one edge per statically resolvable call site:
  direct names, imported names, ``self.method()`` resolution through the
  enclosing class, and lambda targets. Decorated functions keep their
  def as the edge target (``jit``/``instrumented_jit``/``shard_map``/
  ``custom_vmap`` wrappers don't hide the body). Dynamic dispatch
  (``obj.method()`` on an unknown object, dict-of-callables) yields no
  edge — the graph is deliberately under-approximate, and rules built
  on it must treat "unreachable" as "not provably reachable".
* :meth:`CallGraph.reachable_from` — BFS with per-node first-discovery
  call chains (for printing ``entry -> helper -> sink`` in findings) and
  a conservative held-lock context: the locks recorded for a function
  are the intersection over every discovered call path of the ``with
  <lock>:`` blocks enclosing its call sites.
"""
from __future__ import annotations

import ast
import collections
import dataclasses
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Tuple

from .engine import Module

#: wrapper callables whose first argument is (or wraps) the traced
#: function — unwrapped when resolving decorators and entry points
WRAPPER_NAMES = {"jit", "instrumented_jit", "shard_map", "custom_vmap",
                 "custom_jvp", "custom_vjp", "partial", "wraps"}


def module_dotted_name(relpath: str) -> str:
    """``pta_replicator_tpu/utils/sweep.py`` -> ``pta_replicator_tpu.utils.sweep``;
    an ``__init__.py`` names its package."""
    parts = relpath[:-3].split("/") if relpath.endswith(".py") else \
        relpath.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def iter_body_nodes(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body WITHOUT descending into nested function/
    class/lambda scopes (those are their own symbols)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


@dataclasses.dataclass
class FunctionInfo:
    """One project function/method/lambda the graph knows about."""

    symbol: str          # "relpath::qualpath"
    relpath: str
    qualpath: str        # "fn" | "Class.method" | "outer.inner"
    name: str            # terminal name
    cls: Optional[str]   # enclosing class name for methods
    node: ast.AST        # FunctionDef / AsyncFunctionDef / Lambda
    module: Module
    lineno: int

    @property
    def display(self) -> str:
        return f"{self.name} ({self.relpath})"

    def param_names(self) -> List[str]:
        a = self.node.args
        return [p.arg for p in a.posonlyargs + a.args]

    def kwonly_names(self) -> List[str]:
        return [p.arg for p in self.node.args.kwonlyargs]


def arg_bindings(
    call: ast.Call, info: "FunctionInfo"
) -> List[Tuple[str, ast.AST]]:
    """(param_name, argument_expr) pairs for a call to ``info``,
    positional and keyword, skipping ``*``/``**`` and overflow.
    Method calls through ``self.m(...)`` bind past the ``self`` slot."""
    params = info.param_names()
    offset = 1 if (info.cls and params and params[0] in ("self", "cls")) \
        else 0
    out: List[Tuple[str, ast.AST]] = []
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            break
        j = i + offset
        if j < len(params):
            out.append((params[j], arg))
    valid = set(params) | set(info.kwonly_names())
    for kw in call.keywords:
        if kw.arg and kw.arg in valid:
            out.append((kw.arg, kw.value))
    return out


class SymbolIndex:
    """Find project functions by symbol, by (module, name), by AST node
    identity, or by alias-resolved dotted origin."""

    def __init__(self, mods: Sequence[Module]):
        self.mods = list(mods)
        self.by_relpath: Dict[str, Module] = {m.relpath: m for m in mods}
        #: dotted module name -> relpath (plus reverse-suffix buckets)
        self.dotted: Dict[str, str] = {
            module_dotted_name(m.relpath): m.relpath for m in mods
        }
        self.functions: Dict[str, FunctionInfo] = {}
        self.by_qual: Dict[Tuple[str, str], FunctionInfo] = {}
        self.by_name: Dict[Tuple[str, str], List[FunctionInfo]] = {}
        self.by_node: Dict[int, FunctionInfo] = {}
        for m in mods:
            self._index_module(m)

    # -- construction ----------------------------------------------------
    def _index_module(self, mod: Module) -> None:
        def add(node, qualpath, name, cls):
            info = FunctionInfo(
                symbol=f"{mod.relpath}::{qualpath}", relpath=mod.relpath,
                qualpath=qualpath, name=name, cls=cls, node=node,
                module=mod, lineno=getattr(node, "lineno", 1),
            )
            self.functions[info.symbol] = info
            # first binding wins for duplicate qualpaths (redefinition):
            # later defs shadow at runtime, but rules want *a* body, and
            # keeping the first makes chains deterministic
            self.by_qual.setdefault((mod.relpath, qualpath), info)
            self.by_name.setdefault((mod.relpath, name), []).append(info)
            self.by_node[id(node)] = info

        def visit(body, prefix, cls):
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qp = f"{prefix}{stmt.name}"
                    add(stmt, qp, stmt.name, cls)
                    visit(stmt.body, f"{qp}.", cls)
                elif isinstance(stmt, ast.ClassDef):
                    visit(stmt.body, f"{prefix}{stmt.name}.", stmt.name)
                elif isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, ast.Lambda
                ):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            add(stmt.value, f"{prefix}{t.id}", t.id, cls)

        visit(mod.tree.body, "", None)

    # -- dotted-origin resolution ----------------------------------------
    def resolve_module(
        self, head: str, importer_relpath: str = ""
    ) -> Optional[str]:
        """relpath of the project module a dotted head names, by exact
        or suffix match (relative imports were dot-stripped)."""
        if head in self.dotted:
            return self.dotted[head]
        suffix = "." + head
        candidates = [
            rel for dn, rel in self.dotted.items() if dn.endswith(suffix)
        ]
        if not candidates:
            return None
        if len(candidates) == 1:
            return candidates[0]
        # prefer the module sharing the longest path prefix with the
        # importer (same-package relative import), then shortest dotted
        # name, then lexicographic — deterministic either way
        def score(rel):
            common = 0
            for a, b in zip(rel.split("/"), importer_relpath.split("/")):
                if a != b:
                    break
                common += 1
            return (-common, len(rel), rel)
        return sorted(candidates, key=score)[0]

    def resolve_origin(
        self, origin: str, importer_relpath: str = ""
    ) -> Optional[FunctionInfo]:
        """Project function an alias-resolved dotted origin names:
        ``utils.sweep.run`` / ``helpers.Class.method`` -> FunctionInfo."""
        parts = origin.split(".")
        for i in range(len(parts) - 1, 0, -1):
            rel = self.resolve_module(".".join(parts[:i]), importer_relpath)
            if rel is None:
                continue
            info = self.by_qual.get((rel, ".".join(parts[i:])))
            if info is not None:
                return info
        return None

    def enclosing_info(
        self, mod: Module, node: ast.AST
    ) -> Optional[FunctionInfo]:
        """The indexed function whose body contains ``node``."""
        for anc in mod.ancestors(node):
            info = self.by_node.get(id(anc))
            if info is not None:
                return info
        return None


@dataclasses.dataclass
class CallSite:
    caller: str
    callee: str
    lineno: int
    locks: FrozenSet[str]    # locks held by `with` blocks at the site
    call: ast.Call


@dataclasses.dataclass
class Reach:
    """One reachability answer: the first-discovered call chain from
    the entry (inclusive) and the locks guaranteed held on every
    discovered path into the function."""

    chain: Tuple[str, ...]
    locks: FrozenSet[str]


class CallGraph:
    """Conservative project call graph over a :class:`SymbolIndex`."""

    def __init__(self, index: SymbolIndex):
        self.index = index
        self.edges: Dict[str, List[CallSite]] = collections.defaultdict(list)
        for info in index.functions.values():
            self._collect_edges(info)

    # -- call resolution -------------------------------------------------
    def resolve_call(
        self, mod: Module, func_expr: ast.AST,
        enclosing: Optional[FunctionInfo],
    ) -> Optional[FunctionInfo]:
        """FunctionInfo a call's func expression statically names, else
        None (dynamic dispatch)."""
        index = self.index
        qn = mod.qualname(func_expr)
        if qn is None:
            return None
        parts = qn.split(".")
        # self.method() / cls.method(): method on the enclosing class
        if parts[0] in ("self", "cls") and enclosing is not None \
                and enclosing.cls and len(parts) == 2:
            return index.by_qual.get(
                (mod.relpath, f"{enclosing.cls}.{parts[1]}")
            )
        if len(parts) == 1:
            name = parts[0]
            # nearest definition: sibling nested def, then module level
            if enclosing is not None:
                scope_prefix = enclosing.qualpath.rsplit(".", 1)[0] + "." \
                    if "." in enclosing.qualpath else ""
                info = index.by_qual.get(
                    (mod.relpath, f"{enclosing.qualpath}.{name}")
                ) or index.by_qual.get(
                    (mod.relpath, f"{scope_prefix}{name}")
                )
                if info is not None:
                    return info
            info = index.by_qual.get((mod.relpath, name))
            if info is not None:
                return info
            origin = mod.imports.get(name)
            if origin is not None:
                return index.resolve_origin(origin, mod.relpath)
            return None
        # dotted: resolve the head through import aliases
        resolved = mod.resolve(func_expr)
        if resolved is None:
            return None
        info = index.resolve_origin(resolved, mod.relpath)
        if info is not None:
            return info
        # Class().method() / local ClassName.method reference
        if len(parts) == 2:
            return index.by_qual.get((mod.relpath, f"{parts[0]}.{parts[1]}"))
        return None

    def _collect_edges(self, info: FunctionInfo) -> None:
        from .rules_threads import _held_locks

        mod = info.module
        for node in iter_body_nodes(info.node):
            if not isinstance(node, ast.Call):
                continue
            callee = self.resolve_call(mod, node.func, info)
            if callee is None or callee.symbol == info.symbol:
                continue
            self.edges[info.symbol].append(CallSite(
                caller=info.symbol, callee=callee.symbol,
                lineno=node.lineno,
                locks=frozenset(_held_locks(mod, node)), call=node,
            ))

    # -- reachability ----------------------------------------------------
    def reachable_from(
        self, entry: str, predicate=None, max_depth: int = 64,
    ) -> Dict[str, Reach]:
        """Every function reachable from ``entry`` (inclusive), with the
        first-discovered chain and the path-intersection lock context.
        ``predicate(info)`` may prune traversal (return False to stop
        descending into a function)."""
        if entry not in self.index.functions:
            return {}
        out: Dict[str, Reach] = {
            entry: Reach(chain=(entry,), locks=frozenset())
        }
        queue = collections.deque([(entry, 0)])
        while queue:
            sym, depth = queue.popleft()
            if depth >= max_depth:
                continue
            reach = out[sym]
            info = self.index.functions[sym]
            if predicate is not None and not predicate(info):
                continue
            for site in self.edges.get(sym, ()):
                locks = reach.locks | site.locks
                prev = out.get(site.callee)
                if prev is None:
                    out[site.callee] = Reach(
                        chain=reach.chain + (site.callee,), locks=locks
                    )
                    queue.append((site.callee, depth + 1))
                else:
                    shrunk = prev.locks & locks
                    if shrunk != prev.locks:
                        # weaker lock guarantee on a new path: revisit
                        out[site.callee] = Reach(prev.chain, shrunk)
                        queue.append((site.callee, depth + 1))
        return out

    def format_chain(self, chain: Sequence[str]) -> str:
        """``engine (models/batched.py) -> helper (utils/x.py)``."""
        return " -> ".join(
            self.index.functions[s].display for s in chain
        )


# A tiny keyed memo so the interprocedural rules (each invoked
# separately by the engine) share one graph per run. Entries hold the
# Modules alive, so id() keys cannot be recycled while cached.
_GRAPH_MEMO: "collections.OrderedDict[tuple, CallGraph]" = \
    collections.OrderedDict()


def project_graph(mods: Sequence[Module]) -> CallGraph:
    key = tuple(id(m) for m in mods)
    graph = _GRAPH_MEMO.get(key)
    if graph is None:
        graph = CallGraph(SymbolIndex(mods))
        _GRAPH_MEMO[key] = graph
        while len(_GRAPH_MEMO) > 4:
            _GRAPH_MEMO.popitem(last=False)
    return graph
