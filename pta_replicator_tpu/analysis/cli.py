"""The ``python -m pta_replicator_tpu lint`` subcommand body.

Deliberately jax-free (the engine parses source, it never imports the
linted code) so the lint gate stays fast enough for the tier-1 test
path and pre-commit use. Exit codes: 0 clean (possibly with baselined/
suppressed findings), 1 new findings, 2 usage/internal error.
"""
from __future__ import annotations

import json
import os
import sys
from typing import Optional, Sequence

from .engine import lint, write_baseline

#: default lint targets, relative to the repo root (missing entries are
#: skipped so an installed package without the repo harness still lints)
DEFAULT_TARGETS = (
    "pta_replicator_tpu",
    "scripts",
    "benchmarks",
    "bench.py",
)


def repo_root() -> str:
    """The directory containing the package (the repo checkout root)."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg_dir)


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def run_lint(
    paths: Sequence[str],
    fmt: str = "text",
    baseline: Optional[str] = None,
    update_baseline: bool = False,
    changed_only: bool = False,
    root: Optional[str] = None,
    out=None,
) -> int:
    out = out if out is not None else sys.stdout
    if update_baseline and changed_only:
        # a baseline written from a filtered file set would silently
        # DROP every grandfathered entry for unchanged files
        raise ValueError(
            "--update-baseline needs the full finding set; it cannot be "
            "combined with --changed-only"
        )
    root = root or repo_root()
    if not paths:
        paths = [p for p in DEFAULT_TARGETS
                 if os.path.exists(os.path.join(root, p))]
    baseline = baseline if baseline is not None else default_baseline_path()

    result = lint(
        paths, root, baseline_path=None if update_baseline else baseline,
        changed_only=changed_only,
    )

    if update_baseline:
        findings = result["new"]  # baseline was not applied: all active
        write_baseline(baseline, findings)
        print(
            f"wrote {len(findings)} finding(s) to {baseline}", file=out
        )
        return 0

    if fmt == "json":
        json.dump({
            "files": result["files"],
            "new": [f.to_json() for f in result["new"]],
            "baselined": [f.to_json() for f in result["baselined"]],
            "suppressed": [f.to_json() for f in result["suppressed"]],
            "stale_baseline": result["stale"],
            "exit_code": result["exit_code"],
        }, out, indent=1, sort_keys=True)
        out.write("\n")
        return result["exit_code"]

    if result["note"]:
        print(f"note: {result['note']}", file=out)
    for f in result["new"]:
        print(f.format(), file=out)
    for f in result["baselined"]:
        print(f"{f.format()}  (baselined)", file=out)
    for entry in result["stale"]:
        print(
            f"stale baseline entry (finding fixed — remove it): "
            f"{entry.get('rule')} {entry.get('path')}: "
            f"{entry.get('message')}", file=out,
        )
    print(
        f"graftlint: {result['files']} file(s), "
        f"{len(result['new'])} new, "
        f"{len(result['baselined'])} baselined, "
        f"{len(result['suppressed'])} suppressed"
        + (f", {len(result['stale'])} stale baseline entr"
           f"{'y' if len(result['stale']) == 1 else 'ies'}"
           if result["stale"] else ""),
        file=out,
    )
    return result["exit_code"]


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m pta_replicator_tpu lint",
        description="graftlint: JAX/thread/telemetry invariant checker",
    )
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: the "
                         "package, scripts/, benchmarks/, bench.py)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help="baseline JSON (default: "
                         "pta_replicator_tpu/analysis/baseline.json)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline with every current "
                         "finding and exit 0 (use sparingly: the "
                         "baseline is a ratchet, not a dumping ground)")
    ap.add_argument("--changed-only", action="store_true",
                    help="lint only files differing from main "
                         "(plus uncommitted work) for quick iteration")
    args = ap.parse_args(argv)
    try:
        return run_lint(
            args.paths,
            fmt=args.format,
            baseline=args.baseline,
            update_baseline=args.update_baseline,
            changed_only=args.changed_only,
        )
    except (OSError, ValueError) as exc:
        print(f"graftlint: {exc}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
