"""The ``python -m pta_replicator_tpu lint`` subcommand body.

Deliberately jax-free (the engine parses source, it never imports the
linted code) so the lint gate stays fast enough for the tier-1 test
path and pre-commit use. Exit codes: 0 clean (possibly with baselined/
suppressed findings), 1 new findings (or a cache miss under
``--expect-warm``), 2 usage/internal error.
"""
from __future__ import annotations

import json
import os
import sys
from typing import Optional, Sequence

from .engine import default_rules, lint, write_baseline

#: default lint targets, relative to the repo root (missing entries are
#: skipped so an installed package without the repo harness still lints)
DEFAULT_TARGETS = (
    "pta_replicator_tpu",
    "scripts",
    "benchmarks",
    "bench.py",
)

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def repo_root() -> str:
    """The directory containing the package (the repo checkout root)."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg_dir)


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def default_cache_path(root: str) -> str:
    from .cache import CACHE_BASENAME

    return os.path.join(root, CACHE_BASENAME)


# ----------------------------------------------------------------- SARIF
def _sarif_result(f, suppressed_kind: Optional[str] = None) -> dict:
    out = {
        "ruleId": f.rule,
        "level": "error" if f.severity == "error" else "warning",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path},
                "region": {"startLine": max(1, f.line)},
            },
        }],
        "partialFingerprints": {"graftlint/v1": f.fingerprint},
    }
    if suppressed_kind is not None:
        out["suppressions"] = [{"kind": suppressed_kind}]
    return out


def to_sarif(result: dict) -> dict:
    """SARIF 2.1.0 document: new findings as plain results, baselined
    ones carried with an ``external`` suppression (so a SARIF viewer
    shows the debt without failing on it)."""
    rules_meta = []
    seen = set()
    for rule in default_rules():
        if rule.id in seen:
            continue  # per-module and interprocedural variants share ids
        seen.add(rule.id)
        rules_meta.append({
            "id": rule.id,
            "shortDescription": {"text": rule.description or rule.id},
            "defaultConfiguration": {
                "level": "error" if rule.severity == "error"
                else "warning",
            },
        })
    results = [_sarif_result(f) for f in result["new"]]
    results += [
        _sarif_result(f, suppressed_kind="external")
        for f in result["baselined"]
    ]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri":
                    "docs/static-analysis.md",
                "rules": sorted(rules_meta, key=lambda r: r["id"]),
            }},
            "results": results,
        }],
    }


# --------------------------------------------------------------- explain
def explain_rule(rule_id: str, out) -> int:
    matches = [r for r in default_rules() if r.id == rule_id]
    if not matches:
        known = sorted({r.id for r in default_rules()})
        print(f"graftlint: unknown rule {rule_id!r}; known rules:",
              file=out)
        for rid in known:
            print(f"  {rid}", file=out)
        return 2
    for rule in matches:
        cls = type(rule)
        print(f"{rule.id} [{rule.severity}] — "
              f"{cls.__module__.rsplit('.', 1)[-1]}.{cls.__name__}",
              file=out)
        if rule.description:
            print(f"  {rule.description}", file=out)
        doc = (cls.__doc__ or "").strip()
        if doc:
            print(file=out)
            for line in doc.splitlines():
                print(f"  {line.strip()}", file=out)
        if rule.example_fire:
            print("\n  fires on:", file=out)
            for line in rule.example_fire.rstrip().splitlines():
                print(f"    {line}", file=out)
        if rule.example_ok:
            print("\n  clean:", file=out)
            for line in rule.example_ok.rstrip().splitlines():
                print(f"    {line}", file=out)
        print(file=out)
    return 0


# ------------------------------------------------------------------ lint
def run_lint(
    paths: Sequence[str],
    fmt: str = "text",
    baseline: Optional[str] = None,
    update_baseline: bool = False,
    prune_baseline: bool = False,
    changed_only: bool = False,
    root: Optional[str] = None,
    use_cache: bool = True,
    expect_warm: bool = False,
    out=None,
) -> int:
    out = out if out is not None else sys.stdout
    if update_baseline and changed_only:
        # a baseline written from a filtered file set would silently
        # DROP every grandfathered entry for unchanged files
        raise ValueError(
            "--update-baseline needs the full finding set; it cannot be "
            "combined with --changed-only"
        )
    if prune_baseline and (update_baseline or changed_only):
        raise ValueError(
            "--prune-baseline needs the full finding set on its own; it "
            "cannot be combined with --update-baseline or --changed-only"
        )
    root = root or repo_root()
    if not paths:
        paths = [p for p in DEFAULT_TARGETS
                 if os.path.exists(os.path.join(root, p))]
    baseline = baseline if baseline is not None else default_baseline_path()
    cache_path = default_cache_path(root) if use_cache else None

    result = lint(
        paths, root, baseline_path=None if update_baseline else baseline,
        changed_only=changed_only, cache_path=cache_path,
    )

    if update_baseline:
        findings = result["new"]  # baseline was not applied: all active
        write_baseline(baseline, findings)
        print(
            f"wrote {len(findings)} finding(s) to {baseline}", file=out
        )
        return 0

    if prune_baseline:
        kept = result["baselined"]  # entries still matching a finding
        write_baseline(baseline, kept)
        n = len(result["stale"])
        print(
            f"pruned {n} stale entr{'y' if n == 1 else 'ies'} from "
            f"{baseline} ({len(kept)} kept)", file=out,
        )
        return 0

    if fmt == "sarif":
        json.dump(to_sarif(result), out, indent=1, sort_keys=True)
        out.write("\n")
    elif fmt == "json":
        json.dump({
            "files": result["files"],
            "new": [f.to_json() for f in result["new"]],
            "baselined": [f.to_json() for f in result["baselined"]],
            "suppressed": [f.to_json() for f in result["suppressed"]],
            "stale_baseline": result["stale"],
            "exit_code": result["exit_code"],
        }, out, indent=1, sort_keys=True)
        out.write("\n")
    else:
        if result["note"]:
            print(f"note: {result['note']}", file=out)
        for f in result["new"]:
            print(f.format(), file=out)
        for f in result["baselined"]:
            print(f"{f.format()}  (baselined)", file=out)
        for entry in result["stale"]:
            print(
                f"stale baseline entry (finding fixed — remove it): "
                f"{entry.get('rule')} {entry.get('path')}: "
                f"{entry.get('message')}", file=out,
            )
        print(
            f"graftlint: {result['files']} file(s), "
            f"{len(result['new'])} new, "
            f"{len(result['baselined'])} baselined, "
            f"{len(result['suppressed'])} suppressed"
            + (f", {len(result['stale'])} stale baseline entr"
               f"{'y' if len(result['stale']) == 1 else 'ies'}"
               if result["stale"] else ""),
            file=out,
        )

    if expect_warm and result.get("cache") != "warm":
        print(
            f"graftlint: --expect-warm: cache was "
            f"{result.get('cache')!r}, not 'warm' (the tree changed, "
            "the cache was invalidated, or caching is off)",
            file=sys.stderr,
        )
        return 1
    return result["exit_code"]


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m pta_replicator_tpu lint",
        description="graftlint: JAX/thread/telemetry invariant checker",
    )
    ap.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: the "
                         "package, scripts/, benchmarks/, bench.py)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help="baseline JSON (default: "
                         "pta_replicator_tpu/analysis/baseline.json)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline with every current "
                         "finding and exit 0 (use sparingly: the "
                         "baseline is a ratchet, not a dumping ground)")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="drop stale fingerprints (fixed findings) from "
                         "the baseline, keep the rest, exit 0")
    ap.add_argument("--changed-only", action="store_true",
                    help="report only findings in files differing from "
                         "main (plus uncommitted work); the analysis "
                         "still runs whole-program")
    ap.add_argument("--explain", default=None, metavar="RULE",
                    help="print a rule's documentation plus a firing "
                         "and a non-firing example, then exit")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the incremental cache "
                         "(.graftlint-cache.json at the repo root)")
    ap.add_argument("--expect-warm", action="store_true",
                    help="exit 1 unless this run was served entirely "
                         "from the warm cache (CI guard: the cache must "
                         "hit on an unchanged tree)")
    args = ap.parse_args(argv)
    try:
        if args.explain is not None:
            return explain_rule(args.explain, sys.stdout)
        return run_lint(
            args.paths,
            fmt=args.format,
            baseline=args.baseline,
            update_baseline=args.update_baseline,
            prune_baseline=args.prune_baseline,
            changed_only=args.changed_only,
            use_cache=not args.no_cache,
            expect_warm=args.expect_warm,
        )
    except (OSError, ValueError) as exc:
        print(f"graftlint: {exc}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
