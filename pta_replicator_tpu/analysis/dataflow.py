"""Bounded interprocedural dataflow over the project call graph.

A deliberately small taint-style framework: facts about *function
parameters* and *return values* propagate through call arguments and
returns, iterated to a bounded fixpoint over the whole
:class:`~.callgraph.CallGraph`. No abstract interpretation, no path
conditions — each rule supplies a per-function *scan* that reads the
current summary table and produces this function's facts; the engine
re-scans until the table stops changing (or the round bound trips,
which truncates to an under-approximation: interprocedural rules built
here may miss deep chains but never invent facts from stale rounds).

Shipped fact kinds (what :mod:`.rules_interproc` needs today):

* :func:`key_consumer_params` — which parameters of each function flow
  into a ``jax.random`` *sampler* (directly, or by being passed on to
  another function's key-consuming parameter). Flow-sensitive per
  function: a rebinding of the name before the consuming call kills the
  fact, mirroring the per-module ``jax-key-reuse`` semantics. Each fact
  carries a witness chain for finding messages.
* :func:`fresh_key_returns` — functions whose return value is a freshly
  derived PRNG key (``split``/``fold_in``/``PRNGKey``/``clone`` result,
  or transitively another fresh-key-returning call), so callers'
  ``key = derive(key, i)`` rebindings register as key-variable makers
  even when the maker lives in another module.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Dict, Optional, Set, Tuple

from .callgraph import CallGraph, FunctionInfo, arg_bindings, iter_body_nodes

#: fixpoint round bound: facts deeper than this many call layers are
#: dropped (bounded-depth truncation, never stale propagation)
MAX_ROUNDS = 8

_KEY_MAKERS = {"PRNGKey", "key", "split", "fold_in", "clone"}
#: jax.random callables that *inspect* a key without drawing from it
#: (serialization/introspection): passing a key here is not consumption
_NON_CONSUMING = {"key_data", "wrap_key_data", "key_impl"}
_RANDOM_PREFIX = "jax.random."


def fixpoint(
    graph: CallGraph,
    scan: Callable[[FunctionInfo, Dict[str, object]], object],
    max_rounds: int = MAX_ROUNDS,
) -> Dict[str, object]:
    """Iterate ``scan(info, summaries)`` over every function until the
    summary table is stable (or ``max_rounds``). ``scan`` must be
    monotone in the summaries it reads for the bound to truncate safely.
    """
    summaries: Dict[str, object] = {}
    order = sorted(graph.index.functions)
    for _ in range(max_rounds):
        changed = False
        for sym in order:
            facts = scan(graph.index.functions[sym], summaries)
            if facts != summaries.get(sym):
                summaries[sym] = facts
                changed = True
        if not changed:
            break
    return summaries


# -------------------------------------------------------- key dataflow
@dataclasses.dataclass(frozen=True)
class KeyConsume:
    """Parameter ``param`` of a function reaches a jax.random sampler —
    ``witness`` is the call chain (display strings) from the function
    down to the sampler call."""

    param: str
    witness: Tuple[str, ...]


def _is_sampler(mod, call: ast.Call) -> Optional[str]:
    """Resolved jax.random sampler name for a call (``normal``,
    ``uniform``, ...), None for makers/non-random calls."""
    resolved = mod.resolve(call.func) or ""
    if not resolved.startswith(_RANDOM_PREFIX):
        return None
    terminal = resolved.rsplit(".", 1)[-1]
    if terminal in _KEY_MAKERS or terminal in _NON_CONSUMING:
        return None
    return terminal


def _is_key_maker_call(mod, call: ast.Call) -> bool:
    resolved = mod.resolve(call.func) or ""
    return (
        resolved.startswith(_RANDOM_PREFIX)
        and resolved.rsplit(".", 1)[-1] in _KEY_MAKERS
    )


def _line_order(node: ast.AST) -> Tuple[int, int]:
    return (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))


def _assigned_names(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    else:
        return out
    for t in targets:
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            out.update(e.id for e in t.elts if isinstance(e, ast.Name))
    return out


def key_consumer_params(graph: CallGraph) -> Dict[str, Dict[str, Tuple[str, ...]]]:
    """symbol -> {param name -> witness chain} for every parameter that
    flows into a jax.random sampler before being rebound."""

    def scan(info: FunctionInfo, summaries):
        params = set(info.param_names()) | set(info.kwonly_names())
        if not params:
            return {}
        mod = info.module
        events = []  # (order, kind, name, witness)
        for node in iter_body_nodes(info.node):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                for name in _assigned_names(node):
                    events.append((_line_order(node), "rebind", name, ()))
            if not isinstance(node, ast.Call):
                continue
            sampler = _is_sampler(mod, node)
            if sampler is not None and node.args and isinstance(
                node.args[0], ast.Name
            ):
                events.append((
                    _line_order(node), "consume", node.args[0].id,
                    (f"jax.random.{sampler}",),
                ))
                continue
            callee = graph.resolve_call(mod, node.func, info)
            if callee is None:
                continue
            callee_facts = summaries.get(callee.symbol) or {}
            if not callee_facts:
                continue
            for pname, arg in arg_bindings(node, callee):
                if pname in callee_facts and isinstance(arg, ast.Name):
                    events.append((
                        _line_order(node), "consume", arg.id,
                        (callee.display,) + tuple(callee_facts[pname]),
                    ))
        facts: Dict[str, Tuple[str, ...]] = {}
        for _order, kind, name, witness in sorted(
            events, key=lambda e: e[0]
        ):
            if kind == "rebind":
                params.discard(name)
            elif name in params and name not in facts:
                facts[name] = witness
        return facts

    return fixpoint(graph, scan)  # type: ignore[return-value]


def fresh_key_returns(graph: CallGraph) -> Set[str]:
    """Symbols of functions whose return value is a freshly derived
    PRNG key (directly or through another fresh-key-returning call)."""

    def scan(info: FunctionInfo, summaries):
        mod = info.module
        if isinstance(info.node, ast.Lambda):
            returns = [info.node.body]
        else:
            returns = [
                n.value for n in iter_body_nodes(info.node)
                if isinstance(n, ast.Return) and n.value is not None
            ]
        for value in returns:
            # split(...)[0] / tuple returns: look through subscripts
            expr = value.value if isinstance(value, ast.Subscript) else value
            if not isinstance(expr, ast.Call):
                continue
            if _is_key_maker_call(mod, expr):
                return True
            callee = graph.resolve_call(mod, expr.func, info)
            if callee is not None and summaries.get(callee.symbol):
                return True
        return False

    table = fixpoint(graph, scan)
    return {sym for sym, fresh in table.items() if fresh}
