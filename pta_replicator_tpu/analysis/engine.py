"""graftlint rule engine: AST walk, findings, suppressions, baseline.

The engine is deliberately dumb and fast: it parses every target file
once (``ast`` + ``tokenize``, no imports of the linted code, no jax),
hands each :class:`Module` to every rule, and post-processes the
findings through two escape hatches:

* **inline suppression** — a ``# graftlint: disable=<rule>[,<rule>...]``
  comment suppresses findings of those rules *on that line* (``all``
  suppresses every rule). Suppressions are for findings that are
  *intentional* — the comment is the reviewer-visible record of why.
* **baseline** — ``analysis/baseline.json`` holds fingerprints of
  grandfathered findings so the gate starts green on a tree with known
  debt and ratchets: a finding in the baseline is reported as
  "baselined", a finding NOT in the baseline fails the run. Fingerprints
  hash (rule, path, message) — not line numbers — so unrelated edits
  above a grandfathered finding don't break the gate.

Rules subclass :class:`Rule` and implement ``check_module`` (per-file)
or ``check_project`` (cross-file, e.g. instrumentation coverage). Rule
ids are kebab-case strings namespaced by pack (``jax-host-sync``,
``thread-walltime-duration``, ``telemetry-unknown-name``).
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"

BASELINE_VERSION = 1

_SUPPRESS_RE = re.compile(r"graftlint:\s*disable=([A-Za-z0-9_\-, ]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to ``path:line``."""

    rule: str
    severity: str
    path: str  # posix relpath from the lint root
    line: int
    message: str

    @property
    def fingerprint(self) -> str:
        """Baseline identity: stable under edits that only move lines."""
        key = f"{self.rule}|{self.path}|{self.message}"
        return hashlib.sha256(key.encode()).hexdigest()[:16]

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} [{self.severity}] " \
               f"{self.message}"

    def to_json(self) -> dict:
        return {**dataclasses.asdict(self), "fingerprint": self.fingerprint}


class Module:
    """A parsed lint target: source, AST, parent links, import aliases,
    and the per-line suppression table."""

    def __init__(self, path: str, root: str, source: Optional[str] = None):
        self.path = os.path.abspath(path)
        self.relpath = os.path.relpath(self.path, os.path.abspath(root))
        self.relpath = self.relpath.replace(os.sep, "/")
        if source is None:
            with open(self.path, encoding="utf-8", errors="replace") as fh:
                source = fh.read()
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=self.relpath)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.imports = self._import_aliases()
        self.suppressions = self._suppressions()

    # -- suppressions ---------------------------------------------------
    def _suppressions(self) -> Dict[int, set]:
        table: Dict[int, set] = {}
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if m:
                    rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                    table.setdefault(tok.start[0], set()).update(rules)
        except tokenize.TokenizeError:
            pass
        return table

    def is_suppressed(self, finding: Finding) -> bool:
        rules = self.suppressions.get(finding.line)
        return bool(rules) and (finding.rule in rules or "all" in rules)

    # -- import / name resolution ---------------------------------------
    def _import_aliases(self) -> Dict[str, str]:
        """Local name -> dotted origin. Relative imports are resolved
        with leading dots stripped (``from ..obs import span`` maps
        ``span`` -> ``obs.span``) — rules match with suffix checks, so
        the exact package prefix doesn't matter."""
        aliases: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    origin = f"{base}.{a.name}" if base else a.name
                    aliases[a.asname or a.name] = origin
        return aliases

    def qualname(self, node: ast.AST) -> Optional[str]:
        """Dotted source text of a Name/Attribute chain (``jax.random.split``),
        or None for anything more dynamic."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        return None

    def resolve(self, node: ast.AST) -> Optional[str]:
        """:meth:`qualname` with the head rewritten through the module's
        import aliases: ``jnp.float64`` -> ``jax.numpy.float64``,
        ``_traced`` -> ``obs.trace.traced``."""
        qn = self.qualname(node)
        if qn is None:
            return None
        head, _, rest = qn.partition(".")
        origin = self.imports.get(head)
        if origin is None:
            return qn
        return f"{origin}.{rest}" if rest else origin

    def ancestors(self, node: ast.AST):
        node = self.parents.get(node)
        while node is not None:
            yield node
            node = self.parents.get(node)


class Rule:
    """Base class: subclasses set ``id``/``severity``/``description`` and
    override one of the check hooks."""

    id: str = "abstract"
    severity: str = SEVERITY_ERROR
    description: str = ""
    #: short firing / non-firing source examples for ``lint --explain``
    example_fire: str = ""
    example_ok: str = ""

    def check_module(self, mod: Module) -> Iterable[Finding]:
        return ()

    def check_project(self, mods: Sequence[Module]) -> Iterable[Finding]:
        return ()

    def finding(self, mod_or_path, line: int, message: str) -> Finding:
        path = (
            mod_or_path.relpath if isinstance(mod_or_path, Module)
            else str(mod_or_path)
        )
        return Finding(self.id, self.severity, path, line, message)


def default_rules() -> List[Rule]:
    """The shipped rule packs (imported lazily to avoid cycles)."""
    from . import (
        rules_bench,
        rules_cov,
        rules_interproc,
        rules_jax,
        rules_obs,
        rules_robust,
        rules_scenarios,
        rules_telemetry,
        rules_threads,
    )

    return [
        *rules_jax.RULES,
        *rules_threads.RULES,
        *rules_telemetry.RULES,
        *rules_obs.RULES,
        *rules_robust.RULES,
        *rules_scenarios.RULES,
        *rules_cov.RULES,
        *rules_bench.RULES,
        *rules_interproc.RULES,
    ]


# ---------------------------------------------------------- file walking
_SKIP_DIRS = {
    ".git", "__pycache__", ".pytest_cache", "build", "dist", ".eggs",
    "node_modules",
}


def iter_python_files(paths: Sequence[str], root: str) -> List[str]:
    """Expand files/directories into a sorted, de-duplicated .py list."""
    out = []
    seen = set()
    for p in paths:
        p = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(p):
            candidates = [p]
        elif os.path.isdir(p):
            candidates = []
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in _SKIP_DIRS
                )
                candidates.extend(
                    os.path.join(dirpath, f)
                    for f in sorted(filenames) if f.endswith(".py")
                )
        else:
            continue
        for c in candidates:
            c = os.path.abspath(c)
            if c.endswith(".py") and c not in seen:
                seen.add(c)
                out.append(c)
    return out


def parse_modules(
    files: Sequence[str], root: str,
    sources: Optional[Dict[str, str]] = None,
) -> Tuple[List[Module], List[Finding]]:
    """Parse every file; a syntax error becomes a finding, not a crash
    (the linter must be able to report on a broken tree). ``sources``
    (abspath -> text) lets callers that already read the files for
    hashing skip the second read."""
    mods, problems = [], []
    for path in files:
        try:
            source = None if sources is None else sources.get(
                os.path.abspath(path)
            )
            mods.append(Module(path, root, source=source))
        except SyntaxError as exc:
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            problems.append(Finding(
                "syntax-error", SEVERITY_ERROR, rel, exc.lineno or 1,
                f"cannot parse: {exc.msg}",
            ))
    return mods, problems


def _finding_sort_key(f: Finding):
    return (f.path, f.line, f.rule, f.message)


def _classify(
    findings: Iterable[Finding], by_rel: Dict[str, Module]
) -> Tuple[List[Finding], List[Finding]]:
    """Split raw rule output into (active, suppressed) through the
    per-line inline-suppression tables."""
    active: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        mod = by_rel.get(f.path)
        if mod is not None and mod.is_suppressed(f):
            suppressed.append(f)
        else:
            active.append(f)
    return active, suppressed


def run_module_rules(
    mod: Module, rules: Sequence[Rule]
) -> Tuple[List[Finding], List[Finding]]:
    """Per-file layer only: every rule's ``check_module`` over one
    module. Cacheable per file — depends on this source (plus whatever
    its direct imports contribute to name resolution) and the rules."""
    raw: List[Finding] = []
    for rule in rules:
        raw.extend(rule.check_module(mod))
    return _classify(raw, {mod.relpath: mod})


def run_project_rules(
    mods: Sequence[Module], rules: Sequence[Rule]
) -> Tuple[List[Finding], List[Finding]]:
    """Cross-file layers (project rules + interprocedural passes): every
    rule's ``check_project`` over the full module list. Never cached
    per-file — any source change can shift a cross-file fact."""
    by_rel = {m.relpath: m for m in mods}
    raw: List[Finding] = []
    for rule in rules:
        raw.extend(rule.check_project(mods))
    return _classify(raw, by_rel)


def run_rules(
    mods: Sequence[Module], rules: Optional[Sequence[Rule]] = None
) -> Tuple[List[Finding], List[Finding]]:
    """Run every rule; returns (active findings, suppressed findings),
    both sorted by (path, line, rule)."""
    rules = list(rules) if rules is not None else default_rules()
    active: List[Finding] = []
    suppressed: List[Finding] = []
    for mod in mods:
        a, s = run_module_rules(mod, rules)
        active.extend(a)
        suppressed.extend(s)
    a, s = run_project_rules(mods, rules)
    active.extend(a)
    suppressed.extend(s)
    return (
        sorted(active, key=_finding_sort_key),
        sorted(suppressed, key=_finding_sort_key),
    )


# -------------------------------------------------------------- baseline
def load_baseline(path: Optional[str]) -> Dict[str, dict]:
    """fingerprint -> baseline entry; {} for a missing/empty baseline."""
    if not path or not os.path.exists(path):
        return {}
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"{path}: baseline version {doc.get('version')!r} != "
            f"{BASELINE_VERSION} (regenerate with --update-baseline)"
        )
    return {e["fingerprint"]: e for e in doc.get("findings", [])}


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    doc = {
        "version": BASELINE_VERSION,
        "comment": (
            "grandfathered graftlint findings — keep SMALL; new code "
            "must lint clean or carry an inline suppression with a "
            "reason. Regenerate with: python -m pta_replicator_tpu "
            "lint --update-baseline"
        ),
        "findings": [f.to_json() for f in findings],
    }
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")


def apply_baseline(
    findings: Sequence[Finding], baseline: Dict[str, dict]
) -> Tuple[List[Finding], List[Finding], List[dict]]:
    """Split into (new, grandfathered) and report stale baseline entries
    (fixed findings that should be dropped from the baseline — they are
    a warning, not a failure, so fixing debt never blocks a PR)."""
    new, old = [], []
    seen = set()
    for f in findings:
        if f.fingerprint in baseline:
            old.append(f)
            seen.add(f.fingerprint)
        else:
            new.append(f)
    stale = [e for fp, e in sorted(baseline.items()) if fp not in seen]
    return new, old, stale


# ----------------------------------------------------------- change scope
def filter_changed(files: Sequence[str], changed: Sequence[str],
                   root: str) -> List[str]:
    """Restrict ``files`` to those named in ``changed`` (repo-relative
    paths, as ``git diff --name-only`` prints them)."""
    changed_abs = {
        os.path.abspath(os.path.join(root, c)) for c in changed
    }
    return [f for f in files if os.path.abspath(f) in changed_abs]


def git_changed_files(root: str, base: str = "main") -> Optional[List[str]]:
    """Files differing from ``base`` plus uncommitted/untracked work.
    None when git is unavailable (callers then lint everything)."""
    import subprocess

    def _git(*args):
        return subprocess.run(
            ["git", *args], cwd=root, capture_output=True, text=True,
            timeout=30,
        )

    changed = set()
    diff = _git("diff", "--name-only", f"{base}...HEAD")
    if diff.returncode != 0:
        # shallow clone or detached base: fall back to plain HEAD diff
        diff = _git("diff", "--name-only", "HEAD")
        if diff.returncode != 0:
            return None
    changed.update(line for line in diff.stdout.splitlines() if line)
    status = _git("status", "--porcelain")
    if status.returncode == 0:
        for line in status.stdout.splitlines():
            if len(line) > 3:
                changed.add(line[3:].split(" -> ")[-1].strip())
    return sorted(changed)


# ------------------------------------------------------------- top level
def lint(
    paths: Sequence[str],
    root: str,
    rules: Optional[Sequence[Rule]] = None,
    baseline_path: Optional[str] = None,
    changed_only: bool = False,
    changed_files: Optional[Sequence[str]] = None,
    cache_path: Optional[str] = None,
) -> dict:
    """Run the engine end to end; returns a result dict with keys
    ``new`` / ``baselined`` / ``suppressed`` (Finding lists), ``stale``
    (baseline entries), ``files`` (count), ``cache`` (state string), and
    ``exit_code``.

    ``--changed-only`` is a *report* filter, not an analysis filter: the
    engine always parses and runs every rule over the full file set
    (cross-file facts from unchanged files must keep informing findings
    in changed files, and stale-baseline detection needs the full
    picture), then restricts the reported new/baselined/suppressed
    findings to the changed scope. ``changed_files`` overrides the git
    query for tests.

    ``cache_path`` enables the two-tier incremental cache
    (:mod:`.cache`). Only the default rule set is ever cached — passing
    explicit ``rules`` bypasses it, since cache keys don't encode
    out-of-tree rule code.
    """
    files = iter_python_files(paths, root)
    note = None
    scope: Optional[set] = None
    if changed_only:
        changed = (
            list(changed_files) if changed_files is not None
            else git_changed_files(root)
        )
        if changed is None:
            note = "--changed-only: git unavailable, linting everything"
        else:
            changed_abs = {
                os.path.abspath(os.path.join(root, c)) for c in changed
            }
            scope = {
                os.path.relpath(f, os.path.abspath(root)).replace(os.sep, "/")
                for f in files if os.path.abspath(f) in changed_abs
            }

    sources: Dict[str, str] = {}
    rels: Dict[str, str] = {}
    abs_root = os.path.abspath(root)
    for path in files:
        apath = os.path.abspath(path)
        with open(apath, encoding="utf-8", errors="replace") as fh:
            sources[apath] = fh.read()
        rels[apath] = os.path.relpath(apath, abs_root).replace(os.sep, "/")

    cache = None
    cache_state = "off"
    active: Optional[List[Finding]] = None
    suppressed: List[Finding] = []
    if cache_path is not None and rules is None:
        from . import cache as cache_mod

        cache = cache_mod.LintCache.load(cache_path)
        env = cache_mod.env_signature()
        hashes = {
            rels[a]: cache_mod.file_digest(src)
            for a, src in sources.items()
        }
        tkey = cache_mod.tree_key(hashes, env)
        hit = cache.lookup_tree(tkey)
        if hit is not None:
            active, suppressed, _ = hit
            cache_state = "warm"

    if active is None:
        mods, parse_problems = parse_modules(files, root, sources)
        rule_list = list(rules) if rules is not None else default_rules()
        mod_active: List[Finding] = []
        mod_suppressed: List[Finding] = []
        if cache is not None:
            from . import cache as cache_mod

            igraph = cache_mod.project_import_graph(mods)
            for mod in mods:
                mkey = cache_mod.module_key(
                    mod.relpath, hashes, igraph.get(mod.relpath, set()),
                    env,
                )
                cached = cache.lookup_module(mod.relpath, mkey)
                if cached is None:
                    a, s = run_module_rules(mod, rule_list)
                    cache.store_module(mod.relpath, mkey, a, s)
                else:
                    a, s = cached
                mod_active.extend(a)
                mod_suppressed.extend(s)
        else:
            for mod in mods:
                a, s = run_module_rules(mod, rule_list)
                mod_active.extend(a)
                mod_suppressed.extend(s)
        proj_active, proj_suppressed = run_project_rules(mods, rule_list)
        active = sorted(
            mod_active + proj_active, key=_finding_sort_key
        )
        suppressed = sorted(
            mod_suppressed + proj_suppressed, key=_finding_sort_key
        )
        active = parse_problems + active
        if cache is not None:
            cache_state = "cold" if cache.hits == 0 else "partial"
            cache.store_tree(tkey, active, suppressed, len(files))
            cache.prune(set(rels.values()))
            cache.save()

    baseline = load_baseline(baseline_path)
    new, old, stale = apply_baseline(active, baseline)
    if scope is not None:
        new = [f for f in new if f.path in scope]
        old = [f for f in old if f.path in scope]
        suppressed = [f for f in suppressed if f.path in scope]
    return {
        "new": new,
        "baselined": old,
        "suppressed": suppressed,
        "stale": stale,
        "files": len(files),
        "scoped": None if scope is None else len(scope),
        "note": note,
        "cache": cache_state,
        "exit_code": 1 if new else 0,
    }
