"""graftlint rule pack: benchmark gate discipline.

Every benchmark under ``benchmarks/`` is a CI gate: it measures, it
checks, and on failure it exits nonzero so ``scripts/check.sh`` goes
red. The repo-wide idiom (docs/performance.md) is that the red exit is
always paired with a *reason* on stderr::

    print(f"stage_graph GATE FAIL: {reason}", file=sys.stderr)
    return 1

The anti-pattern this pack polices is the silent gate::

    if not ok:
        return 1        # CI goes red; the log says nothing

A silent nonzero exit is the worst failure mode a gate can have: the
round is blocked, the artifact is missing, and the only diagnostic is
an exit status — the investigating human re-runs the whole benchmark
under a debugger just to learn which assertion tripped. Hence:

* ``bench-silent-gate`` — inside ``benchmarks/*.py`` (and nowhere
  else: package modules return status codes for all sorts of reasons),
  flag a gate-failure exit — ``sys.exit(<nonzero int>)``,
  ``raise SystemExit(<nonzero int>)``, or ``return <nonzero int>``
  from a ``main``/``run*`` function (the repo's gate-arm naming) —
  that is not preceded, on the same control-flow path, by a write to
  stderr (``print(..., file=sys.stderr)`` or ``sys.stderr.write``).

What does NOT fire, by design:

- ``sys.exit(main())`` / ``sys.exit(rc)`` — non-constant exit codes
  are dispatch, not a gate verdict; the verdict site is where the
  constant is.
- ``sys.exit("message")`` / ``raise SystemExit("message")`` — the
  interpreter prints a string argument to stderr itself; the reason
  is built in.
- ``return 1`` in helpers not named ``main``/``run*`` — a literal
  int return value is only an exit code in the entrypoint/arm
  functions; elsewhere it is just a value.

Path sensitivity is block-chain scoped: a stderr write anywhere in a
statement *preceding* the exit within the same (or an enclosing)
block covers it — so the common ``for f in failures: print(...,
file=sys.stderr)`` loop before ``return 1`` counts, while a reason
printed only in the *other* arm of the ``if`` does not. A call to a
module-local helper whose own body writes stderr (the ``def
log(msg): print(..., file=sys.stderr)`` idiom) counts too — one
level of indirection, resolved within the file. Exits whose reason
goes through a helper imported from elsewhere carry an inline
``# graftlint: disable=bench-silent-gate`` with the reason.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from .engine import Finding, Module, Rule

#: the subtree this pack polices (posix relpath prefix) — note the
#: INVERTED scope relative to the other packs: benchmarks only
BENCH_PREFIX = "benchmarks/"

#: function-name shapes whose ``return <int>`` is an exit code by repo
#: convention (``sys.exit(main())`` entrypoints and the run_arm/run_*
#: gate arms) rather than an ordinary value
_EXIT_CODE_FUNCS = ("main", "run")


def _nonzero_int(node: Optional[ast.AST]) -> bool:
    return (
        isinstance(node, ast.Constant)
        and type(node.value) is int
        and node.value != 0
    )


def _exit_code_func(name: str) -> bool:
    return name == _EXIT_CODE_FUNCS[0] or name.startswith(
        _EXIT_CODE_FUNCS[1]
    )


def _is_silent_exit(mod: Module, stmt: ast.stmt,
                    in_exit_func: bool) -> bool:
    """True when ``stmt`` terminates the process (or the gate arm)
    with a literal nonzero status and no intrinsic stderr output."""
    if isinstance(stmt, ast.Return):
        return in_exit_func and _nonzero_int(stmt.value)
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        call = stmt.value
        return (
            mod.resolve(call.func) == "sys.exit"
            and len(call.args) == 1
            and _nonzero_int(call.args[0])
        )
    if isinstance(stmt, ast.Raise) and isinstance(stmt.exc, ast.Call):
        call = stmt.exc
        return (
            (mod.resolve(call.func) or "").endswith("SystemExit")
            and len(call.args) == 1
            and _nonzero_int(call.args[0])
        )
    return False


def _writes_stderr_direct(mod: Module, stmt: ast.AST) -> bool:
    """True when any call inside ``stmt`` puts text on stderr
    directly: ``print(..., file=sys.stderr)`` or
    ``sys.stderr.write(...)``."""
    for node in ast.walk(stmt):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "write":
            if mod.resolve(fn.value) == "sys.stderr":
                return True
        if mod.resolve(fn) == "print":
            for kw in node.keywords:
                if kw.arg == "file" and (
                    mod.resolve(kw.value) == "sys.stderr"
                ):
                    return True
    return False


def _stderr_helpers(mod: Module) -> frozenset:
    """Names of module-level functions whose own body writes stderr —
    the local ``log``/``fail`` helper idiom. One level only: a helper
    calling another helper does not transitively qualify."""
    names = set()
    for stmt in mod.tree.body:
        if isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
        ) and any(
            _writes_stderr_direct(mod, sub) for sub in stmt.body
        ):
            names.add(stmt.name)
    return frozenset(names)


def _writes_stderr(mod: Module, stmt: ast.stmt,
                   helpers: frozenset) -> bool:
    if _writes_stderr_direct(mod, stmt):
        return True
    for node in ast.walk(stmt):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in helpers
        ):
            return True
    return False


def _child_blocks(stmt: ast.stmt) -> List[List[ast.stmt]]:
    """The statement lists nested one level under ``stmt`` (if/else
    arms, loop bodies, with bodies, try arms) — NOT function bodies,
    which open a fresh scan scope."""
    blocks: List[List[ast.stmt]] = []
    for field in ("body", "orelse", "finalbody"):
        sub = getattr(stmt, field, None)
        if isinstance(sub, list) and sub and isinstance(
            sub[0], ast.stmt
        ):
            blocks.append(sub)
    for handler in getattr(stmt, "handlers", []) or []:
        blocks.append(handler.body)
    return blocks


class SilentGate(Rule):
    id = "bench-silent-gate"
    severity = "error"
    description = (
        "benchmark gate failure exits nonzero without printing the "
        "reason to stderr — CI goes red with an empty log"
    )
    example_fire = (
        "if regression > budget:\n"
        "    sys.exit(1)                  # red CI, empty log: FIRES\n"
    )
    example_ok = (
        "if regression > budget:\n"
        "    print(f'gate: {regression:.1%} > {budget:.1%}',\n"
        "          file=sys.stderr)\n"
        "    sys.exit(1)\n"
    )

    def _scan(
        self,
        mod: Module,
        body: List[ast.stmt],
        seen_stderr: bool,
        in_exit_func: bool,
        helpers: frozenset,
        out: List[Tuple[int, str]],
    ) -> None:
        seen = seen_stderr
        for stmt in body:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                # fresh path: a gate arm must print its own reason,
                # not inherit one from module import time
                self._scan(
                    mod, stmt.body, False,
                    _exit_code_func(stmt.name), helpers, out,
                )
                continue
            if _is_silent_exit(mod, stmt, in_exit_func) and not seen:
                kind = (
                    "returns" if isinstance(stmt, ast.Return)
                    else "exits"
                )
                out.append((
                    stmt.lineno,
                    f"gate-failure branch {kind} nonzero with no "
                    "stderr reason on the path: add a "
                    "'<bench> GATE FAIL: <why>' print(..., "
                    "file=sys.stderr) before it — or suppress "
                    "inline with the reason",
                ))
            for sub in _child_blocks(stmt):
                self._scan(mod, sub, seen, in_exit_func, helpers, out)
            if _writes_stderr(mod, stmt, helpers):
                seen = True

    def check_module(self, mod: Module) -> Iterable[Finding]:
        if not mod.relpath.startswith(BENCH_PREFIX):
            return
        helpers = _stderr_helpers(mod)
        hits: List[Tuple[int, str]] = []
        self._scan(mod, mod.tree.body, False, False, helpers, hits)
        for lineno, msg in hits:
            yield self.finding(mod, lineno, msg)


RULES = [SilentGate()]
