"""graftlint rule pack: covariance-factorization precision discipline.

A Cholesky factorization (or the triangular solve consuming its
factor) squares the conditioning of whatever feeds it, and at float32
that silently eats half the mantissa — exactly the failure class the
``covariance/`` subsystem's f64-oracle pinning exists to catch
(docs/covariance.md "Precision"). The discipline the pack enforces:

* ``cov-f32-cholesky`` — a ``cholesky``/``solve_triangular`` call in
  package code must either show an explicit float64 cast in its
  argument expression (``np.linalg.cholesky(np.asarray(C,
  np.float64))``, an ``.astype(np.float64)``, an x64-dtype operand
  built in the same call) or carry an inline
  ``# graftlint: disable=cov-f32-cholesky`` naming WHY the caller's
  dtype is safe (an oracle-pinned kernel, a documented f64-only host
  path, a validated f32 serving path). Silent caller-dtype
  factorizations are how a TPU f32 default turns into quietly wrong
  uncertainties.

Suppressions are accepted on the call line itself, the line directly
above it (the readable home for a long reason), or any line inside a
multi-line call — the engine's same-line filter still applies on top.

Test files, benchmarks, and examples are exempt: they pin or exercise
precision deliberately.
"""
from __future__ import annotations

import ast
from typing import Iterable

from .engine import Finding, Module, Rule

#: callee suffixes the rule polices (resolved dotted names)
_FACTOR_SUFFIXES = (".cholesky", ".solve_triangular")
_FACTOR_BARE = ("cholesky", "solve_triangular")

#: subtree markers that count as an explicit f64 cast
_F64_MARKERS = ("float64",)


def _is_package_file(relpath: str) -> bool:
    rel = relpath.replace("\\", "/")
    if not rel.startswith("pta_replicator_tpu/"):
        return False
    base = rel.rsplit("/", 1)[-1]
    return not (base.startswith("test_") or base == "conftest.py")


def _mentions_float64(node: ast.AST) -> bool:
    """True when the call's argument expressions visibly carry an f64
    cast: a ``float64`` attribute/name anywhere in the subtree (covers
    ``np.float64``, ``jnp.float64``, ``.astype(np.float64)``,
    ``np.asarray(x, np.float64)``, ``dtype=np.float64``)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _F64_MARKERS:
            return True
        if isinstance(sub, ast.Name) and sub.id in _F64_MARKERS:
            return True
        if isinstance(sub, ast.Constant) and sub.value == "float64":
            return True
    return False


class CovF32Cholesky(Rule):
    id = "cov-f32-cholesky"
    severity = "error"
    example_fire = (
        "L = jnp.linalg.cholesky(c)       # caller dtype unknown: FIRES\n"
    )
    example_ok = (
        "L = jnp.linalg.cholesky(c.astype(jnp.float64))\n"
    )
    description = (
        "cholesky/solve_triangular call without an explicit float64 "
        "cast or an inline suppression naming why the caller dtype is "
        "safe: factorizations square the conditioning, and an f32 "
        "default silently halves the mantissa of every downstream "
        "uncertainty (docs/covariance.md)"
    )

    def check_module(self, mod: Module) -> Iterable[Finding]:
        if not _is_package_file(mod.relpath):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = mod.resolve(node.func) or ""
            bare = (node.func.id if isinstance(node.func, ast.Name)
                    else getattr(node.func, "attr", ""))
            if not (resolved.endswith(_FACTOR_SUFFIXES)
                    or bare in _FACTOR_BARE):
                continue
            if _mentions_float64(node):
                continue
            # suppression window: the call line, the line above it, or
            # any line inside a multi-line call (the engine filters the
            # same-line case again; this widens to the readable homes)
            end = max(
                (getattr(n, "lineno", node.lineno)
                 for n in ast.walk(node)),
                default=node.lineno,
            )
            if any(
                self.id in mod.suppressions.get(ln, ())
                for ln in range(node.lineno - 1, end + 1)
            ):
                continue
            name = resolved or bare
            yield self.finding(
                mod, node.lineno,
                f"{name} at the caller's dtype: add an explicit "
                "float64 cast in the call, or suppress inline with the "
                "reason f32 is safe here",
            )


RULES = [CovF32Cholesky()]
