"""graftlint rule pack: whole-program interprocedural passes.

These rules run as project rules over the :mod:`.callgraph` /
:mod:`.dataflow` substrate and catch the defect classes the per-module
packs provably cannot see:

* ``jax-host-sync`` (interprocedural) — a host sync (``.item()``,
  ``.block_until_ready()``, ``np.asarray``, ``float(x)``) in any
  function *reachable from* a jit-traced entry, including entries
  wrapped in another module (``instrumented_jit(helper_from_b)``). The
  finding message prints the call chain from the entry to the sync.
* ``jax-key-reuse`` (interprocedural) — a PRNG key consumed twice where
  at least one consumption happens *through* a helper call (the key
  flows into a parameter that reaches a ``jax.random`` sampler,
  possibly in another module), or where the key itself was derived by a
  helper (``key = derive(seed)`` whose body ends in ``split``/
  ``fold_in``). The per-module rule only sees direct sampler calls on
  module-visible key variables.
* ``thread-shared-state-race`` — collects every ``Thread(target=...)``
  / executor ``submit(fn)`` in the package, computes which instance
  attributes and module globals each spawned target (transitively)
  mutates and under which locks (``with`` context at the write site
  plus locks held along the call chain), and flags state written from
  two or more threads-of-control with no common lock. A target spawned
  in a loop (worker pools) races with its own siblings and counts as
  two threads by itself. Locks are matched by terminal name against the
  same convention :data:`.rules_threads.LOCK_HIERARCHY` records.
* ``telemetry-dead-name`` — a constant registered in ``obs/names.py``
  that no call site in the whole tree ever emits: never referenced by
  name in any linted module (or in ``tests/``), and its string value
  never appears at a producer call site. Dead names rot the registry —
  the report renderer and schema checker keep promising a signal nobody
  produces.

Module-covered findings are skipped: anything the per-module packs
already report (a sync lexically inside a decorated jit function, a
double direct-sampler consumption) never double-reports here.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from . import dataflow
from .callgraph import (
    CallGraph,
    FunctionInfo,
    arg_bindings,
    iter_body_nodes,
    project_graph,
)
from .engine import Finding, Module, Rule
from .rules_jax import (
    _decorator_is_jit,
    _is_jitlike_callable,
    _module_level_mutables,
    iter_host_syncs,
    jit_function_nodes,
)
from .rules_telemetry import NAMES_RELPATH, _PRODUCER_KINDS, _is_test_file
from .rules_threads import _MUTATOR_METHODS, _held_locks

#: methods that run before an object is published to other threads —
#: their writes are construction, not racing
_CONSTRUCTION_METHODS = {"__init__", "__new__", "__post_init__"}


# ------------------------------------------------------------ jit entries
def jit_entry_symbols(graph: CallGraph) -> Dict[str, str]:
    """symbol -> entry label for every function that ends up
    jit-compiled, including cross-module wrapper forms the per-module
    detector cannot attribute (``instrumented_jit(imported_helper)``)."""
    index = graph.index
    entries: Dict[str, str] = {}
    for mod in index.mods:
        for fn in jit_function_nodes(mod):
            info = index.by_node.get(id(fn))
            if info is not None:
                entries.setdefault(info.symbol, info.name)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            is_jit = _is_jitlike_callable(mod, node.func) or (
                isinstance(node.func, (ast.Name, ast.Attribute))
                and _decorator_is_jit(mod, node)
            )
            if not is_jit:
                continue
            enclosing = index.enclosing_info(mod, node)
            for sub in ast.walk(node.args[0]):
                if isinstance(sub, (ast.Name, ast.Attribute)):
                    info = graph.resolve_call(mod, sub, enclosing)
                    if info is not None:
                        entries.setdefault(info.symbol, info.name)
    return entries


def _tracer_barrier(info: FunctionInfo) -> bool:
    """True for functions that explicitly discriminate tracers from
    concrete values (``isinstance(x, jax.core.Tracer)``). Both shapes in
    the tree — raise-on-tracer guards and ``host_ok`` branching — mean
    the host-only body can never execute under a trace, so host syncs
    inside (or reached through) such a function are not jit syncs."""
    mod = info.module
    for node in ast.walk(info.node):
        if isinstance(node, (ast.Name, ast.Attribute)):
            resolved = mod.resolve(node) or ""
            if resolved.endswith("core.Tracer"):
                return True
    return False


def _module_covered(index) -> Set[str]:
    """Symbols whose body the per-module jax rules already scan (defs
    the module-local jit detector marks)."""
    covered: Set[str] = set()
    for mod in index.mods:
        for fn in jit_function_nodes(mod):
            info = index.by_node.get(id(fn))
            if info is not None:
                covered.add(info.symbol)
    return covered


class InterprocHostSync(Rule):
    """Host syncs in helpers reachable from a jit entry — the cross-
    module extension of the per-module ``jax-host-sync`` rule, with the
    call chain printed in the finding."""

    id = "jax-host-sync"
    severity = "error"
    description = (
        "host-device sync in a function reachable from a jit-traced "
        "entry (cross-module call chain printed in the finding)"
    )
    example_fire = (
        "# helpers.py\n"
        "def summarize(x):\n"
        "    return x.mean().item()       # host sync, two calls deep\n"
        "# engine.py\n"
        "from helpers import summarize\n"
        "@jax.jit\n"
        "def engine(x):\n"
        "    return summarize(x)\n"
    )
    example_ok = (
        "# engine.py\n"
        "@jax.jit\n"
        "def engine(x):\n"
        "    return x.mean()\n"
        "print(engine(x).item())          # sync outside the trace\n"
    )

    def check_project(self, mods: Sequence[Module]) -> Iterable[Finding]:
        graph = project_graph(mods)
        index = graph.index
        covered = _module_covered(index)
        entries = jit_entry_symbols(graph)
        barriers: Dict[str, bool] = {}

        def not_barrier(info: FunctionInfo) -> bool:
            sym = info.symbol
            if sym not in barriers:
                barriers[sym] = _tracer_barrier(info)
            return not barriers[sym]

        seen: Set[Tuple[str, int, str]] = set()
        for entry in sorted(entries):
            label = entries[entry]
            for sym, reach in sorted(
                graph.reachable_from(entry, predicate=not_barrier).items()
            ):
                if sym in covered:
                    continue  # the per-module rule already scans it
                info = index.functions[sym]
                if _is_test_file(info.relpath) or barriers.get(sym):
                    continue
                for node, head, tail in iter_host_syncs(
                    info.module, info.node
                ):
                    key = (info.relpath, node.lineno, head)
                    if key in seen:
                        continue
                    seen.add(key)
                    chain = graph.format_chain(reach.chain)
                    yield self.finding(
                        info.module, node.lineno,
                        f"{head} in {info.name!r} is reachable from jit "
                        f"entry {label!r}: {chain} — {tail}",
                    )


class InterprocKeyReuse(Rule):
    """PRNG key reuse where a consumption (or the key's derivation)
    crosses a function boundary — invisible to the per-module rule."""

    id = "jax-key-reuse"
    severity = "error"
    description = (
        "PRNG key consumed twice where a consumption or the key's "
        "derivation flows through a helper call (interprocedural)"
    )
    example_fire = (
        "# helpers.py\n"
        "def draw(key, shape):\n"
        "    return jax.random.normal(key, shape)\n"
        "# model.py\n"
        "from helpers import draw\n"
        "def realize(key):\n"
        "    a = draw(key, (4,))          # consumes key in helpers.py\n"
        "    key = jax.random.PRNGKey(0)  # (fresh key: no finding)\n"
        "    b = jax.random.uniform(key)\n"
        "    c = draw(key, (4,))          # second consumption: FIRES\n"
    )
    example_ok = (
        "def realize(key):\n"
        "    k1, k2 = jax.random.split(key)\n"
        "    a = draw(k1, (4,))\n"
        "    b = draw(k2, (4,))\n"
    )

    def check_project(self, mods: Sequence[Module]) -> Iterable[Finding]:
        graph = project_graph(mods)
        consumers = dataflow.key_consumer_params(graph)
        fresh = dataflow.fresh_key_returns(graph)
        for sym in sorted(graph.index.functions):
            info = graph.index.functions[sym]
            if _is_test_file(info.relpath) or isinstance(
                info.node, ast.Lambda
            ):
                continue
            yield from self._check_fn(graph, info, consumers, fresh)

    def _check_fn(self, graph, info, consumers, fresh):
        mod = info.module
        key_vars: Dict[str, str] = {}  # name -> "maker" | "helper"
        events: List[tuple] = []
        for node in iter_body_nodes(info.node):
            if isinstance(node, ast.Assign):
                value = node.value
                expr = value.value if isinstance(value, ast.Subscript) \
                    else value
                origin = None
                if isinstance(expr, ast.Call):
                    if dataflow._is_key_maker_call(mod, expr):
                        origin = "maker"
                    else:
                        callee = graph.resolve_call(mod, expr.func, info)
                        if callee is not None and callee.symbol in fresh:
                            origin = "helper"
                for name in dataflow._assigned_names(node):
                    events.append((
                        dataflow._line_order(node), "assign", name,
                        origin, (),
                    ))
            if not isinstance(node, ast.Call):
                continue
            sampler = dataflow._is_sampler(mod, node)
            if sampler is not None and node.args and isinstance(
                node.args[0], ast.Name
            ):
                events.append((
                    dataflow._line_order(node), "consume",
                    node.args[0].id, "direct",
                    (f"jax.random.{sampler}",),
                ))
                continue
            callee = graph.resolve_call(mod, node.func, info)
            if callee is None:
                continue
            facts = consumers.get(callee.symbol) or {}
            for pname, arg in arg_bindings(node, callee):
                if pname in facts and isinstance(arg, ast.Name):
                    events.append((
                        dataflow._line_order(node), "consume", arg.id,
                        "helper",
                        (callee.display,) + tuple(facts[pname]),
                    ))

        consumed: Dict[str, List[tuple]] = {}
        for order, kind, name, how, witness in sorted(
            events, key=lambda e: e[0]
        ):
            if kind == "assign":
                consumed[name] = []
                if how is not None:
                    key_vars[name] = how
                elif name in key_vars and how is None:
                    del key_vars[name]
            elif name in key_vars:
                consumed.setdefault(name, []).append(
                    (order, how, witness)
                )
                if len(consumed[name]) == 2:
                    first, second = consumed[name]
                    # the per-module rule already reports the all-local
                    # shape: maker-derived key + two direct samplers
                    if key_vars[name] == "maker" and first[1] == \
                            "direct" and second[1] == "direct":
                        continue
                    lineno = second[0][0]
                    chain = " -> ".join(
                        (info.display,) + second[2]
                    )
                    yield self.finding(
                        mod, lineno,
                        f"key {name!r} consumed twice in {info.name!r} "
                        "with no intervening split/fold_in; second "
                        f"consumption via {chain} — the two draws are "
                        "identical/correlated (cross-module: the "
                        "per-module rule cannot see this)",
                    )


# --------------------------------------------------------- race detection
_PKG_PREFIX = "pta_replicator_tpu/"


def _spawn_target_expr(mod: Module, node: ast.Call) -> Optional[ast.AST]:
    """Target expression of a thread-of-control spawn: ``Thread(
    target=f)`` or ``pool.submit(f, ...)`` with a static callable."""
    resolved = mod.resolve(node.func) or ""
    if resolved.rsplit(".", 1)[-1] == "Thread":
        for kw in node.keywords:
            if kw.arg == "target":
                return kw.value
        return None
    if (
        isinstance(node.func, ast.Attribute)
        and node.func.attr == "submit"
        and node.args
        and isinstance(node.args[0], (ast.Name, ast.Attribute))
    ):
        return node.args[0]
    return None


def _in_loop(mod: Module, node: ast.AST) -> bool:
    for anc in mod.ancestors(node):
        if isinstance(anc, (ast.For, ast.While, ast.AsyncFor,
                            ast.comprehension, ast.ListComp,
                            ast.GeneratorExp)):
            return True
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
    return False


def _attr_writes(info: FunctionInfo):
    """(key, node, verb) for every shared-state write in ``info``'s
    body: instance attributes (``self.x = `` / ``self.x.append()`` /
    ``self.x[k] = ``) keyed by (relpath, class, attr), and module-global
    container mutations keyed by (relpath, '', name)."""
    mod = info.module
    if info.name in _CONSTRUCTION_METHODS:
        return
    globals_ = _module_level_mutables(mod)

    def self_attr(expr) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name
        ) and expr.value.id in ("self", "cls"):
            return expr.attr
        return None

    for node in iter_body_nodes(info.node):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                attr = self_attr(t)
                if attr is not None and info.cls:
                    yield ((info.relpath, info.cls, attr), node,
                           "assignment")
                    continue
                if isinstance(t, ast.Subscript):
                    attr = self_attr(t.value)
                    if attr is not None and info.cls:
                        yield ((info.relpath, info.cls, attr), node,
                               "item assignment")
                    elif isinstance(t.value, ast.Name) and \
                            t.value.id in globals_:
                        yield ((info.relpath, "", t.value.id), node,
                               "item assignment")
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ) and node.func.attr in _MUTATOR_METHODS:
            base = node.func.value
            attr = self_attr(base)
            if attr is not None and info.cls:
                yield ((info.relpath, info.cls, attr), node,
                       f".{node.func.attr}()")
            elif isinstance(base, ast.Name) and base.id in globals_:
                yield ((info.relpath, "", base.id), node,
                       f".{node.func.attr}()")


class ThreadSharedStateRace(Rule):
    """Static write-write race detection across every thread-of-control
    the package spawns. See the pack docstring for the model; precision
    notes: reads are not tracked, lock identity is by terminal name
    (the ``LOCK_HIERARCHY`` convention), and a function reachable from
    a spawn is attributed to that spawn's thread wholesale."""

    id = "thread-shared-state-race"
    severity = "error"
    description = (
        "instance/module state written from >=2 threads-of-control "
        "(spawned Thread/executor targets, or a worker pool racing "
        "itself) with no common lock"
    )
    example_fire = (
        "class Pool:\n"
        "    def start(self):\n"
        "        for _ in range(4):\n"
        "            threading.Thread(target=self._run).start()\n"
        "    def _run(self):\n"
        "        self.done += 1           # 4 threads, no lock: FIRES\n"
    )
    example_ok = (
        "    def _run(self):\n"
        "        with self._lock:\n"
        "            self.done += 1       # common lock on every writer\n"
    )

    def check_project(self, mods: Sequence[Module]) -> Iterable[Finding]:
        pkg_mods = [
            m for m in mods
            if m.relpath.startswith(_PKG_PREFIX)
            and not _is_test_file(m.relpath)
        ]
        if not pkg_mods:
            return
        graph = project_graph(mods)
        index = graph.index

        # 1. every spawn site in package code
        spawns = []  # (target FunctionInfo, mod, lineno, multi)
        for mod in pkg_mods:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                expr = _spawn_target_expr(mod, node)
                if expr is None:
                    continue
                enclosing = index.enclosing_info(mod, node)
                target = graph.resolve_call(mod, expr, enclosing)
                if target is None:
                    continue
                multi = _in_loop(mod, node) or (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "submit"
                )
                spawns.append((target, mod, node.lineno, multi))

        # 2. per-thread write events, lock context carried along chains
        events: Dict[tuple, List[dict]] = {}
        threads_per_key: Dict[tuple, Set[str]] = {}
        reached_symbols: Set[str] = set()

        def record(key, thread_id, lockset, node, verb, info, chain):
            events.setdefault(key, []).append({
                "thread": thread_id, "locks": frozenset(lockset),
                "relpath": info.relpath, "lineno": node.lineno,
                "verb": verb, "fn": info.display, "chain": chain,
            })
            threads_per_key.setdefault(key, set()).add(thread_id)

        for target, smod, slineno, multi in spawns:
            thread_id = f"{target.display} spawned at " \
                        f"{smod.relpath}:{slineno}"
            reach = graph.reachable_from(target.symbol)
            for sym, r in sorted(reach.items()):
                info = index.functions[sym]
                if not info.relpath.startswith(_PKG_PREFIX):
                    continue
                reached_symbols.add(sym)
                for key, node, verb in _attr_writes(info):
                    locks = r.locks | set(
                        _held_locks(info.module, node)
                    )
                    record(key, thread_id, locks, node, verb, info,
                           graph.format_chain(r.chain))
                    if multi:
                        threads_per_key[key].add(thread_id + " [pool]")

        # 3. the spawning/main thread-of-control: writes to the same
        # state from functions no spawn reaches
        for sym in sorted(index.functions):
            if sym in reached_symbols:
                continue
            info = index.functions[sym]
            if not info.relpath.startswith(_PKG_PREFIX) or \
                    _is_test_file(info.relpath):
                continue
            for key, node, verb in _attr_writes(info):
                if key not in events:
                    continue  # nobody threaded writes it: not shared
                record(key, "main thread", set(
                    _held_locks(info.module, node)
                ), node, verb, info, info.display)

        # 4. verdicts
        for key in sorted(events):
            if len(threads_per_key[key]) < 2:
                continue
            evs = events[key]
            common = frozenset.intersection(*(e["locks"] for e in evs))
            if common:
                continue
            relpath, cls, attr = key
            what = (
                f"attribute {attr!r} of {cls} ({relpath})" if cls
                else f"module-level {attr!r} ({relpath})"
            )
            anchor = min(
                evs, key=lambda e: (len(e["locks"]), e["relpath"],
                                    e["lineno"]),
            )
            writers = sorted({
                f"{e['thread']} [{e['relpath']}:{e['lineno']}"
                f"{', holding ' + '/'.join(sorted(e['locks'])) if e['locks'] else ', no lock'}]"
                for e in evs
            })
            detail = "; ".join(writers[:3]) + (
                f"; +{len(writers) - 3} more" if len(writers) > 3 else ""
            )
            yield self.finding(
                anchor["relpath"], anchor["lineno"],
                f"{what} is written from "
                f"{len(threads_per_key[key])} threads-of-control with "
                f"no common lock: {detail} — guard every writer with "
                "one shared lock (and record it in "
                "rules_threads.LOCK_HIERARCHY), or suppress with the "
                "reason the write is single-threaded by construction",
            )


# --------------------------------------------------------- dead names
class TelemetryDeadName(Rule):
    """Registry entries nobody emits. Usage evidence: the constant's
    name referenced in any linted module outside ``obs/names.py`` or in
    ``tests/``, or its string value at a telemetry producer call."""

    id = "telemetry-dead-name"
    severity = "error"
    description = (
        "constant registered in obs/names.py that no call site in the "
        "whole tree ever emits (by constant or by literal)"
    )
    example_fire = (
        "# obs/names.py\n"
        "SPAN_OLD_PHASE = 'old_phase'   # nothing references it: FIRES\n"
    )
    example_ok = (
        "# obs/names.py\n"
        "SPAN_FREEZE = 'freeze'\n"
        "# batch.py\n"
        "with span(names.SPAN_FREEZE): ...\n"
    )

    def check_project(self, mods: Sequence[Module]) -> Iterable[Finding]:
        names_mod = next(
            (m for m in mods if m.relpath == NAMES_RELPATH), None
        )
        if names_mod is None:
            return
        constants: List[Tuple[str, str, int]] = []  # (NAME, value, line)
        for stmt in names_mod.tree.body:
            if isinstance(stmt, ast.Assign) and isinstance(
                stmt.value, ast.Constant
            ) and isinstance(stmt.value.value, str):
                for t in stmt.targets:
                    if isinstance(t, ast.Name) and t.id.isupper():
                        constants.append(
                            (t.id, stmt.value.value, stmt.lineno)
                        )
        if not constants:
            return

        other_sources = [
            m.source for m in mods if m.relpath != NAMES_RELPATH
        ]
        # the whole tree includes tests/ and examples/, which are not
        # default lint targets — read them off disk so a name emitted
        # only by a test fixture is not declared dead
        root = names_mod.path[: -len(names_mod.relpath)].rstrip(os.sep)
        linted = {m.path for m in mods}
        for extra_dir in ("tests", "examples"):
            d = os.path.join(root, extra_dir)
            if not os.path.isdir(d):
                continue
            for dirpath, _dirnames, filenames in os.walk(d):
                for f in sorted(filenames):
                    p = os.path.join(dirpath, f)
                    if f.endswith(".py") and p not in linted:
                        try:
                            with open(p, encoding="utf-8",
                                      errors="replace") as fh:
                                other_sources.append(fh.read())
                        except OSError:
                            continue

        produced: Set[str] = set()
        for m in mods:
            if m.relpath == NAMES_RELPATH:
                continue
            for node in ast.walk(m.tree):
                if not isinstance(node, ast.Call):
                    continue
                resolved = m.resolve(node.func) or ""
                if resolved.rsplit(".", 1)[-1] not in _PRODUCER_KINDS:
                    continue
                for expr in list(node.args[:1]) + [
                    kw.value for kw in node.keywords if kw.arg == "name"
                ]:
                    if isinstance(expr, ast.Constant) and isinstance(
                        expr.value, str
                    ):
                        produced.add(expr.value)

        blob = "\n".join(other_sources)
        all_values = {v for _n, v, _l in constants}
        for name, value, lineno in constants:
            if value in produced:
                continue
            if re.search(rf"\b{re.escape(name)}\b", blob):
                continue
            # prefix constants name a dotted *family*, matched by value
            # (startswith) rather than emitted verbatim — live as long
            # as any registered or produced name belongs to the family
            if name.endswith("_PREFIX") and any(
                v != value and v.startswith(value)
                for v in all_values | produced
            ):
                continue
            yield self.finding(
                names_mod, lineno,
                f"{name} = {value!r} is registered but no call site in "
                "the tree ever emits it (no constant reference outside "
                "names.py, no literal at a producer) — remove it or "
                "wire the instrumentation it promises",
            )


RULES = [
    InterprocHostSync(),
    InterprocKeyReuse(),
    ThreadSharedStateRace(),
    TelemetryDeadName(),
]
