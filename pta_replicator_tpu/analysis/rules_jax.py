"""graftlint rule pack: JAX tracing/transfer discipline.

The invariants PR 1's ``instrumented_jit`` accounting and PR 2's
pipelined sweep exist to protect, enforced statically:

* ``jax-host-sync`` — a host-device synchronization primitive
  (``.block_until_ready()``, ``np.asarray``/``np.array``, ``.item()``,
  ``float(x)``) inside a jit-traced function. At trace time these either
  fail outright (tracers aren't concrete) or silently fence the device
  pipeline on every call — the exact stall the pipelined executor was
  built to hide. Syncs belong on the host side of the jit boundary (the
  reader thread's explicit ``readback_fence``/``drain``).
* ``jax-f64-literal`` — a ``float64`` dtype literal in jit-traced code.
  The device path is float32-disciplined (tests/test_f32.py); f64 host
  *pre*computes are fine (and are why ``io/``/``timing/`` are exempt
  wholesale), but an f64 literal inside a traced function doubles
  memory/VPU cost on TPU or silently downcasts under x64-disabled jax.
* ``jax-key-reuse`` — the same PRNG key variable consumed by two
  ``jax.random`` calls with no intervening ``split``/``fold_in``
  rebinding: the two draws are perfectly correlated. (The sweep's
  fold_in-per-chunk key ledger depends on never reusing a key.)
* ``jax-global-closure`` — a jit-traced function reads a module-level
  mutable object. jit captures it by value AT TRACE TIME: later mutation
  is silently ignored (stale constants baked into the executable) — or
  worse, triggers retrace-per-call when used as a static argument.

Detection of "jit-traced" covers decorator forms (``@jax.jit``,
``@instrumented_jit(...)``, ``@partial(jax.jit, ...)``) and wrapper
forms (``instrumented_jit(run, ...)``, ``jax.jit(traced)``, including a
function passed through ``shard_map`` into a jit call) — the idioms
models/batched.py and parallel/mesh.py actually use.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set

from .engine import Finding, Module, Rule

#: callables that jit-compile their (first) argument / decorated function
_JIT_NAMES = {"jit", "instrumented_jit"}

#: jax.random functions that CONSUME a key argument
_KEY_CONSUMERS_PREFIX = "jax.random."
#: jax.random functions whose ASSIGNMENT refreshes a key variable
_KEY_MAKERS = {"PRNGKey", "key", "split", "fold_in", "clone"}

#: device-path exemptions for the f64 rule: host-precision subsystems
#: where float64 is the point (par/tim parsing, timing-model oracles)
_F64_EXEMPT_PARTS = ("/io/", "/timing/")


def _terminal(mod: Module, func: ast.AST) -> str:
    resolved = mod.resolve(func)
    return resolved.rsplit(".", 1)[-1] if resolved else ""


def _is_jitlike_callable(mod: Module, func: ast.AST) -> bool:
    name = _terminal(mod, func)
    if name in _JIT_NAMES:
        return True
    # functools.partial(jax.jit, ...) used as a decorator factory
    if name == "partial":
        return False  # handled at the Call level by _decorator_is_jit
    return False


def _decorator_is_jit(mod: Module, dec: ast.AST) -> bool:
    if isinstance(dec, ast.Call):
        if _is_jitlike_callable(mod, dec.func):
            return True
        if _terminal(mod, dec.func) == "partial" and dec.args:
            return _is_jitlike_callable(mod, dec.args[0])
        return False
    return _is_jitlike_callable(mod, dec)


def jit_function_nodes(mod: Module) -> List[ast.FunctionDef]:
    """Every function def in the module that ends up jit-compiled:
    decorated with a jit form, or passed (possibly through nested calls,
    e.g. ``instrumented_jit(shard_map(local, ...))``) into a jit call."""
    defs: Dict[str, List[ast.FunctionDef]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)

    jitted: List[ast.FunctionDef] = []
    seen: Set[ast.AST] = set()

    def mark(fn: ast.FunctionDef) -> None:
        if fn not in seen:
            seen.add(fn)
            jitted.append(fn)

    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_decorator_is_jit(mod, d) for d in node.decorator_list):
                mark(node)
        elif isinstance(node, ast.Call) and _is_jitlike_callable(
            mod, node.func
        ):
            if not node.args:
                continue
            # names referenced anywhere inside the first argument: covers
            # jax.jit(f), instrumented_jit(shard_map(f, ...), ...)
            for sub in ast.walk(node.args[0]):
                if isinstance(sub, ast.Name) and sub.id in defs:
                    for fn in defs[sub.id]:
                        mark(fn)
    return jitted


def _module_level_mutables(mod: Module) -> Dict[str, int]:
    """name -> lineno of module-level bindings to mutable containers."""
    out: Dict[str, int] = {}
    mutable_ctors = {
        "list", "dict", "set", "defaultdict", "deque", "OrderedDict",
        "Counter", "bytearray",
    }
    for stmt in mod.tree.body:
        targets = []
        value = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        if value is None:
            continue
        is_mutable = isinstance(
            value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                    ast.DictComp, ast.SetComp)
        ) or (
            isinstance(value, ast.Call)
            and _terminal(mod, value.func) in mutable_ctors
        )
        if not is_mutable:
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                out[t.id] = stmt.lineno
    return out


_SYNC_ATTRS = {"block_until_ready", "item"}


def iter_host_syncs(mod: Module, fn: ast.AST):
    """Host-sync call sites inside ``fn``: yields ``(node, head, tail)``
    where messages compose as ``f"{head} inside jit-traced {name!r}:
    {tail}"``. Shared by the per-module rule and the interprocedural
    pass (:mod:`.rules_interproc`)."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr in _SYNC_ATTRS:
                yield (
                    node, f".{func.attr}()",
                    "forces a host sync per call (fence outside the "
                    "jit boundary instead)",
                )
                continue
            resolved = mod.resolve(func) or ""
            if resolved.startswith("numpy.") and func.attr in (
                "asarray", "array",
            ):
                yield (
                    node, f"np.{func.attr}()",
                    "pulls the tracer to host (use jnp, or hoist the "
                    "conversion out of the jit)",
                )
        elif isinstance(func, ast.Name) and func.id == "float":
            if node.args and not isinstance(node.args[0], ast.Constant):
                yield (
                    node, "float(...)",
                    "concretizes a tracer (host sync); keep it an "
                    "array or move the cast outside the jit",
                )


class HostSyncInJit(Rule):
    """A host-device synchronization primitive lexically inside a
    function this module jit-traces. The interprocedural variant (same
    rule id, :mod:`.rules_interproc`) extends this through the project
    call graph into helpers the traced entry reaches."""

    id = "jax-host-sync"
    severity = "error"
    description = (
        "host-device sync (.block_until_ready()/np.asarray/.item()/"
        "float()) inside a jit-traced function"
    )
    example_fire = (
        "@jax.jit\n"
        "def engine(x):\n"
        "    return float(x.sum())   # concretizes a tracer\n"
    )
    example_ok = (
        "@jax.jit\n"
        "def engine(x):\n"
        "    return x.sum()\n"
        "total = float(engine(x))     # sync on the host side\n"
    )

    def check_module(self, mod: Module) -> Iterable[Finding]:
        for fn in jit_function_nodes(mod):
            for node, head, tail in iter_host_syncs(mod, fn):
                yield self.finding(
                    mod, node.lineno,
                    f"{head} inside jit-traced {fn.name!r}: {tail}",
                )


class F64LiteralInJit(Rule):
    id = "jax-f64-literal"
    severity = "error"
    description = (
        "float64 dtype literal in jit-traced device code (f32 "
        "discipline; io/ and timing/ host-precision modules exempt)"
    )
    example_fire = (
        "@jax.jit\n"
        "def step(x):\n"
        "    return x.astype(jnp.float64)   # f64 in device code: FIRES\n"
    )
    example_ok = (
        "@jax.jit\n"
        "def step(x):\n"
        "    return x.astype(jnp.float32)\n"
        "planes = np.asarray(raw, np.float64)  # host precompute: fine\n"
    )

    def _exempt(self, mod: Module) -> bool:
        rel = "/" + mod.relpath
        return any(part in rel for part in _F64_EXEMPT_PARTS)

    def check_module(self, mod: Module) -> Iterable[Finding]:
        if self._exempt(mod):
            return
        jit_fns = jit_function_nodes(mod)
        in_jit = {id(n) for fn in jit_fns for n in ast.walk(fn)}
        for fn in jit_fns:
            for node in ast.walk(fn):
                hit = None
                if isinstance(node, ast.Attribute) and \
                        node.attr == "float64":
                    hit = (mod.qualname(node) or "float64")
                elif isinstance(node, ast.Constant) and \
                        node.value == "float64":
                    hit = '"float64"'
                if hit:
                    yield self.finding(
                        mod, node.lineno,
                        f"{hit} inside jit-traced {fn.name!r}: device "
                        "code is float32-disciplined (tests/test_f32.py)"
                        " — do f64 precomputes on host, outside the jit",
                    )
        # jnp.float64 anywhere in a device-path module is a smell even
        # outside jit: jax arrays built f64 flow straight to device
        # (jit bodies were already reported above — don't double-count)
        for node in ast.walk(mod.tree):
            if id(node) in in_jit:
                continue
            if isinstance(node, ast.Attribute) and node.attr == "float64":
                resolved = mod.resolve(node) or ""
                if resolved.startswith("jax."):
                    yield self.finding(
                        mod, node.lineno,
                        "jnp.float64 literal in a device-path module: "
                        "build f64 data with numpy on host, cast at the "
                        "jit boundary",
                    )


class KeyReuse(Rule):
    id = "jax-key-reuse"
    severity = "error"
    description = (
        "PRNG key consumed by two jax.random calls without an "
        "intervening split/fold_in"
    )
    example_fire = (
        "key = jax.random.PRNGKey(0)\n"
        "a = jax.random.normal(key, (4,))\n"
        "b = jax.random.uniform(key, (4,))   # same key twice: FIRES\n"
    )
    example_ok = (
        "key = jax.random.PRNGKey(0)\n"
        "k1, k2 = jax.random.split(key)\n"
        "a = jax.random.normal(k1, (4,))\n"
        "b = jax.random.uniform(k2, (4,))\n"
    )

    def check_module(self, mod: Module) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_fn(mod, node)

    def _check_fn(self, mod: Module, fn) -> Iterable[Finding]:
        # key variables: names (re)bound from jax.random key makers
        key_vars: set = set()
        events = []  # (lineno, col, kind, name, node)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                value = node.value
                resolved = (
                    mod.resolve(value.func)
                    if isinstance(value, ast.Call) else None
                ) or ""
                is_maker = (
                    resolved.startswith(_KEY_CONSUMERS_PREFIX)
                    and resolved.rsplit(".", 1)[-1] in _KEY_MAKERS
                )
                for t in node.targets:
                    names = []
                    if isinstance(t, ast.Name):
                        names = [t.id]
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        names = [
                            e.id for e in t.elts if isinstance(e, ast.Name)
                        ]
                    for name in names:
                        if is_maker:
                            key_vars.add(name)
                        events.append(
                            (node.lineno, node.col_offset, "assign",
                             name, node)
                        )
            elif isinstance(node, ast.Call):
                resolved = mod.resolve(node.func) or ""
                if (
                    resolved.startswith(_KEY_CONSUMERS_PREFIX)
                    # split/fold_in DERIVE independent streams — only a
                    # sampler (normal, uniform, bits, ...) consumes
                    and resolved.rsplit(".", 1)[-1] not in _KEY_MAKERS
                    and node.args
                ):
                    arg = node.args[0]
                    if isinstance(arg, ast.Name):
                        events.append(
                            (node.lineno, node.col_offset, "consume",
                             arg.id, node)
                        )
        consumed: dict = {}
        for lineno, _col, kind, name, _node in sorted(
            events, key=lambda e: (e[0], e[1])
        ):
            if kind == "assign":
                consumed[name] = 0
            elif name in key_vars:
                consumed[name] = consumed.get(name, 0) + 1
                if consumed[name] == 2:
                    yield self.finding(
                        mod, lineno,
                        f"key {name!r} consumed twice in {fn.name!r} "
                        "with no intervening split/fold_in: the two "
                        "draws are identical/correlated",
                    )


class GlobalClosureInJit(Rule):
    id = "jax-global-closure"
    severity = "warning"
    description = (
        "jit-traced function reads a module-level mutable object "
        "(captured by value at trace time; later mutation is ignored)"
    )
    example_fire = (
        "CONFIG = {'scale': 2.0}\n"
        "@jax.jit\n"
        "def apply(x):\n"
        "    return x * CONFIG['scale']   # trace-time snapshot: FIRES\n"
    )
    example_ok = (
        "@jax.jit\n"
        "def apply(x, scale):\n"
        "    return x * scale             # pass state as an argument\n"
    )

    def check_module(self, mod: Module) -> Iterable[Finding]:
        mutables = _module_level_mutables(mod)
        if not mutables:
            return
        for fn in jit_function_nodes(mod):
            reported: set = set()
            # names that are local to the function shadow the global
            local_names = {
                a.arg for a in (
                    fn.args.args + fn.args.posonlyargs + fn.args.kwonlyargs
                )
            }
            for node in ast.walk(fn):
                if isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Store
                ):
                    local_names.add(node.id)
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in mutables
                    and node.id not in local_names
                    and node.id not in reported
                ):
                    reported.add(node.id)
                    yield self.finding(
                        mod, node.lineno,
                        f"jit-traced {fn.name!r} reads module-level "
                        f"mutable {node.id!r} (bound at line "
                        f"{mutables[node.id]}): jit bakes its trace-time "
                        "value into the executable",
                    )


class PallasOrphanFallback(Rule):
    """A Pallas kernel with no path to verification. The repo's kernel
    discipline (ops/pallas_cw.py, ops/pallas_gp.py, docs/performance.md)
    is ONE per-tile implementation shared by the TPU kernel and a tiled
    XLA fallback, with interpret-mode bit-identity pinned by test —
    a ``pl.pallas_call`` in a module with neither a top-level ``*_xla``
    fallback function nor a ``PALLAS_BIT_IDENTITY_TESTS`` marker (the
    tuple naming its bit-identity tests, for kernels whose fallback
    lives in a consumer module) is a kernel nothing can cross-check."""

    id = "jax-pallas-orphan-fallback"
    severity = "error"
    description = (
        "pl.pallas_call in a module with neither a shared-tile *_xla "
        "fallback function nor a PALLAS_BIT_IDENTITY_TESTS marker"
    )
    example_fire = (
        "def _kernel(x_ref, o_ref):\n"
        "    o_ref[...] = x_ref[...] * 2\n"
        "def double(x):\n"
        "    return pl.pallas_call(_kernel, ...)(x)   # no fallback: FIRES\n"
    )
    example_ok = (
        "def double_xla(x, tile=128):  # same tile fn, lax loop\n"
        "    ...\n"
        "def double(x):\n"
        "    return pl.pallas_call(_kernel, ...)(x)\n"
        "# or: PALLAS_BIT_IDENTITY_TESTS = ('tests/test_x.py::test_bits',)\n"
    )

    @staticmethod
    def _is_pallas_call(mod: Module, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        resolved = mod.resolve(node.func) or ""
        return resolved == "pallas_call" or resolved.endswith(
            ".pallas_call"
        )

    def check_module(self, mod: Module) -> Iterable[Finding]:
        sites = [
            node for node in ast.walk(mod.tree)
            if self._is_pallas_call(mod, node)
        ]
        if not sites:
            return
        has_fallback = any(
            isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name.endswith("_xla")
            for n in mod.tree.body
        )

        def _marker_target(n: ast.AST) -> bool:
            if isinstance(n, ast.Assign):
                return any(
                    isinstance(t, ast.Name)
                    and t.id == "PALLAS_BIT_IDENTITY_TESTS"
                    for t in n.targets
                )
            return isinstance(n, ast.AnnAssign) and isinstance(
                n.target, ast.Name
            ) and n.target.id == "PALLAS_BIT_IDENTITY_TESTS"

        has_marker = any(_marker_target(n) for n in mod.tree.body)
        if has_fallback or has_marker:
            return
        for node in sites:
            yield self.finding(
                mod, node.lineno,
                "pl.pallas_call with no verification path in this "
                "module: add a top-level *_xla fallback sharing the "
                "per-tile implementation, or a module-level "
                "PALLAS_BIT_IDENTITY_TESTS tuple naming the "
                "interpret-mode bit-identity tests that pin it",
            )


RULES = [
    HostSyncInJit(), F64LiteralInJit(), KeyReuse(), GlobalClosureInJit(),
    PallasOrphanFallback(),
]
