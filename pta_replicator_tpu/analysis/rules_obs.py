"""graftlint rule pack: bounded-buffer + trace-handoff discipline in
threaded telemetry paths.

The telemetry layer runs for the LIFE of a multi-hour capture, on
daemon threads (the flight recorder's sampler, the tracer's listeners,
the serve endpoint's request threads). Any unbounded container on
module or instance state there is a slow memory leak with a multi-hour
fuse — exactly the host-RSS creep the series recorder exists to
surface, coming from the telemetry itself. The series rings are
*designed* bounded (fixed budget + decimation); this rule makes the
property mechanical for the whole package:

* ``obs-unbounded-buffer`` — inside ``pta_replicator_tpu/obs/`` modules
  that use threads, flag

  - ``collections.deque()`` constructed WITHOUT ``maxlen`` (an
    unbounded deque on state is the classic accidental ring), and
  - growth calls (``append``/``appendleft``/``extend``/``insert``) on
    module-level or instance (``self.X``) list state,

  unless the module carries **bounding evidence** for that container:
  a ``len(<container>)`` check (the cap-and-drop idiom), a membership
  guard (``if x not in buf`` — bounded by distinct values), or a
  pruning operation (``pop``/``popleft``/``remove``/``clear``/``del``/
  slice reassignment) on the same terminal name. Intentionally
  unbounded-but-pruned structures carry an inline
  ``# graftlint: disable=obs-unbounded-buffer`` with the reason, which
  is the reviewer-visible record the engine's suppression mechanism
  exists for.

The evidence check is per terminal attribute/name, module-wide: it
asks "is there ANY bounding mechanism for this container in this
file", not "is this exact call site guarded" — a ring that prunes in
``observe`` and appends in ``offer`` is bounded even though the append
itself is bare. That keeps the rule quiet on correct code and loud on
the one shape that actually leaks: a buffer that only ever grows.

* ``obs-orphan-thread-span`` — anywhere in PACKAGE code (not just
  obs/): a ``threading.Thread(target=...)`` (or executor
  ``.submit(fn)``) whose target function opens spans but shows NO
  visible trace/ancestry handoff — no ``carry()``/``adopt()`` (the
  TraceContext handoff pair, docs/tracing.md) and no
  ``TRACER.inherit`` (the span-ancestry handoff) anywhere in the
  module. Such a worker records orphan spans: they land at the root of
  the span tree AND outside any causal trace, which is exactly how a
  coalesced batch becomes unattributable to the requests it served.
  The evidence check is module-wide like the buffer rule's — a worker
  whose body delegates to a helper that adopts is handed off; a module
  with threads, spans in the targets, and no handoff anywhere is the
  orphan shape. Intentionally unstitched workers carry an inline
  ``# graftlint: disable=obs-orphan-thread-span`` with the reason.

* ``obs-unprobed-reduction`` — in the package hot paths (``models/``,
  ``likelihood/``, ``covariance/``): a jnp/jax ``cholesky``/``slogdet``
  call whose enclosing function shows no numerics probe
  (``probe``/``probe_cholesky``/``scan_block``, obs/numerics.py). An
  indefinite input NaNs whole rows of a Cholesky factor *silently*,
  and the NaN surfaces three layers downstream as an unattributable
  NaN lnlike — the exact failure the numerics observatory's identity
  probes exist to name at the producing site (docs/numerics.md). The
  numpy f64 oracle factorizations are excluded by construction (the
  resolved callee must carry jax/jnp); reductions that genuinely
  cannot go non-finite carry an inline
  ``# graftlint: disable=obs-unprobed-reduction`` with the reason.
"""
from __future__ import annotations

import ast
from typing import Iterable, Optional, Set

from .engine import Finding, Module, Rule
from .rules_threads import _uses_threads

#: growth calls on list/deque state the rule polices
_GROWTH_METHODS = {"append", "appendleft", "extend", "insert"}
#: calls that count as pruning evidence for a container name
_PRUNE_METHODS = {
    "pop", "popleft", "popitem", "remove", "discard", "clear",
}

#: the subtree this pack polices (posix relpath prefix)
OBS_PREFIX = "pta_replicator_tpu/obs/"


def _terminal(node: ast.AST) -> Optional[str]:
    """Terminal identifier of a Name/Attribute chain (``self._events``
    -> ``_events``; ``ring`` -> ``ring``), else None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _tracked_containers(mod: Module) -> Set[str]:
    """Terminal names of module-level or instance state initialized as
    a list display or a deque() call — the containers whose growth the
    rule polices. Plain function locals are excluded (they die with
    the frame)."""
    tracked: Set[str] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        value = node.value
        if value is None:
            continue
        is_list = isinstance(value, (ast.List, ast.ListComp))
        is_deque = _is_deque_call(mod, value)
        # a dict/set comprehension of deques (occupancy's per-stage
        # table) still tracks the *constructor* rule below; here we
        # only track direct list/deque state
        if not (is_list or is_deque):
            continue
        for t in targets:
            name = _terminal(t)
            if name is None:
                continue
            if isinstance(t, ast.Attribute):
                tracked.add(name)       # self.X / obj.X state
            elif isinstance(t, ast.Name) and not any(
                isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
                for a in mod.ancestors(node)
            ):
                tracked.add(name)       # module-level state
    return tracked


def _is_deque_call(mod: Module, node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and (mod.resolve(node.func) or "").endswith("deque")
    )


def _deque_has_maxlen(call: ast.Call) -> bool:
    if any(kw.arg == "maxlen" for kw in call.keywords):
        return True
    # positional: deque(iterable, maxlen)
    return len(call.args) >= 2


def _bounding_evidence(mod: Module) -> Set[str]:
    """Terminal container names with ANY bounding mechanism in this
    module: a len() check, a membership guard, a pruning call, slice
    reassignment/deletion, or a bounded-deque assignment."""
    evidence: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            fn = node.func
            if (
                isinstance(fn, ast.Name) and fn.id == "len"
                and node.args
            ):
                name = _terminal(node.args[0])
                if name:
                    evidence.add(name)
            elif (
                isinstance(fn, ast.Attribute)
                and fn.attr in _PRUNE_METHODS
            ):
                name = _terminal(fn.value)
                if name:
                    evidence.add(name)
        elif isinstance(node, ast.Compare) and any(
            isinstance(op, (ast.In, ast.NotIn)) for op in node.ops
        ):
            for side in [node.left, *node.comparators]:
                name = _terminal(side)
                if name:
                    evidence.add(name)
        elif isinstance(node, (ast.Delete,)):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    name = _terminal(t.value)
                    if name:
                        evidence.add(name)
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                # slice reassignment prunes; a maxlen deque bounds
                if isinstance(t, ast.Subscript):
                    name = _terminal(t.value)
                    if name:
                        evidence.add(name)
                elif node.value is not None and _is_deque_call(
                    mod, node.value
                ) and _deque_has_maxlen(node.value):
                    name = _terminal(t)
                    if name:
                        evidence.add(name)
    return evidence


class UnboundedObsBuffer(Rule):
    id = "obs-unbounded-buffer"
    severity = "error"
    description = (
        "unbounded buffer on module/instance state in an obs thread/"
        "sampler path (deque without maxlen, or list growth with no "
        "bounding mechanism) — a slow leak over a multi-hour capture"
    )
    example_fire = (
        "class Sampler:\n"
        "    def __init__(self):\n"
        "        self.samples = deque()   # no maxlen, appended from a\n"
        "    def tick(self):              # sampler thread: FIRES\n"
        "        self.samples.append(read())\n"
    )
    example_ok = (
        "        self.samples = deque(maxlen=4096)\n"
    )

    def check_module(self, mod: Module) -> Iterable[Finding]:
        if not mod.relpath.startswith(OBS_PREFIX):
            return
        if not _uses_threads(mod):
            return
        evidence = _bounding_evidence(mod)
        tracked = _tracked_containers(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            # unbounded deque constructor, in any context: even a
            # "local" one is usually about to be stored on state (dict
            # values, comprehensions) where the tracker can't follow
            if _is_deque_call(mod, node) and not _deque_has_maxlen(node):
                yield self.finding(
                    mod, node.lineno,
                    "deque() without maxlen in a threaded obs module: "
                    "give it a maxlen, prune it explicitly (and "
                    "suppress with the reason), or it grows for the "
                    "life of the capture",
                )
                continue
            fn = node.func
            if not (
                isinstance(fn, ast.Attribute)
                and fn.attr in _GROWTH_METHODS
            ):
                continue
            name = _terminal(fn.value)
            if name is None or name not in tracked:
                continue
            if name in evidence:
                continue
            yield self.finding(
                mod, node.lineno,
                f".{fn.attr}() grows {name!r} (module/instance state) "
                "with no bounding mechanism in this module (no len() "
                "cap, membership guard, pruning call, or maxlen) — "
                "bound it or suppress with the reason",
            )


#: call names that count as a visible trace/ancestry handoff
_HANDOFF_NAMES = {"carry", "adopt", "inherit"}
#: the package subtree the orphan-thread-span rule polices
_PKG_PREFIX = "pta_replicator_tpu/"


def _is_thread_spawn(mod: Module, node: ast.Call):
    """The target-function expression of a worker spawn, or None:
    ``threading.Thread(target=f)`` / ``Thread(target=f)``, and executor
    ``pool.submit(f, ...)`` where ``f`` is a name/attribute reference
    (a server's ``submit(**params)`` request API takes no callable and
    never matches)."""
    resolved = mod.resolve(node.func) or ""
    if resolved.rsplit(".", 1)[-1] == "Thread":
        for kw in node.keywords:
            if kw.arg == "target":
                return kw.value
        return None
    if (
        isinstance(node.func, ast.Attribute)
        and node.func.attr == "submit"
        and node.args
        and isinstance(node.args[0], (ast.Name, ast.Attribute))
    ):
        return node.args[0]
    return None


def _target_function(mod: Module, expr: ast.AST):
    """The FunctionDef a spawn target references, resolved by terminal
    name anywhere in the module (covers nested worker defs and
    ``self._run``-style methods); None for lambdas/imported targets —
    not statically attributable."""
    name = _terminal(expr)
    if name is None:
        return None
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            return node
    return None


def _opens_spans(fn: ast.AST) -> bool:
    """True when the function body calls a span producer directly
    (``span(...)`` / ``TRACER.span(...)`` / ``tracer.span(...)``).
    Synthesized records (``record_span``) don't count — they take the
    context explicitly, which IS a handoff."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                _terminal(node.func) == "span":
            return True
    return False


def _has_handoff(mod: Module) -> bool:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and \
                _terminal(node.func) in _HANDOFF_NAMES:
            return True
    return False


class OrphanThreadSpan(Rule):
    id = "obs-orphan-thread-span"
    severity = "error"
    description = (
        "thread/executor target opens spans with no visible "
        "carry()/adopt()/inherit handoff — its spans land at the span-"
        "tree root and outside any causal trace (docs/tracing.md)"
    )
    example_fire = (
        "def worker():\n"
        "    with span('stage'):          # orphan span in a thread\n"
        "        ...\n"
        "threading.Thread(target=worker).start()   # FIRES\n"
    )
    example_ok = (
        "token = trace.carry()\n"
        "def worker():\n"
        "    with trace.adopt(token), span('stage'):\n"
        "        ...\n"
    )

    def check_module(self, mod: Module) -> Iterable[Finding]:
        if not mod.relpath.startswith(_PKG_PREFIX):
            return
        handoff = None  # computed lazily: most modules spawn nothing
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            target = _is_thread_spawn(mod, node)
            if target is None:
                continue
            fn = _target_function(mod, target)
            if fn is None or not _opens_spans(fn):
                continue
            if handoff is None:
                handoff = _has_handoff(mod)
            if handoff:
                continue
            yield self.finding(
                mod, node.lineno,
                f"thread target {_terminal(target)!r} opens spans but "
                "this module shows no carry()/adopt()/inherit handoff "
                "— wrap the worker body in TRACER.inherit(...) and/or "
                "trace.adopt(carry()) (or suppress with the reason)",
            )


#: subtrees whose device reductions the numerics observatory polices —
#: the hot paths where an f32 factorization NaN surfaces as a silent
#: NaN lnlike three layers downstream (docs/numerics.md)
_HOT_PREFIXES = (
    "pta_replicator_tpu/models/",
    "pta_replicator_tpu/likelihood/",
    "pta_replicator_tpu/covariance/",
)
#: resolved-callee suffixes that are ill-conditioned reductions: a
#: cholesky NaNs whole rows on an indefinite input; a slogdet silently
#: returns -inf/NaN. Both feed logdet terms that poison the likelihood.
_REDUCTION_SUFFIXES = (".cholesky", ".slogdet")
#: terminal call names that count as probe evidence in the enclosing
#: function: the identity probes (obs/numerics.py) and the host-side
#: block scanner the drain seam runs
_PROBE_NAMES = {"probe", "probe_cholesky", "scan_block"}


def _enclosing_function(mod: Module, node: ast.AST):
    """Nearest enclosing FunctionDef/AsyncFunctionDef, else None."""
    for anc in mod.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def _function_has_probe(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                _terminal(node.func) in _PROBE_NAMES:
            return True
    return False


class UnprobedReduction(Rule):
    id = "obs-unprobed-reduction"
    severity = "error"
    example_fire = (
        "def gls(c):\n"
        "    return jnp.linalg.cholesky(c)    # unprobed: FIRES\n"
    )
    example_ok = (
        "def gls(c):\n"
        "    c = numerics.probe_cholesky(c, 'gls.cov')\n"
        "    return jnp.linalg.cholesky(c)\n"
    )
    description = (
        "device cholesky/slogdet in a package hot path with no numerics "
        "probe in the enclosing function — an indefinite input NaNs the "
        "factorization silently and surfaces as an unattributable NaN "
        "lnlike; route the result through obs.numerics.probe_cholesky "
        "(or probe) so the episode names its producing site "
        "(docs/numerics.md)"
    )

    def check_module(self, mod: Module) -> Iterable[Finding]:
        if not mod.relpath.startswith(_HOT_PREFIXES):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = mod.resolve(node.func) or ""
            # jnp/jax-resolved only: the numpy f64 oracle paths
            # (dense_loglikelihood, dense() pins) are host-side
            # references a device probe would only add noise to
            if not resolved.endswith(_REDUCTION_SUFFIXES):
                continue
            if "jax" not in resolved and "jnp" not in resolved:
                continue
            fn = _enclosing_function(mod, node)
            if fn is not None and _function_has_probe(fn):
                continue
            # suppression window: the call line or the line above it —
            # same readable homes the cov-f32-cholesky rule accepts
            if any(
                self.id in mod.suppressions.get(ln, ())
                for ln in (node.lineno - 1, node.lineno)
            ):
                continue
            name = resolved.rsplit(".", 1)[-1]
            yield self.finding(
                mod, node.lineno,
                f"{name} in a hot path with no numerics probe in the "
                "enclosing function: wrap the factor in "
                "numerics.probe_cholesky(<site>, ...) (or numerics."
                "probe for a generic reduction), or suppress inline "
                "with the reason it cannot go non-finite",
            )


RULES = [UnboundedObsBuffer(), OrphanThreadSpan(), UnprobedReduction()]
