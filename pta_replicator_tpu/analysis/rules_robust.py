"""graftlint rule pack: robustness discipline in threaded/pipeline code.

PR 11 made the production paths fault-tolerant: errors are CLASSIFIED
(faults/retry.py), retried when transient, and always *visible* — a
counter bump, a ``faults.retry`` event, a recorded ``errors.append``,
a re-raise. The one shape that silently defeats all of that is the
broad swallowed handler::

    except Exception:
        pass            # the fault never happened, as far as anyone knows

In a threaded executor that's not just lost information — it's a hang
factory: a worker that swallows its failure keeps its queue peers
waiting forever, and the flight recorder's stall watchdog is the only
thing left to notice. Hence:

* ``robust-swallowed-exception`` — inside package modules that use
  threads (the pipeline/prefetch/serving/obs executors — the same
  ``_uses_threads`` gate the thread rules key on), flag an
  ``except Exception:`` / ``except BaseException:`` / bare ``except:``
  handler whose body does none of the following:

  - **re-raises** (any ``raise``),
  - **records the exception object** (the handler binds ``as exc`` and
    the body *uses* that name — ``errors.append(exc)``,
    ``fut.set_exception(exc)``, ``_fail(stage, exc)``,
    ``repr(exc)`` in a log line all count: the error object went
    somewhere a human or supervisor can see),
  - **logs or counts** (a call to ``print`` / a ``logging``-style
    method / ``counter(...).inc`` / ``event(...)`` inside the body),
  - **degrades to an explicit fallback value** (``return {}`` /
    ``return False`` — the caller-visible "unavailable" contract the
    obs probes document; the degradation is in the API, not invisible).

The firing shape is the pure swallow: ``pass``, ``continue``, or a
bare fallback assignment with nothing observable.

Narrow handlers (``except OSError:`` cleanup) are out of scope by
design — the rule polices *indiscriminate* swallowing, not considered
error handling. Intentional broad-and-silent sites (best-effort close
on an error path that re-raises the ORIGINAL exception one frame up)
carry an inline ``# graftlint: disable=robust-swallowed-exception``
with the reason, which is the reviewer-visible record the suppression
mechanism exists for.
"""
from __future__ import annotations

import ast
from typing import Iterable

from .engine import Finding, Module, Rule
from .rules_threads import _uses_threads

#: the subtree this pack polices (posix relpath prefix)
PKG_PREFIX = "pta_replicator_tpu/"

#: broad exception type names that make a handler a candidate
_BROAD = {"Exception", "BaseException", "builtins.Exception",
          "builtins.BaseException"}

#: call terminals that count as making the failure visible even when
#: the exception object itself isn't referenced (a counter bump or a
#: log line IS the visibility)
_VISIBILITY_CALLS = {
    "print", "log", "debug", "info", "warning", "warn", "error",
    "exception", "critical", "counter", "inc", "event", "write",
}


def _is_broad(mod: Module, handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for node in types:
        resolved = mod.resolve(node) or ""
        if resolved in _BROAD:
            return True
    return False


def _call_terminal(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _handled(handler: ast.ExceptHandler) -> bool:
    """True when the handler body re-raises, uses the bound exception
    name, or calls something on the visibility list."""
    bound = handler.name  # "exc" in `except Exception as exc`
    for node in ast.walk(ast.Module(body=handler.body,
                                    type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Return) and node.value is not None:
            return True  # explicit fallback value: a documented degrade
        if (
            bound
            and isinstance(node, ast.Name)
            and node.id == bound
            and isinstance(node.ctx, ast.Load)
        ):
            return True  # the exception object went somewhere
        if isinstance(node, ast.Call) and (
            _call_terminal(node) in _VISIBILITY_CALLS
        ):
            return True
    return False


class SwallowedException(Rule):
    id = "robust-swallowed-exception"
    severity = "error"
    example_fire = (
        "try:\n"
        "    stage.drain()\n"
        "except Exception:\n"
        "    pass                         # invisible fault: FIRES\n"
    )
    example_ok = (
        "except Exception as exc:\n"
        "    obs.counter('stages.drain_errors')\n"
        "    log.warning('drain failed: %s', exc)\n"
    )
    description = (
        "broad except handler in a threaded/pipeline module that "
        "neither re-raises, records the exception, logs, nor bumps a "
        "counter — an invisible fault in exactly the code where "
        "invisible faults become hangs"
    )

    def check_module(self, mod: Module) -> Iterable[Finding]:
        if not mod.relpath.startswith(PKG_PREFIX):
            return
        if not _uses_threads(mod):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(mod, node):
                continue
            if _handled(node):
                continue
            yield self.finding(
                mod, node.lineno,
                "broad except swallows the error silently: re-raise, "
                "record the exception object (errors.append / "
                "set_exception / a log line), bump a counter — or "
                "suppress inline with the reason",
            )


RULES = [SwallowedException()]
