"""graftlint rule pack: scenario-layer PRNG seed discipline.

The scenario compiler's correctness contract (scenarios/compile.py) is
*positional independence*: scenario K's draws — and each signal
family's draws within K — must not depend on how many scenarios (or
families) were processed before it. That property is what makes the
fuzz shrinker sound (deleting a spec section leaves every other
section's stream bit-identical) and what keeps a committed spec's
compile output stable forever. It holds exactly when every key
derivation is **indexed** (``fold_in(root, index)``) and none is
**sequential** (``key, sub = jax.random.split(key)`` threaded through a
loop: remove one iteration and every later draw shifts).

* ``scenario-split-chain`` — inside ``scenarios/`` modules, a call to
  ``jax.random.split`` whose result rebinds its own key operand
  (``key, k = split(key)`` / ``key = split(key)[0]``), or any
  ``jax.random.split``/key-consuming draw inside a loop body. Both are
  the sequential-chain shape; the fix is ``fold_in(root, i)`` with the
  loop index (or a per-family constant from ``FAMILY_IDS``).

The general ``jax-key-reuse`` rule (rules_jax.py) still applies in
``scenarios/`` too — this pack adds the stricter, subtree-scoped
"indexed, never sequential" requirement that only the scenario layer
promises.
"""
from __future__ import annotations

import ast
from typing import Iterable

from .engine import Finding, Module, Rule

#: the subtree this pack polices (posix relpath prefix)
SCENARIOS_PREFIX = "pta_replicator_tpu/scenarios/"

#: jax.random callables that CONSUME a key (draws + derivations)
_KEY_CALLS_PREFIX = "jax.random."
#: derivation calls: split is the sequential-chain primitive; fold_in
#: is the sanctioned indexed form
_SPLIT = "jax.random.split"
_FOLD_IN = "jax.random.fold_in"


def _names_in(node: ast.AST):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id


class ScenarioSplitChain(Rule):
    id = "scenario-split-chain"
    severity = "error"
    example_fire = (
        "for i in range(n):\n"
        "    key, sub = jax.random.split(key)   # chain: FIRES\n"
        "    draws.append(jax.random.normal(sub, shape))\n"
    )
    example_ok = (
        "for i in range(n):\n"
        "    sub = jax.random.fold_in(key, i)   # indexed, order-free\n"
        "    draws.append(jax.random.normal(sub, shape))\n"
    )
    description = (
        "sequential PRNG key chain in scenarios/ (split rebinding its "
        "own operand, or a key derivation/draw inside a loop): scenario "
        "and family draws must be fold_in-indexed so they are "
        "independent of iteration order (scenarios/compile.py seed "
        "discipline)"
    )

    def check_module(self, mod: Module) -> Iterable[Finding]:
        rel = mod.relpath.replace("\\", "/")
        if not rel.startswith(SCENARIOS_PREFIX):
            return
        # loop bodies in this module (for/while), for the in-loop check
        loop_spans = []
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                end = max(
                    (getattr(n, "lineno", node.lineno)
                     for n in ast.walk(node)),
                    default=node.lineno,
                )
                loop_spans.append((node.lineno, end))

        def in_loop(lineno: int) -> bool:
            return any(a < lineno <= b for a, b in loop_spans)

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = mod.resolve(node.func) or ""
            if not resolved.startswith(_KEY_CALLS_PREFIX):
                continue
            if resolved in (_KEY_CALLS_PREFIX + "PRNGKey",
                            _KEY_CALLS_PREFIX + "key",
                            _KEY_CALLS_PREFIX + "key_data"):
                continue
            if resolved == _SPLIT:
                # split rebinding its own operand = sequential chain,
                # loop or not
                operands = set(_names_in(node))
                assign = mod.ancestors(node)
                targets = set()
                for anc in assign:
                    if isinstance(anc, (ast.Assign, ast.AugAssign,
                                        ast.AnnAssign)):
                        tgt = (anc.targets if isinstance(anc, ast.Assign)
                               else [anc.target])
                        for t in tgt:
                            targets.update(_names_in(t))
                        break
                if operands & targets:
                    yield self.finding(
                        mod, node.lineno,
                        "jax.random.split rebinds its own key operand "
                        f"({', '.join(sorted(operands & targets))}) — a "
                        "sequential chain; derive with "
                        "jax.random.fold_in(root, index) instead",
                    )
                    continue
            if resolved != _FOLD_IN and in_loop(node.lineno):
                yield self.finding(
                    mod, node.lineno,
                    f"{resolved.rsplit('.', 1)[-1]} inside a loop body "
                    "in scenarios/: per-iteration keys must come from "
                    "jax.random.fold_in(root, loop_index), not "
                    "sequential derivation/draws",
                )


RULES = [ScenarioSplitChain()]
