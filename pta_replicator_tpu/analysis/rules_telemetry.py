"""graftlint rule pack: telemetry name discipline.

``obs/names.py`` is the single registry of span/metric/event names; this
pack closes the loop statically:

* ``telemetry-unknown-name`` — every *literal* name passed to a
  telemetry producer call (``span("freeze")``, ``counter("io.tim.toas")``,
  ``event(...)``, ``traced(...)``, ``instrumented_jit(name=...)``) must
  be registered in obs/names.py; a name referenced *symbolically*
  (``gauge(names.SWEEP_CHUNKS_DONE)``) is verified to point at a real
  constant. Either way, a misspelled or renamed name is a lint error —
  not silent drift between a producer, the report renderer, the flight
  recorder and the schema checker.
* ``telemetry-coverage`` — the public pipeline entrypoints the telemetry
  subsystem promises to instrument (the table formerly duplicated as
  grep markers in ``scripts/check_telemetry_schema.py``) still carry
  their spans/counters. Stripping or renaming instrumentation fails the
  lint instead of silently un-instrumenting the pipeline. The rule is
  AST-based, so it keeps working whether a producer uses the literal or
  the names.py constant.

Both rules skip test files (tests exercise private tracers with ad-hoc
names by design). The coverage rule arms itself only when the lint root
actually contains the names registry (``pta_replicator_tpu/obs/names.py``)
— fixture trees in unit tests aren't the real package and must not
produce a wall of "file missing" findings.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .engine import Finding, Module, Rule

#: producer callables -> the kind of name their first argument carries
_PRODUCER_KINDS = {
    "span": "span",
    "traced": "span",
    # synthesized span records (Tracer.record_span — the queue-wait/
    # resolution shape): same name namespace as live spans
    "record_span": "span",
    "event": "event",
    "counter": "metric",
    "gauge": "metric",
    "histogram": "metric",
    "instrumented_jit": "jit",
}

#: relpath of the registry module — also the coverage rule's arming anchor
NAMES_RELPATH = "pta_replicator_tpu/obs/names.py"


def load_registry() -> dict:
    """The real obs/names.py registry, shaped for the rules: kind ->
    frozenset of names, plus dynamic prefixes and the constant map used
    to validate symbolic references."""
    from ..obs import names

    constants = {
        k: v for k, v in vars(names).items()
        if k.isupper() and isinstance(v, str)
    }
    return {
        "span": names.SPANS,
        "event": names.EVENTS,
        "metric": names.METRICS,
        "jit": names.JIT_LABELS,
        "prefixes": tuple(names.METRIC_PREFIXES),
        "constants": constants,
    }


def _is_test_file(relpath: str) -> bool:
    base = os.path.basename(relpath)
    return (
        "tests/" in relpath
        or "examples/" in relpath
        or base.startswith("test_")
        or base == "conftest.py"
    )


def _producer_kind(mod: Module, call: ast.Call) -> Optional[str]:
    resolved = mod.resolve(call.func)
    if not resolved:
        return None
    return _PRODUCER_KINDS.get(resolved.rsplit(".", 1)[-1])


def _name_expr(call: ast.Call, kind: str) -> Optional[ast.AST]:
    if kind == "jit":
        for kw in call.keywords:
            if kw.arg == "name":
                return kw.value
        return None
    return call.args[0] if call.args else None


def _symbolic_constant(mod: Module, expr: ast.AST) -> Optional[str]:
    """The names.py constant name a symbolic reference points at
    (``names.SWEEP_CHUNKS_DONE`` or an imported ``SWEEP_CHUNKS_DONE``),
    else None."""
    resolved = mod.resolve(expr)
    if not resolved:
        return None
    parts = resolved.split(".")
    if len(parts) >= 2 and parts[-2] == "names":
        return parts[-1]
    return None


def extract_names(
    mod: Module, registry: dict
) -> Tuple[List[Tuple[str, str, int]], List[Finding]]:
    """All telemetry names produced by ``mod``: [(kind, name, lineno)].

    Literal names are returned as-is; symbolic references resolve
    through the registry's constant map. A symbolic reference to a
    constant that does not exist is returned as a problem Finding
    template (rule id filled in by the caller)."""
    out: List[Tuple[str, str, int]] = []
    bad_constants: List[Tuple[int, str]] = []
    constants = registry["constants"]
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        kind = _producer_kind(mod, node)
        if kind is None:
            continue
        expr = _name_expr(node, kind)
        if expr is None:
            continue
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            out.append((kind, expr.value, node.lineno))
            continue
        const = _symbolic_constant(mod, expr)
        if const is not None:
            if const in constants:
                out.append((kind, constants[const], node.lineno))
            else:
                bad_constants.append((node.lineno, const))
        # anything else (f-string, variable) is not statically checkable
    problems = [
        Finding(
            "telemetry-unknown-name", "error", mod.relpath, lineno,
            f"names.{const} does not exist in obs/names.py",
        )
        for lineno, const in bad_constants
    ]
    return out, problems


class UnknownTelemetryName(Rule):
    id = "telemetry-unknown-name"
    severity = "error"
    description = (
        "telemetry name at a producer call site is not registered in "
        "obs/names.py"
    )
    example_fire = (
        "with span('realize_blk'):        # typo, not in names.py: FIRES\n"
        "    ...\n"
    )
    example_ok = (
        "from ..obs import names\n"
        "with span(names.SPAN_REALIZE_BLOCK):\n"
        "    ...\n"
    )

    def __init__(self, registry: Optional[dict] = None):
        self._registry = registry

    @property
    def registry(self) -> dict:
        if self._registry is None:
            self._registry = load_registry()
        return self._registry

    def check_module(self, mod: Module) -> Iterable[Finding]:
        if _is_test_file(mod.relpath) or mod.relpath == NAMES_RELPATH:
            return
        names, problems = extract_names(mod, self.registry)
        yield from problems
        for kind, name, lineno in names:
            table = self.registry[kind]
            if name in table:
                continue
            if kind == "metric" and name.startswith(
                self.registry["prefixes"]
            ):
                continue
            yield self.finding(
                mod, lineno,
                f"{kind} name {name!r} is not registered in "
                "obs/names.py (typo, or add it to the registry)",
            )


#: (relpath, kind, name) triples the instrumentation gate protects — the
#: AST-checked successor of check_telemetry_schema.py's grep-marker
#: list. kinds: span | event | metric | jit | text (plain substring).
def default_coverage() -> Tuple[Tuple[str, str, str], ...]:
    from ..obs import names as n

    pkg = "pta_replicator_tpu"
    return (
        (f"{pkg}/batch.py", "span", n.SPAN_FREEZE),
        (f"{pkg}/simulate.py", "span", n.SPAN_MAKE_IDEAL),
        (f"{pkg}/simulate.py", "span", n.SPAN_LOAD_PULSARS),
        (f"{pkg}/simulate.py", "span", n.SPAN_ORACLE_FIT),
        (f"{pkg}/io/par.py", "span", n.SPAN_READ_PAR),
        (f"{pkg}/io/tim.py", "span", n.SPAN_READ_TIM),
        (f"{pkg}/timing/fit.py", "span", n.SPAN_DESIGN_TENSOR),
        (f"{pkg}/timing/fit.py", "span", n.SPAN_COVARIANCE_FROM_RECIPE),
        (f"{pkg}/parallel/mesh.py", "span", n.SPAN_MAKE_MESH),
        (f"{pkg}/parallel/mesh.py", "span", n.SPAN_SHARD_BATCH),
        (f"{pkg}/parallel/mesh.py", "span", n.SPAN_STATIC_DELAYS),
        (f"{pkg}/parallel/mesh.py", "span", n.SPAN_SHARDED_REALIZE),
        (f"{pkg}/parallel/mesh.py", "span", n.SPAN_SHARDMAP_REALIZE),
        (f"{pkg}/parallel/mesh.py", "jit", n.JIT_MESH_CONSTRAINT_ENGINE),
        (f"{pkg}/models/batched.py", "jit", n.JIT_REALIZE_ENGINE),
        (f"{pkg}/utils/sweep.py", "span", n.SPAN_SWEEP_CHUNK),
        (f"{pkg}/utils/sweep.py", "span", n.SPAN_READBACK_FENCE),
        (f"{pkg}/utils/sweep.py", "span", n.SPAN_SWEEP_PIPELINE),
        (f"{pkg}/utils/sweep.py", "span", n.SPAN_MULTICHIP_SWEEP),
        (f"{pkg}/utils/sweep.py", "metric", n.SWEEP_CHUNKS_TOTAL),
        (f"{pkg}/utils/sweep.py", "metric", n.SWEEP_CHUNKS_DONE),
        (f"{pkg}/utils/sweep.py", "metric", n.SWEEP_REALIZATIONS),
        # parallel sharded-archive writer (r17): the per-shard writer
        # spans, the live writer-pool occupancy gauge, and the
        # overlapped per-shard fsync counter — the fused mesh path's
        # disk fan-out must stay attributable or the io_write
        # exclusive-share evidence goes dark. The busy gauge is a text
        # row: sweep.py passes it as fan_out(busy_gauge=...) and the
        # gauge() call lives in parallel/stages.py with a variable
        # name (same referenced-not-emitted idiom as the pipeline.py
        # rows below).
        (f"{pkg}/utils/sweep.py", "span", n.SPAN_SHARD_WRITE),
        (f"{pkg}/utils/sweep.py", "text",
         "names.SWEEP_SHARD_WRITERS_BUSY"),
        (f"{pkg}/utils/sweep.py", "metric", n.SWEEP_SHARD_FSYNCS),
        # the sweep pipeline + prefetch stage spans and their window/
        # deadline/stall metrics are DECLARED in pipeline.py/prefetch.py
        # but emitted by the generic stage-graph executor (PR 15,
        # parallel/stages.py) — the span()/gauge() calls there take a
        # variable name, which is not statically checkable, so these
        # rows pin the constant REFERENCES at the declaration sites
        # (text markers, same approach as the jax.cost.* prefix rows)
        (f"{pkg}/parallel/pipeline.py", "text", "names.SPAN_DISPATCH"),
        (f"{pkg}/parallel/pipeline.py", "text", "names.SPAN_DRAIN"),
        (f"{pkg}/parallel/pipeline.py", "text", "names.SPAN_IO_WRITE"),
        (f"{pkg}/parallel/pipeline.py", "text",
         "names.SWEEP_INFLIGHT_CHUNKS"),
        (f"{pkg}/parallel/pipeline.py", "text",
         "names.PIPELINE_DRAIN_TIMEOUTS"),
        (f"{pkg}/parallel/pipeline.py", "metric",
         n.SWEEP_LAST_DISPATCHED_CHUNK),
        (f"{pkg}/parallel/prefetch.py", "text",
         "names.SPAN_CW_STREAM_STAGE"),
        (f"{pkg}/parallel/prefetch.py", "metric",
         n.CW_STREAM_BYTES_STAGED),
        (f"{pkg}/parallel/prefetch.py", "text",
         "names.CW_STREAM_PREFETCH_STALL_S"),
        # the stage-graph executor's own telemetry (PR 15): per-edge
        # queue depth, per-stage busy seconds (incl. the occupancy
        # mirror the prefetch contract pins), and the graph deadline
        # counter — every graph (sweep pipeline, prefetchers, fused
        # sweep) reports through these
        (f"{pkg}/parallel/stages.py", "metric", n.STAGES_EDGE_INFLIGHT),
        (f"{pkg}/parallel/stages.py", "metric", n.STAGES_BUSY_S),
        (f"{pkg}/parallel/stages.py", "metric", n.STAGES_DRAIN_TIMEOUTS),
        (f"{pkg}/parallel/stages.py", "metric", n.OCCUPANCY_BUSY_S),
        # multi-chip sweep path (PR 7): the per-shard readback gauge on
        # the mesh fetch, and the per-device staging instrumentation of
        # prefetch_to_mesh rides the cw_stream_stage/bytes_staged rows
        # above (same names, device= label)
        (f"{pkg}/parallel/mesh.py", "metric", n.SWEEP_SHARDS_INFLIGHT),
        (f"{pkg}/models/batched.py", "span", n.SPAN_CW_STREAM_RESPONSE),
        (f"{pkg}/models/batched.py", "metric", n.CW_STREAM_TILES_DONE),
        # likelihood subsystem (ISSUE 9): the serving path's SLO
        # telemetry (request/batch/eval counters, coalescing gauge,
        # queue depth, the serve/batch/project spans) and the two
        # engine jit labels — the simulate-infer loop's instrumentation
        # must not silently un-instrument
        (f"{pkg}/likelihood/serve.py", "span", n.SPAN_LIKELIHOOD_SERVE),
        (f"{pkg}/likelihood/serve.py", "span", n.SPAN_LIKELIHOOD_BATCH),
        (f"{pkg}/likelihood/serve.py", "span",
         n.SPAN_LIKELIHOOD_PROJECT),
        (f"{pkg}/likelihood/serve.py", "metric", n.LIKELIHOOD_REQUESTS),
        (f"{pkg}/likelihood/serve.py", "metric", n.LIKELIHOOD_BATCHES),
        (f"{pkg}/likelihood/serve.py", "metric",
         n.LIKELIHOOD_BATCH_SIZE),
        (f"{pkg}/likelihood/serve.py", "metric", n.LIKELIHOOD_EVALS),
        (f"{pkg}/likelihood/serve.py", "metric",
         n.LIKELIHOOD_COALESCE_EFFICIENCY),
        (f"{pkg}/likelihood/serve.py", "metric",
         n.LIKELIHOOD_QUEUE_DEPTH),
        (f"{pkg}/likelihood/infer.py", "jit", n.JIT_LIKELIHOOD_ENGINE),
        (f"{pkg}/likelihood/infer.py", "jit",
         n.JIT_LIKELIHOOD_REDUCED_ENGINE),
        # robustness layer (PR 11): fault firings must stay countable
        # and event-visible (a chaos run with silent faults proves
        # nothing), the supervised-recovery retries must stay
        # distinguishable from wedges in watch, and the serving path's
        # admission-control/deadline SLO counters must not silently
        # un-instrument
        (f"{pkg}/faults/inject.py", "metric", n.FAULTS_INJECTED),
        (f"{pkg}/faults/inject.py", "event", n.EVENT_FAULT_FIRED),
        (f"{pkg}/faults/retry.py", "event", n.EVENT_FAULT_RETRY),
        (f"{pkg}/utils/sweep.py", "metric", n.SWEEP_CHUNK_RETRIES),
        (f"{pkg}/parallel/prefetch.py", "metric",
         n.CW_STREAM_STAGE_RETRIES),
        (f"{pkg}/likelihood/serve.py", "metric", n.LIKELIHOOD_REJECTED),
        (f"{pkg}/likelihood/serve.py", "metric",
         n.LIKELIHOOD_DEADLINE_EXPIRED),
        # causal tracing + SLO layer (PR 14, docs/tracing.md): the
        # request-trace hop spans and per-request rejection/expiry
        # events on the serving path, the open-request gauge, and the
        # SLO engine's budget/burn gauges + breach event — the
        # request-level accountability story must not silently
        # un-instrument
        (f"{pkg}/likelihood/serve.py", "span", n.SPAN_LIKELIHOOD_SUBMIT),
        (f"{pkg}/likelihood/serve.py", "span",
         n.SPAN_LIKELIHOOD_QUEUE_WAIT),
        (f"{pkg}/likelihood/serve.py", "span",
         n.SPAN_LIKELIHOOD_RESOLVE),
        (f"{pkg}/likelihood/serve.py", "event",
         n.EVENT_LIKELIHOOD_REJECTED),
        (f"{pkg}/likelihood/serve.py", "event",
         n.EVENT_LIKELIHOOD_DEADLINE_EXPIRED),
        (f"{pkg}/likelihood/serve.py", "metric", n.TRACE_OPEN_REQUESTS),
        (f"{pkg}/obs/slo.py", "metric", n.SLO_ERROR_BUDGET_REMAINING),
        (f"{pkg}/obs/slo.py", "metric", n.SLO_BURN_RATE_FAST),
        (f"{pkg}/obs/slo.py", "metric", n.SLO_BURN_RATE_SLOW),
        (f"{pkg}/obs/slo.py", "metric", n.SLO_BREACHES),
        (f"{pkg}/obs/slo.py", "event", n.EVENT_SLO_BREACH),
        (f"{pkg}/obs/flightrec.py", "metric", n.FLIGHTREC_STALLS),
        (f"{pkg}/obs/flightrec.py", "event", n.EVENT_FLIGHTREC_STALL),
        # structured-covariance subsystem (ISSUE 13): the eager solve/
        # sample spans + the adoption counters in the instrumented
        # kernel helpers, and the blocked-Cholesky engine's jit label
        # (devprof roofline accounting) — the ladder's instrumentation
        # must not silently un-instrument
        (f"{pkg}/covariance/kernels.py", "span", n.SPAN_COV_SOLVE),
        (f"{pkg}/covariance/kernels.py", "span", n.SPAN_COV_SAMPLE),
        (f"{pkg}/covariance/kernels.py", "metric", n.COV_SOLVES),
        (f"{pkg}/covariance/kernels.py", "metric",
         n.COV_BLOCKED_FRACTION),
        (f"{pkg}/covariance/kernels.py", "jit", n.JIT_COV_CHOLESKY),
        # stage-occupancy + device-cost layer (PR 6): the heartbeat's
        # duty gauges, the prefetcher's busy accounting, the managed
        # jax.profiler capture, and the jax.cost./jax.roofline. gauge
        # families (emitted via the names.py prefix constants — the
        # text markers pin the constants' use, the f-strings themselves
        # aren't statically checkable)
        (f"{pkg}/obs/flightrec.py", "metric", n.OCCUPANCY_DUTY_CYCLE),
        # temporal layer (PR 8): the sampler's self-accounted overhead
        # counter (the <1%-of-wall evidence series) and the RSS-creep
        # gauge the series recorder samples each tick
        (f"{pkg}/obs/flightrec.py", "metric", n.OBS_OVERHEAD_S),
        (f"{pkg}/obs/series.py", "metric", n.PROC_RSS_BYTES),
        (f"{pkg}/obs/devprof.py", "span", n.SPAN_DEVICE_TRACE),
        (f"{pkg}/obs/devprof.py", "event", n.EVENT_DEVICE_TRACE),
        (f"{pkg}/obs/devprof.py", "text", "JAX_COST_PREFIX"),
        (f"{pkg}/obs/devprof.py", "text", "JAX_ROOFLINE_PREFIX"),
        # scenario layer (PR 12): compile and fuzz-case spans, the
        # compiled/cases/disagreements/shrink-step counters — the fuzz
        # harness's evidence trail (a silent fuzz run proves nothing)
        (f"{pkg}/scenarios/compile.py", "span", n.SPAN_SCENARIO_COMPILE),
        (f"{pkg}/scenarios/compile.py", "metric", n.SCENARIO_COMPILED),
        (f"{pkg}/scenarios/fuzz.py", "span", n.SPAN_SCENARIO_FUZZ_CASE),
        (f"{pkg}/scenarios/fuzz.py", "metric", n.SCENARIO_FUZZ_CASES),
        (f"{pkg}/scenarios/fuzz.py", "metric",
         n.SCENARIO_FUZZ_DISAGREEMENTS),
        (f"{pkg}/scenarios/fuzz.py", "metric", n.SCENARIO_SHRINK_STEPS),
        # critical-path attribution + performance ledger (PR 16): the
        # offline analyzers' own telemetry — the analyze span that
        # bounds the overhead claim, the chunk/straggler gauges, and
        # the ledger's round/regression gauges
        (f"{pkg}/obs/critpath.py", "span", n.SPAN_CRITPATH_ANALYZE),
        (f"{pkg}/obs/critpath.py", "metric", n.CRITPATH_CHUNKS),
        (f"{pkg}/obs/critpath.py", "metric", n.CRITPATH_STRAGGLERS),
        (f"{pkg}/obs/ledger.py", "metric", n.LEDGER_ROUNDS),
        (f"{pkg}/obs/ledger.py", "metric", n.LEDGER_REGRESSIONS),
        # numerics observatory (PR 18): the non-finite counter the SLO
        # layer alerts on, the per-site watermark/headroom gauges, the
        # shadow-oracle drift gauge, the episode event, and the sampled
        # drift-replay span that bounds its overhead claim
        (f"{pkg}/obs/numerics.py", "metric", n.NUMERICS_NONFINITE),
        (f"{pkg}/obs/numerics.py", "metric", n.NUMERICS_HEADROOM_BITS),
        (f"{pkg}/obs/numerics.py", "metric", n.NUMERICS_MAX_ABS),
        (f"{pkg}/obs/numerics.py", "metric", n.NUMERICS_DRIFT),
        (f"{pkg}/obs/numerics.py", "event", n.EVENT_NUMERICS_EPISODE),
        (f"{pkg}/obs/numerics.py", "span", n.SPAN_NUMERICS_DRIFT),
        # raw-speed ladder (PR 20): the fused Woodbury grid/bank engine
        # and the MXU tridiagonal engine must keep their devprof-visible
        # jit labels, and the autotuner's search span + search/cache-hit
        # counters are the evidence that CI never pays the search
        (f"{pkg}/likelihood/infer.py", "jit", n.JIT_GP_FUSED_WOODBURY),
        (f"{pkg}/covariance/kernels.py", "jit", n.JIT_COV_TRIDIAG_MXU),
        (f"{pkg}/likelihood/tuner.py", "span", n.SPAN_GP_TUNE),
        (f"{pkg}/likelihood/tuner.py", "metric", n.TUNER_SEARCHES),
        (f"{pkg}/likelihood/tuner.py", "metric", n.TUNER_CACHE_HITS),
        (f"{pkg}/__main__.py", "span", n.SPAN_COMPUTE),
        (f"{pkg}/__main__.py", "span", n.SPAN_INGEST),
        ("bench.py", "span", n.SPAN_BENCH_MEASURE),
        ("bench.py", "text", "BENCH_TELEMETRY"),
        ("bench.py", "text", "bench_cost_fields"),
    )


class TelemetryCoverage(Rule):
    id = "telemetry-coverage"
    severity = "error"
    description = (
        "required pipeline instrumentation missing (span/metric removed "
        "or renamed without updating the coverage table)"
    )
    example_fire = (
        "# models/batched.py: the realize span the coverage table\n"
        "# requires was deleted in a refactor -> FIRES on the file\n"
    )
    example_ok = (
        "# every (file, producer, name) row of REQUIRED_INSTRUMENTATION\n"
        "# resolves to a real call site (or the table row is removed\n"
        "# alongside the instrumentation, in the same PR)\n"
    )

    def __init__(
        self,
        coverage: Optional[Sequence[Tuple[str, str, str]]] = None,
        registry: Optional[dict] = None,
        anchor: str = NAMES_RELPATH,
        repo_marker: str = "pyproject.toml",
    ):
        self._coverage = coverage
        self._registry = registry
        self.anchor = anchor
        #: "file missing" findings fire only when this file exists under
        #: the lint root: a repo checkout has pyproject.toml, an
        #: installed wheel (site-packages) does not — there bench.py et
        #: al. are legitimately absent, not deleted
        self.repo_marker = repo_marker

    def check_project(self, mods: Sequence[Module]) -> Iterable[Finding]:
        if not mods:
            return
        root = mods[0].path[: -len(mods[0].relpath)].rstrip(os.sep)
        if self.anchor and not os.path.exists(
            os.path.join(root, self.anchor)
        ):
            return  # not the real tree (fixture dir in a unit test)
        coverage = (
            self._coverage if self._coverage is not None
            else default_coverage()
        )
        registry = (
            self._registry if self._registry is not None else load_registry()
        )
        by_rel: Dict[str, Module] = {m.relpath: m for m in mods}
        produced: Dict[str, set] = {}
        for relpath, kind, name in coverage:
            mod = by_rel.get(relpath)
            if mod is None:
                if not os.path.exists(os.path.join(root, relpath)) and \
                        os.path.exists(os.path.join(root, self.repo_marker)):
                    yield self.finding(
                        relpath, 1,
                        "file missing but still listed in the "
                        "telemetry coverage table",
                    )
                continue  # file exists, just not in this (partial) run
            if kind == "text":
                if name not in mod.source:
                    yield self.finding(
                        mod, 1,
                        f"required marker {name!r} not found "
                        "(instrumentation removed or renamed without "
                        "updating rules_telemetry.default_coverage)",
                    )
                continue
            if relpath not in produced:
                produced[relpath] = {
                    (k, v) for k, v, _ in extract_names(mod, registry)[0]
                }
            if (kind, name) not in produced[relpath]:
                yield self.finding(
                    mod, 1,
                    f"required {kind} instrumentation {name!r} not "
                    "found (removed or renamed without updating "
                    "rules_telemetry.default_coverage)",
                )


RULES = [UnknownTelemetryName(), TelemetryCoverage()]
