"""graftlint rule pack: thread/lock/clock discipline.

The concurrency hazards PR 2's pipelined executor and PR 3's flight
recorder work around by careful convention, enforced statically:

* ``thread-unlocked-global`` — in a module that uses threads
  (``threading.Thread``/``Lock``), module-level mutable state mutated at
  function scope outside a ``with <lock>`` block. The flight recorder's
  signal handler explicitly documents why this matters: an interrupted
  thread may hold the lock the handler needs, and unprotected mutation
  is a torn-state bug under exactly that interleaving.
* ``thread-walltime-duration`` — ``time.time()`` used in +/- arithmetic
  (durations, deadlines). Wall clock steps under NTP corrections and DST
  — a backwards jump turns a watchdog deadline into an instant trip or a
  span duration negative. Durations and deadlines use
  ``time.monotonic()`` (or ``perf_counter``); ``time.time()`` is only
  for *exported timestamps* (the ``t0`` fields in events.jsonl).
* ``thread-lock-order`` — nested ``with`` acquisition of two known locks
  in an order that inverts :data:`LOCK_HIERARCHY`. The hierarchy records
  the tracer/flightrec discipline: the flight recorder's lifecycle and
  active-recorder locks are OUTER locks; the tracer's and registry's
  ``_lock`` is the innermost leaf — code holding it must never wait on
  anything else (Tracer._record runs listeners outside it for exactly
  this reason; ``_flush_from_signal`` exists because a suspended main
  thread may hold it).
* ``parallel-adhoc-stage`` — a raw ``threading.Thread`` +
  ``queue.Queue`` pipeline in package code outside ``parallel/``: the
  hand-built staged-executor shape ``parallel/stages.py`` exists to
  replace. An ad-hoc worker/queue pair re-implements (usually
  partially) the bounded window, stop/drain handshake, DrainTimeout
  heartbeats, in-order error propagation, and trace handoff the stage
  graph provides once — declare a ``StageGraph`` instead, or suppress
  inline with the reason the shape genuinely doesn't fit (the
  likelihood server's deadline-coalescing request queue is the one
  intentional site).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from .engine import Finding, Module, Rule
from .rules_jax import _module_level_mutables, _terminal

#: lock acquisition order, outermost first. Acquiring a lock while
#: holding one that appears LATER in this tuple is an inversion. The
#: terminal identifier is matched (``self._pm_lock`` -> ``_pm_lock``), so
#: the hierarchy is shared by the flightrec/tracer/registry instances
#: that use these conventional names.
LOCK_HIERARCHY: Tuple[str, ...] = (
    "_active_lock",     # obs.flightrec: process-global active recorder
    "_lifecycle_lock",  # obs.flightrec: sampler start/stop
    "_pm_lock",         # obs.flightrec: postmortem write-once
    "_install_lock",    # obs.jaxhooks: listener install-once
    "_trace_lock",      # obs.jaxhooks: per-label trace counts
    "_lock",            # obs.trace / obs.metrics: innermost leaf locks
)

_MUTATOR_METHODS = {
    "append", "appendleft", "add", "extend", "insert", "update",
    "setdefault", "pop", "popleft", "popitem", "remove", "discard",
    "clear",
}


def _lock_name(mod: Module, expr: ast.AST) -> Optional[str]:
    """Terminal identifier of a lock-ish context expr, else None.
    Matches names/attributes whose last component contains 'lock'
    (``self._lock``, ``_active_lock``, ``tracer._lock``)."""
    qn = mod.qualname(expr)
    if qn is None and isinstance(expr, ast.Call):
        # `with self._lock:` vs `with lock_factory():` — only direct
        # name/attribute context exprs count as holding a named lock
        return None
    if qn is None:
        return None
    term = qn.rsplit(".", 1)[-1]
    return term if "lock" in term.lower() else None


def _held_locks(mod: Module, node: ast.AST) -> List[str]:
    """Lock names held by enclosing ``with`` statements, outermost
    first."""
    held = []
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                name = _lock_name(mod, item.context_expr)
                if name:
                    held.append(name)
    held.reverse()
    return held


def _uses_threads(mod: Module) -> bool:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call):
            resolved = mod.resolve(node.func) or ""
            if resolved in (
                "threading.Thread", "threading.Lock", "threading.RLock",
                "threading.Condition",
            ):
                return True
    return False


class UnlockedGlobalMutation(Rule):
    id = "thread-unlocked-global"
    severity = "error"
    description = (
        "module-level mutable state mutated outside a lock in a "
        "module that uses threads"
    )
    example_fire = (
        "_SAMPLES = []\n"
        "def worker():                    # module also spawns threads\n"
        "    _SAMPLES.append(read())      # unlocked mutation: FIRES\n"
    )
    example_ok = (
        "_SAMPLES = []\n"
        "_lock = threading.Lock()\n"
        "def worker():\n"
        "    with _lock:\n"
        "        _SAMPLES.append(read())\n"
    )

    def check_module(self, mod: Module) -> Iterable[Finding]:
        if not _uses_threads(mod):
            return
        mutables = _module_level_mutables(mod)
        if not mutables:
            return
        for node in ast.walk(mod.tree):
            name, verb = self._mutation(mod, node)
            if name is None or name not in mutables:
                continue
            # module-level init / re-init is single-threaded import time
            if not any(
                isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
                for a in mod.ancestors(node)
            ):
                continue
            if _held_locks(mod, node):
                continue
            yield self.finding(
                mod, node.lineno,
                f"{verb} of module-level mutable {name!r} outside a "
                "'with <lock>:' block in a threaded module (torn state "
                "under concurrent access / signal handlers)",
            )

    def _mutation(self, mod: Module, node: ast.AST):
        """(name, verb) when ``node`` mutates a plain-Name container."""
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Subscript) and isinstance(
                    t.value, ast.Name
                ):
                    return t.value.id, "item assignment"
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and isinstance(
                    t.value, ast.Name
                ):
                    return t.value.id, "item deletion"
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            if node.func.attr in _MUTATOR_METHODS and isinstance(
                node.func.value, ast.Name
            ):
                return node.func.value.id, f".{node.func.attr}()"
        return None, None


class WallTimeDuration(Rule):
    id = "thread-walltime-duration"
    severity = "error"
    description = (
        "time.time() used in duration/deadline arithmetic — wall clock "
        "steps; use time.monotonic()"
    )
    example_fire = (
        "t0 = time.time()\n"
        "work()\n"
        "elapsed = time.time() - t0       # wall clock steps: FIRES\n"
    )
    example_ok = (
        "t0 = time.monotonic()\n"
        "work()\n"
        "elapsed = time.monotonic() - t0\n"
        "stamp = time.time()              # timestamps (not durations) ok\n"
    )

    def check_module(self, mod: Module) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.BinOp) or not isinstance(
                node.op, (ast.Add, ast.Sub)
            ):
                continue
            for side in (node.left, node.right):
                if (
                    isinstance(side, ast.Call)
                    and (mod.resolve(side.func) or "") == "time.time"
                ):
                    yield self.finding(
                        mod, node.lineno,
                        "time.time() in +/- arithmetic: wall clock can "
                        "step backwards (NTP) — use time.monotonic() "
                        "for durations and deadlines; keep time.time() "
                        "only for exported timestamps",
                    )
                    break


#: the package subtree the ad-hoc-stage rule polices, and the
#: subpackage where staged executors legitimately live
_PKG_PREFIX = "pta_replicator_tpu/"
_STAGES_HOME = "pta_replicator_tpu/parallel/"


class AdhocStagePipeline(Rule):
    id = "parallel-adhoc-stage"
    severity = "error"
    description = (
        "raw threading.Thread + queue.Queue pipeline outside parallel/ "
        "— the shape parallel/stages.py (StageGraph) exists to replace"
    )
    example_fire = (
        "# models/foo.py\n"
        "q = queue.Queue(maxsize=2)\n"
        "threading.Thread(target=producer, args=(q,)).start()  # FIRES\n"
    )
    example_ok = (
        "# models/foo.py\n"
        "from ..parallel.stages import StageGraph\n"
        "graph = StageGraph([('produce', producer), ('write', writer)])\n"
    )

    def check_module(self, mod: Module) -> Iterable[Finding]:
        if not mod.relpath.startswith(_PKG_PREFIX):
            return
        if mod.relpath.startswith(_STAGES_HOME):
            return  # the executors' own home
        queue_lines = [
            node.lineno for node in ast.walk(mod.tree)
            if isinstance(node, ast.Call)
            and (mod.resolve(node.func) or "") in (
                "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
                "queue.PriorityQueue",
            )
        ]
        if not queue_lines:
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if (mod.resolve(node.func) or "") != "threading.Thread":
                continue
            yield self.finding(
                mod, node.lineno,
                "worker thread + queue.Queue pipeline (queue built at "
                f"line {queue_lines[0]}) hand-rolls the staged-executor "
                "pattern — declare a parallel.stages.StageGraph (bounded "
                "window, stop/drain, DrainTimeout heartbeats, in-order "
                "errors, busy accounting, and trace handoff for free), "
                "or suppress with the reason the graph doesn't fit",
            )


class LockOrderInversion(Rule):
    id = "thread-lock-order"
    severity = "error"
    description = (
        "nested lock acquisition inverts the recorded tracer/flightrec "
        "lock hierarchy (deadlock risk)"
    )
    example_fire = (
        "with self._lock:                 # innermost lock first...\n"
        "    with self._trace_lock:       # ...then an outer one: FIRES\n"
        "        flush()\n"
    )
    example_ok = (
        "with self._trace_lock:           # LOCK_HIERARCHY order\n"
        "    with self._lock:\n"
        "        flush()\n"
    )

    def __init__(self, hierarchy: Tuple[str, ...] = LOCK_HIERARCHY):
        self.rank: Dict[str, int] = {
            name: i for i, name in enumerate(hierarchy)
        }

    def check_module(self, mod: Module) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.With):
                continue
            for item in node.items:
                inner = _lock_name(mod, item.context_expr)
                if inner is None or inner not in self.rank:
                    continue
                for outer in _held_locks(mod, node):
                    if outer == inner or outer not in self.rank:
                        continue
                    if self.rank[outer] > self.rank[inner]:
                        yield self.finding(
                            mod, node.lineno,
                            f"acquiring {inner!r} while holding "
                            f"{outer!r} inverts the lock hierarchy "
                            f"({' > '.join(k for k in self.rank)}): "
                            "another thread taking them in order "
                            "deadlocks against this one",
                        )
RULES = [UnlockedGlobalMutation(), WallTimeDuration(),
         LockOrderInversion(), AdhocStagePipeline()]
