"""PulsarBatch: the frozen, device-resident representation of a pulsar array.

This is the heart of the TPU-first inversion of the reference's design
(SURVEY.md section 7). The reference mutates a stateful PINT TOAs object per
injection and re-evaluates the full timing model each time
(/root/reference/pta_replicator/simulate.py:40-42); here the dataset is
ingested once on CPU, frozen into padded (Np, Nt) arrays, and every
injection is a pure function producing per-TOA delays. The total residual
is the (masked, weighted-mean-subtracted) sum of delays — which makes the
reference's provenance ledger (`added_signals_time`) a zero-cost stacked
array instead of a dict of mutations.

Data-dependent structure (ECORR epoch binning, per-backend flag matching,
ragged TOA counts) is resolved here at freeze time into integer index
arrays, so everything under ``jit`` is static-shaped and gather-based.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .constants import DAY_IN_SEC
from .obs import counter, span
from .ops.coords import pulsar_theta_phi, unit_vector
from .ops.quantize import quantize


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class PulsarBatch:
    """Padded/masked arrays describing Np pulsars with up to Nt TOAs each.

    Times are seconds relative to ``tref_mjd`` (a global reference epoch
    near the dataset centre) so that float32 device arithmetic retains
    sub-millisecond time resolution (SURVEY.md "hard parts": precision).
    """

    #: (Np, Nt) TOA epochs [s relative to tref_mjd]
    toas_s: jax.Array
    #: (Np, Nt) TOA uncertainties [s] (1.0 in padding)
    errors_s: jax.Array
    #: (Np, Nt) 1.0 for real TOAs, 0.0 for padding
    mask: jax.Array
    #: (Np, 3) pulsar direction unit vectors
    phat: jax.Array
    #: (Np, Nt) ECORR epoch index (local per pulsar, 0..max_epochs-1)
    epoch_index: jax.Array
    #: (Np, max_epochs) 1.0 for real epochs
    epoch_mask: jax.Array
    #: (Np, max_epochs) backend index of each epoch (its first TOA's flag)
    epoch_backend_index: jax.Array
    #: (Np, Nt) backend/flag-group index (0..max_backends-1)
    backend_index: jax.Array
    #: (Np,) observation span [s] of each pulsar
    tspan_s: jax.Array
    #: (Np,) number of valid TOAs
    ntoas: jax.Array
    #: (Np, Nt) observing radio frequency [MHz] (1400.0 in padding);
    #: None on batches frozen before chromatic ops existed — the
    #: chromatic-noise op requires it and raises otherwise
    freqs_mhz: Optional[jax.Array] = None

    # -- static metadata (not traced)
    tref_mjd: float = field(metadata=dict(static=True), default=0.0)
    names: tuple = field(metadata=dict(static=True), default=())
    backend_names: tuple = field(metadata=dict(static=True), default=())
    start_s: float = field(metadata=dict(static=True), default=0.0)
    stop_s: float = field(metadata=dict(static=True), default=0.0)

    @property
    def npsr(self) -> int:
        return self.toas_s.shape[0]

    @property
    def ntoa_max(self) -> int:
        return self.toas_s.shape[1]

    @property
    def max_epochs(self) -> int:
        return self.epoch_mask.shape[1]

    def astype(self, dtype) -> "PulsarBatch":
        """Cast floating leaves (times stay in their relative frame)."""
        cast = lambda x: (
            x.astype(dtype)
            if x is not None and jnp.issubdtype(x.dtype, jnp.floating)
            else x
        )
        return jax.tree_util.tree_map(cast, self)


def synthetic_batch(
    npsr: int = 68,
    ntoa: int = 7758,
    nbackend: int = 4,
    span_days: float = 365.25 * 16,
    toaerr_s: float = 0.5e-6,
    epoch_days: float = 14.0,
    seed: int = 0,
    dtype=None,
) -> PulsarBatch:
    """Build an NG15-scale synthetic PulsarBatch directly from arrays
    (random sky positions, ~epoch_days observing cadence with several TOAs
    per epoch across nbackend backends). Used by the benchmark harness and
    the graft entry points; mirrors the scale of the realistic workload
    (69 pulsars, ~7.7k TOAs, noise_dicts/ng15_dict.json)."""
    if dtype is None:
        dtype = jnp.zeros(0).dtype
    rng = np.random.default_rng(seed)
    nepoch = max(1, int(span_days / epoch_days))
    per_epoch = max(1, ntoa // nepoch)
    nepoch = (ntoa + per_epoch - 1) // per_epoch

    epoch_times = np.sort(rng.uniform(0.0, span_days, size=(npsr, nepoch)), axis=1)
    offsets = rng.uniform(0.0, 0.2, size=(npsr, nepoch, per_epoch))
    toas_d = (epoch_times[:, :, None] + offsets).reshape(npsr, -1)[:, :ntoa]
    toas_d = np.sort(toas_d, axis=1)
    toas_s = (toas_d - span_days / 2.0) * DAY_IN_SEC

    epoch_idx = (np.arange(ntoa) // per_epoch)[None, :].repeat(npsr, axis=0)
    nep = int(epoch_idx.max()) + 1
    epoch_mask = np.ones((npsr, nep))
    epoch_backend = rng.integers(0, nbackend, size=(npsr, nep))
    backend_idx = np.take_along_axis(epoch_backend, epoch_idx, axis=1)

    costheta = rng.uniform(-1, 1, npsr)
    phi = rng.uniform(0, 2 * np.pi, npsr)
    sintheta = np.sqrt(1 - costheta**2)
    phat = np.stack(
        [sintheta * np.cos(phi), sintheta * np.sin(phi), costheta], axis=1
    )

    # per-backend observing bands (realistic NANOGrav-ish spread) with a
    # little per-TOA bandwidth scatter
    band_centers = np.linspace(430.0, 2300.0, nbackend)
    freqs = band_centers[backend_idx] * rng.uniform(
        0.9, 1.1, size=backend_idx.shape
    )

    return PulsarBatch(
        toas_s=jnp.asarray(toas_s, dtype),
        errors_s=jnp.full((npsr, ntoa), toaerr_s, dtype),
        mask=jnp.ones((npsr, ntoa), dtype),
        phat=jnp.asarray(phat, dtype),
        epoch_index=jnp.asarray(epoch_idx, jnp.int32),
        epoch_mask=jnp.asarray(epoch_mask, dtype),
        epoch_backend_index=jnp.asarray(epoch_backend, jnp.int32),
        backend_index=jnp.asarray(backend_idx, jnp.int32),
        tspan_s=jnp.asarray(toas_s.max(axis=1) - toas_s.min(axis=1), dtype),
        ntoas=jnp.full(npsr, ntoa, jnp.int32),
        freqs_mhz=jnp.asarray(freqs, dtype),
        tref_mjd=55000.0,
        names=tuple(f"SYN{i:04d}" for i in range(npsr)),
        backend_names=tuple(f"backend{i}" for i in range(nbackend)),
        start_s=float(toas_s.min() - DAY_IN_SEC),
        stop_s=float(toas_s.max() + DAY_IN_SEC),
    )


def freeze(
    psrs: List,
    flagid: str = "f",
    coarsegrain: float = 0.1,
    tref_mjd: Optional[float] = None,
    dtype=None,
) -> PulsarBatch:
    """Freeze a list of :class:`~pta_replicator_tpu.simulate.SimulatedPulsar`
    (or anything with ``.toas``/``.loc``/``.name``) into a PulsarBatch.

    Runs once per dataset on CPU: ragged TOA sets are padded to the max
    count, ECORR epochs are binned (greedy ``coarsegrain``-day buckets, same
    rule as the oracle path), and per-TOA backend flags become integer
    groups shared across the array (so per-backend parameters are (Np,
    n_backends) arrays gathered per TOA on device).
    """
    with span("freeze", npsr=len(psrs)) as sp:
        batch = _freeze_impl(
            psrs, flagid=flagid, coarsegrain=coarsegrain,
            tref_mjd=tref_mjd, dtype=dtype,
        )
        sp["ntoa_max"] = batch.ntoa_max
        sp["max_epochs"] = batch.max_epochs
        counter("batch.freezes").inc()
        counter("batch.toas_frozen").inc(int(np.asarray(batch.ntoas).sum()))
        return batch


def _freeze_impl(
    psrs: List,
    flagid: str,
    coarsegrain: float,
    tref_mjd: Optional[float],
    dtype,
) -> PulsarBatch:
    if dtype is None:
        dtype = jnp.zeros(0).dtype  # jax default float (f64 under x64)
    npsr = len(psrs)
    ntoas = np.array([p.toas.ntoas for p in psrs], dtype=np.int32)
    nt = int(ntoas.max())

    mjds = [p.toas.get_mjds() for p in psrs]
    if tref_mjd is None:
        tref_mjd = float(
            0.5 * (min(m.min() for m in mjds) + max(m.max() for m in mjds))
        )

    toas = np.zeros((npsr, nt))
    errors = np.ones((npsr, nt))
    mask = np.zeros((npsr, nt))
    # observing frequencies feed chromatic noise; if ANY pulsar lacks
    # them the whole field stays None so the chromatic op raises loudly
    # instead of silently treating a 1400 MHz fill as real physics
    have_freqs = all(
        getattr(p.toas, "freqs_mhz", None) is not None for p in psrs
    )
    freqs = np.full((npsr, nt), 1400.0)  # benign padding (no div-by-zero)
    backend_idx = np.zeros((npsr, nt), dtype=np.int32)
    epoch_idx = np.zeros((npsr, nt), dtype=np.int32)
    phat = np.zeros((npsr, 3))
    tspan = np.zeros(npsr)

    # global backend vocabulary across pulsars
    backend_names: List[str] = []
    epoch_counts = []
    epoch_indices = []
    for i, p in enumerate(psrs):
        n = p.toas.ntoas
        rel = (mjds[i] - tref_mjd) * DAY_IN_SEC
        toas[i, :n] = rel
        toas[i, n:] = rel[-1] if n else 0.0  # benign padding values
        errors[i, :n] = p.toas.errors_s
        if have_freqs:
            freqs[i, :n] = p.toas.freqs_mhz
        mask[i, :n] = 1.0
        tspan[i] = rel[:n].max() - rel[:n].min() if n else 0.0
        theta, phi = pulsar_theta_phi(p.loc, p.name)
        phat[i] = unit_vector(theta, phi)

        flags = p.toas.get_flag(flagid)
        # vectorized vocab mapping: unique values once, O(V) list work.
        # The global vocabulary grows in order of first appearance (TOA
        # order within each pulsar), so re-freezing a dataset reproduces
        # the backend_names ordering of any tables built against it.
        flags_arr = np.asarray([str(v) for v in flags])
        uniq, first, inv = np.unique(
            flags_arr, return_index=True, return_inverse=True
        )
        local_to_global = np.empty(len(uniq), dtype=np.int32)
        for u_i in np.argsort(first):
            val = str(uniq[u_i])  # plain str, not np.str_
            if val not in backend_names:
                backend_names.append(val)
            local_to_global[u_i] = backend_names.index(val)
        backend_idx[i, :n] = local_to_global[inv]

        bins = quantize(mjds[i], flags=flags, dt=coarsegrain)
        epoch_indices.append(bins.epoch_index)
        epoch_counts.append(bins.nepochs)

    max_epochs = int(max(epoch_counts)) if epoch_counts else 1
    epoch_mask = np.zeros((npsr, max_epochs))
    epoch_backend = np.zeros((npsr, max_epochs), dtype=np.int32)
    for i, p in enumerate(psrs):
        idx, cnt = epoch_indices[i], epoch_counts[i]
        epoch_idx[i, : len(idx)] = idx
        epoch_mask[i, :cnt] = 1.0
        # backend of each epoch = backend of its (time-)first TOA
        order = np.argsort(mjds[i], kind="stable")
        uniq_e, first_pos = np.unique(idx[order], return_index=True)
        epoch_backend[i, uniq_e] = backend_idx[i, order[first_pos]]

    start = float(min(m.min() for m in mjds) - 1.0) * DAY_IN_SEC
    stop = float(max(m.max() for m in mjds) + 1.0) * DAY_IN_SEC

    return PulsarBatch(
        toas_s=jnp.asarray(toas, dtype=dtype),
        errors_s=jnp.asarray(errors, dtype=dtype),
        mask=jnp.asarray(mask, dtype=dtype),
        phat=jnp.asarray(phat, dtype=dtype),
        epoch_index=jnp.asarray(epoch_idx),
        epoch_mask=jnp.asarray(epoch_mask, dtype=dtype),
        epoch_backend_index=jnp.asarray(epoch_backend),
        backend_index=jnp.asarray(backend_idx),
        tspan_s=jnp.asarray(tspan, dtype=dtype),
        ntoas=jnp.asarray(ntoas),
        freqs_mhz=jnp.asarray(freqs, dtype=dtype) if have_freqs else None,
        tref_mjd=tref_mjd,
        names=tuple(p.name for p in psrs),
        backend_names=tuple(backend_names),
        start_s=start - tref_mjd * DAY_IN_SEC,
        stop_s=stop - tref_mjd * DAY_IN_SEC,
    )
