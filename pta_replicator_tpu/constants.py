"""Physical constants in the units used throughout the framework.

Mirrors the constant set of the reference implementation
(/root/reference/pta_replicator/constants.py:1-8) so that injected signal
amplitudes agree numerically, but is computed from scipy.constants here.
"""
import scipy.constants as _sc

DAY_IN_SEC = 86400.0
YEAR_IN_SEC = 365.25 * DAY_IN_SEC

#: radians <-> milliarcseconds, shared by the par value-write,
#: error-write, and par-read paths so their units can never desync
RAD_TO_MAS = (180.0 / _sc.pi) * 3.6e6
MAS_TO_RAD = 1.0 / RAD_TO_MAS

#: Dispersion constant, MHz^2 cm^3 pc s
DM_K = 4.15e3

#: Geometrized solar mass: G M_sun / c^3 [s]
SOLAR2S = _sc.G / _sc.c**3 * 1.98855e30
#: kiloparsec in light-seconds
KPC2S = _sc.parsec / _sc.c * 1e3
#: megaparsec in light-seconds
MPC2S = _sc.parsec / _sc.c * 1e6

#: Speed of light [m/s] and derived helpers used by the population pipeline
C_MS = _sc.c
PC_M = _sc.parsec
MSUN_KG = 1.98855e30
#: astronomical unit in parsec (solar-wind dispersion geometry)
AU_PC = _sc.au / _sc.parsec
