"""Beyond-diagonal noise covariance: structured representations
(:mod:`.structure`) and their blocked/Kronecker solve kernels
(:mod:`.kernels`).

The subsystem closes ROADMAP open item 3 (arXiv:2506.13866's improved
covariance modeling + arXiv:1407.1838's GP formulation): a
:class:`~pta_replicator_tpu.covariance.structure.CovOp` rides inside a
``Recipe`` — the batched engine *samples* correlated noise from it
(``models/batched.realization_delays``), the GLS refit *weights* by it
(the generalized ``white_ecorr_solver``), and the GP likelihood
*prices* it (``likelihood/gp.py``) — all against one dense float64
oracle (:func:`~pta_replicator_tpu.covariance.structure.
dense_noise_covariance`). See docs/covariance.md.
"""
from .structure import (  # noqa: F401
    COV_STREAM_FOLD,
    BandedCov,
    CovOp,
    DenseCov,
    KroneckerCov,
    LowRankCov,
    banded_from_times,
    dense_from_times,
    dense_noise_covariance,
    kron_time_channel,
    recipe_cov_s2,
)
from . import kernels  # noqa: F401
