"""Structured-covariance solve kernels: blocked Cholesky, block-
tridiagonal (banded) Cholesky, and Kronecker solves.

The solver ladder (cheapest structure that fits wins — docs/
covariance.md):

=====================  =======================  =====================
structure              factorization            cost per pulsar
=====================  =======================  =====================
diagonal (+ECORR)      analytic Woodbury        O(Nt)  (white_ecorr_
                                                solver, unchanged)
block-tridiagonal      :func:`block_tridiag_    O(Nt b^2)
("banded", bandwidth   cholesky` — lax.scan of
b)                     (b, b) MXU factor/solve
                       steps
Kronecker time (x)     :func:`kron_solve` —     O(ne^3 + nc^3
channel                per-factor Cholesky      + Nt (ne + nc))
dense                  :func:`blocked_          O(Nt^3), blocked for
                       cholesky` — right-       the MXU (tiled SYRK
                       looking blocked w/       trailing update)
                       Pallas or tiled-XLA
                       trailing update
=====================  =======================  =====================

``blocked_cholesky``'s trailing update — the O(n^3) bulk — has two
backends sharing ONE tile implementation
(:func:`~pta_replicator_tpu.ops.pallas_cw.cov_tile_update`): the
Pallas TPU kernel (``ops/pallas_cw.cov_syrk_update``) and a pure-XLA
tiled loop. Because both run the same op sequence per tile, the two
are bit-identical on CPU under ``interpret=True``
(tests/test_covariance.py pins this), so the CPU path stays a faithful
test double of the TPU kernel. ``backend='auto'`` picks XLA on CPU
(LAPACK beats any hand blocking there) and the Pallas tiling on TPU.

Everything here is shape-static, jit/vmap/grad-safe (scan + batched
(b, b) primitives), and runs at the caller's dtype — covariance
factorizations at f32 are only as good as their conditioning, so every
consumer is pinned against an f64 dense oracle (the `cov-f32-cholesky`
lint rule enforces the cast-or-justify discipline tree-wide).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.linalg import solve_triangular

from ..obs import numerics
from ..ops.pallas_cw import cov_syrk_update, cov_tile_update


def _chol_logdet(L):
    """log det from a (batched) Cholesky factor: 2 sum log diag."""
    return 2.0 * jnp.sum(
        jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)), axis=-1
    )


# ------------------------------------------------------ blocked dense

def _syrk_xla(C, L, tile: int):
    """Tiled-XLA trailing update: the same per-tile op sequence as the
    Pallas kernel (shared :func:`cov_tile_update`), looped over the
    static tile grid — the bit-identical CPU fallback. Strictly-upper
    tiles pass through un-updated, exactly as the kernel's
    ``pl.when`` guard skips them (only the lower triangle is consumed
    downstream)."""
    m = C.shape[-1]
    nt = m // tile
    rows = []
    for i in range(nt):
        li = L[:, i * tile:(i + 1) * tile, :]
        cols = [
            cov_tile_update(
                C[:, i * tile:(i + 1) * tile, j * tile:(j + 1) * tile],
                li,
                L[:, j * tile:(j + 1) * tile, :],
            ) if j <= i else
            C[:, i * tile:(i + 1) * tile, j * tile:(j + 1) * tile]
            for j in range(nt)
        ]
        rows.append(jnp.concatenate(cols, axis=-1))
    return jnp.concatenate(rows, axis=-2)


def blocked_cholesky(A, block: int = 128, backend: str = "auto"):
    """Lower Cholesky factor of a batched SPD matrix ``A`` (Np, n, n)
    via the right-looking blocked algorithm: per step, one (block,
    block) ``jnp.linalg.cholesky`` of the diagonal block, a batched
    triangular panel solve, and the SYRK trailing update — the O(n^3)
    bulk — through the selected backend ('xla' tiled loop, 'pallas'
    TPU kernel, 'pallas_interpret' the same kernel interpreted on CPU,
    'auto' = xla on CPU / pallas on TPU).

    ``n`` is padded up to a multiple of ``block`` with identity rows
    (decoupled — they factor to unit diagonal and touch nothing), so
    any n works. Returns the (Np, n, n) lower factor.
    """
    if backend == "auto":
        backend = "pallas" if jax.default_backend() == "tpu" else "xla"
    npsr, n, _ = A.shape
    nb = -(-n // block)
    npad = nb * block - n
    if npad:
        A = jnp.pad(A, ((0, 0), (0, npad), (0, npad)))
        pad_eye = jnp.concatenate(
            [jnp.zeros(n, A.dtype), jnp.ones(npad, A.dtype)]
        )
        A = A + pad_eye[None, :, None] * pad_eye[None, None, :] * jnp.eye(
            nb * block, dtype=A.dtype
        )
    W = A
    out = jnp.zeros_like(W)
    for k in range(nb):
        k0, k1 = k * block, (k + 1) * block
        # graftlint: disable=cov-f32-cholesky  # caller-dtype by design: the blocked kernel runs at whatever precision its consumer chose; every consumer is pinned against the f64 dense oracle (tests/test_covariance.py) and the f32 TPU path rides the bench ladder's tolerance gate
        Lkk = jnp.linalg.cholesky(W[:, k0:k1, k0:k1])
        # numerics observatory: every pivot block's diagonal streams
        # through ONE aggregated probe site, so a trailing update that
        # drives a late pivot indefinite (NaN diagonal) is attributed
        # to the blocked factorization, not its downstream logdet.
        # Identity when disarmed (obs/numerics.py).
        Lkk = numerics.probe_cholesky("cov.blocked_pivot", Lkk)
        out = out.at[:, k0:k1, k0:k1].set(Lkk)
        if k1 < nb * block:
            B = W[:, k1:, k0:k1]
            # panel: B Lkk^-T  ==  solve_triangular(Lkk, B^T)^T
            P = jnp.swapaxes(
                # graftlint: disable=cov-f32-cholesky  # same caller-dtype contract as the diagonal-block factor above (oracle-pinned)
                solve_triangular(
                    Lkk, jnp.swapaxes(B, -1, -2), lower=True
                ),
                -1, -2,
            )
            out = out.at[:, k1:, k0:k1].set(P)
            trail = W[:, k1:, k1:]
            if backend in ("pallas", "pallas_interpret"):
                trail = cov_syrk_update(
                    trail, P, tile=block,
                    interpret=(backend == "pallas_interpret"),
                )
            else:
                trail = _syrk_xla(trail, P, tile=block)
            W = W.at[:, k1:, k1:].set(trail)
    tri = jnp.tril(out)
    return tri[:, :n, :n]


def dense_cholesky(A, block: int = 128, method: str = "auto"):
    """Batched lower Cholesky of (Np, n, n): ``method='xla'`` is
    ``jnp.linalg.cholesky`` (LAPACK on CPU — unbeatable there),
    ``'blocked'`` the MXU-friendly blocked factorization above,
    ``'auto'`` picks by backend."""
    if method == "auto":
        method = "blocked" if jax.default_backend() == "tpu" else "xla"
    if method == "xla":
        # graftlint: disable=cov-f32-cholesky  # caller-dtype dispatcher: precision policy is the consumer's (every consumer is pinned against the f64 dense oracle in tests/test_covariance.py)
        L = jnp.linalg.cholesky(A)
        return numerics.probe_cholesky("cov.dense_cholesky", L)
    return blocked_cholesky(A, block=block)


def cholesky_solve(L, X):
    """Solve ``(L L^T) Z = X`` for (Np, n, n) factor and (Np, n, Q)
    right-hand sides via two batched triangular solves."""
    # graftlint: disable=cov-f32-cholesky  # caller-dtype solve against an oracle-pinned factor (see blocked_cholesky)
    Y = solve_triangular(L, X, lower=True)
    # graftlint: disable=cov-f32-cholesky  # second leg of the same oracle-pinned solve
    return solve_triangular(L, Y, lower=True, trans=1)


# ----------------------------------------------- block-tridiagonal

def _scan_axis(x):
    """(Np, nb, ...) -> (nb, Np, ...) for lax.scan."""
    return jnp.moveaxis(x, 1, 0)


def _unscan_axis(x):
    return jnp.moveaxis(x, 0, 1)


def block_tridiag_cholesky(D, E):
    """Cholesky of a symmetric positive-definite block-tridiagonal
    matrix: ``D`` (Np, nb, b, b) diagonal blocks, ``E`` (Np, nb-1, b,
    b) sub-diagonal blocks (``E[k]`` is the (k+1, k) block). Returns
    ``(Ld, M)``: the (Np, nb, b, b) diagonal Cholesky blocks and the
    (Np, nb, b, b) sub-diagonal factor blocks (``M[0]`` is zero).

    One lax.scan over block columns — each step is a batched (b, b)
    Cholesky, triangular solve, and matmul (MXU work), so the whole
    factorization costs O(Nt b^2) instead of the dense O(Nt^3).
    """
    npsr, nb, b, _ = D.shape
    Epad = jnp.concatenate(
        [jnp.zeros((npsr, 1, b, b), D.dtype), E], axis=1
    )

    def step(prev_L, inputs):
        Dk, Ek = inputs
        # M_k = E_{k-1} L_{k-1}^-T; E_0 = 0 so M_0 = 0 exactly
        M = jnp.swapaxes(
            # graftlint: disable=cov-f32-cholesky  # caller-dtype structured factor; pinned vs the f64 dense oracle (tests/test_covariance.py)
            solve_triangular(prev_L, jnp.swapaxes(Ek, -1, -2),
                             lower=True),
            -1, -2,
        )
        S = Dk - jnp.einsum(
            "pik,pjk->pij", M, M, precision="highest"
        )
        # graftlint: disable=cov-f32-cholesky  # same oracle-pinned caller-dtype contract
        Lk = jnp.linalg.cholesky(S)
        # one aggregated probe site across every scan step: a late
        # block column driven indefinite by accumulated Schur updates
        # shows up here, attributed to the banded factor itself
        Lk = numerics.probe_cholesky("cov.tridiag_pivot", Lk)
        return Lk, (Lk, M)

    init = jnp.tile(jnp.eye(b, dtype=D.dtype), (npsr, 1, 1))
    _, (Ld, M) = jax.lax.scan(
        step, init, (_scan_axis(D), _scan_axis(Epad))
    )
    return _unscan_axis(Ld), _unscan_axis(M)


def block_tridiag_logdet(Ld):
    """log det from the block-tridiagonal factor's diagonal blocks."""
    return 2.0 * jnp.sum(
        jnp.log(jnp.diagonal(Ld, axis1=-2, axis2=-1)), axis=(-2, -1)
    )


def block_tridiag_solve(Ld, M, X):
    """Solve ``(L L^T) Z = X`` for the block-tridiagonal factor of
    :func:`block_tridiag_cholesky`; ``X`` is (Np, nb, b, Q). Forward
    then backward substitution, each one lax.scan of batched (b, b)
    triangular solves."""
    npsr, nb, b, Q = X.shape

    def fwd(y_prev, inputs):
        Lk, Mk, xk = inputs
        rhs = xk - jnp.einsum(
            "pij,pjq->piq", Mk, y_prev, precision="highest"
        )
        # graftlint: disable=cov-f32-cholesky  # caller-dtype structured solve; oracle-pinned (tests/test_covariance.py)
        yk = solve_triangular(Lk, rhs, lower=True)
        return yk, yk

    y0 = jnp.zeros((npsr, b, Q), X.dtype)
    _, Y = jax.lax.scan(
        fwd, y0, (_scan_axis(Ld), _scan_axis(M), _scan_axis(X))
    )

    Mnext = jnp.concatenate(
        [M[:, 1:], jnp.zeros((npsr, 1, b, b), X.dtype)], axis=1
    )

    def bwd(z_next, inputs):
        Lk, Mk1, yk = inputs
        rhs = yk - jnp.einsum(
            "pji,pjq->piq", Mk1, z_next, precision="highest"
        )
        # graftlint: disable=cov-f32-cholesky  # caller-dtype structured solve; oracle-pinned (tests/test_covariance.py)
        zk = solve_triangular(Lk, rhs, lower=True, trans=1)
        return zk, zk

    _, Z = jax.lax.scan(
        bwd, y0,
        (_scan_axis(Ld), _scan_axis(Mnext), Y),
        reverse=True,
    )
    return _unscan_axis(Z)


@functools.lru_cache(maxsize=None)
def _tridiag_mxu_engine(backend: str):
    """The jitted MXU-rung engine for :func:`block_tridiag_factor_solve`
    (``cov.tridiag_mxu`` label, so devprof cost/roofline attribution
    covers the fused tridiagonal kernel)."""
    from ..obs import instrumented_jit, names
    from ..ops import pallas_gp

    if backend == "xla":

        def run(D, E, X):
            return pallas_gp.tridiag_factor_solve_xla(D, E, X)

    else:
        interpret = backend == "pallas_interpret"

        def run(D, E, X):
            return pallas_gp.tridiag_factor_solve(
                D, E, X, interpret=interpret
            )

    return instrumented_jit(
        run, name=names.JIT_COV_TRIDIAG_MXU, retrace_warn=16,
    )


def block_tridiag_factor_solve(D, E, X, backend: str = "auto"):
    """Fused factor + solve of a block-tridiagonal SPD system: one
    pass produces ``(Ld, M, Z)`` — the factor blocks of
    :func:`block_tridiag_cholesky` plus the solution of ``(L L^T) Z =
    X`` — for (Np, nb, b, b) ``D``/(Np, nb-1, b, b) ``E``/(Np, nb, b,
    Q) ``X``.

    Rung 1b of the raw-speed ladder (docs/performance.md): the
    'scan' backend is the composed pair above (bitwise-identical
    reference — LAPACK per-step Cholesky/solves); 'xla' and
    'pallas'/'pallas_interpret' run the MXU-tiled scan body of
    ops/pallas_gp.py, whose per-tile factor/solve is ONE shared
    implementation (interpret-mode bit-identity pinned by
    tests/test_gp_kernels.py). 'auto' = pallas on TPU, the composed
    scan elsewhere — callers that don't opt in never change paths.
    Factor-once/solve-many callers (covariance/structure.py's banded
    solver) keep the composed pair; this entry is for the
    factor+first-solve pattern where the fusion saves a full pass."""
    if backend == "auto":
        backend = "pallas" if jax.default_backend() == "tpu" else "scan"
    if backend == "scan":
        Ld, M = block_tridiag_cholesky(D, E)
        return Ld, M, block_tridiag_solve(Ld, M, X)
    if backend not in ("xla", "pallas", "pallas_interpret"):
        raise ValueError(
            f"unknown block_tridiag backend {backend!r}: expected "
            "'auto', 'scan', 'xla', 'pallas' or 'pallas_interpret'"
        )
    Ld, M, Z = _tridiag_mxu_engine(backend)(D, E, X)
    # same attribution contract as cov.tridiag_pivot: a late block
    # column driven indefinite inside the fused kernel names the MXU
    # rung, not its downstream logdet/solve consumer
    Ld = numerics.probe_cholesky("cov.tridiag_mxu_pivot", Ld)
    Z = numerics.probe("cov.tridiag_mxu_solve", Z)
    return Ld, M, Z


def block_tridiag_matvec(D, E, X):
    """``C X`` for the block-tridiagonal (D, E) representation and
    (Np, nb, b, Q) operands."""
    out = jnp.einsum("pkij,pkjq->pkiq", D, X, precision="highest")
    lower = jnp.einsum(
        "pkij,pkjq->pkiq", E, X[:, :-1], precision="highest"
    )
    upper = jnp.einsum(
        "pkji,pkjq->pkiq", E, X[:, 1:], precision="highest"
    )
    out = out.at[:, 1:].add(lower)
    out = out.at[:, :-1].add(upper)
    return out


def block_tridiag_matmul_factor(Ld, M, Z):
    """``L Z`` for the block-tridiagonal factor — the sampling map
    (``L z`` has covariance ``L L^T``); ``Z`` is (Np, nb, b)."""
    out = jnp.einsum("pkij,pkj->pki", Ld, Z, precision="highest")
    out = out.at[:, 1:].add(
        jnp.einsum("pkij,pkj->pki", M[:, 1:], Z[:, :-1],
                   precision="highest")
    )
    return out


# ------------------------------------------------------- Kronecker

def kron_cholesky(Ct, Cf):
    """Per-factor Cholesky of a Kronecker covariance ``Ct (x) Cf``
    ((Np, ne, ne) epoch-level temporal factor, (Np, nc, nc) channel
    factor): ``chol(Ct (x) Cf) = chol(Ct) (x) chol(Cf)`` under the
    epoch-major (row-major) TOA ordering — the Kronecker product of
    lower-triangular factors is lower triangular, and Cholesky factors
    are unique, so the structured factor IS the dense factor."""
    # Either factor going indefinite breaks the WHOLE Kronecker product,
    # so the probes keep the temporal/channel factors as separate sites.
    # graftlint: disable=cov-f32-cholesky  # caller-dtype structured factor; pinned vs the f64 dense Kronecker oracle (tests/test_covariance.py)
    Lt = numerics.probe_cholesky("cov.kron_epoch", jnp.linalg.cholesky(Ct))
    # graftlint: disable=cov-f32-cholesky  # caller-dtype structured factor; pinned vs the f64 dense Kronecker oracle (tests/test_covariance.py)
    Lf = numerics.probe_cholesky("cov.kron_channel", jnp.linalg.cholesky(Cf))
    return Lt, Lf


def kron_solve(Lt, Lf, X):
    """Solve ``(Ct (x) Cf) Z = X`` from the per-factor Cholesky
    factors: reshape X (Np, ne*nc, Q) to the (ne, nc) grid and apply
    ``Ct^-1`` along epochs and ``Cf^-1`` along channels — O(Nt (ne +
    nc)) per right-hand side instead of the dense O(Nt^2)."""
    npsr, nt, Q = X.shape
    ne = Lt.shape[-1]
    nc = Lf.shape[-1]
    Xg = X.reshape(npsr, ne, nc * Q)
    Y = cholesky_solve(Lt, Xg).reshape(npsr, ne, nc, Q)
    Yc = jnp.moveaxis(Y, 2, 1).reshape(npsr, nc, ne * Q)
    Z = cholesky_solve(Lf, Yc).reshape(npsr, nc, ne, Q)
    return jnp.moveaxis(Z, 2, 1).reshape(npsr, nt, Q)


def kron_logdet(Lt, Lf):
    """log det of ``Ct (x) Cf`` from the factor Cholesky diagonals."""
    ne = Lt.shape[-1]
    nc = Lf.shape[-1]
    return nc * _chol_logdet(Lt) + ne * _chol_logdet(Lf)


def kron_sample_map(Lt, Lf, Z):
    """``(Lt (x) Lf) z`` for a (Np, ne, nc) standard-normal grid: the
    sampling map ``Lt Z Lf^T`` (epoch-major vec convention)."""
    Y = jnp.einsum("pij,pjc->pic", Lt, Z, precision="highest")
    return jnp.einsum("pic,pkc->pik", Y, Lf, precision="highest")


# --------------------------------------------- eager telemetry shims

#: running tallies behind the cov.blocked_fraction gauge: structured
#: (banded/Kronecker/blocked) solves vs every solve the eager helpers
#: priced. Only the eager, host-driven entry points below count — the
#: jit-traced solver inside the likelihood prices once per compile.
_SOLVE_TALLY = {"total": 0, "structured": 0}


def solve_eager(op, x, s2=None):
    """Eagerly solve ``C z = x`` through a CovOp, under the
    ``cov_solve`` span with the ``cov.{solves,blocked_fraction}``
    telemetry — the instrumented entry the bench ladder, oracle
    harnesses, and CLI paths share (inside jit, call ``op.solve``
    directly; spans and counters cannot live under a trace)."""
    from ..obs import counter, gauge, names, span

    structured = type(op).__name__ != "DenseCov"
    with span(names.SPAN_COV_SOLVE, kind=type(op).__name__,
              structured=structured):
        out = op.solve(x, s2=s2)
        out = jax.block_until_ready(out)
    counter(names.COV_SOLVES).inc()
    _SOLVE_TALLY["total"] += 1
    _SOLVE_TALLY["structured"] += int(structured)
    gauge(names.COV_BLOCKED_FRACTION).set(
        _SOLVE_TALLY["structured"] / _SOLVE_TALLY["total"]
    )
    return out


def sample_eager(op, key, s2=None, rows=None):
    """Eagerly draw one correlated-noise realization through a CovOp,
    under the ``cov_sample`` span — the fuzz harness's batched-side
    entry (the production injection samples inside the jitted engine
    and is span-free by design)."""
    from ..obs import names, span

    with span(names.SPAN_COV_SAMPLE, kind=type(op).__name__):
        return jax.block_until_ready(op.sample(key, s2=s2, rows=rows))


@functools.lru_cache(maxsize=None)
def _dense_solve_engine(method: str, block: int):
    """Jitted dense factor+solve engine, instrumented for devprof
    roofline accounting (the bench ladder's dense arm)."""
    from ..obs import instrumented_jit, names

    def run(A, X):
        L = dense_cholesky(A, block=block, method=method)
        return cholesky_solve(L, X), _chol_logdet(L)

    return instrumented_jit(
        run, name=names.JIT_COV_CHOLESKY, static_argnums=(),
    )


def dense_solve(A, X, method: str = "auto", block: int = 128):
    """Factor + solve a batched dense SPD system through the cached
    ``instrumented_jit`` engine (``cov.blocked_cholesky`` label, so
    ``devprof`` cost/roofline accounting applies). Returns ``(Z,
    logdet)``."""
    return _dense_solve_engine(method, block)(A, X)
