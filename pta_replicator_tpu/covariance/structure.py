"""Structured beyond-diagonal noise-covariance representations.

Every production path in the repo priced noise as the standard
diagonal-white + rank-reduced model — exactly the approximation
arXiv:2506.13866 shows biases PTA analyses once inter-epoch
correlations (solar wind, chromatic DM structure, band noise) matter,
and which the Gaussian-process formulation of arXiv:1407.1838
generalizes. This module is the missing pillar: covariance *structures*
with a common :class:`CovOp` interface —

* ``matvec(x, s2)``   — apply ``s2 * C``
* ``solve(x, s2)``    — apply ``(s2 * C)^-1``
* ``logdet(s2)``      — masked ``log det (s2 * C)`` over valid TOAs
* ``sample(key, s2)`` — one ``N(0, s2 * C)`` draw (Np, Nt)
* ``dense()``         — the numpy-float64 dense oracle every structured
  path is pinned against (tests/test_covariance.py, <= 1e-8 relative)

and four concrete structures:

=====================  ==============================================
:class:`DenseCov`      dense per-pulsar (n, n) — the reference
                       structure and the thing the ladder must beat
:class:`BandedCov`     block-tridiagonal inter-epoch correlation
                       (compact-support Wendland taper, diagonally-
                       dominant by construction): O(Nt b^2) solves
:class:`KroneckerCov`  time (x) frequency-channel chromatic structure
                       (squared-exponential epochs (x) AR(1) channels,
                       the solar-wind shape): O(ne^3 + nc^3) solves
:class:`LowRankCov`    low-rank-plus-structured (Woodbury over any
                       base CovOp)
=====================  ==============================================

Ops are registered pytrees, so a CovOp rides inside a
:class:`~pta_replicator_tpu.models.batched.Recipe` through jit/vmap/
sharding like any other leaf. Builders run on the HOST in float64 at
compile/recipe-build time (the scenario compiler's eager frontier,
same posture as the CW plane fold) and store both the structure AND
its Cholesky factor as leaves — so the per-realization sampling map
inside the jitted engine is a cheap structured matmul, never a
factorization, and the factor is f64-exact regardless of the device
dtype.

Amplitude discipline: ops are built UNIT-NORMALIZED (unit diagonal at
valid TOAs) and scaled at evaluation time by ``s2 = 10^(2
cov_log10_sigma)`` from the Recipe leaf — which keeps the covariance
amplitude a flat, named, fittable hyperparameter (``map_fit`` recovers
it; the round-trip gate in benchmarks/cov_solve.py).

Padding convention: stored structure blocks are ZERO on padding
rows/cols (pure signal part); factors are of the structure plus
identity at padding — decoupled unit rows that price ``log 1 = 0`` and
solve to ``x``. ``nvalid`` (valid-TOA counts) makes the ``s2`` scaling
of ``logdet`` exact under masking.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from . import kernels as K
from ..obs import numerics

#: fold_in index of the correlated-noise draw on the per-realization
#: key (models/batched.py realization_delays): the cov family draws
#: from ``fold_in(key, COV_STREAM_FOLD)``, NOT from a widened split —
#: so enabling it leaves every existing family's stream bit-identical
#: (the same append-only discipline as scenarios' FAMILY_IDS).
COV_STREAM_FOLD = 12


def _as_np64(x):
    return np.asarray(x, np.float64)


def _wendland(r):
    """Compact-support Wendland-C2 taper: (1-r)^4 (4r+1) for r < 1,
    exactly 0 beyond — positive definite in up to three dimensions, so
    the tapered kernel is a genuine covariance with a hard bandwidth."""
    rc = np.clip(r, 0.0, 1.0)
    return np.where(r < 1.0, (1.0 - rc) ** 4 * (4.0 * rc + 1.0), 0.0)


def _np_block_tridiag_cholesky(D, E, valid_blocks):
    """Host float64 block-tridiagonal Cholesky of the UNIT op (D + the
    padding identity): the build-time twin of
    kernels.block_tridiag_cholesky, run once per construction so the
    jitted sampler never factors anything."""
    npsr, nb, b, _ = D.shape
    Ld = np.zeros_like(D)
    M = np.zeros_like(D)
    eye = np.eye(b)
    prev = None
    for k in range(nb):
        # identity at padding rows: decoupled, log det 0, solve to x
        S = D[:, k] + np.einsum(
            "ij,pj->pij", eye, 1.0 - valid_blocks[:, k]
        )
        if k:
            Mk = np.swapaxes(
                np.linalg.solve(prev, np.swapaxes(E[:, k - 1], -1, -2)),
                -1, -2,
            )
            M[:, k] = Mk
            S = S - Mk @ np.swapaxes(Mk, -1, -2)
        # graftlint: disable=cov-f32-cholesky  # host build-time factor, explicitly float64 end to end (builders upcast via _as_np64)
        Ld[:, k] = np.linalg.cholesky(S)
        prev = Ld[:, k]
    return Ld, M


def _s2_arr(s2, dtype):
    """Normalize an s2 operand: None -> 1.0 scalar, else dtype array
    (scalar or per-pulsar (Np,))."""
    if s2 is None:
        return jnp.asarray(1.0, dtype)
    return jnp.asarray(s2, dtype)


def _bcol(s2, extra_dims: int):
    """Broadcast a scalar-or-(Np,) s2 against (Np, ...) operands."""
    if s2.ndim == 0:
        return s2
    return s2.reshape(s2.shape + (1,) * extra_dims)


def _draw_rows(key, npsr, nt, dtype, rows):
    """The z draw behind every sample: ``normal(key, (Np, Nt))``, or an
    exact row window of the global (npsr_global, Nt) stream under a
    pulsar-sharded shard_map (same discipline as models.batched's
    ``_rows_draw``)."""
    if rows is None:
        return jax.random.normal(key, (npsr, nt), dtype)
    npsr_global, row_start = rows
    full = jax.random.normal(key, (npsr_global, nt), dtype)
    return jax.lax.dynamic_slice_in_dim(full, row_start, npsr, 0)


class CovOp:
    """Interface mixin: concrete structures implement the five-method
    contract documented in the module docstring. (Duck-typed on
    purpose — Recipe validation checks for ``sample``, so a foreign
    structure with the same contract plugs in.)"""

    def matvec(self, x, s2=None):
        raise NotImplementedError

    def solve(self, x, s2=None):
        raise NotImplementedError

    def logdet(self, s2=None):
        raise NotImplementedError

    def sample(self, key, s2=None, rows=None):
        raise NotImplementedError

    def dense(self, pad_identity: bool = True) -> np.ndarray:
        raise NotImplementedError


def _solve_2d(solve3, x, s2):
    """Lift a (Np, Nt, Q) solver over (Np, Nt) vectors too."""
    if x.ndim == 2:
        return solve3(x[..., None], s2)[..., 0]
    return solve3(x, s2)


# ------------------------------------------------------------- dense

@jax.tree_util.register_dataclass
@dataclass
class DenseCov(CovOp):
    """Dense per-pulsar covariance: ``mat`` (Np, n, n) pure signal part
    (zero padding rows), ``L`` its host-f64 Cholesky factor (with
    identity at padding), ``valid`` (Np, n) 1/0 mask, ``nvalid`` (Np,)
    valid counts. The reference structure of the ladder — and the
    fallback every other structure's combined-solver path can
    dense-materialize into."""

    mat: jax.Array
    L: jax.Array
    valid: jax.Array
    nvalid: jax.Array

    @classmethod
    def from_dense(cls, mat, mask=None, dtype=None):
        """Wrap an explicit (Np, n, n) SPD matrix (f64 host factor)."""
        m = _as_np64(mat)
        npsr, n, _ = m.shape
        valid = (np.ones((npsr, n)) if mask is None
                 else (_as_np64(mask) > 0).astype(np.float64))
        m = m * valid[:, :, None] * valid[:, None, :]
        pad = np.einsum("ij,pj->pij", np.eye(n), 1.0 - valid)
        # graftlint: disable=cov-f32-cholesky  # host build-time factor, explicitly float64 (_as_np64 above)
        L = np.linalg.cholesky(m + pad)
        if dtype is None:
            dtype = jnp.zeros(0).dtype
        return cls(
            mat=jnp.asarray(m, dtype), L=jnp.asarray(L, dtype),
            valid=jnp.asarray(valid, dtype),
            nvalid=jnp.asarray(valid.sum(axis=-1), dtype),
        )

    def matvec(self, x, s2=None):
        s2 = _s2_arr(s2, x.dtype)
        out = jnp.einsum("pij,pj...->pi...", self.mat, x,
                         precision="highest")
        return out * _bcol(s2, out.ndim - 1)

    def solve(self, x, s2=None):
        s2 = _s2_arr(s2, x.dtype)

        def s3(xx, s2):
            z = K.cholesky_solve(self.L, xx)
            return z / _bcol(s2, 2)

        return _solve_2d(s3, jnp.asarray(x), s2)

    def logdet(self, s2=None):
        s2 = _s2_arr(s2, self.L.dtype)
        return K._chol_logdet(self.L) + self.nvalid * jnp.log(s2)

    def sample(self, key, s2=None, rows=None):
        npsr, n = self.valid.shape
        z = _draw_rows(key, npsr, n, self.L.dtype, rows)
        s2 = _s2_arr(s2, self.L.dtype)
        out = jnp.einsum("pij,pj->pi", self.L, z, precision="highest")
        return out * self.valid * _bcol(jnp.sqrt(s2), 1)

    def dense(self, pad_identity: bool = True) -> np.ndarray:
        m = _as_np64(self.mat)
        if pad_identity:
            v = _as_np64(self.valid)
            m = m + np.einsum("ij,pj->pij", np.eye(m.shape[-1]), 1.0 - v)
        return m

    def dense_device(self, dtype):
        return jnp.asarray(self.mat, dtype)


def dense_from_times(toas_s, mask, corr_s, nugget: float = 0.05,
                     dtype=None) -> DenseCov:
    """Unit-diagonal squared-exponential temporal covariance over the
    full TOA set (no truncation): ``C = (K_SE(dt; corr_s) + nugget I) /
    (1 + nugget)`` — SPD for any geometry. The dense member of the
    scenario family and the ladder's reference arm."""
    t = _as_np64(toas_s)
    dt = t[:, :, None] - t[:, None, :]
    Kse = np.exp(-0.5 * (dt / float(corr_s)) ** 2)
    n = t.shape[1]
    C = (Kse + float(nugget) * np.eye(n)[None]) / (1.0 + float(nugget))
    return DenseCov.from_dense(C, mask=mask, dtype=dtype)


# ------------------------------------------------------------ banded

@jax.tree_util.register_dataclass
@dataclass
class BandedCov(CovOp):
    """Block-tridiagonal inter-epoch correlation: ``D`` (Np, nb, b, b)
    diagonal blocks / ``E`` (Np, nb-1, b, b) sub-diagonal blocks of
    the pure signal part (unit diagonal at valid TOAs, zero padding),
    ``Ld``/``M`` the host-f64 factor of the unit op, ``valid`` (Np,
    nb*b) the padded-grid mask, ``nvalid`` valid counts. ``nt`` is the
    un-padded TOA count (static: a shape)."""

    D: jax.Array
    E: jax.Array
    Ld: jax.Array
    M: jax.Array
    valid: jax.Array
    nvalid: jax.Array
    nt: int = field(metadata=dict(static=True), default=0)

    @property
    def block(self) -> int:
        return int(self.D.shape[-1])

    def _grid(self, x):
        """(Np, Nt, Q) -> zero-padded (Np, nb, b, Q)."""
        npsr, nt, Q = x.shape
        ntp = self.valid.shape[1]
        if ntp != nt:
            x = jnp.pad(x, ((0, 0), (0, ntp - nt), (0, 0)))
        return x.reshape(npsr, -1, self.block, Q)

    def _ungrid(self, xg):
        npsr = xg.shape[0]
        return xg.reshape(npsr, -1, xg.shape[-1])[:, : self.nt]

    def matvec(self, x, s2=None):
        s2 = _s2_arr(s2, x.dtype)

        def s3(xx, s2):
            out = self._ungrid(
                K.block_tridiag_matvec(self.D, self.E, self._grid(xx))
            )
            return out * _bcol(s2, 2)

        return _solve_2d(s3, jnp.asarray(x), s2)

    def solve(self, x, s2=None):
        s2 = _s2_arr(s2, x.dtype)

        def s3(xx, s2):
            z = self._ungrid(
                K.block_tridiag_solve(self.Ld, self.M, self._grid(xx))
            )
            return z / _bcol(s2, 2)

        return _solve_2d(s3, jnp.asarray(x), s2)

    def logdet(self, s2=None):
        s2 = _s2_arr(s2, self.Ld.dtype)
        return K.block_tridiag_logdet(self.Ld) + self.nvalid * jnp.log(s2)

    def sample(self, key, s2=None, rows=None):
        npsr = self.valid.shape[0]
        z = _draw_rows(key, npsr, self.nt, self.Ld.dtype, rows)
        zg = self._grid(z[..., None])[..., 0]
        s = K.block_tridiag_matmul_factor(self.Ld, self.M, zg)
        s = s.reshape(npsr, -1)[:, : self.nt]
        s2 = _s2_arr(s2, self.Ld.dtype)
        return s * self.valid[:, : self.nt] * _bcol(jnp.sqrt(s2), 1)

    def dense(self, pad_identity: bool = True) -> np.ndarray:
        D = _as_np64(self.D)
        E = _as_np64(self.E)
        npsr, nb, b, _ = D.shape
        ntp = nb * b
        C = np.zeros((npsr, ntp, ntp))
        for k in range(nb):
            k0 = k * b
            C[:, k0:k0 + b, k0:k0 + b] = D[:, k]
            if k:
                C[:, k0:k0 + b, k0 - b:k0] = E[:, k - 1]
                C[:, k0 - b:k0, k0:k0 + b] = np.swapaxes(
                    E[:, k - 1], -1, -2
                )
        C = C[:, : self.nt, : self.nt]
        if pad_identity:
            v = _as_np64(self.valid)[:, : self.nt]
            C = C + np.einsum("ij,pj->pij", np.eye(self.nt), 1.0 - v)
        return C

    def dense_device(self, dtype):
        """Traceable dense materialization of the pure part (the
        combined solver's fallback when ECORR shares the covariance)."""
        npsr, nb, b, _ = self.D.shape
        ntp = nb * b
        C = jnp.zeros((npsr, ntp, ntp), dtype)
        for k in range(nb):
            k0 = k * b
            C = C.at[:, k0:k0 + b, k0:k0 + b].set(
                jnp.asarray(self.D[:, k], dtype)
            )
            if k:
                Ek = jnp.asarray(self.E[:, k - 1], dtype)
                C = C.at[:, k0:k0 + b, k0 - b:k0].set(Ek)
                C = C.at[:, k0 - b:k0, k0:k0 + b].set(
                    jnp.swapaxes(Ek, -1, -2)
                )
        return C[:, : self.nt, : self.nt]


def banded_from_times(toas_s, mask, rho, corr_s, block: int = 32,
                      dtype=None) -> BandedCov:
    """Unit-diagonal block-tridiagonal inter-epoch correlation from
    concrete TOA times (host float64, compile-time):

    ``R = I + (rho / max_row_mass) * W_tridiag(dt; corr_s)``

    with ``W`` the compact-support Wendland taper restricted to the
    block-tridiagonal sparsity and the coupling normalized by the
    largest off-diagonal row mass — so ``rho < 1`` makes ``R`` strictly
    diagonally dominant, hence SPD, for ANY cadence (the model is
    defined by this construction; the taper's hard support is what the
    banded solver's O(Nt b^2) cost stands on)."""
    t = _as_np64(toas_s)
    m = (_as_np64(mask) > 0).astype(np.float64)
    npsr, nt = t.shape
    nb = -(-nt // block)
    ntp = nb * block
    tpad = np.pad(t, ((0, 0), (0, ntp - nt)))
    vpad = np.pad(m, ((0, 0), (0, ntp - nt)))
    tg = tpad.reshape(npsr, nb, block)
    vg = vpad.reshape(npsr, nb, block)

    r = float(corr_s)
    dt_d = np.abs(tg[:, :, :, None] - tg[:, :, None, :]) / r
    Wd = _wendland(dt_d) * (vg[:, :, :, None] * vg[:, :, None, :])
    eye = np.eye(block)[None, None]
    Wd = Wd * (1.0 - eye)  # zero diagonal: W is pure coupling
    dt_o = np.abs(tg[:, 1:, :, None] - tg[:, :-1, None, :]) / r
    Wo = _wendland(dt_o) * (vg[:, 1:, :, None] * vg[:, :-1, None, :])

    # off-diagonal row mass: within-block + both adjacent-block sides
    rows = Wd.sum(axis=-1)
    rows[:, 1:] += Wo.sum(axis=-1)
    rows[:, :-1] += Wo.sum(axis=-2)
    denom = np.maximum(rows.reshape(npsr, -1).max(axis=-1), 1e-12)
    rho_arr = np.broadcast_to(_as_np64(rho), (npsr,))
    coup = (rho_arr / denom)[:, None, None, None]

    D = coup * Wd + np.einsum("ij,pkj->pkij", np.eye(block), vg)
    E = coup * Wo
    Ld, M = _np_block_tridiag_cholesky(D, E, vg)
    if dtype is None:
        dtype = jnp.zeros(0).dtype
    return BandedCov(
        D=jnp.asarray(D, dtype), E=jnp.asarray(E, dtype),
        Ld=jnp.asarray(Ld, dtype), M=jnp.asarray(M, dtype),
        valid=jnp.asarray(vpad, dtype),
        nvalid=jnp.asarray(m.sum(axis=-1), dtype), nt=nt,
    )


# --------------------------------------------------------- Kronecker

@jax.tree_util.register_dataclass
@dataclass
class KroneckerCov(CovOp):
    """Time (x) frequency-channel Kronecker covariance ``Ct (x) Cf``
    over an epoch-major (ne, nc) TOA grid: ``Ct`` (Np, ne, ne) epoch-
    level temporal factor, ``Cf`` (Np, nc, nc) channel factor, with
    their host-f64 Cholesky factors. Requires a FULL grid (every TOA
    valid, ``Nt = ne * nc`` in time order) — the scenario compiler
    enforces this at validate time. The chromatic solar-wind shape:
    correlation across epochs (x) correlation across the observing
    band."""

    Ct: jax.Array
    Cf: jax.Array
    Lt: jax.Array
    Lf: jax.Array
    nvalid: jax.Array

    @property
    def shape_grid(self):
        return int(self.Ct.shape[-1]), int(self.Cf.shape[-1])

    def matvec(self, x, s2=None):
        ne, nc = self.shape_grid
        s2 = _s2_arr(s2, x.dtype)

        def s3(xx, s2):
            npsr, nt, Q = xx.shape
            Xg = xx.reshape(npsr, ne, nc, Q)
            Y = jnp.einsum("pij,pjcq->picq", self.Ct, Xg,
                           precision="highest")
            out = jnp.einsum("pcd,pidq->picq", self.Cf, Y,
                             precision="highest")
            return out.reshape(npsr, nt, Q) * _bcol(s2, 2)

        return _solve_2d(s3, jnp.asarray(x), s2)

    def solve(self, x, s2=None):
        s2 = _s2_arr(s2, x.dtype)

        def s3(xx, s2):
            z = K.kron_solve(self.Lt, self.Lf, xx)
            return z / _bcol(s2, 2)

        return _solve_2d(s3, jnp.asarray(x), s2)

    def logdet(self, s2=None):
        s2 = _s2_arr(s2, self.Lt.dtype)
        return K.kron_logdet(self.Lt, self.Lf) + self.nvalid * jnp.log(s2)

    def sample(self, key, s2=None, rows=None):
        ne, nc = self.shape_grid
        npsr = self.Ct.shape[0]
        z = _draw_rows(key, npsr, ne * nc, self.Lt.dtype, rows)
        s = K.kron_sample_map(self.Lt, self.Lf, z.reshape(npsr, ne, nc))
        s2 = _s2_arr(s2, self.Lt.dtype)
        return s.reshape(npsr, ne * nc) * _bcol(jnp.sqrt(s2), 1)

    def dense(self, pad_identity: bool = True) -> np.ndarray:
        Ct = _as_np64(self.Ct)
        Cf = _as_np64(self.Cf)
        return np.stack(
            [np.kron(Ct[p], Cf[p]) for p in range(Ct.shape[0])]
        )

    def dense_device(self, dtype):
        ne, nc = self.shape_grid
        C = jnp.einsum(
            "pij,pcd->picjd", jnp.asarray(self.Ct, dtype),
            jnp.asarray(self.Cf, dtype), precision="highest",
        )
        npsr = C.shape[0]
        return C.reshape(npsr, ne * nc, ne * nc)


def kron_time_channel(toas_s, channels: int, time_ell_s, chan_rho,
                      nugget: float = 0.05, dtype=None,
                      mask=None) -> KroneckerCov:
    """Kronecker time (x) channel covariance from concrete TOA times:
    consecutive groups of ``channels`` TOAs form one epoch (Nt must
    divide evenly — validated upstream); the temporal factor is a
    unit-diagonal squared-exponential kernel over epoch mean times
    (+ nugget), the channel factor an AR(1) correlation
    ``chan_rho^|a-b|`` (SPD for |rho| < 1).

    The Kronecker structure has NO padding-identity escape hatch —
    every TOA is a live grid cell. Pass ``mask`` to have the builder
    enforce that (a masked TOA would otherwise stay cross-coupled in
    the priced C0 while the injection zeroes it, silently biasing the
    likelihood against its oracle)."""
    if mask is not None and not np.all(_as_np64(mask) > 0):
        raise ValueError(
            "KroneckerCov needs a FULL TOA grid (every TOA valid): the "
            "time (x) channel structure cannot decouple masked TOAs; "
            "use BandedCov/DenseCov for ragged batches"
        )
    t = _as_np64(toas_s)
    npsr, nt = t.shape
    nc = int(channels)
    if nt % nc:
        raise ValueError(
            f"Kronecker grid needs ntoa ({nt}) divisible by channels "
            f"({nc})"
        )
    ne = nt // nc
    tg = t.reshape(npsr, ne, nc).mean(axis=-1)
    dt = tg[:, :, None] - tg[:, None, :]
    Ct = np.exp(-0.5 * (dt / float(time_ell_s)) ** 2)
    Ct = (Ct + float(nugget) * np.eye(ne)[None]) / (1.0 + float(nugget))
    rho_arr = np.broadcast_to(_as_np64(chan_rho), (npsr,))
    ab = np.abs(np.arange(nc)[:, None] - np.arange(nc)[None, :])
    Cf = rho_arr[:, None, None] ** ab[None]
    # graftlint: disable=cov-f32-cholesky  # host build-time factors, explicitly float64 (_as_np64 above)
    Lt = np.linalg.cholesky(Ct)
    # graftlint: disable=cov-f32-cholesky  # host build-time factors, explicitly float64 (_as_np64 above)
    Lf = np.linalg.cholesky(Cf)
    if dtype is None:
        dtype = jnp.zeros(0).dtype
    return KroneckerCov(
        Ct=jnp.asarray(Ct, dtype), Cf=jnp.asarray(Cf, dtype),
        Lt=jnp.asarray(Lt, dtype), Lf=jnp.asarray(Lf, dtype),
        nvalid=jnp.asarray(np.full(npsr, float(nt)), dtype),
    )


# ------------------------------------------------- low-rank + base

@jax.tree_util.register_dataclass
@dataclass
class LowRankCov(CovOp):
    """Low-rank-plus-structured: ``C = base + U diag(phi) U^T`` over
    any base CovOp, solved by the Woodbury identity through the base's
    own structured solve (an (R, R) Cholesky on top — the same shape
    as the GP likelihood's rank-reduced block)."""

    base: CovOp
    U: jax.Array
    phi: jax.Array

    @property
    def nvalid(self):
        return self.base.nvalid

    def matvec(self, x, s2=None):
        s2 = _s2_arr(s2, self.U.dtype)

        def s3(xx, s2):
            inner = jnp.einsum("pnr,pnq->prq", self.U, xx,
                               precision="highest")
            lowr = jnp.einsum(
                "pnr,prq->pnq", self.U, inner * self.phi[:, :, None],
                precision="highest",
            )
            return self.base.matvec(xx, s2=s2) + lowr * _bcol(s2, 2)

        return _solve_2d(s3, jnp.asarray(x), s2)

    def _woodbury(self):
        G = self.base.solve(self.U)  # base^-1 U, (Np, Nt, R)
        S = jnp.einsum("pnr,pns->prs", self.U, G, precision="highest")
        R = self.U.shape[-1]
        S = S + jnp.eye(R, dtype=self.U.dtype) / self.phi[:, None, :]
        # graftlint: disable=cov-f32-cholesky  # caller-dtype Woodbury core; pinned vs the f64 dense oracle (tests/test_covariance.py)
        L = jnp.linalg.cholesky(S)
        # The (R, R) Woodbury core inherits the conditioning of phi:
        # a tiny prior variance makes I/phi dominate and S near-singular
        # at f32, so a NaN here names this site instead of surfacing as
        # a silent NaN solve downstream.
        L = numerics.probe_cholesky("cov.lowrank_woodbury", L)
        return G, L

    def solve(self, x, s2=None):
        s2 = _s2_arr(s2, self.U.dtype)

        def s3(xx, s2):
            G, L = self._woodbury()
            y = self.base.solve(xx)
            inner = jnp.einsum("pnr,pnq->prq", self.U, y,
                               precision="highest")
            from jax.scipy.linalg import cho_solve

            corr = cho_solve((L, True), inner)
            z = y - jnp.einsum("pnr,prq->pnq", G, corr,
                               precision="highest")
            return z / _bcol(s2, 2)

        return _solve_2d(s3, jnp.asarray(x), s2)

    def logdet(self, s2=None):
        _G, L = self._woodbury()
        s2 = _s2_arr(s2, self.U.dtype)
        return (
            self.base.logdet()
            + K._chol_logdet(L)
            + jnp.sum(jnp.log(self.phi), axis=-1)
            + self.nvalid * jnp.log(s2)
        )

    def sample(self, key, s2=None, rows=None):
        k_base, k_lr = jax.random.split(key, 2)
        base_s = self.base.sample(k_base, rows=rows)
        npsr, R = self.phi.shape
        nglobal, start = (npsr, 0) if rows is None else rows
        z = jax.lax.dynamic_slice_in_dim(
            jax.random.normal(k_lr, (nglobal, R), self.U.dtype),
            start, npsr, 0,
        )
        lr = jnp.einsum(
            "pnr,pr->pn", self.U, jnp.sqrt(self.phi) * z,
            precision="highest",
        )
        s2 = _s2_arr(s2, self.U.dtype)
        return (base_s + lr) * _bcol(jnp.sqrt(s2), 1)

    def dense(self, pad_identity: bool = True) -> np.ndarray:
        U = _as_np64(self.U)
        phi = _as_np64(self.phi)
        return self.base.dense(pad_identity=pad_identity) + np.einsum(
            "pnr,pr,pmr->pnm", U, phi, U
        )

    def dense_device(self, dtype):
        U = jnp.asarray(self.U, dtype)
        return self.base.dense_device(dtype) + jnp.einsum(
            "pnr,pr,pmr->pnm", U, jnp.asarray(self.phi, dtype), U,
            precision="highest",
        )


# ------------------------------------------- recipe-facing helpers

def recipe_cov_s2(recipe, dtype=None):
    """The evaluation-time amplitude of a recipe's correlated-noise
    block: ``10^(2 cov_log10_sigma)``, or None when the recipe carries
    no amplitude leaf (the op's built-in unit scale applies)."""
    ls = getattr(recipe, "cov_log10_sigma", None)
    if ls is None:
        return None
    ls = jnp.asarray(ls) if dtype is None else jnp.asarray(ls, dtype)
    return 10.0 ** (2.0 * ls)


def banded_combined_solver(op: BandedCov, safe_sigma2, s2, dtype):
    """Structured solver for ``C0 = diag(sigma2) + s2 * R_banded``: the
    white diagonal folds into the block-tridiagonal diagonal blocks, so
    the combined factor stays O(Nt b^2) — the covariance-aware GLS/
    likelihood hot path for the banded family. Padding rows (both
    masked TOAs, whose safe sigma2 is 1, and the block-grid tail) stay
    exact identity. Returns ``(c0inv_mat, logdet)`` with the same
    contract as ``white_ecorr_solver``'s closure."""
    npsr, nb, b, _ = op.D.shape
    ntp = nb * b
    sig = jnp.asarray(safe_sigma2, dtype)
    sig = jnp.pad(sig, ((0, 0), (0, ntp - sig.shape[1])),
                  constant_values=1.0)
    s2v = _s2_arr(s2, dtype)
    sc = _bcol(s2v, 3)
    D = jnp.asarray(op.D, dtype) * sc + jnp.einsum(
        "ij,pkj->pkij", jnp.eye(b, dtype=dtype),
        sig.reshape(npsr, nb, b),
    )
    E = jnp.asarray(op.E, dtype) * sc
    Ld, M = K.block_tridiag_cholesky(D, E)
    logdet = K.block_tridiag_logdet(Ld)

    def c0inv_mat(X):
        npsr_, nt, Q = X.shape
        Xp = jnp.pad(X, ((0, 0), (0, ntp - nt), (0, 0)))
        Z = K.block_tridiag_solve(
            Ld, M, Xp.reshape(npsr_, nb, b, Q)
        )
        return Z.reshape(npsr_, ntp, Q)[:, :nt]

    return c0inv_mat, logdet


def dense_combined_solver(batch, safe_sigma2, ecorr2, extra, s2, dtype):
    """Dense fallback for ``C0 = diag(sigma2) + U_ec diag(ecorr2)
    U_ec^T + s2 * X`` with ANY structured extra: materialize, factor
    with the blocked-Cholesky dispatcher, solve by triangular
    substitution. O(Nt^3) per pulsar — correct for every structure/
    ECORR combination; the banded path above and the pure-structure
    ladders are the fast lanes (docs/covariance.md)."""
    npsr, nt = safe_sigma2.shape
    C = jnp.einsum(
        "ij,pj->pij", jnp.eye(nt, dtype=dtype),
        jnp.asarray(safe_sigma2, dtype),
    )
    if extra is not None:
        s2v = _s2_arr(s2, dtype)
        C = C + extra.dense_device(dtype) * _bcol(s2v, 2)
    if ecorr2 is not None:
        onehot = (
            batch.epoch_index[..., None]
            == jnp.arange(ecorr2.shape[1])[None, None, :]
        ).astype(dtype) * batch.mask[..., None]
        C = C + jnp.einsum(
            "pne,pe,pme->pnm", onehot, jnp.asarray(ecorr2, dtype),
            onehot, precision="highest",
        )
    L = K.dense_cholesky(C)
    logdet = K._chol_logdet(L)

    def c0inv_mat(X):
        return K.cholesky_solve(L, X)

    return c0inv_mat, logdet


def dense_noise_covariance(batch, recipe) -> np.ndarray:
    """The ONE dense (Np, Nt, Nt) float64 oracle assembly of a recipe's
    full noise covariance — white diagonal, analytic ECORR block,
    rank-reduced GP blocks, and the structured correlated-noise block:

        C = N + U_ec diag(ecorr2) U_ec^T + U diag(phi) U^T + s2 X

    built from the SAME ``gls_noise_model`` components (and the same
    CovOp) the device engines consume, so the oracle and the engine can
    never disagree about what C is. Padding rows are zero (pure signal
    part); consumers slice their valid TOAs
    (``likelihood.gp.dense_loglikelihood``) or add their own identity.
    Host numpy, tests/benches only."""
    from ..models.batched import gls_noise_model

    sigma2, ecorr2, U, phi = gls_noise_model(batch, recipe)
    sigma2 = _as_np64(sigma2)
    npsr, nt = sigma2.shape
    C = np.einsum("ij,pj->pij", np.eye(nt), sigma2)
    if ecorr2 is not None:
        ecorr2 = _as_np64(ecorr2)
        epoch_index = np.asarray(batch.epoch_index)
        mask = _as_np64(batch.mask)
        onehot = (
            epoch_index[..., None] == np.arange(ecorr2.shape[1])
        ).astype(np.float64) * mask[..., None]
        C = C + np.einsum("pne,pe,pme->pnm", onehot, ecorr2, onehot)
    if U is not None:
        U = _as_np64(U)
        phi = _as_np64(phi)
        C = C + np.einsum("pnr,pr,pmr->pnm", U, phi, U)
    extra = getattr(recipe, "noise_cov", None)
    if extra is not None:
        s2 = recipe_cov_s2(recipe)
        s2 = 1.0 if s2 is None else _as_np64(s2)
        Xd = extra.dense(pad_identity=False)
        C = C + Xd * np.reshape(
            np.broadcast_to(s2, (npsr,)), (npsr, 1, 1)
        )
    return C
