"""Fault injection + supervised recovery (docs/robustness.md).

Two halves, deliberately in one package so the machinery and the thing
that exercises it can never drift apart:

* :mod:`.inject` — deterministic, seedable fault injection at named
  sites inside the existing stage spans (zero overhead disarmed;
  armed by schedule string, env var, or CLI flag).
* :mod:`.retry` — the ONE transient-vs-fatal classifier and
  exponential-backoff policy shared by the sweep's chunk-retry
  supervision (utils/sweep.py), the prefetch staging retry
  (parallel/prefetch.py), the serving engine retry
  (likelihood/serve.py), and bench.py's tunnel ladder.

stdlib-only and jax-free end to end.
"""
from . import inject, retry
from .inject import InjectedFault, arm, arm_from_env, armed, disarm, fire
from .retry import (
    DEFAULT_POLICY,
    TUNNEL_POLICY,
    RetryPolicy,
    backoff_delay,
    is_transient,
    retry_call,
)

__all__ = [
    "inject", "retry", "InjectedFault", "arm", "arm_from_env", "armed",
    "disarm", "fire", "RetryPolicy", "DEFAULT_POLICY", "TUNNEL_POLICY",
    "backoff_delay", "is_transient", "retry_call",
]
