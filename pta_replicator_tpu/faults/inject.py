"""Deterministic, seedable fault injection at named sites.

The production paths this repo ships — the pipelined/mesh sweep
(parallel/pipeline.py + utils/sweep.py), the host->device prefetch
stream (parallel/prefetch.py), and the likelihood serving loop
(likelihood/serve.py) — were fail-fast end to end until PR 11: one
transient device hiccup killed a multi-hour run. The supervised
recovery machinery that fixed that (faults/retry.py + the sweep's
chunk-retry loop) is only trustworthy if it can be *exercised on
demand*: this module plants named injection sites inside the existing
stage spans and fires scheduled faults through them, deterministically,
so a chaos run is reproducible down to the chunk index
(benchmarks/chaos_sweep.py pins the recovered checkpoint byte-identical
to the fault-free run).

Design constraints, in order:

* **Zero overhead disarmed.** Every site is one module-global ``None``
  check (:func:`fire` returns immediately); no schedule parsing, no
  telemetry, no locks ever run in a production process that didn't opt
  in. Arming is explicit: :func:`arm` / :func:`armed` in code, or the
  ``PTA_FAULTS`` env var / ``--faults`` CLI flag via
  :func:`arm_from_env`.
* **Deterministic.** Triggers are by chunk index (``chunk=K``), by nth
  call at the site (``call=N``), or seeded-probabilistic (``p=P`` with
  the schedule seed) — same schedule + seed + workload => same faults
  at the same points, every run.
* **Observable.** Every firing bumps the ``faults.injected`` counter
  (labeled ``site=``/``kind=``) and emits a ``faults.fired`` event, so
  the flight recorder's ring and ``watch`` distinguish "retrying
  through injected faults" from "wedged" (docs/robustness.md).

Injection sites (the ``SITES`` table) sit inside the stage spans they
perturb, so a fault is attributed to the stage it would naturally occur
in: ``dispatch`` / ``drain`` / ``io_write`` (the sweep executor),
``cw_stream_stage`` (prefetch H2D staging), ``checkpoint_write`` /
``checkpoint_fsync`` (the atomic checkpoint layer — the only sites that
support ``torn``, which truncates the in-flight temp file before
raising, leaving exactly the torn artifact a mid-write crash leaves),
and ``likelihood_batch`` (the server's engine call).

Schedule grammar (one spec per fault, ``;``-separated)::

    site:kind[=param]@trigger[xN]

    kinds    raise | fatal | stall=SECONDS | torn | enospc |
             device_lost | nan
    triggers chunk=K | call=N | p=P        (p uses the schedule seed)
    xN       fire up to N times (default 1 — one-shot, recoverable)

Examples: ``drain:raise@chunk=2`` (transient exception on chunk 2's
readback), ``checkpoint_write:torn@call=3`` (truncate the 3rd
checkpoint temp file mid-write), ``drain:stall=4@chunk=1`` (wedge chunk
1's readback long enough to trip the sweep's ``DrainTimeout``),
``cw_stream_stage:device_lost@p=0.1x3`` (seeded 10% device-lost per
staged tile, at most 3 firings), ``drain:nan@chunk=2`` (silently poison
one seeded element of chunk 2's fetched block).

``nan`` is the one DATA-CORRUPTION kind: it raises nothing — it
overwrites one seeded element of the in-flight chunk block with NaN at
the ``drain`` site (:func:`poison`, wired into utils/sweep's readback).
Silent corruption is deliberately NOT recoverable by the retry
machinery (there is no exception to classify; a retry would persist
the same poisoned bytes) — what it exercises is the numerics
observatory's host-side drain scan (obs.numerics.scan_block), the only
layer that can catch it (benchmarks/numerics_probe.py pins that it
does).

stdlib-only and jax-free; telemetry imports are deferred to the firing
branch so a disarmed process never pays them.
"""
from __future__ import annotations

import errno
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Union

#: the named injection sites wired into the library. Sites named after
#: stage spans fire inside that span; the checkpoint sites fire inside
#: utils/sweep's atomic-write/fsync layer (where ``torn`` has a temp
#: file to tear).
SITE_DISPATCH = "dispatch"
SITE_DRAIN = "drain"
SITE_IO_WRITE = "io_write"
SITE_PREFETCH_STAGE = "cw_stream_stage"
SITE_CHECKPOINT_WRITE = "checkpoint_write"
SITE_CHECKPOINT_FSYNC = "checkpoint_fsync"
SITE_SERVER_ENGINE = "likelihood_batch"

SITES = frozenset({
    SITE_DISPATCH, SITE_DRAIN, SITE_IO_WRITE, SITE_PREFETCH_STAGE,
    SITE_CHECKPOINT_WRITE, SITE_CHECKPOINT_FSYNC, SITE_SERVER_ENGINE,
})

KINDS = frozenset({
    "raise", "fatal", "stall", "torn", "enospc", "device_lost", "nan",
})


class InjectedFault(RuntimeError):
    """A scheduled fault fired. ``transient`` drives the shared
    classifier (faults/retry.py): transient faults are what the
    supervised-recovery machinery must absorb; fatal ones must
    re-raise through every retry layer unchanged."""

    def __init__(self, site: str, kind: str, transient: bool = True,
                 detail: str = ""):
        self.site = site
        self.kind = kind
        self.transient = transient
        msg = f"injected fault at {site!r}: {kind}"
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)


@dataclass
class FaultSpec:
    """One scheduled fault: where, what, and when."""

    site: str
    kind: str
    stall_s: float = 0.0          # kind == "stall"
    chunk: Optional[int] = None   # trigger: ctx chunk/tile index == K
    call: Optional[int] = None    # trigger: Nth call at the site (1-based)
    p: Optional[float] = None     # trigger: seeded probability per call
    max_fires: int = 1
    # runtime state (owned by the armed schedule, mutated under its lock)
    calls: int = field(default=0, repr=False)
    fires: int = field(default=0, repr=False)

    def spec_str(self) -> str:
        kind = self.kind
        if self.kind == "stall":
            kind = f"stall={self.stall_s:g}"
        if self.chunk is not None:
            trig = f"chunk={self.chunk}"
        elif self.call is not None:
            trig = f"call={self.call}"
        else:
            trig = f"p={self.p:g}"
        tail = f"x{self.max_fires}" if self.max_fires != 1 else ""
        return f"{self.site}:{kind}@{trig}{tail}"


def parse_schedule(text: str) -> List[FaultSpec]:
    """Parse the ``;``-separated schedule grammar into specs.

    Raises ``ValueError`` with the offending spec on any malformed
    entry — a chaos run with a typo'd schedule must refuse to start,
    not silently run fault-free."""
    specs: List[FaultSpec] = []
    for raw in text.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        try:
            head, trig = raw.split("@", 1)
            site, kind = head.split(":", 1)
            site = site.strip()
            kind = kind.strip()
            stall_s = 0.0
            if "=" in kind:
                kind, param = kind.split("=", 1)
                if kind != "stall":
                    raise ValueError(f"kind {kind!r} takes no parameter")
                stall_s = float(param)
            if site not in SITES:
                raise ValueError(
                    f"unknown site {site!r} (sites: {sorted(SITES)})"
                )
            if kind not in KINDS:
                raise ValueError(
                    f"unknown kind {kind!r} (kinds: {sorted(KINDS)})"
                )
            if kind == "torn" and site not in (
                SITE_CHECKPOINT_WRITE, SITE_CHECKPOINT_FSYNC
            ):
                raise ValueError(
                    "torn faults need a file to tear — only the "
                    "checkpoint_write/checkpoint_fsync sites support them"
                )
            if kind == "nan" and site != SITE_DRAIN:
                raise ValueError(
                    "nan faults need an in-flight chunk block to "
                    "poison — only the drain site supports them"
                )
            max_fires = 1
            trig = trig.strip()
            if "x" in trig.rsplit("=", 1)[-1]:
                trig, n = trig.rsplit("x", 1)
                max_fires = int(n)
            tkey, _, tval = trig.partition("=")
            tkey = tkey.strip()
            spec = FaultSpec(site=site, kind=kind, stall_s=stall_s,
                             max_fires=max_fires)
            if tkey == "chunk":
                spec.chunk = int(tval)
            elif tkey == "call":
                spec.call = int(tval)
                if spec.call < 1:
                    raise ValueError("call trigger is 1-based")
            elif tkey == "p":
                spec.p = float(tval)
                if not 0.0 < spec.p <= 1.0:
                    raise ValueError("p must be in (0, 1]")
            else:
                raise ValueError(
                    f"unknown trigger {tkey!r} (chunk=K | call=N | p=P)"
                )
        except ValueError as exc:
            raise ValueError(f"bad fault spec {raw!r}: {exc}") from None
        specs.append(spec)
    return specs


class _Schedule:
    """The armed schedule: specs + seeded RNG + the fired-fault log."""

    def __init__(self, specs: Sequence[FaultSpec], seed: int):
        self.specs = list(specs)
        self.seed = int(seed)
        self.lock = threading.Lock()
        # one independent seeded stream per spec: firing order at one
        # site can't perturb another spec's draws
        self.rngs = [
            random.Random(self.seed * 1_000_003 + i)
            for i in range(len(specs))
        ]
        self.log: List[dict] = []  # bounded: see _record

    def _record(self, rec: dict) -> None:
        # bounded evidence ring (chaos benches read it back): cap, drop
        # oldest — a runaway p-trigger must not grow host memory
        self.log.append(rec)
        if len(self.log) > 256:
            del self.log[0]


#: the armed schedule, or None (the zero-overhead disarmed state)
_STATE: Optional[_Schedule] = None


def arm(schedule: Union[str, Sequence[FaultSpec]], seed: int = 0) -> None:
    """Arm a fault schedule process-wide. ``schedule`` is either the
    grammar string or pre-built specs."""
    global _STATE
    specs = (
        parse_schedule(schedule) if isinstance(schedule, str)
        else list(schedule)
    )
    _STATE = _Schedule(specs, seed)


def disarm() -> None:
    global _STATE
    _STATE = None


def is_armed() -> bool:
    return _STATE is not None


def fired() -> List[dict]:
    """Records of every fault fired since arming (site, kind, trigger
    context) — the chaos bench's evidence trail."""
    state = _STATE
    if state is None:
        return []
    with state.lock:
        return list(state.log)


class armed:
    """Context manager: arm for the block, restore on exit (tests)."""

    def __init__(self, schedule, seed: int = 0):
        self._schedule = schedule
        self._seed = seed

    def __enter__(self):
        self._saved = _STATE
        arm(self._schedule, seed=self._seed)
        return _STATE

    def __exit__(self, *exc):
        global _STATE
        _STATE = self._saved


def arm_from_env(env: str = "PTA_FAULTS",
                 seed_env: str = "PTA_FAULTS_SEED") -> bool:
    """Arm from ``PTA_FAULTS`` / ``PTA_FAULTS_SEED`` when set; returns
    whether a schedule was armed. Called by the CLI entry point so any
    subcommand can be chaos'd without code changes."""
    text = os.environ.get(env)
    if not text:
        return False
    arm(text, seed=int(os.environ.get(seed_env, "0")))
    return True


def _tear(path: str) -> None:
    """Truncate ``path`` to half its size — the torn artifact an
    interrupted write leaves. The caller's atomic-write layer never
    renamed it into place, so the *final* checkpoint stays consistent;
    what this exercises is the retry overwriting the torn temp."""
    try:
        size = os.path.getsize(path)
        with open(path, "r+b") as fh:
            fh.truncate(size // 2)
    except OSError:
        pass  # the raise below is the fault either way


def fire(site: str, **ctx) -> None:
    """The injection point. Disarmed: one ``None`` check, returns.

    Armed: match ``ctx`` against every spec for this site and perform
    the first matching fault. ``ctx`` carries the trigger inputs —
    ``chunk=`` (or ``tile=``, which the chunk trigger also matches) —
    plus anything site-specific (``path=`` for the torn kinds)."""
    state = _STATE
    if state is None:
        return
    index = ctx.get("chunk", ctx.get("tile"))
    action = None
    with state.lock:
        # every matching spec's call counter advances for every call at
        # its site, INDEPENDENT of whether some other spec fires on
        # this call — a firing must not shift later specs' "Nth call"
        # triggers (two call=N specs at one site fire at exactly N).
        # `nan` specs are poison()'s alone: fire() is called at the
        # drain site BEFORE the fetch (there is no block to poison
        # yet), so counting or matching them here would double-advance
        # their call counters and mis-fire them as a bare raise.
        for spec in state.specs:
            if spec.site == site and spec.kind != "nan":
                spec.calls += 1
        for k, spec in enumerate(state.specs):
            if (spec.site != site or spec.kind == "nan"
                    or spec.fires >= spec.max_fires):
                continue
            if spec.chunk is not None:
                hit = index is not None and int(index) == spec.chunk
            elif spec.call is not None:
                hit = spec.calls == spec.call
            else:
                hit = state.rngs[k].random() < spec.p
            if not hit:
                continue
            spec.fires += 1
            action = spec
            state._record({
                "site": site, "kind": spec.kind, "spec": spec.spec_str(),
                "chunk": None if index is None else int(index),
                "call": spec.calls,
            })
            break
    if action is None:
        return
    _emit(site, action, index)
    if action.kind == "stall":
        time.sleep(action.stall_s)
        return
    if action.kind == "torn":
        path = ctx.get("path")
        if path:
            _tear(str(path))
        raise InjectedFault(site, "torn", transient=True,
                            detail=f"truncated {ctx.get('path')}")
    if action.kind == "enospc":
        raise OSError(errno.ENOSPC, "No space left on device (injected)")
    if action.kind == "device_lost":
        raise InjectedFault(
            site, "device_lost", transient=True,
            detail="DEVICE_LOST: simulated device failure",
        )
    if action.kind == "fatal":
        raise InjectedFault(site, "fatal", transient=False)
    raise InjectedFault(site, "raise", transient=True)


def _poison_array(arr, rng: random.Random):
    """One seeded element of ``arr`` overwritten with NaN, on a COPY —
    the fetched block may alias a buffer the reader still owns."""
    import numpy as np

    arr = np.array(arr, copy=True)
    if arr.size and np.issubdtype(arr.dtype, np.floating):
        arr.reshape(-1)[rng.randrange(arr.size)] = np.nan
    return arr


def poison(site: str, block, **ctx):
    """The data-corruption injection point. Disarmed: one ``None``
    check, ``block`` passes through untouched (the production drain
    path's entire cost).

    Armed: the first matching ``nan`` spec for this site overwrites ONE
    seeded element of ``block`` (an ndarray, or the first shard of a
    ``utils.sweep.ShardedBlock``) with NaN and returns the poisoned
    copy — silent corruption, no exception, nothing for the retry
    classifier to absorb. The only layer that can catch it is the
    numerics observatory's host drain scan (obs.numerics.scan_block),
    which is exactly what the planted-NaN evidence arm exercises
    (benchmarks/numerics_probe.py). Triggers and the seeded per-spec
    RNG work exactly as :func:`fire`'s; the two surfaces are disjoint
    by kind (``nan`` here, everything else there)."""
    state = _STATE
    if state is None:
        return block
    index = ctx.get("chunk", ctx.get("tile"))
    action = None
    rng = None
    with state.lock:
        for spec in state.specs:
            if spec.site == site and spec.kind == "nan":
                spec.calls += 1
        for k, spec in enumerate(state.specs):
            if (spec.site != site or spec.kind != "nan"
                    or spec.fires >= spec.max_fires):
                continue
            if spec.chunk is not None:
                hit = index is not None and int(index) == spec.chunk
            elif spec.call is not None:
                hit = spec.calls == spec.call
            else:
                hit = state.rngs[k].random() < spec.p
            if not hit:
                continue
            spec.fires += 1
            action = spec
            rng = state.rngs[k]
            state._record({
                "site": site, "kind": "nan", "spec": spec.spec_str(),
                "chunk": None if index is None else int(index),
                "call": spec.calls,
            })
            break
    if action is None:
        return block
    _emit(site, action, index)
    shards = getattr(block, "shards", None)
    if shards is not None:  # utils.sweep.ShardedBlock: poison shard 0
        if shards:
            idx0, arr0 = shards[0]
            shards[0] = (idx0, _poison_array(arr0, rng))
        return block
    return _poison_array(block, rng)


def _emit(site: str, spec: FaultSpec, index) -> None:
    """Telemetry for one firing — deferred import so the disarmed path
    never touches obs."""
    from ..obs import counter, event, names

    counter(names.FAULTS_INJECTED, site=site, kind=spec.kind).inc()
    event(
        names.EVENT_FAULT_FIRED,
        site=site, kind=spec.kind, spec=spec.spec_str(),
        chunk=None if index is None else int(index),
    )
