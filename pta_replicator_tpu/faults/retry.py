"""Shared transient-vs-fatal classification and backoff retry.

Before PR 11 the repo had exactly one retry ladder — bench.py's
hand-rolled probe-and-hold loop (``time.sleep(20 * wedges)`` on exit
codes 3/4) — and every production path was fail-fast. This module is
the ONE policy both now share:

* :func:`is_transient` — the error classifier. Transient means "the
  same operation, retried as-is, can plausibly succeed": a wedged
  readback (``DrainTimeout``), a dropped tunnel/device
  (jaxlib's DEVICE_LOST/UNAVAILABLE message shapes, connection
  errors), interrupted/timed-out syscalls, and a full scratch disk
  (``ENOSPC`` — space is routinely reclaimed by cleanup/rotation, and
  the bounded attempt budget keeps a genuinely full disk from looping
  forever). Everything else — shape errors, fingerprint mismatches,
  OOM (bench handles that by *changing* the chunk size, not
  retrying it) — is fatal and re-raises through every retry layer
  unchanged.
* :class:`RetryPolicy` + :func:`backoff_delay` — exponential backoff
  with seeded jitter. ``TUNNEL_POLICY`` reproduces bench.py's proven
  20 s/40 s ladder (base 20, multiplier 2); the in-process supervisors
  (sweep chunk retry, prefetch staging, server engine) use the faster
  ``DEFAULT_POLICY``.
* :func:`retry_call` — the helper the serving path and prefetch use:
  call, classify, back off, re-call, bounded by the policy. Every
  retry emits a ``faults.retry`` event so a retrying run is
  distinguishable from a wedged one in ``watch``.

The sweep's chunk-level supervision lives in utils/sweep.py (it retries
by *resuming from the checkpoint sidecar*, which is stronger than
re-calling a function — the existing crash-resume tests are its
contract) but classifies and backs off through exactly these helpers.

stdlib-only; the pipeline/obs imports are deferred into the functions
that need them so this module can't cycle with the executors that
import it.
"""
from __future__ import annotations

import errno
import random
import time
from dataclasses import dataclass
from typing import Callable, Optional

from .inject import InjectedFault


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff: attempt ``k`` (1-based) sleeps
    ``min(max_delay_s, base_delay_s * multiplier**(k-1))``, jittered by
    ``+/- jitter`` (fraction). ``max_attempts`` counts total tries
    including the first."""

    max_attempts: int = 3
    base_delay_s: float = 0.5
    multiplier: float = 2.0
    max_delay_s: float = 30.0
    jitter: float = 0.5


#: in-process supervisors (sweep chunk retry, prefetch staging, server
#: engine): fail fast enough that a fatal misdiagnosis costs seconds
DEFAULT_POLICY = RetryPolicy()

#: bench.py's probe-and-hold ladder, unchanged in shape: the tunnel
#: flaps on a minutes cadence, so the first retry waits 20 s and the
#: second 40 s (base 20 x multiplier 2), +/-25% jitter to avoid
#: re-probing in lockstep with a flapping keepalive
TUNNEL_POLICY = RetryPolicy(
    max_attempts=3, base_delay_s=20.0, multiplier=2.0,
    max_delay_s=120.0, jitter=0.25,
)

#: bench child exit codes that are the flapping tunnel's transient
#: signature (3 = backend init wedged/failed fast, 4 = silent fallback
#: to the wrong backend) — the subprocess-level twin of
#: :func:`is_transient`, shared so bench.py and any future child-runner
#: classify identically
TRANSIENT_EXIT_CODES = frozenset({3, 4})

#: syscall errnos a retry can plausibly outlive (see module doc for the
#: ENOSPC rationale)
_TRANSIENT_ERRNOS = frozenset({
    errno.EINTR, errno.EAGAIN, errno.ETIMEDOUT, errno.ECONNRESET,
    errno.ECONNREFUSED, errno.EPIPE, errno.ENOSPC,
})

#: message shapes of the tunnel/device failure modes jaxlib surfaces as
#: bare RuntimeErrors (no typed hierarchy to catch) — lowercase substrings
_TRANSIENT_PATTERNS = (
    "device_lost", "data_loss", "unavailable", "aborted",
    "failed to connect", "connection reset", "socket closed",
    "deadline exceeded",
)


def is_transient(exc: BaseException) -> bool:
    """True when retrying the same operation can plausibly succeed."""
    if isinstance(exc, InjectedFault):
        return exc.transient
    # DrainTimeout imported lazily: pipeline.py imports this package's
    # injection sites, so a module-level import here would cycle
    from ..parallel.pipeline import DrainTimeout

    if isinstance(exc, DrainTimeout):
        return True
    if isinstance(exc, ConnectionError):
        return True
    if isinstance(exc, OSError):
        return exc.errno in _TRANSIENT_ERRNOS
    if isinstance(exc, (RuntimeError, SystemError)):
        msg = str(exc).lower()
        return any(p in msg for p in _TRANSIENT_PATTERNS)
    return False


def backoff_delay(attempt: int, policy: RetryPolicy = DEFAULT_POLICY,
                  seed: Optional[int] = None) -> float:
    """Delay before retry ``attempt`` (1-based). ``seed`` makes the
    jitter deterministic (chaos benches pin wall overhead); None draws
    from the process RNG."""
    base = min(
        policy.max_delay_s,
        policy.base_delay_s * policy.multiplier ** (attempt - 1),
    )
    if policy.jitter <= 0:
        return base
    rng = (
        random.Random(seed * 1_000_003 + attempt)
        if seed is not None else random
    )
    return base * (1.0 + policy.jitter * (2.0 * rng.random() - 1.0))


def retry_call(
    fn: Callable,
    *,
    policy: RetryPolicy = DEFAULT_POLICY,
    classify: Callable[[BaseException], bool] = is_transient,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
    seed: Optional[int] = None,
    scope: str = "retry_call",
):
    """Call ``fn()`` under the policy: a fatal error re-raises
    immediately and unchanged; a transient one backs off and retries
    until the attempt budget is spent (then the LAST error re-raises).
    Each retry emits a ``faults.retry`` event (``scope`` labels whose
    retry it was) and calls ``on_retry(attempt, exc)`` — the hook
    supervisors use to bump their own counters."""
    attempt = 1
    while True:
        try:
            return fn()
        except BaseException as exc:  # noqa: BLE001 — classified, then re-raised
            if attempt >= policy.max_attempts or not classify(exc):
                raise
            from ..obs import event, names

            event(names.EVENT_FAULT_RETRY, scope=scope, attempt=attempt,
                  error=repr(exc)[:200])
            if on_retry is not None:
                on_retry(attempt, exc)
            sleep(backoff_delay(attempt, policy, seed=seed))
            attempt += 1
