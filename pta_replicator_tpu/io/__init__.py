from .par import ParModel, read_par
from .tim import TOAData, read_tim, write_tim
from .noise_dict import parse_noise_dict

__all__ = [
    "ParModel",
    "read_par",
    "TOAData",
    "read_tim",
    "write_tim",
    "parse_noise_dict",
]
