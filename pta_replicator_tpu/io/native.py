"""ctypes bindings for the native (C++) IO fast paths.

The shared library is compiled on first use with g++ into the package's
``_native`` cache directory and loaded via ctypes (the build image has no
pybind11; SURVEY.md's native-component policy). Every entry point
degrades gracefully: if the toolchain or compile is unavailable,
callers fall back to the pure-Python implementations.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading
from typing import Optional

import numpy as np

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False
_HAS_WRITE = False

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "csrc", "fast_tim.cpp")

ERR_OPEN = -1
DIRECTIVE_FOUND = -2
ERR_TEXT_OVERFLOW = -3
ERR_WRITE = -4


def _build_dir() -> str:
    d = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_native")
    os.makedirs(d, exist_ok=True)
    return d


def load_library() -> Optional[ctypes.CDLL]:
    """Compile (once) and load the native library; None if unavailable."""
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        if not os.path.isfile(_SRC):
            return None
        try:
            so_path = os.path.join(_build_dir(), "libfastio.so")
            if (not os.path.isfile(so_path)
                    or os.path.getmtime(so_path) < os.path.getmtime(_SRC)):
                subprocess.run(
                    ["g++", "-O3", "-fPIC", "-shared", "-o", so_path, _SRC],
                    check=True, capture_output=True, timeout=120,
                )
            lib = ctypes.CDLL(so_path)
            lib.fast_tim_count.restype = ctypes.c_int64
            lib.fast_tim_count.argtypes = [ctypes.c_char_p]
            lib.fast_tim_parse.restype = ctypes.c_int64
            lib.fast_tim_parse.argtypes = [
                ctypes.c_char_p,
                ctypes.c_int64,
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
                ctypes.c_char_p,
                ctypes.c_int64,
            ]
            # the writer symbol is newer than the reader: a stale cached
            # .so without it must not disable the working read fast path
            global _HAS_WRITE
            try:
                lib.fast_tim_write.restype = ctypes.c_int64
                lib.fast_tim_write.argtypes = [
                    ctypes.c_char_p,
                    ctypes.c_int64,
                    np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                    np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                    ctypes.c_char_p,
                ]
                _HAS_WRITE = True
            except AttributeError:
                _HAS_WRITE = False
            _LIB = lib
        except Exception as err:  # toolchain missing, compile failure, ...
            print(f"pta_replicator_tpu: native IO unavailable ({err}); "
                  "using the Python tim parser.", file=sys.stderr)
            _LIB = None
        return _LIB


def fast_read_tim(path: str):
    """Parse a plain tim file natively.

    Returns (mjd_longdouble, errors_s, freqs_mhz, labels, observatories,
    flag_strings) or None when the native path is unavailable or the file
    uses stateful directives (INCLUDE/SKIP/TIME/EFAC/EQUAD).
    """
    lib = load_library()
    if lib is None:
        return None
    n = lib.fast_tim_count(path.encode())
    if n < 0:
        return None  # unreadable or needs the stateful Python parser
    mjd_day = np.empty(n, dtype=np.int64)
    mjd_frac = np.empty(n, dtype=np.float64)
    err_us = np.empty(n, dtype=np.float64)
    freq = np.empty(n, dtype=np.float64)
    # the stored text (label\x1fobs\x1fflags\n per TOA) is bounded by the
    # file itself plus the per-record separators
    text_cap = max(4096, os.path.getsize(path) + 4 * int(n))
    text = ctypes.create_string_buffer(text_cap)
    got = lib.fast_tim_parse(path.encode(), n, mjd_day, mjd_frac, err_us,
                             freq, text, text_cap)
    if got != n:
        return None
    mjd = mjd_day.astype(np.longdouble) + mjd_frac.astype(np.longdouble)
    labels, obs, flag_strs = [], [], []
    raw = text.value.decode(errors="replace")
    # split on the exact record separator fast_tim_parse writes ('\n');
    # splitlines() would also break on \x0b/\x0c/\x85 inside flag tails
    for rec in raw.split("\n"):
        if not rec:
            continue
        parts = rec.split("\x1f", 2)
        labels.append(parts[0] if len(parts) > 0 else "")
        obs.append(parts[1] if len(parts) > 1 else "")
        flag_strs.append(parts[2] if len(parts) > 2 else "")
    return mjd, err_us * 1e-6, freq, labels, obs, flag_strs


def fast_write_tim(path: str, mjd_day, frac15, text: bytes) -> bool:
    """Write a FORMAT-1 tim file natively from the split epoch arrays and
    the pre-rendered static line parts (io.tim builds them). Returns
    False when the native writer is unavailable (caller falls back to
    the Python writer); raises OSError when the write itself fails
    (e.g. disk full) — a failed write must never look like a success."""
    lib = load_library()
    if lib is None or not _HAS_WRITE:
        return False
    n = len(mjd_day)
    got = lib.fast_tim_write(
        path.encode(), n,
        np.ascontiguousarray(mjd_day, dtype=np.int64),
        np.ascontiguousarray(frac15, dtype=np.int64),
        text,
    )
    if got != n:
        reason = {
            ERR_OPEN: "could not open for writing",
            ERR_WRITE: "write or close failed mid-file (disk full?)",
            ERR_TEXT_OVERFLOW: "malformed pre-rendered line stream",
        }.get(got, "unknown failure")
        raise OSError(
            f"native tim write failed for {path}: {reason} (code {got})"
        )
    return True
