"""NANOGrav-style noise-dictionary parsing.

The reference ships ``noise_dicts/ng15_dict.json`` (785 keys over 69 pulsars,
keyed ``{PSR}_{backend}_{param}``) and parses it ad hoc in the example
notebook (/root/reference/examples/add_noise.ipynb cells 5-6). Here that
convention is a first-class API: :func:`parse_noise_dict` returns, per
pulsar, the per-backend flag values and aligned parameter vectors ready to
feed the flagged white-noise/jitter operators.
"""
from __future__ import annotations

import json
import os
from collections import defaultdict
from typing import Dict


_WN_PARAMS = ("efac", "log10_t2equad", "log10_tnequad", "log10_ecorr")
_PSR_PARAMS = ("red_noise_gamma", "red_noise_log10_A")


def parse_noise_dict(src) -> Dict[str, dict]:
    """Parse a noise dict (path or mapping) into per-pulsar structures.

    Returns ``{psr_name: {"backends": [...], "efac": [...],
    "log10_t2equad": [...], "log10_ecorr": [...],
    "red_noise_gamma": g, "red_noise_log10_A": a}}`` where the per-backend
    lists are aligned with ``backends`` and missing entries are ``None``.
    """
    if isinstance(src, (str, os.PathLike)):
        with open(src) as fh:
            raw = json.load(fh)
    else:
        raw = dict(src)

    per_psr: Dict[str, dict] = defaultdict(
        lambda: {
            "backends": [],
            **{p: [] for p in _WN_PARAMS},
            **{p: None for p in _PSR_PARAMS},
        }
    )

    for key, value in raw.items():
        psr, rest = key.split("_", 1)
        entry = per_psr[psr]
        matched = False
        for param in _PSR_PARAMS:
            if rest == param:
                entry[param] = value
                matched = True
                break
        if matched:
            continue
        for param in _WN_PARAMS:
            suffix = "_" + param
            if rest.endswith(suffix):
                backend = rest[: -len(suffix)]
                if backend not in entry["backends"]:
                    entry["backends"].append(backend)
                    for p in _WN_PARAMS:
                        entry[p].append(None)
                idx = entry["backends"].index(backend)
                entry[param][idx] = value
                matched = True
                break
        if not matched:
            entry.setdefault("extra", {})[rest] = value

    return dict(per_psr)
