"""Tempo/Tempo2/PINT-style ``.par`` timing-model file parser.

The reference framework delegates par parsing to PINT
(``pint.models.get_model``, /root/reference/pta_replicator/simulate.py:118,154).
This framework is standalone: it carries its own parser that extracts the
parameters the simulation layer needs (spin, astrometry, DM) while preserving
every line verbatim for lossless round-tripping via :func:`ParModel.write`.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

# Keys whose values are plain floats we want typed access to.
_FLOAT_KEYS = {
    "F0", "F1", "F2", "F3",
    "PEPOCH", "POSEPOCH", "DMEPOCH",
    "DM", "DM1", "DM2",
    "PX", "PMRA", "PMDEC", "PMELONG", "PMELAT",
    "ELONG", "ELAT",
    "START", "FINISH", "TZRMJD", "NTOA", "CHI2R",
}


def _parse_hms(text: str) -> float:
    """Parse ``hh:mm:ss.s`` into decimal hours (sign-aware)."""
    sign = -1.0 if text.lstrip().startswith("-") else 1.0
    parts = [abs(float(p)) for p in text.split(":")]
    while len(parts) < 3:
        parts.append(0.0)
    return sign * (parts[0] + parts[1] / 60.0 + parts[2] / 3600.0)


def _parse_dms(text: str) -> float:
    """Parse ``dd:mm:ss.s`` into decimal degrees (sign-aware)."""
    return _parse_hms(text)  # same sexagesimal structure


def _parse_float(token) -> float:
    """Float with tempo's legacy D/d exponent style normalized — the ONE
    numeric-token parser for par values (JUMP offsets, FD terms, DMX
    values and ranges), so no site can forget the normalization."""
    return float(str(token).replace("D", "E").replace("d", "e"))


@dataclass
class ParModel:
    """A parsed pulsar timing model.

    Angles follow the conventions of the reference's ``loc`` dicts
    (/root/reference/pta_replicator/simulate.py:127-132): RAJ in decimal
    *hours*, DECJ in decimal *degrees*, ELONG/ELAT in decimal degrees.
    """

    name: str = ""
    raj_hours: Optional[float] = None
    decj_deg: Optional[float] = None
    elong_deg: Optional[float] = None
    elat_deg: Optional[float] = None
    f0: float = 1.0
    f1: float = 0.0
    f2: float = 0.0
    pepoch_mjd: float = 0.0
    dm: float = 0.0
    params: dict = field(default_factory=dict)
    lines: list = field(default_factory=list)
    path: Optional[str] = None

    @property
    def loc(self) -> dict:
        """Sky-location dict in the reference's units convention."""
        if self.raj_hours is not None and self.decj_deg is not None:
            return {"RAJ": self.raj_hours, "DECJ": self.decj_deg}
        if self.elong_deg is not None and self.elat_deg is not None:
            return {"ELONG": self.elong_deg, "ELAT": self.elat_deg}
        raise AttributeError(
            "No pulsar location information (RAJ/DECJ or ELONG/ELAT) in parfile."
        )

    def set_param(self, key: str, value: float, fmt: str = ".20g") -> None:
        """Update a parameter value, keeping typed fields and the verbatim
        line store in sync (so :meth:`write` persists post-fit models)."""
        key = key.upper()
        text = format(value, fmt)
        if key == "F0":
            self.f0 = value
        elif key == "F1":
            self.f1 = value
        elif key == "F2":
            self.f2 = value
        elif key == "PEPOCH":
            self.pepoch_mjd = value
        elif key == "DM":
            self.dm = value
        elif key == "RAJ":  # decimal hours (colon-free floats re-parse fine)
            self.raj_hours = value
        elif key == "DECJ":  # decimal degrees
            self.decj_deg = value
        updated = False
        for i, line in enumerate(self.lines):
            tokens = line.split()
            if tokens and tokens[0].upper() == key:
                tokens[1] = text
                self.lines[i] = "\t\t".join(tokens[:2]) + (
                    ("\t" + " ".join(tokens[2:])) if len(tokens) > 2 else ""
                )
                updated = True
                break
        if not updated:
            self.lines.append(f"{key}\t\t{text}")
        self.params[key] = [text] + self.params.get(key, [None, None])[1:]

    def set_param_error(self, key: str, error: float, fmt: str = ".20g") -> None:
        """Write a parameter's 1-sigma uncertainty into the par line's
        error column (tempo/PINT layout ``KEY value fit_flag error``).
        A missing fit flag is filled with "1" — errors are only written
        for parameters the fit actually varied. The error is in the
        par file's native display units for that key (e.g. RAJ in
        seconds of right ascension, PX in mas)."""
        key = key.upper()
        text = format(error, fmt)
        for i, line in enumerate(self.lines):
            tokens = line.split()
            if tokens and tokens[0].upper() == key:
                if len(tokens) < 3:
                    tokens.append("1")
                if len(tokens) < 4:
                    tokens.append(text)
                else:
                    tokens[3] = text
                self.lines[i] = "\t".join(
                    [tokens[0], tokens[1]] + tokens[2:]
                )
                vals = self.params.get(key, [tokens[1]])
                vals = list(vals) + [None] * (3 - len(vals))
                vals[1] = tokens[2]
                vals[2] = text
                self.params[key] = vals
                return

    def param_error(self, key: str):
        """1-sigma uncertainty from the par line's error column
        (``KEY value fit_flag error``), or None when absent/unparseable.
        Units are the par file's native display units for the key."""
        toks = self.params.get(key.upper())
        if toks and len(toks) >= 3 and toks[2] is not None:
            try:
                return _parse_float(toks[2])
            except ValueError:
                return None
        return None

    def _jump_lines(self):
        """(line_index, tokens) of every flag-matched JUMP declaration —
        the single filter behind :attr:`jumps` and :meth:`set_jump`, so
        their index mappings can never drift apart."""
        for i, line in enumerate(self.lines):
            tokens = line.split()
            if (
                len(tokens) >= 4
                and tokens[0].upper() == "JUMP"
                and tokens[1].startswith("-")
            ):
                try:
                    _parse_float(tokens[3])
                except ValueError:
                    continue
                yield i, tokens

    @property
    def jumps(self):
        """Flag-matched JUMP declarations, in par-file order.

        Each entry is ``(flag_name, flag_value, offset_s)`` parsed from
        ``JUMP -<flag> <value> <offset> [fit] [err]`` lines — the NANOGrav
        convention all three reference fixtures use (e.g.
        /root/reference/test_partim/par/B1855+09.par "JUMP -fe L-wide ...").
        ``params`` cannot hold these (multiple JUMP lines would collide on
        one key), so they parse from the verbatim line store. MJD-range /
        frequency-range JUMP forms are skipped.
        """
        return [
            (tokens[1].lstrip("-"), tokens[2], _parse_float(tokens[3]))
            for _, tokens in self._jump_lines()
        ]

    def set_jump(self, index: int, offset_s: float) -> None:
        """Update the ``index``-th flag-matched JUMP line's offset value."""
        for seen, (i, tokens) in enumerate(self._jump_lines()):
            if seen == index:
                tokens[3] = format(offset_s, ".20g")
                self.lines[i] = "\t".join(tokens)
                return
        raise IndexError(f"par file has no flag-matched JUMP #{index}")

    def set_jump_error(self, index: int, error_s: float) -> None:
        """Write the ``index``-th flag-matched JUMP line's 1-sigma
        uncertainty (``JUMP -flag value offset fit error`` layout)."""
        for seen, (i, tokens) in enumerate(self._jump_lines()):
            if seen == index:
                if len(tokens) < 5:
                    tokens.append("1")
                if len(tokens) < 6:
                    tokens.append(format(error_s, ".20g"))
                else:
                    tokens[5] = format(error_s, ".20g")
                self.lines[i] = "\t".join(tokens)
                return
        raise IndexError(f"par file has no flag-matched JUMP #{index}")

    # ----------------------------------------------------------- WAVE model
    @property
    def wave_om(self):
        """WAVE fundamental frequency [rad/day], or None when the par
        declares no waves (tempo2/PINT harmonic-whitening model)."""
        if "WAVE_OM" in self.params:
            try:
                return _parse_float(self.params["WAVE_OM"][0])
            except ValueError:
                return None
        return None

    @property
    def wave_epoch(self):
        """WAVEEPOCH [MJD] (PEPOCH when absent, the tempo2 default)."""
        for key in ("WAVEEPOCH", "WAVE_EPOCH"):
            if key in self.params:
                try:
                    return _parse_float(self.params[key][0])
                except ValueError:
                    pass
        return self.pepoch_mjd

    @property
    def waves(self):
        """[(A_sin, B_cos), ...] for WAVE1..WAVEn [s]: harmonic k of
        WAVE_OM contributes A sin(k om (t - epoch)) + B cos(...). Two
        values share one ``WAVEk`` line, so (like JUMPs) these parse from
        the verbatim line store, not ``params``."""
        by_k = {}
        for line in self.lines:
            tokens = line.split()
            if len(tokens) >= 3 and tokens[0].upper().startswith("WAVE"):
                tail = tokens[0][4:]
                if tail.isdigit():
                    try:
                        by_k[int(tail)] = (
                            _parse_float(tokens[1]), _parse_float(tokens[2])
                        )
                    except ValueError:
                        pass
        if not by_k:
            return []
        # a numbering gap (hand-edited par) becomes a zero-amplitude
        # placeholder rather than silently truncating every higher
        # harmonic out of the model/fit/write-back
        return [by_k.get(k, (0.0, 0.0)) for k in range(1, max(by_k) + 1)]

    def set_wave(self, index: int, a_sin: float, b_cos: float) -> None:
        """Update (or append) the ``WAVE{index+1}`` harmonic amplitudes."""
        key = f"WAVE{index + 1}"
        text = f"{format(a_sin, '.20g')} {format(b_cos, '.20g')}"
        for i, line in enumerate(self.lines):
            tokens = line.split()
            if tokens and tokens[0].upper() == key:
                self.lines[i] = f"{key}\t\t{text}"
                return
        self.lines.append(f"{key}\t\t{text}")

    def ensure_waves(self, n: int, om: float = None, epoch: float = None):
        """Declare ``n`` zero-amplitude WAVE harmonics (adding WAVE_OM /
        WAVEEPOCH when absent) so a fit can use the harmonic-whitening
        columns as a nuisance basis on models that had none.

        ``om`` [rad/day] is required when the par has no WAVE_OM
        (2*pi/(1.05*span_days) is the usual choice); when the par
        already declares WAVE_OM, a conflicting ``om`` raises instead of
        silently keeping the old basis under the caller's nose."""
        existing = self.wave_om
        if existing is None:
            if om is None:
                raise ValueError(
                    "par has no WAVE_OM; pass om=2*pi/span_days explicitly"
                )
            self.set_param("WAVE_OM", om)
        elif om is not None and abs(om - existing) > 1e-12 * abs(existing):
            raise ValueError(
                f"par already declares WAVE_OM={existing!r}; refusing to "
                f"rebase the existing harmonics onto om={om!r} (drop the "
                "om argument to extend the existing basis)"
            )
        if epoch is not None:
            self.set_param("WAVEEPOCH", epoch)
        have = len(self.waves)
        for k in range(have, n):
            self.set_wave(k, 0.0, 0.0)

    @property
    def fd_terms(self):
        """[FD1, FD2, ...] profile-evolution coefficients [s], in order.
        PINT/tempo2 convention: delay = sum_k FDk * ln(f_GHz)^k."""
        out = []
        k = 1
        while f"FD{k}" in self.params:
            try:
                out.append(_parse_float(self.params[f"FD{k}"][0]))
            except ValueError:
                break
            k += 1
        return out

    @property
    def dmx_windows(self):
        """NANOGrav DMX dispersion windows: [(label, dmx, r1_mjd, r2_mjd)]
        sorted by label, parsed from DMX_xxxx / DMXR1_xxxx / DMXR2_xxxx
        parameter triples."""
        out = []
        for key, tokens in self.params.items():
            if not key.startswith("DMX_"):
                continue
            idx = key[4:]
            r1 = self.params.get(f"DMXR1_{idx}")
            r2 = self.params.get(f"DMXR2_{idx}")
            if not (r1 and r2):
                continue
            try:
                out.append((
                    idx,
                    _parse_float(tokens[0]),
                    _parse_float(r1[0]),
                    _parse_float(r2[0]),
                ))
            except ValueError:
                continue
        # time order (not label order): the delay model's searchsorted
        # pass requires monotonic window starts, and labels need not be
        # zero-padded ('10' sorts before '2' lexicographically)
        return sorted(out, key=lambda w: w[2])

    def write(self, path: str) -> None:
        """Write the par file back out, preserving original content."""
        with open(path, "w") as fh:
            for line in self.lines:
                fh.write(line.rstrip("\n") + "\n")


def read_par(path: str) -> ParModel:
    """Parse a ``.par`` file into a :class:`ParModel`."""
    from ..obs import counter, span

    with span("read_par", file=os.path.basename(path)) as sp:
        model = _read_par_impl(path)
        sp["nparams"] = len(model.params)
        counter("io.par.files").inc()
    return model


def _read_par_impl(path: str) -> ParModel:
    model = ParModel(path=path)
    with open(path) as fh:
        raw = fh.read().splitlines()
    for line in raw:
        model.lines.append(line)
        stripped = line.strip()
        if not stripped or stripped.startswith(("#", "C ")):
            continue
        tokens = stripped.split()
        key = tokens[0].upper()
        if len(tokens) < 2:
            continue
        value = tokens[1]
        model.params[key] = tokens[1:]
        if key in ("PSR", "PSRJ", "PSRB"):
            model.name = value
        elif key == "RAJ":
            model.raj_hours = _parse_hms(value)
        elif key == "DECJ":
            model.decj_deg = _parse_dms(value)
        elif key in _FLOAT_KEYS:
            try:
                fval = _parse_float(value)
            except ValueError:
                continue
            if key == "F0":
                model.f0 = fval
            elif key == "F1":
                model.f1 = fval
            elif key == "F2":
                model.f2 = fval
            elif key == "PEPOCH":
                model.pepoch_mjd = fval
            elif key == "DM":
                model.dm = fval
            elif key == "ELONG":
                model.elong_deg = fval
            elif key == "ELAT":
                model.elat_deg = fval
    return model
