"""Tempo2-format ``.tim`` TOA file reader/writer.

Replaces the reference's use of ``pint.toa.get_TOAs``
(/root/reference/pta_replicator/simulate.py:155). TOA epochs are held as
``np.longdouble`` MJDs (~18 significant digits, sub-nanosecond at MJD 5e4),
the precision PINT achieves with its pair-of-doubles representation.

Mutation model: the framework never rewrites parsed strings in place; TOA
adjustments (`adjust_seconds`) accumulate in the longdouble MJD array, which
is the single source of truth for epochs, and `write_tim` re-serializes it.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from ..constants import DAY_IN_SEC


@dataclass
class TOAData:
    """Columnar TOA container (the device-independent CPU representation)."""

    #: observation epochs, UTC MJD, longdouble
    mjd: np.ndarray = None
    #: TOA uncertainties [s], float64
    errors_s: np.ndarray = None
    #: observing radio frequency [MHz]
    freqs_mhz: np.ndarray = None
    #: observatory codes
    observatories: List[str] = field(default_factory=list)
    #: per-TOA flag dicts, e.g. {"pta": "PPTA", "f": "L-wide_PUPPI"}
    flags: List[dict] = field(default_factory=list)
    #: TOA label column (usually source file or profile name)
    labels: List[str] = field(default_factory=list)

    @property
    def ntoas(self) -> int:
        return 0 if self.mjd is None else len(self.mjd)

    def get_mjds(self) -> np.ndarray:
        """Epochs as float64 MJD (reference analog: ``toas.get_mjds().value``)."""
        return np.asarray(self.mjd, dtype=np.float64)

    def get_errors_s(self) -> np.ndarray:
        return self.errors_s

    def get_flag(self, flagid: str, default: str = "") -> np.ndarray:
        """Vector of one flag's values across TOAs."""
        return np.array([f.get(flagid, default) for f in self.flags])

    @property
    def first_mjd(self) -> float:
        return float(self.mjd.min())

    @property
    def last_mjd(self) -> float:
        return float(self.mjd.max())

    def adjust_seconds(self, dt_s: np.ndarray) -> None:
        """Shift TOA epochs by ``dt_s`` seconds (the injection primitive).

        Reference analog: ``toas.adjust_TOAs(TimeDelta(...))``
        (e.g. /root/reference/pta_replicator/white_noise.py:124).
        """
        dt_s = np.asarray(dt_s)
        if dt_s.shape != self.mjd.shape:
            raise ValueError(
                f"delay shape {dt_s.shape} does not match ntoas {self.mjd.shape}"
            )
        self.mjd = self.mjd + dt_s.astype(np.longdouble) / np.longdouble(DAY_IN_SEC)

    def copy(self) -> "TOAData":
        return TOAData(
            mjd=self.mjd.copy(),
            errors_s=self.errors_s.copy(),
            freqs_mhz=self.freqs_mhz.copy(),
            observatories=list(self.observatories),
            flags=[dict(f) for f in self.flags],
            labels=list(self.labels),
        )


class _TimParserState:
    """Mutable directive state threaded through INCLUDE recursion.

    Tempo-style commands honored: SKIP/NOSKIP blocks, ``TIME <s>``
    cumulative offsets, ``EFAC <k>`` / ``EQUAD <us>`` error rescaling, and
    ``INCLUDE <file>`` (resolved relative to the including file).
    """

    def __init__(self):
        self.skipping = False
        self.time_offset_s = 0.0
        self.efac = 1.0
        self.equad_us = 0.0
        self.mjds: List[np.longdouble] = []
        self.errs: List[float] = []
        self.freqs: List[float] = []
        self.obs: List[str] = []
        self.flags: List[dict] = []
        self.labels: List[str] = []


def _parse_tim_into(path: str, st: _TimParserState, depth: int = 0) -> None:
    if depth > 10:
        raise RecursionError(f"tim INCLUDE nesting too deep at {path}")
    base = os.path.dirname(os.path.abspath(path))
    with open(path) as fh:
        for line in fh:
            stripped = line.strip()
            if not stripped:
                continue
            tokens = stripped.split()
            head = tokens[0].upper()
            if head == "NOSKIP":
                st.skipping = False
                continue
            if head == "SKIP":
                st.skipping = True
                continue
            if st.skipping:
                continue
            if head == "INCLUDE" and len(tokens) >= 2:
                _parse_tim_into(os.path.join(base, tokens[1]), st, depth + 1)
                continue
            if head == "TIME" and len(tokens) >= 2:
                st.time_offset_s += float(tokens[1])
                continue
            if head == "EFAC" and len(tokens) >= 2:
                st.efac = float(tokens[1])
                continue
            if head == "EQUAD" and len(tokens) >= 2:
                st.equad_us = float(tokens[1])
                continue
            if head in ("FORMAT", "MODE", "JUMP") or stripped.startswith(("C ", "#")):
                continue
            if len(tokens) < 5:
                continue
            st.labels.append(tokens[0])
            st.freqs.append(float(tokens[1]))
            # longdouble parse keeps ~18 digits (sub-ns at MJD ~5e4)
            mjd = np.longdouble(tokens[2])
            if st.time_offset_s:
                mjd = mjd + np.longdouble(st.time_offset_s) / np.longdouble(DAY_IN_SEC)
            st.mjds.append(mjd)
            err_us = float(tokens[3])
            err_us = np.hypot(st.efac * err_us, st.equad_us)
            st.errs.append(err_us * 1e-6)  # us -> s
            st.obs.append(tokens[4])
            st.flags.append(_parse_flag_tail(tokens[5:]))


def _is_number(tok: str) -> bool:
    try:
        float(tok)
        return True
    except ValueError:
        return False


def _is_flag_key(tok: str) -> bool:
    """'-fe' is a flag key; '-1.5e-6'-style negative numbers are values.
    The char-class prefilter keeps the exception-driven float() probe off
    the hot path (keys start with letters in practice)."""
    if len(tok) < 2 or tok[0] != "-":
        return False
    c = tok[1]
    # only '-<digit>', '-.', '-inf'/'-nan' spellings can parse as floats;
    # anything else is a key without paying the float() probe
    if not (c.isdigit() or c in ".iInN"):
        return True
    return not _is_number(tok)


def _parse_flag_tail(toks) -> dict:
    """'-key value ...' pairs from a token list (or raw string)."""
    if isinstance(toks, str):
        toks = toks.split()
    out = {}
    i, n = 0, len(toks)
    while i < n:
        tok = toks[i]
        if _is_flag_key(tok):
            if i + 1 < n and not _is_flag_key(toks[i + 1]):
                out[tok[1:]] = toks[i + 1]
                i += 2
                continue
            out[tok[1:]] = ""
        i += 1
    return out


def read_tim(path: str, use_native: bool = True) -> TOAData:
    """Parse a Tempo2 ``FORMAT 1`` tim file (with SKIP/NOSKIP, INCLUDE,
    TIME, EFAC, EQUAD command handling).

    Plain files (no stateful directives) go through the native C++
    tokenizer when available (csrc/fast_tim.cpp); directive-bearing files
    and toolchain-less environments use the Python parser.
    """
    from ..obs import counter, span

    with span("read_tim", file=os.path.basename(path)) as sp:
        toas = _read_tim_impl(path, use_native=use_native, span_attrs=sp)
        sp["ntoa"] = toas.ntoas
        counter("io.tim.files").inc()
        counter("io.tim.toas").inc(toas.ntoas)
    return toas


def _read_tim_impl(path: str, use_native: bool, span_attrs: dict) -> TOAData:
    if use_native:
        from .native import fast_read_tim

        fast = fast_read_tim(path)
        if fast is not None:
            mjd, errs, freqs, labels, obs, flag_strs = fast
            span_attrs["parser"] = "native"
            return TOAData(
                mjd=mjd,
                errors_s=errs,
                freqs_mhz=freqs,
                observatories=obs,
                flags=[_parse_flag_tail(s) for s in flag_strs],
                labels=labels,
            )
    st = _TimParserState()
    _parse_tim_into(path, st)
    span_attrs["parser"] = "python"
    return TOAData(
        mjd=np.array(st.mjds, dtype=np.longdouble),
        errors_s=np.array(st.errs, dtype=np.float64),
        freqs_mhz=np.array(st.freqs, dtype=np.float64),
        observatories=st.obs,
        flags=st.flags,
        labels=st.labels,
    )


def _static_line_parts(
    toas: TOAData, name: Optional[str], reuse_cache: bool = False,
    pairs_only: bool = False,
):
    """Pre-rendered epoch-invariant parts of every tim line: a list of
    ``(prefix, suffix)`` pairs (prefix = " label freq", suffix =
    "err obs flags") plus the ``"prefix\\x1fsuffix\\n"`` byte stream the
    native writer consumes. Returns ``(pairs, stream_bytes)``; ``pairs``
    is None on a cache hit (only the bytes are retained — so the
    static-cache speedup is a native-writer feature; the no-toolchain
    fallback re-renders pairs per write, with ``pairs_only=True``
    skipping the then-unused byte join), and ``stream_bytes`` is None
    when ``pairs_only``.

    ``reuse_cache`` is an *opt-in* contract for callers that rewrite the
    same TOAs with only the epochs changed (the dataset-materialization
    sweep, utils/export.py, where rendering these parts — flag joins +
    float formatting — was ~70% of the write cost). Default off: plain
    ``write_tim`` callers may mutate flag/error/label elements in place
    between writes, which no cheap cache key can detect."""
    cached = getattr(toas, "_write_parts_cache", None)
    if reuse_cache and cached is not None and cached[0] == (name, toas.ntoas):
        return None, cached[1]
    pairs = []
    for i in range(toas.ntoas):
        label = name or (toas.labels[i] if toas.labels else "toa")
        flag_str = "".join(
            f" -{k} {v}" for k, v in (toas.flags[i] if toas.flags else {}).items()
        )
        pre = f" {label} {toas.freqs_mhz[i]:.8f}"
        suf = f"{toas.errors_s[i]*1e6:.10g} {toas.observatories[i]}{flag_str}"
        # Control characters in metadata would corrupt FORMAT-1 output:
        # '\n' injects bogus records (the Python fallback would silently
        # write a malformed line), '\x1f' is the native writer's field
        # separator (it would abort mid-file, leaving a truncated tim),
        # '\r' splits lines on round-trip. Fail loudly before any file
        # byte is written.
        bad = pre + suf
        if "\n" in bad or "\x1f" in bad or "\r" in bad:
            raise ValueError(
                f"TOA {i}: label/observatory/flag metadata contains a "
                "control character (\\n, \\r, or \\x1f) that would corrupt "
                f"the tim file: {bad!r}"
            )
        pairs.append((pre, suf))
    if pairs_only:
        return pairs, None
    text = "".join(f"{p}\x1f{s}\n" for p, s in pairs).encode()
    if reuse_cache:
        toas._write_parts_cache = ((name, toas.ntoas), text)
    return pairs, text


def _mjd_day_frac15(mjd):
    """Split longdouble MJD epochs into (int day, int 1e-15-day fraction)
    — 86 ps resolution, exact to carry."""
    day = np.floor(mjd).astype(np.int64)
    frac = (mjd - day.astype(np.longdouble)) * np.longdouble(1e15)
    f15 = np.rint(frac).astype(np.int64)
    carry = f15 >= 10**15
    return day + carry, np.where(carry, 0, f15)


def write_tim(
    toas: TOAData,
    path: str,
    name: Optional[str] = None,
    reuse_static_parts: bool = False,
) -> None:
    """Serialize TOAs back to a Tempo2 ``FORMAT 1`` tim file.

    Reference analog: ``toas.write_TOA_file(outtim, format='Tempo2')``
    (/root/reference/pta_replicator/simulate.py:75). Uses the native
    (C++) writer when available — the egress mirror of the parse fast
    path — falling back to pure Python; both emit epochs at fixed
    15-decimal (86 ps) precision. ``reuse_static_parts``: opt-in cache of
    the epoch-invariant line parts for callers that guarantee only the
    epochs change between writes (see _static_line_parts).
    """
    from .native import fast_write_tim

    if toas.ntoas == 0:  # empty set: a valid header-only file
        with open(path, "w") as fh:
            fh.write("FORMAT 1\nMODE 1\n")
        return
    pairs, text = _static_line_parts(toas, name, reuse_cache=reuse_static_parts)
    day, f15 = _mjd_day_frac15(toas.mjd)
    if fast_write_tim(path, day, f15, text):
        return
    if pairs is None:  # cache hit (bytes only) but no native writer
        pairs, _ = _static_line_parts(toas, name, pairs_only=True)
    with open(path, "w") as fh:
        fh.write("FORMAT 1\nMODE 1\n")
        fh.writelines(
            f"{pre} {d}.{f:015d} {suf}\n"
            for (pre, suf), d, f in zip(pairs, day, f15)
        )


def fabricate_toas(
    mjds,
    error_us,
    freq_mhz=1440.0,
    observatory: str = "AXIS",
    flags: Optional[dict] = None,
) -> TOAData:
    """Build a synthetic evenly-specified TOA set.

    Reference analog: ``pint.simulation.make_fake_toas_fromMJDs`` as used by
    ``simulate_pulsar`` (/root/reference/pta_replicator/simulate.py:119-123).
    """
    mjds = np.asarray(mjds, dtype=np.longdouble)
    n = len(mjds)
    err = np.broadcast_to(np.asarray(error_us, dtype=np.float64) * 1e-6, (n,)).copy()
    frq = np.broadcast_to(np.asarray(freq_mhz, dtype=np.float64), (n,)).copy()
    flagdicts = [dict(flags) if flags else {} for _ in range(n)]
    return TOAData(
        mjd=mjds.copy(),
        errors_s=err,
        freqs_mhz=frq,
        observatories=[observatory] * n,
        flags=flagdicts,
        labels=["fake"] * n,
    )
