"""likelihood/ — rank-reduced GP likelihood engine + simulate-infer
serving (ROADMAP open item 1: the repo's first CONSUMER of the
realizations it synthesizes).

Three layers, bottom-up:

* :mod:`.gp` — the math: the rank-reduced Gaussian-process
  log-likelihood under the same noise model the injections use
  (white/ECORR/red-noise/GWB, timing model marginalized analytically),
  Woodbury-evaluated so the hot path is a small Cholesky over the
  reduced basis; a :class:`~.gp.ReducedGP` precompute for fixed-noise
  serving; a dense-covariance numpy oracle for tests.
* :mod:`.infer` — drivers: vmapped hyperparameter grids (auto-routed
  to the ReducedGP fast path), BFGS MAP fits with Fisher-matrix
  uncertainties, realization-bank evaluation sharded across the mesh.
* :mod:`.serve` — the service: request-batched evaluation over
  precomputed realization banks (sweep checkpoints loaded through the
  prefetch layer), deadline/size coalescing into device-shaped
  batches, per-request futures, SLO telemetry (latency percentiles,
  coalescing efficiency, evals/s) on the obs stack.

docs/likelihood.md walks the math and the serving model;
benchmarks/likelihood_serve.py is the bench ladder.
"""
from .gp import (
    ReducedGP,
    dense_loglikelihood,
    loglikelihood,
    phi_for_recipe,
)
from .infer import (
    MapResult,
    bank_loglikelihood,
    grid_cartesian,
    grid_loglikelihood,
    map_fit,
)
from .serve import (
    DeadlineExpired,
    LikelihoodServer,
    RealizationBank,
    ServerSaturated,
    project_bank,
)

__all__ = [
    "loglikelihood", "dense_loglikelihood", "ReducedGP",
    "phi_for_recipe",
    "grid_loglikelihood", "grid_cartesian", "bank_loglikelihood",
    "map_fit", "MapResult",
    "LikelihoodServer", "RealizationBank", "project_bank",
    "ServerSaturated", "DeadlineExpired",
]
