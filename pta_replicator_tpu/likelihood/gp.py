"""Rank-reduced Gaussian-process PTA log-likelihood.

The simulate side of the repo injects signals whose covariance is
exactly the rank-reduced model of ``models.batched.gls_noise_model``:

    C = N + U_ec diag(ecorr2) U_ec^T + U diag(phi) U^T

with N the EFAC/EQUAD white diagonal, U_ec the disjoint ECORR epoch
indicators, and U the stacked low-rank Fourier blocks (achromatic red
noise, chromatic noise, the injected GWB's per-pulsar auto-term) with
their power-law prior variances phi. This module closes the
simulate->infer loop (ROADMAP open item 1; the lightning-fast
rank-reduced likelihood of arXiv:2607.06834): the Gaussian
log-likelihood of residuals under that covariance, with the timing
model analytically marginalized, evaluated via the Woodbury identity so
the hot path is a small Cholesky over the rank-reduced basis — batched
(Nt x R) MXU contractions plus an (R, R) factorization per pulsar,
never an (Nt, Nt) dense solve.

Three evaluation tiers:

* :func:`loglikelihood` — the direct rank-reduced evaluation, jit- and
  vmap-safe over residuals AND over hyperparameter batches (every
  Recipe array leaf may be traced).
* :class:`ReducedGP` — the serving hot path: for grids/requests that
  hold the WHITE noise fixed (the common case — hyperparameter sweeps
  over red-noise/GWB amplitudes and slopes), every Nt-sized contraction
  is precomputed once (``T^T C0^-1 T``, and per-residual projections
  ``T^T C0^-1 r`` / ``r^T C0^-1 r``); each subsequent evaluation costs
  one (R, R) Cholesky per pulsar and nothing proportional to Nt at
  all. This is what lets a realization bank be priced at thousands of
  hyperparameter points per second (likelihood/serve.py).
* :func:`dense_loglikelihood` — the oracle-grade numpy float64
  reference: builds the dense (Nt, Nt) covariance per pulsar and pays
  the O(Nt^3) factorization. Exists for tests (the Woodbury path must
  match it to <= 1e-8 relative — tests/test_likelihood.py) and for
  nothing else.

Timing-model marginalization uses the exact flat-prior identity (not a
large-but-finite prior variance, which would wreck the conditioning of
the dense oracle it must be compared against):

    log L = -1/2 [ r^T C^-1 r - b^T A^-1 b + log det C + log det A
                   + (n - k) log 2pi ],
    A = M^T C^-1 M,  b = M^T C^-1 r

with M the (column-normalized) design tensor of
``timing.fit.design_tensor`` and k its per-pulsar non-padding column
count. Column normalization shifts log L by a hyperparameter-
independent constant (the flat-prior measure); both the Woodbury and
the dense paths use the same normalization, so they agree exactly and
likelihood *ratios* are unaffected.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.scipy.linalg import cho_solve, solve_triangular

from ..batch import PulsarBatch
from ..covariance.kernels import _chol_logdet
from ..models.batched import (
    Recipe,
    gls_noise_model,
    white_ecorr_parts,
    white_ecorr_solver,
)
from ..ops import pallas_gp
# numerics observatory: the (R, R)/(ktm, ktm) Cholesky diagonals below
# pass through identity probes so an indefinite S (NaN rows from f32
# conditioning loss) names its factorization site instead of surfacing
# as a silent NaN lnlike. Disarmed, probe_cholesky returns its factor
# untouched before importing jax machinery (obs/numerics.py).
from ..obs import numerics

_LOG_2PI = float(np.log(2.0 * np.pi))

#: Recipe fields that change the white/ECORR(/correlated-noise) block
#: C0 — a :class:`ReducedGP` precompute is only valid while these are
#: fixed (likelihood/infer.py routes grids over any of them to the
#: direct path instead). ``cov_log10_sigma`` scales the structured
#: ``noise_cov`` block, which lives inside C0.
WHITE_NOISE_FIELDS = frozenset(
    {"efac", "log10_equad", "log10_ecorr", "tnequad", "cov_log10_sigma"}
)

#: The numerics-observatory sites the fused Woodbury-assembly rung
#: writes (ops/pallas_gp.py outputs). The bf16 precision policy is
#: refused at runtime unless a capture's ladder verdict says every one
#: of these is ready — see :func:`require_precision_ready`.
FUSED_PRECISION_SITES = ("gp.fused_tnt", "gp.fused_d", "gp.fused_rnr")


class PrecisionNotReady(RuntimeError):
    """Raised when ``precision='bf16'`` is requested without a numerics
    capture whose ladder verdict clears every fused-kernel probe site
    (docs/numerics.md "the precision ladder"). The remedy is always the
    same: run the fused path armed (``numerics.arm()`` +
    ``numerics.write(dir)``) on representative data, then pass that
    capture via ``numerics_capture=``."""


def require_precision_ready(precision, numerics_capture=None):
    """Validate a ``precision=`` policy against the numerics
    observatory's ladder verdict — the runtime gate that makes bf16
    compute opt-in AND evidence-backed rather than a free-floating flag.

    ``precision='highest'`` (the default) always passes.
    ``precision='bf16'`` requires ``numerics_capture``: a directory
    containing (or a path to) a ``numerics.json`` written by an armed
    run of the fused path. The capture's
    :func:`~pta_replicator_tpu.obs.numerics.ladder_verdict` must mark
    every :data:`FUSED_PRECISION_SITES` entry ready (zero non-finites,
    >= 8 bits of bf16 headroom, family drift within tolerance);
    otherwise :class:`PrecisionNotReady` names the failing sites and
    reasons. Returns the validated policy string."""
    if precision in (None, "highest"):
        return "highest"
    if precision != "bf16":
        raise ValueError(
            f"unknown precision policy {precision!r}: expected one of "
            f"{pallas_gp.PRECISIONS}"
        )
    if numerics_capture is None:
        raise PrecisionNotReady(
            "precision='bf16' needs evidence: pass numerics_capture= a "
            "numerics.json (or its directory) written by an armed run "
            "of the fused path, so the ladder verdict for "
            f"{FUSED_PRECISION_SITES} can be checked"
        )
    import json
    import os

    path = os.fspath(numerics_capture)
    if os.path.isdir(path):
        path = os.path.join(path, "numerics.json")
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError) as exc:
        raise PrecisionNotReady(
            f"numerics capture {path!r} is unreadable ({exc}); rerun "
            "the fused path armed and write a fresh capture"
        ) from exc
    verdict = numerics.ladder_verdict(doc)
    missing = [s for s in FUSED_PRECISION_SITES if s not in verdict]
    if missing:
        raise PrecisionNotReady(
            f"numerics capture {path!r} never observed fused sites "
            f"{missing} — it must come from an armed run of the fused "
            "path itself, not an unrelated capture"
        )
    blocked = {
        s: verdict[s]["reasons"]
        for s in FUSED_PRECISION_SITES
        if not verdict[s]["ready"]
    }
    if blocked:
        raise PrecisionNotReady(
            f"ladder verdict refuses bf16 for {sorted(blocked)}: "
            f"{blocked}"
        )
    return "bf16"


def _resolve_fused_backend(backend: str) -> str:
    """'auto' -> the platform's native rung ('pallas' on TPU, tiled
    'xla' elsewhere) — same routing contract as
    covariance.kernels.blocked_cholesky."""
    if backend == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    if backend not in ("xla", "pallas", "pallas_interpret"):
        raise ValueError(
            f"unknown fused backend {backend!r}: expected 'auto', "
            "'xla', 'pallas' or 'pallas_interpret'"
        )
    return backend


def _fused_assembly(T, winv, gain, seg_sum, r, tile, precision, backend):
    """One fused pass over the TOA axis: ``T^T C0^-1 T``, ``T^T C0^-1
    r`` and ``r^T C0^-1 r`` without ever materializing ``C0^-1 T``
    (the (Nt, Q) intermediate of the composed build).

    The kernel (ops/pallas_gp.py) prices the diagonal-N part by
    accumulating (tile, Q) slabs; the per-epoch ECORR Woodbury
    correction is exact O(E) algebra applied here OUTSIDE the kernel —
    epochs are irregular segments, so the correction is a segment-sum
    (``white_ecorr_parts``'s operator, the SAME algebra the composed
    solver applies) followed by three small (E, Q) contractions. The
    bf16 policy applies to the kernel's O(Nt Q^2) bulk; the O(E Q^2)
    correction and everything downstream stay at the accumulator
    dtype."""
    if backend == "pallas":
        tnt, d, rnr = pallas_gp.fused_woodbury_update(
            T, winv, r, tile=tile, precision=precision
        )
    elif backend == "pallas_interpret":
        tnt, d, rnr = pallas_gp.fused_woodbury_update(
            T, winv, r, tile=tile, precision=precision, interpret=True
        )
    else:
        tnt, d, rnr = pallas_gp.fused_woodbury_xla(
            T, winv, r, tile=tile, precision=precision
        )
    if gain is not None:
        acc = tnt.dtype
        S = seg_sum(winv[..., None] * T).astype(acc)  # (Np, E, Q)
        s_r = seg_sum((winv * r)[..., None])[..., 0].astype(acc)
        g = gain.astype(acc)
        tnt = tnt - jnp.einsum(
            "peq,pe,pes->pqs", S, g, S, precision="highest"
        )
        d = d - jnp.einsum("peq,pe->pq", S, g * s_r, precision="highest")
        rnr = rnr - jnp.sum(g * s_r * s_r, axis=-1)
    # numerics observatory: the fused outputs are exactly the blocks
    # the reduced likelihood consumes — probing them (identity when
    # disarmed) is what gives the bf16 ladder verdict its evidence.
    tnt = numerics.probe("gp.fused_tnt", tnt)
    d = numerics.probe("gp.fused_d", d)
    rnr = numerics.probe("gp.fused_rnr", rnr)
    return tnt, d, rnr


def _tm_columns(batch: PulsarBatch, design, dtype):
    """Masked, column-normalized timing design: ``(Mn, zero_col)``.

    Norms are UNWEIGHTED (hyperparameter-independent), so the
    normalization constant they fold into log L cannot drift across a
    grid; all-zero padding columns get unit norms and are neutralized
    by the callers (unit diagonal in A, zero rhs — they solve to
    exactly nothing and price log det 1 = 0)."""
    M = jnp.asarray(design, dtype) * batch.mask[..., None]
    norms = jnp.sqrt(jnp.sum(M * M, axis=-2))
    zero_col = norms == 0.0
    norms = jnp.where(zero_col, 1.0, norms)
    return M / norms[:, None, :], zero_col


def loglikelihood(
    residuals,
    batch: PulsarBatch,
    recipe: Recipe,
    design=None,
    per_pulsar: bool = False,
):
    """Rank-reduced GP log-likelihood of ``residuals`` (Np, Nt) under
    the recipe's own noise model.

    ``design``: optional (Np, Nt, K) timing design tensor
    (timing.fit.design_tensor) to marginalize analytically (flat
    prior); padding (all-zero) columns are inert. ``per_pulsar``
    returns the (Np,) per-pulsar terms instead of their sum (the
    likelihood factorizes over pulsars — cross-pulsar GWB correlations
    are not modeled, matching the GLS refit's weighting).

    Pure JAX: jit it, vmap it over residual banks, vmap it over
    hyperparameter batches (traced Recipe leaves) — likelihood/infer.py
    wraps all three. Every contraction runs at ``precision='highest'``
    for the same reason the GLS refit does (the TPU bf16 default leaves
    ~1e-2 relative error on Gram entries).
    """
    from ..covariance.structure import recipe_cov_s2

    dtype = jnp.asarray(residuals).dtype
    sigma2, ecorr2, U, phi = gls_noise_model(batch, recipe)
    _winv, c0inv, logdet_c0 = white_ecorr_solver(
        batch, sigma2, ecorr2, dtype,
        extra=recipe.noise_cov,
        extra_s2=recipe_cov_s2(recipe, dtype),
    )
    r = jnp.asarray(residuals, dtype) * batch.mask
    x0 = c0inv(r[..., None])[..., 0]  # C0^-1 r, (Np, Nt)
    quad = jnp.einsum("pn,pn->p", r, x0, precision="highest")
    logdet = logdet_c0

    if U is not None:
        # phi=0 modes must be exactly inert (same zeroing as
        # _gls_design_system: the phi->0 limit is an infinite 1/phi
        # prior; zeroed basis columns + unit S/Phi diagonals contribute
        # exactly nothing to the quad or either determinant)
        active = (phi > 0).astype(dtype)
        U = U * active[:, None, :]
        G = c0inv(U)  # C0^-1 U, (Np, Nt, R)
        S = jnp.einsum("pnr,pns->prs", U, G, precision="highest")
        phi_safe = jnp.where(phi > 0, phi, 1.0)
        S = S + jnp.eye(U.shape[-1], dtype=dtype) / phi_safe[:, None, :]
        L = jnp.linalg.cholesky(S)  # graftlint: disable=cov-f32-cholesky  # caller-dtype by design: the rank-reduced hot path runs at the residual dtype; f32 use is validated against the f64 dense oracle (tests/test_likelihood.py) and map_fit documents its f64 requirement
        L = numerics.probe_cholesky("gp.chol_rank", L)
        b = jnp.einsum("pnr,pn->pr", U, x0, precision="highest")
        z = solve_triangular(L, b[..., None], lower=True)[..., 0]  # graftlint: disable=cov-f32-cholesky  # same oracle-pinned contract as the factor above
        quad = quad - jnp.sum(z * z, axis=-1)
        # log det C = log det C0 + log det S + log det Phi
        logdet = logdet + _chol_logdet(L) + jnp.sum(
            jnp.log(phi_safe) * active, axis=-1
        )

        def cinv_mat(X):
            X0 = c0inv(X)
            inner = jnp.einsum(
                "pnr,pnq->prq", U, X0, precision="highest"
            )
            corr = cho_solve((L, True), inner)
            return X0 - jnp.einsum(
                "pnr,prq->pnq", G, corr, precision="highest"
            )

        w = x0 - jnp.einsum(
            "pnr,pr->pn", G, cho_solve((L, True), b[..., None])[..., 0],
            precision="highest",
        )  # C^-1 r
    else:
        cinv_mat = c0inv
        w = x0

    ndof = batch.ntoas.astype(dtype)
    if design is not None:
        Mn, zero_col = _tm_columns(batch, design, dtype)
        K = Mn.shape[-1]
        CiM = cinv_mat(Mn)
        A = jnp.einsum("pnk,pnl->pkl", Mn, CiM, precision="highest")
        A = A + jnp.eye(K, dtype=dtype) * zero_col[:, None, :].astype(
            dtype
        )
        La = jnp.linalg.cholesky(A)  # graftlint: disable=cov-f32-cholesky  # caller-dtype by design: the rank-reduced hot path runs at the residual dtype; f32 use is validated against the f64 dense oracle (tests/test_likelihood.py) and map_fit documents its f64 requirement
        La = numerics.probe_cholesky("gp.chol_tm", La)
        bm = jnp.einsum("pnk,pn->pk", Mn, w, precision="highest")
        zm = solve_triangular(La, bm[..., None], lower=True)[..., 0]  # graftlint: disable=cov-f32-cholesky  # same oracle-pinned contract as the factor above
        quad = quad - jnp.sum(zm * zm, axis=-1)
        logdet = logdet + _chol_logdet(La)
        ndof = ndof - jnp.sum((~zero_col).astype(dtype), axis=-1)

    ll = -0.5 * (quad + logdet + ndof * dtype.type(_LOG_2PI))
    return ll if per_pulsar else jnp.sum(ll)


# ----------------------------------------------------- serving hot path

@jax.tree_util.register_dataclass
@dataclass
class GPProjection:
    """One residual vector's Nt-sized reductions against a
    :class:`ReducedGP`'s fixed C0 and basis: everything a likelihood
    evaluation needs that touches the TOA axis. Computed once per
    residual vector (per bank row), reused by every hyperparameter
    evaluation after."""

    #: (Np,) r^T C0^-1 r
    rNr: jax.Array
    #: (Np, Q) T^T C0^-1 r over the full column stack [Mn, U]
    d: jax.Array


def shard_projection(proj: GPProjection, mesh) -> GPProjection:
    """Place a bank's projections sharded along the mesh 'real' axis
    (realization-bank parallelism). The ONE sharding layout for
    projections — serve.project_bank and infer.bank_loglikelihood both
    route through it, so the handle path and the raw-array path cannot
    diverge. No-op on a single-device (or absent) mesh."""
    if mesh is None or int(mesh.devices.size) <= 1:
        return proj
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import put_sharded

    return GPProjection(
        rNr=put_sharded(proj.rNr, mesh, P("real", None)),
        d=put_sharded(proj.d, mesh, P("real", None, None)),
    )


@jax.tree_util.register_dataclass
@dataclass
class ReducedGP:
    """Precomputed rank-reduced likelihood with FIXED white/ECORR noise.

    Build once per (batch, recipe, design); :meth:`project` each
    residual vector once (the only Nt-sized work); then
    :meth:`loglikelihood` prices any (red-noise/GWB/chromatic)
    hyperparameter point from the precomputed blocks alone — one
    (R, R) Cholesky per pulsar, nothing proportional to Nt. A pytree,
    so it passes through jit/vmap boundaries and shards like any other
    operand (likelihood/infer.py places the projection bank on the
    mesh's 'real' axis for realization-bank parallelism).

    The GP blocks' BASIS is fixed at build time (mode counts, Tspan,
    frequency grids); only the prior variances phi move with the
    hyperparameters. That covers amplitude/slope grids — the serving
    workload — exactly; grids over :data:`WHITE_NOISE_FIELDS` or over
    basis shape invalidate the precompute and must use
    :func:`loglikelihood` (infer.py enforces this).
    """

    #: (Np, Q, Q) T^T C0^-1 T over the stacked columns [Mn, U]
    TNT: jax.Array
    #: (Np, Nt, Q) C0^-1 T — the projector applied to residual
    #: vectors. None on the fused rung, whose whole point is never
    #: materializing it (:meth:`project` then uses the retained ``T``
    #: and the O(Nt) direct C0^-1 apply instead).
    CiT: Optional[jax.Array]
    #: (Np,) masked log det C0
    logdet_c0: jax.Array
    #: (Np, Nt) white per-TOA variance and (Np, E) per-epoch ECORR
    #: variance (None without ECORR): the C0 inputs, retained so
    #: :meth:`project` rebuilds the operator through the ONE shared
    #: ``white_ecorr_solver`` instead of duplicating its algebra
    sigma2: jax.Array
    ecorr2: Optional[jax.Array]
    #: (Np, ktm) True where a timing column is padding (inert)
    zero_col: Optional[jax.Array]
    #: (Np,) valid-TOA count minus fitted timing columns
    ndof: jax.Array
    #: structured correlated-noise block (a covariance CovOp) and its
    #: frozen amplitude 10^(2 cov_log10_sigma): part of C0, retained so
    #: :meth:`project` rebuilds the SAME generalized solver the build
    #: used (grids over cov_log10_sigma invalidate the precompute —
    #: WHITE_NOISE_FIELDS routes them to the direct path)
    extra: Optional[object] = None
    extra_s2: Optional[jax.Array] = None
    #: (Np, Nt, Q) stacked column basis [Mn, U] — retained ONLY on the
    #: fused rung (where CiT is None) so :meth:`project` can form
    #: T^T C0^-1 r directly; None on the composed path
    T: Optional[jax.Array] = None
    #: number of leading timing-model columns in the stack
    ktm: int = field(metadata=dict(static=True), default=0)
    #: True when built by :meth:`build_fused` (routes :meth:`project`
    #: through the direct O(Nt) apply instead of CiT)
    fused: bool = field(metadata=dict(static=True), default=False)
    #: fused-kernel compute policy ('highest' | 'bf16'); 'highest'
    #: everywhere off the fused rung
    precision: str = field(metadata=dict(static=True), default="highest")
    #: fused-kernel TOA tile size (likelihood/tuner.py picks it)
    tile: int = field(
        metadata=dict(static=True), default=pallas_gp.DEFAULT_WOODBURY_TILE
    )
    #: fused-kernel backend ('xla' | 'pallas' | 'pallas_interpret')
    backend: str = field(metadata=dict(static=True), default="xla")

    @classmethod
    def build(
        cls,
        batch: PulsarBatch,
        recipe: Recipe,
        design=None,
        dtype=None,
        fused: bool = False,
        precision: str = "highest",
        tile: Optional[int] = None,
        backend: str = "auto",
    ) -> "ReducedGP":
        """Precompute every Nt-sized block. ``recipe`` fixes the white/
        ECORR noise AND the GP basis layout; its phi values are not
        retained (evaluations supply their own via
        :func:`phi_for_recipe`).

        ``fused=True`` (or a non-default ``precision``) routes through
        :meth:`build_fused` — same blocks, one fused kernel pass, no
        (Np, Nt, Q) ``CiT`` intermediate. The default path below is
        bitwise unchanged."""
        from ..covariance.structure import recipe_cov_s2

        if fused or precision != "highest":
            reduced, _proj = cls.build_fused(
                batch, recipe, design=design, dtype=dtype,
                precision=precision, tile=tile, backend=backend,
            )
            return reduced
        if dtype is None:
            dtype = batch.toas_s.dtype
        sigma2, ecorr2, U, phi = gls_noise_model(batch, recipe)
        extra = recipe.noise_cov
        extra_s2 = recipe_cov_s2(recipe, dtype)
        _winv, c0inv, logdet_c0 = white_ecorr_solver(
            batch, sigma2, ecorr2, dtype, extra=extra, extra_s2=extra_s2
        )
        cols = []
        zero_col = None
        ktm = 0
        if design is not None:
            Mn, zero_col = _tm_columns(batch, design, dtype)
            ktm = Mn.shape[-1]
            cols.append(Mn)
        if U is not None:
            cols.append(jnp.asarray(U, dtype))
        if not cols:
            raise ValueError(
                "ReducedGP needs at least one low-rank block (a GP "
                "noise term in the recipe or a design tensor) — a "
                "white-noise-only likelihood has no reduced basis; "
                "call loglikelihood directly"
            )
        T = jnp.concatenate(cols, axis=-1)
        CiT = c0inv(T)
        TNT = jnp.einsum("pnq,pns->pqs", T, CiT, precision="highest")
        ndof = batch.ntoas.astype(dtype)
        if zero_col is not None:
            ndof = ndof - jnp.sum((~zero_col).astype(dtype), axis=-1)
        return cls(
            TNT=TNT, CiT=CiT, logdet_c0=logdet_c0,
            sigma2=jnp.asarray(sigma2, dtype),
            ecorr2=None if ecorr2 is None else jnp.asarray(ecorr2, dtype),
            zero_col=zero_col, ndof=ndof, extra=extra,
            extra_s2=extra_s2, ktm=ktm,
        )

    @classmethod
    def build_fused(
        cls,
        batch: PulsarBatch,
        recipe: Recipe,
        residuals=None,
        design=None,
        dtype=None,
        precision: str = "highest",
        tile: Optional[int] = None,
        backend: str = "auto",
    ):
        """The fused rung of the speed ladder: one kernel pass
        (ops/pallas_gp.py) assembles ``T^T C0^-1 T`` — and, when
        ``residuals`` is given, ``T^T C0^-1 r`` / ``r^T C0^-1 r`` in
        the same pass — without materializing the (Np, Nt, Q) ``CiT``
        intermediate the composed :meth:`build` pays for. Returns
        ``(ReducedGP, GPProjection or None)``.

        ``precision='bf16'`` runs the kernel's O(Nt Q^2) contractions
        in bf16 with f32 accumulation; callers gate it through
        :func:`require_precision_ready` first (likelihood/infer.py
        does). ``tile=None`` asks likelihood/tuner.py for the cached
        roofline-tuned tile (falling back to the default constant
        untuned). Only the analytic white+ECORR C0 is fusable — a
        structured ``noise_cov`` block raises (the composed build
        handles it)."""
        if recipe.noise_cov is not None:
            raise ValueError(
                "the fused Woodbury rung prices the analytic white/"
                "ECORR C0 only; a recipe with a structured noise_cov "
                "block must use the composed ReducedGP.build"
            )
        if dtype is None:
            dtype = batch.toas_s.dtype
        backend = _resolve_fused_backend(backend)
        if tile is None:
            from .tuner import woodbury_tile

            tile = woodbury_tile(batch, backend)
        sigma2, ecorr2, U, _phi = gls_noise_model(batch, recipe)
        winv, seg_sum, gain, logdet_c0 = white_ecorr_parts(
            batch, sigma2, ecorr2, dtype
        )
        winv = numerics.probe("solver.winv", winv)
        logdet_c0 = numerics.probe("solver.logdet_c0", logdet_c0)
        cols = []
        zero_col = None
        ktm = 0
        if design is not None:
            Mn, zero_col = _tm_columns(batch, design, dtype)
            ktm = Mn.shape[-1]
            cols.append(Mn)
        if U is not None:
            cols.append(jnp.asarray(U, dtype))
        if not cols:
            raise ValueError(
                "ReducedGP needs at least one low-rank block (a GP "
                "noise term in the recipe or a design tensor) — a "
                "white-noise-only likelihood has no reduced basis; "
                "call loglikelihood directly"
            )
        T = jnp.concatenate(cols, axis=-1)
        if residuals is None:
            r = jnp.zeros(batch.mask.shape, dtype)
        else:
            r = jnp.asarray(residuals, dtype) * batch.mask
        TNT, d, rNr = _fused_assembly(
            T, winv, gain, seg_sum, r, tile, precision, backend
        )
        ndof = batch.ntoas.astype(dtype)
        if zero_col is not None:
            ndof = ndof - jnp.sum((~zero_col).astype(dtype), axis=-1)
        reduced = cls(
            TNT=TNT, CiT=None, logdet_c0=logdet_c0,
            sigma2=jnp.asarray(sigma2, dtype),
            ecorr2=None if ecorr2 is None else jnp.asarray(ecorr2, dtype),
            zero_col=zero_col, ndof=ndof, extra=None, extra_s2=None,
            T=T, ktm=ktm, fused=True, precision=precision,
            tile=int(tile), backend=backend,
        )
        proj = None if residuals is None else GPProjection(rNr=rNr, d=d)
        return reduced, proj

    @property
    def ngp(self) -> int:
        return int(self.TNT.shape[-1]) - self.ktm

    def project(self, residuals, batch: PulsarBatch) -> GPProjection:
        """The Nt-sized reductions of one (Np, Nt) residual vector.
        vmap over the leading axis of a (R, Np, Nt) bank to project a
        whole realization bank in one pass. The C0^-1 apply comes from
        the same :func:`white_ecorr_solver` the build used (rebuilt
        from the retained sigma2/ecorr2 — free under jit), so the
        projection and the precompute cannot price different C0s."""
        if self.fused:
            # fused rung: CiT was never materialized. T^T C0^-1 r via
            # the O(Nt) direct apply y = C0^-1 r (white_ecorr_parts —
            # the SAME algebra the kernel assembly corrected with),
            # then one (Nt, Q) contraction against the retained T.
            dtype = self.T.dtype
            winv, seg_sum, gain, _ld = white_ecorr_parts(
                batch, self.sigma2, self.ecorr2, dtype
            )
            r = jnp.asarray(residuals, dtype) * batch.mask
            y = winv * r
            if gain is not None:
                s_r = seg_sum(y[..., None])[..., 0]
                picked = jnp.take_along_axis(
                    gain * s_r, batch.epoch_index, axis=1
                )
                y = y - winv * picked
            rNr = jnp.einsum("pn,pn->p", r, y, precision="highest")
            d = jnp.einsum("pnq,pn->pq", self.T, y, precision="highest")
            if self.precision == "bf16":
                # match the kernel's f32 accumulator dtype so banked
                # and build-time projections agree exactly
                rNr = rNr.astype(jnp.float32)
                d = d.astype(jnp.float32)
            rNr = numerics.probe("gp.fused_rnr", rNr)
            d = numerics.probe("gp.fused_d", d)
            return GPProjection(rNr=rNr, d=d)
        dtype = self.CiT.dtype
        _winv, c0inv, _logdet = white_ecorr_solver(
            batch, self.sigma2, self.ecorr2, dtype,
            extra=self.extra, extra_s2=self.extra_s2,
        )
        r = jnp.asarray(residuals, dtype) * batch.mask
        y = c0inv(r[..., None])[..., 0]
        rNr = jnp.einsum("pn,pn->p", r, y, precision="highest")
        # C0^-1 is symmetric: T^T C0^-1 r == (C0^-1 T)^T r
        d = jnp.einsum("pnq,pn->pq", self.CiT, r, precision="highest")
        return GPProjection(rNr=rNr, d=d)

    def loglikelihood(
        self, proj: GPProjection, phi, per_pulsar: bool = False
    ):
        """log L of one projected residual vector at GP prior ``phi``
        (Np, ngp) — :func:`phi_for_recipe` evaluates it for a
        hyperparameter point. No Nt-sized work: two small Cholesky
        factorizations per pulsar ((R, R) and (ktm, ktm)), identical in
        value to :func:`loglikelihood` on the raw residuals (pinned by
        tests/test_likelihood.py)."""
        dtype = self.TNT.dtype
        k = self.ktm
        phi = jnp.asarray(phi, dtype)
        active = (phi > 0).astype(dtype)
        phi_safe = jnp.where(phi > 0, phi, 1.0)
        TNT_uu = self.TNT[:, k:, k:] * (
            active[:, :, None] * active[:, None, :]
        )
        S = TNT_uu + jnp.eye(self.ngp, dtype=dtype) / phi_safe[:, None, :]
        L = jnp.linalg.cholesky(S)  # graftlint: disable=cov-f32-cholesky  # caller-dtype by design: the rank-reduced hot path runs at the residual dtype; f32 use is validated against the f64 dense oracle (tests/test_likelihood.py) and map_fit documents its f64 requirement
        L = numerics.probe_cholesky("gp.reduced_chol_rank", L)
        d_u = proj.d[:, k:] * active
        z = solve_triangular(L, d_u[..., None], lower=True)[..., 0]  # graftlint: disable=cov-f32-cholesky  # same oracle-pinned contract as the factor above
        quad = proj.rNr - jnp.sum(z * z, axis=-1)
        logdet = self.logdet_c0 + _chol_logdet(L) + jnp.sum(
            jnp.log(phi_safe) * active, axis=-1
        )
        if k:
            TNT_mu = self.TNT[:, :k, k:] * active[:, None, :]
            X = cho_solve((L, True), jnp.swapaxes(TNT_mu, -1, -2))
            A = self.TNT[:, :k, :k] - jnp.einsum(
                "pkr,prl->pkl", TNT_mu, X, precision="highest"
            )
            A = A + jnp.eye(k, dtype=dtype) * self.zero_col[
                :, None, :
            ].astype(dtype)
            La = jnp.linalg.cholesky(A)  # graftlint: disable=cov-f32-cholesky  # caller-dtype by design: the rank-reduced hot path runs at the residual dtype; f32 use is validated against the f64 dense oracle (tests/test_likelihood.py) and map_fit documents its f64 requirement
            La = numerics.probe_cholesky("gp.reduced_chol_tm", La)
            bm = proj.d[:, :k] - jnp.einsum(
                "pkr,pr->pk", TNT_mu,
                cho_solve((L, True), d_u[..., None])[..., 0],
                precision="highest",
            )
            zm = solve_triangular(La, bm[..., None], lower=True)[..., 0]  # graftlint: disable=cov-f32-cholesky  # same oracle-pinned contract as the factor above
            quad = quad - jnp.sum(zm * zm, axis=-1)
            logdet = logdet + _chol_logdet(La)
        ll = -0.5 * (quad + logdet + self.ndof * dtype.type(_LOG_2PI))
        return ll if per_pulsar else jnp.sum(ll)


def phi_for_recipe(batch: PulsarBatch, recipe: Recipe):
    """The stacked GP prior variances (Np, R) of ``recipe``'s noise
    model — the only piece of :func:`gls_noise_model` a hyperparameter
    point moves when the white noise and basis layout are fixed. Under
    jit the (Np, Nt, R) basis feeding the discarded U output is dead
    code (phi depends only on the frequency grids), so this costs
    O(Np x R), not O(Np x Nt x R)."""
    _sigma2, _ecorr2, U, phi = gls_noise_model(batch, recipe)
    if U is None:
        raise ValueError(
            "recipe has no GP noise block (red noise, chromatic, or "
            "GWB) — nothing for phi_for_recipe to evaluate"
        )
    return phi


# ------------------------------------------------------------- oracle

def dense_loglikelihood(
    residuals,
    batch: PulsarBatch,
    recipe: Recipe,
    design=None,
    per_pulsar: bool = False,
):
    """Oracle-grade dense-covariance reference: numpy float64, one
    explicit (n, n) covariance Cholesky per pulsar.

    The covariance comes from the ONE shared dense assembler
    (:func:`~pta_replicator_tpu.covariance.structure.
    dense_noise_covariance`) — C = N + U_ec diag(ecorr2) U_ec^T +
    U diag(phi) U^T + s2 X, built from the same
    :func:`gls_noise_model` components (and the same CovOp) the
    Woodbury/structured paths consume, so the oracle and the engine
    can never disagree about C. What this verifies is the ENTIRE
    rank-reduced evaluation (analytic ECORR inversion, Woodbury quad/
    determinant, the structured correlated-noise solve, exact
    timing-model marginalization), while the components themselves are
    validated against the enterprise-convention dense oracle in
    tests/test_batched.py. O(Nt^3): tests only.
    """
    from ..covariance.structure import dense_noise_covariance

    C_all = dense_noise_covariance(batch, recipe)
    r_all = np.asarray(residuals, np.float64)
    mask = np.asarray(batch.mask)
    design = None if design is None else np.asarray(design, np.float64)

    out = np.zeros(batch.npsr)
    for p in range(batch.npsr):
        idx = np.nonzero(mask[p] > 0)[0]
        n = idx.size
        r = r_all[p, idx]
        C = C_all[p][np.ix_(idx, idx)]
        # graftlint: disable=cov-f32-cholesky  # numpy-float64 oracle by construction (dense_noise_covariance returns f64)
        L = np.linalg.cholesky(C)
        half = np.linalg.solve(L, r)
        quad = float(half @ half)
        logdet = 2.0 * float(np.sum(np.log(np.diag(L))))
        ndof = float(n)
        if design is not None:
            M = design[p][idx] * mask[p, idx][:, None]
            norms = np.sqrt(np.sum((design[p] * mask[p][:, None]) ** 2,
                                   axis=0))
            keep = norms > 0.0
            Mn = M[:, keep] / norms[keep][None, :]
            k = int(keep.sum())
            MnL = np.linalg.solve(L, Mn)
            rL = half
            A = MnL.T @ MnL
            bm = MnL.T @ rL
            # graftlint: disable=cov-f32-cholesky  # numpy-float64 oracle (design cast to f64 above)
            La = np.linalg.cholesky(A)
            zm = np.linalg.solve(La, bm)
            quad -= float(zm @ zm)
            logdet += 2.0 * float(np.sum(np.log(np.diag(La))))
            ndof -= k
        out[p] = -0.5 * (quad + logdet + ndof * _LOG_2PI)
    return out if per_pulsar else float(out.sum())
