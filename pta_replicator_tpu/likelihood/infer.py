"""Inference drivers over the rank-reduced GP likelihood.

The rapid-inference shape of arXiv:2412.13379 on top of
``likelihood/gp.py``: batched evaluation over hyperparameter grids
(vmapped, with the ReducedGP fast path whenever the grid holds the
white noise fixed), a gradient-based MAP fit with a Fisher-matrix
uncertainty estimate, and realization-bank evaluation sharded across
the device mesh ('real' axis — the same realization parallelism every
other workload in the repo scales on).

Hyperparameter axes are named Recipe fields with SCALAR values — a
grid is ``{"rn_log10_amplitude": (G,) array, ...}`` with every axis
the same length G (use :func:`grid_cartesian` to flatten a mesh of
1-D axes into aligned arrays). Structural Recipe switches (mode
counts, convention flags) are static and cannot be grid axes.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..batch import PulsarBatch
from ..models.batched import Recipe
from . import gp


def _check_axes(names: Tuple[str, ...]):
    for name in names:
        if name not in Recipe.__dataclass_fields__:
            raise ValueError(f"{name!r} is not a Recipe field")
        meta = Recipe.__dataclass_fields__[name].metadata
        if meta and meta.get("static"):
            raise ValueError(
                f"{name!r} is a static Recipe switch — it changes the "
                "compiled program and cannot be a hyperparameter axis"
            )


def _replace(recipe: Recipe, names: Tuple[str, ...], values) -> Recipe:
    return dataclasses.replace(recipe, **dict(zip(names, values)))


def grid_cartesian(axes: Dict[str, object]) -> Tuple[dict, tuple]:
    """Cartesian product of 1-D axes -> aligned flat arrays + the mesh
    shape (to reshape the flat (G,) results back into the grid)."""
    names = tuple(axes)
    arrs = [np.atleast_1d(np.asarray(axes[k])) for k in names]
    mesh = np.meshgrid(*arrs, indexing="ij")
    shape = mesh[0].shape if mesh else ()
    return {k: m.reshape(-1) for k, m in zip(names, mesh)}, shape


def _reducible(names: Tuple[str, ...], recipe: Recipe) -> bool:
    """True when the grid can ride the ReducedGP fast path: white/ECORR
    noise fixed, and every moving field feeds only the GP priors phi
    (amplitudes/slopes of blocks the recipe already enables)."""
    phi_fields = {
        "rn_log10_amplitude", "rn_gamma",
        "chrom_log10_amplitude", "chrom_gamma",
        "gwb_log10_amplitude", "gwb_gamma",
    }
    if not set(names) <= phi_fields:
        return False
    # a moving amplitude whose block is OFF in the base recipe would
    # change the basis layout itself — not phi-only
    for name in names:
        if getattr(recipe, name) is None:
            return False
    return recipe.rn_log10_amplitude is not None or (
        recipe.chrom_log10_amplitude is not None
    ) or (
        recipe.gwb_log10_amplitude is not None
        or recipe.gwb_user_spectrum is not None
    )


@functools.lru_cache(maxsize=None)
def _direct_grid_engine(names: Tuple[str, ...], per_pulsar: bool):
    """Jitted vmap of the DIRECT likelihood over a (G, P) theta block
    (full noise-model rebuild per point — any Recipe array leaf may
    move, including white noise)."""
    from ..obs import instrumented_jit
    from ..obs import names as n

    def run(theta, residuals, batch, recipe, design):
        def one(th):
            return gp.loglikelihood(
                residuals, batch, _replace(recipe, names, list(th)),
                design=design, per_pulsar=per_pulsar,
            )

        return jax.vmap(one)(theta)

    return instrumented_jit(
        run, name=n.JIT_LIKELIHOOD_ENGINE, retrace_warn=32,
    )


@functools.lru_cache(maxsize=None)
def _fused_build_engine(precision: str, tile: int, backend: str):
    """Jitted fused ReducedGP precompute+projection (rung 1 of the
    raw-speed ladder): ONE kernel pass over the TOA axis assembles
    T^T C0^-1 T / T^T C0^-1 r / r^T C0^-1 r (ops/pallas_gp.py via
    ``ReducedGP.build_fused``) — no (Np, Nt, Q) CiT intermediate.
    Labelled ``gp.fused_woodbury`` so devprof cost/roofline accounting
    attributes the fused kernels. Runs once per grid/bank call; the
    per-point evaluation then rides the SAME reduced engine as the
    composed path."""
    from ..obs import instrumented_jit
    from ..obs import names as n

    def run(residuals, batch, recipe, design):
        return gp.ReducedGP.build_fused(
            batch, recipe, residuals=residuals, design=design,
            dtype=None if residuals is None else residuals.dtype,
            precision=precision, tile=tile, backend=backend,
        )

    return instrumented_jit(
        run, name=n.JIT_GP_FUSED_WOODBURY, retrace_warn=32,
    )


def _resolve_fused(batch, recipe, fused, precision, tile, backend,
                   numerics_capture):
    """Shared fused-path argument resolution for the grid/bank
    drivers: validate the precision policy against the numerics
    ladder verdict (:func:`~.gp.require_precision_ready` — bf16 is
    refused without capture evidence), resolve 'auto' to the
    platform backend, and look the tile up in the autotuner cache
    (pure lookup; defaults when untuned). Returns the resolved
    ``(fused, precision, tile, backend)`` with everything host-side
    concrete (engine cache keys)."""
    precision = gp.require_precision_ready(precision, numerics_capture)
    fused = bool(fused) or precision != "highest"
    if not fused:
        return False, "highest", None, None
    if recipe.noise_cov is not None:
        raise ValueError(
            "fused=True prices the analytic white/ECORR C0 only; a "
            "recipe with a structured noise_cov block must use the "
            "composed path (fused=False)"
        )
    backend = gp._resolve_fused_backend(backend)
    if tile is None:
        from .tuner import woodbury_tile

        tile = woodbury_tile(batch, backend)
    return True, precision, int(tile), backend


@functools.lru_cache(maxsize=None)
def _reduced_grid_engine(names: Tuple[str, ...], per_pulsar: bool):
    """Jitted vmap of the ReducedGP fast path over a (G, P) theta
    block: per point, only the phi priors are re-evaluated (the basis
    feeding gls_noise_model's discarded outputs is dead code under
    jit) and the small Cholesky runs."""
    from ..obs import instrumented_jit
    from ..obs import names as n

    def run(theta, reduced, proj, batch, recipe):
        def one(th):
            phi = gp.phi_for_recipe(
                batch, _replace(recipe, names, list(th))
            )
            return reduced.loglikelihood(proj, phi, per_pulsar=per_pulsar)

        return jax.vmap(one)(theta)

    return instrumented_jit(
        run, name=n.JIT_LIKELIHOOD_REDUCED_ENGINE, retrace_warn=32,
    )


def _theta_block(grid: Dict[str, object], dtype) -> Tuple[tuple, jax.Array]:
    names = tuple(sorted(grid))
    _check_axes(names)
    cols = [jnp.atleast_1d(jnp.asarray(grid[k], dtype)) for k in names]
    sizes = {c.shape[0] for c in cols}
    if len(sizes) != 1:
        raise ValueError(
            f"grid axes must be aligned 1-D arrays of one length, got "
            f"{ {k: c.shape for k, c in zip(names, cols)} } — use "
            "grid_cartesian to flatten a product grid"
        )
    return names, jnp.stack(cols, axis=-1)  # (G, P)


def grid_loglikelihood(
    residuals,
    batch: PulsarBatch,
    recipe: Recipe,
    grid: Dict[str, object],
    design=None,
    per_pulsar: bool = False,
    chunk: Optional[int] = None,
    fused: bool = False,
    precision: str = "highest",
    tile: Optional[int] = None,
    backend: str = "auto",
    numerics_capture=None,
):
    """log L over a hyperparameter grid: (G,) totals (or (G, Np) with
    ``per_pulsar``) for aligned 1-D grid axes (Recipe field name ->
    (G,) values).

    Routes automatically: a grid moving only GP amplitudes/slopes of
    blocks the base recipe enables rides the :class:`~.gp.ReducedGP`
    fast path (one Nt-sized precompute + projection, then O(R^3) per
    point); anything else (white-noise axes, blocks toggling on/off)
    pays the full per-point rebuild. ``chunk`` bounds the vmapped block
    size (device memory control for huge grids); results are identical
    at any chunking.

    The raw-speed ladder (docs/performance.md) is opt-in: ``fused=True``
    runs the precompute through the fused Woodbury-assembly kernel
    (requires a reducible grid — it IS the fast path, made faster);
    ``precision='bf16'`` additionally runs the kernel's contractions in
    bf16/f32-accumulate, refused unless ``numerics_capture`` holds a
    ladder verdict clearing the fused sites
    (:func:`~.gp.require_precision_ready`). ``tile``/``backend`` pin the
    kernel tiling (default: autotuner cache, then constants). All
    defaults keep this function bitwise identical to its pre-ladder
    behavior.
    """
    dtype = jnp.asarray(residuals).dtype
    names, theta = _theta_block(grid, dtype)
    fused, precision, tile, backend = _resolve_fused(
        batch, recipe, fused, precision, tile, backend, numerics_capture
    )
    if fused and not _reducible(names, recipe):
        raise ValueError(
            f"fused=True requires a reducible grid (phi-only axes of "
            f"enabled GP blocks); got {names} — the fused rung "
            "accelerates the ReducedGP precompute, which this grid "
            "cannot use"
        )
    G = theta.shape[0]
    step = G if not chunk else max(1, int(chunk))
    # pad the tail block to the full chunk shape (repeat the last row)
    # so every slice hits the ONE compiled engine — a narrower final
    # chunk would trace and compile a second full program, on exactly
    # the huge-grid case `chunk` exists for; the padded rows are
    # sliced off below
    pad = (-G) % step
    if pad:
        theta = jnp.concatenate(
            [theta, jnp.repeat(theta[-1:], pad, axis=0)]
        )
    outs = []
    if _reducible(names, recipe):
        if fused:
            reduced, proj = _fused_build_engine(precision, tile, backend)(
                jnp.asarray(residuals, dtype), batch, recipe, design
            )
        else:
            reduced = gp.ReducedGP.build(batch, recipe, design=design,
                                         dtype=dtype)
            proj = reduced.project(residuals, batch)
        engine = _reduced_grid_engine(names, per_pulsar)
        for i in range(0, G + pad, step):
            outs.append(engine(theta[i:i + step], reduced, proj, batch,
                               recipe))
    else:
        engine = _direct_grid_engine(names, per_pulsar)
        for i in range(0, G + pad, step):
            outs.append(engine(theta[i:i + step], residuals, batch,
                               recipe, design))
    out = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)
    return out[:G]


def bank_loglikelihood(
    bank,
    batch: PulsarBatch,
    recipe: Recipe,
    grid: Optional[Dict[str, object]] = None,
    design=None,
    mesh=None,
    prefetch_depth: int = 2,
    fused: bool = False,
    precision: str = "highest",
    tile: Optional[int] = None,
    backend: str = "auto",
    numerics_capture=None,
):
    """log L of every realization in a residual bank — (R,) without a
    grid, (G, R) with one. ``bank`` is a (R, Np, Nt) array, or a
    :class:`~.serve.RealizationBank` — banks stream chunk-by-chunk
    through the prefetch layer (``project_bank``), so a multi-GB sweep
    checkpoint never materializes whole on the host.

    The bank projects ONCE through the ReducedGP precompute (the only
    pass that touches the TOA axis); each grid point then prices all R
    realizations from the projections alone. On a multi-device
    ``mesh`` the projections shard along the 'real' axis
    (realization-bank parallelism — each chip prices its own bank
    rows; R must divide the mesh's 'real' extent).

    ``fused``/``precision``/``tile``/``backend``/``numerics_capture``
    engage the raw-speed ladder exactly as in
    :func:`grid_loglikelihood`: the precompute runs through the fused
    Woodbury kernel, the per-row projections take the direct O(Nt)
    apply (no CiT), and bf16 is gated on the capture's ladder verdict.
    Defaults unchanged.
    """
    from .serve import RealizationBank, project_bank

    dtype = batch.toas_s.dtype
    fused, precision, tile, backend = _resolve_fused(
        batch, recipe, fused, precision, tile, backend, numerics_capture
    )
    if grid is not None:
        names, theta = _theta_block(grid, dtype)
        if not _reducible(names, recipe):
            raise ValueError(
                f"bank grids support phi-only axes (GP amplitudes/"
                f"slopes of enabled blocks); got {names} — evaluate "
                "white-noise axes per realization via "
                "grid_loglikelihood instead"
            )
    if fused:
        reduced, _ = _fused_build_engine(precision, tile, backend)(
            None, batch, recipe, design
        )
    else:
        reduced = gp.ReducedGP.build(batch, recipe, design=design,
                                     dtype=dtype)
    if isinstance(bank, RealizationBank):
        proj = project_bank(bank, reduced, batch,
                            prefetch_depth=prefetch_depth, mesh=mesh)
    else:
        bank = jnp.asarray(bank, dtype)
        if bank.ndim != 3:
            raise ValueError(
                f"bank must be (R, Np, Nt), got {bank.shape}"
            )
        proj = gp.shard_projection(
            jax.vmap(lambda r: reduced.project(r, batch))(bank), mesh
        )
    if grid is None:
        return _bank_engine()(reduced, proj,
                              gp.phi_for_recipe(batch, recipe))
    engine = _reduced_grid_engine_bank(names)
    return engine(theta, reduced, proj, batch, recipe)


@functools.lru_cache(maxsize=None)
def _bank_engine():
    from ..obs import instrumented_jit
    from ..obs import names as n

    def run(reduced, proj, phi):
        return jax.vmap(
            lambda pj: reduced.loglikelihood(pj, phi)
        )(proj)

    return instrumented_jit(
        run, name=n.JIT_LIKELIHOOD_REDUCED_ENGINE, retrace_warn=32,
    )


@functools.lru_cache(maxsize=None)
def _reduced_grid_engine_bank(names: Tuple[str, ...]):
    """(G, P) theta x projected bank -> (G, R) totals, the serving
    engine (likelihood/serve.py coalesces requests into the theta
    axis)."""
    from ..obs import instrumented_jit
    from ..obs import names as n

    def run(theta, reduced, proj, batch, recipe):
        def one(th):
            phi = gp.phi_for_recipe(
                batch, _replace(recipe, names, list(th))
            )
            return jax.vmap(
                lambda pj: reduced.loglikelihood(pj, phi)
            )(proj)

        return jax.vmap(one)(theta)

    return instrumented_jit(
        run, name=n.JIT_LIKELIHOOD_REDUCED_ENGINE, retrace_warn=32,
    )


# ----------------------------------------------------------- MAP/Fisher

@dataclasses.dataclass
class MapResult:
    """Gradient-based MAP fit + Fisher-matrix uncertainties."""

    #: hyperparameter names, in the order of every array below
    names: Tuple[str, ...]
    #: (P,) MAP point
    x: np.ndarray
    #: log L at the MAP point
    loglikelihood: float
    #: (P, P) observed Fisher information (-hessian of log L)
    fisher: np.ndarray
    #: (P, P) covariance (Fisher inverse), NaN when singular
    covariance: np.ndarray
    #: (P,) 1-sigma uncertainties sqrt(diag covariance)
    sigma: np.ndarray
    #: optimizer converged (BFGS gradient tolerance met)
    converged: bool
    #: optimizer iterations
    iterations: int

    def as_dict(self) -> dict:
        return {
            "names": list(self.names),
            "x": [float(v) for v in self.x],
            "loglikelihood": float(self.loglikelihood),
            "sigma": [float(v) for v in self.sigma],
            "converged": bool(self.converged),
            "iterations": int(self.iterations),
        }


def map_fit(
    residuals,
    batch: PulsarBatch,
    recipe: Recipe,
    params: Dict[str, float],
    design=None,
    maxiter: int = 50,
    gtol: float = 1e-4,
) -> MapResult:
    """MAP hyperparameter fit + Fisher-matrix uncertainties — the
    rapid-inference estimator of arXiv:2412.13379: climb to the
    likelihood peak and read the curvature there, instead of sampling
    a posterior.

    The climb is damped Newton (Levenberg): the step solves
    ``(H + lam I) dx = -g`` with jitted ``jax.grad``/``jax.hessian``
    evaluations, ``lam`` shrinking on accepted steps and growing on
    rejected ones — the curvature matrix the uncertainties need anyway
    IS the step preconditioner, and on these smooth few-parameter
    surfaces it converges in a handful of iterations where a generic
    line-searched quasi-Newton stalls on the |log L| ~ 1e4 scale.
    Convergence: max |gradient| < ``gtol``.

    The objective is the flat-prior log-likelihood itself; informative
    priors belong to the caller. Degenerate curvature (non-positive
    Fisher diagonal at the peak) reports NaN sigmas rather than
    raising.

    Wants f64 (enable x64, or pass an f64 batch/residuals): |log L| is
    ~1e4-1e5, so f32 evaluation noise (~eps x |log L|) drowns the
    near-peak likelihood DIFFERENCES the damping loop and the Fisher
    curvature are built from — on f32 the fit degrades to
    ``converged=False`` + NaN sigmas instead of silently wrong numbers
    (same precision posture as design_fit_subtract's exact-recovery
    caveat; grid/serving evaluation is comparison-of-equals and stays
    fine at f32).
    """
    names = tuple(sorted(params))
    _check_axes(names)
    dtype = jnp.asarray(residuals).dtype
    x = np.asarray([float(params[k]) for k in names], np.float64)

    def neg_ll(xv):
        r2 = _replace(recipe, names,
                      [xv[i] for i in range(len(names))])
        return -gp.loglikelihood(residuals, batch, r2, design=design)

    val_grad = jax.jit(jax.value_and_grad(neg_ll))
    hess = jax.jit(jax.hessian(neg_ll))

    lam = 1e-3
    f, g = val_grad(jnp.asarray(x, dtype))
    f, g = float(f), np.asarray(g, np.float64)
    it = 0
    converged = bool(np.max(np.abs(g)) < gtol)
    while it < maxiter and not converged:
        it += 1
        H = np.asarray(hess(jnp.asarray(x, dtype)), np.float64)
        accepted = False
        for _ in range(12):  # grow damping until the step helps
            try:
                dx = np.linalg.solve(
                    H + lam * np.eye(len(x)), -g
                )
            except np.linalg.LinAlgError:
                lam *= 10.0
                continue
            f_new, g_new = val_grad(jnp.asarray(x + dx, dtype))
            f_new = float(f_new)
            if np.isfinite(f_new) and f_new <= f:
                x = x + dx
                f, g = f_new, np.asarray(g_new, np.float64)
                lam = max(lam / 3.0, 1e-12)
                accepted = True
                break
            lam *= 10.0
        if not accepted:
            break  # damping exhausted: report the best point found
        converged = bool(np.max(np.abs(g)) < gtol)

    fisher = np.asarray(hess(jnp.asarray(x, dtype)), np.float64)
    try:
        cov = np.linalg.inv(fisher)
        with np.errstate(invalid="ignore"):
            sigma = np.sqrt(np.where(np.diag(cov) > 0,
                                     np.diag(cov), np.nan))
    except np.linalg.LinAlgError:
        cov = np.full_like(fisher, np.nan)
        sigma = np.full(len(names), np.nan)
    return MapResult(
        names=names,
        x=x,
        loglikelihood=-f,
        fisher=fisher,
        covariance=cov,
        sigma=sigma,
        converged=converged,
        iterations=it,
    )
