"""Request-batched likelihood serving over precomputed realization banks.

The "millions of users" shape of ROADMAP open item 1: a sweep produces
a bank of NG15-scale realizations (utils/sweep.py checkpoints — the
consolidated npz or the per-chunk/sharded archives of a run still in
flight); this module prices hyperparameter requests against that bank
as a service.

The economics come from :class:`~.gp.ReducedGP`: the bank is projected
ONCE through the fixed-noise precompute (the only pass that touches
the TOA axis, streamed chunk-by-chunk through the prefetch layer so no
stage holds the whole bank), after which one request costs a small
per-pulsar Cholesky — so the right execution model is request
COALESCING, not request-at-a-time: concurrent requests queue, a worker
collects them until a device-shaped batch fills or a deadline expires
(size/deadline trigger, the classic dynamic-batching tradeoff:
coalescing efficiency vs tail latency), pads the theta block to the
fixed batch shape (one compile, ever), runs ONE vmapped evaluation
over (batch, realizations), and resolves each request's future with
its own (R,) log-likelihood row.

Serving hardening (PR 11, docs/robustness.md): a bounded request queue
with reject-on-saturation admission control (``max_queue`` —
:class:`ServerSaturated` instead of unbounded queue growth under
overload), per-request deadlines (``request_deadline_s`` /
``submit(deadline_s=)`` — an expired future raises
:class:`DeadlineExpired`, it is never served late and never stranded),
and a single in-place retry of transiently-failed engine calls through
the shared faults/retry policy.

SLO telemetry rides the obs stack: ``likelihood.requests`` /
``likelihood.batches`` / ``likelihood.batch_size`` /
``likelihood.evals`` / ``likelihood.coalesce_efficiency`` /
``likelihood.queue_depth`` / ``likelihood.rejected`` /
``likelihood.deadline_expired`` metrics, a ``likelihood_batch`` span per
coalesced evaluation (so a capture's series layer yields batch-latency
percentiles for free), and request-latency p50/p95/p99 tracked by the
streaming P^2 estimators of obs/series.py — :meth:`LikelihoodServer.
stats` returns the whole SLO block, and benchmarks/likelihood_serve.py
commits it as the LIKELIHOOD bench series.

Causal tracing (PR 14, docs/tracing.md): every submit mints a
:class:`~..obs.trace.TraceContext` (``future.trace_id``); the request's
life — the ``likelihood_submit`` span on the client thread, the
synthesized ``likelihood_queue_wait``/``likelihood_resolve`` spans on
the worker, the coalesced ``likelihood_batch`` span that served it
(via its ``links`` fan-in field), and any rejection/expiry event — all
share that trace_id, so one grep of the capture reconstructs one
request end to end. Open (unresolved) request traces register in
obs.trace's bounded registry, which the flight recorder's postmortem
flushes — a killed server names the in-flight requests it took down.
"""
from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..batch import PulsarBatch
from ..faults import inject as faults
from ..faults.retry import RetryPolicy, is_transient, retry_call
from ..models.batched import Recipe
from ..obs import counter, event, gauge, names, span
from ..obs.series import SpanQuantiles
from ..obs.trace import (
    TRACER,
    TraceContext,
    adopt,
    new_trace_context,
    open_request_count,
    register_open_request,
    resolve_open_request,
)
from . import gp
from .infer import _check_axes, _reduced_grid_engine_bank, _reducible

_STOP = object()

#: one in-place retry of a transiently-failed engine evaluation (a
#: flapped device call fails one coalesced batch = up to max_batch
#: client futures at once; the retry costs milliseconds) — fatal errors
#: still fail every future in the batch immediately
_ENGINE_RETRY = RetryPolicy(max_attempts=2, base_delay_s=0.05,
                            max_delay_s=1.0)


class ServerSaturated(RuntimeError):
    """Admission control refused the request: the bounded queue is at
    ``max_queue``. Shed load upstream (back off and resubmit) — an
    unbounded queue under sustained overload turns every latency SLO
    into heap growth and multi-second tails."""


class DeadlineExpired(TimeoutError):
    """The request's deadline passed while it was still queued; its
    future raises this instead of being served late (the client
    already gave up — evaluating it would burn device time on an
    answer nobody reads)."""


class RealizationBank:
    """Host-side handle on a (R, Np, Nt) residual bank.

    ``chunks`` is a list of loader callables (one per chunk) so a bank
    larger than host memory can stream: only one chunk is resident per
    iteration step. Build from a live array (:meth:`from_array`) or
    from a sweep checkpoint in ANY state (:meth:`from_checkpoint` —
    consolidated npz, or the per-chunk ``.npy``/sharded-archive files
    of an unfinished run, reassembled under any topology).
    """

    def __init__(self, chunks: Sequence, shape: Tuple[int, ...], dtype,
                 lengths: Optional[Sequence[int]] = None):
        self._chunks = list(chunks)
        self.shape = tuple(int(n) for n in shape)
        self.dtype = np.dtype(dtype)
        #: realizations per chunk (for single-row access without
        #: loading the whole bank); None = unknown until iterated
        self._lengths = None if lengths is None else [
            int(n) for n in lengths
        ]
        if len(self.shape) != 3:
            raise ValueError(
                f"realization banks are (R, Np, Nt) residual cubes; got "
                f"shape {self.shape} — sweeps that keep a reduce_fn "
                "store summaries, not banks (run with reduce_fn=None)"
            )

    @property
    def nreal(self) -> int:
        return self.shape[0]

    def row(self, i: int) -> np.ndarray:
        """One (Np, Nt) realization, loading ONLY its containing chunk
        (a MAP fit on row 3 of a multi-GB bank must not concatenate
        the whole cube first)."""
        if not 0 <= i < self.nreal:
            raise IndexError(f"row {i} out of range (nreal={self.nreal})")
        if self._lengths is not None:
            lo = 0
            for k, n in enumerate(self._lengths):
                if i < lo + n:
                    return np.asarray(self._chunks[k]())[i - lo]
                lo += n
        lo = 0
        for block in self.iter_chunks():
            if i < lo + block.shape[0]:
                return block[i - lo]
            lo += block.shape[0]
        raise IndexError(f"row {i} beyond the bank's chunks")

    @classmethod
    def from_array(cls, arr, chunk: int = 256) -> "RealizationBank":
        arr = np.asarray(arr)
        loaders = [
            (lambda lo=lo: arr[lo:lo + chunk])
            for lo in range(0, arr.shape[0], chunk)
        ]
        lengths = [
            min(chunk, arr.shape[0] - lo)
            for lo in range(0, arr.shape[0], chunk)
        ]
        return cls(loaders, arr.shape, arr.dtype, lengths=lengths)

    @classmethod
    def from_checkpoint(cls, checkpoint_path: str) -> "RealizationBank":
        from ..utils.sweep import iter_checkpoint_chunk_infos

        # header-only probe: shapes from npy headers / shard manifests,
        # zero data bytes read — the chunks themselves stream later,
        # on demand, through the loaders
        probe = list(iter_checkpoint_chunk_infos(checkpoint_path))
        if not probe:
            raise FileNotFoundError(
                f"no completed sweep chunks at {checkpoint_path} "
                "(neither a consolidated archive nor chunk files)"
            )
        nreal = sum(shape[0] for _i, shape, _d in probe)
        _, shape0, dtype0 = probe[0]

        def loader(i):
            def load(i=i):
                from ..utils.sweep import load_checkpoint_chunk

                return load_checkpoint_chunk(checkpoint_path, i)

            return load

        loaders = [loader(i) for i, _s, _d in probe]
        return cls(loaders, (nreal,) + tuple(shape0[1:]), dtype0,
                   lengths=[s[0] for _i, s, _d in probe])

    def iter_chunks(self):
        for load in self._chunks:
            yield np.asarray(load())

    def load(self) -> np.ndarray:
        return np.concatenate(list(self.iter_chunks()), axis=0)


def project_bank(
    bank: RealizationBank,
    reduced: gp.ReducedGP,
    batch: PulsarBatch,
    prefetch_depth: int = 2,
    mesh=None,
) -> gp.GPProjection:
    """Project a whole bank through the ReducedGP precompute: the
    one-time Nt-sized pass, streamed chunk-by-chunk through the
    prefetch layer (the next chunk loads from disk and stages
    host->device while the current one projects), returning the
    (R, Np[, Q]) projection pytree the request path consumes. On a
    multi-device ``mesh`` the projections land sharded along 'real'
    (realization-bank parallelism for the evaluation engine)."""
    from ..parallel.prefetch import prefetch_to_device

    project = jax.jit(
        lambda block: jax.vmap(lambda r: reduced.project(r, batch))(block)
    )
    parts = []
    with span(names.SPAN_LIKELIHOOD_PROJECT, nreal=bank.nreal) as sp:
        staged = prefetch_to_device(
            bank.iter_chunks(), depth=prefetch_depth
        )
        for block in staged:
            parts.append(project(block))
        sp["chunks"] = len(parts)
    proj = gp.GPProjection(
        rNr=jnp.concatenate([p.rNr for p in parts], axis=0),
        d=jnp.concatenate([p.d for p in parts], axis=0),
    )
    return gp.shard_projection(proj, mesh)


@dataclass
class _Request:
    theta: np.ndarray
    future: Future
    t_submit: float  # monotonic
    t_submit_wall: float  # wall clock (trace-span t0 stamps)
    ctx: TraceContext  # the request's causal trace (docs/tracing.md)
    deadline: Optional[float] = None  # monotonic; None = no deadline


class LikelihoodServer:
    """Request-batched likelihood evaluation over a realization bank.

    ``axes``: the Recipe hyperparameter fields a request supplies
    (sorted internally; must be phi-only axes — GP amplitudes/slopes
    of blocks the base recipe enables — because the serving engine IS
    the fixed-noise ReducedGP path). ``max_batch`` is the device batch
    capacity; ``max_delay_s`` the coalescing deadline measured from
    the oldest queued request. Each :meth:`submit` returns a
    ``concurrent.futures.Future`` resolving to the (R,) per-realization
    total log-likelihood at that hyperparameter point.

    Lifecycle: ``start()`` spawns the coalescing worker; ``stop()``
    drains the queue (pending requests are SERVED, not dropped) and
    joins it. Thread-safe submit from any number of client threads.
    """

    def __init__(
        self,
        bank: RealizationBank,
        batch: PulsarBatch,
        recipe: Recipe,
        axes: Sequence[str],
        design=None,
        mesh=None,
        max_batch: int = 8,
        max_delay_s: float = 0.005,
        prefetch_depth: int = 2,
        max_queue: Optional[int] = None,
        request_deadline_s: Optional[float] = None,
    ):
        self.axes = tuple(sorted(axes))
        _check_axes(self.axes)
        if not _reducible(self.axes, recipe):
            raise ValueError(
                f"serving axes {self.axes} must be phi-only hyper"
                "parameters (GP amplitudes/slopes of blocks the base "
                "recipe enables) — white-noise axes invalidate the "
                "fixed-noise precompute the serving path is built on"
            )
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1 (got {max_batch})")
        if max_queue is not None and max_queue < 1:
            raise ValueError(f"max_queue must be >= 1 (got {max_queue})")
        self.batch = batch
        self.recipe = recipe
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_s)
        #: admission control: queued-but-unserved requests are capped at
        #: this; submit() past it raises ServerSaturated instead of
        #: growing the queue (None = unbounded, the pre-PR-11 behavior)
        self.max_queue = None if max_queue is None else int(max_queue)
        #: default per-request deadline measured from submit (a request
        #: may override per call); None = no deadline
        self.request_deadline_s = (
            None if request_deadline_s is None else float(request_deadline_s)
        )
        self.nreal = bank.nreal
        dtype = batch.toas_s.dtype
        self._reduced = gp.ReducedGP.build(
            batch, recipe, design=design, dtype=dtype
        )
        self._proj = project_bank(
            bank, self._reduced, batch,
            prefetch_depth=prefetch_depth, mesh=mesh,
        )
        self._engine = _reduced_grid_engine_bank(self.axes)
        self._queue: queue.Queue = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._closing = False
        self._lock = threading.Lock()
        self._latency = SpanQuantiles()
        self._batch_fill = SpanQuantiles()
        self._requests = 0
        self._batches = 0
        self._started_at: Optional[float] = None
        self._busy_s = 0.0
        self._pending = 0   # admitted, not yet picked up by the worker
        self._rejected = 0
        self._deadline_expired = 0

    # ------------------------------------------------------- lifecycle

    def start(self) -> "LikelihoodServer":
        if self._worker is not None:
            raise RuntimeError("server already started")
        self._closing = False
        self._started_at = time.monotonic()
        self._worker = threading.Thread(  # graftlint: disable=parallel-adhoc-stage — not a staged FIFO pipeline: the request queue coalesces by size/deadline (items are merged, not forwarded 1:1), admission control rejects at the bound instead of back-pressuring, and futures resolve out of the graph
            target=self._run, name="likelihood-serve", daemon=True
        )
        self._worker.start()
        return self

    def stop(self, timeout: Optional[float] = None) -> None:
        """Drain and join: queued requests are served before the worker
        exits (a shutdown must not strand client futures). ``submit``
        raises once the shutdown begins; a request that slips through
        the closing race is served by a final drain HERE, after the
        join, so no accepted future is ever stranded. The default waits
        for the drain to finish (it is bounded by the queue content);
        with a finite ``timeout`` a still-running worker raises instead
        of being silently abandoned (a second ``start`` on a live
        worker would double-serve the queue)."""
        if self._worker is None:
            return
        with self._lock:
            self._closing = True
        self._queue.put(_STOP)
        self._worker.join(timeout=timeout)
        if self._worker.is_alive():
            raise RuntimeError(
                f"likelihood-serve worker still draining after "
                f"{timeout}s — the server is NOT stopped (retry stop() "
                "with a longer/None timeout)"
            )
        self._worker = None
        # defensive invariant: submit() enqueues atomically with the
        # closing check, so every admitted request precedes _STOP in
        # the FIFO queue and the worker has already served it. If that
        # invariant is ever broken, serve the stragglers here anyway
        # rather than strand their futures.
        tail = []
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not _STOP:
                tail.append(item)
        for lo in range(0, len(tail), self.max_batch):
            self._serve_batch(tail[lo:lo + self.max_batch])

    def __enter__(self) -> "LikelihoodServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # --------------------------------------------------------- clients

    def submit(self, deadline_s: Optional[float] = None,
               **params) -> Future:
        """Queue one hyperparameter point; returns a Future resolving
        to the (R,) per-realization total log L.

        ``deadline_s`` (default: the server's ``request_deadline_s``)
        bounds how long the request may wait in the queue: a request
        still unserved when it expires has its future raise
        :class:`DeadlineExpired` instead of being evaluated late.
        Raises :class:`ServerSaturated` — without enqueueing — when
        the bounded queue (``max_queue``) is full.

        Every request gets a causal :class:`~..obs.trace.TraceContext`
        at submit (exposed as ``future.trace_id``, and stamped into a
        rejection/expiry exception message), so a caller can grep the
        capture for exactly their request: the ``likelihood_submit``
        span here, the synthesized queue-wait and resolution spans on
        the worker, and the coalesced ``likelihood_batch`` span that
        served it (via its ``links`` fan-in field) all share the
        trace_id (docs/tracing.md)."""
        if set(params) != set(self.axes):
            raise ValueError(
                f"request must supply exactly {self.axes}, got "
                f"{tuple(sorted(params))}"
            )
        theta = np.asarray([float(params[k]) for k in self.axes])
        fut: Future = Future()
        ctx = new_trace_context()
        fut.trace_id = ctx.trace_id
        now = time.monotonic()
        if deadline_s is None:
            deadline_s = self.request_deadline_s
        deadline = None if deadline_s is None else now + float(deadline_s)
        # the enqueue is atomic with the closing check: stop() flips
        # _closing under this lock BEFORE posting the worker's _STOP,
        # so any request admitted here is already in the queue ahead of
        # the sentinel (FIFO) and the drain is guaranteed to serve it.
        # Admission control shares the same critical section, so the
        # pending count can never over-admit under concurrent submits
        # (the worker only ever SHRINKS it concurrently — a race there
        # rejects one request early, never admits one past the bound).
        # The submit span wraps the whole admission decision, so even a
        # REJECTED request leaves a span carrying its trace_id.
        with adopt(ctx), span(names.SPAN_LIKELIHOOD_SUBMIT) as sp:
            with self._lock:
                if self._worker is None or self._closing:
                    raise RuntimeError("server not started (or stopping)")
                rejected = (
                    self.max_queue is not None
                    and self._pending >= self.max_queue
                )
                if rejected:
                    self._rejected += 1
                else:
                    self._pending += 1
                    # registration precedes the enqueue (the worker
                    # cannot dequeue — and resolve — what is not yet
                    # queued), so the open-request registry can never
                    # leak a register that arrives after its resolve
                    register_open_request(
                        ctx, kind="likelihood_request",
                        params={k: float(params[k]) for k in self.axes},
                    )
                    self._queue.put(_Request(
                        theta, fut, now, time.time(), ctx,
                        deadline=deadline,
                    ))
            # telemetry and the stamped exception run OUTSIDE the
            # admission lock: under saturation every submit lands here,
            # and the event emission is a line-buffered sink write —
            # concurrent submitters must not serialize their admission
            # checks behind each other's disk I/O
            if rejected:
                counter(names.LIKELIHOOD_REJECTED).inc()
                sp["rejected"] = True
                event(names.EVENT_LIKELIHOOD_REJECTED,
                      max_queue=self.max_queue)
                raise ServerSaturated(
                    f"request queue at max_queue={self.max_queue} — "
                    "load shed; back off and resubmit "
                    f"(trace {ctx.trace_id})"
                )
        gauge(names.TRACE_OPEN_REQUESTS).set(open_request_count())
        counter(names.LIKELIHOOD_REQUESTS).inc()
        gauge(names.LIKELIHOOD_QUEUE_DEPTH).set(self._queue.qsize())
        return fut

    def evaluate(self, **params) -> np.ndarray:
        """Blocking convenience wrapper around :meth:`submit`."""
        return self.submit(**params).result()

    # ---------------------------------------------------------- worker

    def _run(self) -> None:
        with span(names.SPAN_LIKELIHOOD_SERVE,
                  max_batch=self.max_batch,
                  max_delay_s=self.max_delay_s):
            stopping = False
            while not stopping:
                item = self._queue.get()
                if item is _STOP:
                    break
                reqs = [item]
                deadline = item.t_submit + self.max_delay_s
                while len(reqs) < self.max_batch:
                    # backlog that accumulated while the previous batch
                    # evaluated coalesces UNCONDITIONALLY (an expired
                    # deadline must not ship a 1-request batch past a
                    # full queue); the deadline only bounds how long we
                    # WAIT for requests that have not arrived yet
                    try:
                        nxt = self._queue.get_nowait()
                    except queue.Empty:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        try:
                            nxt = self._queue.get(timeout=remaining)
                        except queue.Empty:
                            break
                    if nxt is _STOP:
                        stopping = True
                        break
                    reqs.append(nxt)
                self._serve_batch(reqs)
            # drain anything still queued after the stop sentinel
            tail = []
            while True:
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    break
                if item is not _STOP:
                    tail.append(item)
            for lo in range(0, len(tail), self.max_batch):
                self._serve_batch(tail[lo:lo + self.max_batch])

    def _expire(self, reqs) -> list:
        """Split off requests whose deadline passed while queued: their
        futures raise DeadlineExpired (never strand, never burn device
        time on an answer the client stopped waiting for); returns the
        still-live requests. A request that makes the cut is evaluated
        even if it expires mid-batch — the deadline bounds QUEUE time,
        the engine latency is bounded by the batch itself."""
        now = time.monotonic()
        live = []
        expired = []
        for r in reqs:
            if r.deadline is not None and now > r.deadline:
                expired.append(r)
            else:
                live.append(r)
        if expired:
            with self._lock:
                self._deadline_expired += len(expired)
            counter(names.LIKELIHOOD_DEADLINE_EXPIRED).inc(len(expired))
            for r in expired:
                # the trace still closes: the queue-wait span records
                # where the request died, the expiry event carries its
                # trace_id, and the exception message stamps it so the
                # caller can grep the capture for exactly this request
                with adopt(r.ctx):
                    TRACER.record_span(
                        names.SPAN_LIKELIHOOD_QUEUE_WAIT,
                        r.t_submit_wall, now - r.t_submit,
                        expired=True,
                    )
                    event(names.EVENT_LIKELIHOOD_DEADLINE_EXPIRED,
                          waited_s=round(now - r.t_submit, 6))
                resolve_open_request(r.ctx)
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(DeadlineExpired(
                        f"request expired after {now - r.t_submit:.3f}s "
                        "in the queue (deadline "
                        f"{r.deadline - r.t_submit:.3f}s) "
                        f"(trace {r.ctx.trace_id})"
                    ))
            gauge(names.TRACE_OPEN_REQUESTS).set(open_request_count())
        return live

    def _serve_batch(self, reqs) -> None:
        with self._lock:
            # every dequeued request leaves the admission window here,
            # served or expired (submit's bound counts queued-only)
            self._pending -= len(reqs)
        reqs = self._expire(reqs)
        if not reqs:
            gauge(names.LIKELIHOOD_QUEUE_DEPTH).set(self._queue.qsize())
            return
        nb = len(reqs)
        # queue-wait spans: the dequeue instant closes each request's
        # queue residence (synthesized — the interval's endpoints live
        # on two different threads)
        t_deq = time.monotonic()
        for r in reqs:
            with adopt(r.ctx):
                TRACER.record_span(
                    names.SPAN_LIKELIHOOD_QUEUE_WAIT,
                    r.t_submit_wall, max(0.0, t_deq - r.t_submit),
                )
        theta = np.stack([r.theta for r in reqs])
        if nb < self.max_batch:
            # pad to the fixed device batch shape: ONE compiled program
            # regardless of fill (the padding rows repeat the last
            # request and are discarded — wasted FLOPs, not a retrace)
            theta = np.concatenate(
                [theta, np.repeat(theta[-1:], self.max_batch - nb,
                                  axis=0)]
            )
        t0 = time.monotonic()

        def _eval():
            faults.fire(faults.SITE_SERVER_ENGINE, requests=nb)
            return np.asarray(
                self._engine(
                    jnp.asarray(theta, self.batch.toas_s.dtype),
                    self._reduced, self._proj, self.batch,
                    self.recipe,
                )
            )

        try:
            # links= is the fan-in: ONE coalesced batch span naming the
            # trace of every request it serves, so each request's trace
            # stitches through the shared engine evaluation
            with span(names.SPAN_LIKELIHOOD_BATCH,
                      links=[r.ctx.trace_id for r in reqs],
                      requests=nb, capacity=self.max_batch):
                # one in-place retry of a transient engine failure: a
                # flapped device call must not fail max_batch client
                # futures at once (fatal errors still do, immediately)
                out = retry_call(_eval, policy=_ENGINE_RETRY,
                                 classify=is_transient, scope="serve")
        except BaseException as exc:  # noqa: BLE001 — delivered per-future
            fail_wall = time.time()
            for r in reqs:
                # the trace closes on the failure path too — a resolve
                # span with the error, so a failed request is never an
                # open-ended trace
                with adopt(r.ctx):
                    TRACER.record_span(
                        names.SPAN_LIKELIHOOD_RESOLVE, fail_wall, 0.0,
                        error=repr(exc)[:200],
                    )
                resolve_open_request(r.ctx)
                if not r.future.set_running_or_notify_cancel():
                    continue
                r.future.set_exception(exc)
            gauge(names.TRACE_OPEN_REQUESTS).set(open_request_count())
            return
        done = time.monotonic()
        with self._lock:
            self._requests += nb
            self._batches += 1
            self._busy_s += done - t0
            self._batch_fill.observe(nb)
            for r in reqs:
                self._latency.observe(done - r.t_submit)
            eff = self._requests / (self._batches * self.max_batch)
        counter(names.LIKELIHOOD_BATCHES).inc()
        counter(names.LIKELIHOOD_EVALS).inc(nb * self.nreal)
        gauge(names.LIKELIHOOD_BATCH_SIZE).set(nb)
        gauge(names.LIKELIHOOD_COALESCE_EFFICIENCY).set(round(eff, 6))
        gauge(names.LIKELIHOOD_QUEUE_DEPTH).set(self._queue.qsize())
        done_wall = time.time()
        for k, r in enumerate(reqs):
            if r.future.set_running_or_notify_cancel():
                r.future.set_result(out[k])
            # resolution closes the trace: t0 = the engine-done
            # instant, duration = the time to hand this future its
            # result (synthesized; adopt() makes the record a child of
            # the request's root, like the queue-wait span)
            with adopt(r.ctx):
                TRACER.record_span(
                    names.SPAN_LIKELIHOOD_RESOLVE, done_wall,
                    max(0.0, time.monotonic() - done),
                    latency_s=round(done - r.t_submit, 6),
                )
            resolve_open_request(r.ctx)
        gauge(names.TRACE_OPEN_REQUESTS).set(open_request_count())

    # ------------------------------------------------------------ SLOs

    def reset_stats(self) -> None:
        """Zero the SLO window (counts, percentile estimators, the
        throughput clock) — so a measurement window can exclude warmup
        (the first request pays the engine compile)."""
        with self._lock:
            self._latency = SpanQuantiles()
            self._batch_fill = SpanQuantiles()
            self._requests = 0
            self._batches = 0
            self._busy_s = 0.0
            self._rejected = 0
            self._deadline_expired = 0
            self._started_at = time.monotonic()

    def stats(self) -> dict:
        """The SLO block: request/batch counts, coalescing efficiency,
        streaming latency percentiles, and throughput over the server's
        lifetime so far."""
        with self._lock:
            requests = self._requests
            batches = self._batches
            busy_s = self._busy_s
            rejected = self._rejected
            deadline_expired = self._deadline_expired
            latency = self._latency.summary()
            fill = self._batch_fill.summary()
        elapsed = (
            time.monotonic() - self._started_at
            if self._started_at is not None else 0.0
        )
        evals = requests * self.nreal
        return {
            "requests": requests,
            "batches": batches,
            "nreal": self.nreal,
            "max_batch": self.max_batch,
            "max_delay_s": self.max_delay_s,
            "coalesce_efficiency": (
                requests / (batches * self.max_batch) if batches else 0.0
            ),
            "batch_fill_mean": (
                requests / batches if batches else 0.0
            ),
            "evals": evals,
            "evals_per_s": evals / elapsed if elapsed > 0 else 0.0,
            "requests_per_s": requests / elapsed if elapsed > 0 else 0.0,
            "device_busy_s": round(busy_s, 6),
            # admission-control / deadline SLO counters (PR 11): load
            # shed instead of queue growth, expiries instead of strands
            "rejected": rejected,
            "deadline_expired": deadline_expired,
            "max_queue": self.max_queue,
            "request_deadline_s": self.request_deadline_s,
            "latency": {
                k: v for k, v in latency.items()
                if v is not None and np.isfinite(v)
            },
            "batch_fill": {
                k: v for k, v in fill.items()
                if v is not None and np.isfinite(v)
            },
        }
