"""Roofline-driven tile autotuner for the fused likelihood kernels.

Rung 3 of the raw-speed ladder (docs/performance.md): the fused
Woodbury-assembly kernel (ops/pallas_gp.py) is tiled along the TOA
axis, and the right tile is a property of the (backend, shape-bucket,
device) triple — not something a constant can be right about on both a
laptop CPU and a TPU pod slice. This module searches the small discrete
candidate space ONCE per triple, scores each candidate by its measured
roofline position (obs/devprof.py ``jax.cost.*``/``jax.roofline.*``
gauges — achieved FLOP/s of the compiled kernel, not a proxy), and
persists the winner in a fingerprint-keyed JSON cache.

The cache contract mirrors the plane-tile cache
(parallel/prefetch.py): every entry is keyed by a fingerprint of
exactly the things that would invalidate it (kernel schema version,
backend, shape bucket, device kind, candidate set). The split of
responsibilities is deliberate:

* :func:`woodbury_tile` — the LOOKUP. Called on the build path
  (``ReducedGP.build_fused`` with ``tile=None``). Never searches,
  never compiles: a cache hit returns the tuned tile (and bumps
  ``tuner.cache_hits``); a miss — no file, corrupt file, fingerprint
  mismatch, foreign device — silently falls back to
  ``DEFAULT_WOODBURY_TILE``. CI and laptops never pay the search.
* :func:`autotune` — the SEARCH. Run explicitly (benchmarks/
  gp_kernels.py ``--tune``) under the ``gp_tune`` span; bumps
  ``tuner.searches``; writes the cache atomically (tmp + rename,
  merging entries already present).

Corruption degrades, never raises: an unreadable or schema-mismatched
cache behaves exactly like no cache (pinned by
tests/test_gp_kernels.py).
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..batch import PulsarBatch
from ..ops import pallas_gp

#: discrete TOA-tile candidates the search scores — small by design
#: (the objective is a full compile + timed run per candidate)
WOODBURY_CANDIDATES = (128, 256, 512)

#: bump when the kernel's tiling semantics change — invalidates every
#: cached entry at once (the fingerprint folds it in)
TUNER_SCHEMA_VERSION = 1

#: committed default cache location (repo layout); overridable per call
#: and via ``PTA_GP_TUNER_CACHE`` for installed-package deployments
DEFAULT_CACHE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
    "benchmarks",
    "gp_tuner_cache.json",
)


def _cache_path(cache_path: Optional[str]) -> str:
    if cache_path is not None:
        return os.fspath(cache_path)
    return os.environ.get("PTA_GP_TUNER_CACHE", DEFAULT_CACHE_PATH)


def _pow2_bucket(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length()


def shape_bucket(npsr: int, ntoa: int) -> str:
    """Coarse shape key: each dimension rounded up to a power of two,
    so nearby problem sizes share one tuned tile instead of fracturing
    the cache per-dataset. The column count Q is deliberately NOT part
    of the bucket — the tile partitions the TOA axis, and lookups
    happen before the basis is ever assembled."""
    return f"np{_pow2_bucket(npsr)}_nt{_pow2_bucket(ntoa)}"


def device_kind() -> str:
    try:
        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


def fingerprint(
    backend: str,
    bucket: str,
    kind: Optional[str] = None,
) -> str:
    """Cache key for one tuned choice: sha256 over everything whose
    change must invalidate it (kernel schema, backend, shape bucket,
    device kind — NOT the candidate set, which only bounds how good
    the tuned choice can be, never whether it is valid). Same refusal
    contract as the plane-tile cache's workload fingerprint — a stale
    entry is never *almost* right, it is simply not found."""
    kind = device_kind() if kind is None else kind
    blob = json.dumps(
        {
            "schema": TUNER_SCHEMA_VERSION,
            "kernel": "fused_woodbury",
            "backend": backend,
            "bucket": bucket,
            "device_kind": kind,
        },
        sort_keys=True,
    )
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def load_cache(cache_path: Optional[str] = None) -> dict:
    """The cache's ``entries`` dict ({fingerprint: choice}); {} for a
    missing, unreadable, or wrong-schema file — corruption means
    untuned, never an exception (the fallback rung is the defaults)."""
    path = _cache_path(cache_path)
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return {}
    if not isinstance(doc, dict) or doc.get("schema") != TUNER_SCHEMA_VERSION:
        return {}
    entries = doc.get("entries")
    return entries if isinstance(entries, dict) else {}


def save_cache(entries: dict, cache_path: Optional[str] = None) -> str:
    """Atomically persist ``entries`` (merged over whatever the file
    already holds): write-to-tmp + rename, so a crashed search can
    corrupt at most the tmp file, never the committed cache."""
    path = _cache_path(cache_path)
    merged = dict(load_cache(path))
    merged.update(entries)
    doc = {"schema": TUNER_SCHEMA_VERSION, "entries": merged}
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return path


def woodbury_tile(
    batch: PulsarBatch,
    backend: str,
    cache_path: Optional[str] = None,
) -> int:
    """The TOA tile for ``batch``'s fused Woodbury assembly: the tuned
    choice when the cache holds one for this (backend, bucket, device)
    fingerprint, else ``DEFAULT_WOODBURY_TILE``. Pure lookup — never
    searches, never compiles (see module docstring)."""
    from ..obs import counter, names

    npsr, ntoa = batch.mask.shape
    bucket = shape_bucket(npsr, ntoa)
    entry = load_cache(cache_path).get(fingerprint(backend, bucket))
    if isinstance(entry, dict) and isinstance(entry.get("tile"), int):
        counter(names.TUNER_CACHE_HITS, backend=backend).inc()
        return int(entry["tile"])
    return pallas_gp.DEFAULT_WOODBURY_TILE


def _time_compiled(compiled, args, reps: int) -> float:
    """Median wall seconds of ``reps`` executions (one warm call
    first)."""
    jax.block_until_ready(compiled(*args))
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(compiled(*args))
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def autotune(
    batch: PulsarBatch,
    T,
    backend: str = "xla",
    candidates: Sequence[int] = WOODBURY_CANDIDATES,
    reps: int = 5,
    cache_path: Optional[str] = None,
    write: bool = True,
) -> dict:
    """Search ``candidates`` for the fastest fused-Woodbury TOA tile on
    this device and persist the winner.

    For each candidate the kernel is compiled at the search shape,
    its XLA cost analysis recorded (``jax.cost.*`` gauges via
    :func:`~pta_replicator_tpu.obs.devprof.record_compiled`), a median
    execution timed, and the roofline position computed
    (``jax.roofline.*`` gauges). The objective is achieved FLOP/s —
    with no cost model (some CPU builds), inverse median time stands
    in (monotone-equivalent at fixed shape: flops per call is
    tile-independent). Returns the choice record that was cached."""
    from ..obs import counter, devprof, names, span

    npsr, ntoa = batch.mask.shape
    T = jnp.asarray(T)
    bucket = shape_bucket(npsr, ntoa)
    key = fingerprint(backend, bucket)
    dtype = T.dtype
    winv = jnp.where(batch.mask > 0, 1.0, 0.0).astype(dtype)
    r = jnp.zeros((npsr, ntoa), dtype)

    with span(names.SPAN_GP_TUNE, backend=backend, bucket=bucket):
        counter(names.TUNER_SEARCHES, backend=backend).inc()
        scored = []
        for tile in candidates:
            label = f"{names.JIT_GP_FUSED_WOODBURY}[tile={tile}]"
            if backend == "xla":
                fn = pallas_gp.fused_woodbury_xla
                kw = dict(tile=int(tile))
            else:
                fn = pallas_gp.fused_woodbury_update
                kw = dict(
                    tile=int(tile),
                    interpret=(backend == "pallas_interpret"),
                )
            try:
                compiled = (
                    jax.jit(
                        lambda a, b, c, _fn=fn, _kw=kw: _fn(a, b, c, **_kw)
                    )
                    .lower(T, winv, r)
                    .compile()
                )
                cost = devprof.record_compiled(
                    names.JIT_GP_FUSED_WOODBURY, compiled
                )
                elapsed = _time_compiled(compiled, (T, winv, r), reps)
            except Exception as exc:  # candidate unrunnable, not fatal
                scored.append(
                    {"tile": int(tile), "error": f"{type(exc).__name__}: {exc}"}
                )
                continue
            # two normalizations before the cost gauges can be an
            # objective: (1) XLA's cost analysis prices a scan/grid
            # BODY once, not x trip count — extrapolate by the step
            # count or small tiles read as 1/steps the flops of big
            # ones; (2) a tile larger than Nt pads the grid, and
            # padded rows are counted work that produces nothing —
            # score only the unpadded fraction (otherwise a 3x-padded
            # tile can "win" on busywork).
            padded = -(-ntoa // int(tile)) * int(tile)
            steps = padded // int(tile)
            useful = ntoa / padded
            flops = float(cost.get("flops", 0.0)) * steps
            nbytes = cost.get("bytes_accessed")
            roof = devprof.roofline(
                label,
                flops=flops,
                bytes_accessed=(
                    None if nbytes is None else float(nbytes) * steps
                ),
                elapsed_s=elapsed,
            )
            base = roof.get("flops_per_s") or 1.0 / max(elapsed, 1e-12)
            objective = float(base) * useful
            scored.append(
                {
                    "tile": int(tile),
                    "median_s": elapsed,
                    "flops": flops,
                    "useful_fraction": useful,
                    "objective_flops_per_s": objective,
                }
            )
        ok = [s for s in scored if "error" not in s]
        if not ok:
            raise RuntimeError(
                f"gp_tune: no runnable tile candidate on backend "
                f"{backend!r}: {scored}"
            )
        best = max(ok, key=lambda s: s["objective_flops_per_s"])
        choice = {
            "tile": best["tile"],
            "backend": backend,
            "bucket": bucket,
            "device_kind": device_kind(),
            "objective_flops_per_s": best["objective_flops_per_s"],
            "candidates": [int(c) for c in candidates],
            "scored": scored,
        }
        if write:
            save_cache({key: choice}, cache_path)
    return choice
