from .white_noise import add_measurement_noise, add_jitter
from .red_noise import add_chromatic_noise, add_red_noise
from .gwb import add_gwb
from .cgw import add_cgw, add_catalog_of_cws
from .bursts import add_burst, add_noise_transient, add_gw_memory
from .population import add_gwb_plus_outlier_cws, population_recipe, split_population

__all__ = [
    "add_measurement_noise",
    "add_jitter",
    "add_chromatic_noise",
    "add_red_noise",
    "add_gwb",
    "add_cgw",
    "add_catalog_of_cws",
    "add_burst",
    "add_noise_transient",
    "add_gw_memory",
    "add_gwb_plus_outlier_cws",
    "population_recipe",
    "split_population",
]
