"""Batched, key-driven device ops: every injection as a pure JAX function.

Each op maps ``(key, batch, params) -> delays`` of shape (Np, Nt); a
realization is the sum of the ops a :class:`Recipe` enables, and a
realization *batch* is ``jax.vmap`` of :func:`realization_delays` over PRNG
keys — the realization axis the reference lacks entirely (its operators
mutate one global dataset; SURVEY.md section 2, parallelism inventory).

Per-backend parameters are (Np, n_backends) arrays gathered per TOA/epoch
through the integer index arrays the freeze step produced — the device
equivalent of the reference's string-flag loops
(/root/reference/pta_replicator/white_noise.py:95-103).
"""
from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..batch import PulsarBatch
from .cgw import principal_axes
from .gwb import (
    characteristic_strain,
    dft_synthesis_matrices,
    gwb_grid,
    residual_psd_coeff,
)


#: Version of the op suite's PRNG stream contract. Bump whenever any
#: op's key-consumption order or draw layout changes (e.g. the red-noise
#: coefficient interleave), so resumable sweeps checkpointed under a
#: different stream refuse to resume instead of silently mixing streams.
#: v3: white noise draws ONE combined-variance normal per TOA (was two).
#: v4: realization_delays splits 5 subkeys (chromatic-noise stage added
#: between red noise and GWB).
STREAM_VERSION = 4


def _check_backend_table(params, batch, name: str):
    """Fail loudly (at trace time — shapes are static) when a per-backend
    table is narrower than the batch's backend vocabulary: the
    out-of-bounds gather would otherwise FILL with NaN and silently
    poison every downstream realization."""
    params = jnp.asarray(params)
    nb = len(batch.backend_names)
    if params.ndim == 2 and nb and params.shape[1] < nb:
        raise ValueError(
            f"{name} table has {params.shape[1]} backend column(s) but "
            f"the batch carries {nb} backends ({batch.backend_names}); "
            "size per-backend tables to PulsarBatch.backend_names"
        )
    return params


def _per_toa(params, index, mask):
    """Gather per-backend parameters onto TOAs: (Np, NB) -> (Np, Nt)."""
    params = jnp.asarray(params)
    if params.ndim == 1:
        return params[:, None] * mask
    return jnp.take_along_axis(params, index, axis=1) * mask


def _rows_draw(draw, key, rows, local_shape, *args):
    """Draw a pulsar-major random block, optionally as an exact row
    window of the *global* draw.

    ``rows=None``: plain ``draw(key, local_shape, *args)``. Under a
    pulsar-sharded ``shard_map`` (parallel.mesh.shardmap_realize),
    ``rows=(npsr_global, row_start)``: every shard regenerates the full
    (npsr_global, ...) stream from the replicated key and slices its own
    rows — bitwise equal to the unsharded computation, with zero
    collectives (same device-replicated-RNG idea as the GWB mix in
    :func:`gwb_delays`). Deliberate tradeoff: the RNG-bit generation is
    replicated per shard, so 'psr' sharding only reduces the non-RNG
    portion of per-device work (basis contractions, epoch gathers, the
    ORF mix rows) — it is a memory/model-parallel axis, not a way to
    speed up draw-bound stages. Scale those with the 'real' axis.
    """
    if rows is None:
        return draw(key, local_shape, *args)
    npsr_global, row_start = rows
    full = draw(key, (npsr_global,) + tuple(local_shape[1:]), *args)
    return jax.lax.dynamic_slice_in_dim(full, row_start, local_shape[0], 0)


# ------------------------------------------------------------- injection ops

def white_noise_delays(
    key,
    batch: PulsarBatch,
    efac=1.0,
    log10_equad=None,
    tnequad: bool = False,
    rows=None,
):
    """EFAC/EQUAD white noise. ``efac``/``log10_equad`` are scalars, (Np,)
    vectors, or (Np, n_backends) per-backend tables. ``rows``: global-row
    window for pulsar-sharded SPMD (see :func:`_rows_draw`).

    One normal per TOA at the combined per-TOA standard deviation
    (sum of two independent zero-mean Gaussians == one Gaussian with the
    summed variance) — the oracle path keeps the reference's two-draw
    layout for seed parity (models.white_noise.measurement_noise_delay,
    reference white_noise.py:112-121); on device the draw is the dominant
    cost of this op, and halving the RNG bits is distribution-exact.
    The per-signal ledger decomposition is unaffected: the op reports one
    'measurement_noise' delay vector either way."""
    dtype = batch.toas_s.dtype
    shape = batch.toas_s.shape
    eps = _rows_draw(jax.random.normal, key, rows, shape, dtype)
    ef = _check_backend_table(efac, batch, "efac").astype(dtype)
    ef = jnp.broadcast_to(ef, (batch.npsr,)) if ef.ndim == 0 else ef
    efac_t = _per_toa(ef, batch.backend_index, batch.mask)
    var = (efac_t * batch.errors_s) ** 2
    if log10_equad is not None:
        eq = 10.0 ** _check_backend_table(
            log10_equad, batch, "log10_equad"
        ).astype(dtype)
        eq = jnp.broadcast_to(eq, (batch.npsr,)) if eq.ndim == 0 else eq
        equad_t = _per_toa(eq, batch.backend_index, batch.mask)
        if not tnequad:
            equad_t = efac_t * equad_t
        var = var + equad_t**2
    return jnp.sqrt(var) * eps * batch.mask


def jitter_delays(key, batch: PulsarBatch, log10_ecorr, rows=None):
    """ECORR jitter: one draw per (pulsar, epoch), scaled per-epoch and
    gathered onto TOAs. ``log10_ecorr``: scalar, (Np,), or (Np, NB).
    ``rows``: global-row window for pulsar-sharded SPMD."""
    eps = _rows_draw(
        jax.random.normal, key, rows,
        (batch.npsr, batch.max_epochs), batch.toas_s.dtype,
    )
    ec = 10.0 ** _check_backend_table(
        log10_ecorr, batch, "log10_ecorr"
    ).astype(batch.toas_s.dtype)
    if ec.ndim == 0:
        per_epoch = ec * batch.epoch_mask
    elif ec.ndim == 1:
        per_epoch = ec[:, None] * batch.epoch_mask
    else:
        per_epoch = (
            jnp.take_along_axis(ec, batch.epoch_backend_index, axis=1)
            * batch.epoch_mask
        )
    val = per_epoch * eps
    return jnp.take_along_axis(val, batch.epoch_index, axis=1) * batch.mask


def red_noise_basis_prior(
    batch: PulsarBatch,
    log10_amplitude,
    gamma,
    nmodes: int = 30,
    modes=None,
    logf: bool = False,
    fmin=None,
    fmax=None,
    phase_shift=None,
    libstempo_convention: bool = False,
    tspan_s=None,
):
    """Per-pulsar Fourier basis and power-law prior for the device path,
    with the full option surface of the reference's design matrix
    (reference red_noise.py:36-103): default k/T grids, log/linear
    fmin-fmax spacing, explicit modes, per-mode phase shifts, and the
    libstempo convention ([cos, sin] column order, times referenced to
    each pulsar's first TOA).

    Returns ``(F (Np, Nt, 2K), prior (Np, 2K))`` with sin/cos columns
    interleaved per frequency exactly like the oracle basis
    (ops.fourier.fourier_basis), so a shared coefficient stream produces
    identical delays on both paths.
    """
    from ..ops.fourier import (
        fourier_basis,
        fourier_frequencies,
        powerlaw_prior,
    )

    dtype = batch.toas_s.dtype
    log10_amplitude = jnp.broadcast_to(
        jnp.asarray(log10_amplitude, dtype), (batch.npsr,)
    )
    gamma = jnp.broadcast_to(jnp.asarray(gamma, dtype), (batch.npsr,))
    T = batch.tspan_s if tspan_s is None else jnp.broadcast_to(
        jnp.asarray(tspan_s, dtype), (batch.npsr,)
    )
    freqs = fourier_frequencies(
        T, nmodes=nmodes, logf=logf, fmin=fmin, fmax=fmax, modes=modes,
        xp=jnp,
    )
    freqs = jnp.broadcast_to(
        jnp.asarray(freqs, dtype), (batch.npsr, freqs.shape[-1])
    )
    shift = (
        None if phase_shift is None
        else jnp.broadcast_to(jnp.asarray(phase_shift, dtype), freqs.shape)
    )
    F = fourier_basis(
        batch.toas_s, freqs, phase_shift=shift,
        libstempo_convention=libstempo_convention, xp=jnp,
    )
    prior2 = powerlaw_prior(
        jnp.repeat(freqs, 2, axis=-1), log10_amplitude, gamma, T, xp=jnp
    )
    return F, prior2


def red_noise_delays(
    key,
    batch: PulsarBatch,
    log10_amplitude,
    gamma,
    nmodes: int = 30,
    modes=None,
    logf: bool = False,
    fmin=None,
    fmax=None,
    pshift: bool = False,
    phase_shift=None,
    libstempo_convention: bool = False,
    tspan_s=None,
    eps=None,
    rows=None,
):
    """Per-pulsar power-law red noise on the rank-reduced Fourier basis.

    The (Np, Nt, 2K) basis is built in-kernel from the frozen times (cheap,
    XLA fuses the trig into the MXU contraction). Accepts everything the
    oracle ``add_red_noise`` / reference design matrix does: explicit
    ``modes``, log/linear ``fmin``-``fmax`` grids, random per-mode phase
    shifts (``pshift``, drawn from ``key``; or explicit via
    ``phase_shift``), ``libstempo_convention``, and a ``tspan_s``
    override. ``eps`` injects an explicit (Np, 2K) coefficient stream
    (oracle-equivalence tests; normally drawn from ``key``).
    """
    dtype = batch.toas_s.dtype
    if pshift and phase_shift is None:
        k_eps, k_shift = jax.random.split(key)
        nm = nmodes if modes is None else len(modes)
        phase_shift = _rows_draw(
            jax.random.uniform, k_shift, rows,
            (batch.npsr, nm), dtype, 0.0, 2.0 * jnp.pi,
        )
    else:
        k_eps = key
    F, prior2 = red_noise_basis_prior(
        batch, log10_amplitude, gamma, nmodes=nmodes, modes=modes,
        logf=logf, fmin=fmin, fmax=fmax, phase_shift=phase_shift,
        libstempo_convention=libstempo_convention, tspan_s=tspan_s,
    )
    if eps is None:
        eps = _rows_draw(jax.random.normal, k_eps, rows, prior2.shape, dtype)
    coeff = jnp.sqrt(prior2) * jnp.asarray(eps, dtype)
    return jnp.einsum("pnk,pk->pn", F, coeff) * batch.mask


def chromatic_noise_delays(
    key,
    batch: PulsarBatch,
    log10_amplitude,
    gamma,
    chromatic_index=2.0,
    nmodes: int = 30,
    ref_freq_mhz: float = 1400.0,
    tspan_s=None,
    eps=None,
    rows=None,
):
    """Chromatic (radio-frequency-dependent) power-law red noise: the
    achromatic Fourier-basis process scaled per TOA by
    ``(ref_freq/freq)^chromatic_index`` — index 2 is dispersion-measure
    noise, 4 scattering. Amplitude is defined at ``ref_freq_mhz``.

    Beyond-reference signal family (the reference injects only
    achromatic red noise, red_noise.py:106-135); the oracle twin is
    models.red_noise.add_chromatic_noise. Requires the batch to carry
    observing frequencies (``freeze`` populates them from the tim files).
    """
    if batch.freqs_mhz is None:
        raise ValueError(
            "chromatic noise needs batch.freqs_mhz — re-freeze a dataset "
            "whose TOAs carry observing frequencies (batches frozen "
            "before chromatic support, or from frequency-less TOAs, "
            "lack them)"
        )
    dtype = batch.toas_s.dtype
    idx = jnp.asarray(chromatic_index, dtype)
    if idx.ndim >= 1:  # per-pulsar exponent broadcasts over the TOA axis
        idx = idx[..., None]
    # freq <= 0 is the TEMPO convention for infinite-frequency
    # (barycentric) TOAs: the chromatic delay there is exactly zero, not
    # the inf a naive (ref/0)^idx would inject. Substitute 1.0 (not a tiny
    # epsilon) for the untaken branch: (ref/eps)^idx overflows to inf at
    # f32, and an inf in the untaken where-branch poisons gradients if
    # this op is ever differentiated (the oracle uses the same 1.0
    # substitution)
    safe = jnp.where(
        batch.freqs_mhz > 0.0, batch.freqs_mhz, jnp.asarray(1.0, dtype)
    )
    scale = jnp.where(
        batch.freqs_mhz > 0.0,
        (jnp.asarray(ref_freq_mhz, dtype) / safe) ** idx,
        0.0,
    )
    # the achromatic process IS red_noise_delays (same stream, same
    # basis/prior); chromaticity is a per-TOA elementwise scale on top
    return scale * red_noise_delays(
        key, batch, log10_amplitude, gamma, nmodes=nmodes,
        tspan_s=tspan_s, eps=eps, rows=rows,
    )


def uniform_grid_interp(t, start, stop, series):
    """Linear interpolation of (..., npts) series sampled on a *uniform*
    grid [start, stop] onto (..., Nt) query times.

    Equivalent to ``jnp.interp`` for in-range queries but with direct index
    arithmetic instead of a searchsorted binary search (the grid spacing is
    known), which removes the gather-heavy log(npts) search from the GWB's
    per-TOA resampling."""
    npts = series.shape[-1]
    pos = (t - start) / (stop - start) * (npts - 1)
    idx = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, npts - 2)
    frac = jnp.clip(pos - idx, 0.0, 1.0)
    lo = jnp.take_along_axis(series, idx, axis=-1)
    hi = jnp.take_along_axis(series, idx + 1, axis=-1)
    return lo + frac * (hi - lo)


def gwb_delays(
    key,
    batch: PulsarBatch,
    log10_amplitude,
    gamma,
    orf_cholesky,
    npts: int = 600,
    howml: float = 10,
    turnover: bool = False,
    f0: float = 1e-9,
    beta: float = 1.0,
    power: float = 1.0,
    user_spectrum=None,
    synthesis: str = "auto",
    synthesis_precision=None,
):
    """Correlated GWB across the array: the one cross-pulsar op.

    The (Np x Np) x (Np x Nf) mix is a single einsum against the Cholesky
    factor of the ORF (computed once on CPU in f64 — see ops.orf); the
    synthesis FFT and the per-pulsar interpolation are batched. Under a
    sharded realization axis this whole function is embarrassingly
    parallel; with the pulsar axis sharded, XLA turns the einsum into a
    psum over the pulsar mesh axis (reference analog red_noise.py:265-287).
    """
    dtype = batch.toas_s.dtype
    ut, dt_grid, f = gwb_grid(batch.start_s, batch.stop_s, npts, howml)
    ut = jnp.asarray(ut, dtype)
    f = jnp.asarray(f, dtype)
    nf = f.shape[0]
    dur = batch.stop_s - batch.start_s

    # draw per-pulsar spectra at the ORF's *column* count, not batch.npsr:
    # identical when M is the usual square (Np, Np) factor, but under
    # explicit pulsar sharding (shard_map with M rows sharded over 'psr')
    # every shard holds a (Np_local, Np_global) row block and — because
    # the key is replicated — regenerates the same global w, so the local
    # mix M_local @ w equals the corresponding rows of the unsharded
    # result with zero collectives (parallel/mesh.shardmap_realize).
    w = jax.random.normal(key, (2, jnp.shape(orf_cholesky)[1], nf), dtype)
    w = jax.lax.complex(w[0], w[1])

    hcf = characteristic_strain(
        f,
        log10_amplitude,
        gamma,
        turnover=turnover,
        f0=f0,
        beta=beta,
        power=power,
        user_spectrum=user_spectrum,
        xp=jnp,
    )
    C = residual_psd_coeff(hcf, f, dur, howml, xp=jnp)

    M = jnp.asarray(orf_cholesky, dtype)
    res_f = jnp.einsum("ab,bf->af", M, w) * jnp.sqrt(C)
    # zero DC and "Nyquist" bins, then synthesize the hermitian spectrum on
    # the time grid. Only npts+10 of the 2*nf-2 output samples are used, so
    # when the grid is oversampled (howml > ~1, always in practice) a direct
    # (Np, nf) x (nf, npts) MXU contraction beats the FFT — whose length
    # 2*nf-2 is a terrible radix for the default config (5998 = 2 x 2999,
    # prime => Bluestein). 'fft' is kept for cross-checking.
    mask = jnp.concatenate([jnp.zeros(1, dtype), jnp.ones(nf - 2, dtype), jnp.zeros(1, dtype)])
    res_f = res_f * mask
    if synthesis == "auto":
        synthesis = "matmul" if npts + 10 < 2 * nf - 2 else "fft"
    if synthesis == "matmul":
        cos_m, sin_m = dft_synthesis_matrices(nf, npts)
        scale = 2.0 / ((2 * nf - 2) * dt_grid)
        # synthesis_precision tunes the MXU pass count of the DFT
        # contraction (None = backend default; 'highest' = full f32;
        # lower settings trade GWB waveform accuracy for speed -- the
        # knob exists so the tradeoff is measurable, DESIGN.md section 7)
        grid_series = (
            jnp.matmul(
                jnp.real(res_f), jnp.asarray(cos_m, dtype),
                precision=synthesis_precision,
            )
            - jnp.matmul(
                jnp.imag(res_f), jnp.asarray(sin_m, dtype),
                precision=synthesis_precision,
            )
        ) * jnp.asarray(scale, dtype)
    else:
        res_t = jnp.fft.irfft(res_f, n=2 * nf - 2, axis=-1) / dt_grid
        grid_series = res_t[:, 10 : npts + 10].astype(dtype)

    return uniform_grid_interp(batch.toas_s, ut[0], ut[-1], grid_series) * batch.mask


def _cw_tile_response(toas_rel, src_tile, psr_tile, psr_term: bool,
                      evolve: bool):
    """(Np, Nt) response sum of ONE ``chunk``-wide coefficient tile
    (``src_tile`` (NC_SRC, chunk), ``psr_tile`` (NC_PSR, Np, chunk)),
    vmapped over pulsars with a (chunk, Nt) workspace per pulsar.

    The ONE per-tile op sequence shared by the monolithic scan backend
    (:func:`_cw_scan_response`'s body) and the streamed accumulator
    (:func:`cw_stream_response`'s jitted step): the f32 phase math
    amplifies even 1-ulp formula differences to ~3e-4 after
    sin(2*phase), so the two paths must run the SAME ops to be — as
    tests/test_cw_stream.py asserts — bit-identical."""
    from ..ops.pallas_cw import (
        _PSR_PLANES,
        _SRC_PLANES,
        _polarized,
        _term_response,
    )

    def one_psr(u_row, psr_t, src_t):
        # (chunk, 1) coefficient columns against the (1, Nt) time row;
        # named plane lookups keep this in lockstep with the kernel
        sp = lambda n: src_t[_SRC_PLANES.index(n)][:, None]
        pp = lambda n: psr_t[_PSR_PLANES.index(n)][:, None]
        u = u_row[None, :]
        inc1, inc2 = sp("incfac1"), sp("incfac2")
        s2p, c2p = sp("sin2psi"), sp("cos2psi")
        phase, alpha = _term_response(
            u, sp("phi0_e"), sp("rate_e"), sp("pn_e"), sp("amp_e"), evolve
        )
        rplus, rcross = _polarized(phase, alpha, inc1, inc2, s2p, c2p)
        if psr_term:
            phase_p, alpha_p = _term_response(
                u, pp("phi0_p"), pp("rate_p"), pp("pn_p"), pp("amp_p"),
                evolve,
            )
            rplus_p, rcross_p = _polarized(
                phase_p, alpha_p, inc1, inc2, s2p, c2p
            )
            res = pp("fplus") * (rplus_p - rplus) + pp("fcross") * (
                rcross_p - rcross
            )
        else:
            res = -pp("fplus") * rplus - pp("fcross") * rcross
        res = jnp.where(jnp.isnan(res), 0.0, res) * sp("valid")
        return jnp.sum(res, axis=0)

    return jax.vmap(one_psr, in_axes=(0, 1, None))(
        toas_rel, psr_tile, src_tile
    )


def _cw_scan_response(
    toas_rel, src_c, psr_c, psr_term: bool, evolve: bool, chunk: int
):
    """Portable plane-consuming fallback for :func:`cw_catalog_response`:
    ``lax.scan`` over ``chunk``-sized source tiles, vmapped over pulsars,
    so only a (chunk, Nt) workspace is live per pulsar while the scan
    accumulates the (Np, Nt) sum. The streamed pipeline
    (:func:`cw_stream_response`) runs the same scan body per macro tile
    via :func:`_cw_stream_step`, carrying its accumulator through as
    the scan init."""
    from ..ops.pallas_cw import NC_PSR, NC_SRC

    dtype = toas_rel.dtype
    npsr, _ = toas_rel.shape
    nsrc = src_c.shape[1]
    npad = (-nsrc) % chunk
    src_p = jnp.pad(src_c, ((0, 0), (0, npad)))
    psr_p = jnp.pad(psr_c, ((0, 0), (0, 0), (0, npad)))
    nch = (nsrc + npad) // chunk
    src_tiles = src_p.reshape(NC_SRC, nch, chunk).transpose(1, 0, 2)
    psr_tiles = psr_p.reshape(NC_PSR, npsr, nch, chunk).transpose(2, 0, 1, 3)

    def step(carry, tiles):
        src_tile, psr_tile = tiles
        return carry + _cw_tile_response(
            toas_rel, src_tile, psr_tile, psr_term, evolve
        ), None

    # derive the carry init from the (possibly device-varying) input so
    # its sharding/vma type matches the body output under shard_map with
    # a sharded pulsar axis (a fresh jnp.zeros is 'unvarying' and fails
    # scan's carry type check there)
    init = toas_rel * jnp.zeros((), dtype)
    total, _ = jax.lax.scan(step, init, (src_tiles, psr_tiles))
    return total


def cw_catalog_planes_for(
    batch: PulsarBatch,
    gwtheta,
    gwphi,
    mc,
    dist,
    fgw,
    phase0,
    psi,
    inc,
    pdist=1.0,
    pphase=None,
    evolve: bool = True,
    phase_approx: bool = False,
    tref_s: float = 0.0,
):
    """Accurate (f64 host) epoch-folded CW coefficient planes for this
    batch: ``(src (NC_SRC, Ns), psr (NC_PSR, Np, Ns), evolve)``, fold
    epoch matched to the batch's time reference. The returned ``evolve``
    flag is the one the response kernels must branch on — it travels
    with the planes so the two stages cannot silently disagree:

        src, psr, evolve = cw_catalog_planes_for(batch, *params)
        d = cgw_catalog_delays_from_planes(batch, src, psr, evolve=evolve)

    Requires concrete (non-tracer) parameters — this is the precompute
    that makes the f32 device path accurate. For catalog *sweeps*, call
    this per catalog on host, stack the planes, and vmap
    :func:`cgw_catalog_delays_from_planes` over the stacks; planes are
    plain data, so passing them through jit boundaries loses nothing
    (unlike raw source parameters — docs/DESIGN.md section 3).
    """
    from ..ops.pallas_cw import cw_catalog_planes

    params = (gwtheta, gwphi, mc, dist, fgw, phase0, psi, inc)
    tracer = jax.core.Tracer
    if any(
        isinstance(x, tracer)
        for x in (batch.phat, pdist, pphase, *params)
        if x is not None
    ):
        raise TypeError(
            "cw_catalog_planes_for requires concrete parameters (the f64 "
            "host precompute cannot run on tracers); precompute planes "
            "outside jit and pass them through as data"
        )
    t_fold = batch.tref_mjd * 86400.0 - tref_s + batch.start_s
    src_c, psr_c = cw_catalog_planes(
        np.asarray(batch.phat, np.float64),
        *[np.atleast_1d(np.asarray(x, np.float64)) for x in params],
        pdist=np.asarray(pdist, np.float64),
        pphase=None if pphase is None else np.asarray(pphase, np.float64),
        t_fold=t_fold, evolve=evolve, phase_approx=phase_approx,
        xp=np, dtype=batch.toas_s.dtype,
    )
    return src_c, psr_c, evolve


def cw_catalog_plane_tiles_for(
    batch: PulsarBatch,
    gwtheta,
    gwphi,
    mc,
    dist,
    fgw,
    phase0,
    psi,
    inc,
    pdist=1.0,
    pphase=None,
    evolve: bool = True,
    phase_approx: bool = False,
    tref_s: float = 0.0,
    chunk: int = 65536,
):
    """Streaming twin of :func:`cw_catalog_planes_for`: a generator of
    ``chunk``-sized host plane tiles ``(src (NC_SRC, cs),
    psr (NC_PSR, Np, cs))``, f64 host math per tile, cast to the batch
    dtype — each tile bit-identical to the corresponding column slice
    of the monolithic planes, with peak host memory O(Np x chunk)
    instead of O(Np x Ns) (ops.pallas_cw.cw_catalog_plane_tiles).

    Feed the tiles to :func:`cw_stream_response` (optionally through
    the parallel.prefetch on-disk cache), or simply call
    :func:`cgw_catalog_delays_streamed`, which wires the whole
    pipeline. Requires concrete (non-tracer) parameters like the
    monolithic precompute — there is no traced fallback here: the
    whole point of streaming is the bounded-memory HOST build.
    """
    from ..ops.pallas_cw import cw_catalog_plane_tiles

    params = (gwtheta, gwphi, mc, dist, fgw, phase0, psi, inc)
    tracer = jax.core.Tracer
    if any(
        isinstance(x, tracer)
        for x in (batch.phat, pdist, pphase, *params)
        if x is not None
    ):
        raise TypeError(
            "cw_catalog_plane_tiles_for requires concrete parameters "
            "(the f64 host precompute cannot run on tracers); build the "
            "streamed delays outside jit and pass them through as data "
            "(e.g. the `static=` argument of realize/sweep)"
        )
    t_fold = batch.tref_mjd * 86400.0 - tref_s + batch.start_s
    return cw_catalog_plane_tiles(
        np.asarray(batch.phat, np.float64),
        *[np.atleast_1d(np.asarray(x, np.float64)) for x in params],
        pdist=np.asarray(pdist, np.float64),
        pphase=None if pphase is None else np.asarray(pphase, np.float64),
        t_fold=t_fold, evolve=evolve, phase_approx=phase_approx,
        chunk=chunk, dtype=batch.toas_s.dtype,
    )


@functools.lru_cache(maxsize=None)
def _cw_stream_step(psr_term: bool, evolve: bool, donate: bool):
    """Jitted macro-tile accumulator: ``lax.scan`` the monolithic
    backend's own per-tile body over a staged macro — a host-stacked
    ``(K, NC_SRC, chunk)`` / ``(K, NC_PSR, Np, chunk)`` tile block,
    already in the scan's operand layout — with the accumulator as the
    scan CARRY. Per-call dispatch overhead amortizes over the macro,
    the f32 accumulation order stays that of one monolithic scan
    (bit-identity), and the monolithic path's on-device
    pad/reshape/transpose of the full plane set has no streamed
    counterpart at all: the stacking happened tile-by-tile on the
    prefetch worker. Cached per (psr_term, evolve, donate); jit
    re-specializes per macro shape (two in practice: full macros and
    the tail). ``donate`` aliases the accumulator buffer into the
    output off-CPU (the previous partial sum is dead the moment the
    new one exists)."""

    def step(acc, toas_rel, src_tiles, psr_tiles):
        def body(carry, tiles):
            src_tile, psr_tile = tiles
            return carry + _cw_tile_response(
                toas_rel, src_tile, psr_tile, psr_term, evolve
            ), None

        total, _ = jax.lax.scan(body, acc, (src_tiles, psr_tiles))
        return total

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def cw_stream_response(
    batch: PulsarBatch,
    tiles,
    evolve: bool,
    psr_term: bool = True,
    prefetch_depth: int = 2,
    tiles_per_step: int = 8,
    stall_timeout_s=900.0,
    mesh=None,
):
    """Summed CW response (Np, Nt) from a *stream* of plane tiles, with
    double-buffered host->device prefetch: the next macro tile is built
    (f64 host math) and staged (``jax.device_put``) on a background
    thread while the jitted scan step consumes the current one,
    accumulating the (Np, Nt) sum on device — no stage ever holds more
    than ``prefetch_depth`` macro tiles, and the monolithic path's
    full-catalog pad/reshape/transpose copies never exist.

    ``tiles`` yields host ``(src, psr)`` tiles in catalog order
    (:func:`cw_catalog_plane_tiles_for`, or a cache iterator from
    parallel.prefetch.load_plane_tiles), all the same width except an
    optionally narrower LAST tile (zero-padded on the host — the same
    zeros the monolithic path pads with, inert via ``valid=0``).
    ``tiles_per_step`` tiles are stacked per staged macro — the stack
    IS the scan's operand layout, so the device runs no
    pad/reshape/transpose at all — amortizing per-dispatch overhead
    while the staging granularity stays bounded
    (tiles_per_step x tile bytes).

    Bit-identical to ``cgw_catalog_delays_from_planes(...,
    backend="scan", chunk=<tile width>)`` on the same catalog: each
    macro is scanned by the SAME per-tile body
    (:func:`_cw_tile_response`), with the accumulator threaded through
    as the scan carry — same tile sequence, same f32 accumulation
    order as one monolithic scan (tests/test_cw_stream.py asserts
    exact equality at prefetch depths 1/2/4 and several
    ``tiles_per_step`` groupings).

    On a multi-device ``mesh`` the staging fans out per device
    (parallel.prefetch.prefetch_to_mesh): the pulsar-plane macros
    shard along 'psr' (each chip receives and accumulates only its
    pulsars — the per-source sum order per pulsar is unchanged, so the
    result stays bit-identical to the single-chip stream), the source
    planes replicate, and the (Np, Nt) accumulator lives psr-sharded
    on the mesh — ready for :func:`~pta_replicator_tpu.parallel.mesh.
    static_delays` to hand to the sharded engines without a host
    round-trip.
    """
    from ..obs import gauge, names, numerics, span
    from ..parallel.prefetch import prefetch_to_device

    if tiles_per_step < 1:
        raise ValueError(f"tiles_per_step must be >= 1 (got {tiles_per_step})")
    dtype = batch.toas_s.dtype
    u = batch.toas_s - jnp.asarray(batch.start_s, dtype)
    width = [None]  # established by the stream's first tile

    def macros():
        """Stack ``tiles_per_step`` host tiles per staged macro (runs
        on the prefetch worker thread, so the copy overlaps device
        compute)."""
        buf_s, buf_p = [], []
        tail_seen = False
        for src, psr in tiles:
            src, psr = np.asarray(src), np.asarray(psr)  # graftlint: disable=jax-host-sync — prefetch worker thread stacking host tiles (cw_stream_response is host-driven; traced params raise in cw_catalog_plane_tiles_for)
            if width[0] is None:
                width[0] = src.shape[-1]
            if tail_seen or src.shape[-1] > width[0]:
                raise ValueError(
                    f"plane tile of width {src.shape[-1]} after the "
                    f"stream established width {width[0]}; tiles must be "
                    "uniform with an optional narrower LAST tile "
                    "(anything else would misalign the scan windows and "
                    "break bit-identity with the monolithic backend)"
                )
            pad = width[0] - src.shape[-1]
            if pad:
                tail_seen = True
                src = np.pad(src, ((0, 0), (0, pad)))
                psr = np.pad(psr, ((0, 0), (0, 0), (0, pad)))
            buf_s.append(src)
            buf_p.append(psr)
            if len(buf_s) == tiles_per_step:
                yield np.stack(buf_s), np.stack(buf_p)
                buf_s, buf_p = [], []
        if buf_s:
            yield np.stack(buf_s), np.stack(buf_p)

    multichip = mesh is not None and int(mesh.devices.size) > 1
    platform = (
        mesh.devices.flat[0].platform if multichip
        else jax.default_backend()
    )
    donate = bool(donate_keys_argnums(platform))
    step = _cw_stream_step(psr_term, evolve, donate)
    acc = jnp.zeros(batch.toas_s.shape, dtype)
    nmacros = 0
    with span(names.SPAN_CW_STREAM_RESPONSE, depth=prefetch_depth) as sp:
        gauge(names.CW_STREAM_TILES_DONE).set(0)
        if multichip:
            from jax.sharding import PartitionSpec as P

            from ..parallel.mesh import put_sharded
            from ..parallel.prefetch import prefetch_to_mesh

            # accumulator + TOA grid live psr-sharded; the psr-plane
            # macros (K, NC_PSR, Np, cs) shard their pulsar axis so
            # each chip stages and accumulates only its own pulsars,
            # while the source planes replicate to every chip
            acc = put_sharded(acc, mesh, P("psr", None))
            u = put_sharded(u, mesh, P("psr", None))
            staged = prefetch_to_mesh(
                macros(),
                mesh,
                specs=(P(), P(None, None, "psr", None)),
                depth=prefetch_depth,
                stall_timeout_s=stall_timeout_s,
            )
        else:
            staged = prefetch_to_device(
                macros(),
                depth=prefetch_depth,
                stall_timeout_s=stall_timeout_s,
            )
        ntiles = 0
        for src_macro, psr_macro in staged:
            acc = step(acc, u, src_macro, psr_macro)
            nmacros += 1
            # the gauge reads in TILE units (a macro's leading axis is
            # its tile count), matching the docs and the ungrouped
            # streams memprobe/tests consume
            ntiles += int(src_macro.shape[0])
            gauge(names.CW_STREAM_TILES_DONE).set(ntiles)
        sp["macros"] = nmacros
        sp["tiles"] = ntiles
        sp["tiles_per_step"] = tiles_per_step
    # numerics observatory seam: the streamed accumulator is the one
    # place a whole catalog's f32 accumulation order concentrates —
    # an overflowing tile surfaces here, not per-source. Identity (and
    # compiled out entirely) while disarmed; see obs/numerics.py.
    return numerics.probe("cw.stream_tile", acc * batch.mask)


def cgw_catalog_delays_streamed(
    batch: PulsarBatch,
    gwtheta,
    gwphi,
    mc,
    dist,
    fgw,
    phase0,
    psi,
    inc,
    pdist=1.0,
    pphase=None,
    psr_term: bool = True,
    evolve: bool = True,
    phase_approx: bool = False,
    tref_s: float = 0.0,
    chunk: int = 65536,
    prefetch_depth: int = 2,
    tiles_per_step: int = 8,
    stall_timeout_s=900.0,
    mesh=None,
):
    """Summed CW-catalog response with the full streaming pipeline:
    tiled f64 host precompute -> double-buffered host->device prefetch
    -> jitted on-device accumulation, peak memory O(Np x chunk) at
    every stage. Bit-identical to
    ``cgw_catalog_delays(..., chunk=chunk, backend="scan")`` — same
    planes (per tile), same op sequence, same accumulation order —
    but never materializes the (NC_PSR, Np, Ns) plane set that
    segfaults the monolithic path at the reference's 1e7-source
    flagship regime (CW_SCALING_r05_cpu.json, ~113 GB at 68 pulsars).

    Deterministic (no key), host-driven (not jittable): source
    parameters must be concrete, and the result is plain data — pass
    it through jit boundaries like any precomputed ``static`` plane.
    """
    tiles = cw_catalog_plane_tiles_for(
        batch, gwtheta, gwphi, mc, dist, fgw, phase0, psi, inc,
        pdist=pdist, pphase=pphase, evolve=evolve,
        phase_approx=phase_approx, tref_s=tref_s, chunk=chunk,
    )
    return cw_stream_response(
        batch, tiles, evolve=evolve, psr_term=psr_term,
        prefetch_depth=prefetch_depth, tiles_per_step=tiles_per_step,
        stall_timeout_s=stall_timeout_s, mesh=mesh,
    )


def cgw_catalog_delays_from_planes(
    batch: PulsarBatch,
    src_c,
    psr_c,
    evolve: bool,
    psr_term: bool = True,
    chunk: int = 512,
    backend: str = "auto",
):
    """Summed CW-catalog response from precomputed coefficient planes
    (:func:`cw_catalog_planes_for`): the jit/vmap-safe form for catalog
    sweeps — planes are data, so accuracy survives the jit boundary that
    demotes raw traced parameters. ``evolve`` is required and must be
    the flag the planes were built with (cw_catalog_planes_for returns
    it alongside them; the kernels branch on it, and a mismatch would
    apply chirp factors to linear-mode coefficients without any error).
    Backend semantics as in :func:`cgw_catalog_delays`.
    """
    from ..ops.pallas_cw import cw_catalog_response

    dtype = batch.toas_s.dtype
    u = batch.toas_s - jnp.asarray(batch.start_s, dtype)
    if backend == "auto":
        backend = "scan"  # docs/DESIGN.md section 4
    if backend == "pallas":
        # Retired round 5: measured tied-or-lost vs the scan tiling on a
        # real v5e at the flagship shape (rounds 3-4), never chosen by
        # `auto`, and the large-catalog regime where it might win never
        # got a hardware window. The kernel stays in ops/pallas_cw.py as
        # a working Mosaic study — `pallas_interpret` still runs its
        # logic everywhere, and benchmarks/cw_scaling.py measures the
        # archived kernel directly on TPU. docs/DESIGN.md section 4.
        raise ValueError(
            "CW-catalog backend 'pallas' was retired in round 5 (see "
            "docs/DESIGN.md section 4); use 'scan' (production) or "
            "'pallas_interpret' (kernel-logic study)"
        )
    if backend not in ("pallas_interpret", "scan"):
        raise ValueError(f"unknown CW-catalog backend {backend!r}")
    if backend == "pallas_interpret":
        out = cw_catalog_response(
            u, src_c, psr_c, psr_term=psr_term, evolve=evolve,
            interpret=True,
        )
    else:
        out = _cw_scan_response(u, src_c, psr_c, psr_term, evolve, chunk)
    return out * batch.mask


def cgw_catalog_delays(
    batch: PulsarBatch,
    gwtheta,
    gwphi,
    mc,
    dist,
    fgw,
    phase0,
    psi,
    inc,
    pdist=1.0,
    pphase=None,
    psr_term: bool = True,
    evolve: bool = True,
    phase_approx: bool = False,
    tref_s: float = 0.0,
    chunk: int = 512,
    backend: str = "auto",
):
    """Summed response of a CW-source catalog, tiled over sources.

    Replaces the reference's numba prange + 1e7-source python chunking
    (deterministic.py:258-294, 321-440) with explicit memory tiling of the
    (Nsrc x Nt) product. ``pdist`` (kpc) may be a scalar, (Ns,), or
    (Np, Ns); ``pphase`` ((Ns,) or (Np, Ns)) overrides it with explicit
    pulsar-term phases (reference deterministic.py:99-108). The backends
    consume the same epoch-folded coefficient planes
    (ops.pallas_cw.cw_catalog_planes — precomputed in float64 on
    the host whenever the parameters are concrete, which is what makes
    the float32 device path accurate; see the pallas_cw module docstring):

    * ``"scan"`` (= ``"auto"``, production) — a portable ``lax.scan``
      over ``chunk``-sized source tiles (the (chunk x Nt) workspace
      stays VMEM-scale while the scan accumulates the (Np, Nt) sum);
    * ``"pallas_interpret"`` — the archived Mosaic kernel's logic in
      Pallas interpret mode (kernel study / tests).

    ``"pallas"`` (the compiled TPU kernel) was RETIRED in round 5: it
    measured statistically tied-or-slower than scan on a real v5e at
    the flagship shape and was never chosen by ``auto``
    (docs/DESIGN.md section 4 keeps the Mosaic findings;
    benchmarks/cw_scaling.py can still measure the archived kernel
    directly). Deterministic (no key): source parameters are data.

    For catalog sweeps under jit/vmap, precompute planes per catalog
    with :func:`cw_catalog_planes_for` and run
    :func:`cgw_catalog_delays_from_planes` — traced source parameters
    here fall back to ambient-precision planes (docs/DESIGN.md
    section 3).
    """
    from ..ops.pallas_cw import cw_catalog_planes

    dtype = batch.toas_s.dtype
    params = (gwtheta, gwphi, mc, dist, fgw, phase0, psi, inc)
    tracer = jax.core.Tracer
    host_ok = not any(
        isinstance(x, tracer)
        for x in (batch.phat, pdist, pphase, *params)
        if x is not None
    )
    if host_ok:
        # float64 host precompute: the supported accurate path for f32
        src_c, psr_c, evolve = cw_catalog_planes_for(
            batch, *params, pdist=pdist, pphase=pphase,
            evolve=evolve, phase_approx=phase_approx, tref_s=tref_s,
        )
    else:  # traced parameters: same formulas at ambient precision.
        # fold epoch: batch start, in absolute source-frame seconds —
        # start_s is static metadata, so it stays concrete even when the
        # arrays are traced
        t_fold = batch.tref_mjd * 86400.0 - tref_s + batch.start_s
        src_c, psr_c = cw_catalog_planes(
            batch.phat, *params, pdist=pdist, pphase=pphase,
            t_fold=t_fold, evolve=evolve, phase_approx=phase_approx,
            xp=jnp, dtype=dtype,
        )
    return cgw_catalog_delays_from_planes(
        batch, src_c, psr_c, evolve=evolve, psr_term=psr_term,
        chunk=chunk, backend=backend,
    )


def _batch_antenna(gwtheta, gwphi, phat):
    """F+, Fx for one source direction against all pulsars: (Np,) each."""
    m, n, omhat = principal_axes(gwtheta, gwphi, xp=jnp)
    mp, np_, op = phat @ m, phat @ n, phat @ omhat
    fplus = 0.5 * (mp**2 - np_**2) / (1.0 + op)
    fcross = mp * np_ / (1.0 + op)
    return fplus, fcross


def gw_memory_delays(batch: PulsarBatch, strain, gwtheta, gwphi, bwm_pol,
                     t0_mjd):
    """Burst-with-memory across the array: polarization-projected strain
    ramp from epoch t0 (batched analog of bursts.add_gw_memory, reference
    deterministic.py:822-884 — whose per-TOA Python loop becomes one
    masked ramp over (Np, Nt))."""
    dtype = batch.toas_s.dtype
    fplus, fcross = _batch_antenna(
        jnp.asarray(gwtheta, dtype), jnp.asarray(gwphi, dtype), batch.phat
    )
    pol = jnp.cos(2.0 * jnp.asarray(bwm_pol, dtype)) * fplus + jnp.sin(
        2.0 * jnp.asarray(bwm_pol, dtype)
    ) * fcross
    t0_s = (jnp.asarray(t0_mjd, dtype) - batch.tref_mjd) * 86400.0
    ramp = jnp.maximum(batch.toas_s - t0_s, 0.0)
    return jnp.asarray(strain, dtype) * pol[:, None] * ramp * batch.mask


def burst_delays(batch: PulsarBatch, gwtheta, gwphi, hplus_grid, hcross_grid,
                 grid_start_s, grid_stop_s, psi=0.0):
    """Arbitrary elliptically-polarized burst across the array.

    The reference takes waveform *callables* evaluated per TOA
    (deterministic.py:718-793) — data-dependent control flow a traced
    program can't host. Device form: the waveforms arrive pre-sampled on a
    uniform (G,) grid over [grid_start_s, grid_stop_s] (times relative to
    the batch epoch, zero outside), and are linearly interpolated onto
    each pulsar's TOAs. Pair with quadratic_fit_subtract for the
    reference's remove_quad option.
    """
    dtype = batch.toas_s.dtype
    hp = jnp.asarray(hplus_grid, dtype)
    hc = jnp.asarray(hcross_grid, dtype)
    c2, s2 = jnp.cos(2.0 * jnp.asarray(psi, dtype)), jnp.sin(
        2.0 * jnp.asarray(psi, dtype)
    )
    rp, rc = hp * c2 - hc * s2, hp * s2 + hc * c2
    fplus, fcross = _batch_antenna(
        jnp.asarray(gwtheta, dtype), jnp.asarray(gwphi, dtype), batch.phat
    )
    series = -fplus[:, None] * rp[None, :] - fcross[:, None] * rc[None, :]
    out = uniform_grid_interp(batch.toas_s, grid_start_s, grid_stop_s, series)
    inside = (batch.toas_s >= grid_start_s) & (batch.toas_s <= grid_stop_s)
    return jnp.where(inside, out, 0.0) * batch.mask


def transient_delays(batch: PulsarBatch, psr_index: int, waveform_grid,
                     grid_start_s, grid_stop_s):
    """Un-projected arbitrary transient in a single pulsar (glitch-like;
    batched analog of bursts.add_noise_transient, reference
    deterministic.py:796-819), pre-sampled like burst_delays."""
    dtype = batch.toas_s.dtype
    wf = jnp.asarray(waveform_grid, dtype)
    t = batch.toas_s[psr_index]
    row = uniform_grid_interp(t, grid_start_s, grid_stop_s, wf)
    inside = (t >= grid_start_s) & (t <= grid_stop_s)
    row = jnp.where(inside, row, 0.0) * batch.mask[psr_index]
    return jnp.zeros(batch.toas_s.shape, dtype).at[psr_index].set(row)


# ------------------------------------------------------------------ recipes

@jax.tree_util.register_dataclass
@dataclass
class Recipe:
    """Which signals to inject, with their (possibly per-backend) params.

    Array leaves are traced (so parameter sweeps can be vmapped too);
    structural switches are static.
    """

    efac: Optional[jax.Array] = None
    log10_equad: Optional[jax.Array] = None
    log10_ecorr: Optional[jax.Array] = None
    rn_log10_amplitude: Optional[jax.Array] = None
    rn_gamma: Optional[jax.Array] = None
    #: explicit red-noise mode frequencies [Hz] (overrides rn_nmodes)
    rn_modes: Optional[jax.Array] = None
    #: red-noise frequency-grid bounds [Hz] (scalar or (Np,)); with
    #: rn_logf they select the general log/linear grids of the reference
    #: design matrix (red_noise.py:74-81)
    rn_fmin: Optional[jax.Array] = None
    rn_fmax: Optional[jax.Array] = None
    #: common red-noise Tspan override [s] (scalar or (Np,))
    rn_tspan_s: Optional[jax.Array] = None
    #: chromatic (DM-like) red noise: power-law amplitude at
    #: chrom_ref_freq_mhz, scaled per TOA by (ref/freq)^chrom_index
    #: (index 2 = DM noise, 4 = scattering); beyond-reference family
    chrom_log10_amplitude: Optional[jax.Array] = None
    chrom_gamma: Optional[jax.Array] = None
    chrom_index: Optional[jax.Array] = None  # defaults to 2.0 when enabled
    gwb_log10_amplitude: Optional[jax.Array] = None
    gwb_gamma: Optional[jax.Array] = None
    orf_cholesky: Optional[jax.Array] = None
    #: (F, 2) [freq_hz, hc] user characteristic-strain spectrum; overrides
    #: the power-law when present (population free-spec injection)
    gwb_user_spectrum: Optional[jax.Array] = None
    #: turnover-spectrum shape parameters (used when gwb_turnover is set;
    #: reference red_noise.py:246-252). Defaults mirror gwb_delays'.
    gwb_f0: float = 1e-9
    gwb_beta: float = 1.0
    gwb_power: float = 1.0
    #: (8, Ns) stacked CW-catalog params in the order
    #: (gwtheta, gwphi, mc, dist, fgw, phase0, psi, inc); deterministic,
    #: shared by every realization (the population-synthesis outliers)
    cgw_params: Optional[jax.Array] = None
    #: CW-catalog pulsar distances [kpc]: scalar, (Ns,), or (Np, Ns)
    cgw_pdist: Optional[jax.Array] = None
    #: explicit CW-catalog pulsar-term phases ((Ns,) or (Np, Ns));
    #: overrides cgw_pdist (reference deterministic.py:99-108)
    cgw_pphase: Optional[jax.Array] = None
    #: (5,) burst-with-memory params (strain, gwtheta, gwphi, bwm_pol,
    #: t0_mjd)
    gwm_params: Optional[jax.Array] = None
    #: (3,) burst sky/polarization (gwtheta, gwphi, psi) with the (G,)
    #: pre-sampled waveforms and (2,) [start_s, stop_s] grid window
    burst_sky: Optional[jax.Array] = None
    burst_hplus: Optional[jax.Array] = None
    burst_hcross: Optional[jax.Array] = None
    burst_grid: Optional[jax.Array] = None
    #: (G,) single-pulsar transient waveform on the (2,) grid window,
    #: injected into pulsar ``transient_psr``
    transient_waveform: Optional[jax.Array] = None
    transient_grid: Optional[jax.Array] = None
    #: structured beyond-diagonal correlated-noise block: a
    #: covariance.structure CovOp (unit-normalized; a nested pytree, so
    #: its arrays trace/shard like any other leaf). Sampled into every
    #: realization from ``fold_in(key, COV_STREAM_FOLD)`` — NOT from a
    #: widened family split, so enabling it leaves every existing
    #: family's draws bit-identical — and priced by the GLS refit and
    #: the GP likelihood through the generalized white_ecorr_solver.
    noise_cov: Optional[object] = None
    #: correlated-noise amplitude: the block's covariance is scaled by
    #: 10^(2 cov_log10_sigma) (scalar or (Np,)). A flat Recipe leaf on
    #: purpose: hyperparameter grids and map_fit address it by name.
    cov_log10_sigma: Optional[jax.Array] = None

    tnequad: bool = field(metadata=dict(static=True), default=False)
    gwb_turnover: bool = field(metadata=dict(static=True), default=False)
    rn_nmodes: int = field(metadata=dict(static=True), default=30)
    rn_logf: bool = field(metadata=dict(static=True), default=False)
    rn_pshift: bool = field(metadata=dict(static=True), default=False)
    rn_libstempo: bool = field(metadata=dict(static=True), default=False)
    chrom_nmodes: int = field(metadata=dict(static=True), default=30)
    #: Fourier modes for the GWB auto-term block in GLS weighting
    #: (gls_noise_model); the injected GWB's per-pulsar auto-covariance
    #: is weighted like a red-noise process with prior
    #: hc^2(f)/(12 pi^2 f^3 T) — Monte-Carlo-measured to match the
    #: synthesis op's coefficient variance to ~1% (test_batched).
    gwb_gls_nmodes: int = field(metadata=dict(static=True), default=30)
    chrom_ref_freq_mhz: float = field(metadata=dict(static=True), default=1400.0)
    gwb_npts: int = field(metadata=dict(static=True), default=600)
    gwb_howml: float = field(metadata=dict(static=True), default=10.0)
    cgw_tref_s: float = field(metadata=dict(static=True), default=0.0)
    cgw_chunk: int = field(metadata=dict(static=True), default=512)
    #: source-tile size for the STREAMED CW-catalog pipeline (tiled f64
    #: host precompute + double-buffered host->device prefetch,
    #: cgw_catalog_delays_streamed). None (default) = the monolithic
    #: plane build. Set it for catalogs whose full plane set exceeds
    #: host memory (the reference's 1e7-source regime). Bit-identical
    #: to the monolithic path at EQUAL tile width (== cgw_chunk); a
    #: different width reorders the f32 accumulation exactly as
    #: changing cgw_chunk itself does. Host-driven: requires concrete
    #: cgw params, so deterministic_delays with this set must run
    #: OUTSIDE jit (the sweep/bench `static=` precompute path,
    #: parallel.mesh.static_delays).
    cgw_stream_chunk: Optional[int] = field(
        metadata=dict(static=True), default=None
    )
    #: in-flight window of the streamed pipeline's prefetch stage
    #: (2 = double buffering; parallel.prefetch)
    cgw_prefetch_depth: int = field(metadata=dict(static=True), default=2)
    cgw_psr_term: bool = field(metadata=dict(static=True), default=True)
    cgw_evolve: bool = field(metadata=dict(static=True), default=True)
    cgw_phase_approx: bool = field(metadata=dict(static=True), default=False)
    #: (Np, Nt, K) full-model design tensor for the per-realization
    #: refit (timing.fit.design_tensor); None = quadratic F0/F1 proxy
    fit_design: Optional[jax.Array] = None
    #: weight the full-model design fit by the recipe's own noise model
    #: (GLS via gls_fit_subtract) instead of plain WLS
    fit_gls: bool = field(metadata=dict(static=True), default=False)
    #: GWB DFT-synthesis matmul precision (None = backend default;
    #: 'highest' forces full-f32 MXU passes; see gwb_delays)
    gwb_synthesis_precision: object = field(
        metadata=dict(static=True), default=None
    )
    #: CW-catalog backend: "auto" (= "scan", the production tiling) or
    #: "pallas_interpret" (archived-kernel logic study). "pallas" was
    #: retired round 5 — tied-or-lost on a real v5e, never chosen by
    #: auto (docs/DESIGN.md section 4) — and now raises.
    cgw_backend: str = field(metadata=dict(static=True), default="auto")
    transient_psr: int = field(metadata=dict(static=True), default=0)

    def __post_init__(self):
        _validate_recipe(self)


def _leaf_shape(x):
    """Shape of an array-ish Recipe leaf, else None. None gates the
    shape checks below: ``register_dataclass`` re-runs ``__init__`` on
    every pytree unflatten, where leaves may be tracers (shaped — check
    them) but also non-array stand-ins that must be waved through — a
    ``tree_map(lambda _: 0, recipe)`` structure probe, or
    parallel/mesh.py's PartitionSpec tree (tree_unflatten of per-leaf
    shard specs into the Recipe structure). Only a genuine ``.shape``
    attribute qualifies; lists/tuples are deliberately NOT coerced
    (PartitionSpec IS a tuple)."""
    s = getattr(x, "shape", None)
    if s is not None and not isinstance(x, (list, tuple)):
        return tuple(s)
    return None


def _validate_recipe(r: "Recipe"):
    """Reject mutually inconsistent Recipe fields at construction with
    a message naming the field — the combinations below otherwise fail
    deep inside jit with a shape/NoneType error pointing at nothing (or
    worse, silently inject nothing). Presence (None-ness) checks always
    run; shape checks run only when the leaf actually carries a shape
    (see :func:`_leaf_shape` for why).

    The scenario layer (scenarios/spec.py) validates the DECLARATIVE
    surface before compiling; this is the last line of defense for
    recipes assembled by hand."""

    def need(cond: bool, msg: str):
        if not cond:
            raise ValueError(f"Recipe: {msg}")

    burst_fields = ("burst_sky", "burst_hplus", "burst_hcross",
                    "burst_grid")
    burst_present = [f for f in burst_fields
                     if getattr(r, f) is not None]
    need(
        len(burst_present) in (0, len(burst_fields)),
        f"a burst needs all of {burst_fields}, got only "
        f"{tuple(burst_present)} (the sky/polarization triple, both "
        "pre-sampled waveforms, and the [start_s, stop_s] grid window "
        "travel together)",
    )
    need(
        (r.transient_waveform is None) == (r.transient_grid is None),
        "transient_waveform and transient_grid travel together (the "
        "pre-sampled waveform is meaningless without its [start_s, "
        "stop_s] grid window, and vice versa)",
    )
    need(
        r.cgw_params is not None or (r.cgw_pdist is None
                                     and r.cgw_pphase is None),
        "cgw_pdist/cgw_pphase describe the pulsar term of a CW catalog "
        "— set cgw_params too (or drop them)",
    )
    need(
        r.rn_gamma is not None or r.rn_log10_amplitude is None,
        "red noise needs rn_gamma alongside rn_log10_amplitude (the "
        "power-law prior has two parameters)",
    )
    need(
        r.chrom_gamma is not None or r.chrom_log10_amplitude is None,
        "chromatic noise needs chrom_gamma alongside "
        "chrom_log10_amplitude",
    )
    need(
        r.gwb_log10_amplitude is None or r.gwb_gamma is not None
        or r.gwb_user_spectrum is not None,
        "a power-law GWB needs gwb_gamma alongside gwb_log10_amplitude "
        "(or a gwb_user_spectrum, which overrides the power law)",
    )
    need(
        r.cov_log10_sigma is None or r.noise_cov is not None,
        "cov_log10_sigma scales the correlated-noise block — set "
        "noise_cov too (covariance.structure builders), or drop it",
    )
    need(
        r.noise_cov is None or hasattr(r.noise_cov, "sample"),
        "noise_cov must be a covariance.structure CovOp (or any object "
        "with the matvec/solve/logdet/sample/dense contract), got "
        f"{type(r.noise_cov).__name__}",
    )

    cgw_shape = _leaf_shape(r.cgw_params)
    if cgw_shape is not None:
        need(
            len(cgw_shape) == 2 and cgw_shape[0] == 8,
            f"cgw_params must be the (8, Ns) stacked catalog (gwtheta, "
            f"gwphi, mc, dist, fgw, phase0, psi, inc), got shape "
            f"{cgw_shape}",
        )
        ns = cgw_shape[1]
        for fname in ("cgw_pdist", "cgw_pphase"):
            s = _leaf_shape(getattr(r, fname))
            if s is not None and len(s) >= 1:
                need(
                    len(s) <= 2 and s[-1] == ns,
                    f"{fname} has shape {s} but the catalog has "
                    f"{ns} source(s); pass a scalar, (Ns,), or "
                    f"(Np, Ns)",
                )
    for fname, want in (("gwm_params", (5,)), ("burst_sky", (3,)),
                        ("burst_grid", (2,)), ("transient_grid", (2,))):
        s = _leaf_shape(getattr(r, fname))
        if s is not None:
            need(
                s == want,
                f"{fname} must have shape {want}, got {s}",
            )
    if isinstance(r.transient_psr, int):
        need(r.transient_psr >= 0,
             f"transient_psr must be >= 0, got {r.transient_psr}")


def realization_delays(key, batch: PulsarBatch, recipe: Recipe, rows=None):
    """One realization: (Np, Nt) summed delays from the enabled signals.

    ``rows=(npsr_global, row_start)`` runs the stochastic draws as exact
    row windows of the global streams (pulsar-sharded SPMD — see
    :func:`_rows_draw`; the GWB handles its own globality through the
    sharded ORF rows).

    Stream contract: the 5-way split below is public (STREAM_VERSION;
    the fuzz harness replays it). The correlated-noise block draws from
    ``fold_in(key, covariance.COV_STREAM_FOLD)`` instead of a widened
    split, so enabling it leaves every family's stream bit-identical
    (pinned by tests/test_covariance.py)."""
    # numerics observatory seams: each enabled family's (Np, Nt) output
    # passes through an identity probe (obs/numerics.py) that, when
    # armed, streams non-finite counts and overflow watermarks to the
    # host per SITE — so an inf lands on the family that produced it,
    # not on the summed total three ops later. Disarmed (the default)
    # the probe returns its argument before touching jax: this function
    # traces to today's graph, bitwise (pinned by tests/test_numerics).
    from ..obs import numerics

    k_wn, k_ec, k_rn, k_chrom, k_gwb = jax.random.split(key, 5)
    total = jnp.zeros(batch.toas_s.shape, batch.toas_s.dtype)
    if recipe.efac is not None or recipe.log10_equad is not None:
        total = total + numerics.probe("realization.white", white_noise_delays(
            k_wn,
            batch,
            efac=recipe.efac if recipe.efac is not None else 1.0,
            log10_equad=recipe.log10_equad,
            tnequad=recipe.tnequad,
            rows=rows,
        ))
    if recipe.log10_ecorr is not None:
        total = total + numerics.probe(
            "realization.ecorr",
            jitter_delays(k_ec, batch, recipe.log10_ecorr, rows=rows),
        )
    if recipe.rn_log10_amplitude is not None:
        total = total + numerics.probe("realization.red", red_noise_delays(
            k_rn,
            batch,
            recipe.rn_log10_amplitude,
            recipe.rn_gamma,
            nmodes=recipe.rn_nmodes,
            modes=recipe.rn_modes,
            logf=recipe.rn_logf,
            fmin=recipe.rn_fmin,
            fmax=recipe.rn_fmax,
            pshift=recipe.rn_pshift,
            libstempo_convention=recipe.rn_libstempo,
            tspan_s=recipe.rn_tspan_s,
            rows=rows,
        ))
    if recipe.chrom_log10_amplitude is not None:
        total = total + numerics.probe("realization.chromatic", chromatic_noise_delays(
            k_chrom,
            batch,
            recipe.chrom_log10_amplitude,
            recipe.chrom_gamma,
            chromatic_index=(
                recipe.chrom_index if recipe.chrom_index is not None else 2.0
            ),
            nmodes=recipe.chrom_nmodes,
            ref_freq_mhz=recipe.chrom_ref_freq_mhz,
            rows=rows,
        ))
    if recipe.gwb_log10_amplitude is not None or recipe.gwb_user_spectrum is not None:
        if recipe.orf_cholesky is None:
            # uncorrelated common process: ORF = 2*I (the reference's
            # no_correlations mode, red_noise.py:200-201)
            orf_chol = jnp.sqrt(2.0) * jnp.eye(batch.npsr, dtype=batch.toas_s.dtype)
        else:
            orf_chol = recipe.orf_cholesky
        total = total + numerics.probe("realization.gwb", gwb_delays(
            k_gwb,
            batch,
            recipe.gwb_log10_amplitude,
            recipe.gwb_gamma,
            orf_chol,
            npts=recipe.gwb_npts,
            howml=recipe.gwb_howml,
            user_spectrum=recipe.gwb_user_spectrum,
            turnover=recipe.gwb_turnover,
            f0=recipe.gwb_f0,
            beta=recipe.gwb_beta,
            power=recipe.gwb_power,
            synthesis_precision=recipe.gwb_synthesis_precision,
        ))
    if recipe.noise_cov is not None:
        from ..covariance.structure import COV_STREAM_FOLD, recipe_cov_s2

        k_cov = jax.random.fold_in(key, COV_STREAM_FOLD)
        total = total + numerics.probe(
            "realization.covariance",
            recipe.noise_cov.sample(
                k_cov, s2=recipe_cov_s2(recipe, total.dtype), rows=rows
            ) * batch.mask,
        )
    return total


def gls_noise_model(batch: PulsarBatch, recipe: "Recipe"):
    """Rank-reduced per-pulsar noise model for the batched GLS refit.

    Returns ``(sigma2, ecorr2, U, phi)``:

    - ``sigma2`` (Np, Nt): white per-TOA variance, (EFAC sigma)^2 +
      EQUAD^2 with the recipe's t2equad/tnequad convention — exactly
      what white_noise_delays injects;
    - ``ecorr2`` (Np, E) or None: per-epoch ECORR variance (the epoch
      indicator block is applied analytically in gls_fit_subtract via a
      segment-sum Woodbury — epochs are disjoint, so U_ec^T N^-1 U_ec
      is diagonal and no dense (Nt, E) one-hot is ever materialized);
    - ``U`` (Np, Nt, R) / ``phi`` (Np, R) or (None, None): the low-rank
      red-noise block(s) — the achromatic Fourier basis and, when the
      recipe injects chromatic noise, the same basis row-scaled by
      (ref/f)^idx — with their power-law prior variances.

    Oracle twin: timing.fit.covariance_from_recipe builds the same
    C = N + U_ec diag(ecorr2) U_ec^T + U diag(phi) U^T densely.
    """
    dtype = batch.toas_s.dtype
    err = batch.errors_s
    if recipe.efac is not None:
        ef = _check_backend_table(recipe.efac, batch, "efac").astype(dtype)
        ef = jnp.broadcast_to(ef, (batch.npsr,)) if ef.ndim == 0 else ef
        efac_t = _per_toa(ef, batch.backend_index, batch.mask)
    else:
        efac_t = batch.mask
    sigma2 = (efac_t * err) ** 2
    if recipe.log10_equad is not None:
        eq = 10.0 ** _check_backend_table(
            recipe.log10_equad, batch, "log10_equad"
        ).astype(dtype)
        eq = jnp.broadcast_to(eq, (batch.npsr,)) if eq.ndim == 0 else eq
        equad_t = _per_toa(eq, batch.backend_index, batch.mask)
        if not recipe.tnequad:
            equad_t = efac_t * equad_t
        sigma2 = sigma2 + equad_t**2

    ecorr2 = None
    if recipe.log10_ecorr is not None:
        ec = 10.0 ** _check_backend_table(
            recipe.log10_ecorr, batch, "log10_ecorr"
        ).astype(dtype)
        if ec.ndim == 0:
            ecorr2 = ec**2 * batch.epoch_mask
        elif ec.ndim == 1:
            ecorr2 = ec[:, None] ** 2 * batch.epoch_mask
        else:
            ecorr2 = (
                jnp.take_along_axis(ec, batch.epoch_backend_index, axis=1)
                ** 2
                * batch.epoch_mask
            )

    blocks = []
    priors = []
    if recipe.rn_log10_amplitude is not None:
        F, phi = red_noise_basis_prior(
            batch, recipe.rn_log10_amplitude, recipe.rn_gamma,
            nmodes=recipe.rn_nmodes, modes=recipe.rn_modes,
            logf=recipe.rn_logf, fmin=recipe.rn_fmin, fmax=recipe.rn_fmax,
            libstempo_convention=recipe.rn_libstempo,
            tspan_s=recipe.rn_tspan_s,
        )
        blocks.append(F * batch.mask[..., None])
        priors.append(phi)
    if recipe.chrom_log10_amplitude is not None:
        Fc, phic = red_noise_basis_prior(
            batch, recipe.chrom_log10_amplitude, recipe.chrom_gamma,
            nmodes=recipe.chrom_nmodes,
        )
        idx = jnp.asarray(
            recipe.chrom_index if recipe.chrom_index is not None else 2.0,
            dtype,
        )
        if idx.ndim >= 1:
            idx = idx[..., None]
        scale = jnp.where(
            batch.freqs_mhz > 0.0,
            (recipe.chrom_ref_freq_mhz
             / jnp.where(batch.freqs_mhz > 0.0, batch.freqs_mhz, 1.0))
            ** idx,
            0.0,
        )
        blocks.append(Fc * (scale * batch.mask)[..., None])
        priors.append(phic)
    if (
        recipe.gwb_log10_amplitude is not None
        or recipe.gwb_user_spectrum is not None
    ):
        # The injected GWB's per-pulsar AUTO-covariance (the reference
        # inherits PINT's blind spot here and omits it — a GWB-recipe
        # refit there is mis-specified; this framework knows its own
        # injected spectrum, so it weights by it). Cross-pulsar GWB
        # correlations remain unmodeled: the refit is per-pulsar, like
        # the reference's. phi = hc^2(f) / (12 pi^2 f^3 T) per sin/cos
        # coefficient — for a power law this is exactly the enterprise
        # powerlaw prior at (A_gwb, gamma_gwb); Monte-Carlo against the
        # synthesis op measures the ratio at 1.00 (test_batched).
        from ..ops.fourier import fourier_basis, fourier_frequencies
        from .gwb import characteristic_strain

        Tg = batch.tspan_s
        fg = fourier_frequencies(
            Tg, nmodes=recipe.gwb_gls_nmodes, xp=jnp
        )
        fg = jnp.broadcast_to(
            jnp.asarray(fg, dtype), (batch.npsr, fg.shape[-1])
        )
        ga, gg = recipe.gwb_log10_amplitude, recipe.gwb_gamma
        if ga is not None and jnp.asarray(ga).ndim >= 1:
            ga = jnp.asarray(ga, dtype)[..., None]  # (Np,) -> (Np, 1)
        if gg is not None and jnp.asarray(gg).ndim >= 1:
            gg = jnp.asarray(gg, dtype)[..., None]
        hc = characteristic_strain(
            fg,
            log10_amplitude=ga,
            spectral_index=gg,
            turnover=recipe.gwb_turnover,
            f0=recipe.gwb_f0,
            beta=recipe.gwb_beta,
            power=recipe.gwb_power,
            user_spectrum=recipe.gwb_user_spectrum,
            xp=jnp,
        )
        Tcol = jnp.broadcast_to(jnp.asarray(Tg, dtype), (batch.npsr,))
        phig = hc**2 / (12.0 * jnp.pi**2 * fg**3 * Tcol[:, None])
        Fg = fourier_basis(batch.toas_s, fg, xp=jnp)
        blocks.append(Fg * batch.mask[..., None])
        priors.append(jnp.repeat(phig, 2, axis=-1))

    U = jnp.concatenate(blocks, axis=-1) if blocks else None
    phi = jnp.concatenate(priors, axis=-1) if blocks else None
    return sigma2, ecorr2, U, phi


def white_ecorr_solver(batch: PulsarBatch, sigma2, ecorr2, dtype,
                       extra=None, extra_s2=None):
    """The white+ECORR block C0 = N + U_ec diag(ecorr2) U_ec^T as an
    inverse-applicator plus its masked log-determinant — the analytic
    per-epoch Woodbury every consumer of the rank-reduced noise model
    shares (the GLS refit below and the GP likelihood in
    ``likelihood/gp.py``), so the two can never disagree about the C0
    algebra.

    ``extra`` generalizes C0 beyond the diagonal: a structured
    :mod:`~pta_replicator_tpu.covariance` CovOp (a Recipe's
    ``noise_cov``) scaled by ``extra_s2`` joins the block,
    C0 = N + ECORR + s2 X. The solve stays structured where the
    structure allows it — a :class:`~pta_replicator_tpu.covariance.
    structure.BandedCov` without ECORR folds the white diagonal into
    its block-tridiagonal factor (O(Nt b^2)); every other combination
    (Kronecker/dense/low-rank extras, or banded + ECORR) materializes
    C0 once and pays one blocked dense Cholesky per evaluation — the
    documented fallback rung of the solver ladder (docs/covariance.md).
    With ``extra=None`` the path below is the original analytic
    Woodbury, unchanged.

    Returns ``(winv, c0inv_mat, logdet_c0)``: the masked N^-1 diagonal
    (Np, Nt) (the white diagonal's inverse even when ``extra`` is set —
    callers use it for diagnostics only), a map ``(Np, Nt, Q) ->
    (Np, Nt, Q)`` applying C0^-1, and the (Np,) log-determinant over
    VALID TOAs only (padding rows, whose sigma2 is zero, contribute
    nothing — they are excluded by the mask, not priced at log 0).
    Epochs are disjoint, so U_ec^T N^-1 U_ec is diagonal and both the
    solve and the determinant are exact with no dense (Nt, E) one-hot
    ever materialized:
    log det C0 = sum_t log sigma2_t + sum_e log(1 + ecorr2_e s_e)."""
    # numerics seams: winv overflows f32 first when a sigma2 underflows
    # (1/sigma2 before the masked logdet ever sees it), and logdet_c0
    # is the scalar that silently NaNs a whole pulsar's likelihood —
    # both probed per-site so a corrupt solve names THIS solver, not
    # the downstream lnlike. Identity while disarmed (obs/numerics.py).
    from ..obs import numerics

    winv = numerics.probe(
        "solver.winv", jnp.where(batch.mask > 0, 1.0 / sigma2, 0.0)
    )  # N^-1 diagonal
    if extra is not None:
        from ..covariance.structure import (
            BandedCov,
            banded_combined_solver,
            dense_combined_solver,
        )

        safe_sigma2 = jnp.where(batch.mask > 0, sigma2, 1.0)
        if isinstance(extra, BandedCov) and ecorr2 is None:
            c0inv_mat, logdet_c0 = banded_combined_solver(
                extra, safe_sigma2, extra_s2, dtype
            )
        else:
            c0inv_mat, logdet_c0 = dense_combined_solver(
                batch, safe_sigma2, ecorr2, extra, extra_s2, dtype
            )
        return winv, c0inv_mat, numerics.probe(
            "solver.logdet_c0", logdet_c0
        )
    winv, seg_sum, gain, logdet_c0 = white_ecorr_parts(
        batch, sigma2, ecorr2, dtype, winv=winv
    )

    def c0inv_mat(X):
        """(N + ECORR)^-1 X for (Np, Nt, Q) X, per-epoch Woodbury."""
        y = winv[..., None] * X
        if ecorr2 is None:
            return y
        corr = gain[..., None] * seg_sum(y)
        picked = jnp.take_along_axis(
            corr, batch.epoch_index[..., None], axis=1
        )
        return y - winv[..., None] * picked

    return winv, c0inv_mat, numerics.probe("solver.logdet_c0", logdet_c0)


def white_ecorr_parts(batch: PulsarBatch, sigma2, ecorr2, dtype,
                      winv=None):
    """The analytic white+ECORR Woodbury pieces WITHOUT the solver
    closure: the masked N^-1 diagonal, the epoch segment-sum operator,
    the per-epoch Woodbury gain (None without ECORR) and the masked
    log-determinant. Split out of :func:`white_ecorr_solver` so the
    fused Woodbury-assembly rung (likelihood/gp.py over
    ops/pallas_gp.py) prices the SAME C0 algebra the composed solver
    applies — the two can never disagree. ``winv`` lets the solver
    thread its probed diagonal through so the probe stays on the
    consumed data path."""
    if winv is None:
        winv = jnp.where(batch.mask > 0, 1.0 / sigma2, 0.0)
    psr_rows = jnp.arange(batch.npsr)[:, None]

    def seg_sum(x):
        """Per-pulsar epoch segment sum over TOAs: (Np, Nt, Q) ->
        (Np, E, Q) (scatter-add; no dense one-hot)."""
        z = jnp.zeros(
            (batch.npsr, batch.max_epochs) + x.shape[2:], dtype
        )
        return z.at[psr_rows, batch.epoch_index].add(
            x * batch.mask[..., None]
        )

    gain = None
    safe_sigma2 = jnp.where(batch.mask > 0, sigma2, 1.0)
    logdet_c0 = jnp.sum(jnp.log(safe_sigma2) * batch.mask, axis=-1)
    if ecorr2 is not None:
        s_e = seg_sum(winv[..., None])[..., 0]  # U_ec^T N^-1 U_ec diag
        gain = ecorr2 / (1.0 + ecorr2 * s_e)  # k/(1 + k s), 0 at k=0
        # log1p: ecorr2 is 0 at padded epochs (epoch_mask applied by
        # gls_noise_model), so those terms vanish exactly
        logdet_c0 = logdet_c0 + jnp.sum(
            jnp.log1p(ecorr2 * s_e) * batch.epoch_mask, axis=-1
        )
    return winv, seg_sum, gain, logdet_c0


def _gls_design_system(batch: PulsarBatch, design, recipe: "Recipe",
                       ridge, dtype):
    """Shared assembly for the batched GLS refit: the column-normalized
    normal matrix A = N^-1 (M^T C^-1 M) N^-1 (+ ridge and padding-column
    unit rows), its normalization, and the C^-1 operator itself. Split
    out so :func:`gls_fit_uncertainties` prices the SAME system
    gls_fit_subtract solves — the two can never drift apart. A recipe
    carrying a structured ``noise_cov`` block weights by it through
    the generalized solver (the covariance-aware GLS path)."""
    from ..covariance.structure import recipe_cov_s2

    sigma2, ecorr2, U, phi = gls_noise_model(batch, recipe)
    _winv, c0inv_mat, _logdet = white_ecorr_solver(
        batch, sigma2, ecorr2, dtype,
        extra=recipe.noise_cov,
        extra_s2=recipe_cov_s2(recipe, dtype),
    )

    design = jnp.asarray(design, dtype) * batch.mask[..., None]
    K = design.shape[-1]

    if U is not None:
        # phi=0 modes must be exactly inert (the phi->0 limit is an
        # infinite 1/phi prior, not the unit variance a plain phi_safe=1
        # substitution would give — wrong for e.g. a per-pulsar
        # red-noise-off row whose basis columns are still populated).
        # Zeroing the basis columns makes the inner products vanish, and
        # the unit diagonal then only keeps the solve nonsingular.
        U = U * (phi > 0)[:, None, :].astype(dtype)
        G = c0inv_mat(U)  # C0^-1 U, (Np, Nt, R)
        S = jnp.einsum("pnr,pns->prs", U, G, precision="highest")
        phi_safe = jnp.where(phi > 0, phi, 1.0)
        S = S + jnp.eye(U.shape[-1], dtype=dtype) / phi_safe[:, None, :]

        def cinv_mat(X):
            X0 = c0inv_mat(X)
            inner = jnp.einsum("pnr,pnq->prq", U, X0, precision="highest")
            corr = jnp.linalg.solve(S, inner)
            return X0 - jnp.einsum(
                "pnr,prq->pnq", G, corr, precision="highest"
            )
    else:
        cinv_mat = c0inv_mat

    CiM = cinv_mat(design)  # (Np, Nt, K)
    # column normalization + zero-column neutralization, as in
    # design_fit_subtract (padded columns solve to exactly 0)
    norms = jnp.sqrt(
        jnp.maximum(
            jnp.einsum("pnk,pnk->pk", design, CiM, precision="highest"),
            0.0,
        )
    )
    zero_col = norms == 0.0
    norms = jnp.where(zero_col, 1.0, norms)
    A = (
        jnp.einsum("pnk,pnl->pkl", design, CiM, precision="highest")
        / norms[:, :, None]
        / norms[:, None, :]
    )
    A = A + jnp.eye(K, dtype=dtype) * zero_col[:, None, :].astype(dtype)
    A = A + ridge * jnp.eye(K, dtype=dtype)
    return A, norms, zero_col, cinv_mat, design


def gls_fit_subtract(
    delays, batch: PulsarBatch, design, recipe: "Recipe", ridge=1e-10
):
    """Batched full-model GLS refit on device: subtract the
    C^-1-weighted best fit of the design columns, with
    C = N + U_ec diag(ecorr2) U_ec^T + U diag(phi) U^T from the recipe's
    own noise model (gls_noise_model) — the device analog of the
    oracle's ``fit(fitter='gls', recipe=...)`` and of the reference's
    PINT GLSFitter path (simulate.py:57-61).

    C is never materialized: the ECORR block inverts analytically
    per-epoch (disjoint indicators -> diagonal inner system, segment
    sums), and the red-noise block goes through a Woodbury solve of an
    (R, R) system, so the cost is batched (Nt x K/R) matmuls — MXU
    work — instead of an (Nt, Nt) dense factorization per pulsar.
    f32 caveat as design_fit_subtract: validate against the oracle GLS
    when exact parameter recovery matters (test_batched does, in f64).
    """
    dtype = delays.dtype
    A, norms, _zero, cinv_mat, design = _gls_design_system(
        batch, design, recipe, ridge, dtype
    )
    Cir = cinv_mat(delays[..., None])[..., 0]  # (Np, Nt)
    b = jnp.einsum("pnk,pn->pk", design, Cir, precision="highest") / norms
    coef = jnp.linalg.solve(A, b[..., None])[..., 0] / norms
    model = jnp.einsum("pnk,pk->pn", design, coef, precision="highest")
    return (delays - model) * batch.mask


def gls_fit_uncertainties(
    batch: PulsarBatch, design, recipe: "Recipe", ridge=1e-10, dtype=None
):
    """Per-parameter 1-sigma uncertainties of the batched GLS refit:
    sqrt(diag((M^T C^-1 M)^-1)), (Np, K) — the device twin of the
    oracle ``fit()``'s ``fit_uncertainties`` (timing.fit.gls_fit
    ``return_cov``; the reference reports these via PINT's fitters).

    Delay-independent (the covariance describes the estimator, not any
    one realization), so a sweep prices it ONCE per (batch, design,
    recipe), not per realization. Padding (all-zero) design columns
    report 0. Same nested-Woodbury system as gls_fit_subtract — the
    shared :func:`_gls_design_system` assembly guarantees it, PROVIDED
    the dtypes match: gls_fit_subtract assembles at its ``delays``
    dtype, and this helper defaults to the batch dtype — the dtype a
    subtract of batch-dtype delays (the production pipelines) assembles
    at. When your delays dtype differs (e.g. f64 delays on an f32 batch
    under JAX_ENABLE_X64), pass ``dtype=delays.dtype`` explicitly or
    the sigmas describe a different-precision system than the one the
    residuals were actually fit with.
    """
    if dtype is None:
        dtype = batch.toas_s.dtype
    A, norms, zero_col, _cinv, _design = _gls_design_system(
        batch, design, recipe, ridge, dtype
    )
    Ainv = jnp.linalg.inv(A)
    var = jnp.maximum(jnp.diagonal(Ainv, axis1=-2, axis2=-1), 0.0)
    sig = jnp.sqrt(var) / norms
    return jnp.where(zero_col, 0.0, sig)


def residualize(delays, batch: PulsarBatch):
    """Delays -> timing residuals: subtract the per-pulsar error-weighted
    mean over valid TOAs (what a timing-model phase fit absorbs first;
    oracle analog timing.model.phase_residuals)."""
    w = batch.mask / batch.errors_s**2
    mean = jnp.sum(w * delays, axis=-1, keepdims=True) / jnp.sum(
        w, axis=-1, keepdims=True
    )
    return (delays - mean) * batch.mask


def quadratic_fit_subtract(delays, batch: PulsarBatch):
    """Project out the weighted best-fit quadratic in time per pulsar — the
    batched analog of the post-injection F0/F1 refit
    (oracle analog SimulatedPulsar.fit, reference simulate.py:44-69).

    The normal-equation einsums run at ``precision='highest'``: on TPU the
    default matmul precision is bf16, whose ~3-digit Gram matrix leaves a
    visible (~1e-2 relative) un-projected component in the fit columns —
    measured directly on a v5e, where the weighted mean of the bf16-fit
    residual was 5% of the residual RMS instead of ~f32-eps. The (Np,3,3)
    contractions are a negligible share of the pipeline, so full precision
    costs nothing and makes the projection exact to f32; downstream this
    lets ``realize`` skip the redundant weighted-mean ``residualize`` pass
    after the fit (the constant column absorbs it)."""
    t = batch.toas_s / jnp.maximum(batch.tspan_s[:, None], 1.0)
    M = jnp.stack([jnp.ones_like(t), t, t**2], axis=-1)  # (Np, Nt, 3)
    w = batch.mask / batch.errors_s**2
    MtWM = jnp.einsum("pni,pn,pnj->pij", M, w, M, precision="highest")
    MtWr = jnp.einsum("pni,pn,pn->pi", M, w, delays, precision="highest")
    coef = jnp.linalg.solve(MtWM, MtWr[..., None])[..., 0]
    model = jnp.einsum("pni,pi->pn", M, coef, precision="highest")
    return (delays - model) * batch.mask


def design_fit_subtract(delays, batch: PulsarBatch, design, ridge=1e-10):
    """Project out the weighted best-fit of an arbitrary per-pulsar
    design tensor — the device form of the oracle's FULL-model refit
    (timing.fit.wls_fit over timing.components.full_design_matrix,
    reference analog: the per-realization PINT fit, simulate.py:44-69).

    ``design``: (Np, Nt, K) delay-derivative columns, built once on the
    CPU frontier by :func:`~pta_replicator_tpu.timing.fit.design_tensor`
    and padded to a common K with all-zero columns (those are
    neutralized here, not fitted). Column-normalized normal equations +
    Cholesky solve: one (Np, K, K) batched factorization per
    realization, MXU-friendly. Note the f32 caveat: squaring the
    condition number costs accuracy on nearly-collinear columns — run
    f64 (or validate against the oracle fit) when exact parameter
    recovery matters; residual *power absorption* is robust.
    """
    dtype = delays.dtype
    design = jnp.asarray(design, dtype)
    w = batch.mask / batch.errors_s  # sqrt of the WLS weights
    Mw = design * w[..., None]  # (Np, Nt, K)
    norms = jnp.sqrt(jnp.sum(Mw**2, axis=-2))  # (Np, K)
    zero_col = norms == 0.0  # padding columns
    norms = jnp.where(zero_col, 1.0, norms)
    Mn = Mw / norms[:, None, :]
    # precision='highest' on every contraction: the TPU bf16 matmul
    # default puts ~1e-2 relative error on Gram entries, which the
    # (already squared) condition number amplifies into a visibly wrong
    # projector — same failure class measured on the quadratic fit
    # (quadratic_fit_subtract docstring); these einsums are a small share
    # of the realization pipeline even at full precision
    A = jnp.einsum("pnk,pnl->pkl", Mn, Mn, precision="highest")
    # all-zero padding columns get a unit diagonal and a zero rhs, so
    # their coefficients solve to exactly 0
    K = design.shape[-1]
    A = A + jnp.eye(K, dtype=dtype) * zero_col[:, None, :].astype(dtype)
    # tiny Tikhonov term (columns are unit-normalized, so diag(A) = 1):
    # exactly duplicated columns would make A singular and jnp.linalg
    # .solve would silently return NaN for the whole pulsar; the ridge
    # turns that into a deterministic even split at ~1e-10 relative cost
    A = A + ridge * jnp.eye(K, dtype=dtype)
    b = jnp.einsum("pnk,pn->pk", Mn, delays * w, precision="highest")
    coef = jnp.linalg.solve(A, b[..., None])[..., 0]
    model = jnp.einsum("pnk,pk->pn", Mn, coef, precision="highest") / jnp.where(
        jnp.abs(w) > 0, w, 1.0
    )
    return (delays - model) * batch.mask


def finalize_residuals(delays, batch: PulsarBatch, recipe: Recipe, fit: bool):
    """Fit (when requested) and residualize — the shared tail of every
    realization pipeline. After the quadratic fit the weighted-mean
    subtraction of :func:`residualize` is a no-op (the constant column is
    projected out at full precision — see quadratic_fit_subtract), so it
    is skipped; the design fit keeps it because an arbitrary design
    tensor need not span a constant (test_quadratic_fit_projects_mean).
    ``recipe.fit_gls`` upgrades the design fit from WLS to the
    nested-Woodbury GLS weighted by the recipe's own noise model
    (gls_fit_subtract) — the device analog of the reference's PINT
    GLSFitter path."""
    if not fit:
        return residualize(delays, batch)
    if recipe.fit_design is not None:
        if recipe.fit_gls:
            sub = gls_fit_subtract(delays, batch, recipe.fit_design, recipe)
        else:
            sub = design_fit_subtract(delays, batch, recipe.fit_design)
        return residualize(sub, batch)
    return quadratic_fit_subtract(delays, batch)


def deterministic_delays(batch: PulsarBatch, recipe: Recipe, mesh=None):
    """Realization-independent delays (CW outlier catalog, bursts, memory,
    transients): computed once per batch, shared across the whole
    realization axis. ``mesh`` routes the streamed CW pipeline's
    staging per device (cw_stream_response) — the monolithic paths
    ignore it (parallel.mesh.static_delays places their result)."""
    total = jnp.zeros(batch.toas_s.shape, batch.toas_s.dtype)
    if recipe.cgw_params is not None:
        if recipe.cgw_stream_chunk is not None:
            # bounded-memory streamed pipeline (tiled host precompute +
            # prefetch); host-driven, so the recipe must reach here
            # eagerly (the static= precompute path) — tracer params
            # raise in cw_catalog_plane_tiles_for with guidance
            total = total + cgw_catalog_delays_streamed(
                batch,
                *[recipe.cgw_params[i] for i in range(8)],
                pdist=(
                    recipe.cgw_pdist if recipe.cgw_pdist is not None else 1.0
                ),
                pphase=recipe.cgw_pphase,
                psr_term=recipe.cgw_psr_term,
                evolve=recipe.cgw_evolve,
                phase_approx=recipe.cgw_phase_approx,
                tref_s=recipe.cgw_tref_s,
                chunk=recipe.cgw_stream_chunk,
                prefetch_depth=recipe.cgw_prefetch_depth,
                mesh=mesh,
            )
        else:
            total = total + cgw_catalog_delays(
                batch,
                *[recipe.cgw_params[i] for i in range(8)],
                pdist=(
                    recipe.cgw_pdist if recipe.cgw_pdist is not None else 1.0
                ),
                pphase=recipe.cgw_pphase,
                psr_term=recipe.cgw_psr_term,
                evolve=recipe.cgw_evolve,
                phase_approx=recipe.cgw_phase_approx,
                tref_s=recipe.cgw_tref_s,
                chunk=recipe.cgw_chunk,
                backend=recipe.cgw_backend,
            )
    if recipe.gwm_params is not None:
        total = total + gw_memory_delays(batch, *recipe.gwm_params)
    if recipe.burst_sky is not None:
        total = total + burst_delays(
            batch,
            recipe.burst_sky[0],
            recipe.burst_sky[1],
            recipe.burst_hplus,
            recipe.burst_hcross,
            recipe.burst_grid[0],
            recipe.burst_grid[1],
            psi=recipe.burst_sky[2],
        )
    if recipe.transient_waveform is not None:
        total = total + transient_delays(
            batch,
            recipe.transient_psr,
            recipe.transient_waveform,
            recipe.transient_grid[0],
            recipe.transient_grid[1],
        )
    return total


def realize_block(
    keys, batch: PulsarBatch, recipe: Recipe, fit: bool, rows=None,
    static=None, collect: bool = False,
):
    """The per-block realization pipeline: vmap of
    ``realization_delays + static -> finalize_residuals`` over a key
    block. The ONE implementation shared by the single-device engine
    below and every mesh engine (parallel.mesh), so the per-realization
    pipeline cannot silently diverge between paths.

    ``rows=(npsr_global, row_start)`` makes every stochastic draw an
    exact row window of the global stream (pulsar-sharded shard_map).

    ``collect=True`` (the armed single-device engine) threads the
    numerics observatory's donated stats buffer through the outputs:
    probes hit inside the vmap stage their stat scalars in a trace-
    local collector instead of emitting host callbacks, and the return
    becomes ``(residuals, {site: (nonfinite, max_abs, min_nonzero)})``
    with the per-realization stats reduced in-graph. Mesh engines keep
    the default (probe callbacks are shard_map-safe; a donated buffer
    is not, per-shard partials have no replicated out_spec)."""
    if static is None:
        static = deterministic_delays(batch, recipe)

    if not collect:
        def one(k):
            d = realization_delays(k, batch, recipe, rows=rows) + static
            return finalize_residuals(d, batch, recipe, fit)

        return jax.vmap(one)(keys)

    from ..obs import numerics

    col = numerics.Collector()

    def one(k):
        with numerics.collecting(col):
            d = realization_delays(k, batch, recipe, rows=rows) + static
            out = finalize_residuals(d, batch, recipe, fit)
            return out, col.take()

    out, stats = jax.vmap(one)(keys)
    return out, numerics.reduce_stats(stats)


def donate_keys_argnums(platform: str) -> tuple:
    """``donate_argnums`` for an engine's per-chunk key block: keys are
    split fresh per call and never reused, so donating them is always
    safe. The shared ``static`` delays and the batch are deliberately
    NOT donated — the same arrays feed every chunk of a sweep. CPU
    doesn't implement donation (and warns per compile), so it opts out.
    The ONE policy shared by the single-device and mesh engines.

    Best-effort by design: XLA honors a donation only when the buffer
    can alias an output, and the tiny key block rarely can — expect a
    one-time "donated buffers were not usable" note per engine compile
    on donation-capable backends, not a guaranteed saving. Donating the
    *safe* inputs anyway keeps the engines ready to alias if a future
    output layout permits it, and documents which inputs never may
    (``static``)."""
    return () if platform == "cpu" else (0,)


@functools.lru_cache(maxsize=None)
def _realize_engine(fit: bool, donate_keys: bool):
    """Jitted single-device realization engine, cached per (fit, donate)
    so repeated chunked calls (utils.sweep) hit jax's compile cache
    instead of re-dispatching the op graph eagerly every chunk.

    ``donate_keys``: see :func:`donate_keys_argnums` (keys are fresh per
    call, so donation is safe; ``static`` is reused every chunk and is
    never donated).
    """
    from ..obs import instrumented_jit, names
    from ..obs import numerics

    def run(keys, batch, recipe, static):
        # trace-time branch, same contract as the probes themselves:
        # arming clears the compile caches, so this body re-traces with
        # the current armed state and the donated stats buffer appears
        # exactly when the probes do
        if numerics.collector_default():
            return realize_block(
                keys, batch, recipe, fit, static=static, collect=True
            )
        return realize_block(keys, batch, recipe, fit, static=static), {}

    return instrumented_jit(
        run,
        name=names.JIT_REALIZE_ENGINE,
        retrace_warn=32,
        donate_argnums=(0,) if donate_keys else (),
    )


def realize(
    key,
    batch: PulsarBatch,
    recipe: Recipe,
    nreal: int,
    fit: bool = False,
    static=None,
):
    """Batch of independent realizations: (R, Np, Nt) residuals.

    vmap over PRNG keys gives the realization axis; shard it across
    devices with parallel.sharded_realize. Returns the UN-FETCHED output
    of a cached jitted engine: dispatch is asynchronous, so a pipelined
    caller (parallel.pipeline via utils.sweep) can queue the next chunk
    and fence this one later with a host readback.

    ``static``: precomputed :func:`deterministic_delays` result. The
    deterministic delays (CW catalog, bursts, memory) depend only on
    (batch, recipe), so a caller invoking ``realize`` repeatedly — a
    chunked sweep — should compute them once and pass them in; rebuilding
    the CW catalog per chunk costs ~10 ms/call at the bench workload,
    which dominates a 100-realization chunk (and the eager precompute is
    also what keeps the CW planes at f64 host accuracy — static is
    computed OUTSIDE the engine's jit boundary here for that reason,
    see parallel.mesh.static_delays).
    """
    keys = jax.random.split(key, nreal)
    if static is None:
        static = deterministic_delays(batch, recipe)
    donate = bool(donate_keys_argnums(jax.default_backend()))
    out, stats = _realize_engine(fit, donate)(keys, batch, recipe, static)
    if stats:
        # the armed engine's donated stats buffer: queue the UN-FETCHED
        # scalars for the chunk drain (obs.numerics.on_drain/flush) —
        # fetching here would fence the async dispatch
        from ..obs import numerics

        numerics.stash_step_stats(stats, nreal)
    return out
