"""Batched, key-driven device ops: every injection as a pure JAX function.

Each op maps ``(key, batch, params) -> delays`` of shape (Np, Nt); a
realization is the sum of the ops a :class:`Recipe` enables, and a
realization *batch* is ``jax.vmap`` of :func:`realization_delays` over PRNG
keys — the realization axis the reference lacks entirely (its operators
mutate one global dataset; SURVEY.md section 2, parallelism inventory).

Per-backend parameters are (Np, n_backends) arrays gathered per TOA/epoch
through the integer index arrays the freeze step produced — the device
equivalent of the reference's string-flag loops
(/root/reference/pta_replicator/white_noise.py:95-103).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..batch import PulsarBatch
from ..constants import YEAR_IN_SEC
from .cgw import cw_delay
from .gwb import (
    characteristic_strain,
    dft_synthesis_matrices,
    gwb_grid,
    residual_psd_coeff,
)


def _per_toa(params, index, mask):
    """Gather per-backend parameters onto TOAs: (Np, NB) -> (Np, Nt)."""
    params = jnp.asarray(params)
    if params.ndim == 1:
        return params[:, None] * mask
    return jnp.take_along_axis(params, index, axis=1) * mask


# ------------------------------------------------------------- injection ops

def white_noise_delays(
    key,
    batch: PulsarBatch,
    efac=1.0,
    log10_equad=None,
    tnequad: bool = False,
):
    """EFAC/EQUAD white noise. ``efac``/``log10_equad`` are scalars, (Np,)
    vectors, or (Np, n_backends) per-backend tables."""
    dtype = batch.toas_s.dtype
    k1, k2 = jax.random.split(key)
    shape = batch.toas_s.shape
    eps1 = jax.random.normal(k1, shape, dtype)
    eps2 = jax.random.normal(k2, shape, dtype)
    ef = jnp.asarray(efac, dtype)
    ef = jnp.broadcast_to(ef, (batch.npsr,)) if ef.ndim == 0 else ef
    efac_t = _per_toa(ef, batch.backend_index, batch.mask)
    if log10_equad is None:
        equad_t = jnp.zeros(shape, dtype)
    else:
        eq = 10.0 ** jnp.asarray(log10_equad, dtype)
        eq = jnp.broadcast_to(eq, (batch.npsr,)) if eq.ndim == 0 else eq
        equad_t = _per_toa(eq, batch.backend_index, batch.mask)
    dt = efac_t * batch.errors_s * eps1 * batch.mask
    if tnequad:
        return dt + equad_t * eps2
    return dt + efac_t * equad_t * eps2


def jitter_delays(key, batch: PulsarBatch, log10_ecorr):
    """ECORR jitter: one draw per (pulsar, epoch), scaled per-epoch and
    gathered onto TOAs. ``log10_ecorr``: scalar, (Np,), or (Np, NB)."""
    eps = jax.random.normal(
        key, (batch.npsr, batch.max_epochs), batch.toas_s.dtype
    )
    ec = 10.0 ** jnp.asarray(log10_ecorr, batch.toas_s.dtype)
    if ec.ndim == 0:
        per_epoch = ec * batch.epoch_mask
    elif ec.ndim == 1:
        per_epoch = ec[:, None] * batch.epoch_mask
    else:
        per_epoch = (
            jnp.take_along_axis(ec, batch.epoch_backend_index, axis=1)
            * batch.epoch_mask
        )
    val = per_epoch * eps
    return jnp.take_along_axis(val, batch.epoch_index, axis=1) * batch.mask


def red_noise_delays(
    key,
    batch: PulsarBatch,
    log10_amplitude,
    gamma,
    nmodes: int = 30,
):
    """Per-pulsar power-law red noise on the rank-reduced Fourier basis.

    The (Np, Nt, 2K) basis is built in-kernel from the frozen times (cheap,
    XLA fuses the trig into the MXU contraction); frequencies are k/Tspan
    per pulsar. Times are referenced to the batch epoch (a per-mode phase
    convention — statistically identical to the oracle's absolute-time
    convention, reference red_noise.py:92-101).
    """
    dtype = batch.toas_s.dtype
    log10_amplitude = jnp.broadcast_to(jnp.asarray(log10_amplitude, dtype), (batch.npsr,))
    gamma = jnp.broadcast_to(jnp.asarray(gamma, dtype), (batch.npsr,))
    k = jnp.arange(1, nmodes + 1, dtype=dtype)
    freqs = k[None, :] / batch.tspan_s[:, None]  # (Np, K)
    arg = 2.0 * jnp.pi * freqs[:, None, :] * batch.toas_s[:, :, None]
    F = jnp.concatenate([jnp.sin(arg), jnp.cos(arg)], axis=-1)  # (Np, Nt, 2K)

    fyr = 1.0 / YEAR_IN_SEC
    amp = 10.0 ** log10_amplitude
    prior = (
        amp[:, None] ** 2
        * (freqs / fyr) ** (-gamma[:, None])
        / (12.0 * jnp.pi**2 * batch.tspan_s[:, None])
        * YEAR_IN_SEC**3
    )
    prior2 = jnp.concatenate([prior, prior], axis=-1)  # sin and cos blocks
    eps = jax.random.normal(key, prior2.shape, dtype)
    coeff = jnp.sqrt(prior2) * eps
    return jnp.einsum("pnk,pk->pn", F, coeff) * batch.mask


def uniform_grid_interp(t, start, stop, series):
    """Linear interpolation of (..., npts) series sampled on a *uniform*
    grid [start, stop] onto (..., Nt) query times.

    Equivalent to ``jnp.interp`` for in-range queries but with direct index
    arithmetic instead of a searchsorted binary search (the grid spacing is
    known), which removes the gather-heavy log(npts) search from the GWB's
    per-TOA resampling."""
    npts = series.shape[-1]
    pos = (t - start) / (stop - start) * (npts - 1)
    idx = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, npts - 2)
    frac = jnp.clip(pos - idx, 0.0, 1.0)
    lo = jnp.take_along_axis(series, idx, axis=-1)
    hi = jnp.take_along_axis(series, idx + 1, axis=-1)
    return lo + frac * (hi - lo)


def gwb_delays(
    key,
    batch: PulsarBatch,
    log10_amplitude,
    gamma,
    orf_cholesky,
    npts: int = 600,
    howml: float = 10,
    turnover: bool = False,
    f0: float = 1e-9,
    beta: float = 1.0,
    power: float = 1.0,
    user_spectrum=None,
    synthesis: str = "auto",
):
    """Correlated GWB across the array: the one cross-pulsar op.

    The (Np x Np) x (Np x Nf) mix is a single einsum against the Cholesky
    factor of the ORF (computed once on CPU in f64 — see ops.orf); the
    synthesis FFT and the per-pulsar interpolation are batched. Under a
    sharded realization axis this whole function is embarrassingly
    parallel; with the pulsar axis sharded, XLA turns the einsum into a
    psum over the pulsar mesh axis (reference analog red_noise.py:265-287).
    """
    dtype = batch.toas_s.dtype
    ut, dt_grid, f = gwb_grid(batch.start_s, batch.stop_s, npts, howml)
    ut = jnp.asarray(ut, dtype)
    f = jnp.asarray(f, dtype)
    nf = f.shape[0]
    dur = batch.stop_s - batch.start_s

    w = jax.random.normal(key, (2, batch.npsr, nf), dtype)
    w = jax.lax.complex(w[0], w[1])

    hcf = characteristic_strain(
        f,
        log10_amplitude,
        gamma,
        turnover=turnover,
        f0=f0,
        beta=beta,
        power=power,
        user_spectrum=user_spectrum,
        xp=jnp,
    )
    C = residual_psd_coeff(hcf, f, dur, howml, xp=jnp)

    M = jnp.asarray(orf_cholesky, dtype)
    res_f = jnp.einsum("ab,bf->af", M, w) * jnp.sqrt(C)
    # zero DC and "Nyquist" bins, then synthesize the hermitian spectrum on
    # the time grid. Only npts+10 of the 2*nf-2 output samples are used, so
    # when the grid is oversampled (howml > ~1, always in practice) a direct
    # (Np, nf) x (nf, npts) MXU contraction beats the FFT — whose length
    # 2*nf-2 is a terrible radix for the default config (5998 = 2 x 2999,
    # prime => Bluestein). 'fft' is kept for cross-checking.
    mask = jnp.concatenate([jnp.zeros(1, dtype), jnp.ones(nf - 2, dtype), jnp.zeros(1, dtype)])
    res_f = res_f * mask
    if synthesis == "auto":
        synthesis = "matmul" if npts + 10 < 2 * nf - 2 else "fft"
    if synthesis == "matmul":
        cos_m, sin_m = dft_synthesis_matrices(nf, npts)
        scale = 2.0 / ((2 * nf - 2) * dt_grid)
        grid_series = (
            jnp.real(res_f) @ jnp.asarray(cos_m, dtype)
            - jnp.imag(res_f) @ jnp.asarray(sin_m, dtype)
        ) * jnp.asarray(scale, dtype)
    else:
        res_t = jnp.fft.irfft(res_f, n=2 * nf - 2, axis=-1) / dt_grid
        grid_series = res_t[:, 10 : npts + 10].astype(dtype)

    return uniform_grid_interp(batch.toas_s, ut[0], ut[-1], grid_series) * batch.mask


def cgw_catalog_delays(
    batch: PulsarBatch,
    gwtheta,
    gwphi,
    mc,
    dist,
    fgw,
    phase0,
    psi,
    inc,
    pdist=1.0,
    psr_term: bool = True,
    evolve: bool = True,
    phase_approx: bool = False,
    tref_s: float = 0.0,
    chunk: int = 512,
):
    """Summed response of a CW-source catalog, tiled over sources.

    Replaces the reference's numba prange + 1e7-source python chunking
    (deterministic.py:258-294, 321-440) with a ``lax.scan`` over
    ``chunk``-sized source tiles: the (chunk x Nt) workspace stays in
    VMEM-scale memory while the scan accumulates the (Np, Nt) sum.
    Deterministic (no key): source parameters are data.
    """
    dtype = batch.toas_s.dtype
    # absolute-seconds times as the reference kernels use them
    toas_abs = batch.toas_s + jnp.asarray(
        batch.tref_mjd * 86400.0 - tref_s, dtype
    )
    params = [
        jnp.asarray(x, dtype)
        for x in (gwtheta, gwphi, mc, dist, fgw, phase0, psi, inc)
    ]
    nsrc = params[0].shape[0]
    npad = (-nsrc) % chunk
    params = [jnp.concatenate([p, jnp.zeros(npad, dtype)]) for p in params]
    valid = jnp.concatenate([jnp.ones(nsrc, dtype), jnp.zeros(npad, dtype)])
    nchunks = (nsrc + npad) // chunk
    stacked = jnp.stack(params + [valid])  # (9, nsrc+pad)
    tiles = stacked.reshape(9, nchunks, chunk).transpose(1, 0, 2)

    per_psr = jax.vmap(
        lambda toas, phat, tile: jnp.sum(
            cw_delay(
                toas,
                phat,
                *[tile[i] for i in range(8)],
                pdist=pdist,
                psr_term=psr_term,
                evolve=evolve,
                phase_approx=phase_approx,
                nan_to_zero=True,
                xp=jnp,
            )
            * tile[8][:, None],
            axis=0,
        ),
        in_axes=(0, 0, None),
    )

    def step(carry, tile):
        return carry + per_psr(toas_abs, batch.phat, tile), None

    init = jnp.zeros(batch.toas_s.shape, dtype)
    total, _ = jax.lax.scan(step, init, tiles)
    return total * batch.mask


# ------------------------------------------------------------------ recipes

@jax.tree_util.register_dataclass
@dataclass
class Recipe:
    """Which signals to inject, with their (possibly per-backend) params.

    Array leaves are traced (so parameter sweeps can be vmapped too);
    structural switches are static.
    """

    efac: Optional[jax.Array] = None
    log10_equad: Optional[jax.Array] = None
    log10_ecorr: Optional[jax.Array] = None
    rn_log10_amplitude: Optional[jax.Array] = None
    rn_gamma: Optional[jax.Array] = None
    gwb_log10_amplitude: Optional[jax.Array] = None
    gwb_gamma: Optional[jax.Array] = None
    orf_cholesky: Optional[jax.Array] = None
    #: (F, 2) [freq_hz, hc] user characteristic-strain spectrum; overrides
    #: the power-law when present (population free-spec injection)
    gwb_user_spectrum: Optional[jax.Array] = None
    #: (8, Ns) stacked CW-catalog params in the order
    #: (gwtheta, gwphi, mc, dist, fgw, phase0, psi, inc); deterministic,
    #: shared by every realization (the population-synthesis outliers)
    cgw_params: Optional[jax.Array] = None

    tnequad: bool = field(metadata=dict(static=True), default=False)
    rn_nmodes: int = field(metadata=dict(static=True), default=30)
    gwb_npts: int = field(metadata=dict(static=True), default=600)
    gwb_howml: float = field(metadata=dict(static=True), default=10.0)
    cgw_tref_s: float = field(metadata=dict(static=True), default=0.0)
    cgw_chunk: int = field(metadata=dict(static=True), default=512)


def realization_delays(key, batch: PulsarBatch, recipe: Recipe):
    """One realization: (Np, Nt) summed delays from the enabled signals."""
    k_wn, k_ec, k_rn, k_gwb = jax.random.split(key, 4)
    total = jnp.zeros(batch.toas_s.shape, batch.toas_s.dtype)
    if recipe.efac is not None or recipe.log10_equad is not None:
        total = total + white_noise_delays(
            k_wn,
            batch,
            efac=recipe.efac if recipe.efac is not None else 1.0,
            log10_equad=recipe.log10_equad,
            tnequad=recipe.tnequad,
        )
    if recipe.log10_ecorr is not None:
        total = total + jitter_delays(k_ec, batch, recipe.log10_ecorr)
    if recipe.rn_log10_amplitude is not None:
        total = total + red_noise_delays(
            k_rn,
            batch,
            recipe.rn_log10_amplitude,
            recipe.rn_gamma,
            nmodes=recipe.rn_nmodes,
        )
    if recipe.gwb_log10_amplitude is not None or recipe.gwb_user_spectrum is not None:
        total = total + gwb_delays(
            k_gwb,
            batch,
            recipe.gwb_log10_amplitude,
            recipe.gwb_gamma,
            recipe.orf_cholesky,
            npts=recipe.gwb_npts,
            howml=recipe.gwb_howml,
            user_spectrum=recipe.gwb_user_spectrum,
        )
    return total


def residualize(delays, batch: PulsarBatch):
    """Delays -> timing residuals: subtract the per-pulsar error-weighted
    mean over valid TOAs (what a timing-model phase fit absorbs first;
    oracle analog timing.model.phase_residuals)."""
    w = batch.mask / batch.errors_s**2
    mean = jnp.sum(w * delays, axis=-1, keepdims=True) / jnp.sum(
        w, axis=-1, keepdims=True
    )
    return (delays - mean) * batch.mask


def quadratic_fit_subtract(delays, batch: PulsarBatch):
    """Project out the weighted best-fit quadratic in time per pulsar — the
    batched analog of the post-injection F0/F1 refit
    (oracle analog SimulatedPulsar.fit, reference simulate.py:44-69)."""
    t = batch.toas_s / jnp.maximum(batch.tspan_s[:, None], 1.0)
    M = jnp.stack([jnp.ones_like(t), t, t**2], axis=-1)  # (Np, Nt, 3)
    w = batch.mask / batch.errors_s**2
    MtWM = jnp.einsum("pni,pn,pnj->pij", M, w, M)
    MtWr = jnp.einsum("pni,pn,pn->pi", M, w, delays)
    coef = jnp.linalg.solve(MtWM, MtWr[..., None])[..., 0]
    return (delays - jnp.einsum("pni,pi->pn", M, coef)) * batch.mask


def deterministic_delays(batch: PulsarBatch, recipe: Recipe):
    """Realization-independent delays (the CW outlier catalog): computed
    once per batch, shared across the whole realization axis."""
    if recipe.cgw_params is None:
        return jnp.zeros(batch.toas_s.shape, batch.toas_s.dtype)
    return cgw_catalog_delays(
        batch,
        *[recipe.cgw_params[i] for i in range(8)],
        tref_s=recipe.cgw_tref_s,
        chunk=recipe.cgw_chunk,
    )


def realize(key, batch: PulsarBatch, recipe: Recipe, nreal: int, fit: bool = False):
    """Batch of independent realizations: (R, Np, Nt) residuals.

    vmap over PRNG keys gives the realization axis; shard it across
    devices with parallel.sharded_realize.
    """
    keys = jax.random.split(key, nreal)
    static = deterministic_delays(batch, recipe)

    def one(k):
        d = realization_delays(k, batch, recipe) + static
        d = quadratic_fit_subtract(d, batch) if fit else d
        return residualize(d, batch)

    return jax.vmap(one)(keys)
