"""Deterministic transient signals: GW bursts, bursts with memory, and
arbitrary noise transients.

Reference analogs: ``add_burst`` (/root/reference/pta_replicator/
deterministic.py:718-793), ``add_noise_transient`` (796-819),
``add_gw_memory`` (822-884).
"""
from __future__ import annotations

import numpy as np

from ..constants import DAY_IN_SEC
from ..models.cgw import antenna_pattern, _psr_phat
from ..simulate import SimulatedPulsar


# ----------------------------------------------------------------- pure math

def polarization_rotation(hplus, hcross, psi, xp=np):
    """Rotate (h+, hx) by polarization angle psi along the propagation
    direction (Maggiore 2008 eq. 7.24-25)."""
    c2, s2 = xp.cos(2.0 * psi), xp.sin(2.0 * psi)
    return hplus * c2 - hcross * s2, hplus * s2 + hcross * c2


def quadratic_subtract(toas_s, res, xp=np):
    """Remove the best-fit quadratic in time — mimics the absorption of a
    signal's low-order structure by an F0/F1 refit
    (reference deterministic.py:776-778)."""
    t = xp.asarray(toas_s, dtype=xp.float64)
    # column-scaled quadratic design for conditioning
    scale = xp.maximum(xp.max(xp.abs(t)), 1.0)
    ts = t / scale
    M = xp.stack([ts**2, ts, xp.ones_like(ts)], axis=-1)
    coef, *_ = xp.linalg.lstsq(M, res)
    return res - M @ coef


def memory_ramp(toas_s, t0_s, pol_amp, strain, xp=np):
    """Burst-with-memory residual: a linear ramp pol*strain*(t-t0) after t0."""
    t = xp.asarray(toas_s)
    return xp.where(t < t0_s, 0.0, pol_amp * strain * (t - t0_s))


# ------------------------------------------------------- oracle (CPU) layer

def add_burst(
    psr: SimulatedPulsar,
    gwtheta,
    gwphi,
    waveform_plus,
    waveform_cross,
    psi: float = 0.0,
    tref=0,
    remove_quad: bool = False,
    signal_name: str = "burst",
):
    """Inject an arbitrary elliptically-polarized GW burst given waveform
    callables h+(t), hx(t) evaluated at t - tref [s]."""
    toas_s = psr.toas.get_mjds() * DAY_IN_SEC - tref
    fplus, fcross, _ = antenna_pattern(gwtheta, gwphi, _psr_phat(psr))
    hplus = np.asarray(waveform_plus(toas_s))
    hcross = np.asarray(waveform_cross(toas_s))
    rplus, rcross = polarization_rotation(hplus, hcross, psi)
    res = -fplus * rplus - fcross * rcross
    if remove_quad:
        res = quadratic_subtract(toas_s.astype(np.float64), res)
    psr.inject(
        f"{psr.name}_{signal_name}",
        {
            "gwtheta": gwtheta,
            "gwphi": gwphi,
            "waveform_plus": waveform_plus,
            "waveform_cross": waveform_cross,
            "psi": psi,
            "tref": tref,
            "remove_quad": remove_quad,
        },
        res,
    )


def add_noise_transient(
    psr: SimulatedPulsar,
    waveform,
    tref=0,
    signal_name: str = "noise_transient",
):
    """Inject an un-projected arbitrary waveform into one pulsar
    (glitch-like incoherent transient)."""
    toas_s = psr.toas.get_mjds() * DAY_IN_SEC - tref
    res = np.asarray(waveform(toas_s))
    psr.inject(
        f"{psr.name}_{signal_name}",
        {"waveform": waveform, "tref": tref},
        res,
    )


def add_gw_memory(
    psr: SimulatedPulsar,
    strain,
    gwtheta,
    gwphi,
    bwm_pol,
    t0_mjd,
    signal_name: str = "gw_memory",
):
    """Inject a burst with memory: a polarization-projected strain ramp
    starting at epoch t0_mjd."""
    fplus, fcross, _ = antenna_pattern(gwtheta, gwphi, _psr_phat(psr))
    pol_amp = np.cos(2.0 * bwm_pol) * fplus + np.sin(2.0 * bwm_pol) * fcross
    toas_s = psr.toas.get_mjds() * DAY_IN_SEC
    res = memory_ramp(toas_s, t0_mjd * DAY_IN_SEC, pol_amp, strain)
    psr.inject(
        f"{psr.name}_{signal_name}",
        {
            "strain": strain,
            "gwtheta": gwtheta,
            "gwphi": gwphi,
            "bwm_pol": bwm_pol,
            "t0_mjd": t0_mjd,
        },
        res,
    )
