"""Continuous gravitational waves from SMBH binaries: single sources and
source catalogs.

Reference analogs: ``add_cgw`` (/root/reference/pta_replicator/
deterministic.py:13-185) and ``add_catalog_of_cws`` + numba kernels
(deterministic.py:188-561). Physics per Sesana et al. 2010 / Ellis et al.
2012, three evolution modes (full 8/3-power chirp, phase approximation,
monochromatic).

Architecture: one backend-agnostic, source-vectorized delay function
replaces the reference's per-source numba loops. Sources broadcast along a
leading axis, so the oracle path evaluates (chunked) numpy, while the
device path vmaps/scans the same function and reduces over sources on
device (the reference's 1e7-source chunking becomes memory tiling of the
scan).
"""
from __future__ import annotations

import numpy as np

from ..constants import DAY_IN_SEC, KPC2S, MPC2S, SOLAR2S
from ..ops.coords import pulsar_theta_phi, unit_vector
from ..simulate import SimulatedPulsar


# ----------------------------------------------------------------- pure math

def principal_axes(gwtheta, gwphi, xp=np):
    """GW principal axes m, n and propagation direction omhat (each
    (..., 3)) for source sky position(s) — the single home of the
    polarization-frame convention every projection site shares."""
    gwtheta = xp.asarray(gwtheta)
    gwphi = xp.asarray(gwphi)
    ct, st = xp.cos(gwtheta), xp.sin(gwtheta)
    cp, sp_ = xp.cos(gwphi), xp.sin(gwphi)
    m = xp.stack([sp_, -cp, xp.zeros_like(cp)], axis=-1)
    n = xp.stack([-ct * cp, -ct * sp_, st], axis=-1)
    omhat = xp.stack([-st * cp, -st * sp_, -ct], axis=-1)
    return m, n, omhat


def antenna_pattern(gwtheta, gwphi, phat, xp=np):
    """Antenna responses F+, Fx and cos(mu) for source direction(s) against
    one pulsar direction ``phat`` (3,). Source angles may carry a leading
    source axis."""
    m, n, omhat = principal_axes(gwtheta, gwphi, xp=xp)

    mp = m @ phat
    np_ = n @ phat
    op = omhat @ phat
    fplus = 0.5 * (mp**2 - np_**2) / (1.0 + op)
    fcross = mp * np_ / (1.0 + op)
    cosmu = -op
    return fplus, fcross, cosmu


def cw_delay(
    toas_s,
    phat,
    gwtheta,
    gwphi,
    mc,
    dist,
    fgw,
    phase0,
    psi,
    inc,
    pdist=1.0,
    pphase=None,
    psr_term: bool = True,
    evolve: bool = True,
    phase_approx: bool = False,
    nan_to_zero: bool = False,
    xp=np,
):
    """Per-source CW-induced residuals [s], shape (..., ntoa).

    Units follow the reference API: mc in solar masses, dist in Mpc, fgw in
    Hz (twice the orbital frequency), pdist in kpc, angles in radians,
    toas_s in seconds relative to the caller's tref. Source parameters may
    carry a leading source axis; the caller reduces over it.

    ``nan_to_zero`` applies the merged-binary guard of the catalog kernels
    (reference deterministic.py:433-438): chirp evolution past merger
    produces NaNs which are injected as zeros.
    """
    t = xp.asarray(toas_s)

    mc_s = xp.asarray(mc) * SOLAR2S
    dist_s = xp.asarray(dist) * MPC2S
    w0 = xp.pi * xp.asarray(fgw)
    phi0_orb = xp.asarray(phase0) / 2.0
    w053 = w0 ** (-5.0 / 3.0)

    sin2psi, cos2psi = xp.sin(2 * xp.asarray(psi)), xp.cos(2 * xp.asarray(psi))
    incfac1 = 0.5 * (3.0 + xp.cos(2 * xp.asarray(inc)))
    incfac2 = 2.0 * xp.cos(xp.asarray(inc))

    fplus, fcross, cosmu = antenna_pattern(gwtheta, gwphi, phat, xp=xp)

    chirp_rate = 256.0 / 5.0 * mc_s ** (5.0 / 3.0) * w0 ** (8.0 / 3.0)
    phase_norm = 1.0 / 32.0 / mc_s ** (5.0 / 3.0)
    amp_norm = mc_s ** (5.0 / 3.0) / dist_s

    if pphase is not None:
        pd_s = xp.asarray(pphase) / (2.0 * xp.pi * xp.asarray(fgw) * (1.0 - cosmu))
    else:
        pd_s = xp.asarray(pdist) * KPC2S

    # broadcast source axis against TOA axis
    def src(x):
        return xp.asarray(x)[..., None]

    tp = t - src(pd_s * (1.0 - cosmu))

    if evolve:
        omega = src(w0) * (1.0 - src(chirp_rate) * t) ** (-3.0 / 8.0)
        omega_p = src(w0) * (1.0 - src(chirp_rate) * tp) ** (-3.0 / 8.0)
        phase = src(phi0_orb) + src(phase_norm) * (src(w053) - omega ** (-5.0 / 3.0))
        phase_p = src(phi0_orb) + src(phase_norm) * (src(w053) - omega_p ** (-5.0 / 3.0))
    elif phase_approx:
        omega = src(w0) * xp.ones_like(t)
        omega_p = src(w0 * (1.0 + chirp_rate * pd_s * (1.0 - cosmu)) ** (-3.0 / 8.0)) * xp.ones_like(t)
        phase = src(phi0_orb) + omega * t
        phase_p = (
            src(phi0_orb)
            + src(phase_norm) * (src(w053) - omega_p ** (-5.0 / 3.0))
            + omega_p * t
        )
    else:
        omega = src(w0) * xp.ones_like(t)
        omega_p = omega
        phase = src(phi0_orb) + omega * t
        phase_p = src(phi0_orb) + omega * tp

    At = xp.sin(2.0 * phase) * src(incfac1)
    Bt = xp.cos(2.0 * phase) * src(incfac2)
    At_p = xp.sin(2.0 * phase_p) * src(incfac1)
    Bt_p = xp.cos(2.0 * phase_p) * src(incfac2)

    alpha = src(amp_norm) / omega ** (1.0 / 3.0)
    alpha_p = src(amp_norm) / omega_p ** (1.0 / 3.0)

    rplus = alpha * (At * src(cos2psi) + Bt * src(sin2psi))
    rcross = alpha * (-At * src(sin2psi) + Bt * src(cos2psi))
    rplus_p = alpha_p * (At_p * src(cos2psi) + Bt_p * src(sin2psi))
    rcross_p = alpha_p * (-At_p * src(sin2psi) + Bt_p * src(cos2psi))

    if psr_term:
        res = src(fplus) * (rplus_p - rplus) + src(fcross) * (rcross_p - rcross)
    else:
        res = -src(fplus) * rplus - src(fcross) * rcross

    if nan_to_zero:
        res = xp.where(xp.isnan(res), 0.0, res)
    return res


# ------------------------------------------------------- oracle (CPU) layer

def _psr_phat(psr) -> np.ndarray:
    theta, phi = pulsar_theta_phi(psr.loc, psr.name)
    return unit_vector(theta, phi)


def add_cgw(
    psr: SimulatedPulsar,
    gwtheta,
    gwphi,
    mc,
    dist,
    fgw,
    phase0,
    psi,
    inc,
    pdist=1.0,
    pphase=None,
    psrTerm: bool = True,
    evolve: bool = True,
    phase_approx: bool = False,
    tref=0,
    signal_name: str = "cw",
):
    """Inject one continuous wave (reference deterministic.py:13-185)."""
    toas_s = psr.toas.get_mjds() * DAY_IN_SEC - tref
    res = cw_delay(
        toas_s,
        _psr_phat(psr),
        gwtheta,
        gwphi,
        mc,
        dist,
        fgw,
        phase0,
        psi,
        inc,
        pdist=pdist,
        pphase=pphase,
        psr_term=psrTerm,
        evolve=evolve,
        phase_approx=phase_approx,
    )
    psr.inject(
        f"{psr.name}_{signal_name}",
        {
            "gwtheta": gwtheta,
            "gwphi": gwphi,
            "mc": mc,
            "dist": dist,
            "fgw": fgw,
            "phase0": phase0,
            "psi": psi,
            "inc": inc,
            "pdist": pdist,
            "pphase": pphase,
            "psrTerm": psrTerm,
            "evolve": evolve,
            "phase_approx": phase_approx,
            "tref": tref,
        },
        np.asarray(res),
    )


def add_catalog_of_cws(
    psr: SimulatedPulsar,
    gwtheta_list,
    gwphi_list,
    mc_list,
    dist_list,
    fgw_list,
    phase0_list,
    psi_list,
    inc_list,
    pdist=1.0,
    pphase=None,
    psrTerm: bool = True,
    evolve: bool = True,
    phase_approx: bool = False,
    tref=0,
    chunk_size: int = 10_000_000,
    signal_name: str = "cw_catalog",
):
    """Inject a catalog of N continuous waves in one summed pass
    (reference deterministic.py:188-318).

    Sources are processed in memory-bounded chunks; unlike the reference,
    arbitrarily large catalogs produce a single ledger entry (the
    reference's per-chunk ledger updates raise on the second chunk).
    """
    toas_s = (psr.toas.get_mjds() * DAY_IN_SEC - tref).astype(np.float64)
    phat = _psr_phat(psr).astype(np.float64)
    params = [
        np.atleast_1d(np.asarray(x, dtype=np.float64))
        for x in (gwtheta_list, gwphi_list, mc_list, dist_list, fgw_list,
                  phase0_list, psi_list, inc_list)
    ]
    nsrc = params[2].size
    ntoa = toas_s.size
    # per-source pdist/pphase vectors must be chunk-sliced with the params
    pdist_v = np.atleast_1d(np.asarray(pdist, dtype=np.float64))
    pphase_v = (
        None if pphase is None
        else np.atleast_1d(np.asarray(pphase, dtype=np.float64))
    )
    # bound the (sources x toas) workspace at ~2e7 elements
    step = max(1, min(chunk_size, int(2e7) // max(ntoa, 1)))
    total = np.zeros(ntoa)
    for lo in range(0, nsrc, step):
        sl = slice(lo, min(lo + step, nsrc))
        res = cw_delay(
            toas_s,
            phat,
            *[p[sl] for p in params],
            pdist=pdist_v[sl] if pdist_v.size > 1 else pdist_v,
            pphase=(
                None if pphase_v is None
                else (pphase_v[sl] if pphase_v.size > 1 else pphase_v)
            ),
            psr_term=psrTerm,
            evolve=evolve,
            phase_approx=phase_approx,
            nan_to_zero=True,
        )
        total += res.sum(axis=0)

    psr.inject(
        f"{psr.name}_{signal_name}",
        {
            "gwtheta_list": params[0],
            "gwphi_list": params[1],
            "mc_list": params[2],
            "dist_list": params[3],
            "fgw_list": params[4],
            "phase0_list": params[5],
            "psi_list": params[6],
            "inc_list": params[7],
            "pdist": pdist,
            "pphase": pphase,
            "psrTerm": psrTerm,
            "evolve": evolve,
            "phase_approx": phase_approx,
            "tref": tref,
        },
        total,
    )
