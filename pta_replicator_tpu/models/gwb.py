"""Stochastic gravitational-wave-background injection.

Frequency-domain method of Chamberlin, Creighton, Demorest et al. 2014:
draw complex Gaussian frequency series per pulsar, mix across pulsars with
the Cholesky factor of the overlap-reduction-function matrix, scale by the
characteristic-strain spectrum, inverse-FFT to a common time grid, and
interpolate onto each pulsar's TOAs.

Reference analog: ``add_gwb`` (/root/reference/pta_replicator/
red_noise.py:138-298). The math here is split into pure, backend-agnostic
stages so the device path can run them batched over realizations with the
cross-pulsar mix as a single einsum.
"""
from __future__ import annotations

import functools
import warnings

import numpy as np

from ..constants import DAY_IN_SEC
from ..ops.coords import pulsar_ra_dec
from ..ops.orf import assemble_orf


# ----------------------------------------------------------------- pure math

def gwb_grid(start_s: float, stop_s: float, npts: int, howml: float):
    """Common time grid and frequency grid for the synthesis FFT.

    Frequencies span DC..Nyquist in steps of 1/(dur*howml), with f[0]
    replaced by f[1] to avoid the 1/f^3 divergence at DC (the DC bin is
    zeroed later anyway).
    """
    dur = stop_s - start_s
    ut = np.linspace(start_s, stop_s, npts)
    dt_grid = dur / npts
    # The grid is k/(dur*howml) for k < Nyquist/step = npts*howml/2 exactly.
    # An arange(0, nyquist, step) here is numerically unstable: the endpoint
    # ratio is an exact integer in the default configuration, and float
    # rounding of dur decides whether the boundary bin is included — which
    # would silently shift every subsequent RNG draw. Fix the count
    # analytically instead (endpoint excluded when the ratio is integral).
    ratio = npts * howml / 2.0
    nf = int(np.floor(ratio)) if float(ratio).is_integer() else int(np.ceil(ratio))  # graftlint: disable=jax-host-sync — ratio is Python scalar config (npts*howml/2), never a tracer; the grid is static shape metadata
    f = np.arange(nf) / (dur * howml)
    f[0] = f[1]
    return ut, dt_grid, f


def characteristic_strain(
    f,
    log10_amplitude=None,
    spectral_index=None,
    turnover: bool = False,
    f0: float = 1e-9,
    beta: float = 1.0,
    power: float = 1.0,
    user_spectrum=None,
    xp=np,
):
    """hc(f): power law A (f/f_1yr)^alpha with optional turnover, or a
    user-supplied spectrum interpolated in log-log space and clamped to
    the endpoint values outside the node range — the reference's shipped
    ``extrap1d`` behavior (red_noise.py:11-33, 255-263: the slope
    continuation there is commented out, so out-of-range frequencies get
    the flat endpoint value). f_1yr = 1/3.16e7 Hz as in the reference."""
    f = xp.asarray(f)
    if user_spectrum is not None:
        uf = xp.asarray(user_spectrum[:, 0])
        raw = xp.asarray(user_spectrum[:, 1])
        # Clamp so zero/underflowed strain entries cannot put -inf nodes
        # into the log-log interpolation (f32 device path). The reference
        # log-log-interpolates whatever it is given (red_noise.py:255-263),
        # so flooring a legitimate ultra-low spectrum is a behavioral
        # divergence — warn when the floor actually engages. Inside jit
        # the spectrum is a tracer and cannot be inspected; the warning
        # fires on the host/oracle path and whenever concrete values
        # reach this function.
        try:
            n_floored = int(np.count_nonzero(np.asarray(raw) < 1e-30))  # graftlint: disable=jax-host-sync — deliberate host-path inspection; the except arm below handles the traced case
        except Exception:  # traced under jit — values not inspectable
            n_floored = 0
        if n_floored:
            warnings.warn(
                f"user GWB spectrum: {n_floored} strain value(s) below "
                "1e-30 were floored to 1e-30 for log-log interpolation "
                "(the reference interpolates the raw values); rescale "
                "the spectrum if the ultra-low entries are intentional",
                stacklevel=2,
            )
        uh = xp.maximum(raw, 1e-30)
        lf, luf, luh = xp.log10(f), xp.log10(uf), xp.log10(uh)
        # xp.interp clamps to the endpoint values outside the node range,
        # which is exactly the reference's extrap1d (its slope continuation
        # is commented out). The synthesis grid extends ~howml (10x) below
        # typical user grids, where hc^2/f^3 dominates — so flat-vs-slope
        # there changes injected power by large factors; match the
        # reference.
        logh = xp.interp(lf, luf, luh)
        return 10.0**logh
    amp = 10.0**log10_amplitude
    alpha = -0.5 * (spectral_index - 3.0)
    f1yr = 1.0 / 3.16e7
    hcf = amp * (f / f1yr) ** alpha
    if turnover:
        si = alpha - beta
        hcf = hcf / (1.0 + (f / f0) ** (power * si)) ** (1.0 / power)
    return hcf


def residual_psd_coeff(hcf, f, dur: float, howml: float, xp=np):
    """C(f) = hc^2 / (96 pi^2 f^3) * dur * howml — the variance scaling
    turning strain into timing-residual Fourier amplitudes."""
    return 1.0 / (96.0 * xp.pi**2) * hcf**2 / xp.asarray(f) ** 3 * dur * howml


@functools.lru_cache(maxsize=8)
def dft_synthesis_matrices(nf: int, npts: int, drop: int = 10):
    """(nf, npts) cosine/sine matrices evaluating the hermitian-packed
    inverse FFT at output samples ``drop .. drop+npts`` only.

    The synthesis FFT length is N = 2*nf-2, which for the reference's
    default grid (npts=600, howml=10 -> N=5998 = 2 x 2999, prime) forces a
    Bluestein FFT — while only npts+drop of the N output samples are ever
    used (reference red_noise.py:275-287 computes the full ifft and slices).
    Evaluating those samples directly is a dense (Np, nf) x (nf, npts)
    contraction: fewer FLOPs than Bluestein and it runs on the MXU.

    Because the DC and Nyquist bins are zeroed by the caller,

        x[n] = (2/N) * sum_k [Re X[k] cos(2 pi k n / N)
                              - Im X[k] sin(2 pi k n / N)]

    The phase is reduced with exact integer arithmetic (k*n mod N) so the
    trig arguments stay in [0, 2 pi) — f32-safe on device.
    """
    N = 2 * nf - 2
    k = np.arange(nf, dtype=np.int64)[:, None]
    n = np.arange(drop, drop + npts, dtype=np.int64)[None, :]
    phase = 2.0 * np.pi * ((k * n) % N) / N
    return np.cos(phase), np.sin(phase)


def gwb_time_series(w, M, C, dt_grid: float, npts: int, xp=np):
    """Mix per-pulsar complex draws across pulsars and synthesize the time
    series on the common grid.

    w: (..., Np, Nf) complex draws; M: (Np, Np) Cholesky factor of the ORF;
    C: (Nf,) variance scaling. Returns (..., Np, npts) residual series.
    The first 10 samples are dropped (FFT wrap-around transient), matching
    the reference (red_noise.py:285).
    """
    res_f = xp.einsum("ab,...bf->...af", M, w) * xp.sqrt(C)
    nf = res_f.shape[-1]
    # zero DC and Nyquist bins (backend-agnostic, no in-place update)
    mask = xp.concatenate([xp.zeros(1), xp.ones(nf - 2), xp.zeros(1)])
    res_f = res_f * mask
    packed = xp.concatenate([res_f, xp.conj(res_f[..., -2:0:-1])], axis=-1)
    res_t = xp.real(xp.fft.ifft(packed, axis=-1) / dt_grid)
    return res_t[..., 10 : npts + 10]


def interp_to_toas(ut, series, toas_s, xp=np):
    """Linear interpolation of a common-grid series onto one pulsar's TOAs."""
    return xp.interp(xp.asarray(toas_s), ut, series)


# ------------------------------------------------------- oracle (CPU) layer

def add_gwb(
    psrs: list,
    log10_amplitude: float,
    spectral_index: float,
    no_correlations: bool = False,
    seed: int = None,
    turnover: bool = False,
    clm=None,
    lmax: int = 0,
    f0: float = 1e-9,
    beta: float = 1.0,
    power: float = 1.0,
    userSpec=None,
    npts: int = 600,
    howml: float = 10,
):
    """Inject a correlated stochastic GWB across a pulsar array.

    Matches the reference's parameterization and legacy draw order
    (red_noise.py:138-298): per-pulsar real then imaginary N(0,1)^Nf
    streams, drawn pulsar-by-pulsar after ORF assembly.
    """
    if clm is None:
        clm = [np.sqrt(4.0 * np.pi)]
    if seed is not None:
        np.random.seed(seed)

    npsr = len(psrs)
    start = float(min(p.toas.first_mjd for p in psrs) * DAY_IN_SEC - DAY_IN_SEC)
    stop = float(max(p.toas.last_mjd for p in psrs) * DAY_IN_SEC + DAY_IN_SEC)
    dur = stop - start
    if npts is None:
        npts = int(dur / (DAY_IN_SEC * 14))

    ut, dt_grid, f = gwb_grid(start, stop, npts, howml)

    if no_correlations:
        orf = 2.0 * np.eye(npsr)
    else:
        locs = np.zeros((npsr, 2))
        for i, p in enumerate(psrs):
            ra, dec = pulsar_ra_dec(p.loc, p.name)
            locs[i] = ra, np.pi / 2.0 - dec  # (phi, theta)
        orf = assemble_orf(locs, clm=clm, lmax=lmax)

    M = np.linalg.cholesky(np.asarray(orf, np.float64))

    nf = len(f)
    w = np.empty((npsr, nf), dtype=complex)
    for i in range(npsr):
        w[i] = np.random.randn(nf) + 1j * np.random.randn(nf)

    hcf = characteristic_strain(
        f,
        log10_amplitude,
        spectral_index,
        turnover=turnover,
        f0=f0,
        beta=beta,
        power=power,
        user_spectrum=userSpec,
    )
    C = residual_psd_coeff(hcf, f, dur, howml)
    res_grid = gwb_time_series(w, M, C, dt_grid, npts)

    for i, psr in enumerate(psrs):
        toas_s = psr.toas.get_mjds() * DAY_IN_SEC
        dt = interp_to_toas(ut, res_grid[i], toas_s)
        psr.inject(
            f"{psr.name}_gwb",
            {"amplitude": log10_amplitude, "spectral_index": spectral_index},
            dt,
        )
