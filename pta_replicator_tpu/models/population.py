"""Realistic datasets from SMBHB populations: loudest binaries injected as
individual continuous waves, the remainder as a free-spectrum GWB.

Reference analog: ``add_gwb_plus_outlier_cws``
(/root/reference/pta_replicator/deterministic.py:565-715), the Becsy,
Cornish & Kelley 2022 method. The holodeck-provided pieces (chirp mass,
comoving distance, source strain) come from :mod:`..utils.cosmology`.

Two entry points share the binning core:

* :func:`add_gwb_plus_outlier_cws` — oracle path, mutates pulsars with the
  reference's RNG stream semantics (one seed drives the GWB draws and then
  the outlier sky/phase/orientation draws from the same legacy stream);
* :func:`population_recipe` — device path, turns the same population into
  a :class:`~pta_replicator_tpu.models.batched.Recipe` (user-spectrum GWB
  + stacked CW catalog) for batched TPU realization.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..utils.cosmology import (
    MPC_CM,
    MSOL_G,
    chirp_mass,
    comoving_distance_cm,
    gw_strain_source,
    m1m2_from_mtmr,
)
from .cgw import add_catalog_of_cws
from .gwb import add_gwb


@dataclass
class PopulationSplit:
    """Binned population split into outlier CWs and a free-spectrum GWB."""

    #: frequency bin centers [Hz]
    f_centers: np.ndarray
    #: summed weighted h_c^2 per bin, outliers excluded
    free_spec: np.ndarray
    #: per-outlier observed GW frequency [Hz]
    outlier_fo: np.ndarray
    #: per-outlier weighted characteristic strain^2
    outlier_hs: np.ndarray
    #: per-outlier observer-frame chirp mass [Msol]
    outlier_mc: np.ndarray
    #: per-outlier luminosity distance [Mpc]
    outlier_dl: np.ndarray

    @property
    def user_spectrum(self) -> np.ndarray:
        """(F, 2) [freq, hc] table for the GWB injector."""
        return np.column_stack([self.f_centers, np.sqrt(self.free_spec)])


def split_population(vals, weights, fobs, T_obs, outlier_per_bin: int = 100) -> PopulationSplit:
    """Bin a binary population by observed GW frequency and split off the
    ``outlier_per_bin`` loudest (by weighted h_c^2) binaries per bin.

    Parameters follow the reference API (deterministic.py:565-612):
    ``vals`` = [Mtot_g, Mrat, redz, Fobs_gw_hz] per binary (cgs rest-frame
    masses), ``weights`` = number of binaries represented by each entry,
    ``fobs`` = frequency bin edges [Hz], ``T_obs`` = observing time [s].
    """
    vals = [np.asarray(v, dtype=np.float64) for v in vals]
    weights = np.asarray(weights, dtype=np.float64)
    mtot, mrat, redz, fo = vals

    f_centers = 0.5 * (np.asarray(fobs)[1:] + np.asarray(fobs)[:-1])
    nbins = len(f_centers)

    mc_rest = chirp_mass(*m1m2_from_mtmr(mtot, mrat))  # grams, rest frame
    frst = fo * (1.0 + redz)  # rest-frame GW frequency
    dcom = comoving_distance_cm(redz)
    dlum = dcom * (1.0 + redz)
    hs = gw_strain_source(mc_rest, dcom, frst / 2.0)
    mc_obs = mc_rest * (1.0 + redz)

    # weighted characteristic strain^2 of each entry over the observation
    hc2 = weights * hs**2 * fo * T_obs

    bin_idx = np.digitize(fo, fobs) - 1
    # empty-bin floor: tiny but float32-representable as hc (the reference's
    # 1e-100 floor underflows to 0 in the f32 device path and poisons the
    # log-log interpolation with -inf)
    free_spec = np.full(nbins, 1e-40)
    out_hs, out_fo, out_mc, out_dl = [], [], [], []

    for k in range(nbins):
        sel = bin_idx == k
        if not np.any(sel):
            continue
        order = np.argsort(hc2[sel])[::-1]
        take = min(outlier_per_bin, len(order))
        # zero-strain entries (e.g. weight=0 bins) never become outliers —
        # the reference filters them post hoc (deterministic.py:689-692),
        # which also keeps the orientation-draw count identical
        loud = order[:take]
        loud = loud[hc2[sel][loud] > 0]
        rest = order[take:]
        out_hs.extend(hc2[sel][loud])
        out_fo.extend(fo[sel][loud])
        out_mc.extend(mc_obs[sel][loud] / MSOL_G)
        out_dl.extend(dlum[sel][loud] / MPC_CM)
        free_spec[k] += hc2[sel][rest].sum()

    return PopulationSplit(
        f_centers=f_centers,
        free_spec=free_spec,
        outlier_fo=np.asarray(out_fo),
        outlier_hs=np.asarray(out_hs),
        outlier_mc=np.asarray(out_mc),
        outlier_dl=np.asarray(out_dl),
    )


def _random_orientations(n):
    """Sky positions, phases, polarizations, inclinations for outliers —
    legacy global-RNG draws in the reference's order
    (deterministic.py:696-700)."""
    gwtheta = np.arccos(np.random.uniform(low=-1.0, high=1.0, size=n))
    gwphi = np.random.uniform(low=0.0, high=2 * np.pi, size=n)
    phase0 = np.random.uniform(low=0.0, high=2 * np.pi, size=n)
    psi = np.random.uniform(low=0.0, high=np.pi, size=n)
    inc = np.arccos(np.random.uniform(low=-1.0, high=1.0, size=n))
    return gwtheta, gwphi, phase0, psi, inc


def add_gwb_plus_outlier_cws(
    psrs,
    vals,
    weights,
    fobs,
    T_obs,
    outlier_per_bin: int = 100,
    seed: int = None,
    howml: float = 10,
    cw_tref_s: float = 53000 * 86400,
):
    """Inject a population-derived dataset: free-spectrum GWB plus the
    loudest binaries as individually-resolvable CWs (oracle path).

    Returns the same tuple as the reference (deterministic.py:715):
    (f_centers, free_spec, outlier_fo, outlier_hs, outlier_mc, outlier_dl,
    gwthetas, gwphis, phases, psis, incs).
    """
    split = split_population(vals, weights, fobs, T_obs, outlier_per_bin)

    add_gwb(psrs, None, None, userSpec=split.user_spectrum, howml=howml, seed=seed)

    n_cw = split.outlier_fo.shape[0]
    gwtheta, gwphi, phase0, psi, inc = _random_orientations(n_cw)

    for psr in psrs:
        add_catalog_of_cws(
            psr,
            gwtheta_list=gwtheta,
            gwphi_list=gwphi,
            mc_list=split.outlier_mc,
            dist_list=split.outlier_dl,
            fgw_list=split.outlier_fo,
            phase0_list=phase0,
            psi_list=psi,
            inc_list=inc,
            pdist=1.0,
            pphase=None,
            psrTerm=True,
            evolve=True,
            phase_approx=False,
            tref=cw_tref_s,
        )

    return (
        split.f_centers,
        split.free_spec,
        split.outlier_fo,
        split.outlier_hs,
        split.outlier_mc,
        split.outlier_dl,
        gwtheta,
        gwphi,
        phase0,
        psi,
        inc,
    )


def population_recipe(
    vals,
    weights,
    fobs,
    T_obs,
    orf_cholesky,
    outlier_per_bin: int = 100,
    seed: int = 0,
    howml: float = 10.0,
    gwb_npts: int = 600,
    cw_tref_s: float = 53000 * 86400.0,
    base_recipe=None,
    split: PopulationSplit = None,
):
    """Device-path variant: same population split, returned as a Recipe
    (user-spectrum GWB + stacked CW catalog) for batched realization.

    ``split`` short-circuits the binning with a precomputed
    :class:`PopulationSplit` (the scenario compiler bins once and feeds
    both the recipe and its coverage record); ``vals``/``weights``/
    ``fobs``/``T_obs`` are ignored then. A split with zero outliers
    (``outlier_per_bin=0``, or every bin empty) leaves the CW catalog
    off instead of injecting a zero-source catalog the tiled response
    kernels cannot chunk."""
    import jax.numpy as jnp

    from .batched import Recipe

    if split is None:
        split = split_population(vals, weights, fobs, T_obs,
                                 outlier_per_bin)
    n_cw = split.outlier_fo.shape[0]
    rng = np.random.default_rng(seed)
    gwtheta = np.arccos(rng.uniform(-1.0, 1.0, n_cw))
    gwphi = rng.uniform(0.0, 2 * np.pi, n_cw)
    phase0 = rng.uniform(0.0, 2 * np.pi, n_cw)
    psi = rng.uniform(0.0, np.pi, n_cw)
    inc = np.arccos(rng.uniform(-1.0, 1.0, n_cw))

    kwargs = dict(vars(base_recipe)) if base_recipe is not None else {}
    kwargs.update(
        gwb_log10_amplitude=jnp.asarray(0.0),  # unused under user spectrum
        gwb_gamma=jnp.asarray(0.0),
        gwb_user_spectrum=jnp.asarray(split.user_spectrum),
        orf_cholesky=jnp.asarray(orf_cholesky),
        gwb_npts=gwb_npts,
        gwb_howml=howml,
    )
    if n_cw:
        cat = np.stack(
            [gwtheta, gwphi, split.outlier_mc, split.outlier_dl,
             split.outlier_fo, phase0, psi, inc]
        )
        kwargs.update(
            cgw_params=jnp.asarray(cat),
            cgw_tref_s=cw_tref_s,
        )
    return Recipe(**kwargs)
