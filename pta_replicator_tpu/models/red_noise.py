"""Power-law red (timing) noise via the rank-reduced Fourier basis.

Reference analog: ``add_red_noise``
(/root/reference/pta_replicator/red_noise.py:106-135).
"""
from __future__ import annotations

import numpy as np

from ..constants import DAY_IN_SEC
from ..ops.fourier import fourier_basis, fourier_frequencies, powerlaw_prior
from ..simulate import SimulatedPulsar


# ----------------------------------------------------------------- pure math

def red_noise_delay(
    toas_s,
    log10_amplitude: float,
    gamma: float,
    eps,
    nmodes: int = 30,
    tspan_s: float = None,
    libstempo_convention: bool = False,
    modes=None,
    xp=np,
):
    """Red-noise delay [s]: F @ (sqrt(prior) * eps), eps ~ N(0,1)^(2K).

    ``modes`` overrides the default k/T frequency grid with an explicit
    list (then K = len(modes) and eps must have 2*len(modes) entries).
    """
    t = xp.asarray(toas_s)
    T = tspan_s if tspan_s is not None else float(t.max() - t.min())
    f = fourier_frequencies(T, nmodes=nmodes, modes=modes, xp=xp)
    F = fourier_basis(t, f, libstempo_convention=libstempo_convention, xp=xp)
    fdoubled = xp.repeat(f, 2)
    prior = powerlaw_prior(fdoubled, log10_amplitude, gamma, T, xp=xp)
    return F @ (xp.sqrt(prior) * eps)


# ------------------------------------------------------- oracle (CPU) layer

def add_red_noise(
    psr: SimulatedPulsar,
    log10_amplitude: float,
    spectral_index: float,
    components: int = 30,
    seed: int = None,
    modes=None,
    Tspan: float = None,
    libstempo_convention: bool = False,
):
    """Inject power-law red noise P(f) = A^2/(12 pi^2) (f yr)^-gamma yr^3.

    Draw order matches the reference (red_noise.py:118-127): one
    N(0,1)^(2*components) stream after optional seeding. Times are TOA
    epochs in seconds (the reference uses the TDB timescale; the constant
    ~69 s offset is irrelevant to the basis, exactly so under
    ``libstempo_convention`` which references times to the first TOA).

    Divergence from the reference: a caller-supplied ``Tspan`` is honored
    (for pinning a common span across pulsars); the reference accepts the
    argument but overwrites it from the TOAs (red_noise.py:124).
    """
    if seed is not None:
        np.random.seed(seed)

    toas_s = psr.toas.get_mjds() * DAY_IN_SEC
    tspan = float(Tspan) if Tspan is not None else float(toas_s.max() - toas_s.min())
    nmodes = components if modes is None else len(modes)
    eps = np.random.randn(2 * nmodes)
    dt = red_noise_delay(
        toas_s,
        log10_amplitude,
        spectral_index,
        eps,
        nmodes=nmodes,
        tspan_s=tspan,
        libstempo_convention=libstempo_convention,
        modes=modes,
    )
    psr.update_added_signals(
        f"{psr.name}_red_noise",
        {"amplitude": log10_amplitude, "spectral_index": spectral_index},
        dt,
    )
    psr.toas.adjust_seconds(dt)
    psr.update_residuals()


def add_chromatic_noise(
    psr: SimulatedPulsar,
    log10_amplitude: float,
    spectral_index: float,
    components: int = 30,
    chromatic_index: float = 2.0,
    ref_freq_mhz: float = 1400.0,
    seed: int = None,
    Tspan: float = None,
    signal_name: str = "chromatic_noise",
):
    """Inject chromatic (radio-frequency-dependent) power-law red noise:
    the achromatic Fourier-basis process scaled per TOA by
    ``(ref_freq_mhz / freq)^chromatic_index`` — index 2 is
    dispersion-measure noise, 4 scattering; the amplitude is defined at
    ``ref_freq_mhz``.

    Beyond-reference signal family (the reference injects only achromatic
    red noise, red_noise.py:106-135): real PTA datasets carry DM noise,
    and multi-band TOAs make it separable from achromatic red noise.
    Same draw layout as :func:`add_red_noise` (one N(0,1)^(2K) stream
    after optional seeding); device twin
    models.batched.chromatic_noise_delays.
    """
    if seed is not None:
        np.random.seed(seed)

    toas_s = psr.toas.get_mjds() * DAY_IN_SEC
    tspan = float(Tspan) if Tspan is not None else float(toas_s.max() - toas_s.min())
    eps = np.random.randn(2 * components)
    dt = red_noise_delay(
        toas_s,
        log10_amplitude,
        spectral_index,
        eps,
        nmodes=components,
        tspan_s=tspan,
    )
    if psr.toas.freqs_mhz is None:
        raise ValueError(
            f"{psr.name}: chromatic noise needs TOA observing frequencies "
            "(the tim data carries none)"
        )
    freqs = np.asarray(psr.toas.freqs_mhz, dtype=np.float64)
    # freq <= 0 is the TEMPO convention for infinite-frequency
    # (barycentric) TOAs: zero chromatic delay there
    scale = np.where(
        freqs > 0.0,
        (ref_freq_mhz / np.where(freqs > 0.0, freqs, 1.0)) ** chromatic_index,
        0.0,
    )
    dt = dt * scale
    psr.update_added_signals(
        f"{psr.name}_{signal_name}",
        {
            "amplitude": log10_amplitude,
            "spectral_index": spectral_index,
            "chromatic_index": chromatic_index,
            "ref_freq_mhz": ref_freq_mhz,
        },
        dt,
    )
    psr.toas.adjust_seconds(dt)
    psr.update_residuals()
