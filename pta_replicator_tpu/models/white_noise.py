"""White measurement noise (EFAC/EQUAD) and correlated jitter (ECORR).

Reference analogs: ``add_measurement_noise`` and ``add_jitter``
(/root/reference/pta_replicator/white_noise.py:47-198).

Architecture: random draws are separated from the (backend-agnostic) delay
math. The oracle wrappers below consume numpy's legacy global RNG in the
reference's draw order, so seeded runs are draw-for-draw identical to the
reference; the device path feeds the same math functions with
``jax.random`` draws batched over realizations.
"""
from __future__ import annotations

import numpy as np

from ..ops.quantize import quantize
from ..simulate import SimulatedPulsar


# ----------------------------------------------------------------- pure math

def measurement_noise_delay(errors_s, efac_vec, equad_vec, eps_efac, eps_equad,
                            tnequad: bool = False, xp=np):
    """Per-TOA white-noise delay [s].

    t2equad convention (default): EFAC scales both the nominal error and the
    EQUAD draw; tnequad convention: EFAC * sigma + EQUAD
    (reference white_noise.py:105-109).
    """
    dt = efac_vec * errors_s * eps_efac
    if tnequad:
        return dt + equad_vec * eps_equad
    return dt + efac_vec * equad_vec * eps_equad


def jitter_delay(epoch_index, ecorr_per_epoch, eps_epoch, xp=np):
    """Per-TOA jitter delay [s]: every TOA in an epoch shares one draw,
    scaled by that epoch's ECORR rms."""
    per_epoch = ecorr_per_epoch * eps_epoch
    return xp.take(per_epoch, epoch_index, axis=-1)


def expand_by_flags(values, flags, toa_flag_values, default=0.0):
    """Expand per-backend parameter values to a per-TOA (or per-epoch) vector.

    ``values`` aligned with ``flags``; positions whose flag value is not
    listed get ``default``.
    """
    out = np.full(len(toa_flag_values), default, dtype=np.float64)
    for val, flag in zip(values, flags):
        out[np.asarray(toa_flag_values) == flag] = val
    return out


# ------------------------------------------------------- oracle (CPU) layer

def _efac_equad_vectors(psr, efac, equad, flagid, flags):
    n = psr.toas.ntoas
    if flags is None:
        if not np.isscalar(efac) or not np.isscalar(equad):
            raise ValueError("If flags is None, efac and equad must be scalars")
        return np.full(n, efac, float), np.full(n, equad, float)
    toa_flags = psr.toas.get_flag(flagid)
    efac_l = np.full(len(flags), efac, float) if np.isscalar(efac) else np.asarray(efac, float)
    equad_l = np.full(len(flags), equad, float) if np.isscalar(equad) else np.asarray(equad, float)
    if len(efac_l) != len(flags) or len(equad_l) != len(flags):
        raise ValueError("flags must be same length as efac and log10_equad")
    return (
        expand_by_flags(efac_l, flags, toa_flags),
        expand_by_flags(equad_l, flags, toa_flags),
    )


def add_measurement_noise(
    psr: SimulatedPulsar,
    efac: float = 1.0,
    log10_equad: float = None,
    flagid: str = "f",
    flags: list = None,
    seed: int = None,
    tnequad: bool = False,
):
    """Inject EFAC/EQUAD white noise (reference white_noise.py:47-125).

    ``efac``/``log10_equad`` may be scalars, or per-backend lists aligned
    with ``flags`` (values of TOA flag ``flagid``). Note: unlike the
    reference, a scalar parameter combined with ``flags`` broadcasts to all
    listed backends instead of silently injecting zeros.
    """
    equad_str = "tnequad" if tnequad else "t2equad"
    if log10_equad is not None:
        equad = (
            10.0 ** np.asarray(log10_equad, dtype=np.float64)
            if not np.isscalar(log10_equad)
            else 10.0 ** log10_equad
        )
    else:
        equad = 0.0
    if seed is not None:
        np.random.seed(seed)

    efacvec, equadvec = _efac_equad_vectors(psr, efac, equad, flagid, flags)

    # legacy draw order: efac stream first, then equad stream (always drawn)
    eps_efac = np.random.randn(psr.toas.ntoas)
    eps_equad = np.random.randn(psr.toas.ntoas)
    dt = measurement_noise_delay(
        psr.toas.errors_s, efacvec, equadvec, eps_efac, eps_equad, tnequad=tnequad
    )

    if flags is None:
        psr.update_added_signals(
            f"{psr.name}_measurement_noise",
            {"efac": efac, "log10_" + equad_str: log10_equad},
            dt,
        )
    else:
        psr.update_added_signals(f"{psr.name}_measurement_noise", {}, dt)
        for i, flag in enumerate(flags):
            psr.update_added_signals(
                f"{psr.name}_{flag}_measurement_noise",
                {
                    "efac": efac if np.isscalar(efac) else efac[i],
                    "log10_" + equad_str: (
                        log10_equad if log10_equad is None or np.isscalar(log10_equad)
                        else log10_equad[i]
                    ),
                },
            )
    psr.toas.adjust_seconds(dt)
    psr.update_residuals()


def add_jitter(
    psr: SimulatedPulsar,
    log10_ecorr: float,
    flagid: str = "f",
    flags: list = None,
    coarsegrain: float = 0.1,
    seed: int = None,
):
    """Inject epoch-correlated (ECORR) jitter noise
    (reference white_noise.py:128-198). ``coarsegrain`` is the epoch width
    in days."""
    ecorr = (
        10.0 ** np.asarray(log10_ecorr, dtype=np.float64)
        if not np.isscalar(log10_ecorr)
        else 10.0 ** log10_ecorr
    )
    if seed is not None:
        np.random.seed(seed)

    mjds = psr.toas.get_mjds()
    if flags is None:
        if not np.isscalar(ecorr):
            raise ValueError("If flags is None, jitter must be a scalar")
        bins = quantize(mjds, dt=coarsegrain)
        ecorrvec = np.full(bins.nepochs, ecorr, float)
    else:
        bins = quantize(mjds, flags=psr.toas.get_flag(flagid), dt=coarsegrain)
        ecorr_l = np.full(len(flags), ecorr, float) if np.isscalar(ecorr) else np.asarray(ecorr, float)
        if len(ecorr_l) != len(flags):
            raise ValueError("flags must be same length as jitter")
        ecorrvec = expand_by_flags(ecorr_l, flags, bins.ave_flags)

    eps = np.random.randn(bins.nepochs)
    dt = jitter_delay(bins.epoch_index, ecorrvec, eps)

    if flags is None:
        psr.update_added_signals(
            f"{psr.name}_jitter", {"log10_ecorr": log10_ecorr}, dt
        )
    else:
        psr.update_added_signals(f"{psr.name}_jitter", {}, dt)
        for i, flag in enumerate(flags):
            psr.update_added_signals(
                f"{psr.name}_{flag}_jitter",
                {"log10_ecorr": log10_ecorr if np.isscalar(log10_ecorr) else log10_ecorr[i]},
            )
    psr.toas.adjust_seconds(dt)
    psr.update_residuals()
