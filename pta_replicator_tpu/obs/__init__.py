"""Structured telemetry for pta_replicator_tpu: spans, metrics, and JAX
compile/retrace accounting.

Quick start (library instrumentation uses exactly these entry points)::

    from ..obs import span, counter

    with span("freeze", npsr=npsr) as sp:
        ...
        sp["ntoa_max"] = nt
    counter("io.tim.toas").inc(ntoas)

Capturing a run::

    from pta_replicator_tpu import obs
    obs.start_capture("/tmp/telemetry")   # spans stream to events.jsonl
    ...                                    # run the pipeline
    obs.finish_capture(context={"argv": sys.argv})

then ``python -m pta_replicator_tpu report /tmp/telemetry``. The CLI's
``--telemetry DIR`` flag does the capture automatically; docs in
docs/observability.md.
"""
from __future__ import annotations

import sys
import time

from . import jaxhooks, metrics, report, trace
from .jaxhooks import (
    RetraceWarning,
    device_memory_snapshot,
    instrumented_jit,
    record_transfer,
    trace_count,
    tree_nbytes,
)
from .metrics import REGISTRY, counter, gauge, histogram
from .trace import TRACER, configure, event, span, traced

install_jax_hooks = jaxhooks.install

__all__ = [
    "span", "event", "configure", "traced", "counter", "gauge", "histogram",
    "REGISTRY", "TRACER", "RetraceWarning", "instrumented_jit",
    "install_jax_hooks", "device_memory_snapshot", "record_transfer",
    "trace_count", "tree_nbytes", "start_capture", "finish_capture",
    "telemetry_summary", "reset_all", "metrics", "trace", "report",
    "jaxhooks",
]


def start_capture(directory: str) -> None:
    """Begin streaming telemetry to ``directory`` and install the JAX
    compile-accounting hooks. Safe to call early (before jax init).

    Starts the capture from a clean slate: tracer buffers and the metrics
    registry are reset so the directory describes exactly one run — the
    same contract under which ``configure`` truncates events.jsonl
    (otherwise a second capture in one process would write metrics.json /
    chrome_trace.json still carrying the first run's counts)."""
    TRACER.reset()
    REGISTRY.reset()
    trace.configure(directory)
    jaxhooks.install()


def finish_capture(context: dict = None) -> None:
    """Write the remaining artifacts of the configured telemetry dir:
    metrics.json / metrics.prom / chrome_trace.json / meta.json. The
    events.jsonl stream was written live; this just flushes it."""
    import json
    import os

    directory = TRACER.directory
    if directory is None:
        return
    TRACER.flush()
    with open(os.path.join(directory, "metrics.json"), "w") as fh:
        json.dump(REGISTRY.to_json(), fh, indent=1, sort_keys=True)
    with open(os.path.join(directory, "metrics.prom"), "w") as fh:
        fh.write(REGISTRY.to_prometheus())
    with open(os.path.join(directory, "chrome_trace.json"), "w") as fh:
        json.dump(TRACER.chrome_trace(), fh)
    meta = {
        "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "dropped_events": TRACER.dropped,
        "device_memory": device_memory_snapshot(),
    }
    if "jax" in sys.modules:
        import jax

        meta["jax_version"] = jax.__version__
        try:
            meta["backend"] = jax.default_backend()
        except Exception:
            pass
    meta.update(context or {})
    with open(os.path.join(directory, "meta.json"), "w") as fh:
        json.dump(meta, fh, indent=1, sort_keys=True, default=repr)


def telemetry_summary() -> dict:
    """In-process snapshot for embedding into other evidence artifacts
    (bench.py's BENCH JSON): per-stage wall times + the jax counters."""
    spans = {
        path: {
            "calls": s["calls"],
            "total_s": round(s["total_s"], 6),
            "mean_s": round(s["mean_s"], 6),
        }
        for path, s in TRACER.summary().items()
    }
    jax_metrics = {}
    for name, insts in REGISTRY.to_json().items():
        if not name.startswith("jax."):
            continue
        for inst in insts:
            key = name + (
                "{" + ",".join(
                    f"{k}={v}" for k, v in sorted(inst["labels"].items())
                ) + "}" if inst["labels"] else ""
            )
            if inst["kind"] == "histogram":
                jax_metrics[key] = {
                    "count": inst["count"],
                    "sum_s": round(inst["sum"], 6),
                }
            else:
                jax_metrics[key] = inst["value"]
    return {"spans": spans, "jax": jax_metrics}


def reset_all() -> None:
    """Clear the global tracer buffers and metrics registry (tests)."""
    TRACER.reset()
    REGISTRY.reset()
