"""Structured telemetry for pta_replicator_tpu: spans, metrics, and JAX
compile/retrace accounting.

Quick start (library instrumentation uses exactly these entry points)::

    from ..obs import span, counter

    with span("freeze", npsr=npsr) as sp:
        ...
        sp["ntoa_max"] = nt
    counter("io.tim.toas").inc(ntoas)

Capturing a run::

    from pta_replicator_tpu import obs
    obs.start_capture("/tmp/telemetry")   # spans stream to events.jsonl
    ...                                    # run the pipeline
    obs.finish_capture(context={"argv": sys.argv})

then ``python -m pta_replicator_tpu report /tmp/telemetry``. The CLI's
``--telemetry DIR`` flag does the capture automatically; docs in
docs/observability.md.
"""
from __future__ import annotations

import sys
import time

from . import (
    critpath,
    devprof,
    flightrec,
    jaxhooks,
    ledger,
    metrics,
    names,
    numerics,
    occupancy,
    regress,
    report,
    serve,
    series,
    slo,
    timeline,
    trace,
)
from .flightrec import FlightRecorder, StallWarning
from .jaxhooks import (
    RetraceWarning,
    device_memory_snapshot,
    instrumented_jit,
    record_transfer,
    trace_count,
    tree_nbytes,
)
from .metrics import REGISTRY, counter, gauge, histogram
from .trace import (
    TRACER,
    TraceContext,
    adopt,
    carry,
    configure,
    current_trace,
    event,
    span,
    traced,
)

install_jax_hooks = jaxhooks.install

__all__ = [
    "span", "event", "configure", "traced", "counter", "gauge", "histogram",
    "REGISTRY", "TRACER", "RetraceWarning", "instrumented_jit",
    "install_jax_hooks", "device_memory_snapshot", "record_transfer",
    "trace_count", "tree_nbytes", "start_capture", "finish_capture",
    "telemetry_summary", "reset_all", "metrics", "trace", "report",
    "jaxhooks", "flightrec", "regress", "FlightRecorder", "StallWarning",
    "names", "devprof", "occupancy", "series", "timeline", "serve",
    "slo", "critpath", "ledger", "numerics",
    "TraceContext", "adopt", "carry", "current_trace",
]


def start_capture(
    directory: str,
    *,
    flight_recorder: bool = True,
    heartbeat_interval_s: float = 1.0,
    stall_timeout_s: float = 300.0,
    crash_hooks: bool = True,
    slo: object = None,
) -> None:
    """Begin streaming telemetry to ``directory`` and install the JAX
    compile-accounting hooks. Safe to call early (before jax init).

    Starts the capture from a clean slate: tracer buffers and the metrics
    registry are reset so the directory describes exactly one run — the
    same contract under which ``configure`` truncates events.jsonl
    (otherwise a second capture in one process would write metrics.json /
    chrome_trace.json still carrying the first run's counts).

    ``flight_recorder`` (default on) also starts the live-health sampler
    (obs.flightrec): a ``progress.json`` heartbeat every
    ``heartbeat_interval_s``, a :class:`StallWarning` watchdog at
    ``stall_timeout_s`` (None disables just the watchdog), and — when
    ``crash_hooks`` and running on the main thread — SIGTERM/SIGINT +
    excepthook chaining that flushes ``postmortem.json`` before the
    process dies. ``finish_capture`` stops it.

    ``slo`` declares the capture's objectives (a grammar string, a
    spec list, or ``obs.slo.Objective`` objects — see docs/tracing.md;
    default: the ``PTA_SLO`` env var): the flight recorder then scores
    them continuously, embeds the verdict in the heartbeat, and writes
    the ``slo.json`` live artifact the ``/slo`` and ``/readyz``
    endpoints serve."""
    stale = flightrec.active()
    if stale is not None:
        # back-to-back captures without finish_capture: the previous
        # recorder must not keep heartbeating into the old directory
        stale.stop(finished=False)
    TRACER.reset()
    REGISTRY.reset()
    devprof.reset()
    trace.configure(directory)
    # one capture dir describes ONE run: configure() truncated
    # events.jsonl, and a previous run's black box must go too, or a
    # rerun into the dir (bench.py's OOM retry ladder, a resumed sweep)
    # reads as dead to watch/report while it is running fine
    import os as _os

    for stale_artifact in ("progress.json", "postmortem.json",
                           "series.json", "series.jsonl",
                           "timeline.json", "metrics.prom", "slo.json",
                           "critpath.json", "numerics.json"):
        try:
            _os.remove(_os.path.join(directory, stale_artifact))
        except OSError:
            pass
    jaxhooks.install()
    # PTA_NUMERICS=1 arms the numerics observatory for this capture —
    # here, before any engine compiles, so the probes are in the first
    # traced graph (no cache clear needed; see obs/numerics.py)
    numerics.arm_from_env()
    if flight_recorder:
        flightrec.FlightRecorder(
            directory,
            interval_s=heartbeat_interval_s,
            stall_timeout_s=stall_timeout_s,
            slo_objectives=slo,
        ).start()
        if crash_hooks:
            flightrec.install_crash_hooks()


def finish_capture(context: dict = None) -> None:
    """Write the remaining artifacts of the configured telemetry dir:
    metrics.json / metrics.prom / chrome_trace.json / meta.json. The
    events.jsonl stream was written live; this just flushes it.

    Without a prior ``start_capture`` this is a documented no-op (there
    is no directory to write into), so teardown paths may call it
    unconditionally. When called while an exception is propagating
    (e.g. from a ``finally``), the flight recorder's ``postmortem.json``
    is flushed first so the failed run leaves its black box."""
    import json
    import os

    directory = TRACER.directory
    if directory is None:
        return
    rec = flightrec.active()
    if rec is not None:
        exc = sys.exc_info()[1]
        if exc is not None:
            rec.write_postmortem("exception", exc=exc)
        rec.stop(finished=exc is None)
    TRACER.flush()
    with open(os.path.join(directory, "metrics.json"), "w") as fh:
        json.dump(REGISTRY.to_json(), fh, indent=1, sort_keys=True)
    with open(os.path.join(directory, "metrics.prom"), "w") as fh:
        fh.write(REGISTRY.to_prometheus())
    with open(os.path.join(directory, "chrome_trace.json"), "w") as fh:
        json.dump(TRACER.chrome_trace(), fh)
    meta = {
        "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "dropped_events": TRACER.dropped,
        "device_memory": device_memory_snapshot(),
    }
    traces = devprof.trace_dirs(relative_to=directory)
    if traces:
        meta["device_traces"] = traces
    if "jax" in sys.modules:
        import jax

        meta["jax_version"] = jax.__version__
        try:
            meta["backend"] = jax.default_backend()
        except Exception:
            pass
    meta.update(context or {})
    with open(os.path.join(directory, "meta.json"), "w") as fh:
        json.dump(meta, fh, indent=1, sort_keys=True, default=repr)


def telemetry_summary() -> dict:
    """In-process snapshot for embedding into other evidence artifacts
    (bench.py's BENCH JSON): per-stage wall times + the jax counters."""
    spans = {
        path: {
            "calls": s["calls"],
            "total_s": round(s["total_s"], 6),
            "mean_s": round(s["mean_s"], 6),
        }
        for path, s in TRACER.summary().items()
    }
    jax_metrics = {}
    for name, insts in REGISTRY.to_json().items():
        if not name.startswith(names.JAX_PREFIX):
            continue
        for inst in insts:
            key = name + (
                "{" + ",".join(
                    f"{k}={v}" for k, v in sorted(inst["labels"].items())
                ) + "}" if inst["labels"] else ""
            )
            if inst["kind"] == "histogram":
                jax_metrics[key] = {
                    "count": inst["count"],
                    "sum_s": round(inst["sum"], 6),
                }
            else:
                jax_metrics[key] = inst["value"]
    return {"spans": spans, "jax": jax_metrics}


def reset_all() -> None:
    """Clear the global tracer buffers and metrics registry, and stop any
    flight recorder still sampling (tests)."""
    rec = flightrec.active()
    if rec is not None:
        rec.stop(finished=False)
    TRACER.reset()
    REGISTRY.reset()
    devprof.reset()
    numerics.reset()
