"""Critical-path attribution over a finished capture.

The occupancy module answers "how busy was each stage?"; this module
answers the question every optimization PR actually starts from:
**where does the next second of wall time live?** It reconstructs the
per-chunk span DAG from a capture's stage spans (every stage span the
staged executors emit carries the chunk index in its attrs and a
deterministic per-chunk trace id — parallel/stages.py ``_execute``),
computes the critical path per chunk and aggregated over the phase
window, and emits a ranked bottleneck verdict with an estimated
saving, as ``critpath.json`` + a report section + a ``critpath DIR``
CLI subcommand + a ``/critpath`` route on the telemetry server.

The attribution semantics, precisely:

* **aggregate critical path** — a greedy shadow decomposition of the
  phase window: stages are ranked by total busy seconds, and each
  instant of the window is attributed to the busiest stage active at
  that instant (rank order). A stage's ``critical_s`` is therefore its
  *exclusive* contribution — the seconds that would come off the wall
  if that stage alone were fully overlapped away — and the ranking is
  consistent with the occupancy duty table by construction (the
  busiest stage's critical_s equals its in-window busy time).
  ``blocked_s`` is the remainder: window time where *no* stage ran
  (coordination / scheduling overhead), and ``attributed_fraction`` =
  1 - blocked_s / wall is the coverage acceptance metric.
* **per-chunk critical path** — for each chunk, its stage spans in
  dataflow order (static_build -> dispatch -> drain -> io_write) form
  a chain; gaps inside the chain are **queue-wait** (the item sat in
  an edge FIFO between workers), and the gap between successive
  chunks' first-stage spans is **blocked-on-window** (the admitting
  stage is serial, so idle time between admissions is window-credit /
  upstream backpressure). A chunk's bottleneck is its longest stage;
  the per-stage ``chunk_bottleneck_fraction`` table is what backs
  verdict phrasing like "io_write off the critical path for 71% of
  chunks".
* **stragglers** — per-device busy spread from replica-stage spans
  (``cw_stream_stage{device=}`` and any other span carrying a device
  attr): ``straggler_ratio`` = max / median device busy, and devices
  more than :data:`STRAGGLER_THRESHOLD` x the median are named.

Strictly offline and jax-free: the analyzer runs over events.jsonl (or
``TRACER.events()``) *after* a run, wraps its own work in a
``critpath_analyze`` span and stamps its own ``analyzer.overhead_s`` —
the instrumented hot paths pay nothing for any of this.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, Iterable, List, Optional, Tuple

from . import names, occupancy
from .metrics import gauge
from .trace import TRACER

#: bump when a field keeps its spelling but changes meaning/units —
#: check_telemetry_schema.py and the report renderer refuse newer files
CRITPATH_SCHEMA_VERSION = 1

#: per-chunk pipeline stages in dataflow order — the chain the DAG
#: reconstruction threads per chunk index (fused runs have
#: static_build; stacked runs start at dispatch)
CHUNK_STAGES: Tuple[str, ...] = (
    names.SPAN_STATIC_BUILD,
    names.SPAN_DISPATCH,
    names.SPAN_DRAIN,
    names.SPAN_IO_WRITE,
)

#: a device whose busy time exceeds this multiple of the median device
#: busy time is named a straggler
STRAGGLER_THRESHOLD = 1.2


def _subtract(
    intervals: List[Tuple[float, float]],
    taken: List[Tuple[float, float]],
) -> List[Tuple[float, float]]:
    """``intervals`` minus ``taken`` (both sorted+disjoint), as a
    sorted disjoint list — the shadow step of the greedy decomposition."""
    out: List[Tuple[float, float]] = []
    for t0, t1 in intervals:
        cur = t0
        for s0, s1 in taken:
            if s1 <= cur or s0 >= t1:
                continue
            if s0 > cur:
                out.append((cur, s0))
            cur = max(cur, s1)
            if cur >= t1:
                break
        if cur < t1:
            out.append((cur, t1))
    return out


def _decompose(
    per_stage: Dict[str, List[Tuple[float, float]]], window: Tuple[float, float]
) -> Tuple[Dict[str, dict], Dict[str, List[Tuple[float, float]]]]:
    """Greedy shadow decomposition of the window: stages ranked by busy
    seconds; each gets the part of its busy intervals no busier stage
    already claimed. Nested stages (occupancy.NESTED_STAGES) are
    excluded when their parent is present — their time is inside the
    parent's and would double-claim the same instants. Returns (per-
    stage stats, per-stage EXCLUSIVE intervals — the annotated timeline
    track's slice set)."""
    clipped = {
        name: c
        for name, iv in per_stage.items()
        if (c := occupancy._clip(occupancy.merge_intervals(iv), *window))
    }
    clipped = {
        k: v for k, v in clipped.items()
        if occupancy.NESTED_STAGES.get(k) not in clipped
    }
    taken: List[Tuple[float, float]] = []
    out: Dict[str, dict] = {}
    exclusive: Dict[str, List[Tuple[float, float]]] = {}
    # name tiebreak: equal-busy stages must rank deterministically or
    # byte-identical reruns could swap exclusive attributions
    order = sorted(
        clipped,
        key=lambda s: (-occupancy.busy_seconds(clipped[s]), s),
    )
    for name in order:
        mine = _subtract(clipped[name], taken)
        exclusive[name] = mine
        out[name] = {
            "busy_s": round(occupancy.busy_seconds(clipped[name]), 6),
            "critical_s": round(occupancy.busy_seconds(mine), 6),
        }
        taken = occupancy.merge_intervals(taken + clipped[name])
    return out, exclusive


def critical_intervals(
    events: Iterable[dict],
    window: Optional[Tuple[float, float]] = None,
) -> Tuple[Optional[Tuple[float, float]], Dict[str, List[Tuple[float, float]]]]:
    """(window, per-stage exclusive critical intervals) for annotation
    consumers (the merged timeline's ``critical path`` track). Empty
    when the events carry no stage spans."""
    events = [e for e in events if e.get("type") == "span"]
    per_stage = occupancy.stage_intervals(events)
    if not per_stage:
        return None, {}
    if window is None:
        window = occupancy._phase_window(events)
    if window is None:
        window = (
            min(t0 for iv in per_stage.values() for t0, _ in iv),
            max(t1 for iv in per_stage.values() for _, t1 in iv),
        )
    _, exclusive = _decompose(per_stage, window)
    return window, exclusive


def _chunk_chains(events: Iterable[dict]) -> Dict[object, dict]:
    """chunk index -> {"stages": {name: [(t0, t1), ...]}, "traces":
    set of trace ids seen} for the per-chunk pipeline stage spans."""
    chains: Dict[object, dict] = {}
    for rec in events:
        if rec.get("type") != "span" or rec.get("name") not in CHUNK_STAGES:
            continue
        attrs = rec.get("attrs") or {}
        if "chunk" not in attrs:
            continue
        c = chains.setdefault(attrs["chunk"], {"stages": {}, "traces": set()})
        t0 = float(rec.get("t0", 0.0))
        c["stages"].setdefault(rec["name"], []).append(
            (t0, t0 + float(rec.get("wall_s", 0.0)))
        )
        if rec.get("trace_id"):
            c["traces"].add(rec["trace_id"])
    return chains


def _chunk_stats(chains: Dict[object, dict]) -> Optional[dict]:
    """Per-chunk chain accounting aggregated: queue-wait inside chains,
    blocked-on-window between successive admissions, per-stage
    chunk-bottleneck fractions, and trace coherence."""
    if not chains:
        return None
    n = len(chains)
    queue_wait: Dict[str, float] = {}
    bottleneck_counts: Dict[str, int] = {}
    admissions: List[Tuple[float, float]] = []  # first-stage (t0, t1)
    coherent = 0
    for c in chains.values():
        stages = c["stages"]
        ordered = [s for s in CHUNK_STAGES if s in stages]
        # a retried chunk has several spans per stage; the chain uses
        # each stage's full extent (first start .. last end)
        extents = {
            s: (min(t0 for t0, _ in stages[s]),
                max(t1 for _, t1 in stages[s]))
            for s in ordered
        }
        for prev, cur in zip(ordered, ordered[1:]):
            gap = extents[cur][0] - extents[prev][1]
            if gap > 0.0:
                queue_wait[cur] = queue_wait.get(cur, 0.0) + gap
        busiest = max(
            ordered,
            key=lambda s: sum(t1 - t0 for t0, t1 in stages[s]),
        )
        bottleneck_counts[busiest] = bottleneck_counts.get(busiest, 0) + 1
        admissions.append(extents[ordered[0]])
        if len(c["traces"]) <= 1:
            coherent += 1
    blocked_on_window = 0.0
    for (_, prev_end), (cur_start, _) in zip(
        sorted(admissions), sorted(admissions)[1:]
    ):
        if cur_start > prev_end:
            blocked_on_window += cur_start - prev_end
    return {
        "count": n,
        "trace_coherent_fraction": round(coherent / n, 3),
        "queue_wait_s": {k: round(v, 6) for k, v in sorted(queue_wait.items())},
        "blocked_on_window_s": round(blocked_on_window, 6),
        "bottleneck_fraction": {
            k: round(v / n, 3) for k, v in sorted(bottleneck_counts.items())
        },
    }


def _device_stats(events: Iterable[dict]) -> Optional[dict]:
    """Per-device busy spread from replica-stage spans carrying a
    ``device`` attr — the mesh straggler detector."""
    per_dev: Dict[str, List[Tuple[float, float]]] = {}
    for rec in events:
        if rec.get("type") != "span":
            continue
        dev = (rec.get("attrs") or {}).get("device")
        if dev is None:
            continue
        t0 = float(rec.get("t0", 0.0))
        per_dev.setdefault(str(dev), []).append(
            (t0, t0 + float(rec.get("wall_s", 0.0)))
        )
    if not per_dev:
        return None
    busy = {
        d: round(occupancy.busy_seconds(iv), 6)
        for d, iv in sorted(per_dev.items())
    }
    vals = sorted(busy.values())
    median = vals[len(vals) // 2] if len(vals) % 2 else (
        0.5 * (vals[len(vals) // 2 - 1] + vals[len(vals) // 2])
    )
    ratio = 1.0 if median <= 0.0 or len(vals) < 2 else max(vals) / median
    stragglers = (
        [d for d, b in busy.items() if b > STRAGGLER_THRESHOLD * median]
        if len(vals) >= 2 and median > 0.0 else []
    )
    return {
        "count": len(busy),
        "busy_s": busy,
        "straggler_ratio": round(ratio, 3),
        "stragglers": stragglers,
    }


def _verdict(
    stages: Dict[str, dict], chunks: Optional[dict], wall: float
) -> dict:
    """Ranked bottleneck verdict with the estimated saving: removing
    (fully overlapping) the top stage saves exactly its exclusive
    critical seconds, after which the bound shifts to the runner-up."""
    ranked = [
        {
            "stage": name,
            "resource": occupancy.STAGES.get(name, name),
            "busy_s": s["busy_s"],
            "critical_s": s["critical_s"],
            "critical_share": round(s["critical_s"] / wall, 3),
        }
        for name, s in sorted(
            stages.items(),
            key=lambda kv: (-kv[1]["critical_s"], kv[0]),
        )
    ]
    if not ranked:
        return {"summary": "no stage spans to attribute", "ranked": []}
    top = ranked[0]
    summary = (
        f"{top['stage']} holds {top['critical_share']:.0%} of the "
        f"critical path -> {top['resource']}-bound; "
        f"est. -{top['critical_s']:.2f}s wall if fully overlapped"
    )
    if len(ranked) > 1:
        summary += f" (bound then shifts to {ranked[1]['stage']})"
    if chunks:
        frac = chunks["bottleneck_fraction"].get(top["stage"], 0.0)
        if 0.0 < frac < 1.0:
            summary += (
                f"; off the per-chunk critical path for "
                f"{1.0 - frac:.0%} of chunks"
            )
    return {
        "bottleneck": top["stage"],
        "resource": top["resource"],
        "est_savings_s": top["critical_s"],
        "summary": summary,
        "ranked": ranked,
    }


def analyze(
    events: Iterable[dict],
    window: Optional[Tuple[float, float]] = None,
) -> Optional[dict]:
    """Critical-path attribution over span records (events.jsonl shape
    or ``TRACER.events()``). Returns None when no stage spans are
    present. ``window`` defaults to the longest phase span (same rule
    as :func:`occupancy.analyze`), else to the stage extent."""
    events = [e for e in events if e.get("type") == "span"]
    per_stage = occupancy.stage_intervals(events)
    if not per_stage:
        return None
    if window is None:
        window = occupancy._phase_window(events)
    if window is None:
        window = (
            min(t0 for iv in per_stage.values() for t0, _ in iv),
            max(t1 for iv in per_stage.values() for _, t1 in iv),
        )
    wall = max(1e-9, window[1] - window[0])
    stages, _ = _decompose(per_stage, window)
    if not stages:
        return None
    chains = {
        c: ch for c, ch in _chunk_chains(events).items()
        # chains entirely outside the window belong to another phase of
        # the same capture (bench A/B arms) and must not dilute this one
        if any(
            t0 < window[1] and t1 > window[0]
            for iv in ch["stages"].values() for t0, t1 in iv
        )
    }
    chunks = _chunk_stats(chains)
    critical = sum(s["critical_s"] for s in stages.values())
    doc = {
        "schema_version": CRITPATH_SCHEMA_VERSION,
        "window": {
            "t0": round(window[0], 6),
            "t1": round(window[1], 6),
            "wall_s": round(wall, 6),
        },
        "critical_path_s": round(critical, 6),
        "blocked_s": round(max(0.0, wall - critical), 6),
        "attributed_fraction": round(min(1.0, critical / wall), 4),
        "stages": {
            name: {
                **s,
                "duty": round(min(1.0, s["busy_s"] / wall), 3),
                "critical_share": round(s["critical_s"] / wall, 3),
                "chunk_bottleneck_fraction": (
                    (chunks or {}).get("bottleneck_fraction", {})
                    .get(name, 0.0)
                ),
            }
            for name, s in sorted(stages.items())
        },
        "chunks": chunks,
        "devices": _device_stats(events),
        "verdict": _verdict(stages, chunks, wall),
    }
    return doc


def analyze_capture(directory: str) -> Optional[dict]:
    """Attribution pass over a capture directory's events.jsonl,
    self-measured: the pass runs inside a ``critpath_analyze`` span,
    stamps ``analyzer.overhead_s`` into the doc, and sets the
    ``critpath.chunks`` / ``critpath.stragglers`` gauges — evidence
    that the attribution layer is offline-only (a capture with zero
    critpath_analyze spans paid zero analysis cost during the run)."""
    from .report import load_events

    path = os.path.join(directory, "events.jsonl")
    if not os.path.exists(path):
        return None
    events = load_events(path)
    # the live tracer may still sink into this very capture (in-process
    # analysis right after finish_capture): appending our own span to
    # the stream we just read would mutate the evidence and break
    # byte-identical reruns — time the pass without the span then
    sink_here = (
        TRACER.directory is not None
        and os.path.abspath(TRACER.directory) == os.path.abspath(directory)
    )
    t0 = time.perf_counter()
    if sink_here:
        doc = analyze(events)
    else:
        with TRACER.span(names.SPAN_CRITPATH_ANALYZE, directory=directory):
            doc = analyze(events)
    if doc is None:
        return None
    doc["analyzer"] = {"overhead_s": round(time.perf_counter() - t0, 6)}
    gauge(names.CRITPATH_CHUNKS).set((doc["chunks"] or {}).get("count", 0))
    gauge(names.CRITPATH_STRAGGLERS).set(
        len((doc["devices"] or {}).get("stragglers", []))
    )
    return doc


def write_critpath(
    directory: str, out: Optional[str] = None, doc: Optional[dict] = None
) -> Optional[str]:
    """Analyze ``directory`` and write ``critpath.json`` next to the
    capture (atomic tmp+replace, like every other live artifact).
    Returns the path, or None when there was nothing to attribute."""
    if doc is None:
        doc = analyze_capture(directory)
    if doc is None:
        return None
    out = out or os.path.join(directory, "critpath.json")
    tmp = out + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, out)
    return out


def render_critpath(doc: dict) -> str:
    """The report's critical-path section: per-stage attribution table,
    chunk chain decomposition, straggler spread, ranked verdict."""
    from .report import _fmt_s

    lines = ["critical path (attribution over the phase window):"]
    for name, s in (doc.get("stages") or {}).items():
        lines.append(
            f"  {name:<18} critical {_fmt_s(s['critical_s']):>10} "
            f"({100 * s['critical_share']:5.1f}% of wall)  "
            f"busy {_fmt_s(s['busy_s']):>10}  "
            f"chunk-bottleneck {100 * s['chunk_bottleneck_fraction']:.0f}%"
        )
    lines.append(
        f"  attributed {100 * doc.get('attributed_fraction', 0.0):.1f}% "
        f"of {_fmt_s((doc.get('window') or {}).get('wall_s', 0.0))} wall; "
        f"blocked (no stage running) {_fmt_s(doc.get('blocked_s', 0.0))}"
    )
    chunks = doc.get("chunks")
    if chunks:
        lines.append(
            f"  chunks: {chunks['count']} chains, "
            f"window-blocked {_fmt_s(chunks['blocked_on_window_s'])}, "
            f"queue-wait " + (
                ", ".join(
                    f"{k} {_fmt_s(v)}"
                    for k, v in chunks["queue_wait_s"].items()
                ) or "none"
            )
        )
    devices = doc.get("devices")
    if devices and devices["count"] >= 2:
        line = (
            f"  devices: {devices['count']}, straggler ratio "
            f"{devices['straggler_ratio']:.2f}x"
        )
        if devices["stragglers"]:
            line += " — STRAGGLERS: " + ", ".join(devices["stragglers"])
        lines.append(line)
    verdict = doc.get("verdict") or {}
    if verdict.get("summary"):
        lines.append(f"  verdict: {verdict['summary']}")
    return "\n".join(lines)
